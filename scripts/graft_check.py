"""Driver-contract check: entry() compiles, dryrun_multichip(8) executes."""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_num_cpu_devices", 8)
import importlib.util

spec = importlib.util.spec_from_file_location(
    "g",
    os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                 "__graft_entry__.py"),
)
m = importlib.util.module_from_spec(spec)
spec.loader.exec_module(m)
fn, args = m.entry()
jax.eval_shape(fn, *args)
m.dryrun_multichip(8)
print("graft contract OK")
