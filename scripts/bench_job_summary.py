"""Append one bench JSON line's provenance to the GitHub job summary.

Usage: bench_job_summary.py LABEL FILE — FILE holds a bench.py run's stdout;
the last JSON object with a "metric" key is the line. The row leads with the
explicit `platform` / `cpu_fallback` fields so a CPU-only smoke round can
never be skim-read as TPU signal in the checks tab.
"""

import json
import os
import sys


def main() -> int:
    if len(sys.argv) != 3:
        print("usage: bench_job_summary.py LABEL FILE", file=sys.stderr)
        return 2
    label, path = sys.argv[1], sys.argv[2]
    last = None
    try:
        with open(path, encoding="utf-8") as f:
            for ln in f:
                try:
                    obj = json.loads(ln)
                except ValueError:
                    continue
                if isinstance(obj, dict) and "metric" in obj:
                    last = obj
    except OSError:
        pass
    if last is None:
        row = f"- **{label}**: no bench JSON line produced"
    else:
        row = (f"- **{label}**: `platform={last.get('platform', '?')}` "
               f"`cpu_fallback={last.get('cpu_fallback', '?')}` — "
               f"{last.get('metric')} = {last.get('value')} "
               f"{last.get('unit', '')}")
    summary = os.environ.get("GITHUB_STEP_SUMMARY")
    if summary:
        with open(summary, "a", encoding="utf-8") as f:
            f.write(row + "\n")
    print(row)
    # a missing line means bench crashed or printed garbage — the step must
    # go red (the smoke jobs are continue-on-error, so this never blocks)
    return 0 if last is not None else 1


if __name__ == "__main__":
    sys.exit(main())
