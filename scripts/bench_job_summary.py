"""Append one bench JSON line's provenance to the GitHub job summary.

Usage: bench_job_summary.py LABEL FILE — FILE holds a bench.py run's stdout;
the last JSON object with a "metric" key is the line. The row leads with the
explicit `platform` / `cpu_fallback` fields so a CPU-only smoke round can
never be skim-read as TPU signal in the checks tab.
"""

import json
import os
import sys


def main() -> int:
    if len(sys.argv) != 3:
        print("usage: bench_job_summary.py LABEL FILE", file=sys.stderr)
        return 2
    label, path = sys.argv[1], sys.argv[2]
    last = None
    try:
        with open(path, encoding="utf-8") as f:
            for ln in f:
                try:
                    obj = json.loads(ln)
                except ValueError:
                    continue
                if isinstance(obj, dict) and "metric" in obj:
                    last = obj
    except OSError:
        pass
    if last is None:
        row = f"- **{label}**: no bench JSON line produced"
    else:
        row = (f"- **{label}**: `platform={last.get('platform', '?')}` "
               f"`cpu_fallback={last.get('cpu_fallback', '?')}` — "
               f"{last.get('metric')} = {last.get('value')} "
               f"{last.get('unit', '')}")
        # serve-bench decode-path provenance: which attention read produced
        # the number (pallas kernel vs XLA gather vs dense), lifted next to
        # platform/cpu_fallback so a kernel regression can't hide behind an
        # unlabeled tokens/s figure
        if "decode_path" in last:
            row += f" `decode_path={last.get('decode_path')}`"
        # pre-flight phase timings (backend init / first compile / first
        # execute) next to the provenance fields; a degraded line names the
        # phase the device died in
        pf = last.get("preflight")
        if isinstance(pf, dict):
            phases = pf.get("phases_ms") or {}
            shown = " ".join(f"{k}={phases[k]}ms" for k in
                             ("backend_init", "first_compile",
                              "first_execute", "pallas_execute")
                             if k in phases)
            hung = pf.get("timed_out_phase") or pf.get("failed_phase")
            row += (f"\n  - preflight: `ok={pf.get('ok')}` "
                    f"attempts={pf.get('attempts')} {shown}")
            if hung:
                row += f" — **died in `{hung}`**"
        # serving latency distribution: the p50/p95/p99 TTFT/TPOT the
        # serve smoke exists to surface (p99 is where chunked-prefill
        # head-of-line damage shows first)
        sv = last.get("serve")
        if isinstance(sv, dict):
            row += ("\n  - serve: "
                    f"ttft p50={sv.get('ttft_ms_p50')}ms "
                    f"p95={sv.get('ttft_ms_p95')}ms "
                    f"p99={sv.get('ttft_ms_p99')}ms · "
                    f"tpot p50={sv.get('tpot_ms_p50')}ms "
                    f"p95={sv.get('tpot_ms_p95')}ms "
                    f"p99={sv.get('tpot_ms_p99')}ms · "
                    f"requests={sv.get('requests')} "
                    f"errors={sv.get('errors')}")
            if sv.get("decode_parity_checked"):
                row += " · kernel-vs-gather parity: checked"
            # adapter-churn mode: residency hit rate + load latency are the
            # dynamic multi-adapter plane's own north-stars
            ad = sv.get("adapters")
            if isinstance(ad, dict):
                row += ("\n  - adapters: "
                        f"{ad.get('count')} over {ad.get('pool_slots')} "
                        f"pool slots · hit_rate={ad.get('hit_rate')} · "
                        f"loads={ad.get('loads')} "
                        f"evictions={ad.get('evictions')} · "
                        f"load p50={ad.get('load_ms_p50')}ms "
                        f"p95={ad.get('load_ms_p95')}ms")
        # speculative-decoding twin bench: acceptance + the TPOT delta vs
        # the spec-off twin are the headline; the adversarial sub-run's
        # controller verdict proves the never-slower contract
        sp = last.get("spec")
        if isinstance(sp, dict):
            al = sp.get("aligned") or {}
            adv = sp.get("adversarial") or {}
            on_s, off_s = al.get("on") or {}, al.get("off") or {}
            row += ("\n  - spec (aligned): "
                    f"accept={al.get('accept_rate')} "
                    f"mean_len={al.get('mean_accept_len')}/{sp.get('k')} · "
                    f"tpot p50 {on_s.get('tpot_ms_p50')}ms vs "
                    f"{off_s.get('tpot_ms_p50')}ms off "
                    f"(ratio {al.get('tpot_p50_ratio')}) · "
                    f"{on_s.get('tokens_per_sec')} vs "
                    f"{off_s.get('tokens_per_sec')} tok/s")
            if al.get("parity_checked"):
                row += " · spec-vs-off parity: checked"
            adv_on = (adv.get("on") or {})
            adv_off = (adv.get("off") or {})
            row += ("\n  - spec (adversarial): "
                    f"accept={adv.get('accept_rate')} · controller "
                    + ("**disabled spec** " if adv.get("controller_disabled")
                       else "STILL ACTIVE ")
                    + f"(spec_steps={adv.get('spec_steps')} "
                      f"plain_steps={adv.get('plain_steps')}) · "
                      f"tpot p50 {adv_on.get('tpot_ms_p50')}ms vs "
                      f"{adv_off.get('tpot_ms_p50')}ms off")
            # tree-draft sub-run: accept-length p50 tree vs chain at equal
            # draft cost is the headline; the adversarial verdict proves
            # never-slower carries over to trees
            tr = sp.get("tree")
            if isinstance(tr, dict):
                tc = tr.get("contested") or {}
                cc = tr.get("chain_contested") or {}
                row += ("\n  - spec tree "
                        f"`{tr.get('spec_tree')}`: accept_len p50 "
                        f"{tc.get('accept_len_p50')} tree vs "
                        f"{cc.get('accept_len_p50')} chain "
                        f"(lift {tr.get('accept_len_p50_lift')}) · "
                        f"tpot ratio {tc.get('tpot_p50_ratio')} tree vs "
                        f"{cc.get('tpot_p50_ratio')} chain "
                        f"(tree<=chain: {tr.get('tpot_ratio_le_chain')})")
                tadv = tr.get("adversarial") or {}
                row += (" · adversarial: controller "
                        + ("**disabled tree spec**"
                           if tadv.get("controller_disabled")
                           else "STILL ACTIVE"))
                # learned-vs-fixed tree shapes: the learned controller
                # prunes dead branches, so tokens/s must not regress
                tl = tr.get("learned")
                if isinstance(tl, dict):
                    tf = tr.get("fixed") or {}
                    widths = (tl.get("tree") or {}).get("widths")
                    row += ("\n  - spec tree learned: "
                            f"{(tl.get('on') or {}).get('tokens_per_sec')} "
                            f"tok/s vs "
                            f"{(tf.get('on') or {}).get('tokens_per_sec')} "
                            f"fixed "
                            f"(ratio {tr.get('learned_tps_ratio')}, "
                            f"learned>=fixed: {tr.get('learned_ge_fixed')})"
                            f" · widths={widths}")
            # fused sampling epilogue: on-vs-off TPOT on the aligned twin
            # (the run's parity gate already proved token-exactness)
            ep = sp.get("epilogue")
            if isinstance(ep, dict):
                row += ("\n  - sampling epilogue "
                        f"[{ep.get('impl')}]: tpot p50 "
                        f"{(ep.get('on') or {}).get('tpot_ms_p50')}ms on "
                        f"vs {(ep.get('off') or {}).get('tpot_ms_p50')}ms "
                        f"off (ratio {ep.get('tpot_p50_ratio')}, "
                        f"on<=off: {ep.get('tpot_le_off')}) · "
                        f"fused_steps={ep.get('fused_steps')}")
        # KV-overcommit capacity twin: peak concurrent sessions at one
        # block budget is the headline; blocks-per-session and preemption
        # round-trips show HOW the extra sessions fit
        cap = last.get("capacity")
        if isinstance(cap, dict):
            ov = cap.get("overcommit") or {}
            eg = cap.get("eager") or {}
            row += ("\n  - capacity: peak sessions "
                    f"{ov.get('peak_sessions')} overcommit vs "
                    f"{eg.get('peak_sessions')} eager "
                    f"(ratio {cap.get('peak_ratio')}) on "
                    f"{cap.get('kv_blocks')} blocks of "
                    f"{cap.get('block_size')} · "
                    f"{ov.get('tokens_per_sec')} vs "
                    f"{eg.get('tokens_per_sec')} tok/s")
            row += ("\n  - overcommit: blocks/session "
                    f"p50={ov.get('blocks_per_session_p50')} "
                    f"p95={ov.get('blocks_per_session_p95')} · "
                    f"preemptions={ov.get('preemptions')} "
                    f"resumes={ov.get('resumes')} "
                    f"errors={ov.get('errors')}")
            if cap.get("parity_checked"):
                row += " · overcommit-vs-eager parity: checked"
        # multi-tenant QoS twin: the pinned tenant's p95 on/off plus the
        # host adapter tier's hit split — the isolation and the zero-orbax
        # reload story in one row
        tn = last.get("tenant")
        if isinstance(tn, dict):
            on_t = tn.get("qos_on") or {}
            off_t = tn.get("qos_off") or {}
            host = on_t.get("host_tier") or {}
            row += ("\n  - tenant: pinned p95 "
                    f"{on_t.get('plat_ttft_ms_p95')}ms qos-on vs "
                    f"{off_t.get('plat_ttft_ms_p95')}ms off "
                    f"(source={tn.get('p95_source')}) · "
                    f"host tier hit_rate={tn.get('host_hit_rate')} "
                    f"(host_hits={host.get('host_hits')} "
                    f"orbax_loads={host.get('orbax_loads')}) · "
                    f"pinned resident at end: "
                    f"on={on_t.get('pinned_resident_at_end')} "
                    f"off={off_t.get('pinned_resident_at_end')}")
        # load-replay mode: the SLO verdict IS the headline — a chaos run
        # whose objectives held, or the violated objectives by name
        rp = last.get("replay")
        if isinstance(rp, dict):
            chaos_ops = " ".join(
                f"{c.get('op')}@{c.get('t')}s" for c in rp.get("chaos", []))
            row += ("\n  - replay: "
                    f"requests={rp.get('requests')} "
                    f"errors={rp.get('errors')} · "
                    f"ttft p50={rp.get('ttft_ms_p50')}ms "
                    f"p95={rp.get('ttft_ms_p95')}ms "
                    f"p99={rp.get('ttft_ms_p99')}ms · "
                    f"chaos: {chaos_ops or 'none'}")
            ho = rp.get("handoff")
            if isinstance(ho, dict):
                cold = last.get("replay_cold") or {}
                row += ("\n  - replay drain handoff: "
                        f"imported={ho.get('imported', 0)} "
                        f"cold={ho.get('cold', 0)} "
                        f"re_prefills={rp.get('re_prefills', 0)} "
                        f"(handoff-off baseline: "
                        f"re_prefills={cold.get('re_prefills', '?')})")
            if rp.get("slo_pass"):
                row += "\n  - replay SLO verdict: **PASS**"
            else:
                names = "; ".join(rp.get("slo_violations") or []) \
                    or "unknown objective"
                row += f"\n  - replay SLO verdict: **FAIL** — {names}"
    summary = os.environ.get("GITHUB_STEP_SUMMARY")
    if summary:
        with open(summary, "a", encoding="utf-8") as f:
            f.write(row + "\n")
    print(row)
    # a missing line means bench crashed or printed garbage — the step must
    # go red (the smoke jobs are continue-on-error, so this never blocks)
    return 0 if last is not None else 1


if __name__ == "__main__":
    sys.exit(main())
