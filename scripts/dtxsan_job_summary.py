"""Append one dtxsan raw-report verdict line to the GitHub job summary.

Usage: dtxsan_job_summary.py LABEL FILE — FILE is the raw report the
pytest plugin writes (``DTX_SAN_REPORT=...`` / ``dtx san --report``).
The row leads with the verdict, then the per-rule finding split and the
compile counters, so the checks tab shows WHAT the sanitizers saw, not
just red/green. Stdlib-only, like the rest of analysis/.
"""

import json
import os
import sys


def main() -> int:
    if len(sys.argv) != 3:
        print("usage: dtxsan_job_summary.py LABEL FILE", file=sys.stderr)
        return 2
    label, path = sys.argv[1], sys.argv[2]
    try:
        with open(path, encoding="utf-8") as f:
            doc = json.load(f)
    except (OSError, ValueError) as e:
        row = f"- **{label}**: no dtxsan report produced ({e})"
        _emit(row)
        return 1

    findings = doc.get("findings", [])
    by_rule = {}
    for f in findings:
        by_rule[f.get("rule", "?")] = by_rule.get(f.get("rule", "?"), 0) + 1
    split = " ".join(f"{r}={n}" for r, n in sorted(by_rule.items())) \
        or "none"
    counters = doc.get("counters", {})
    verdict = "**CLEAN**" if not findings else \
        f"**{len(findings)} finding(s)**"
    row = (f"- **{label}**: {verdict} — findings: {split} · "
           f"suppressed={doc.get('suppressed', 0)} · "
           f"classes={','.join(doc.get('classes', [])) or '?'} · "
           f"compiles: {counters.get('lowerings', '?')} lowered / "
           f"{counters.get('backend_compiles', '?')} backend")
    for f in findings[:8]:
        row += (f"\n  - `{f.get('rule')}` {f.get('path')}:{f.get('line')} "
                f"— {f.get('message', '')[:160]}")
    if len(findings) > 8:
        row += f"\n  - … and {len(findings) - 8} more"
    _emit(row)
    return 0 if not findings else 1


def _emit(row: str):
    summary = os.environ.get("GITHUB_STEP_SUMMARY")
    if summary:
        with open(summary, "a", encoding="utf-8") as f:
            f.write(row + "\n")
    print(row)


if __name__ == "__main__":
    sys.exit(main())
