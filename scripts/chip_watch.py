"""Chip-health watcher (VERDICT r3 next-round #1).

The tunneled TPU relay wedges for hours at a time (r1: timeout, r3: wedged
all round); every perf claim in this project is blocked on catching a
healthy window. This daemon:

1. probes the default device with a tiny matmul IN A SUBPROCESS every
   ``--interval`` seconds (a wedged relay hangs rather than errors, and a
   process that touched the wedged platform can't recover — isolation is
   mandatory), appending every probe to the committed ``CHIPWATCH.log``;
2. on the FIRST successful probe, runs the full evidence-capture sequence:
     a. ``scripts/tpu_validate.py``        -> TPU_VALIDATE.log
     b. ``scripts/bench_7b.py`` (pallas)   -> line in BENCH_7B_TPU.json
     c. ``scripts/bench_7b.py`` (xla)      -> line in BENCH_7B_TPU.json
     d. ``bench.py``                       -> persists BENCH_TPU.json itself
     e. ``scripts/bench_serving.py``       -> persists BENCH_SERVING_TPU.json
   re-probing between phases (the relay can wedge mid-window; a wedge costs
   that child's timeout, not the artifacts already captured);
3. writes ``CHIPWATCH_RESULT.json`` summarizing what landed, and exits 0.

If the deadline passes with no healthy window, the log itself is the
evidence that the relay never answered; exit 3.

Run (round open):  nohup python scripts/chip_watch.py &
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time
from datetime import datetime, timezone

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LOG = os.path.join(REPO, "CHIPWATCH.log")

PROBE_CODE = (
    "import jax, jax.numpy as jnp;"
    "assert jax.default_backend() in ('tpu','axon'), jax.default_backend();"
    "x = jnp.ones((256, 256), jnp.float32);"
    "print(float((x @ x)[0, 0]))"
)


def now() -> str:
    return datetime.now(timezone.utc).isoformat(timespec="seconds")


def log(msg: str) -> None:
    line = f"{now()} {msg}"
    print(line, flush=True)
    with open(LOG, "a") as f:
        f.write(line + "\n")


def probe(timeout_s: float) -> bool:
    """One isolated device probe; True iff the chip multiplied matrices."""
    try:
        p = subprocess.run(
            [sys.executable, "-c", PROBE_CODE],
            timeout=timeout_s, capture_output=True, text=True, cwd=REPO,
        )
        return p.returncode == 0 and "256.0" in p.stdout
    except subprocess.TimeoutExpired:
        return False


def run_phase(name: str, argv: list[str], timeout_s: float,
              logfile: str | None = None) -> dict:
    """Run one capture phase as a subprocess; return a summary record."""
    log(f"phase {name}: start ({' '.join(argv)})")
    t0 = time.monotonic()
    try:
        p = subprocess.run(
            argv, timeout=timeout_s, capture_output=True, text=True, cwd=REPO,
        )
        rc, out, err = p.returncode, p.stdout, p.stderr
        timed_out = False
    except subprocess.TimeoutExpired as e:
        rc, timed_out = -1, True
        out = (e.stdout or b"").decode() if isinstance(e.stdout, bytes) \
            else (e.stdout or "")
        err = (e.stderr or b"").decode() if isinstance(e.stderr, bytes) \
            else (e.stderr or "")
    dt = time.monotonic() - t0
    if logfile:
        with open(os.path.join(REPO, logfile), "a") as f:
            f.write(f"=== {now()} {name} rc={rc} dt={dt:.0f}s ===\n")
            f.write(out)
            if err:
                f.write("\n--- stderr ---\n" + err[-8000:])
            f.write("\n")
    # last JSON line, if the phase emits one
    parsed = None
    for line in reversed(out.strip().splitlines()):
        try:
            obj = json.loads(line)
            if isinstance(obj, dict):
                parsed = obj
                break
        except ValueError:
            continue
    log(f"phase {name}: rc={rc}{' TIMEOUT' if timed_out else ''} "
        f"dt={dt:.0f}s")
    return {"name": name, "rc": rc, "timed_out": timed_out,
            "seconds": round(dt, 1), "json": parsed}


def capture(args) -> list[dict]:
    """The full evidence sequence, with re-probes between phases."""
    phases = []

    def alive() -> bool:
        ok = probe(args.probe_timeout)
        if not ok:
            log("re-probe failed — relay wedged mid-window; waiting for the "
                "next healthy window for remaining phases")
        return ok

    phases.append(run_phase(
        "tpu_validate",
        [sys.executable, os.path.join(REPO, "scripts", "tpu_validate.py")],
        timeout_s=1500, logfile="TPU_VALIDATE.log"))

    results7b = []
    for impl in ("pallas", "xla"):
        if not alive():
            return phases
        rec = run_phase(
            f"bench_7b_{impl}",
            [sys.executable, os.path.join(REPO, "scripts", "bench_7b.py"),
             "--quant_impl", impl, "--steps", str(args.bench_7b_steps)],
            timeout_s=2400, logfile="TPU_VALIDATE.log")
        phases.append(rec)
        if rec["json"] is not None:
            results7b.append(rec["json"])
    if results7b:
        with open(os.path.join(REPO, "BENCH_7B_TPU.json"), "w") as f:
            json.dump({"timestamp": now(),
                       "hardware": "TPU v5e-1 (tunneled)",
                       "lines": results7b}, f, indent=1)
            f.write("\n")
        log("persisted BENCH_7B_TPU.json")

    if not alive():
        return phases
    phases.append(run_phase(
        "bench", [sys.executable, os.path.join(REPO, "bench.py")],
        timeout_s=900))  # persists BENCH_TPU.json on success

    if not alive():
        return phases
    phases.append(run_phase(
        "bench_serving",
        [sys.executable, os.path.join(REPO, "scripts", "bench_serving.py")],
        timeout_s=1200))  # persists BENCH_SERVING_TPU.json on success

    return phases


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--interval", type=float, default=180.0,
                    help="seconds between probes while wedged")
    ap.add_argument("--probe-timeout", type=float, default=75.0)
    ap.add_argument("--deadline-hours", type=float, default=11.0,
                    help="give up (exit 3) after this long with no window")
    ap.add_argument("--bench-7b-steps", type=int, default=10)
    ap.add_argument("--once", action="store_true",
                    help="single probe + capture attempt, no wait loop")
    args = ap.parse_args()

    t_start = time.monotonic()
    log(f"chip_watch start pid={os.getpid()} interval={args.interval:.0f}s "
        f"deadline={args.deadline_hours:.1f}h")
    n = 0
    while True:
        n += 1
        ok = probe(args.probe_timeout)
        log(f"probe #{n}: {'HEALTHY' if ok else 'wedged/hung'}")
        if ok:
            phases = capture(args)
            artifacts = [p for p in (
                "BENCH_TPU.json", "BENCH_7B_TPU.json",
                "BENCH_SERVING_TPU.json", "TPU_VALIDATE.log")
                if os.path.exists(os.path.join(REPO, p))]
            result = {
                "timestamp": now(), "probes": n,
                "wait_seconds": round(time.monotonic() - t_start, 0),
                "phases": phases, "artifacts": artifacts,
            }
            with open(os.path.join(REPO, "CHIPWATCH_RESULT.json"), "w") as f:
                json.dump(result, f, indent=1)
                f.write("\n")
            log(f"capture complete: artifacts={artifacts}")
            return 0
        if args.once:
            return 3
        if time.monotonic() - t_start > args.deadline_hours * 3600:
            log("deadline reached with no healthy window — relay never "
                "answered; the probe log above is the evidence")
            return 3
        time.sleep(args.interval)


if __name__ == "__main__":
    sys.exit(main())
