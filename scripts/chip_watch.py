"""Chip-health watcher (VERDICT r3 next-round #1).

The tunneled TPU relay wedges for hours at a time (r1: timeout, r3: wedged
all round); every perf claim in this project is blocked on catching a
healthy window. This daemon:

1. probes the default device with a tiny matmul IN A SUBPROCESS every
   ``--interval`` seconds (a wedged relay hangs rather than errors, and a
   process that touched the wedged platform can't recover — isolation is
   mandatory), appending every probe to the committed ``CHIPWATCH.log``;
2. on the FIRST successful probe, runs the full evidence-capture sequence:
     a. ``scripts/tpu_validate.py``        -> TPU_VALIDATE.log
     b. ``scripts/bench_7b.py`` (pallas)   -> line in BENCH_7B_TPU.json
     c. ``scripts/bench_7b.py`` (xla)      -> line in BENCH_7B_TPU.json
     d. ``bench.py``                       -> persists BENCH_TPU.json itself
     e. ``scripts/bench_serving.py``       -> persists BENCH_SERVING_TPU.json
   re-probing between phases (the relay can wedge mid-window; a wedge costs
   that child's timeout, not the artifacts already captured);
3. writes ``CHIPWATCH_RESULT.json`` summarizing what landed, and exits 0.

If the deadline passes with no healthy window, the log itself is the
evidence that the relay never answered; exit 3.

Run (round open):  nohup python scripts/chip_watch.py &
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time
from datetime import datetime, timezone

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LOG = os.path.join(REPO, "CHIPWATCH.log")

PROBE_CODE = (
    "import jax, jax.numpy as jnp;"
    "assert jax.default_backend() in ('tpu','axon'), jax.default_backend();"
    "x = jnp.ones((256, 256), jnp.float32);"
    "print(float((x @ x)[0, 0]))"
)


def now() -> str:
    return datetime.now(timezone.utc).isoformat(timespec="seconds")


def log(msg: str) -> None:
    line = f"{now()} {msg}"
    print(line, flush=True)
    with open(LOG, "a") as f:
        f.write(line + "\n")


def probe(timeout_s: float) -> bool:
    """One isolated device probe; True iff the chip multiplied matrices."""
    try:
        p = subprocess.run(
            [sys.executable, "-c", PROBE_CODE],
            timeout=timeout_s, capture_output=True, text=True, cwd=REPO,
        )
        return p.returncode == 0 and "256.0" in p.stdout
    except subprocess.TimeoutExpired:
        return False


def run_phase(name: str, argv: list[str], timeout_s: float,
              logfile: str | None = None) -> dict:
    """Run one capture phase as a subprocess; return a summary record."""
    log(f"phase {name}: start ({' '.join(argv)})")
    t0 = time.monotonic()
    try:
        p = subprocess.run(
            argv, timeout=timeout_s, capture_output=True, text=True, cwd=REPO,
        )
        rc, out, err = p.returncode, p.stdout, p.stderr
        timed_out = False
    except subprocess.TimeoutExpired as e:
        rc, timed_out = -1, True
        out = (e.stdout or b"").decode() if isinstance(e.stdout, bytes) \
            else (e.stdout or "")
        err = (e.stderr or b"").decode() if isinstance(e.stderr, bytes) \
            else (e.stderr or "")
    dt = time.monotonic() - t0
    if logfile:
        with open(os.path.join(REPO, logfile), "a") as f:
            f.write(f"=== {now()} {name} rc={rc} dt={dt:.0f}s ===\n")
            f.write(out)
            if err:
                f.write("\n--- stderr ---\n" + err[-8000:])
            f.write("\n")
    # last JSON line, if the phase emits one
    parsed = None
    for line in reversed(out.strip().splitlines()):
        try:
            obj = json.loads(line)
            if isinstance(obj, dict):
                parsed = obj
                break
        except ValueError:
            continue
    log(f"phase {name}: rc={rc}{' TIMEOUT' if timed_out else ''} "
        f"dt={dt:.0f}s")
    return {"name": name, "rc": rc, "timed_out": timed_out,
            "seconds": round(dt, 1), "json": parsed}


ARTIFACTS = ("BENCH_TPU.json", "BENCH_7B_TPU.json",
             "BENCH_SERVING_TPU.json", "TPU_VALIDATE.log")


def phase_plan(args) -> list[tuple[str, list, float, str | None]]:
    """(name, argv, timeout_s, logfile) in capture order."""
    py = sys.executable
    return [
        ("tpu_validate",
         [py, os.path.join(REPO, "scripts", "tpu_validate.py")],
         1500, "TPU_VALIDATE.log"),
        ("bench_7b_pallas",
         [py, os.path.join(REPO, "scripts", "bench_7b.py"),
          "--quant_impl", "pallas", "--steps", str(args.bench_7b_steps)],
         2400, "TPU_VALIDATE.log"),
        ("bench_7b_xla",
         [py, os.path.join(REPO, "scripts", "bench_7b.py"),
          "--quant_impl", "xla", "--steps", str(args.bench_7b_steps)],
         2400, "TPU_VALIDATE.log"),
        ("bench", [py, os.path.join(REPO, "bench.py")], 900, None),
        ("bench_serving",
         [py, os.path.join(REPO, "scripts", "bench_serving.py")],
         1200, None),
    ]


MAX_ATTEMPTS = 3


def capture(args, done: dict, attempts: dict) -> bool:
    """Run the not-yet-settled phases of the evidence sequence, re-probing
    between phases. ``done`` maps phase name → record and persists across
    windows, so a mid-window wedge resumes (not restarts) at the next
    healthy window. Returns True when every phase has a settled outcome.

    Settled = the phase succeeded, OR it failed (rc != 0 / timeout) while
    the relay stayed healthy — a genuine failure, not wedge collateral —
    OR it has burned MAX_ATTEMPTS windows. A failure with a wedged relay
    stays eligible for retry."""
    for name, argv, timeout_s, logfile in phase_plan(args):
        if name in done:
            continue
        attempts[name] = attempts.get(name, 0) + 1
        rec = run_phase(name, argv, timeout_s, logfile)
        relay_ok = probe(args.probe_timeout)
        failed = rec["timed_out"] or rec["rc"] != 0
        if failed and not relay_ok and attempts[name] < MAX_ATTEMPTS:
            log(f"phase {name}: failed (rc={rec['rc']}) with the relay "
                f"wedged (attempt {attempts[name]}/{MAX_ATTEMPTS}) — will "
                "retry in the next healthy window")
            return False
        done[name] = rec
        if name.startswith("bench_7b") and rec["json"] is not None:
            lines = [done[k]["json"] for k in ("bench_7b_pallas",
                                               "bench_7b_xla")
                     if k in done and done[k]["json"] is not None]
            with open(os.path.join(REPO, "BENCH_7B_TPU.json"), "w") as f:
                json.dump({"timestamp": now(),
                           "hardware": "TPU v5e-1 (tunneled)",
                           "lines": lines}, f, indent=1)
                f.write("\n")
            log("persisted BENCH_7B_TPU.json")
        remaining = [n for n, *_ in phase_plan(args) if n not in done]
        if not relay_ok and remaining:
            log("re-probe failed — relay wedged mid-window; waiting for "
                "the next healthy window for remaining phases")
            return False
    return True


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--interval", type=float, default=180.0,
                    help="seconds between probes while wedged")
    ap.add_argument("--probe-timeout", type=float, default=75.0)
    ap.add_argument("--deadline-hours", type=float, default=11.0,
                    help="give up (exit 3) after this long with no window")
    ap.add_argument("--bench-7b-steps", type=int, default=10)
    ap.add_argument("--once", action="store_true",
                    help="single probe + capture attempt, no wait loop")
    args = ap.parse_args()

    t_start = time.monotonic()
    # only artifacts WRITTEN BY THIS RUN may be reported — a stale file from
    # a previous round must not read as captured by this window
    t_wall_start = time.time()
    log(f"chip_watch start pid={os.getpid()} interval={args.interval:.0f}s "
        f"deadline={args.deadline_hours:.1f}h")
    n = 0
    done: dict = {}
    attempts: dict = {}

    def finish(code: int) -> int:
        fresh = [p for p in ARTIFACTS
                 if os.path.exists(os.path.join(REPO, p))
                 and os.path.getmtime(os.path.join(REPO, p)) >= t_wall_start]
        result = {
            "timestamp": now(), "probes": n, "complete": code == 0,
            "wait_seconds": round(time.monotonic() - t_start, 0),
            "phases": list(done.values()), "artifacts": fresh,
        }
        with open(os.path.join(REPO, "CHIPWATCH_RESULT.json"), "w") as f:
            json.dump(result, f, indent=1)
            f.write("\n")
        log(f"{'capture complete' if code == 0 else 'exiting incomplete'}: "
            f"fresh artifacts={fresh}")
        return code

    while True:
        n += 1
        ok = probe(args.probe_timeout)
        log(f"probe #{n}: {'HEALTHY' if ok else 'wedged/hung'}")
        if ok and capture(args, done, attempts):
            return finish(0)
        if args.once:
            return finish(3)
        if time.monotonic() - t_start > args.deadline_hours * 3600:
            if done:
                log("deadline reached with capture incomplete — partial "
                    "phases recorded")
            else:
                log("deadline reached with no healthy window — relay never "
                    "answered; the probe log above is the evidence")
            return finish(3)
        time.sleep(args.interval)


if __name__ == "__main__":
    sys.exit(main())
