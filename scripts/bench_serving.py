"""Serving throughput benchmark (VERDICT r2 next-round #7): continuous-batching
decode tokens/s vs slot count, plus the prefix-cache hit path.

The serving half of the parity story — the reference serves via Ray Serve
LlamaDeployment replicas (reference pkg/util/generate/generate.go:160-329);
here one BatchedEngine decodes S slots inside a single jitted program.

Prints one JSON line per configuration:
  {"metric": "serving_decode_tokens_per_sec[tinyllama-1.1b,slots=4]", ...}
plus a prefix-cache line (admission latency with/without a warm prefix).

CPU fallback: marked "cpu_fallback": true with the debug preset (shape
signal only, no TPU claim) — same honesty contract as bench.py.

Run: python scripts/bench_serving.py [--slots 1,4,8] [--tokens 128]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def bench_slots(model: str, slots: int, gen_tokens: int, prompt_len: int,
                max_seq: int, cpu_fallback: bool) -> dict:
    """Saturate all S slots with concurrent requests; measure aggregate
    emitted tokens/s from submit of the batch to last completion."""
    from datatunerx_tpu.serving.batched_engine import BatchedEngine

    eng = BatchedEngine(model, template="vanilla", max_seq_len=max_seq,
                        slots=slots, decode_chunk=8)
    try:
        import numpy as np

        rng = np.random.default_rng(0)
        prompts = [
            [int(t) for t in rng.integers(10, 1000, prompt_len)]
            for _ in range(slots)
        ]
        # warmup: compile prefill + decode chunks, fill each slot once
        for p in prompts[:1]:
            eng.generate(p, max_new_tokens=8, timeout=900)

        t0 = time.perf_counter()
        reqs = [eng.submit(p, max_new_tokens=gen_tokens, temperature=0.0,
                           stop_ids={-1})  # unreachable stop: full budget
                for p in prompts]
        total = 0
        for r in reqs:
            if not r.done.wait(timeout=900):
                raise TimeoutError("decode timed out")
            if r.error:
                raise RuntimeError(r.error)
            total += len(r.tokens)
        dt = time.perf_counter() - t0
        tag = f"{model.split(':')[-1]},slots={slots},gen={gen_tokens}"
        lines = [
            {
                "metric": f"serving_decode_tokens_per_sec[{tag}]",
                "value": round(total / dt, 1),
                "unit": "tokens/s",
                "vs_baseline": None,
            },
            # per-slot steady-state decode rate: the number that composes
            # across TPU runs and slot counts (VERDICT r3 #8)
            {
                "metric": f"serving_decode_tokens_per_sec_per_slot[{tag}]",
                "value": round(total / dt / slots, 1),
                "unit": "tokens/s/slot",
                "vs_baseline": None,
            },
        ]
        if cpu_fallback:
            for line in lines:
                line["cpu_fallback"] = True
        return lines
    finally:
        eng.close()


def bench_prefix_cache(model: str, prompt_len: int, max_seq: int,
                       cpu_fallback: bool) -> dict:
    """Admission cost with a warm longest-prefix hit vs a cold full prefill:
    the trie lookup + suffix-extension path end-to-end."""
    from datatunerx_tpu.serving.batched_engine import BatchedEngine

    eng = BatchedEngine(model, template="vanilla", max_seq_len=max_seq,
                        slots=2, decode_chunk=4, prefix_cache=8)
    try:
        import numpy as np

        rng = np.random.default_rng(1)
        base = [int(t) for t in rng.integers(10, 1000, prompt_len)]
        tail1 = [int(t) for t in rng.integers(10, 1000, 16)]
        tail2 = [int(t) for t in rng.integers(10, 1000, 16)]

        eng.generate(base, max_new_tokens=1, timeout=900)  # warm prefill+cache
        # first extension COMPILES the suffix-extension program — warm it so
        # the timed run measures steady-state admission, not XLA compile
        eng.generate(base + tail1, max_new_tokens=1, timeout=900)

        t0 = time.perf_counter()
        eng.generate(base + tail2, max_new_tokens=1, timeout=900)
        warm = time.perf_counter() - t0
        assert eng.prefill_stats["extend"] >= 2, eng.prefill_stats

        cold_eng_stats = dict(eng.prefill_stats)
        rng2 = np.random.default_rng(2)
        cold_prompt = [int(t) for t in rng2.integers(10, 1000,
                                                     prompt_len + 16)]
        t0 = time.perf_counter()
        eng.generate(cold_prompt, max_new_tokens=1, timeout=900)
        cold = time.perf_counter() - t0
        assert eng.prefill_stats["full"] == cold_eng_stats["full"] + 1

        tag = f"{model.split(':')[-1]},prompt={prompt_len}"
        lines = [
            # absolute admission latencies in ms (VERDICT r3 #8): these
            # compose with TPU runs directly, unlike the ratio
            {
                "metric": f"serving_admission_latency_ms[{tag},warm_prefix]",
                "value": round(warm * 1e3, 2),
                "unit": "ms",
                "vs_baseline": None,
            },
            {
                "metric": f"serving_admission_latency_ms[{tag},cold]",
                "value": round(cold * 1e3, 2),
                "unit": "ms",
                "vs_baseline": None,
            },
            {
                "metric": f"serving_prefix_hit_speedup[{tag}]",
                "value": round(cold / max(warm, 1e-9), 2),
                "unit": "x (cold prefill / warm suffix-extension latency)",
                "vs_baseline": None,
            },
        ]
        if cpu_fallback:
            for line in lines:
                line["cpu_fallback"] = True
        return lines
    finally:
        eng.close()


def bench_multi_adapter(model: str, n_adapters: int, gen_tokens: int,
                        prompt_len: int, max_seq: int,
                        cpu_fallback: bool) -> list:
    """BASELINE row 6 schema: N tuned checkpoints served side-by-side by ONE
    engine (stacked adapters, per-slot indexing) — per-adapter admission
    latency + per-slot decode tok/s while all N decode concurrently."""
    import tempfile

    from datatunerx_tpu.serving.adapters import make_adapter_checkpoint
    from datatunerx_tpu.serving.batched_engine import BatchedEngine

    tag = f"{model.split(':')[-1]},adapters={n_adapters}"
    with tempfile.TemporaryDirectory() as tmp:
        paths = {
            f"a{i}": make_adapter_checkpoint(f"{tmp}/ckpt{i}", model, seed=i,
                                             rank=8)
            for i in range(n_adapters)
        }
        eng = BatchedEngine(model, adapters=paths, template="vanilla",
                            max_seq_len=max_seq, slots=n_adapters,
                            decode_chunk=8)
        try:
            import numpy as np

            rng = np.random.default_rng(3)
            prompts = {name: [int(t) for t in rng.integers(10, 1000,
                                                           prompt_len)]
                       for name in paths}
            # warm compile (prefill + decode with adapter indexing)
            eng.generate(prompts["a0"], max_new_tokens=4, adapter="a0",
                         timeout=900)

            lines = []
            # per-adapter admission latency: prefill + first token
            for name in paths:
                t0 = time.perf_counter()
                eng.generate(prompts[name], max_new_tokens=1, adapter=name,
                             timeout=900)
                lines.append({
                    "metric": (f"serving_admission_latency_ms[{tag},"
                               f"slot={name}]"),
                    "value": round((time.perf_counter() - t0) * 1e3, 2),
                    "unit": "ms",
                    "vs_baseline": None,
                })

            # concurrent decode: one request per adapter, all slots busy
            t0 = time.perf_counter()
            reqs = {name: eng.submit(prompts[name],
                                     max_new_tokens=gen_tokens,
                                     temperature=0.0, stop_ids={-1},
                                     adapter=name)
                    for name in paths}
            per_slot = {}
            for name, r in reqs.items():
                if not r.done.wait(timeout=900):
                    raise TimeoutError(f"adapter {name} decode timed out")
                if r.error:
                    raise RuntimeError(r.error)
                per_slot[name] = len(r.tokens)
            dt = time.perf_counter() - t0
            for name, n_tok in sorted(per_slot.items()):
                lines.append({
                    "metric": (f"serving_multi_adapter_decode_tokens_per_sec"
                               f"[{tag},slot={name}]"),
                    "value": round(n_tok / dt, 1),
                    "unit": "tokens/s",
                    "vs_baseline": None,
                })
            lines.append({
                "metric": (f"serving_multi_adapter_decode_tokens_per_sec"
                           f"[{tag},aggregate]"),
                "value": round(sum(per_slot.values()) / dt, 1),
                "unit": "tokens/s",
                "vs_baseline": None,
            })
            if cpu_fallback:
                for line in lines:
                    line["cpu_fallback"] = True
            return lines
        finally:
            eng.close()


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--slots", default="1,4,8")
    ap.add_argument("--tokens", type=int, default=128)
    ap.add_argument("--prompt_len", type=int, default=64)
    args = ap.parse_args()

    import jax

    on_tpu = jax.default_backend() == "tpu"
    if on_tpu:
        model, max_seq = "preset:tinyllama-1.1b", 1024
        gen_tokens, prompt_len = args.tokens, args.prompt_len
    else:
        model, max_seq = "preset:debug", 256
        gen_tokens, prompt_len = min(args.tokens, 32), min(args.prompt_len, 32)

    results = []
    for s in [int(x) for x in args.slots.split(",") if x]:
        for line in bench_slots(model, s, gen_tokens, prompt_len, max_seq,
                                cpu_fallback=not on_tpu):
            print(json.dumps(line), flush=True)
            results.append(line)
    for line in bench_prefix_cache(model, prompt_len, max_seq,
                                   cpu_fallback=not on_tpu):
        print(json.dumps(line), flush=True)
        results.append(line)
    for line in bench_multi_adapter(model, 3, gen_tokens, prompt_len, max_seq,
                                    cpu_fallback=not on_tpu):
        print(json.dumps(line), flush=True)
        results.append(line)

    if on_tpu:
        from datetime import datetime, timezone

        doc = {
            "timestamp": datetime.now(timezone.utc).isoformat(
                timespec="seconds"),
            "hardware": "TPU v5e-1 (tunneled)",
            "lines": results,
        }
        out = os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "BENCH_SERVING_TPU.json")
        with open(out, "w") as f:
            json.dump(doc, f, indent=1)
            f.write("\n")
        print(f"[bench_serving] wrote {out}", file=sys.stderr)


if __name__ == "__main__":
    main()
