"""Generate CRD manifests (deploy/crds/) + webhook configs (deploy/webhooks.yaml)
for the 8 kinds in operator/api.py.

Rendering lives in datatunerx_tpu/operator/crdgen.py (so `dtx install` can use
it too); this script writes the files kubectl users apply directly.

Run: python scripts/gen_crds.py
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import yaml  # noqa: E402

from datatunerx_tpu.operator.api import ALL_KINDS  # noqa: E402
from datatunerx_tpu.operator.crdgen import crd_for, webhook_manifests  # noqa: E402

OUT_DIR = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                       "deploy", "crds")


def main():
    os.makedirs(OUT_DIR, exist_ok=True)
    for cls in ALL_KINDS:
        group, _, _ = cls.api_version.partition("/")
        path = os.path.join(OUT_DIR, f"{cls.kind.lower()}s.{group}.yaml")
        with open(path, "w") as f:
            yaml.safe_dump(crd_for(cls), f, sort_keys=False)
        print(f"wrote {path}")
    wh_path = os.path.join(os.path.dirname(OUT_DIR), "webhooks.yaml")
    with open(wh_path, "w") as f:
        yaml.safe_dump_all(webhook_manifests(), f, sort_keys=False)
    print(f"wrote {wh_path}")


if __name__ == "__main__":
    main()
