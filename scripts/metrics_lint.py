"""Exposition-format + naming lint for the gateway, serving, experiment
and loadgen /metrics.

Builds each plane's exposition IN PROCESS (the same bytes a scraper gets:
`Gateway.metrics_text()` — including the per-replica traffic-weight and
attempt-outcome series the canary promotion reads, plus the dtx_slo_*
verdict gauges — the serving server's `metrics_text()` against a
duck-typed engine, an `ExperimentMetrics` registry driven through one
simulated closed-loop pass, and a load-replay recording pass whose TTFT
histogram carries a trace-id exemplar so the OpenMetrics exemplar format
stays under this blocking gate), then validates:

  format  — the invariants a real Prometheus server enforces: one # TYPE
            line per metric preceding all its samples, no duplicate
            series, parseable samples, escaped label values, trailing
            newline.
  naming  — house conventions the dashboards rely on: every metric starts
            with ``dtx_``, carries its plane (``dtx_gateway_`` /
            ``dtx_serving_`` — shared identity series like
            ``dtx_build_info`` are the deliberate exceptions), counters
            end in ``_total``, and time-valued metrics carry an explicit
            unit suffix (``_ms`` / ``_seconds``).

Run by tier1.yml next to dtxlint: a metric added with the wrong shape
fails the PR, not the dashboard. Exit 0 clean, 1 on findings.
"""

import re
import sys

sys.path.insert(0, __file__.rsplit("/", 2)[0])  # repo root when run from CI

NAME_RE = re.compile(r"^[a-z][a-z0-9_]*$")
# metrics whose name carries no plane prefix on purpose (shared identity /
# process series stated by obs.metrics on every plane)
SHARED_NAMES = {"dtx_build_info"}
# shared FAMILIES: the SLO verdict gauges (obs/slo.py) are restated into
# every plane's registry under one name so dashboards join them across
# planes on the {slo} label; the fleet-plane series (fleet/) describe
# cross-replica state and keep one name wherever they surface
SHARED_PREFIXES = ("dtx_slo_", "dtx_fleet_")
# words that mean "this samples a duration" and demand a unit suffix
TIME_WORDS = ("latency", "wait", "duration", "uptime", "elapsed", "ttft",
              "tpot")
UNIT_SUFFIXES = ("_ms", "_seconds", "_ms_bucket", "_ms_sum", "_ms_count",
                 "_seconds_bucket", "_seconds_sum", "_seconds_count")


def lint_exposition(text: str, plane: str):
    """-> list of finding strings for one server's exposition."""
    from tests.test_prometheus_exposition import parse_exposition

    findings = []
    try:
        _, types = parse_exposition(text)
    except AssertionError as e:
        return [f"{plane}: exposition format invalid: {e}"]
    for name, mtype in sorted(types.items()):
        where = f"{plane}: {name}"
        if not NAME_RE.match(name):
            findings.append(f"{where}: invalid metric name")
        if not name.startswith("dtx_"):
            findings.append(f"{where}: missing dtx_ prefix")
        elif (name not in SHARED_NAMES
              and not name.startswith(SHARED_PREFIXES)
              and not name.startswith(f"dtx_{plane}_")):
            findings.append(
                f"{where}: missing plane prefix dtx_{plane}_ (shared "
                "names must be registered in metrics_lint SHARED_NAMES "
                "or SHARED_PREFIXES)")
        if mtype == "counter" and not name.endswith("_total"):
            findings.append(f"{where}: counter must end in _total")
        if mtype != "counter" and name.endswith("_total"):
            findings.append(f"{where}: _total suffix on a {mtype}")
        if (any(w in name for w in TIME_WORDS)
                and not name.endswith(("_ms", "_seconds"))):
            findings.append(
                f"{where}: time-valued metric needs a _ms or _seconds "
                "unit suffix")
    return findings


class _StatsEngine:
    """Duck-typed engine exposing what serving.metrics_text reads —
    including the dynamic adapter plane (pool occupancy, residency sets,
    per-adapter request counters) so every dtx_serving_adapter_* series
    is built and linted."""

    slots = 4
    _slot_req = [object(), None, None, None]
    prefill_stats = {"full": 2, "reuse": 1, "extend": 0}
    # paged KV pool + overcommit plane: free/reserved/block-size gauges,
    # the overcommit ratio, and the preemption outcome counter — built and
    # linted on BOTH planes (the gateway pass scrapes these through the
    # InProcessReplica stats surface into its per-replica gauges)
    total_kv_blocks = 32
    free_kv_blocks = 20
    kv_blocks_reserved = 12
    block_size = 16
    kv_overcommit_ratio = 1.5
    preempt_stats = {"exported": 3, "resumed": 2, "requeued_prefill": 1}
    # disaggregation plane: parked-session depth behind the spill
    # coordinator's eligibility scan (dtx_serving_sessions_parked)
    parked_sessions = 1
    # KV migration fabric outcome counters (dtx_serving_session_* series)
    session_stats = {"export": {"ok": 2, "skipped_prefill": 1},
                     "import": {"ok": 2, "refused": 1}}
    adapter_ids = {"": 0, "tenant-a": 1, "tenant-b": -1}
    resident_adapters = {"tenant-a": 1}
    adapter_requests = {"": 3, "tenant-a": 2, "tenant-b": 1}
    # fused sampling epilogue: the mode gauge + per-path tick counter
    # (dtx_serving_sampling_*) read straight off the engine, so the lint
    # document carries both attributes
    _epilogue_impl = "xla"
    sampling_stats = {"fused_steps": 7, "legacy_steps": 2}

    # multi-tenant QoS plane: tenant_usage() turns the dtx_serving_tenant_*
    # families on, and the registry stub's host_tier_stats() builds every
    # dtx_serving_adapter_host_* / orbax-load series — both absent at
    # defaults by design, so the lint must opt in here to cover them
    class _HostTierRegistry:
        @staticmethod
        def host_tier_stats():
            return {"host_hits": 2, "orbax_loads": 1, "evictions": 1,
                    "bytes": 1 << 16, "entries": 1}

    adapter_registry = _HostTierRegistry()

    def tenant_usage(self):
        return {"acme": {"requests": 3, "tokens_in": 120, "tokens_out": 40,
                         "kv_blocks": 6, "adapters_resident": 1,
                         "tier": "pinned"},
                "": {"requests": 1, "tokens_in": 10, "tokens_out": 4}}

    def adapter_occupancy(self):
        return {"slots": 4, "free": 3, "resident": 1, "pinned": 0,
                "rank_max": 8, "targets": ["q_proj", "v_proj"],
                "registered": 2, "hbm_bytes": 1 << 20,
                "loads": 2, "evictions": 1, "hits": 1, "misses": 2,
                "resident_adapters": ["tenant-a"],
                "registered_adapters": ["tenant-a", "tenant-b"],
                "load_ms": [12.5], "requests": dict(self.adapter_requests)}

    def spec_info(self):
        # speculative-decoding document: builds every dtx_serving_spec_*
        # series (incl. the per-adapter/per-slot EMA gauges) AND feeds the
        # gateway's per-replica acceptance gauge through replica stats
        # the tree sub-document turns the dtx_serving_spec_tree_* families
        # on (steps counter, width/depth gauges, per-slot path-length EMA)
        # so both the serving pass and the gateway's replica-stats pass
        # lint them
        return {"enabled": True, "mode": "auto", "draft": "take:2",
                "k_max": 4, "k": 2, "accept_rate": 0.62,
                "adapter_accept_rate": {"": 0.7, "tenant-a": 0.5},
                "slot_accept_rate": {0: 0.62}, "slots_off": [],
                "active": True, "disabled_events": 1,
                "proposed": 40, "accepted": 25, "row_steps": 10,
                "spec_steps": 10, "plain_steps": 3, "tree_steps": 6,
                "sampling_epilogue": "on", "epilogue_impl": "xla",
                "fused_steps": 7, "legacy_steps": 2,
                "tree": {"spec": "4x3", "width": 4, "depth": 3,
                         "learned": True, "widths": [3, 2, 1],
                         "plan_width": 3, "slot_path_len": {0: 1.8},
                         "depth_ema": [0.7, 0.4, 0.2],
                         "decisive_ema": 0.1}}

    def chat(self, messages, **kw):
        return "ok"


def gateway_exposition() -> str:
    from datatunerx_tpu.gateway.replica_pool import (
        InProcessReplica,
        ReplicaPool,
    )
    from datatunerx_tpu.gateway.server import Gateway

    pool = ReplicaPool([InProcessReplica("r0", _StatsEngine())])
    # fleet plane ON so the dtx_fleet_* series (prefix tier, handoff and
    # spill outcome counters) and the role-routing series are built and
    # linted — at defaults they are absent by design; the tenant directory
    # likewise turns the dtx_gateway_tenant_* + prefetch families on
    gw = Gateway(pool, model_name="preset:lint", prefill_threshold=8,
                 fleet_prefix_bytes=1 << 20, fleet_handoff=True,
                 fleet_spill=True,
                 tenants={"acme": {"tier": "pinned",
                                   "adapters": ["tenant-a"],
                                   "share": 2.0, "ttft_p95_ms": 750.0}})
    try:
        # drive one request so the labeled counters and the queue-wait
        # histogram expose real series, not just TYPE lines — and one
        # ADAPTER request so the residency-routing outcome counters and
        # per-adapter demand series are built and linted too
        gw.chat({"messages": [{"role": "user", "content": "hi"}]},
                trace_id="lint-trace")
        gw.chat({"messages": [{"role": "user", "content": "hi"}],
                 "model": "tenant-a"}, trace_id="lint-trace-adapter",
                tenant="acme")
        gw.record_request(200)
        return gw.metrics_text()
    finally:
        gw.close()


def serving_exposition() -> str:
    from datatunerx_tpu.serving import server as serving

    old_engine = serving.STATE.engine
    serving.STATE.engine = _StatsEngine()
    try:
        return serving.metrics_text()
    finally:
        serving.STATE.engine = old_engine


def loadgen_exposition() -> str:
    """Drive the load-replay recording path once (a stub client, no
    sockets) so every dtx_loadgen_* series AND the dtx_slo_* verdict
    gauges are built and linted — including at least one trace-id exemplar
    on the TTFT histogram, which keeps the exemplar exposition format
    under the blocking lint."""
    from datatunerx_tpu.loadgen.replay import ReplayRunner
    from datatunerx_tpu.obs.slo import SLOEvaluator, default_slos

    class _StubClient:
        def send(self, event, trace_id):
            code = 503 if event.get("fail") else 200
            return {"code": code, "error": None, "chars": 8,
                    "ttft_ms": 12.5, "latency_ms": 40.0}

    runner = ReplayRunner(_StubClient(), max_inflight=2)
    evaluator = SLOEvaluator(runner.registry, default_slos("loadgen"))
    runner.run([{"t": 0.0, "messages": [{"role": "user", "content": "x"}]},
                {"t": 0.0, "messages": [{"role": "user", "content": "y"}],
                 "fail": True}])
    evaluator.restate_gauges(evaluator.evaluate())
    text = runner.registry.expose()
    if ' # {trace_id="' not in text:
        raise AssertionError(
            "loadgen exposition carries no trace-id exemplar — the "
            "exemplar contract regressed")
    return text


def experiment_exposition() -> str:
    """Drive every ExperimentMetrics recording path once so each
    dtx_experiment_* series exposes real samples."""
    from datatunerx_tpu.experiment.metrics import ExperimentMetrics

    em = ExperimentMetrics(experiment="lint")
    em.set_job_states({"Running": 2, "Pending": 1})
    em.set_pool(free=1, held=2)
    em.preempted()
    em.resumed()
    em.early_stopped()
    em.scored("job-a", 61.5)
    em.set_best(61.5)
    em.set_canary_weight(0.25)
    em.set_promotion_phase("shifting")
    em.promotion_finished("completed")
    em.promotion_finished("rolled_back")
    return em.expose()


def main() -> int:
    findings = []
    for plane, build in (("gateway", gateway_exposition),
                         ("serving", serving_exposition),
                         ("experiment", experiment_exposition),
                         ("loadgen", loadgen_exposition)):
        try:
            text = build()
        except Exception as e:  # noqa: BLE001 — a crash IS the finding
            findings.append(f"{plane}: building exposition crashed: {e}")
            continue
        findings.extend(lint_exposition(text, plane))
    for f in findings:
        print(f"metrics-lint: {f}")
    if not findings:
        print("metrics-lint: gateway + serving + experiment + loadgen "
              "expositions clean")
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
