"""Deviceless AOT certification against the v5e TPU target (VERDICT r4 #1).

The tunneled TPU relay has been wedged for most of rounds 1-4, so no Pallas
kernel had compile evidence from a real TPU toolchain since round 2. This
script removes the relay from the loop entirely: JAX topology-based AOT
compilation against the locally-installed libtpu runs the REAL Mosaic /
XLA-TPU pipeline — lowering, tiling, buffer assignment — with zero devices
attached:

    jax.config.update("jax_platforms", "cpu")     # never touch the relay
    topo = topologies.get_topology_desc(platform="tpu", topology_name="v5e:2x2")
    jax.jit(fn).lower(<abstract args on topo.devices[0]>).compile()

``DTX_PALLAS_INTERPRET=0`` (set below) is load-bearing: with the platform
forced to cpu the kernels' default interpret gate would silently swap in
the emulated pallas path and the "certification" would prove nothing
(ops/_pallas.py).

Certified artifacts (each records compile status + compiler cost analysis +
buffer-assignment memory analysis into ``AOT_CERTIFY.json``):

  kernels   flash attention fwd/bwd (causal GQA + packed segments), int8
            matmul fwd/bwd, nf4 matmul fwd, TRANSPOSED nf4 backward (the
            default training path, never compiled by a real toolchain
            before this script), fused LoRA
  steps     full Llama-2-7B QLoRA train step under both --quant_impl
            values (BASELINE row 2 geometry); Qwen1.5-14B nf4 B1 + B2
            (BASELINE row 5 + its stated over-budget point); Mistral-7B
            full-param fsdp=16 per-shard program on a 16-chip v5e
            topology (BASELINE row 4)
  serving   BatchedEngine decode step (debug scale; the decode graph's
            Mosaic lowering is scale-independent)
  memory    compiler buffer-assignment bytes vs parallel/memory.py's
            ``estimate_footprint`` for the three BASELINE configs
            (VERDICT r4 #3)
  roofline  per-step flops + HBM bytes for the pallas vs xla 7B paths →
            bandwidth/compute-bound tokens/s/chip upper bounds on v5e
            (197 TFLOP/s bf16, 819 GB/s HBM; VERDICT r4 #4)

Run:  python scripts/aot_certify.py [--only PATTERN] [--out AOT_CERTIFY.json]
Make: make aot-certify
"""

from __future__ import annotations

import argparse
import fnmatch
import json
import os
import sys
import time
import traceback
from datetime import datetime, timezone

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

# Must be set before the kernels' interpret gates are consulted; platform
# must be cpu before anything touches the (possibly wedged) relay backend.
os.environ["DTX_PALLAS_INTERPRET"] = "0"
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402
from jax.experimental import topologies  # noqa: E402
from jax.sharding import (  # noqa: E402
    NamedSharding,
    PartitionSpec as P,
    SingleDeviceSharding,
)

# v5e peaks for the roofline (How to Scale Your Model, v5e spec sheet).
V5E_BF16_FLOPS = 197e12
V5E_HBM_BYTES_S = 819e9

TOPOLOGY_1CHIP = "v5e:2x2"   # v5e:1x1 is rejected (chips_per_host_bounds 2x2)
TOPOLOGY_16CHIP = "v5e:4x4"


def _topo(name: str):
    return topologies.get_topology_desc(platform="tpu", topology_name=name)


def _sds(tree, sharding):
    """Attach `sharding` to every leaf of an abstract (eval_shape) tree."""
    return jax.tree_util.tree_map(
        lambda s: jax.ShapeDtypeStruct(s.shape, s.dtype, sharding=sharding),
        tree)


def _cost(compiled) -> dict:
    ca = compiled.cost_analysis() or {}
    if isinstance(ca, (list, tuple)):  # jax returns [dict] on some versions
        ca = ca[0] if ca else {}
    return {
        "flops": ca.get("flops"),
        "bytes_accessed": ca.get("bytes accessed"),
    }


def _memory(compiled) -> dict:
    ma = compiled.memory_analysis()
    if ma is None:
        return {}
    arg = int(ma.argument_size_in_bytes)
    out = int(ma.output_size_in_bytes)
    tmp = int(ma.temp_size_in_bytes)
    alias = int(ma.alias_size_in_bytes)
    return {
        "argument_bytes": arg,
        "output_bytes": out,
        "temp_bytes": tmp,
        "alias_bytes": alias,
        # live HBM while the program runs: args + outputs + scratch, minus
        # donated buffers counted on both sides
        "peak_bytes": arg + out + tmp - alias,
    }


class Certifier:
    def __init__(self, out_path: str, only: str | None):
        self.out_path = out_path
        self.only = only
        self.records = []
        self.meta = {
            "date": datetime.now(timezone.utc).isoformat(timespec="seconds"),
            "jax": jax.__version__,
            "topology": {"single": TOPOLOGY_1CHIP, "sharded": TOPOLOGY_16CHIP},
            "pallas_interpret": False,
        }

    def run(self, name: str, fn):
        if self.only and not fnmatch.fnmatch(name, self.only):
            return None
        t0 = time.perf_counter()
        rec = {"name": name}
        try:
            extra = fn() or {}
            rec.update(extra)
            rec["ok"] = True
        except Exception as e:  # noqa: BLE001 — each artifact independent
            rec["ok"] = False
            rec["error"] = f"{type(e).__name__}: {e}"
            rec["traceback"] = traceback.format_exc(limit=8)
        rec["compile_s"] = round(time.perf_counter() - t0, 1)
        self.records.append(rec)
        self.flush()
        status = "OK " if rec["ok"] else "FAIL"
        print(f"[{status}] {name} ({rec['compile_s']}s)"
              + ("" if rec["ok"] else f"  {rec['error']}"), flush=True)
        return rec

    def flush(self):
        doc = dict(self.meta)
        doc["artifacts"] = self.records
        doc["ok"] = all(r["ok"] for r in self.records)
        with open(self.out_path, "w") as f:
            json.dump(doc, f, indent=1)
            f.write("\n")


# ------------------------------------------------------------------ kernels

def kernel_artifacts(cert: Certifier, dev):
    from datatunerx_tpu.ops.flash_attention import flash_attention
    from datatunerx_tpu.ops.pallas_lora import pallas_lora_matmul
    from datatunerx_tpu.ops.pallas_quant import (
        pallas_matmul_int8,
        pallas_matmul_nf4,
    )
    from datatunerx_tpu.ops.quant import quantize_int8, quantize_nf4

    sh = SingleDeviceSharding(dev)
    B, T, H, KV, D = 1, 1024, 8, 2, 64  # GQA 4:1
    q = jax.ShapeDtypeStruct((B, T, H, D), jnp.bfloat16, sharding=sh)
    kv = jax.ShapeDtypeStruct((B, T, KV, D), jnp.bfloat16, sharding=sh)
    seg = jax.ShapeDtypeStruct((B, T), jnp.int32, sharding=sh)

    def _lower(fn, *args, mosaic: bool = True):
        lo = jax.jit(fn).lower(*args)
        if mosaic:
            assert "tpu_custom_call" in lo.as_text(), "not Mosaic-lowered"
        c = lo.compile()
        return {"cost": _cost(c), "memory": _memory(c)}

    cert.run("kernel/flash_fwd_causal_gqa",
             lambda: _lower(lambda q, k, v: flash_attention(q, k, v), q, kv, kv))
    cert.run("kernel/flash_bwd_causal_gqa", lambda: _lower(
        lambda q, k, v: jax.grad(
            lambda q, k, v: flash_attention(q, k, v).astype(jnp.float32).sum(),
            argnums=(0, 1, 2))(q, k, v), q, kv, kv))
    cert.run("kernel/flash_fwd_segmented", lambda: _lower(
        lambda q, k, v, s: flash_attention(q, k, v, segment_ids=s),
        q, kv, kv, seg))
    cert.run("kernel/flash_bwd_segmented", lambda: _lower(
        lambda q, k, v, s: jax.grad(
            lambda q, k, v: flash_attention(
                q, k, v, segment_ids=s).astype(jnp.float32).sum(),
            argnums=(0, 1, 2))(q, k, v), q, kv, kv, seg))

    K, N, M = 4096, 4096, 512
    x = jax.ShapeDtypeStruct((M, K), jnp.bfloat16, sharding=sh)
    qw = _sds(jax.eval_shape(
        quantize_nf4, jax.ShapeDtypeStruct((K, N), jnp.bfloat16)), sh)
    q8 = _sds(jax.eval_shape(
        quantize_int8, jax.ShapeDtypeStruct((K, N), jnp.bfloat16)), sh)

    cert.run("kernel/nf4_matmul_fwd", lambda: _lower(
        lambda x, qw: pallas_matmul_nf4(x, qw, (K, N)), x, qw))
    cert.run("kernel/nf4_matmul_bwd_transposed", lambda: _lower(
        lambda x, qw: jax.grad(
            lambda x: pallas_matmul_nf4(
                x, qw, (K, N)).astype(jnp.float32).sum())(x), x, qw))
    cert.run("kernel/int8_matmul_fwd", lambda: _lower(
        lambda x, q8: pallas_matmul_int8(x, q8["q"], q8["scale"]), x, q8))
    # int8's custom VJP is deliberately XLA (dx = (g*scale) @ qT einsum —
    # pallas_quant.py:64-71): certify it compiles for TPU, not that it's Mosaic
    cert.run("kernel/int8_matmul_bwd_xla_vjp", lambda: _lower(
        lambda x, q8: jax.grad(
            lambda x: pallas_matmul_int8(
                x, q8["q"], q8["scale"]).astype(jnp.float32).sum())(x),
        x, q8, mosaic=False))

    w = jax.ShapeDtypeStruct((K, N), jnp.bfloat16, sharding=sh)
    a = jax.ShapeDtypeStruct((K, 8), jnp.bfloat16, sharding=sh)
    b = jax.ShapeDtypeStruct((8, N), jnp.bfloat16, sharding=sh)
    cert.run("kernel/lora_fused_fwd", lambda: _lower(
        lambda x, w, a, b: pallas_lora_matmul(x, w, a, b, scale=4.0),
        x, w, a, b))

    # paged-decode attention (ops/pallas_paged_attention.py): the serving
    # fast path — scalar-prefetched block-table walk, bf16 and int8 pools
    # at tinyllama serving geometry (GQA 32q/4kv, bs=16, 64 blocks/slot)
    from datatunerx_tpu.ops.pallas_paged_attention import (
        paged_decode_attention,
    )

    Bd, Hd, KVd, dd, bsd, nbps, NBd = 4, 32, 4, 64, 16, 64, 256
    qd = jax.ShapeDtypeStruct((Bd, Hd, dd), jnp.bfloat16, sharding=sh)
    tables = jax.ShapeDtypeStruct((Bd, nbps), jnp.int32, sharding=sh)
    pos = jax.ShapeDtypeStruct((NBd, bsd), jnp.int32, sharding=sh)
    qpos = jax.ShapeDtypeStruct((Bd,), jnp.int32, sharding=sh)
    pool_bf16 = jax.ShapeDtypeStruct((NBd, bsd, KVd, dd), jnp.bfloat16,
                                     sharding=sh)
    pool_i8 = jax.ShapeDtypeStruct((NBd, bsd, KVd, dd), jnp.int8,
                                   sharding=sh)
    pool_sc = jax.ShapeDtypeStruct((NBd, bsd, KVd), jnp.float32, sharding=sh)
    cert.run("kernel/paged_decode_bf16", lambda: _lower(
        lambda q, k, v, t, p, qp: paged_decode_attention(
            q, k, v, None, None, t, p, qp),
        qd, pool_bf16, pool_bf16, tables, pos, qpos))
    cert.run("kernel/paged_decode_int8_kv", lambda: _lower(
        lambda q, k, v, ks, vs, t, p, qp: paged_decode_attention(
            q, k, v, ks, vs, t, p, qp),
        qd, pool_i8, pool_i8, pool_sc, pool_sc, tables, pos, qpos))


# -------------------------------------------------------------- train steps

def _abstract_params(cfg):
    from datatunerx_tpu.models import init_params

    def build(key):
        p = init_params(cfg, key, dtype=jnp.bfloat16)
        if cfg.quantization:
            from datatunerx_tpu.ops.quant import quantize_model_params

            p = quantize_model_params(p, cfg.quantization)
        return p

    return jax.eval_shape(build, jax.random.PRNGKey(0))


def _single_chip_step(cfg, train_cfg, batch: int, seq: int, dev):
    """Compile one full Trainer.train_step on one topology device; returns
    (compiled, trainer)."""
    from datatunerx_tpu.training import Trainer
    from datatunerx_tpu.training.loss import IGNORE_INDEX  # noqa: F401

    sh = SingleDeviceSharding(dev)
    tr = Trainer(cfg, train_cfg)
    params_abs = _abstract_params(cfg)
    state_abs = _sds(
        jax.eval_shape(tr.init_state, params_abs, jax.random.PRNGKey(1)), sh)
    batch_abs = {
        "input_ids": jax.ShapeDtypeStruct((batch, seq), jnp.int32, sharding=sh),
        "labels": jax.ShapeDtypeStruct((batch, seq), jnp.int32, sharding=sh),
    }
    compiled = jax.jit(tr._train_step_impl, donate_argnums=(0,)).lower(
        state_abs, batch_abs).compile()
    return compiled


def _estimate(cfg, train_cfg, batch, seq, mesh_shape=None):
    from datatunerx_tpu.parallel.memory import estimate_footprint

    fp = estimate_footprint(cfg, train_cfg, batch=batch, seq=seq,
                            mesh_shape=mesh_shape)
    return fp


def _mem_vs_estimate(compiled, fp) -> dict:
    mem = _memory(compiled)
    est = fp.total
    peak = mem.get("peak_bytes")
    out = {
        "memory": mem,
        "estimate_bytes": int(est),
        "estimate_gb": fp.gb(),
    }
    if peak:
        out["compiler_peak_gb"] = round(peak / 1e9, 3)
        out["estimate_over_compiler"] = round(est / peak, 3)
    return out


def _lora_cfg(**kw):
    from datatunerx_tpu.training import TrainConfig

    return TrainConfig(
        finetuning_type="lora", lora_rank=8, lora_alpha=32.0,
        lora_dropout=0.05, lora_targets=("q_proj", "v_proj"),
        learning_rate=2e-4, scheduler="cosine", optimizer="adamw",
        total_steps=1000, compute_dtype=jnp.bfloat16, **kw)


def step_artifacts(cert: Certifier, dev):
    from datatunerx_tpu.models import get_config

    def seven_b(quant_impl):
        def go():
            cfg = get_config("llama2-7b", remat="full", attention_impl="flash",
                             quantization="int4", quant_impl=quant_impl)
            tc = _lora_cfg()
            compiled = _single_chip_step(cfg, tc, 4, 1024, dev)
            fp = _estimate(cfg, tc, 4, 1024)
            rec = _mem_vs_estimate(compiled, fp)
            rec["cost"] = _cost(compiled)
            rec["cost_note"] = ("XLA cost_analysis counts the layer scan "
                                "body ONCE (trip count invisible) and sees "
                                "no flops inside Mosaic custom calls — see "
                                "analysis/roofline_7b_v5e for the corrected "
                                "per-step totals")
            rec["tokens_per_step"] = 4 * 1024
            return rec
        return go

    cert.run("step/train_7b_qlora_pallas", seven_b("pallas"))
    cert.run("step/train_7b_qlora_xla", seven_b("xla"))

    # Roofline from compiler-derived per-layer costs (VERDICT r4 #4).
    # Method: cost_analysis counts a lax.scan body ONCE (trip count is
    # invisible), so the full-step numbers above under-report by ~L×. To
    # recover exact per-layer cost WITHOUT compiling a 32-layer unrolled
    # program (measured pathological: >1 h), compile the same step for
    # num_layers=1 and num_layers=2 models of identical geometry with the
    # scan FULLY unrolled (DTX_SCAN_UNROLL = L, so the loop is inlined and
    # every op is counted): C2 - C1 = one layer's exact fwd+remat+bwd cost,
    # nonscan (embed+lm_head+loss) = C1 - (C2 - C1), per-step total =
    # L*(C2-C1) + nonscan. Mosaic custom-call flops are invisible to the
    # compiler either way, so kernel matmul flops (exact by construction:
    # 2*b*t*K*N per projection) are added analytically for the pallas path;
    # bytes_accessed DOES count custom-call operands, so HBM traffic needs
    # no correction.
    def roofline():
        from datatunerx_tpu.models import get_config as _gc

        out = {}
        L = 32
        B, T = 4, 1024
        tok = B * T
        # exact matmul flops inside the Mosaic kernels, per layer per step:
        # 7 quantized projections (q,k,v,o 4096x4096; gate,up 4096x11008;
        # down 11008x4096) x (fwd + remat-refwd + bwd dx) = 3 passes
        D, F = 4096, 11008
        proj_flops = 2 * tok * (4 * D * D + 3 * D * F)
        kernel_flops_per_layer = 3 * proj_flops
        # flash attention also lives in Mosaic custom calls (invisible to
        # cost_analysis on BOTH paths): per layer, causal-halved QK^T/AV
        # matmuls — fwd 2, bwd 4 (dQ/dK/dV/dS) + remat refwd 2 = 8 passes of
        # 2*B*H*T^2*Dh*0.5 each (~1.4e11 at T=1024, ~2.8% of a layer; grows
        # quadratically with T, so omitting it would eventually flip the
        # compute-vs-HBM verdict at long context)
        Hh, Dh = 32, 128
        flash_flops_per_layer = 8 * (2 * B * Hh * T * T * Dh // 2)
        for impl in ("pallas", "xla"):
            cs = {}
            for n_layers in (1, 2):
                os.environ["DTX_SCAN_UNROLL"] = str(n_layers)
                try:
                    cfg = _gc("llama2-7b", remat="full",
                              attention_impl="flash", quantization="int4",
                              quant_impl=impl, num_layers=n_layers)
                    compiled_n = _single_chip_step(cfg, _lora_cfg(), B, T,
                                                   dev)
                    cs[n_layers] = _cost(compiled_n)
                finally:
                    os.environ["DTX_SCAN_UNROLL"] = "1"
            c1, c2 = cs[1], cs[2]
            layer = {k: c2[k] - c1[k] for k in ("flops", "bytes_accessed")}
            nonscan = {k: c1[k] - layer[k] for k in layer}
            fl = L * layer["flops"] + nonscan["flops"]
            by = L * layer["bytes_accessed"] + nonscan["bytes_accessed"]
            fl += L * flash_flops_per_layer  # flash kernels, both paths
            if impl == "pallas":
                fl += L * kernel_flops_per_layer
            t_flops = fl / V5E_BF16_FLOPS
            t_hbm = by / V5E_HBM_BYTES_S
            out[impl] = {
                "per_layer": layer,
                "nonscan": nonscan,
                "kernel_flops_per_layer": (kernel_flops_per_layer
                                           if impl == "pallas" else 0),
                "flash_flops_per_layer": flash_flops_per_layer,
                "flops_per_step": fl,
                "hbm_bytes_per_step": by,
                "flops_time_s": round(t_flops, 5),
                "hbm_time_s": round(t_hbm, 5),
                "bound": "hbm" if t_hbm > t_flops else "flops",
                "tokens_per_sec_upper_bound": round(
                    tok / max(t_flops, t_hbm), 1),
                "mfu_at_bound": round(
                    (fl / max(t_flops, t_hbm)) / V5E_BF16_FLOPS, 3),
            }
        return {"roofline": out, "tokens_per_step": tok, "layers": L}

    cert.run("analysis/roofline_7b_v5e", roofline)

    def qwen(batch):
        def go():
            cfg = get_config("qwen1.5-14b", remat="full",
                             attention_impl="flash", quantization="int4",
                             quant_impl="pallas")
            tc = _lora_cfg()
            compiled = _single_chip_step(cfg, tc, batch, 1024, dev)
            fp = _estimate(cfg, tc, batch, 1024)
            rec = _mem_vs_estimate(compiled, fp)
            rec["cost"] = _cost(compiled)
            from datatunerx_tpu.parallel.memory import hbm_budget

            rec["hbm_budget_bytes"] = hbm_budget("v5e")
            peak = rec["memory"].get("peak_bytes")
            if peak:
                rec["fits_v5e1_by_compiler"] = peak <= rec["hbm_budget_bytes"]
            return rec
        return go

    cert.run("step/train_qwen14b_qlora_b1", qwen(1))
    cert.run("step/train_qwen14b_qlora_b2_overbudget", qwen(2))


def mistral_fsdp_artifact(cert: Certifier):
    from datatunerx_tpu.models import get_config
    from datatunerx_tpu.parallel.mesh import make_mesh
    from datatunerx_tpu.parallel.sharding import batch_shardings, tree_shardings
    from datatunerx_tpu.training import TrainConfig, Trainer

    def go():
        topo = _topo(TOPOLOGY_16CHIP)
        mesh = make_mesh(devices=topo.devices, fsdp=16)
        cfg = get_config("mistral-7b", remat="full", attention_impl="flash")
        tc = TrainConfig(finetuning_type="full", compute_dtype=jnp.bfloat16)
        tr = Trainer(cfg, tc, mesh=mesh)
        params_abs = _abstract_params(cfg)
        state_abs = jax.eval_shape(tr.init_state, params_abs,
                                   jax.random.PRNGKey(1))
        # shard the abstract state by the trainer's OWN rules (the same
        # _spec_for path rules shard_tree applies on device): adam moment
        # trees mirror the param tree's paths, so tree_shardings covers
        # params + opt state; scalars/rng fall to P() (replicated). Relying
        # on XLA output-sharding propagation through an AOT init compile
        # instead replicated the moments and "OOM"ed the per-shard step at
        # 27.8 GB of arguments.
        state_sh = tree_shardings(state_abs, mesh)
        state_in = jax.tree_util.tree_map(
            lambda s, sd: jax.ShapeDtypeStruct(s.shape, s.dtype, sharding=sd),
            state_abs, state_sh)
        B, T = 16, 1024
        batch_abs = {"input_ids": jax.ShapeDtypeStruct((B, T), jnp.int32),
                     "labels": jax.ShapeDtypeStruct((B, T), jnp.int32)}
        bsh = batch_shardings(batch_abs, mesh)
        batch_in = jax.tree_util.tree_map(
            lambda s, sd: jax.ShapeDtypeStruct(s.shape, s.dtype, sharding=sd),
            batch_abs, bsh)
        # pin the new state to the input layouts so donation aliases (else
        # XLA may re-shard outputs, no buffers alias, and "peak" double
        # counts the whole state); metrics are replicated scalars
        metrics_abs = jax.eval_shape(tr._train_step_impl, state_abs,
                                     batch_abs)[1]
        repl = NamedSharding(mesh, P())
        out_sh = (state_sh, jax.tree_util.tree_map(lambda _: repl,
                                                   metrics_abs))
        compiled = jax.jit(tr._train_step_impl, donate_argnums=(0,),
                           out_shardings=out_sh).lower(
            state_in, batch_in).compile()
        fp = _estimate(cfg, tc, B, T, mesh_shape={"fsdp": 16})
        rec = _mem_vs_estimate(compiled, fp)
        rec["cost"] = _cost(compiled)
        rec["mesh"] = {"fsdp": 16}
        return rec

    cert.run("step/train_mistral7b_full_fsdp16", go)


# ----------------------------------------------------------------- serving

def serving_artifact(cert: Certifier, dev):
    def go():
        from datatunerx_tpu.serving.batched_engine import BatchedEngine

        eng = BatchedEngine("preset:debug", template="vanilla",
                            max_seq_len=256, slots=4, decode_chunk=8)
        try:
            sh = SingleDeviceSharding(dev)
            to_sds = lambda t: _sds(  # noqa: E731
                jax.tree_util.tree_map(
                    lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), t), sh)
            args = (eng.params, eng._cache, eng._logits, eng._pos,
                    eng._remaining, eng._active, eng._rng, eng._temps,
                    eng._top_ps, eng._stops, eng._adapter_idx)
            abs_args = tuple(to_sds(a) for a in args)
            compiled = jax.jit(
                eng._decode_impl, static_argnames=("K",)).lower(
                *abs_args, K=8).compile()
            return {"cost": _cost(compiled), "memory": _memory(compiled),
                    "scale": "debug (decode graph lowering is "
                             "scale-independent)"}
        finally:
            eng.close()

    cert.run("serving/decode_step", go)


def extra_artifacts(cert: Certifier, dev):
    """The remaining compute paths: preference stages (dpo/rm), PPO
    rollout+update, ring-SP sharded training, int8-KV decode. Certified at
    debug/1B scale — lowering legality is geometry-independent; the 7B/14B
    artifacts above already cover full-scale memory."""
    from datatunerx_tpu.models import get_config
    from datatunerx_tpu.training import TrainConfig, Trainer

    sh = SingleDeviceSharding(dev)

    def stage_step(stage):
        def go():
            cfg = get_config("debug", attention_impl="flash", remat="full")
            tc = TrainConfig(stage=stage, finetuning_type="lora",
                             lora_rank=4, lora_dropout=0.0,
                             compute_dtype=jnp.bfloat16)
            tr = Trainer(cfg, tc)
            params_abs = _abstract_params(cfg)
            state_abs = _sds(jax.eval_shape(
                tr.init_state, params_abs, jax.random.PRNGKey(1)), sh)
            B, T = 2, 128
            ids = jax.ShapeDtypeStruct((B, T), jnp.int32, sharding=sh)
            batch = {"chosen_ids": ids, "chosen_labels": ids,
                     "rejected_ids": ids, "rejected_labels": ids}
            compiled = jax.jit(tr._train_step_impl, donate_argnums=(0,)
                               ).lower(state_abs, batch).compile()
            return {"cost": _cost(compiled), "memory": _memory(compiled)}
        return go

    cert.run("extra/train_dpo_step", stage_step("dpo"))
    cert.run("extra/train_rm_step", stage_step("rm"))

    def ppo():
        from datatunerx_tpu.models.lora import init_lora_params, lora_scaling
        from datatunerx_tpu.training.ppo import PPOConfig, PPOTrainer

        cfg = get_config("debug", attention_impl="xla", remat="none")
        tc = TrainConfig(stage="ppo", finetuning_type="lora", lora_rank=4,
                         lora_dropout=0.0, scheduler="constant",
                         compute_dtype=None)
        rwd = jax.eval_shape(
            lambda k: init_lora_params(cfg, k, rank=4), jax.random.PRNGKey(7))
        rwd = dict(rwd)
        rwd["v_head"] = jax.ShapeDtypeStruct((cfg.hidden_size,), jnp.float32)
        # reward tree must be concrete for trainer construction; zeros have
        # the right shapes and PPO numerics are irrelevant to lowering
        rwd = jax.tree_util.tree_map(
            lambda s: jnp.zeros(s.shape, s.dtype), rwd)
        tr = PPOTrainer(cfg, tc, PPOConfig(gen_len=16),
                        reward_lora=rwd, reward_scaling=lora_scaling(32.0, 4),
                        eos_id=2, pad_id=0)
        params_abs = _abstract_params(cfg)
        state_abs = _sds(jax.eval_shape(
            tr.init_state, params_abs, jax.random.PRNGKey(1)), sh)
        B, T = 2, 32
        batch = {"prompt_ids": jax.ShapeDtypeStruct((B, T), jnp.int32,
                                                    sharding=sh),
                 "prompt_mask": jax.ShapeDtypeStruct((B, T), jnp.int32,
                                                     sharding=sh)}
        ro_lower = jax.jit(tr._rollout_impl).lower(state_abs, batch,
                                                   jnp.float32(0.2))
        ro_c = ro_lower.compile()
        ro_abs = jax.tree_util.tree_map(
            lambda s: jax.ShapeDtypeStruct(s.shape, s.dtype, sharding=sh),
            jax.eval_shape(tr._rollout_impl, state_abs, batch,
                           jnp.float32(0.2))[0])  # (ro, stats) -> ro
        up_c = jax.jit(tr._ppo_update_impl, donate_argnums=(0,)).lower(
            state_abs, ro_abs).compile()
        return {"rollout": {"cost": _cost(ro_c), "memory": _memory(ro_c)},
                "update": {"cost": _cost(up_c), "memory": _memory(up_c)}}

    cert.run("extra/ppo_rollout_and_update", ppo)

    def ring_sp():
        from datatunerx_tpu.parallel.mesh import make_mesh
        from datatunerx_tpu.parallel.sharding import (
            batch_shardings,
            tree_shardings,
        )

        topo = _topo(TOPOLOGY_1CHIP)
        mesh = make_mesh(devices=topo.devices, sp=4, dp=1)
        cfg = get_config("tinyllama-1.1b", attention_impl="ring",
                         remat="dots")
        tc = _lora_cfg()
        tr = Trainer(cfg, tc, mesh=mesh)
        params_abs = _abstract_params(cfg)
        state_abs = jax.eval_shape(tr.init_state, params_abs,
                                   jax.random.PRNGKey(1))
        state_in = jax.tree_util.tree_map(
            lambda s, sd: jax.ShapeDtypeStruct(s.shape, s.dtype, sharding=sd),
            state_abs, tree_shardings(state_abs, mesh))
        B, T = 1, 4096  # sequence sharded 4-way over sp
        babs = {"input_ids": jax.ShapeDtypeStruct((B, T), jnp.int32),
                "labels": jax.ShapeDtypeStruct((B, T), jnp.int32)}
        batch_in = jax.tree_util.tree_map(
            lambda s, sd: jax.ShapeDtypeStruct(s.shape, s.dtype, sharding=sd),
            babs, batch_shardings(babs, mesh))
        compiled = jax.jit(tr._train_step_impl, donate_argnums=(0,)).lower(
            state_in, batch_in).compile()
        return {"cost": _cost(compiled), "memory": _memory(compiled),
                "mesh": {"sp": 4}}

    cert.run("extra/train_ring_sp4_tinyllama", ring_sp)

    def int8_kv_decode():
        from datatunerx_tpu.serving.batched_engine import BatchedEngine

        eng = BatchedEngine("preset:debug", template="vanilla",
                            max_seq_len=256, slots=4, decode_chunk=8,
                            kv_quant="int8")
        try:
            to_sds = lambda t: _sds(  # noqa: E731
                jax.tree_util.tree_map(
                    lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), t), sh)
            args = (eng.params, eng._cache, eng._logits, eng._pos,
                    eng._remaining, eng._active, eng._rng, eng._temps,
                    eng._top_ps, eng._stops, eng._adapter_idx)
            compiled = jax.jit(
                eng._decode_impl, static_argnames=("K",)).lower(
                *(to_sds(a) for a in args), K=8).compile()
            return {"cost": _cost(compiled), "memory": _memory(compiled)}
        finally:
            eng.close()

    cert.run("serving/decode_step_int8_kv", int8_kv_decode)

    def dcn_hybrid():
        """Multi-slice shape: dp-major crosses slices over DCN
        (parallel/mesh.py make_mesh(dcn_dp=…)); without slice indices the
        contiguous chunks of the topology's device list emulate slices —
        the SAME program shape that runs on real multislice compiles here
        for the TPU target."""
        from datatunerx_tpu.parallel.mesh import make_mesh
        from datatunerx_tpu.parallel.sharding import (
            batch_shardings,
            tree_shardings,
        )

        topo = _topo(TOPOLOGY_16CHIP)
        mesh = make_mesh(devices=topo.devices, dp=4, fsdp=4, dcn_dp=2)
        cfg = get_config("tinyllama-1.1b", attention_impl="flash",
                         remat="dots")
        tc = _lora_cfg()
        tr = Trainer(cfg, tc, mesh=mesh)
        params_abs = _abstract_params(cfg)
        state_abs = jax.eval_shape(tr.init_state, params_abs,
                                   jax.random.PRNGKey(1))
        state_in = jax.tree_util.tree_map(
            lambda s, sd: jax.ShapeDtypeStruct(s.shape, s.dtype, sharding=sd),
            state_abs, tree_shardings(state_abs, mesh))
        B, T = 16, 1024
        babs = {"input_ids": jax.ShapeDtypeStruct((B, T), jnp.int32),
                "labels": jax.ShapeDtypeStruct((B, T), jnp.int32)}
        batch_in = jax.tree_util.tree_map(
            lambda s, sd: jax.ShapeDtypeStruct(s.shape, s.dtype, sharding=sd),
            babs, batch_shardings(babs, mesh))
        compiled = jax.jit(tr._train_step_impl, donate_argnums=(0,)).lower(
            state_in, batch_in).compile()
        return {"cost": _cost(compiled), "memory": _memory(compiled),
                "mesh": {"dp": 4, "fsdp": 4, "dcn_dp": 2}}

    cert.run("extra/train_dcn_hybrid_dp4x2_fsdp4", dcn_hybrid)

    def ring_long_context():
        """Long-context shape: ring SP at T=32k (8k tokens/device on sp=4)
        — the O(T_local) memory claim is only real if the sharded program
        actually compiles at long T for the TPU target."""
        from datatunerx_tpu.parallel.mesh import make_mesh
        from datatunerx_tpu.parallel.sharding import (
            batch_shardings,
            tree_shardings,
        )

        topo = _topo(TOPOLOGY_1CHIP)
        mesh = make_mesh(devices=topo.devices, sp=4, dp=1)
        cfg = get_config("tinyllama-1.1b", attention_impl="ring",
                         remat="full", max_seq_len=32768)
        tc = _lora_cfg()
        tr = Trainer(cfg, tc, mesh=mesh)
        params_abs = _abstract_params(cfg)
        state_abs = jax.eval_shape(tr.init_state, params_abs,
                                   jax.random.PRNGKey(1))
        state_in = jax.tree_util.tree_map(
            lambda s, sd: jax.ShapeDtypeStruct(s.shape, s.dtype, sharding=sd),
            state_abs, tree_shardings(state_abs, mesh))
        B, T = 1, 32768
        babs = {"input_ids": jax.ShapeDtypeStruct((B, T), jnp.int32),
                "labels": jax.ShapeDtypeStruct((B, T), jnp.int32)}
        batch_in = jax.tree_util.tree_map(
            lambda s, sd: jax.ShapeDtypeStruct(s.shape, s.dtype, sharding=sd),
            babs, batch_shardings(babs, mesh))
        compiled = jax.jit(tr._train_step_impl, donate_argnums=(0,)).lower(
            state_in, batch_in).compile()
        return {"cost": _cost(compiled), "memory": _memory(compiled),
                "mesh": {"sp": 4}, "seq_len": T}

    cert.run("extra/train_ring_sp4_T32k_long_context", ring_long_context)

    def serving_prefill():
        from datatunerx_tpu.serving.batched_engine import BatchedEngine

        eng = BatchedEngine("preset:debug", template="vanilla",
                            max_seq_len=256, slots=4, decode_chunk=8)
        try:
            to_sds = lambda t: _sds(  # noqa: E731
                jax.tree_util.tree_map(
                    lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), t), sh)
            plen = 64
            tok = jax.ShapeDtypeStruct((1, plen), jnp.int32, sharding=sh)
            aidx = jax.ShapeDtypeStruct((), jnp.int32, sharding=sh)
            # eng._prefill is the memoized _Programs.prefill jit (the impl
            # lives on the shared program holder, not the engine); lora is
            # an argument now (None = base-only engine)
            compiled = eng._prefill.lower(
                to_sds(eng.params), None, tok, tok, tok, aidx,
                prompt_len=plen).compile()
            return {"cost": _cost(compiled), "memory": _memory(compiled)}
        finally:
            eng.close()

    cert.run("serving/prefill_step", serving_prefill)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default=os.path.join(REPO, "AOT_CERTIFY.json"))
    ap.add_argument("--only", default=None,
                    help="fnmatch pattern over artifact names")
    args = ap.parse_args()

    cert = Certifier(args.out, args.only)
    dev = _topo(TOPOLOGY_1CHIP).devices[0]

    kernel_artifacts(cert, dev)
    step_artifacts(cert, dev)
    mistral_fsdp_artifact(cert)
    serving_artifact(cert, dev)
    extra_artifacts(cert, dev)

    cert.flush()
    n_ok = sum(r["ok"] for r in cert.records)
    print(f"\n{n_ok}/{len(cert.records)} artifacts certified "
          f"-> {args.out}", flush=True)
    return 0 if n_ok == len(cert.records) else 1


if __name__ == "__main__":
    sys.exit(main())
