"""Real-TPU validation of every Pallas kernel (ROADMAP §1).

Compiles each kernel with interpret=False on the live chip and checks
numerics against XLA reference implementations. Prints one PASS/FAIL line
per check plus max abs/rel error; exits non-zero on any failure.

Run: python scripts/tpu_validate.py
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

RESULTS = []


def check(name, got, want, atol, rtol=0.0):
    got = np.asarray(got, np.float32)
    want = np.asarray(want, np.float32)
    err = np.abs(got - want)
    rel = err / (np.abs(want) + 1e-6)
    ok = bool(np.all(err <= atol + rtol * np.abs(want)))
    RESULTS.append((name, ok, float(err.max()), float(rel.max())))
    print(f"{'PASS' if ok else 'FAIL'} {name}: max_abs={err.max():.3e} "
          f"max_rel={rel.max():.3e}", flush=True)


def ref_attention(q, k, v, segment_ids=None):
    """Plain XLA causal GQA attention, fp32 accumulate."""
    B, T, H, d = q.shape
    KV = k.shape[2]
    G = H // KV
    kx = jnp.repeat(k, G, axis=2)
    vx = jnp.repeat(v, G, axis=2)
    s = jnp.einsum("bthd,bshd->bhts", q.astype(jnp.float32),
                   kx.astype(jnp.float32)) / (d ** 0.5)
    mask = jnp.tril(jnp.ones((T, T), bool))[None, None]
    if segment_ids is not None:
        seg = segment_ids[:, None, :, None] == segment_ids[:, None, None, :]
        mask = mask & seg
    s = jnp.where(mask, s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhts,bshd->bthd", p, vx.astype(jnp.float32))


def validate_flash():
    from datatunerx_tpu.ops.flash_attention import flash_attention
    key = jax.random.PRNGKey(0)
    B, T, H, KV, d = 2, 1024, 8, 4, 128
    kq, kk, kv_ = jax.random.split(key, 3)
    q = jax.random.normal(kq, (B, T, H, d), jnp.bfloat16)
    k = jax.random.normal(kk, (B, T, KV, d), jnp.bfloat16)
    v = jax.random.normal(kv_, (B, T, KV, d), jnp.bfloat16)

    # --- forward, plain causal
    out = jax.jit(lambda *a: flash_attention(*a, interpret=False))(q, k, v)
    want = ref_attention(q, k, v)
    check("flash_fwd_causal_gqa", out, want, atol=3e-2)

    # --- forward, packed segments
    seg = jnp.concatenate([
        jnp.full((B, T // 2), 1, jnp.int32),
        jnp.full((B, T // 2), 2, jnp.int32)], axis=1)
    out_s = jax.jit(lambda *a: flash_attention(
        *a, segment_ids=seg, interpret=False))(q, k, v)
    want_s = ref_attention(q, k, v, segment_ids=seg)
    check("flash_fwd_segments", out_s, want_s, atol=3e-2)

    # --- backward
    def loss_flash(q, k, v):
        o = flash_attention(q, k, v, segment_ids=seg, interpret=False)
        return (o.astype(jnp.float32) ** 2).sum()

    def loss_ref(q, k, v):
        o = ref_attention(q, k, v, segment_ids=seg)
        return (o ** 2).sum()

    gf = jax.jit(jax.grad(loss_flash, argnums=(0, 1, 2)))(q, k, v)
    gr = jax.jit(jax.grad(loss_ref, argnums=(0, 1, 2)))(q, k, v)
    for name, a, b in zip(("dq", "dk", "dv"), gf, gr):
        scale = float(jnp.abs(b.astype(jnp.float32)).max())
        check(f"flash_bwd_{name}", a, b, atol=3e-2 * max(scale, 1.0))


def validate_quant():
    from datatunerx_tpu.ops.quant import (
        quantize_int8, matmul_int8, quantize_nf4, matmul_nf4)
    from datatunerx_tpu.ops.pallas_quant import (
        pallas_matmul_int8, pallas_matmul_nf4)
    key = jax.random.PRNGKey(1)
    K, N, M = 1024, 1024, 512
    kw, kx = jax.random.split(key)
    w = jax.random.normal(kw, (K, N), jnp.float32) * 0.05
    x = jax.random.normal(kx, (M, K), jnp.bfloat16)

    q8 = quantize_int8(w)
    got = jax.jit(pallas_matmul_int8)(x, q8["q"], q8["scale"])
    want = matmul_int8(x, q8["q"], q8["scale"])
    check("int8_matmul", got, want, atol=2e-2, rtol=2e-2)

    q4 = quantize_nf4(w)
    got = jax.jit(lambda x: pallas_matmul_nf4(x, q4, (K, N)))(x)
    want = matmul_nf4(x, q4, (K, N))
    check("nf4_matmul", got, want, atol=2e-2, rtol=2e-2)

    # real-model K that is NOT a multiple of 128·64: tinyllama down_proj
    # (K=5632 → 88 nf4 blocks, chunk 11 blocks) — exercises the chunk-major
    # layout with an odd blocks-per-chunk
    K2, N2 = 5632, 256
    w2 = jax.random.normal(jax.random.PRNGKey(9), (K2, N2), jnp.float32) * 0.05
    x2 = jax.random.normal(jax.random.PRNGKey(10), (M, K2), jnp.bfloat16)
    q42 = quantize_nf4(w2)
    got = jax.jit(lambda x: pallas_matmul_nf4(x, q42, (K2, N2)))(x2)
    want = matmul_nf4(x2, q42, (K2, N2))
    check("nf4_matmul_k5632", got, want, atol=2e-2, rtol=2e-2)


def validate_nf4_transposed():
    """The fused dx kernel (g @ Wᵀ, ops/pallas_quant.py:245-297) — the
    round-3 DEFAULT training backward for every quantized matmul. VERDICT r3
    weak #2: it had never been covered by this script, so a Mosaic lowering
    failure would surface mid-training, not at certification."""
    from datatunerx_tpu.ops.pallas_quant import _pallas_matmul_nf4_t_impl
    from datatunerx_tpu.ops.quant import dequant_nf4, quantize_nf4

    M = 512
    # 1024-aligned AND a real-model K that is NOT a multiple of 128·64
    # (tinyllama down_proj K=5632): both chunk layouts must lower
    for K, N in ((1024, 1024), (5632, 256)):
        w = jax.random.normal(
            jax.random.PRNGKey(20 + K), (K, N), jnp.float32) * 0.05
        q4 = quantize_nf4(w)
        g = jax.random.normal(jax.random.PRNGKey(21), (M, N), jnp.bfloat16)
        # two iterations with DIFFERENT shapes: each would recompile even
        # through one wrapper, so the per-iteration jit costs nothing here
        got = jax.jit(  # dtxlint: disable=DTX002
            lambda g, q4=q4, K=K, N=N: _pallas_matmul_nf4_t_impl(
                g, q4, (K, N)))(g)
        wd = dequant_nf4(q4, (K, N))
        want = g.astype(jnp.float32) @ wd.astype(jnp.float32).T
        check(f"nf4_t_matmul_k{K}", got, want, atol=5e-1, rtol=3e-2)


def validate_qlora_step():
    """One full QLoRA fwd+bwd train step, --quant_impl pallas vs xla: loss
    and updated-LoRA numerics must agree. This is the exact program the
    default 7B training path compiles (quantized base + fused kernels fwd
    AND bwd + remat), at debug scale."""
    from datatunerx_tpu.models import get_config, init_params
    from datatunerx_tpu.ops.quant import quantize_model_params
    from datatunerx_tpu.training import TrainConfig, Trainer
    from datatunerx_tpu.training.loss import IGNORE_INDEX

    B, T = 4, 128
    results = {}
    for impl in ("pallas", "xla"):
        cfg = get_config("debug", quantization="int4", quant_impl=impl,
                         remat="full")
        tr = Trainer(
            cfg,
            TrainConfig(
                finetuning_type="lora", lora_rank=8, lora_alpha=32.0,
                lora_dropout=0.0, lora_targets=("q_proj", "v_proj"),
                learning_rate=2e-4, optimizer="adamw", total_steps=10,
                compute_dtype=jnp.bfloat16,
            ),
        )
        params = init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.bfloat16)
        params = quantize_model_params(params, "int4")
        state = tr.init_state(params, jax.random.PRNGKey(1))
        toks = jax.random.randint(
            jax.random.PRNGKey(2), (B, T), 0, cfg.vocab_size, jnp.int32)
        labels = jnp.where(jnp.arange(T)[None, :] < T // 4, IGNORE_INDEX,
                           toks)
        state, m = tr.train_step(
            state, {"input_ids": toks, "labels": labels})
        lora_flat = jax.tree_util.tree_leaves(state.lora)
        results[impl] = (float(m["loss"]),
                         np.concatenate([np.asarray(x, np.float32).ravel()
                                         for x in lora_flat]))

    loss_p, lora_p = results["pallas"]
    loss_x, lora_x = results["xla"]
    check("qlora_step_loss_pallas_vs_xla", [loss_p], [loss_x],
          atol=5e-2, rtol=1e-2)
    check("qlora_step_lora_update_pallas_vs_xla", lora_p, lora_x,
          atol=5e-4, rtol=5e-2)


def validate_lora():
    from datatunerx_tpu.ops.pallas_lora import pallas_lora_matmul
    key = jax.random.PRNGKey(2)
    K, N, M, r = 1024, 1024, 512, 16
    ks = jax.random.split(key, 4)
    w = jax.random.normal(ks[0], (K, N), jnp.bfloat16) * 0.05
    a = jax.random.normal(ks[1], (K, r), jnp.bfloat16) * 0.05
    b = jax.random.normal(ks[2], (r, N), jnp.bfloat16) * 0.05
    x = jax.random.normal(ks[3], (M, K), jnp.bfloat16)
    scale = 2.0
    got = jax.jit(lambda *t: pallas_lora_matmul(*t, scale))(x, w, a, b)
    xf = x.astype(jnp.float32)
    want = xf @ w.astype(jnp.float32) + (
        xf @ a.astype(jnp.float32)) @ b.astype(jnp.float32) * scale
    check("fused_lora_matmul", got, want, atol=5e-1, rtol=3e-2)


def main():
    dev = jax.devices()[0]
    print(f"backend={jax.default_backend()} device={dev}", flush=True)
    if jax.default_backend() not in ("tpu", "axon"):
        print("WARNING: no TPU — kernels will run in interpret mode where "
              "forced off this is expected to fail compile")
    validate_flash()
    validate_quant()
    validate_nf4_transposed()
    validate_lora()
    validate_qlora_step()
    bad = [r for r in RESULTS if not r[1]]
    print(f"\n{len(RESULTS) - len(bad)}/{len(RESULTS)} checks passed")
    sys.exit(1 if bad else 0)


if __name__ == "__main__":
    main()
