"""7B-scale single-chip proof (VERDICT next-round #6, BASELINE.md row 1).

Llama-2-7B architecture, nf4-quantized base + LoRA, one v5e chip:
init + quantize on host (7B bf16 = 13.5 GB; nf4 ≈ 3.5 GB fits the 16 GB HBM
with remat'd activations), then time train steps on the device.

Prints one JSON line per measured config:
  {"metric": "qlora_sft_tokens_per_sec_per_chip[llama2-7b,...]", ...}

Run: python scripts/bench_7b.py [--batch 4] [--seq 1024] [--steps 10]
     [--attention flash] [--quant_impl xla|pallas]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=4)
    ap.add_argument("--seq", type=int, default=1024)
    ap.add_argument("--steps", type=int, default=10)
    ap.add_argument("--attention", default="flash", choices=["xla", "flash"])
    ap.add_argument("--quant_impl", default="xla", choices=["xla", "pallas"])
    ap.add_argument("--remat", default="full", choices=["none", "dots", "full"])
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp

    from datatunerx_tpu.models import get_config, init_params
    from datatunerx_tpu.ops.quant import quantize_model_params
    from datatunerx_tpu.training import TrainConfig, Trainer
    from datatunerx_tpu.training.loss import IGNORE_INDEX

    assert jax.default_backend() == "tpu", "7B bench needs the real chip"
    cpu = jax.devices("cpu")[0]

    cfg = get_config(
        "llama2-7b", remat=args.remat, attention_impl=args.attention,
        quantization="int4", quant_impl=args.quant_impl,
    )

    t0 = time.perf_counter()
    with jax.default_device(cpu):
        params = init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.bfloat16)
        params = quantize_model_params(params, "int4")
    print(f"host init+quantize: {time.perf_counter() - t0:.1f}s",
          file=sys.stderr)

    tr = Trainer(
        cfg,
        TrainConfig(
            finetuning_type="lora", lora_rank=8, lora_alpha=32.0,
            lora_dropout=0.05, lora_targets=("q_proj", "v_proj"),
            learning_rate=2e-4, scheduler="cosine", optimizer="adamw",
            total_steps=1000, compute_dtype=jnp.bfloat16,
        ),
    )
    t0 = time.perf_counter()
    params = jax.device_put(params, jax.devices()[0])
    state = tr.init_state(params, jax.random.PRNGKey(1))
    print(f"device transfer + opt init: {time.perf_counter() - t0:.1f}s",
          file=sys.stderr)

    B, T = args.batch, args.seq
    toks = jax.random.randint(
        jax.random.PRNGKey(2), (B, T), 0, cfg.vocab_size, jnp.int32)
    labels = jnp.where(jnp.arange(T)[None, :] < T // 8, IGNORE_INDEX, toks)
    batch = {"input_ids": toks, "labels": labels}

    t0 = time.perf_counter()
    state, m = tr.train_step(state, batch)
    loss0 = float(m["loss"])  # host fetch = real sync (tunnel-safe)
    print(f"compile + first step: {time.perf_counter() - t0:.1f}s "
          f"loss={loss0:.3f}", file=sys.stderr)

    t0 = time.perf_counter()
    for _ in range(args.steps):
        state, m = tr.train_step(state, batch)
    float(m["loss"])
    dt = time.perf_counter() - t0
    toks_per_sec = B * T * args.steps / dt

    # 7B LoRA step ≈ 2 (fwd) + 4 (bwd) matmul-FLOPs per param-token
    approx_flops = 6 * 6.74e9 * toks_per_sec
    mfu = approx_flops / 197e12  # v5e bf16 peak 197 TFLOP/s

    print(json.dumps({
        "metric": (f"qlora_sft_tokens_per_sec_per_chip[llama2-7b,nf4,"
                   f"B{B}xT{T},{args.attention},remat={args.remat},"
                   f"quant={args.quant_impl}]"),
        "value": round(toks_per_sec, 1),
        "unit": "tokens/s/chip",
        "vs_baseline": round(mfu, 3),  # MFU in lieu of a reference number
    }))


if __name__ == "__main__":
    main()
