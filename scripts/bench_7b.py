"""7B-scale single-chip proof (VERDICT next-round #6, BASELINE.md row 1).

Llama-2-7B architecture, nf4-quantized base + LoRA, one v5e chip:
init + quantize on host (7B bf16 = 13.5 GB; nf4 ≈ 3.5 GB fits the 16 GB HBM
with remat'd activations), then time train steps on the device.

Prints one JSON line per measured config:
  {"metric": "qlora_sft_tokens_per_sec_per_chip[llama2-7b,...]", ...}

Run: python scripts/bench_7b.py [--batch 4] [--seq 1024] [--steps 10]
     [--attention flash] [--quant_impl xla|pallas]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _tree_flatten_paths(tree):
    import jax

    flat = jax.tree_util.tree_flatten_with_path(tree)[0]
    out = {}
    for path, leaf in flat:
        key = "/".join(p.key for p in path)
        out[key] = leaf
    return out


def _npz_path(path):
    return path if path.endswith(".npz") else path + ".npz"  # savez appends


def _cache_format():
    # a cache from an older nf4 layout would silently reintroduce the tile-
    # padding HBM OOM the flat-byte layout fixed — version the file and
    # requantize on any mismatch
    from datatunerx_tpu.ops.quant import NF4_LAYOUT_VERSION

    return {"mode": "int4", "nf4_layout": NF4_LAYOUT_VERSION,
            "packed_flat": True}


def _save_cached(path, params):
    import json

    import numpy as np

    import jax

    flat, dtypes = {}, {}
    for k, v in _tree_flatten_paths(params).items():
        arr = np.asarray(jax.device_get(v))
        dtypes[k] = str(arr.dtype)
        if arr.dtype.name == "bfloat16":  # npy can't portably store bf16
            arr = arr.astype(np.float32)
        flat[k] = arr
    flat["__dtypes__"] = np.asarray(json.dumps(dtypes))
    flat["__format__"] = np.asarray(json.dumps(_cache_format()))
    np.savez(_npz_path(path), **flat)


def _load_cached(path):
    import json
    import os

    import numpy as np

    import jax.numpy as jnp

    path = _npz_path(path)
    if not os.path.exists(path):
        return None
    z = np.load(path)
    if "__format__" not in z.files or \
            json.loads(str(z["__format__"])) != _cache_format():
        print(f"[cache] {path}: stale/unversioned format — requantizing",
              file=sys.stderr)
        return None
    dtypes = json.loads(str(z["__dtypes__"]))
    tree = {}
    for key in z.files:
        if key == "__dtypes__":
            continue
        node = tree
        parts = key.split("/")
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = jnp.asarray(z[key]).astype(dtypes[key])
    return tree


def _fast_host_init(cfg, init_params, seed: int):
    """Throughput-bench init: same param TREE as init_params (via eval_shape)
    but leaves filled with numpy's PCG64 instead of jax's counter-based
    threefry — ~50× faster on a single host core, and a 7B threefry init
    takes half an hour there. Values only need plausible scale for a
    tokens/sec measurement, not reproducibility against training runs."""
    import numpy as np

    import jax
    import jax.numpy as jnp

    abstract = jax.eval_shape(
        lambda k: init_params(cfg, k, dtype=jnp.bfloat16),
        jax.random.PRNGKey(seed))
    rng = np.random.default_rng(seed)

    def fill(path, s):
        name = path[-1].key if hasattr(path[-1], "key") else str(path[-1])
        if name == "scale":   # rms-norm scales init to 1
            return jnp.ones(s.shape, s.dtype)
        if name == "bias":
            return jnp.zeros(s.shape, s.dtype)
        w = rng.standard_normal(s.shape, dtype=np.float32) * 0.02
        return jnp.asarray(w, s.dtype)

    return jax.tree_util.tree_map_with_path(fill, abstract)


def _synth_packed_init(cfg, init_params, seed: int):
    """Direct synthesis of the QUANTIZED param tree — random packed nf4 bytes
    with plausible scales, no bf16 materialization and no quantize pass.
    Throughput-only: the compiled program is byte-identical to one fed real
    quantized weights (same shapes/dtypes), so tokens/sec is unaffected, and
    init drops from ~40 min (threefry+quantize) to seconds. Loss values are
    meaningless; use the cache/--real_quant path for numerics."""
    import numpy as np

    import jax
    import jax.numpy as jnp

    from datatunerx_tpu.ops.quant import NF4_BLOCK, NF4_LAYOUT_VERSION

    abstract = jax.eval_shape(
        lambda k: init_params(cfg, k, dtype=jnp.bfloat16),
        jax.random.PRNGKey(seed))
    rng = np.random.default_rng(seed)

    def fill(path, s):
        name = path[-1].key if hasattr(path[-1], "key") else str(path[-1])
        if name == "scale":
            return jnp.ones(s.shape, s.dtype)
        if name == "bias":
            return jnp.zeros(s.shape, s.dtype)
        w = rng.standard_normal(s.shape, dtype=np.float32) * 0.02
        return jnp.asarray(w, s.dtype)

    full = jax.tree_util.tree_map_with_path(fill, abstract)
    # replace the stacked transformer kernels with synthesized packed leaves
    from datatunerx_tpu.ops.quant import QUANT_KERNELS

    layers = dict(full["layers"])
    for kname in QUANT_KERNELS:
        proj = dict(layers[kname])
        kern = proj.pop("kernel")
        L, in_dim, out_dim = kern.shape
        del kern
        nb = in_dim * out_dim // NF4_BLOCK
        packed = rng.integers(0, 256, (L, nb * NF4_BLOCK // 2), dtype=np.uint8)
        scale_q = rng.integers(1, 128, (L, nb), dtype=np.int8)
        meta = np.stack(
            [np.full((L,), 0.08 / 127.0, np.float32),
             np.full((L,), NF4_LAYOUT_VERSION, np.float32)], axis=1)
        proj["quant"] = {
            "packed": jnp.asarray(packed),
            "scale_q": jnp.asarray(scale_q),
            "meta": jnp.asarray(meta),
        }
        layers[kname] = proj
    full = dict(full)
    full["layers"] = layers
    return full


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=4)
    ap.add_argument("--seq", type=int, default=1024)
    ap.add_argument("--steps", type=int, default=10)
    ap.add_argument("--attention", default="flash", choices=["xla", "flash"])
    ap.add_argument("--quant_impl", default="pallas",
                    choices=["xla", "pallas"],
                    help="pallas = fused nf4 kernels fwd+bwd (weights stay "
                         "packed in HBM; round-3 default), xla = dequant+dot "
                         "(the round-2 709 tok/s/chip path)")
    ap.add_argument("--remat", default="full", choices=["none", "dots", "full"])
    ap.add_argument("--cache", default="/tmp/bench7b_params.npz",
                    help="quantized-params disk cache ('' disables): host "
                         "init+quantize of 7B costs ~40 min on one core, "
                         "variant sweeps shouldn't pay it twice")
    ap.add_argument("--real_quant", action="store_true",
                    help="on cache miss, do the real init+quantize pass "
                         "instead of synthesizing packed bytes (slow; only "
                         "needed when loss values must be meaningful)")
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp

    from datatunerx_tpu.models import get_config, init_params
    from datatunerx_tpu.ops.quant import quantize_model_params
    from datatunerx_tpu.training import TrainConfig, Trainer
    from datatunerx_tpu.training.loss import IGNORE_INDEX

    assert jax.default_backend() == "tpu", "7B bench needs the real chip"
    cpu = jax.devices("cpu")[0]

    cfg = get_config(
        "llama2-7b", remat=args.remat, attention_impl=args.attention,
        quantization="int4", quant_impl=args.quant_impl,
    )

    t0 = time.perf_counter()
    params = _load_cached(args.cache) if args.cache else None
    if params is None:
        with jax.default_device(cpu):
            if args.real_quant:
                params = _fast_host_init(cfg, init_params, seed=0)
                params = quantize_model_params(params, "int4")
            else:
                params = _synth_packed_init(cfg, init_params, seed=0)
            jax.block_until_ready(params)
        if args.cache and args.real_quant:
            _save_cached(args.cache, params)
    print(f"host init+quantize: {time.perf_counter() - t0:.1f}s",
          file=sys.stderr)

    tr = Trainer(
        cfg,
        TrainConfig(
            finetuning_type="lora", lora_rank=8, lora_alpha=32.0,
            lora_dropout=0.05, lora_targets=("q_proj", "v_proj"),
            learning_rate=2e-4, scheduler="cosine", optimizer="adamw",
            total_steps=1000, compute_dtype=jnp.bfloat16,
        ),
    )
    t0 = time.perf_counter()
    params = jax.device_put(params, jax.devices()[0])
    state = tr.init_state(params, jax.random.PRNGKey(1))
    print(f"device transfer + opt init: {time.perf_counter() - t0:.1f}s",
          file=sys.stderr)

    B, T = args.batch, args.seq
    toks = jax.random.randint(
        jax.random.PRNGKey(2), (B, T), 0, cfg.vocab_size, jnp.int32)
    labels = jnp.where(jnp.arange(T)[None, :] < T // 8, IGNORE_INDEX, toks)
    batch = {"input_ids": toks, "labels": labels}

    t0 = time.perf_counter()
    state, m = tr.train_step(state, batch)
    loss0 = float(m["loss"])  # host fetch = real sync (tunnel-safe)
    print(f"compile + first step: {time.perf_counter() - t0:.1f}s "
          f"loss={loss0:.3f}", file=sys.stderr)

    t0 = time.perf_counter()
    for _ in range(args.steps):
        state, m = tr.train_step(state, batch)
    float(m["loss"])
    dt = time.perf_counter() - t0
    toks_per_sec = B * T * args.steps / dt

    # 7B LoRA step ≈ 2 (fwd) + 4 (bwd) matmul-FLOPs per param-token
    approx_flops = 6 * 6.74e9 * toks_per_sec
    mfu = approx_flops / 197e12  # v5e bf16 peak 197 TFLOP/s

    print(json.dumps({
        "metric": (f"qlora_sft_tokens_per_sec_per_chip[llama2-7b,nf4,"
                   f"B{B}xT{T},{args.attention},remat={args.remat},"
                   f"quant={args.quant_impl}]"),
        "value": round(toks_per_sec, 1),
        "unit": "tokens/s/chip",
        "vs_baseline": round(mfu, 3),  # MFU in lieu of a reference number
    }))


if __name__ == "__main__":
    main()
