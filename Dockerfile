# Operator image (parity with reference Dockerfile:1-17 — small runtime image
# for the controller-manager; no accelerator needed).
FROM python:3.12-slim

WORKDIR /app
RUN apt-get update && apt-get install -y --no-install-recommends g++ \
    && rm -rf /var/lib/apt/lists/*
COPY pyproject.toml ./
COPY datatunerx_tpu ./datatunerx_tpu
RUN pip install --no-cache-dir . numpy

EXPOSE 8080 8081
ENTRYPOINT ["python", "-m", "datatunerx_tpu.operator.manager"]
