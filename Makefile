# Build/test/deploy targets (parity with the reference's kubebuilder Makefile
# test/docker-build/deploy surface, Makefile:96-165).

IMG_OPERATOR ?= datatunerx-tpu/operator:latest
IMG_TRAINER  ?= datatunerx-tpu/trainer:latest

.PHONY: test test-fast native bench graft-check aot-certify docker-build deploy undeploy fmt lint lint-fix

test:            ## full test suite (8-device virtual CPU mesh)
	python -m pytest tests/ -q

lint:            ## dtxlint: program-level JAX-aware static analysis (the tier-1 CI gate)
	python -m datatunerx_tpu.analysis datatunerx_tpu/ scripts/ bench.py __graft_entry__.py

lint-fix:        ## apply dtxlint's mechanical autofixes (DTX002/DTX008), then re-lint
	python -m datatunerx_tpu.analysis datatunerx_tpu/ scripts/ bench.py __graft_entry__.py --fix

test-fast:       ## skip the slow live-pipeline e2e
	python -m pytest tests/ -q -m "not slow"

native:          ## build the C++ data-path extension
	python -c "from datatunerx_tpu import native; assert native.available(); print('native OK')"

bench:           ## headline benchmark (one JSON line)
	python bench.py

graft-check:     ## driver contract: entry() + dryrun_multichip(8)
	python scripts/graft_check.py

aot-certify:     ## deviceless Mosaic/XLA-TPU compile certification (v5e)
	python scripts/aot_certify.py

docker-build:    ## operator + trainer images
	docker build -t $(IMG_OPERATOR) -f Dockerfile .
	docker build -t $(IMG_TRAINER) -f Dockerfile.trainer .

deploy:          ## apply operator manifests to the current cluster
	kubectl apply -f deploy/crds/ -f deploy/rbac.yaml -f deploy/operator.yaml

undeploy:
	kubectl delete -f deploy/operator.yaml -f deploy/rbac.yaml

fmt:
	python -m compileall -q datatunerx_tpu
