"""In-process fake Kubernetes apiserver — the envtest stand-in.

The reference's Makefile test target runs reconcilers against envtest (a real
kube-apiserver + etcd, reference Makefile:115-117); this sandbox has no k8s
binaries, so this module implements the apiserver REST semantics the
controllers + KubeObjectStore depend on, with high fidelity:

- group/version/plural endpoints for ANY resource (CRDs and e.g. JobSet alike)
- optimistic concurrency via metadata.resourceVersion (409 Conflict)
- the status subresource (PUT …/status writes only .status)
- finalizer-gated deletion (DELETE sets deletionTimestamp while finalizers
  remain; removal of the last finalizer completes the delete)
- ownerReference cascade GC on actual deletion
- label-selector list filtering (equality terms)
- watch streams (?watch=true) with resourceVersion resume + initial-state
  ADDED events, one JSON object per line
- admission webhooks: stored Mutating/ValidatingWebhookConfiguration objects
  are honored on create/update — the fake POSTs admission.k8s.io/v1
  AdmissionReview to the configured url over TLS (verified against the
  config's caBundle), applies returned JSONPatches, and surfaces denials as
  400s, exactly like a real apiserver front-running the operator's webhook
  server
- structural-schema enforcement (VERDICT r3 #5): stored
  CustomResourceDefinition objects drive type/enum/required validation AND
  unknown-field pruning on create/update of their resources, honoring
  x-kubernetes-preserve-unknown-fields exactly as written, in the real
  apiserver's phase order (mutating webhooks → prune+validate → validating
  webhooks). Resources with no stored CRD pass through untouched (builtin
  kinds). This makes the published deploy/crds/ schemas load-bearing.

Single global revision counter (etcd-style); resourceVersions are digit
strings as on a real cluster.
"""

from __future__ import annotations

import json
import threading
import time
import urllib.parse
import uuid
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer


def _now() -> str:
    return time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())


class _State:
    def __init__(self):
        self.lock = threading.RLock()
        self.cond = threading.Condition(self.lock)
        self.rv = 0
        # (group, plural, namespace, name) -> object dict
        self.objects: dict = {}
        # append-only: (seq, group, plural, namespace, type, snapshot)
        self.events: list = []

    def bump(self) -> int:
        self.rv += 1
        return self.rv

    def emit(self, group, plural, ns, ev_type, obj):
        self.events.append((self.rv, group, plural, ns, ev_type,
                            json.loads(json.dumps(obj))))
        self.cond.notify_all()


class FakeKubeApiServer:
    def __init__(self):
        self.state = _State()
        outer = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):
                pass

            def _send(self, code, body: dict):
                data = json.dumps(body).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def _status_err(self, code, reason, message):
                self._send(code, {
                    "kind": "Status", "apiVersion": "v1", "status": "Failure",
                    "reason": reason, "message": message, "code": code,
                })

            def _read_body(self):
                n = int(self.headers.get("Content-Length", 0))
                return json.loads(self.rfile.read(n)) if n else {}

            def do_GET(self):
                outer._get(self)

            def do_POST(self):
                outer._post(self)

            def do_PUT(self):
                outer._put(self)

            def do_DELETE(self):
                outer._delete(self)

        self.server = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self.server.daemon_threads = True
        self.thread = threading.Thread(target=self.server.serve_forever, daemon=True)

    # ------------------------------------------------------------ lifecycle
    def start(self):
        self.thread.start()
        return self

    def stop(self):
        self.server.shutdown()
        self.server.server_close()
        if self.thread.is_alive():
            self.thread.join(timeout=5)

    @property
    def url(self) -> str:
        return f"http://127.0.0.1:{self.server.server_port}"

    # ------------------------------------------------------------- routing
    @staticmethod
    def _parse(path: str):
        """→ (group, version, plural, namespace, name, subresource, query)"""
        parsed = urllib.parse.urlparse(path)
        q = urllib.parse.parse_qs(parsed.query)
        parts = [p for p in parsed.path.split("/") if p]
        group = version = plural = ns = name = sub = None
        if not parts:
            return None
        if parts[0] == "api":  # core
            group, rest = "", parts[2:] if len(parts) > 2 else []
            version = parts[1] if len(parts) > 1 else "v1"
        elif parts[0] == "apis" and len(parts) >= 3:
            group, version, rest = parts[1], parts[2], parts[3:]
        else:
            return None
        # "/namespaces/<x>" alone addresses the Namespace RESOURCE itself;
        # it is only a scoping prefix when a plural follows
        if rest[:1] == ["namespaces"] and len(rest) >= 3:
            ns, rest = rest[1], rest[2:]
        if rest:
            plural, rest = rest[0], rest[1:]
        if rest:
            name, rest = rest[0], rest[1:]
        if rest:
            sub = rest[0]
        return group, version, plural, ns, name, sub, q

    # --------------------------------------------------------------- verbs
    def _get(self, h):
        r = self._parse(h.path)
        if not r or not r[2]:
            return h._status_err(404, "NotFound", "unrecognized path")
        group, version, plural, ns, name, sub, q = r
        st = self.state
        if name:
            with st.lock:
                obj = st.objects.get((group, plural, ns, name))
            if obj is None:
                return h._status_err(404, "NotFound", f"{plural} {ns}/{name}")
            return h._send(200, obj)
        if q.get("watch", ["false"])[0] == "true":
            return self._watch(h, group, plural, ns, q)
        # list
        selector = q.get("labelSelector", [None])[0]
        terms = {}
        if selector:
            for t in selector.split(","):
                k, _, v = t.partition("=")
                terms[k] = v
        with st.lock:
            items = [
                o for (g, p, n, _), o in st.objects.items()
                if g == group and p == plural and (ns is None or n == ns)
                and all((o["metadata"].get("labels") or {}).get(k) == v
                        for k, v in terms.items())
            ]
            rv = st.rv
        return h._send(200, {
            "kind": "List", "apiVersion": f"{group}/{version}" if group else "v1",
            "metadata": {"resourceVersion": str(rv)},
            "items": json.loads(json.dumps(items)),
        })

    # ----------------------------------------------------------- admission
    WEBHOOK_GROUP = "admissionregistration.k8s.io"
    # plurals stored without a namespace, as on a real cluster
    CLUSTER_SCOPED = {
        "namespaces", "customresourcedefinitions", "clusterroles",
        "clusterrolebindings", "mutatingwebhookconfigurations",
        "validatingwebhookconfigurations", "priorityclasses",
    }

    def _webhook_configs(self, plural_cfg: str):
        """Stored webhook configurations of the given plural (cluster-scoped;
        the fake namespaces them under whatever ns they were POSTed with)."""
        with self.state.lock:
            return [
                json.loads(json.dumps(o))
                for (g, p, _, _), o in self.state.objects.items()
                if g == self.WEBHOOK_GROUP and p == plural_cfg
            ]

    @staticmethod
    def _rules_match(rules, group, version, plural, operation) -> bool:
        for rule in rules or []:
            if operation not in (rule.get("operations") or []):
                continue
            if group not in (rule.get("apiGroups") or []):
                continue
            vs = rule.get("apiVersions") or []
            if "*" not in vs and version not in vs:
                continue
            rs = rule.get("resources") or []
            if "*" in rs or plural in rs:
                return True
        return False

    @staticmethod
    def _call_webhook(webhook: dict, review: dict) -> dict:
        """POST an AdmissionReview to the webhook url, TLS-verified against
        its caBundle. Returns the response dict; raises on transport error
        (failurePolicy Fail semantics at the call site)."""
        import base64
        import ssl
        import urllib.request

        cc = webhook.get("clientConfig") or {}
        url = cc.get("url")
        if not url:
            raise RuntimeError("only url-style clientConfig supported")
        ca = cc.get("caBundle")
        if ca:
            # self-signed server certs carry only SAN entries for
            # localhost/127.0.0.1 — keep hostname checking ON (the cert
            # manager includes them), just trust the provided CA
            ctx = ssl.create_default_context(
                cadata=base64.b64decode(ca).decode())
        else:
            ctx = ssl.create_default_context()
        req = urllib.request.Request(
            url, data=json.dumps(review).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=10, context=ctx) as resp:
            return json.loads(resp.read()).get("response") or {}

    @staticmethod
    def _apply_json_patch(obj: dict, patch_b64: str) -> dict:
        """RFC-6902 subset: add/replace (what defaulting webhooks emit)."""
        import base64

        ops = json.loads(base64.b64decode(patch_b64))
        for op in ops:
            if op.get("op") not in ("add", "replace"):
                raise RuntimeError(f"unsupported patch op {op.get('op')!r}")
            parts = [p.replace("~1", "/").replace("~0", "~")
                     for p in op["path"].lstrip("/").split("/")]
            node = obj
            for p in parts[:-1]:
                nxt = node.get(p)
                if not isinstance(nxt, dict):
                    nxt = {}
                    node[p] = nxt
                node = nxt
            node[parts[-1]] = op["value"]
        return obj

    # ------------------------------------------------- structural schemas

    def _crd_schema(self, group: str, plural: str):
        """openAPIV3Schema of the stored CRD serving (group, plural), or
        None when no CRD is registered (builtin kinds stay ungated)."""
        with self.state.lock:
            for (g, p, _, _), o in self.state.objects.items():
                if g != "apiextensions.k8s.io" or \
                        p != "customresourcedefinitions":
                    continue
                spec = o.get("spec") or {}
                names = spec.get("names") or {}
                if spec.get("group") != group or \
                        names.get("plural") != plural:
                    continue
                for v in spec.get("versions") or []:
                    if v.get("served"):
                        return (v.get("schema") or {}).get("openAPIV3Schema")
        return None

    @classmethod
    def _prune_validate(cls, schema: dict, value, path: str, errors: list):
        """Structural-schema semantics (types, enums, required, pruning with
        x-kubernetes-preserve-unknown-fields honored as written). Returns the
        pruned value; appends apiserver-shaped messages to ``errors``."""
        if schema is None:
            return value
        preserve = schema.get("x-kubernetes-preserve-unknown-fields") is True
        t = schema.get("type")
        if "enum" in schema and value not in schema["enum"]:
            errors.append(
                f'{path}: Unsupported value: {json.dumps(value)}: supported'
                f' values: {", ".join(json.dumps(e) for e in schema["enum"])}')
            return value
        if t == "object" or (t is None and "properties" in schema):
            if not isinstance(value, dict):
                errors.append(f"{path}: Invalid value: {json.dumps(value)}: "
                              f"expected object")
                return value
            props = schema.get("properties") or {}
            for req in schema.get("required") or []:
                if req not in value:
                    errors.append(f"{path}.{req}: Required value")
            out = {}
            for k, v in value.items():
                if k in props:
                    out[k] = cls._prune_validate(props[k], v, f"{path}.{k}",
                                                 errors)
                elif preserve or not props:
                    # open node (explicit preserve, or a bare object with no
                    # declared properties): unknown fields survive untouched
                    out[k] = v
                # else: pruned (a real structural schema drops it silently)
            return out
        if t == "array":
            if not isinstance(value, list):
                errors.append(f"{path}: Invalid value: {json.dumps(value)}: "
                              f"expected array")
                return value
            items = schema.get("items")
            return [cls._prune_validate(items, v, f"{path}[{i}]", errors)
                    for i, v in enumerate(value)]
        if t == "string":
            if not isinstance(value, str):
                errors.append(f"{path}: Invalid value: {json.dumps(value)}: "
                              f"expected string")
        elif t == "integer":
            if isinstance(value, bool) or not isinstance(value, int):
                errors.append(f"{path}: Invalid value: {json.dumps(value)}: "
                              f"expected integer")
        elif t == "number":
            if isinstance(value, bool) or not isinstance(value, (int, float)):
                errors.append(f"{path}: Invalid value: {json.dumps(value)}: "
                              f"expected number")
        elif t == "boolean":
            if not isinstance(value, bool):
                errors.append(f"{path}: Invalid value: {json.dumps(value)}: "
                              f"expected boolean")
        return value

    def _enforce_crd_schema(self, group, plural, body):
        """→ (pruned body, None) or (None, (code, reason, message)).
        metadata/apiVersion/kind are apiserver-owned and never schema-pruned;
        status is subresource-managed (stripped on create, preserved on
        update) so only spec-level data fields go through the schema."""
        schema = self._crd_schema(group, plural)
        if schema is None:
            return body, None
        errors: list = []
        props = (schema.get("properties") or {})
        out = dict(body)
        for k, sub in props.items():
            if k in ("metadata", "status") or k not in body:
                continue
            out[k] = self._prune_validate(sub, body[k], k, errors)
        if errors:
            kind = body.get("kind") or plural[:-1].capitalize()
            name = (body.get("metadata") or {}).get("name", "")
            return None, (
                422, "Invalid",
                f'{kind}.{group} "{name}" is invalid: ' + "; ".join(errors))
        return out, None

    def _admit(self, group, version, plural, ns, body, operation):
        """Mutating webhooks → structural-schema prune+validate → validating
        webhooks (the real apiserver's phase order). Returns
        (possibly-mutated body, None) or (None, (code, reason, message))."""
        if group == self.WEBHOOK_GROUP:
            return body, None  # configurations themselves are not gated
        kind = body.get("kind") or plural[:-1].capitalize()
        review_of = lambda obj: {  # noqa: E731
            "apiVersion": "admission.k8s.io/v1",
            "kind": "AdmissionReview",
            "request": {
                "uid": str(uuid.uuid4()),
                "kind": {"group": group, "version": version, "kind": kind},
                "resource": {"group": group, "version": version,
                             "resource": plural},
                "namespace": ns,
                "operation": operation,
                "object": obj,
            },
        }
        def run_phase(cfg_plural, phase, body):
            for cfg in self._webhook_configs(cfg_plural):
                for wh in cfg.get("webhooks") or []:
                    if not self._rules_match(wh.get("rules"), group, version,
                                             plural, operation):
                        continue
                    try:
                        resp = self._call_webhook(wh, review_of(body))
                    except Exception as e:  # noqa: BLE001
                        if (wh.get("failurePolicy") or "Fail") == "Ignore":
                            continue
                        return None, (
                            500, "InternalError",
                            f'failed calling webhook "{wh.get("name")}": {e}')
                    if not resp.get("allowed"):
                        msg = ((resp.get("status") or {}).get("message")
                               or "denied")
                        return None, (
                            400, "AdmissionDenied",
                            f'admission webhook "{wh.get("name")}" denied '
                            f"the request: {msg}")
                    if phase == "mutate" and resp.get("patch"):
                        try:
                            body = self._apply_json_patch(body, resp["patch"])
                        except Exception as e:  # noqa: BLE001
                            return None, (500, "InternalError",
                                          f"bad webhook patch: {e}")
            return body, None

        body, denial = run_phase("mutatingwebhookconfigurations", "mutate",
                                 body)
        if denial is not None:
            return None, denial
        # prune + schema-validate AFTER mutation, BEFORE validating webhooks
        # (kube-apiserver order: defaulted fields are pruned/validated too,
        # and validating webhooks see the object as it will be persisted)
        body, denial = self._enforce_crd_schema(group, plural, body)
        if denial is not None:
            return None, denial
        return run_phase("validatingwebhookconfigurations", "validate", body)

    def _post(self, h):
        r = self._parse(h.path)
        if not r or not r[2] or r[4]:
            return h._status_err(404, "NotFound", "bad create path")
        group, version, plural, ns, _, _, _ = r
        body = h._read_body()
        name = (body.get("metadata") or {}).get("name")
        if not name:
            return h._status_err(422, "Invalid", "metadata.name required")
        if plural in self.CLUSTER_SCOPED:
            ns = None
        else:
            ns = (ns or (body.get("metadata") or {}).get("namespace")
                  or "default")
        body, denial = self._admit(group, version, plural, ns, body, "CREATE")
        if denial is not None:
            return h._status_err(*denial)
        st = self.state
        with st.lock:
            key = (group, plural, ns, name)
            if key in st.objects:
                return h._status_err(409, "AlreadyExists",
                                     f"{plural} {ns}/{name} already exists")
            meta = body.setdefault("metadata", {})
            if ns is not None:
                meta["namespace"] = ns
            meta.setdefault("uid", str(uuid.uuid4()))
            meta["creationTimestamp"] = _now()
            meta["generation"] = 1
            meta.pop("deletionTimestamp", None)
            body["status"] = {}  # status subresource: not settable on create
            rv = st.bump()
            meta["resourceVersion"] = str(rv)
            st.objects[key] = body
            st.emit(group, plural, ns, "ADDED", body)
            return h._send(201, body)

    def _put(self, h):
        r = self._parse(h.path)
        if not r or not r[4]:
            return h._status_err(404, "NotFound", "bad update path")
        group, version, plural, ns, name, sub, _ = r
        body = h._read_body()
        if sub is None:
            # status writes bypass admission (real apiservers only call
            # webhooks for subresources explicitly scoped to them)
            body, denial = self._admit(group, version, plural, ns, body,
                                       "UPDATE")
            if denial is not None:
                return h._status_err(*denial)
        st = self.state
        with st.lock:
            key = (group, plural, ns, name)
            cur = st.objects.get(key)
            if cur is None:
                return h._status_err(404, "NotFound", f"{plural} {ns}/{name}")
            sent_rv = (body.get("metadata") or {}).get("resourceVersion")
            if sent_rv is not None and sent_rv != cur["metadata"]["resourceVersion"]:
                return h._status_err(
                    409, "Conflict",
                    f"rv {sent_rv} != {cur['metadata']['resourceVersion']}")
            new = json.loads(json.dumps(cur))
            if sub == "status":
                new["status"] = body.get("status", {})
            else:
                # main resource write: data fields (spec, or e.g. `webhooks`
                # on admissionregistration kinds) + mutable metadata; status
                # immutable
                for k in set(body) | set(new):
                    if k in ("metadata", "status", "apiVersion", "kind"):
                        continue
                    if k in body:
                        new[k] = body[k]
                    else:
                        new.pop(k, None)
                m, bm = new["metadata"], body.get("metadata") or {}
                for f in ("labels", "annotations", "finalizers",
                          "ownerReferences"):
                    if f in bm:
                        m[f] = bm[f]
                    else:
                        m.pop(f, None)
                if new.get("spec") != cur.get("spec"):
                    m["generation"] = int(m.get("generation", 1)) + 1
            if new == cur:
                return h._send(200, cur)  # no-op: no rv bump, no event
            rv = st.bump()
            new["metadata"]["resourceVersion"] = str(rv)
            st.objects[key] = new
            st.emit(group, plural, ns, "MODIFIED", new)
            # finalizer-gated deletion completes when finalizers empty out
            if (new["metadata"].get("deletionTimestamp")
                    and not new["metadata"].get("finalizers")):
                self._finalize_delete(key)
            return h._send(200, new)

    def _delete(self, h):
        r = self._parse(h.path)
        if not r or not r[4]:
            return h._status_err(404, "NotFound", "bad delete path")
        group, version, plural, ns, name, _, _ = r
        st = self.state
        with st.lock:
            key = (group, plural, ns, name)
            cur = st.objects.get(key)
            if cur is None:
                return h._status_err(404, "NotFound", f"{plural} {ns}/{name}")
            if cur["metadata"].get("finalizers"):
                if not cur["metadata"].get("deletionTimestamp"):
                    cur = json.loads(json.dumps(cur))
                    cur["metadata"]["deletionTimestamp"] = _now()
                    cur["metadata"]["resourceVersion"] = str(st.bump())
                    st.objects[key] = cur
                    st.emit(group, plural, ns, "MODIFIED", cur)
                return h._send(200, cur)
            self._finalize_delete(key)
            return h._send(200, {"kind": "Status", "status": "Success"})

    def _finalize_delete(self, key):
        """Caller holds the lock. Removes + emits DELETED + GC cascade."""
        st = self.state
        obj = st.objects.pop(key, None)
        if obj is None:
            return
        group, plural, ns, _ = key
        st.bump()
        st.emit(group, plural, ns, "DELETED", obj)
        uid = obj["metadata"].get("uid")
        # ownerReference cascade (the GC controller on a real cluster)
        for ckey, child in list(st.objects.items()):
            for ref in child["metadata"].get("ownerReferences") or []:
                if ref.get("uid") == uid:
                    cg, cp, cns, cname = ckey
                    if child["metadata"].get("finalizers"):
                        if not child["metadata"].get("deletionTimestamp"):
                            child = json.loads(json.dumps(child))
                            child["metadata"]["deletionTimestamp"] = _now()
                            child["metadata"]["resourceVersion"] = str(st.bump())
                            st.objects[ckey] = child
                            st.emit(cg, cp, cns, "MODIFIED", child)
                    else:
                        self._finalize_delete(ckey)
                    break

    # --------------------------------------------------------------- watch
    def _watch(self, h, group, plural, ns, q):
        st = self.state
        since = q.get("resourceVersion", [None])[0]
        since = int(since) if since and since.isdigit() else None
        h.send_response(200)
        h.send_header("Content-Type", "application/json")
        h.send_header("Transfer-Encoding", "chunked")
        h.end_headers()

        def write_event(ev_type, obj):
            line = json.dumps({"type": ev_type, "object": obj}).encode() + b"\n"
            h.wfile.write(f"{len(line):x}\r\n".encode() + line + b"\r\n")
            h.wfile.flush()

        try:
            with st.lock:
                if since is None:
                    # initial-state snapshot (k8s "send initial events")
                    for (g, p, n, _), o in list(st.objects.items()):
                        if g == group and p == plural and (ns is None or n == ns):
                            write_event("ADDED", json.loads(json.dumps(o)))
                    cursor = len(st.events)
                else:
                    cursor = 0
                while True:
                    while cursor < len(st.events):
                        seq, g, p, n, ev_type, obj = st.events[cursor]
                        cursor += 1
                        if g != group or p != plural:
                            continue
                        if ns is not None and n != ns:
                            continue
                        if since is not None and seq <= since:
                            continue
                        write_event(ev_type, obj)
                    if not st.cond.wait(timeout=30):
                        return  # idle timeout: client reconnects
        except (BrokenPipeError, ConnectionResetError):
            return
