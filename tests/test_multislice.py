"""Multi-slice (DCN) meshes: dp's major dimension crosses slices; the full
training step compiles and matches single-slice results on the virtual CPU
mesh (ROADMAP §4; SURVEY §5.8 marks DCN as the multi-slice extension)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from datatunerx_tpu.models import get_config, init_params
from datatunerx_tpu.parallel.mesh import make_mesh, mesh_shape_for
from datatunerx_tpu.training import TrainConfig, Trainer


def test_hybrid_mesh_device_order_groups_slices():
    devices = jax.devices()
    assert len(devices) >= 8
    mesh = make_mesh((4, 2, 1, 1), devices=devices[:8], dcn_dp=2)
    assert mesh.shape == {"dp": 4, "fsdp": 2, "tp": 1, "sp": 1}
    arr = mesh.devices  # [dp, fsdp, tp, sp]
    # dp-major crosses "slices": first half of dp rows = first device chunk
    first_slice = {d.id for d in np.asarray(arr)[:2].flatten()}
    second_slice = {d.id for d in np.asarray(arr)[2:].flatten()}
    assert first_slice == {d.id for d in devices[:4]}
    assert second_slice == {d.id for d in devices[4:8]}


def test_dcn_dp_must_divide_dp():
    with pytest.raises(ValueError, match="divisible"):
        make_mesh((3, 2, 1, 1), devices=jax.devices()[:6], dcn_dp=2)


def test_train_step_matches_single_slice():
    """Same data, same init: the 2-'slice' hybrid mesh must produce the same
    loss as the flat mesh (the hierarchy changes collective ROUTING, not
    math)."""
    cfg = get_config("debug", num_heads=4, num_kv_heads=2, hidden_size=64,
                     intermediate_size=128)
    shape = mesh_shape_for(8, fsdp=2, tp=1, sp=1)  # dp=4, fsdp=2

    def run(dcn_dp):
        mesh = make_mesh(shape, dcn_dp=dcn_dp)
        tr = Trainer(cfg, TrainConfig(
            finetuning_type="full", learning_rate=1e-3, total_steps=4,
            compute_dtype=None), mesh=mesh)
        state = tr.init_state(init_params(cfg, jax.random.PRNGKey(0)),
                              jax.random.PRNGKey(1))
        toks = jax.random.randint(jax.random.PRNGKey(2), (8, 16), 0,
                                  cfg.vocab_size, jnp.int32)
        batch = {"input_ids": toks, "labels": toks}
        losses = []
        for _ in range(2):
            state, m = tr.train_step(state, batch)
            losses.append(float(m["loss"]))
        return losses

    flat = run(dcn_dp=1)
    hybrid = run(dcn_dp=2)
    np.testing.assert_allclose(hybrid, flat, rtol=1e-5, atol=1e-6)


def test_cli_mesh_accepts_dcn(tmp_path):
    """--mesh dcn=2,fsdp=2 runs end-to-end through the trainer CLI."""
    import json

    from datatunerx_tpu.tuning.parser import parse_train_args
    from datatunerx_tpu.tuning.train import run

    data = tmp_path / "t.csv"
    with open(data, "w") as f:
        f.write("instruction,response\n")
        for i in range(40):
            f.write(f"q {i},a {i}\n")
    args = parse_train_args([
        "--model_name_or_path", "preset:debug",
        "--train_path", str(data), "--output_dir", str(tmp_path / "out"),
        "--storage_path", str(tmp_path / "s"), "--uid", "dcn-run",
        "--template", "vanilla", "--max_steps", "2", "--bf16", "false",
        "--remat", "none", "--per_device_train_batch_size", "4",
        "--block_size", "64", "--mesh", "dcn=2,fsdp=2",
    ])
    r = run(args)
    assert r["steps"] == 2
    mf = json.load(open(tmp_path / "s" / "dcn-run" / "manifest.json"))
    assert mf["mesh"] == {"dp": 4, "fsdp": 2, "tp": 1, "sp": 1}
