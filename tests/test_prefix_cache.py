"""Prefix-cache reuse in the continuous-batching engine (ROADMAP §2): exact
hits skip prefill, shared-prefix prompts extend a cached row instead of
recomputing it, LRU evicts, and — the correctness bar — every reuse path
produces exactly the generation the cold path produces."""

import pytest

from datatunerx_tpu.serving.batched_engine import BatchedEngine, _PrefixCache


@pytest.fixture(scope="module")
def cold():
    eng = BatchedEngine("preset:debug", template="vanilla", max_seq_len=256,
                        slots=2, decode_chunk=4)
    yield eng
    eng.close()


@pytest.fixture(scope="module")
def cached():
    eng = BatchedEngine("preset:debug", template="vanilla", max_seq_len=256,
                        slots=2, decode_chunk=4, prefix_cache=3)
    yield eng
    eng.close()


# ----------------------------------------------------------- unit: LRU

def test_lru_unit():
    pc = _PrefixCache(2)
    pc.put(((1, 2), 0), {"cursor": 2})
    pc.put(((1, 2, 3), 0), {"cursor": 3})
    assert pc.get(((1, 2), 0)) is not None  # refresh
    pc.put(((9,), 0), {"cursor": 1})        # evicts (1,2,3)
    assert pc.get(((1, 2, 3), 0)) is None
    assert pc.get(((1, 2), 0)) is not None

    key, ent = pc.longest_prefix((1, 2, 7, 8), 0)
    assert key == ((1, 2), 0)
    # strict prefix only: the full tuple itself must not match
    key2, _ = pc.longest_prefix((1, 2), 0)
    assert key2 is None
    # adapter isolation
    key3, _ = pc.longest_prefix((1, 2, 7), 1)
    assert key3 is None


def test_trie_deepest_wins_and_eviction_prunes():
    pc = _PrefixCache(4)
    pc.put(((5,), 0), {"cursor": 1})
    pc.put(((5, 6), 0), {"cursor": 2})
    pc.put(((5, 6, 7), 0), {"cursor": 3})
    # deepest stored prefix wins over shallower ones on one descent
    key, _ = pc.longest_prefix((5, 6, 7, 8, 9), 0)
    assert key == ((5, 6, 7), 0)

    # evicting the deep entry must fall back to the next-deepest, not to a
    # stale trie terminal
    pc.get(((5,), 0))
    pc.get(((5, 6), 0))
    pc.put(((1,), 0), {"cursor": 1})
    pc.put(((2,), 0), {"cursor": 1})  # evicts (5,6,7) (LRU)
    assert pc.get(((5, 6, 7), 0)) is None
    key, _ = pc.longest_prefix((5, 6, 7, 8, 9), 0)
    assert key == ((5, 6), 0)
    assert pc.evictions == 1


def test_trie_update_existing_key_keeps_single_terminal():
    pc = _PrefixCache(2)
    pc.put(((3, 4), 0), {"cursor": 2})
    pc.put(((3, 4), 0), {"cursor": 9})  # update, not insert
    assert len(pc) == 1
    key, ent = pc.longest_prefix((3, 4, 5), 0)
    assert key == ((3, 4), 0) and ent["cursor"] == 9
    # updating must not have doubled trie terminals: one eviction clears it
    pc.put(((8,), 0), {"cursor": 1})
    pc.put(((9,), 0), {"cursor": 1})
    assert pc.longest_prefix((3, 4, 5), 0) == (None, None)


def test_trie_adapter_roots_isolated():
    pc = _PrefixCache(4)
    pc.put(((1, 2), 0), {"cursor": 2})
    pc.put(((1, 2), 1), {"cursor": 2})
    k0, _ = pc.longest_prefix((1, 2, 3), 0)
    k1, _ = pc.longest_prefix((1, 2, 3), 1)
    assert k0 == ((1, 2), 0) and k1 == ((1, 2), 1)
    # evict adapter-0's entry; adapter-1's must survive the shared token path
    pc.put(((7,), 0), {"cursor": 1})
    pc.put(((8,), 0), {"cursor": 1})
    pc.put(((9,), 0), {"cursor": 1})  # capacity 4: evicts ((1,2),0)
    assert pc.longest_prefix((1, 2, 3), 0) == (None, None)
    k1b, _ = pc.longest_prefix((1, 2, 3), 1)
    assert k1b == ((1, 2), 1)


# ------------------------------------------------- engine: reuse paths

def test_exact_reuse_matches_cold(cold, cached):
    prompt = cold.tokenizer.encode("the quick brown fox jumps")
    want = cold.generate(prompt, max_new_tokens=10)

    got1 = cached.generate(prompt, max_new_tokens=10)
    full_after_first = cached.prefill_stats["full"]
    got2 = cached.generate(prompt, max_new_tokens=10)

    assert got1 == want
    assert got2 == want
    assert cached.prefill_stats["full"] == full_after_first  # no new prefill
    assert cached.prefill_stats["reuse"] >= 1


def test_prefix_extension_matches_cold(cold, cached):
    base = cold.tokenizer.encode("shared system preamble for every request")
    longer = base + cold.tokenizer.encode(" user question one")
    want = cold.generate(longer, max_new_tokens=10)

    cached.generate(base, max_new_tokens=1)  # seed the prefix entry
    before = dict(cached.prefill_stats)
    got = cached.generate(longer, max_new_tokens=10)

    assert got == want
    assert cached.prefill_stats["extend"] == before["extend"] + 1
    assert cached.prefill_stats["full"] == before["full"]


def test_extension_chain_and_second_hit(cached):
    """The extended entry is itself cached: a repeat of the longer prompt is
    an exact hit, and a yet-longer prompt extends the extended row."""
    base = cached.tokenizer.encode("chain base segment")
    mid = base + cached.tokenizer.encode(" plus middle")
    long_ = mid + cached.tokenizer.encode(" plus tail")

    cached.generate(base, max_new_tokens=1)
    cached.generate(mid, max_new_tokens=1)
    before = dict(cached.prefill_stats)

    r1 = cached.generate(mid, max_new_tokens=4)
    assert cached.prefill_stats["reuse"] == before["reuse"] + 1
    r2 = cached.generate(long_, max_new_tokens=4)
    assert cached.prefill_stats["extend"] == before["extend"] + 1
    assert r1 and r2


def test_long_generation_after_extension_matches_cold(cold, cached):
    """Decode must continue writing at the row's REAL KV depth (the cache
    cursor), not at this prompt's own bucketed plen: an extended row sits
    deeper, and a cursor reset to plen would overwrite cached suffix KV once
    generation runs long enough to reach it."""
    base = cold.tokenizer.encode("kv depth regression base prompt")
    longer = base + cold.tokenizer.encode(" with extra tail words")
    want = cold.generate(longer, max_new_tokens=110)

    cached.generate(base, max_new_tokens=1)  # seed prefix entry
    before = dict(cached.prefill_stats)
    got = cached.generate(longer, max_new_tokens=110)
    assert cached.prefill_stats["extend"] == before["extend"] + 1
    assert got == want


def test_reuse_never_shrinks_decode_budget(cold, cached):
    """A request whose decode budget fits the cold path but not the (deeper)
    cached row must fall back to cold prefill — cache state may never change
    the response."""
    base = cached.tokenizer.encode("budget parity base")
    longer = base + cached.tokenizer.encode(" tail")
    cached.generate(base, max_new_tokens=1)
    cached.generate(longer, max_new_tokens=1)  # extended entry, deep cursor
    # drive the entry deeper via chained extensions until an extension would
    # leave < 200 decode room (max_seq_len=256, plen stays 64 for short
    # prompts → cold budget 192)
    want = cold.generate(longer, max_new_tokens=180)
    before = dict(cached.prefill_stats)
    got = cached.generate(longer, max_new_tokens=180)
    assert got == want
    # the exact entry exists but its cursor (>=128) can't serve 180 new
    # tokens; the engine must NOT have reused it
    assert cached.prefill_stats["reuse"] == before["reuse"]
    assert cached.prefill_stats["full"] == before["full"] + 1


def test_metrics_endpoint_exposes_prefix_counters(cached):
    """/metrics (serving server) surfaces hit/miss/eviction counters in
    Prometheus text format (VERDICT r2 next-round #9)."""
    import urllib.request
    from http.server import ThreadingHTTPServer

    from datatunerx_tpu.serving import server as srv_mod

    prompt = cached.tokenizer.encode("metrics endpoint probe")
    cached.generate(prompt, max_new_tokens=2)
    cached.generate(prompt, max_new_tokens=2)  # exact hit

    old_engine = srv_mod.STATE.engine
    srv_mod.STATE.engine = cached
    srv = ThreadingHTTPServer(("127.0.0.1", 0), srv_mod.Handler)
    import threading

    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    try:
        url = f"http://127.0.0.1:{srv.server_port}/metrics"
        body = urllib.request.urlopen(url, timeout=10).read().decode()
    finally:
        srv.shutdown()
        srv_mod.STATE.engine = old_engine
    assert "dtx_serving_prefix_cache_hits_total" in body
    assert "dtx_serving_prefix_cache_misses_total" in body
    assert "dtx_serving_prefix_cache_evictions_total" in body
    assert "dtx_serving_prefix_cache_entries" in body
    hits = [line for line in body.splitlines()
            if line.startswith("dtx_serving_prefix_cache_hits_total")]
    assert hits and float(hits[0].split()[-1]) >= 1


def test_reuse_does_not_corrupt_shared_entry(cached):
    """Two requests admitted from the same cached prefix must not interfere:
    stored rows are immutable, slots get copies."""
    prompt = cached.tokenizer.encode("immutability probe prompt")
    a = cached.generate(prompt, max_new_tokens=8)
    b = cached.generate(prompt, max_new_tokens=8)
    c = cached.generate(prompt, max_new_tokens=8)
    assert a == b == c
