"""Continuous-batching engine: greedy parity with the single-request engine,
slot reuse/admission under load, measurable request overlap, streaming deltas,
and unmerged multi-adapter LoRA correctness (VERDICT round-1 item 5)."""

import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from datatunerx_tpu.models.llama import forward, init_cache
from datatunerx_tpu.models.lora import init_lora_params, lora_scaling, merge_lora
from datatunerx_tpu.serving.batched_engine import BatchedEngine
from datatunerx_tpu.serving.engine import InferenceEngine


@pytest.fixture(scope="module")
def single():
    return InferenceEngine("preset:debug", template="vanilla", max_seq_len=256)


@pytest.fixture(scope="module")
def batched():
    eng = BatchedEngine("preset:debug", template="vanilla", max_seq_len=256,
                        slots=2, decode_chunk=4)
    yield eng
    eng.close()


# ----------------------------------------------------- model primitive

def test_per_slot_cache_matches_scalar_cache():
    """Vector-cursor decode must equal scalar-cursor decode when all rows are
    at the same depth (the aligned case is exactly the old semantics)."""
    from datatunerx_tpu.models import get_config, init_params

    cfg = get_config("debug")
    params = init_params(cfg, jax.random.PRNGKey(0))
    B, P = 2, 8
    toks = jax.random.randint(jax.random.PRNGKey(1), (B, P), 0,
                              cfg.vocab_size, jnp.int32)

    cache_s = init_cache(cfg, B, P + 4, dtype=jnp.float32)
    logits_s, cache_s = forward(params, toks, cfg, cache=cache_s)
    cache_v = init_cache(cfg, B, P + 4, dtype=jnp.float32, per_slot=True)
    logits_v, cache_v = forward(params, toks, cfg, cache=cache_v)
    np.testing.assert_allclose(np.asarray(logits_s), np.asarray(logits_v),
                               rtol=2e-4, atol=2e-4)

    nxt = jnp.argmax(logits_s[:, -1], axis=-1).astype(jnp.int32)[:, None]
    pos = jnp.full((B, 1), P, jnp.int32)
    l2s, _ = forward(params, nxt, cfg, positions=pos, cache=cache_s)
    l2v, _ = forward(params, nxt, cfg, positions=pos, cache=cache_v)
    np.testing.assert_allclose(np.asarray(l2s), np.asarray(l2v),
                               rtol=2e-4, atol=2e-4)


def test_multi_adapter_matches_per_row_merge():
    """forward(lora_adapter_idx=…) with stacked adapters must equal running
    each row through its own merged model."""
    from datatunerx_tpu.models import get_config, init_params

    cfg = get_config("debug")
    params = init_params(cfg, jax.random.PRNGKey(0))
    rank = 4
    l1 = init_lora_params(cfg, jax.random.PRNGKey(1), rank=rank)
    l2 = init_lora_params(cfg, jax.random.PRNGKey(2), rank=rank)
    # non-zero B so adapters actually change the output
    for lo in (l1, l2):
        for t, ab in lo["layers"].items():
            ab["b"] = jax.random.normal(jax.random.PRNGKey(7), ab["b"].shape) * 0.05
    s1, s2 = lora_scaling(32, rank), lora_scaling(16, rank)

    # stacked tree: [L, E, …] with E=3 (0 = zero adapter)
    stack = {}
    for t in l1["layers"]:
        a = jnp.stack([jnp.zeros_like(l1["layers"][t]["a"]),
                       l1["layers"][t]["a"], l2["layers"][t]["a"]], axis=1)
        b = jnp.stack([jnp.zeros_like(l1["layers"][t]["b"]),
                       l1["layers"][t]["b"], l2["layers"][t]["b"]], axis=1)
        stack[t] = {"a": a, "b": b}
    scales = jnp.asarray([0.0, s1, s2], jnp.float32)

    toks = jax.random.randint(jax.random.PRNGKey(3), (3, 6), 0,
                              cfg.vocab_size, jnp.int32)
    got, _ = forward(params, toks, cfg, lora=({"layers": stack}, scales),
                     lora_adapter_idx=jnp.asarray([0, 1, 2], jnp.int32))

    base, _ = forward(params, toks[:1], cfg)
    m1, _ = forward(merge_lora(params, l1, s1), toks[1:2], cfg)
    m2, _ = forward(merge_lora(params, l2, s2), toks[2:3], cfg)
    want = jnp.concatenate([base, m1, m2], axis=0)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=5e-3, atol=5e-3)


# ------------------------------------------------------------- engine

def test_batched_greedy_matches_single_engine(single, batched):
    prompt = single.tokenizer.encode("the quick brown fox")
    want = single.generate(prompt, max_new_tokens=12)
    got = batched.generate(prompt, max_new_tokens=12)
    assert got == want, (got, want)


def test_more_requests_than_slots_all_complete(batched):
    prompts = [batched.tokenizer.encode(f"prompt number {i}") for i in range(5)]
    reqs = [batched.submit(p, max_new_tokens=6) for p in prompts]
    for r in reqs:
        assert r.done.wait(300), "request did not finish"
        assert r.error is None
        assert len(r.tokens) <= 6


def test_concurrent_requests_overlap(batched):
    """Two in-flight requests must occupy two slots of the same decode
    program at the same time — continuous batching, not serial turn-taking."""
    prompt = batched.tokenizer.encode("overlap test prompt")
    r1 = batched.submit(prompt, max_new_tokens=48)
    r2 = batched.submit(prompt, max_new_tokens=48)
    overlapped = False
    deadline = time.time() + 300
    while time.time() < deadline and not (r1.done.is_set() and r2.done.is_set()):
        if sum(r is not None for r in batched._slot_req) >= 2:
            overlapped = True
            break
        time.sleep(0.005)
    r1.done.wait(300), r2.done.wait(300)
    assert overlapped, "requests never shared the decode program"
    assert r1.error is None and r2.error is None


def test_streaming_deltas_concatenate_to_full_output(batched):
    msgs = [{"role": "user", "content": "hello there"}]
    full = batched.chat(msgs, max_new_tokens=10)
    pieces = []
    n_events = 0
    for delta in batched.chat_stream(msgs, max_new_tokens=10):
        pieces.append(delta)
        n_events += 1
    assert "".join(pieces) == full
    if len(full) > 1:
        assert n_events >= 1


def test_unknown_adapter_rejected(batched):
    with pytest.raises(KeyError, match="unknown adapter"):
        batched.submit([1, 2, 3], adapter="nope")


def test_interleaved_admission_prefix_consistency(batched):
    """A request admitted mid-decode of another must not perturb the other's
    output (slot isolation): run A alone, then A with B injected midway."""
    tok = batched.tokenizer
    pa = tok.encode("isolation check alpha")
    pb = tok.encode("a different prompt entirely for the second slot")
    want_a = batched.generate(pa, max_new_tokens=24)

    ra = batched.submit(pa, max_new_tokens=24)
    time.sleep(0.01)  # land B mid-flight (chunked decode ⇒ admission gap)
    rb = batched.submit(pb, max_new_tokens=8)
    assert ra.done.wait(300) and rb.done.wait(300)
    assert ra.tokens == want_a, (ra.tokens, want_a)


# ----------------------------------------------------- int8 KV cache

def test_int8_kv_cache_close_to_bf16_cache():
    """Quantized-cache decode logits track the full-precision cache within
    int8 tolerance (per-vector scales over head_dim)."""
    from datatunerx_tpu.models import get_config, init_params

    cfg = get_config("debug")
    params = init_params(cfg, jax.random.PRNGKey(0))
    B, P = 2, 8
    toks = jax.random.randint(jax.random.PRNGKey(1), (B, P), 0,
                              cfg.vocab_size, jnp.int32)

    ref_cache = init_cache(cfg, B, P + 4, dtype=jnp.float32)
    ref_logits, ref_cache = forward(params, toks, cfg, cache=ref_cache)
    q_cache = init_cache(cfg, B, P + 4, dtype=jnp.float32, quantize="int8")
    q_logits, q_cache = forward(params, toks, cfg, cache=q_cache)
    assert q_cache["k"].dtype == jnp.int8
    assert q_cache["k_scale"].shape == q_cache["k"].shape[:-1]
    np.testing.assert_allclose(np.asarray(q_logits), np.asarray(ref_logits),
                               rtol=0.1, atol=0.15)

    nxt = jnp.argmax(ref_logits[:, -1], axis=-1).astype(jnp.int32)[:, None]
    pos = jnp.full((B, 1), P, jnp.int32)
    l_ref, _ = forward(params, nxt, cfg, positions=pos, cache=ref_cache)
    l_q, _ = forward(params, nxt, cfg, positions=pos, cache=q_cache)
    np.testing.assert_allclose(np.asarray(l_q), np.asarray(l_ref),
                               rtol=0.1, atol=0.15)
    # and greedy argmax agrees on this step
    np.testing.assert_array_equal(
        np.argmax(np.asarray(l_q)[:, -1], -1),
        np.argmax(np.asarray(l_ref)[:, -1], -1))


def test_int8_kv_engine_end_to_end(single):
    """Batched engine with int8 cache completes requests; greedy output
    matches the full-precision engine on the debug model."""
    eng = BatchedEngine("preset:debug", template="vanilla", max_seq_len=256,
                        slots=2, decode_chunk=4, kv_quant="int8")
    try:
        prompt = single.tokenizer.encode("the quick brown fox")
        want = single.generate(prompt, max_new_tokens=8)
        got = eng.generate(prompt, max_new_tokens=8)
        assert got == want, (got, want)
    finally:
        eng.close()
