"""Kubernetes-native admission (VERDICT r2 next-round #4): the webhook rules
in operator/webhooks.py served behind a TLS AdmissionReview endpoint, wired
into the (fake) apiserver via Mutating/ValidatingWebhookConfiguration — so a
direct apiserver create of an invalid CR is rejected with the webhook's
message, exactly the guarantee the reference gets from its meta-server
webhooks + cert-rotator (reference controller_manager.go:83-135).
"""

import datetime
import json

import pytest

from datatunerx_tpu.operator.kubeclient import ApiError, KubeClient
from datatunerx_tpu.operator.webhook_server import (
    AdmissionWebhookServer,
    CertManager,
    install_webhooks,
    review_mutate,
    review_validate,
    webhook_configurations,
)
from tests.fake_apiserver import FakeKubeApiServer

GROUP_CORE = "core.datatunerx.io"


@pytest.fixture()
def apiserver():
    srv = FakeKubeApiServer().start()
    yield srv
    srv.stop()


@pytest.fixture(scope="module")
def webhook(tmp_path_factory):
    # TLS cert generation needs the optional `cryptography` dep (dev extra);
    # skip — not error — where it's absent
    pytest.importorskip("cryptography")
    certs = CertManager(str(tmp_path_factory.mktemp("wh-certs")),
                        dns_names=["localhost", "127.0.0.1"])
    srv = AdmissionWebhookServer(certs, host="127.0.0.1", port=0).start()
    yield srv
    srv.stop()


def _install(apiserver, webhook):
    client = KubeClient(base_url=apiserver.url)
    install_webhooks(client, webhook.certs.ca_bundle_b64(),
                     f"https://localhost:{webhook.port}")
    return client


def _hp(name, params):
    return {
        "apiVersion": f"{GROUP_CORE}/v1beta1",
        "kind": "Hyperparameter",
        "metadata": {"name": name, "namespace": "default"},
        "spec": {"parameters": params},
    }


# ------------------------------------------------------------ cert manager

def test_cert_manager_generates_and_reports_rotation(tmp_path):
    pytest.importorskip("cryptography")
    cm = CertManager(str(tmp_path / "certs"))
    assert cm.needs_rotation()  # nothing on disk yet
    assert cm.ensure() is True
    assert not cm.needs_rotation()
    assert cm.ensure() is False  # idempotent while valid
    assert cm.ca_bundle_b64()

    # a cert inside the refresh margin rotates
    short = CertManager(str(tmp_path / "short"), validity_days=5,
                        refresh_margin_days=30)
    assert short.ensure() is True
    assert short.needs_rotation()  # 5d validity < 30d margin
    exp1 = short._expiry()
    assert short.ensure() is True  # regenerated
    assert short._expiry() >= exp1
    assert isinstance(exp1, datetime.datetime)


# --------------------------------------------------------- review handlers

def test_review_validate_denies_bad_dropout():
    resp = review_validate({
        "uid": "u1",
        "kind": {"kind": "Hyperparameter"},
        "object": _hp("h", {"loRA_Dropout": "2.0"}),
    })
    assert resp["allowed"] is False
    assert "loRA_Dropout" in resp["status"]["message"]
    assert resp["uid"] == "u1"


def test_review_mutate_emits_defaulting_patch():
    resp = review_mutate({
        "uid": "u2",
        "kind": {"kind": "Hyperparameter"},
        "object": _hp("h", {"scheduler": "linear"}),
    })
    assert resp["allowed"] is True
    import base64

    ops = json.loads(base64.b64decode(resp["patch"]))
    paths = {op["path"] for op in ops}
    assert "/spec/parameters/optimizer" in paths  # defaulted
    assert "/spec/parameters/scheduler" not in paths  # already set


# ------------------------------------------- end-to-end via fake apiserver

def test_direct_apiserver_create_of_invalid_cr_rejected(apiserver, webhook):
    client = _install(apiserver, webhook)
    with pytest.raises(ApiError) as ei:
        client.request(
            "POST",
            f"/apis/{GROUP_CORE}/v1beta1/namespaces/default/hyperparameters",
            body=_hp("bad", {"loRA_Dropout": "2.0"}),
        )
    assert ei.value.status == 400
    assert "admission webhook" in ei.value.body
    assert "loRA_Dropout" in ei.value.body
    # nothing persisted
    with pytest.raises(ApiError):
        client.request(
            "GET",
            f"/apis/{GROUP_CORE}/v1beta1/namespaces/default/"
            "hyperparameters/bad",
        )


def test_valid_cr_created_with_defaults_applied(apiserver, webhook):
    client = _install(apiserver, webhook)
    created = client.request(
        "POST",
        f"/apis/{GROUP_CORE}/v1beta1/namespaces/default/hyperparameters",
        body=_hp("good", {"scheduler": "linear"}),
    )
    p = created["spec"]["parameters"]
    assert p["scheduler"] == "linear"          # user value kept
    assert p["optimizer"] == "adamw"           # defaulted via JSONPatch
    assert p["loRA_R"] == "8"


def test_update_also_gated(apiserver, webhook):
    client = _install(apiserver, webhook)
    created = client.request(
        "POST",
        f"/apis/{GROUP_CORE}/v1beta1/namespaces/default/hyperparameters",
        body=_hp("upd", {}),
    )
    created["spec"]["parameters"]["warmupRatio"] = "7.5"
    with pytest.raises(ApiError) as ei:
        client.request(
            "PUT",
            f"/apis/{GROUP_CORE}/v1beta1/namespaces/default/"
            "hyperparameters/upd",
            body=created,
        )
    assert ei.value.status == 400
    assert "warmupRatio" in ei.value.body


def test_unrelated_resources_not_gated(apiserver, webhook):
    client = _install(apiserver, webhook)
    client.request(
        "POST",
        "/apis/jobset.x-k8s.io/v1alpha2/namespaces/default/jobsets",
        body={"apiVersion": "jobset.x-k8s.io/v1alpha2", "kind": "JobSet",
              "metadata": {"name": "j1"}, "spec": {}},
    )  # no webhook rules match → no gating, no error


def test_invalid_dataset_rejected_via_webhook(apiserver, webhook):
    client = _install(apiserver, webhook)
    with pytest.raises(ApiError) as ei:
        client.request(
            "POST",
            "/apis/extension.datatunerx.io/v1beta1/namespaces/default/"
            "datasets",
            body={
                "apiVersion": "extension.datatunerx.io/v1beta1",
                "kind": "Dataset",
                "metadata": {"name": "d"},
                "spec": {"datasetMetadata": {"datasetInfo": {}}},
            },
        )
    assert ei.value.status == 400
    assert "subsets" in ei.value.body


def test_cert_rotation_repatches_cabundle(apiserver, tmp_path):
    """Rotation regenerates the CA, reloads TLS in place, and the re-patched
    caBundle keeps admission working — the cert-rotator loop end-to-end."""
    pytest.importorskip("cryptography")
    certs = CertManager(str(tmp_path / "rot"), validity_days=365,
                        dns_names=["localhost", "127.0.0.1"])
    srv = AdmissionWebhookServer(certs, host="127.0.0.1", port=0).start()
    try:
        client = KubeClient(base_url=apiserver.url)
        base = f"https://localhost:{srv.port}"
        install_webhooks(client, certs.ca_bundle_b64(), base)

        # force rotation: shrink validity window check
        certs.refresh_margin = datetime.timedelta(days=9999)
        assert certs.ensure() is True
        srv._ssl_ctx.load_cert_chain(certs.cert_path, certs.key_path)
        install_webhooks(client, certs.ca_bundle_b64(), base)

        # admission still enforced under the rotated chain
        with pytest.raises(ApiError) as ei:
            client.request(
                "POST",
                f"/apis/{GROUP_CORE}/v1beta1/namespaces/default/"
                "hyperparameters",
                body=_hp("rot-bad", {"loRA_Dropout": "3.0"}),
            )
        assert ei.value.status == 400
    finally:
        srv.stop()


def test_webhook_configuration_shape():
    cfgs = webhook_configurations("Q0E=", "https://localhost:9443")
    kinds = [c["kind"] for c in cfgs]
    assert kinds == ["MutatingWebhookConfiguration",
                     "ValidatingWebhookConfiguration"]
    val = cfgs[1]["webhooks"][0]
    assert val["failurePolicy"] == "Fail"
    assert val["clientConfig"]["caBundle"] == "Q0E="
    covered = {r for rule in val["rules"] for r in rule["resources"]}
    assert covered == {"finetunejobs", "finetuneexperiments", "llms",
                       "hyperparameters", "datasets"}


# -------------------------------------------------- round-4 ADVICE fixes

def test_serving_cert_sans_cover_service_dns(tmp_path):
    """ADVICE r3 high: in-cluster admission routes via
    <service>.<ns>.svc and the apiserver verifies the serving cert against
    that DNS name — the cert must carry the Service SANs, not just
    localhost."""
    pytest.importorskip("cryptography")
    from cryptography import x509
    from cryptography.x509.oid import ExtensionOID

    from datatunerx_tpu.operator.manager import webhook_cert_sans

    sans = webhook_cert_sans("datatunerx-webhook-service", "dtx-ns")
    assert sans[0] == "localhost"  # default url-base derives from [0]
    assert "datatunerx-webhook-service.dtx-ns.svc" in sans
    assert "datatunerx-webhook-service.dtx-ns.svc.cluster.local" in sans

    cm = CertManager(str(tmp_path / "certs"), dns_names=sans)
    cm.ensure()
    with open(cm.cert_path, "rb") as f:
        cert = x509.load_pem_x509_certificate(f.read())
    ext = cert.extensions.get_extension_for_oid(
        ExtensionOID.SUBJECT_ALTERNATIVE_NAME).value
    dns = set(ext.get_values_for_type(x509.DNSName))
    assert "datatunerx-webhook-service.dtx-ns.svc" in dns
    assert "datatunerx-webhook-service.dtx-ns.svc.cluster.local" in dns


def test_review_mutate_specless_object_adds_whole_spec():
    """ADVICE r3 low: RFC 6902 'add /spec/foo' is invalid when /spec does
    not exist — a specless object must get a single 'add /spec' op."""
    import base64

    resp = review_mutate({
        "uid": "u3",
        "kind": {"kind": "Hyperparameter"},
        "object": {
            "apiVersion": f"{GROUP_CORE}/v1beta1",
            "kind": "Hyperparameter",
            "metadata": {"name": "nospec", "namespace": "default"},
        },
    })
    assert resp["allowed"] is True
    ops = json.loads(base64.b64decode(resp["patch"]))
    assert len(ops) == 1
    assert ops[0]["op"] == "add" and ops[0]["path"] == "/spec"
    assert ops[0]["value"]["parameters"]["optimizer"]  # defaulted inside


def test_cert_rotates_on_san_drift(tmp_path):
    """A persisted cert dir from an older deploy (localhost-only SANs) must
    regenerate when the configured dns_names grow — months of remaining
    validity notwithstanding — or service-style TLS keeps failing."""
    pytest.importorskip("cryptography")
    d = str(tmp_path / "certs")
    old = CertManager(d, dns_names=["localhost", "127.0.0.1"])
    assert old.ensure() is True
    # same dir, new deploy wants service SANs
    new = CertManager(d, dns_names=["localhost", "127.0.0.1",
                                    "svc.ns.svc", "svc.ns.svc.cluster.local"])
    assert new.needs_rotation()
    assert new.ensure() is True
    assert not new.needs_rotation()
    # old manager config against the regenerated superset cert: no churn
    assert old.needs_rotation() is False


def test_review_mutate_null_spec_replaces_whole_spec():
    """`spec:` with no value in YAML arrives as spec: null — 'add /spec/foo'
    would fail RFC 6902 evaluation; must replace /spec wholesale."""
    import base64

    resp = review_mutate({
        "uid": "u4",
        "kind": {"kind": "Hyperparameter"},
        "object": {
            "apiVersion": f"{GROUP_CORE}/v1beta1",
            "kind": "Hyperparameter",
            "metadata": {"name": "nullspec", "namespace": "default"},
            "spec": None,
        },
    })
    assert resp["allowed"] is True
    ops = json.loads(base64.b64decode(resp["patch"]))
    assert len(ops) == 1
    assert ops[0]["op"] == "replace" and ops[0]["path"] == "/spec"
    assert ops[0]["value"]["parameters"]["optimizer"]
