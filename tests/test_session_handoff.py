"""KV migration fabric: live session export/import (serving/migration.py).

The correctness bar is the ISSUE's oracle: a session exported mid-decode
and imported on another replica resumes TOKEN-EXACTLY vs an undisturbed
run — greedy AND fixed-seed sampled, bf16 AND int8 kv_quant caches, base
AND mixed-rank pooled adapters (the target resolves the adapter NAME,
load-on-miss included). On top of the engine primitive: the gateway's
drain handoff (export → import → mid-stream SSE splice with no duplicate
or missing text), the admin HTTP wire format, refusal paths, the
replacement-inheritance satellite lives in test_gateway.py, and the
burn-rate autoscale + trace-log converter satellites."""

import json
import threading
import time
from http.server import ThreadingHTTPServer

import pytest

from datatunerx_tpu.serving.batched_engine import BatchedEngine

MODEL = "preset:debug"


def _throttled(eng, delay=0.04):
    """Slow each decode chunk so a test can deterministically catch a
    request mid-decode. Returns the original to restore."""
    orig = eng._decode

    def slow(*a, **k):
        time.sleep(delay)
        return orig(*a, **k)

    eng._decode = slow
    return orig


def _export_mid_decode(src, prompt, min_tokens=3, **kw):
    """Submit on a throttled ``src``, wait until it has streamed a few
    tokens, then export. Returns the (single) payload."""
    orig = _throttled(src)
    try:
        req = src.submit(prompt, **kw)
        deadline = time.monotonic() + 30
        while len(req.tokens) < min_tokens and time.monotonic() < deadline:
            time.sleep(0.01)
        assert len(req.tokens) >= min_tokens, "decode never started"
        doc = src.export_sessions()
    finally:
        src._decode = orig
    assert len(doc["sessions"]) == 1, doc
    assert req.done.wait(10) and "session migrated" in (req.error or "")
    return doc["sessions"][0]


def _import_and_wait(dst, payload, timeout=120):
    meta = dst.import_session(json.loads(json.dumps(payload)))
    handle = meta.pop("_request")
    assert handle.done.wait(timeout), "imported session never finished"
    assert handle.error is None, handle.error
    return handle, meta


@pytest.fixture(scope="module")
def paged_pair():
    src = BatchedEngine(MODEL, template="vanilla", max_seq_len=256,
                        slots=2, decode_chunk=4, kv_block_size=16)
    dst = BatchedEngine(MODEL, template="vanilla", max_seq_len=256,
                        slots=2, decode_chunk=4, kv_block_size=16)
    yield src, dst
    src.close()
    dst.close()


# --------------------------------------------------- engine-level parity

def test_export_import_greedy_parity(paged_pair):
    src, dst = paged_pair
    prompt = src.tokenizer.encode("the quick brown fox jumps over")
    want = src.generate(prompt, max_new_tokens=24)
    payload = _export_mid_decode(src, prompt, max_new_tokens=24)
    assert payload["kv"]["wire"] == "bf16"  # lossless native encoding
    handle, meta = _import_and_wait(dst, payload)
    assert handle.tokens == want, (handle.tokens, want)
    # the migrated tail was already streamed by the source; the import
    # receipt carries it detokenized for the gateway's splice
    assert meta["tokens"] == len(payload["tokens"])
    # elastic accounting on BOTH sides: source freed at export, target
    # freed at completion
    assert src.free_kv_blocks == src.total_kv_blocks
    assert dst.free_kv_blocks == dst.total_kv_blocks
    assert src.session_stats["export"].get("ok", 0) >= 1
    assert dst.session_stats["import"].get("ok", 0) >= 1


def test_export_import_sampled_parity(paged_pair):
    """Fixed-seed sampled resume: the payload carries the slot's LIVE rng
    key (not the seed), so the continuation consumes the same stream the
    undisturbed run would."""
    src, dst = paged_pair
    prompt = src.tokenizer.encode("sampling determinism migrates too")
    for seed in (0, 11):
        want = src.generate(prompt, max_new_tokens=16, temperature=0.8,
                            top_p=0.9, seed=seed)
        payload = _export_mid_decode(src, prompt, max_new_tokens=16,
                                     temperature=0.8, top_p=0.9, seed=seed)
        handle, _ = _import_and_wait(dst, payload)
        assert handle.tokens == want, (seed, handle.tokens, want)


def test_export_import_spec_active_session(paged_pair):
    """A SPEC-ACTIVE session (pending-token form, draft cache live) exports
    cleanly: the engine settles the pending token so the payload is the
    standard logits-form wire format, the importer re-primes its own draft
    cache from the payload's prompt + tail, and the greedy continuation is
    token-exact vs an undisturbed non-spec run — both into a spec engine
    and into a plain engine (the wire carries no spec state at all)."""
    ref, plain_dst = paged_pair
    src = BatchedEngine(MODEL, template="vanilla", max_seq_len=256,
                        slots=2, decode_chunk=4, kv_block_size=16,
                        spec_draft="take:2", spec_k=3, spec_mode="on")
    dst = BatchedEngine(MODEL, template="vanilla", max_seq_len=256,
                        slots=2, decode_chunk=4, kv_block_size=16,
                        spec_draft="take:2", spec_k=3, spec_mode="on")
    try:
        prompt = src.tokenizer.encode("speculative sessions migrate too")
        want = ref.generate(prompt, max_new_tokens=24)

        def export_mid_spec(target_dst):
            # throttle the SPEC tick (the spec engine never runs _decode)
            orig = src._spec_decode_tick

            def slow(*a, **k):
                time.sleep(0.04)
                return orig(*a, **k)

            src._spec_decode_tick = slow
            try:
                req = src.submit(prompt, max_new_tokens=24)
                deadline = time.monotonic() + 30
                while len(req.tokens) < 3 and time.monotonic() < deadline:
                    time.sleep(0.01)
                assert len(req.tokens) >= 3
                doc = src.export_sessions()
            finally:
                src._spec_decode_tick = orig
            assert len(doc["sessions"]) == 1, doc
            assert req.done.wait(10)
            # the settle wrote the pending token: payload cursor covers
            # every emitted token and carries next-token logits
            payload = doc["sessions"][0]
            assert any(ev[0] == "spec_settle" for ev in src.sched_trace)
            handle, _ = _import_and_wait(target_dst, payload)
            return handle

        handle = export_mid_spec(dst)
        assert handle.tokens == want, (handle.tokens, want)
        # the spec importer RE-PRIMED its draft (re-prime contract: no
        # draft KV on the wire) and kept speculating after the import
        assert any(ev[0] == "spec_prime" for ev in dst.sched_trace)
        assert (dst.spec_info() or {}).get("proposed", 0) > 0

        handle2 = export_mid_spec(plain_dst)  # spec → non-spec replica
        assert handle2.tokens == want, (handle2.tokens, want)
    finally:
        src.close()
        dst.close()


def test_export_import_spec_tree_active_session(paged_pair):
    """A TREE-SPEC-ACTIVE session exports cleanly: the settle collapses
    the in-flight verify columns to the standard logits-form wire format
    (no tree state on the wire), and the greedy continuation is
    token-exact — into a tree replica, and into a PLAIN replica that has
    never heard of trees."""
    ref, plain_dst = paged_pair
    # same config as test_speculative's tree engine, so the tree program
    # family compiles once per suite run (weak take:1 draft — the export
    # interrupts REAL rejection/rollback traffic, not an all-accept run)
    src = BatchedEngine(MODEL, template="vanilla", max_seq_len=256,
                        slots=3, decode_chunk=4, kv_block_size=16,
                        spec_draft="take:1", spec_k=3, spec_mode="on",
                        spec_tree="2x2")
    # the tree importer is the EXPORTER itself: its slot freed at export,
    # so the import lands in a fresh slot of the same tree engine
    dst = src
    try:
        prompt = src.tokenizer.encode("tree sessions migrate too")
        want = ref.generate(prompt, max_new_tokens=16)

        orig = src._spec_decode_tick

        def slow(*a, **k):
            time.sleep(0.04)
            return orig(*a, **k)

        src._spec_decode_tick = slow
        try:
            req = src.submit(prompt, max_new_tokens=16)
            deadline = time.monotonic() + 30
            while len(req.tokens) < 3 and time.monotonic() < deadline:
                time.sleep(0.01)
            assert len(req.tokens) >= 3
            doc = src.export_sessions()
        finally:
            src._spec_decode_tick = orig
        assert len(doc["sessions"]) == 1, doc
        assert req.done.wait(10)
        payload = doc["sessions"][0]
        # the exporter really was mid-TREE decode, not chain, and the
        # settle collapsed it; the wire format is tree-agnostic
        assert src.spec_info()["tree_steps"] > 0
        assert any(ev[0] == "spec_settle" for ev in src.sched_trace)
        # the KV/logits/rng wire stays tree-agnostic; the learned
        # spec-controller document rides alongside as plain JSON (slot
        # acceptance EMA + learned widths warm the importer's controller)
        wire_doc = {k: v for k, v in payload.items() if k != "spec"}
        assert "tree" not in json.dumps(wire_doc)
        assert payload["spec"]["plan"][0] == "tree"
        # the learned per-depth evidence rides along (importer controllers
        # adopt it instead of restarting the width search cold)
        assert "depth_ema" in payload["spec"]
        json.dumps(payload["spec"])  # JSON-safe end to end

        n_prime0 = sum(1 for ev in dst.sched_trace if ev[0] == "spec_prime")
        steps0 = dst.spec_info()["tree_steps"]
        handle, _ = _import_and_wait(dst, payload)
        assert handle.tokens == want, (handle.tokens, want)
        # the tree importer re-primed and kept tree-verifying after import
        assert sum(1 for ev in dst.sched_trace
                   if ev[0] == "spec_prime") > n_prime0
        assert dst.spec_info()["tree_steps"] > steps0

        # the SAME payload lands on a plain replica too: tree → plain
        handle2, _ = _import_and_wait(plain_dst, payload)
        assert handle2.tokens == want, (handle2.tokens, want)
    finally:
        src.close()


def test_export_import_int8_kv_parity():
    """int8 kv_quant engines ship their cache's own int8+scale bytes —
    the 'int8 over the wire' path is EXACT for them, greedy and sampled."""
    src = BatchedEngine(MODEL, template="vanilla", max_seq_len=256,
                        slots=2, decode_chunk=4, kv_block_size=16,
                        kv_quant="int8")
    dst = BatchedEngine(MODEL, template="vanilla", max_seq_len=256,
                        slots=2, decode_chunk=4, kv_block_size=16,
                        kv_quant="int8")
    try:
        prompt = src.tokenizer.encode("quantized cache migration probe")
        for kw in ({}, {"temperature": 0.7, "top_p": 0.9, "seed": 5}):
            want = src.generate(prompt, max_new_tokens=16, **kw)
            payload = _export_mid_decode(src, prompt, max_new_tokens=16,
                                         **kw)
            assert payload["kv"]["wire"] == "int8"
            assert "k_scale" in payload["kv"]
            handle, _ = _import_and_wait(dst, payload)
            assert handle.tokens == want, (kw, handle.tokens, want)
    finally:
        src.close()
        dst.close()


def test_export_import_mixed_rank_adapters(tmp_path):
    """Adapter sessions migrate by NAME across heterogeneous resident
    sets: the target's pool may hold the adapter in a different slot — or
    not at all, in which case the import itself pays the load-on-miss
    (parked and retried, like admission) — and still resumes
    token-exactly. Ranks 2 and 4 prove rank-padding survives the trip."""
    from datatunerx_tpu.serving.adapters import make_adapter_checkpoint

    cks = {n: make_adapter_checkpoint(str(tmp_path / n), MODEL,
                                      seed=3 + i, rank=2 * (i + 1))
           for i, n in enumerate(("a", "b"))}
    src = BatchedEngine(MODEL, adapters=cks, adapter_pool=2,
                        adapter_rank_max=8, template="vanilla",
                        max_seq_len=256, slots=2, decode_chunk=4,
                        kv_block_size=16)
    dst = BatchedEngine(MODEL, adapters=cks, adapter_pool=1,
                        adapter_rank_max=8, template="vanilla",
                        max_seq_len=256, slots=2, decode_chunk=4,
                        kv_block_size=16)
    try:
        prompt = src.tokenizer.encode("tenant session on the move")
        for adapter in ("a", "b"):
            want = src.generate(prompt, max_new_tokens=12, adapter=adapter)
            payload = _export_mid_decode(src, prompt, max_new_tokens=12,
                                         adapter=adapter)
            assert payload["adapter"] == adapter
            # dst has ONE pool slot: importing "b" after "a" forces an
            # evict + load-on-miss inside the import retry loop
            handle, meta = _import_and_wait(dst, payload)
            assert handle.tokens == want, (adapter, handle.tokens, want)
            assert meta["adapter"] == adapter
        assert dst.adapter_occupancy()["resident"] == 1
        # adapter sessions must differ from base, or parity is vacuous
        assert want != src.generate(prompt, max_new_tokens=12)
    finally:
        src.close()
        dst.close()


def test_int8_wire_from_bf16_cache_resumes(paged_pair):
    """Forcing the int8 wire encoding from a bf16 cache (bandwidth mode)
    rounds the prefix through kv_quantize — the session must still resume
    and run to completion (token-exactness is only promised for native
    encodings; this asserts the lossy path is functional, not identical)."""
    src, dst = paged_pair
    prompt = src.tokenizer.encode("compressed wire migration")
    n_new = 16
    orig = _throttled(src)
    try:
        req = src.submit(prompt, max_new_tokens=n_new)
        deadline = time.monotonic() + 30
        while len(req.tokens) < 3 and time.monotonic() < deadline:
            time.sleep(0.01)
        doc = src.export_sessions(wire_quant="int8")
    finally:
        src._decode = orig
    payload = doc["sessions"][0]
    assert payload["kv"]["wire"] == "int8"
    handle, _ = _import_and_wait(dst, payload)
    assert len(handle.tokens) <= n_new
    # the migrated tail is preserved verbatim
    assert handle.tokens[:len(payload["tokens"])] == payload["tokens"]


def test_export_deactivates_slot_next_tenant_uncorrupted():
    """Regression (review find): export released the slot host-side but
    left it ACTIVE on device — an interleaved decode chunk kept sampling
    the stale slot and wrote a stale token through the NEXT tenant's
    freshly-installed block table while that tenant was still
    chunk-prefilling, corrupting its prompt KV. The exported slot must be
    deactivated at export, and a request admitted into the freed slot
    while another slot keeps decoding must produce undisturbed tokens."""
    eng = BatchedEngine(MODEL, template="vanilla", max_seq_len=256,
                        slots=2, decode_chunk=4, kv_block_size=16,
                        prefill_chunk=64, prefill_token_budget=64)
    try:
        long_prompt = eng.tokenizer.encode("chunked prefill target " * 40)
        short = eng.tokenizer.encode("short co-tenant")
        want = eng.generate(long_prompt, max_new_tokens=8)

        orig = _throttled(eng, delay=0.05)
        try:
            # A keeps decoding throughout; B is exported; C admits into
            # B's freed slot and chunk-prefills WHILE A's decode interleaves
            req_a = eng.submit(short, max_new_tokens=64, temperature=0.9,
                               seed=1)
            req_b = eng.submit(short, max_new_tokens=64, temperature=0.9,
                               seed=2)
            deadline = time.monotonic() + 30
            while (any(r is None for r in eng._slot_req)
                   or not all(eng._decode_ready)) \
                    and time.monotonic() < deadline:
                time.sleep(0.01)
            slot_b = eng._slot_req.index(req_b)
            doc = eng.export_sessions(slots=[slot_b])
            assert len(doc["sessions"]) == 1
            req_c = eng.submit(long_prompt, max_new_tokens=8)
            assert req_c.done.wait(120) and req_c.error is None, req_c.error
            assert req_c.tokens == want, (req_c.tokens, want)
            assert req_a.done.wait(120) and req_a.error is None
        finally:
            eng._decode = orig
    finally:
        eng.close()


# ------------------------------------------------------------- refusals

def test_import_refusals(paged_pair):
    src, dst = paged_pair
    prompt = src.tokenizer.encode("refusal probe")
    payload = _export_mid_decode(src, prompt, max_new_tokens=12)

    # incompatible model signature → immediate refusal
    bad = json.loads(json.dumps(payload))
    bad["model_sig"]["layers"] = 999
    with pytest.raises(ValueError, match="incompatible model"):
        dst.import_session(bad)

    # unknown adapter name → immediate refusal (dst has no pool)
    bad = json.loads(json.dumps(payload))
    bad["adapter"] = "nobody-registered-this"
    with pytest.raises(ValueError, match="unknown adapter"):
        dst.import_session(bad)

    # full pool: every slot busy → parked import refused at its deadline
    orig = _throttled(dst, delay=0.05)
    try:
        occupants = [dst.submit(prompt, max_new_tokens=48)
                     for _ in range(dst.slots)]
        deadline = time.monotonic() + 30
        while (any(r is None for r in dst._slot_req)
               and time.monotonic() < deadline):
            time.sleep(0.01)
        with pytest.raises(ValueError, match="no free cache slot"):
            dst.import_session(json.loads(json.dumps(payload)),
                               wait_s=0.3)
        assert dst.session_stats["import"].get("refused", 0) >= 1
    finally:
        dst._decode = orig
        for r in occupants:
            r.done.wait(120)


# ------------------------------------------------------ gateway e2e splice

def test_gateway_drain_splices_stream_no_dup_no_missing(paged_pair):
    """The tentpole's consumer: a mid-stream /admin/drain exports the
    session, imports it on the peer, and the client's SSE stream continues
    with NO duplicate and NO missing text — final text equals an
    undisturbed run byte-for-byte. The drained replica is empty the moment
    drain returns (free rolling restart), and the whole handoff is visible
    in the request trace and the handoff counters."""
    from datatunerx_tpu.gateway.replica_pool import (
        InProcessReplica,
        ReplicaPool,
    )
    from datatunerx_tpu.gateway.server import Gateway

    src, dst = paged_pair
    engines = [src, dst]
    pool = ReplicaPool([InProcessReplica(f"replica-{i}", e)
                        for i, e in enumerate(engines)])
    gw = Gateway(pool, model_name=MODEL)
    req = {"messages": [{"role": "user",
                         "content": "tell me a long story about foxes"}],
           "max_tokens": 40, "temperature": 0.0}
    try:
        want = gw.chat(dict(req), trace_id="dtx-undisturbed")

        origs = [(e, _throttled(e)) for e in engines]
        collected: dict = {}

        def consume():
            collected["text"] = "".join(
                gw.chat_stream(dict(req), trace_id="dtx-handoff-e2e"))

        try:
            th = threading.Thread(target=consume)
            th.start()
            # drain the moment the request is actually DECODING (a slot
            # still mid-chunked-prefill is skipped by export, by design)
            deadline = time.monotonic() + 15
            src_i = None
            while src_i is None and time.monotonic() < deadline:
                src_i = next(
                    (i for i, e in enumerate(engines)
                     if any(r is not None and e._decode_ready[s]
                            for s, r in enumerate(e._slot_req))), None)
                time.sleep(0.002)
            assert src_i is not None, "stream never reached a decode slot"
            assert gw.drain(f"replica-{src_i}")
            assert gw.last_handoff["imported"] == 1, gw.last_handoff
            # free rolling restart: the drained replica holds NOTHING the
            # reap would wait on
            assert all(r is None for r in engines[src_i]._slot_req)
            th.join(timeout=120)
            assert not th.is_alive(), "spliced stream never finished"
        finally:
            for e, o in origs:
                e._decode = o
        assert collected["text"] == want, (collected["text"], want)

        stats = gw.handoff_stats()
        assert stats.get("imported") == 1 and stats.get("splice_ok") == 1
        assert not stats.get("cold")
        # the import landed in the TARGET's scheduler trace
        assert any(ev[0] == "import"
                   for ev in engines[1 - src_i].sched_trace)
        # handoff span events merged into the end-to-end trace
        doc = gw.trace("dtx-handoff-e2e")
        names = {ev.get("name") for sp in doc["spans"]
                 for ev in sp.get("events", [])}
        assert {"handoff_pending", "handoff_splice"} <= names, names
        assert {"export", "import"} <= names, names
    finally:
        for r in pool.replicas():
            r.undrain()
        gw.slo.stop()


# ------------------------------------------------------------ HTTP wire

def test_admin_sessions_http_roundtrip(paged_pair):
    """The serving admin surface end-to-end over real sockets: import an
    exported session via POST /admin/sessions/import (SSE receipt +
    continuation), then export a live session back out via
    POST /admin/sessions/export through HTTPReplica."""
    from datatunerx_tpu.gateway.replica_pool import HTTPReplica
    from datatunerx_tpu.serving import server as serving

    src, dst = paged_pair
    old_engine, old_model = serving.STATE.engine, serving.STATE.model_path
    serving.STATE.engine, serving.STATE.model_path = dst, MODEL
    srv = ThreadingHTTPServer(("127.0.0.1", 0), serving.Handler)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    replica = HTTPReplica("r-http", f"http://127.0.0.1:{srv.server_port}")
    try:
        prompt = src.tokenizer.encode("over the wire we go")
        want_text = src.tokenizer.decode(
            src.generate(prompt, max_new_tokens=20),
            skip_special_tokens=True)
        payload = _export_mid_decode(src, prompt, max_new_tokens=20)

        out = replica.import_session(payload)
        assert out is not None
        meta, stream = out
        assert meta["session"] == payload["trace_id"]
        text = str(meta.get("text_so_far") or "") + "".join(stream)
        assert text == want_text, (text, want_text)

        # now export FROM the server side: a fresh live session on dst
        orig = _throttled(dst)
        try:
            req2 = dst.submit(prompt, max_new_tokens=20)
            deadline = time.monotonic() + 30
            while len(req2.tokens) < 3 and time.monotonic() < deadline:
                time.sleep(0.01)
            doc = replica.export_sessions()
        finally:
            dst._decode = orig
        assert doc is not None and len(doc["sessions"]) == 1
        handle, _ = _import_and_wait(src, doc["sessions"][0])
        assert src.tokenizer.decode(
            handle.tokens, skip_special_tokens=True) == want_text
    finally:
        srv.shutdown()
        srv.server_close()
        serving.STATE.engine, serving.STATE.model_path = (old_engine,
                                                          old_model)


def test_serving_metrics_expose_session_series(paged_pair):
    src, _ = paged_pair
    from datatunerx_tpu.serving import server as serving

    old_engine = serving.STATE.engine
    serving.STATE.engine = src
    try:
        text = serving.metrics_text()
    finally:
        serving.STATE.engine = old_engine
    assert 'dtx_serving_session_export_total{outcome="ok"}' in text
    assert 'dtx_serving_session_import_total{outcome="ok"}' in text


# ----------------------------------------- selftest fleet (no model load)

def test_selftest_fleet_drain_handoff():
    """The CI smoke path in miniature: fake engines with the migration
    surface behind a REAL gateway — a drain fired while a stream is in
    flight hands the session over, the client sees every token exactly
    once, and nothing lands on the cold path."""
    from datatunerx_tpu.loadgen.replay import (
        build_selftest_fleet,
        drain_when_busy,
    )

    gw, engines = build_selftest_fleet(adapters=[], delay_s=0.01)
    try:
        req = {"messages": [{"role": "user", "content": "hi"}],
               "max_tokens": 8}
        collected: dict = {}

        def consume():
            collected["text"] = "".join(
                gw.chat_stream(dict(req), trace_id="dtx-fake-1"))

        th = threading.Thread(target=consume)
        th.start()
        # wait until some replica actually streams, then drain it
        deadline = time.monotonic() + 5
        busy = None
        while busy is None and time.monotonic() < deadline:
            busy = next((r for r in gw.pool.replicas() if r.inflight), None)
            time.sleep(0.002)
        assert busy is not None
        out = drain_when_busy(gw, busy.name)
        assert out["drained"]
        th.join(timeout=10)
        assert collected["text"] == "tok " * 8, collected
        stats = gw.handoff_stats()
        assert stats.get("imported") == 1 and not stats.get("cold"), stats
    finally:
        gw.slo.stop()


def test_selftest_fleet_handoff_off_is_cold():
    """With session_handoff off the same drain kills nothing (sessions
    complete in place) — and an export-kill falls back to the legacy
    re-emit path, still serving the client."""
    from datatunerx_tpu.loadgen.replay import build_selftest_fleet

    gw, engines = build_selftest_fleet(adapters=[], delay_s=0.01,
                                       session_handoff=False)
    try:
        req = {"messages": [{"role": "user", "content": "hi"}],
               "max_tokens": 8}
        collected: dict = {}

        def consume():
            collected["text"] = "".join(
                gw.chat_stream(dict(req), trace_id="dtx-fake-2"))

        th = threading.Thread(target=consume)
        th.start()
        deadline = time.monotonic() + 5
        busy = None
        while busy is None and time.monotonic() < deadline:
            busy = next((e for e in engines if e._live), None)
            time.sleep(0.002)
        assert busy is not None
        busy.export_sessions()  # reap-deadline kill: payload discarded
        th.join(timeout=10)
        # legacy failover re-emits with the prefix skipped: complete text
        assert collected["text"] == "tok " * 8, collected
        assert not gw.handoff_stats().get("imported")
    finally:
        gw.slo.stop()


# --------------------------------------------------- satellite: autoscale

def test_autoscale_hint_consumes_slo_burn():
    from datatunerx_tpu.gateway.autoscale import autoscale_hint

    base = dict(replicas=2, available_replicas=2, queue_depth=0,
                queued_tokens=0, shed_count=0, p95_latency_s=0.0)
    # burning faster than budget → scale up, objective NAMED
    hint = autoscale_hint(**base, slo_burn={"name": "gw-avail",
                                            "burn_rate": 2.5})
    assert hint["desiredReplicas"] == 3
    assert "gw-avail" in hint["reason"] and "2.50" in hint["reason"]
    assert hint["sloBurnRate"] == 2.5
    # comfortable burn + idle queue → scale down
    hint = autoscale_hint(**base, slo_burn={"name": "gw-avail",
                                            "burn_rate": 0.1})
    assert hint["desiredReplicas"] == 1 and hint["reason"] == "idle"
    # burn replaces the raw-p95 trigger entirely when present
    hint = autoscale_hint(**{**base, "p95_latency_s": 999.0},
                          slo_burn={"name": "gw-avail", "burn_rate": 0.5})
    assert hint["desiredReplicas"] == 2
    # without slo_burn the p95 branch is byte-identical to before
    hint = autoscale_hint(**{**base, "p95_latency_s": 999.0})
    assert hint["desiredReplicas"] == 3 and "p95" in hint["reason"]
    assert "sloBurnRate" not in hint


def test_gateway_autoscale_burn_rate_wiring():
    """A CONFIGURED gateway (slos passed = --slo_config) scales on burn
    rate; serving 5xx burns the availability budget and the hint names
    the objective."""
    from datatunerx_tpu.gateway.replica_pool import (
        InProcessReplica,
        ReplicaPool,
    )
    from datatunerx_tpu.gateway.server import Gateway
    from datatunerx_tpu.obs.slo import SLO
    from tests.test_gateway import FakeEngine

    slos = [SLO.from_dict({
        "name": "gw-avail", "objective": 0.9, "windows_s": [60],
        "sli": {"kind": "error_ratio",
                "metric": "dtx_gateway_requests_total",
                "bad": {"code": "^5"}}})]
    pool = ReplicaPool([InProcessReplica("r0", FakeEngine("r0"))])
    gw = Gateway(pool, slos=slos)
    try:
        assert gw.slo_configured
        for _ in range(5):
            gw.record_request(500)
        hint = gw.autoscale()
        assert hint["desiredReplicas"] == 2, hint
        assert "gw-avail" in hint["reason"]
        # unconfigured gateway: no SLO keys in the hint at all
        gw2 = Gateway(ReplicaPool([InProcessReplica(
            "r0", FakeEngine("r0"))]))
        try:
            assert not gw2.slo_configured
            assert "sloBurnRate" not in gw2.autoscale()
        finally:
            gw2.slo.stop()
    finally:
        gw.slo.stop()


# ------------------------------------------- satellite: trace-log convert

def test_from_trace_log_converter(tmp_path):
    from datatunerx_tpu.loadgen.workload import (
        from_trace_log,
        read_trace,
        write_trace,
    )

    log = tmp_path / "gw_spans.jsonl"
    spans = [
        {"name": "gateway.stream", "trace_id": "dtx-1",
         "start_ms": 1000.0, "attrs": {"chars": 40, "adapter": "t-a"}},
        {"name": "engine.request", "trace_id": "dtx-1",
         "start_ms": 1001.0, "attrs": {}},  # replica half: skipped
        {"name": "gateway.request", "trace_id": "dtx-2",
         "start_ms": 1500.0, "attrs": {}},
        {"name": "gateway.stream", "trace_id": "dtx-3",
         "start_ms": 1250.0, "attrs": {"chars": 8}},
    ]
    with open(log, "w", encoding="utf-8") as f:
        for sp in spans:
            f.write(json.dumps(sp) + "\n")

    meta, events = from_trace_log(str(log))
    assert meta["source"] == "trace_log" and meta["requests"] == 3
    # sorted by start, offsets relative to the first span
    assert [e["t"] for e in events] == [0.0, 0.25, 0.5]
    assert events[0]["model"] == "t-a"
    assert events[0]["max_tokens"] == 10  # 40 chars / 4 chars-per-token
    assert events[1]["max_tokens"] == 2
    assert events[2]["max_tokens"] == 16  # non-streamed: default
    assert all(e["messages"][0]["content"] for e in events)
    # converted events survive the dtx-load-trace roundtrip
    out = tmp_path / "converted.jsonl"
    write_trace(str(out), events, meta)
    meta2, events2 = read_trace(str(out))
    assert events2 == events and meta2 == meta

    with pytest.raises(ValueError, match="no gateway request spans"):
        empty = tmp_path / "empty.jsonl"
        empty.write_text(json.dumps({"name": "other"}) + "\n")
        from_trace_log(str(empty))
