"""REST API server + dtx CLI: the kubectl/dtx-ctl-shaped user surface."""

import json

import pytest

from datatunerx_tpu.cli import main as dtx_main
from datatunerx_tpu.operator.apiserver import serve_api
from datatunerx_tpu.operator.store import ObjectStore
from datatunerx_tpu.operator.webhooks import AdmittingStore


@pytest.fixture()
def api():
    store = AdmittingStore(ObjectStore())
    srv, port = serve_api(store, port=0)
    yield store, f"http://127.0.0.1:{port}"
    srv.shutdown()


def _req(method, url, payload=None):
    import urllib.error
    import urllib.request

    data = json.dumps(payload).encode() if payload is not None else None
    req = urllib.request.Request(url, data=data, method=method,
                                 headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=10) as resp:
            return resp.status, json.load(resp)
    except urllib.error.HTTPError as e:
        return e.code, json.load(e)


def _dataset(name="ds1"):
    return {
        "kind": "Dataset",
        "metadata": {"name": name},
        "spec": {"datasetMetadata": {"datasetInfo": {
            "subsets": [{"splits": {"train": {"file": "/data/t.csv"}}}],
            "features": [{"name": "instruction", "mapTo": "q"},
                         {"name": "response", "mapTo": "a"}],
        }}},
    }


def test_crud_roundtrip(api):
    store, server = api
    base = f"{server}/apis/extension.datatunerx.io/v1beta1/dataset"

    code, resp = _req("POST", base, _dataset())
    assert code == 201 and resp["metadata"]["resource_version"] == 1

    code, resp = _req("GET", f"{base}/default/ds1")
    assert code == 200 and resp["kind"] == "Dataset"

    # stale update -> 409
    stale = dict(resp)
    stale["metadata"] = dict(resp["metadata"], resource_version=999)
    code, _ = _req("PUT", f"{base}/default/ds1", stale)
    assert code == 409

    code, resp2 = _req("PUT", f"{base}/default/ds1",
                       {**resp, "spec": {**resp["spec"]}})
    assert code == 200

    code, listing = _req("GET", f"{base}/default")
    assert code == 200 and len(listing["items"]) == 1

    code, _ = _req("DELETE", f"{base}/default/ds1")
    assert code == 200
    code, _ = _req("GET", f"{base}/default/ds1")
    assert code == 404


def test_admission_enforced_over_http(api):
    store, server = api
    base = f"{server}/apis/extension.datatunerx.io/v1beta1/datasets"  # plural ok
    code, resp = _req("POST", base, {"kind": "Dataset",
                                     "metadata": {"name": "bad"}, "spec": {}})
    assert code == 422 and "subsets" in resp["error"]

    hp_base = f"{server}/apis/core.datatunerx.io/v1beta1/hyperparameter"
    code, resp = _req("POST", hp_base, {
        "kind": "Hyperparameter", "metadata": {"name": "h"},
        "spec": {"parameters": {"scheduler": "warp"}}})
    assert code == 422


def test_discovery_and_unknown_kind(api):
    _, server = api
    code, resp = _req("GET", f"{server}/apis")
    assert code == 200 and "finetune.datatunerx.io" in resp["groups"]
    code, _ = _req("GET", f"{server}/apis/x/v1/frobnicator")
    assert code == 404


def test_dtx_cli_flow(api, tmp_path, capsys):
    _, server = api
    manifest = tmp_path / "res.json"
    manifest.write_text(json.dumps([
        _dataset("cli-ds"),
        {"kind": "Hyperparameter", "metadata": {"name": "cli-hp"}, "spec": {}},
    ]))

    assert dtx_main(["--server", server, "apply", "-f", str(manifest)]) == 0
    out = capsys.readouterr().out
    assert "Dataset/cli-ds created" in out
    assert "Hyperparameter/cli-hp created" in out

    # re-apply -> configured (update path via rv fetch)
    assert dtx_main(["--server", server, "apply", "-f", str(manifest)]) == 0
    assert "configured" in capsys.readouterr().out

    assert dtx_main(["--server", server, "get", "datasets"]) == 0
    out = capsys.readouterr().out
    assert "cli-ds" in out and "NAME" in out

    assert dtx_main(["--server", server, "get", "hp", "cli-hp", "-o", "json"]) == 0
    parsed = json.loads(capsys.readouterr().out)
    # defaulting webhook ran on create
    assert parsed["spec"]["parameters"]["loRA_R"] == "8"

    assert dtx_main(["--server", server, "delete", "dataset", "cli-ds"]) == 0
    with pytest.raises(SystemExit):
        dtx_main(["--server", server, "get", "dataset", "cli-ds"])
        capsys.readouterr()


def test_delete_unknown_kind_and_put_mismatch(api):
    _, server = api
    code, resp = _req("DELETE", f"{server}/apis/x/v1/frobnicator/default/foo")
    assert code == 404

    base = f"{server}/apis/extension.datatunerx.io/v1beta1/dataset"
    code, created = _req("POST", base, _dataset("pm"))
    assert code == 201
    # body names a different object than the path -> 400
    body = dict(created)
    body["metadata"] = dict(created["metadata"], name="other")
    code, resp = _req("PUT", f"{base}/default/pm", body)
    assert code == 400 and "match the URL path" in resp["error"]

    code, _ = _req("GET", f"{base}/default?labelSelector=oops")
    assert code == 400


def test_bearer_token_auth():
    from datatunerx_tpu.operator.apiserver import serve_api as _serve

    store = AdmittingStore(ObjectStore())
    srv, port = _serve(store, port=0, token="s3cret")
    base = f"http://127.0.0.1:{port}/apis/core.datatunerx.io/v1beta1/llm"
    try:
        code, resp = _req("GET", f"{base}/default")
        assert code == 401
        import urllib.request

        req = urllib.request.Request(
            f"{base}/default", headers={"Authorization": "Bearer s3cret"})
        with urllib.request.urlopen(req, timeout=5) as r:
            assert r.status == 200
    finally:
        srv.shutdown()


def test_logs_endpoint_and_cli(tmp_path, capsys):
    """/logs/<ns>/<name> serves the trainer log tail; dtx logs prints it."""
    from datatunerx_tpu.operator.backends import FakeServingBackend, LocalProcessBackend
    from datatunerx_tpu.operator.manager import build_manager

    store = AdmittingStore(ObjectStore())
    backend = LocalProcessBackend(str(tmp_path / "jobs"))
    mgr = build_manager(store, backend, FakeServingBackend(),
                        storage_path=str(tmp_path), with_scoring=False)
    # the Finetune CR must exist for its logs to be addressable
    from datatunerx_tpu.operator.api import Finetune, ObjectMeta

    store._store.create(Finetune(metadata=ObjectMeta(name="myrun")))  # bypass admission
    jobdir = tmp_path / "jobs" / "myrun"
    jobdir.mkdir(parents=True)
    (jobdir / "log.txt").write_text("line1\nline2\n")

    srv, port = serve_api(store, manager=mgr, port=0)
    try:
        server = f"http://127.0.0.1:{port}"
        code, resp = _req("GET", f"{server}/logs/default/myrun")
        assert code == 200 and "line2" in resp["log"]

        assert dtx_main(["--server", server, "logs", "myrun"]) == 0
        assert "line1" in capsys.readouterr().out

        # unknown job -> 404; path-escape name -> 400
        code, _ = _req("GET", f"{server}/logs/default/nope")
        assert code == 404
        code, _ = _req("GET", f"{server}/logs/default/..%2f..")
        assert code in (400, 404)
    finally:
        srv.shutdown()


def test_metrics_reconcile_counters(tmp_path):
    from datatunerx_tpu.operator.backends import FakeServingBackend, FakeTrainingBackend
    from datatunerx_tpu.operator.manager import build_manager
    from datatunerx_tpu.operator.api import Finetune, ObjectMeta
    import urllib.request

    raw = ObjectStore()
    mgr = build_manager(raw, FakeTrainingBackend(), FakeServingBackend(),
                        storage_path=str(tmp_path), with_scoring=False)

    raw.create(Finetune(metadata=ObjectMeta(name="f1"), spec={"llm": "x"}))
    mgr.run_until_idle()
    srv, port = serve_api(raw, manager=mgr, port=0)
    try:
        with urllib.request.urlopen(f"http://127.0.0.1:{port}/metrics", timeout=5) as r:
            text = r.read().decode()
        assert 'dtx_operator_reconciles_total{kind="Finetune"}' in text
    finally:
        srv.shutdown()


# ------------------------------------------------------------------- web UI

def test_ui_served_and_trainermetrics(tmp_path):
    """The single-file UI + the jsonl metrics-series endpoint behind it
    (reference datatunerx-ui equivalent, README.md:30-32)."""
    import json as _json
    import os
    import urllib.request

    from datatunerx_tpu.operator.api import Finetune, ObjectMeta
    from datatunerx_tpu.operator.backends import LocalProcessBackend
    from datatunerx_tpu.operator.manager import build_manager
    from datatunerx_tpu.operator.backends import FakeServingBackend

    store = ObjectStore()
    backend = LocalProcessBackend(str(tmp_path / "work"))
    mgr = build_manager(store, backend, FakeServingBackend(),
                        storage_path=str(tmp_path / "s"), with_scoring=False)
    store.create(Finetune(metadata=ObjectMeta(name="run-ui"),
                          spec={"llm": "x", "dataset": "y"}))
    # fabricate the jsonl the trainer would write
    watch = tmp_path / "work" / "run-ui" / "result" / "watch"
    os.makedirs(watch)
    with open(watch / "trainer_log.jsonl", "w") as f:
        for i in range(3):
            f.write(_json.dumps({"current_steps": i + 1, "total_steps": 3,
                                 "loss": 2.0 - i * 0.5, "lr": 1e-4}) + "\n")
    srv, port = serve_api(store, manager=mgr, port=0)
    try:
        with urllib.request.urlopen(f"http://127.0.0.1:{port}/", timeout=10) as r:
            html = r.read().decode()
        assert "datatunerx-tpu" in html and "trainermetrics" in html
        assert r.headers.get("Content-Type", "").startswith("text/html")

        code, body = _req("GET", f"http://127.0.0.1:{port}/trainermetrics/default/run-ui")
        assert code == 200
        assert [row["loss"] for row in body["train"]] == [2.0, 1.5, 1.0]

        code, _ = _req("GET", f"http://127.0.0.1:{port}/trainermetrics/default/nope")
        assert code == 404
    finally:
        srv.shutdown()


def test_ui_crud_workflow_templates_pass_admission(api):
    """The web UI's write path (ui.html r5: resource CRUD + job/experiment
    submission) drives the same endpoints with the same prefill templates;
    every template must clear admission or the '+ new' buttons ship broken."""
    store, base = api

    # the UI's TEMPLATES map, verbatim shapes (ui.html)
    templates = {
        "datasets": {
            "apiVersion": "extension.datatunerx.io/v1beta1", "kind": "Dataset",
            "metadata": {"name": "my-dataset", "namespace": "default"},
            "spec": {"datasetMetadata": {"datasetInfo": {
                "subsets": [{"splits": {
                    "train": {"file": "/data/train.csv"},
                    "validate": {"file": "/data/val.csv"}}}],
                "features": [{"name": "instruction", "mapTo": "q"},
                             {"name": "response", "mapTo": "a"}]}}},
        },
        "llms": {
            "apiVersion": "core.datatunerx.io/v1beta1", "kind": "LLM",
            "metadata": {"name": "my-llm", "namespace": "default"},
            "spec": {"path": "/models/llama2-7b"},
        },
        "hyperparameters": {
            "apiVersion": "core.datatunerx.io/v1beta1", "kind": "Hyperparameter",
            "metadata": {"name": "my-hp", "namespace": "default"},
            "spec": {"parameters": {
                "scheduler": "cosine", "optimizer": "adamw", "loRA_R": "8",
                "loRA_Alpha": "32", "loRA_Dropout": "0.1",
                "learningRate": "2e-4", "epochs": "1", "blockSize": "1024",
                "batchSize": "4", "gradAccSteps": "1", "PEFT": "true",
                "FP16": "false"}},
        },
        "scorings": {
            "apiVersion": "extension.datatunerx.io/v1beta1", "kind": "Scoring",
            "metadata": {"name": "my-scoring", "namespace": "default"},
            "spec": {"inferenceService": "http://127.0.0.1:8000/chat/completions",
                     "probes": [{"prompt": "What is a TPU?",
                                 "reference": "An ML accelerator."}]},
        },
    }
    groups = {"datasets": "extension.datatunerx.io",
              "llms": "core.datatunerx.io",
              "hyperparameters": "core.datatunerx.io",
              "scorings": "extension.datatunerx.io"}
    for plural, obj in templates.items():
        code, body = _req(
            "POST", f"{base}/apis/{groups[plural]}/v1beta1/{plural}", obj)
        assert code == 201, (plural, body)

    # the UI's jobSpec() builder, then submit + edit + delete round trip
    job = {
        "apiVersion": "finetune.datatunerx.io/v1beta1", "kind": "FinetuneJob",
        "metadata": {"name": "my-job", "namespace": "default"},
        "spec": {"finetune": {"name": "my-job-finetune", "finetuneSpec": {
            "llm": "my-llm", "dataset": "my-dataset",
            "hyperparameter": {"hyperparameterRef": "my-hp"},
            "image": {"name": "my-job-img", "path": ""}, "node": 1}}},
    }
    code, body = _req(
        "POST", f"{base}/apis/finetune.datatunerx.io/v1beta1/finetunejobs", job)
    assert code == 201, body

    # experiment with the UI's learningRate sweep shape
    exp = {
        "apiVersion": "finetune.datatunerx.io/v1beta1",
        "kind": "FinetuneExperiment",
        "metadata": {"name": "my-exp", "namespace": "default"},
        "spec": {"finetuneJobs": [
            {"name": f"my-exp-v{i}", "spec": {"finetune": {
                "name": f"my-exp-v{i}-finetune", "finetuneSpec": {
                    "llm": "my-llm", "dataset": "my-dataset",
                    "hyperparameter": {"hyperparameterRef": "my-hp",
                                       "overrides": {"learningRate": v}},
                    "image": {"name": f"my-exp-v{i}-img", "path": ""},
                    "node": 1}}}}
            for i, v in enumerate(["1e-4", "2e-4"])]},
    }
    code, body = _req(
        "POST",
        f"{base}/apis/finetune.datatunerx.io/v1beta1/finetuneexperiments", exp)
    assert code == 201, body

    # edit (UI PUT path): bump a hyperparameter value
    code, cur = _req(
        "GET", f"{base}/apis/core.datatunerx.io/v1beta1/hyperparameters/default/my-hp")
    assert code == 200
    cur.pop("status", None)
    cur["spec"]["parameters"]["learningRate"] = "1e-4"
    code, body = _req(
        "PUT", f"{base}/apis/core.datatunerx.io/v1beta1/hyperparameters/default/my-hp", cur)
    assert code == 200, body

    # delete (UI DELETE path)
    for path in ("finetune.datatunerx.io/v1beta1/finetuneexperiments/default/my-exp",
                 "finetune.datatunerx.io/v1beta1/finetunejobs/default/my-job"):
        code, _ = _req("DELETE", f"{base}/apis/{path}")
        assert code == 200

    # the served page carries the CRUD surface markers
    import urllib.request

    with urllib.request.urlopen(base + "/", timeout=10) as r:
        html = r.read().decode()
    for marker in ("newResource", "newJob", "newExperiment", "TEMPLATES",
                   "m-json"):
        assert marker in html, marker
