"""Interpret-mode unit tests for the Pallas in-place paged-decode kernel
(ops/pallas_paged_attention.py): the kernel must reproduce the XLA gather
oracle — gathered linear view + causal bias + xla_attention — through every
cache shape it claims: block-table walk, ragged per-slot lens, -1 sentinel
entries, GQA head mapping, int8 dequant-by-scale, single-block and
full-table slots. Engine-level token parity lives in test_paged_engine.py;
these tests pin the kernel primitive itself."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from datatunerx_tpu.ops.attention import (
    kv_dequantize,
    kv_quantize,
    make_causal_bias,
    xla_attention,
)
from datatunerx_tpu.ops.paged_attention import POS_SENTINEL
from datatunerx_tpu.ops.pallas_paged_attention import paged_decode_attention

BS = 8  # block size (tokens per block)


def _make_pool(key, B, NB, KV, d, lens, tables, dtype=jnp.float32,
               quant=False):
    """A block pool whose gathered view holds ``lens[b]`` real tokens per
    slot: values written through the tables, positions 0..len-1, sentinel
    elsewhere (exactly what the engine's scrub + writes produce)."""
    kk, kv_, kq = jax.random.split(key, 3)
    k_pool = jnp.zeros((NB, BS, KV, d), jnp.float32)
    v_pool = jnp.zeros((NB, BS, KV, d), jnp.float32)
    pos = jnp.full((NB, BS), POS_SENTINEL, jnp.int32)
    k_rows, v_rows = [], []
    for b in range(B):
        W = tables.shape[1] * BS
        kr = jax.random.normal(jax.random.fold_in(kk, b), (W, KV, d))
        vr = jax.random.normal(jax.random.fold_in(kv_, b), (W, KV, d))
        k_rows.append(kr)
        v_rows.append(vr)
        for i in range(int(lens[b])):
            blk, off = tables[b, i // BS], i % BS
            assert blk >= 0, "test table too short for its len"
            k_pool = k_pool.at[blk, off].set(kr[i])
            v_pool = v_pool.at[blk, off].set(vr[i])
            pos = pos.at[blk, off].set(i)
    if not quant:
        return (k_pool.astype(dtype), v_pool.astype(dtype), None, None, pos,
                k_rows, v_rows)
    kq_pool, ks_pool = kv_quantize(k_pool)
    vq_pool, vs_pool = kv_quantize(v_pool)
    return kq_pool, vq_pool, ks_pool, vs_pool, pos, k_rows, v_rows


def _oracle(q, k_pool, v_pool, ks, vs, tables, pos, q_positions, dtype):
    """The gather path, element for element: clamp the table, gather the
    linear view, sentinel-mask the positions, bias, xla_attention."""
    B = q.shape[0]
    tbl = jnp.where(tables >= 0, tables, 0)
    k_all = k_pool[tbl].reshape(B, -1, k_pool.shape[-2], k_pool.shape[-1])
    v_all = v_pool[tbl].reshape(B, -1, v_pool.shape[-2], v_pool.shape[-1])
    if ks is not None:
        k_all = kv_dequantize(k_all, ks[tbl].reshape(B, -1, ks.shape[-1]),
                              dtype)
        v_all = kv_dequantize(v_all, vs[tbl].reshape(B, -1, vs.shape[-1]),
                              dtype)
    else:
        k_all, v_all = k_all.astype(dtype), v_all.astype(dtype)
    kv_pos = pos[tbl]  # [B, nbps, BS]
    kv_pos = jnp.where((tables >= 0)[:, :, None], kv_pos, POS_SENTINEL)
    kv_pos = kv_pos.reshape(B, -1)
    bias = make_causal_bias(q_positions[:, None], kv_pos)
    return xla_attention(q[:, None].astype(dtype), k_all, v_all, bias)[:, 0]


def _run(B=2, NB=8, nbps=3, KV=2, G=2, d=16, lens=(17, 5), dtype=jnp.float32,
         quant=False, tables=None, seed=0):
    H = KV * G
    key = jax.random.PRNGKey(seed)
    if tables is None:
        rows = []
        nxt = 0
        for b in range(B):
            need = -(-int(lens[b]) // BS)
            row = list(range(nxt, nxt + need)) + [-1] * (nbps - need)
            nxt += need
            rows.append(row)
        tables = jnp.asarray(rows, jnp.int32)
    kp, vp, ks, vs, pos, _, _ = _make_pool(key, B, NB, KV, d, lens, tables,
                                           dtype=dtype, quant=quant)
    q = jax.random.normal(jax.random.fold_in(key, 99),
                          (B, H, d)).astype(dtype)
    q_positions = jnp.asarray([int(x) - 1 for x in lens], jnp.int32)
    got = paged_decode_attention(q, kp, vp, ks, vs, tables, pos, q_positions)
    want = _oracle(q, kp, vp, ks, vs, tables, pos, q_positions, dtype)
    assert got.dtype == q.dtype
    return np.asarray(got, np.float32), np.asarray(want, np.float32)


def test_block_table_walk_matches_gather_f32():
    got, want = _run()
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_ragged_lens_and_sentinel_entries():
    """Slots at different depths, tables padded with -1: unallocated entries
    contribute nothing, mid-block raggedness masks by pos sentinel."""
    got, want = _run(B=3, NB=10, nbps=4, lens=(25, 9, 1))
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_single_block_and_full_table_slots():
    # slot 0: exactly one block; slot 1: every table entry live
    got, want = _run(B=2, NB=8, nbps=3, lens=(BS, 3 * BS))
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_gqa_head_mapping():
    """H = KV * G with G > 1: each query-head group must read ITS kv head —
    a mapping bug would still produce plausible numbers, so compare against
    the oracle with distinctly-keyed heads."""
    got, want = _run(KV=4, G=3, d=8, lens=(11, 20), nbps=3, NB=8)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_no_gqa_single_group():
    got, want = _run(KV=2, G=1, lens=(13, 6))
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_int8_dequant_inside_kernel():
    got, want = _run(quant=True, dtype=jnp.float32, lens=(19, 7))
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_bf16_pools_match_oracle_bitwise():
    """bf16 is the serving dtype: the kernel's phase-1 probs quantization
    replicates xla_attention's probs.astype(bf16), so outputs round to the
    SAME bf16 values (the engine token-parity guarantee)."""
    got, want = _run(dtype=jnp.bfloat16, lens=(17, 5))
    np.testing.assert_array_equal(got, want)


def test_aliased_tables_shared_prefix_blocks():
    """COW prefix sharing (kv_overcommit): several slots' tables map the
    SAME physical blocks for their shared prefix, diverging only in their
    owned tails. Kernel reads walk each slot's own table, so aliasing must
    be invisible — pinned against the oracle over genuinely shared blocks
    (the shared region's positions 0..15 coincide across slots, exactly
    what a mapped prefix-cache entry produces)."""
    tables = jnp.asarray([[0, 1, 2, -1],   # donor: prefix + own tail
                          [0, 1, 3, -1],   # sharer at a different depth
                          [0, 1, 4, 5]],   # deeper sharer, two own blocks
                         jnp.int32)
    got, want = _run(B=3, NB=8, nbps=4, lens=(21, 17, 30), tables=tables)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)
    # bf16 serving dtype: aliased reads must stay BITWISE oracle-equal
    got, want = _run(B=3, NB=8, nbps=4, lens=(21, 17, 30), tables=tables,
                     dtype=jnp.bfloat16)
    np.testing.assert_array_equal(got, want)


def test_bf16_int8_pools_match_oracle_bitwise():
    got, want = _run(dtype=jnp.bfloat16, quant=True, lens=(12, 23))
    np.testing.assert_array_equal(got, want)


def test_bf16_nonpow2_head_dim_matches_oracle_bitwise():
    """d=96: 1/sqrt(d) is where python-double vs f32 scale arithmetic
    diverges by an ulp — the kernel must use the oracle's f32 formula."""
    got, want = _run(d=96, dtype=jnp.bfloat16, lens=(17, 5))
    np.testing.assert_array_equal(got, want)


def test_empty_slot_yields_finite_output():
    """A slot with no valid block (all -1): the kernel returns zeros, never
    NaN — the engine's emit mask discards the row either way, but NaNs must
    not leak into the batch."""
    tables = jnp.asarray([[0, 1, -1], [-1, -1, -1]], jnp.int32)
    got, _ = _run(B=2, NB=4, nbps=3, lens=(10, 0), tables=tables)
    assert np.isfinite(got).all()
    np.testing.assert_array_equal(got[1], 0.0)


def test_decode_step_wrapper_shape():
    from datatunerx_tpu.ops.pallas_paged_attention import (
        paged_attention_decode_step,
    )

    B, KV, G, d, nbps, NB = 2, 2, 2, 8, 2, 4
    H = KV * G
    key, kq, kq2 = jax.random.split(jax.random.PRNGKey(3), 3)
    tables = jnp.asarray([[0, 1], [2, -1]], jnp.int32)
    kp, vp, ks, vs, pos, _, _ = _make_pool(key, B, NB, KV, d, (9, 4), tables)
    q = jax.random.normal(kq, (B, 1, H, d))
    cache = {"block_tables": tables}
    out = paged_attention_decode_step(
        q, kp, vp, None, None, cache, pos, jnp.asarray([[8], [3]], jnp.int32))
    assert out.shape == (B, 1, H, d)
    with pytest.raises(AssertionError):
        paged_attention_decode_step(
            jax.random.normal(kq2, (B, 2, H, d)), kp, vp, None, None, cache,
            pos, jnp.asarray([[8, 9], [3, 4]], jnp.int32))


# --------------------------------------- multi-token (bucketed q_len) kernel

from datatunerx_tpu.ops.attention import attention_allow  # noqa: E402
from datatunerx_tpu.ops.pallas_paged_attention import (  # noqa: E402
    paged_multitoken_attention,
)


def _gathered_view(kp, vp, ks, vs, tables, pos, dtype):
    """The gather oracle's linear view: clamped-table gather, dequant,
    sentinel-masked positions — what the model biases over."""
    B = tables.shape[0]
    tbl = jnp.where(tables >= 0, tables, 0)
    k_all = kp[tbl].reshape(B, -1, kp.shape[-2], kp.shape[-1])
    v_all = vp[tbl].reshape(B, -1, vp.shape[-2], vp.shape[-1])
    if ks is not None:
        k_all = kv_dequantize(k_all, ks[tbl].reshape(B, -1, ks.shape[-1]),
                              dtype)
        v_all = kv_dequantize(v_all, vs[tbl].reshape(B, -1, vs.shape[-1]),
                              dtype)
    else:
        k_all, v_all = k_all.astype(dtype), v_all.astype(dtype)
    kv_pos = pos[tbl]
    kv_pos = jnp.where((tables >= 0)[:, :, None], kv_pos, POS_SENTINEL)
    return k_all, v_all, kv_pos.reshape(B, -1)


def _run_mt(B=2, NB=8, nbps=3, KV=2, G=2, d=16, lens=(17, 5), T=3,
            dtype=jnp.float32, quant=False, tables=None, seed=0,
            window=None):
    """Multi-token kernel vs the gather oracle. Queries sit on the last T
    written lanes per slot (the post-write verify/chunk shape), so every
    row has a DIFFERENT causal offset on a ragged batch. ``window=WN``
    additionally carves a random branch mask over the last WN lanes — the
    tree-verify operand (requires lens[b] > WN so no row is fully
    masked)."""
    H = KV * G
    key = jax.random.PRNGKey(seed)
    if tables is None:
        rows = []
        nxt = 0
        for b in range(B):
            need = max(1, -(-int(lens[b]) // BS))
            row = list(range(nxt, nxt + need)) + [-1] * (nbps - need)
            nxt += need
            rows.append(row)
        tables = jnp.asarray(rows, jnp.int32)
    kp, vp, ks, vs, pos, _, _ = _make_pool(key, B, NB, KV, d, lens, tables,
                                           dtype=dtype, quant=quant)
    q = jax.random.normal(jax.random.fold_in(key, 7),
                          (B, T, H, d)).astype(dtype)
    q_positions = jnp.asarray(
        [[max(int(lens[b]) - T + t, t) for t in range(T)]
         for b in range(B)], jnp.int32)
    k_all, v_all, kv_pos = _gathered_view(kp, vp, ks, vs, tables, pos, dtype)
    window_mask = window_start = None
    if window is not None:
        assert all(int(x) > window for x in lens)
        window_mask = jax.random.bernoulli(
            jax.random.fold_in(key, 13), 0.6, (B, T, window))
        window_start = jnp.asarray(
            [int(x) - window for x in lens], jnp.int32)
    allow = attention_allow(q_positions, kv_pos, window_mask=window_mask,
                            window_start=window_start)
    got = paged_multitoken_attention(q, kp, vp, ks, vs, tables, allow)
    bias = make_causal_bias(q_positions, kv_pos, window_mask=window_mask,
                            window_start=window_start)
    want = xla_attention(q.astype(dtype), k_all, v_all, bias)
    assert got.dtype == q.dtype
    return np.asarray(got, np.float32), np.asarray(want, np.float32)


def test_multitoken_matches_gather_f32():
    got, want = _run_mt()
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_multitoken_q_len_one_degenerate():
    """T=1 through the multi-token path must equal the oracle too — the
    bucketed kernel's smallest bucket, not a special case."""
    got, want = _run_mt(T=1, lens=(17, 5))
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_multitoken_ragged_causal_offsets():
    """Ragged depths: each row's T queries carry row-specific absolute
    positions, so the per-row causal frontier differs across the batch —
    the chunked-prefill shape."""
    got, want = _run_mt(B=3, NB=10, nbps=4, lens=(25, 9, 4), T=4)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_multitoken_gqa_int8_dequant_inside_kernel():
    """GQA head mapping and int8 dequant together: 3 query heads share
    each of 4 kv heads, and the kernel dequantizes the int8 pools by
    their scales before the same two-pass arithmetic."""
    got, want = _run_mt(KV=4, G=3, d=8, lens=(11, 20), T=3, quant=True)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


@pytest.mark.slow
def test_multitoken_bf16_matches_oracle_bitwise():
    """The serving dtype: same per-block normalize-then-cast rounding as
    the decode kernel, so bf16 outputs are BITWISE oracle-equal — the
    engine token-parity guarantee for chunked prefill + verify columns."""
    got, want = _run_mt(dtype=jnp.bfloat16, lens=(17, 6), T=3)
    np.testing.assert_array_equal(got, want)


def test_multitoken_bf16_int8_matches_oracle_bitwise():
    got, want = _run_mt(dtype=jnp.bfloat16, quant=True, lens=(12, 23), T=5)
    np.testing.assert_array_equal(got, want)


def test_multitoken_tree_branch_window_mask():
    """The tree-verify operand: a random per-(row, column) branch mask over
    the step's own window of lanes. Inside the window the mask AND causal
    both gate (siblings share rope positions); outside, plain causal — the
    kernel must agree with the oracle biased by the SAME allow tensor."""
    got, want = _run_mt(lens=(17, 9), T=3, window=4)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)
    got, want = _run_mt(lens=(17, 9), T=3, window=4, quant=True,
                        dtype=jnp.bfloat16)
    np.testing.assert_array_equal(got, want)


def test_lower_triangular_window_mask_is_chain():
    """A lower-triangular window mask over the queries' own lanes adds
    nothing beyond causality — chain verify semantics reproduce exactly,
    which is why the chain path never builds a mask."""
    B, T, lens = 2, 3, (17, 9)
    key = jax.random.PRNGKey(5)
    tables = jnp.asarray([[0, 1, 2], [3, 4, -1]], jnp.int32)
    kp, vp, ks, vs, pos, _, _ = _make_pool(key, B, 8, 2, 16, lens, tables)
    q_positions = jnp.asarray(
        [[int(x) - T + t for t in range(T)] for x in lens], jnp.int32)
    _, _, kv_pos = _gathered_view(kp, vp, ks, vs, tables, pos, jnp.float32)
    tri = jnp.broadcast_to(
        jnp.tril(jnp.ones((T, T), bool))[None], (B, T, T))
    start = jnp.asarray([int(x) - T for x in lens], jnp.int32)
    with_mask = attention_allow(q_positions, kv_pos, window_mask=tri,
                                window_start=start)
    without = attention_allow(q_positions, kv_pos)
    np.testing.assert_array_equal(np.asarray(with_mask),
                                  np.asarray(without))


def test_multitoken_empty_slot_yields_finite_output():
    tables = jnp.asarray([[0, 1, -1], [-1, -1, -1]], jnp.int32)
    got, _ = _run_mt(B=2, NB=4, nbps=3, lens=(10, 0), T=3, tables=tables)
    assert np.isfinite(got).all()
    np.testing.assert_array_equal(got[1], 0.0)


def test_multitoken_step_wrapper_shape_and_allow_contract():
    from datatunerx_tpu.ops.pallas_paged_attention import (
        paged_attention_multitoken_step,
    )

    B, KV, G, d, nbps, NB, T = 2, 2, 2, 8, 2, 4, 3
    H = KV * G
    key = jax.random.PRNGKey(3)
    tables = jnp.asarray([[0, 1], [2, -1]], jnp.int32)
    kp, vp, ks, vs, pos, _, _ = _make_pool(key, B, NB, KV, d, (9, 4), tables)
    q = jax.random.normal(key, (B, T, H, d))
    allow = jnp.ones((B, T, nbps * BS), bool)
    cache = {"block_tables": tables}
    out = paged_attention_multitoken_step(q, kp, vp, None, None, cache,
                                          allow)
    assert out.shape == (B, T, H, d)
    with pytest.raises(AssertionError, match="allow"):
        paged_attention_multitoken_step(
            q, kp, vp, None, None, cache,
            jnp.ones((B, T + 1, nbps * BS), bool))
