"""PPO stage (reference reserves --stage ppo + knobs with no runtime,
cmd/tuning/parser.py:117-120,170-185): GAE math, rollout/update log-prob
alignment (cache decode vs full-sequence forward), reward improvement under
a fixed reward model, and the CLI driver path rm → ppo."""

import json

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from datatunerx_tpu.data.loader import PromptBatchIterator
from datatunerx_tpu.data.preprocess import preprocess_prompt_records
from datatunerx_tpu.data.templates import get_template
from datatunerx_tpu.models import get_config, init_params
from datatunerx_tpu.models.lora import init_lora_params, lora_scaling
from datatunerx_tpu.training import TrainConfig
from datatunerx_tpu.training.ppo import PPOConfig, PPOTrainer, compute_gae
from tests.fake_tokenizer import FakeTokenizer


@pytest.fixture(scope="module")
def tok():
    return FakeTokenizer()


def _reward_lora(cfg, seed=7, rank=4):
    """A frozen 'rm checkpoint': zero-delta adapters (B=0 at init) + a fixed
    random value head — a deterministic, nontrivial reward function."""
    lora = init_lora_params(cfg, jax.random.PRNGKey(seed), rank=rank)
    lora["v_head"] = jax.random.normal(
        jax.random.PRNGKey(seed + 1), (cfg.hidden_size,), jnp.float32)
    return lora


def _prompt_batch(tok, n=4, block=32):
    tpl = get_template("vanilla", tok)
    recs = [{"instruction": f"question {i}"} for i in range(n)]
    ex = preprocess_prompt_records(recs, tpl, tok, cutoff_len=block)
    assert len(ex) == n
    it = PromptBatchIterator(ex, global_batch=n, block_size=block,
                             pad_id=0, shuffle=False)
    return next(iter(it))


def _make_trainer(cfg, ppo_cfg, lr=1e-3, seed=0):
    tcfg = TrainConfig(
        stage="ppo", finetuning_type="lora", lora_rank=4, lora_dropout=0.0,
        learning_rate=lr, scheduler="constant", total_steps=100,
        compute_dtype=None,
    )
    tr = PPOTrainer(cfg, tcfg, ppo_cfg,
                    reward_lora=_reward_lora(cfg),
                    reward_scaling=lora_scaling(32.0, 4),
                    eos_id=2, pad_id=0)
    state = tr.init_state(init_params(cfg, jax.random.PRNGKey(seed)),
                          jax.random.PRNGKey(seed + 1))
    return tr, state


def test_gae_hand_computed():
    """Two-token episode, γ=1, λ=0.5, against hand math."""
    rewards = np.array([[1.0, 2.0, 99.0]])  # third slot is post-episode noise
    values = np.array([[0.5, 1.0, 99.0]])
    mask = np.array([[1.0, 1.0, 0.0]])
    adv, rets = compute_gae(jnp.asarray(rewards), jnp.asarray(values),
                            jnp.asarray(mask), gamma=1.0, lam=0.5)
    # t=1 (last): delta = 2 - 1 = 1; adv = 1
    # t=0: delta = 1 + 1.0 - 0.5 = 1.5; adv = 1.5 + 0.5*1 = 2.0
    np.testing.assert_allclose(np.asarray(adv[0]), [2.0, 1.0, 0.0], atol=1e-6)
    np.testing.assert_allclose(np.asarray(rets[0]), [2.5, 2.0, 0.0], atol=1e-6)


def test_stage_validation():
    with pytest.raises(ValueError, match="lora"):
        TrainConfig(stage="ppo", finetuning_type="full")
    from datatunerx_tpu.tuning.parser import parse_train_args

    with pytest.raises(ValueError, match="reward_model"):
        parse_train_args([
            "--model_name_or_path", "preset:debug", "--stage", "ppo",
            "--train_path", "x.jsonl",
        ])


def test_reward_lora_requires_v_head():
    cfg = get_config("debug")
    with pytest.raises(ValueError, match="v_head"):
        PPOTrainer(
            cfg,
            TrainConfig(stage="ppo", finetuning_type="lora",
                        compute_dtype=None),
            PPOConfig(gen_len=4),
            reward_lora=init_lora_params(cfg, jax.random.PRNGKey(0)),
            reward_scaling=1.0, eos_id=2,
        )


def test_rollout_masks_and_logp_alignment(tok):
    """The rollout's cached decode and the update's full-sequence forward must
    agree: with lr=0 the first update pass sees ratio == 1 everywhere
    (approx_kl ≈ 0, clipfrac == 0). This pins the off-by-one between
    logits[t-1] → token[t], the left-pad positions, and the KV-cache path."""
    cfg = get_config("debug")
    tr, state = _make_trainer(cfg, PPOConfig(gen_len=8, temperature=1.0,
                                             ppo_epochs=1), lr=0.0)
    batch = _prompt_batch(tok)
    ro, stats = tr._rollout(state, tr._put_batch(batch), jnp.float32(0.1))
    m = np.asarray(ro["resp_mask"])
    # response mask is a contiguous prefix of the gen window, ≥ 1 token
    assert (m.sum(1) >= 1).all()
    for row in m:
        on = np.flatnonzero(row)
        assert on.size == on.max() + 1  # prefix: indices 0..k-1
    assert np.isfinite(np.asarray(ro["old_logp"])[m.astype(bool)]).all()
    assert np.isfinite(float(stats["reward_score"]))

    state2, metrics = tr._update(state, ro)
    assert abs(float(metrics["approx_kl"])) < 1e-4
    assert float(metrics["clipfrac"]) == 0.0


def test_rollout_stops_at_eos(tok):
    """Force instant EOS by making temperature greedy toward eos: instead,
    check the mechanical contract — tokens after a sampled eos are pad and
    masked out."""
    cfg = get_config("debug")
    tr, state = _make_trainer(cfg, PPOConfig(gen_len=12, temperature=1.0,
                                             ppo_epochs=1))
    batch = _prompt_batch(tok)
    ro, _ = tr._rollout(state, tr._put_batch(batch), jnp.float32(0.1))
    toks = np.asarray(ro["seq"])[:, -12:]
    m = np.asarray(ro["resp_mask"])
    for r in range(toks.shape[0]):
        n = int(m[r].sum())
        if n < 12:  # episode ended: eos emitted at the last response slot
            assert toks[r, n - 1] == tr.eos_id
            assert (toks[r, n:] == tr.pad_id).all()
            assert (m[r, n:] == 0).all()


def test_ppo_improves_reward(tok):
    """PPO must climb ANY fixed reward: under a frozen random v_head reward,
    mean scores late in training exceed early ones."""
    cfg = get_config("debug")
    tr, state = _make_trainer(
        cfg,
        PPOConfig(gen_len=8, temperature=1.0, kl_coef=0.02, ppo_epochs=2,
                  vf_coef=0.1, gae_lambda=0.95, whiten_advantages=True),
        lr=8e-3,
    )
    batch = _prompt_batch(tok)
    scores = []
    for _ in range(18):
        state, metrics = tr.step(state, batch)
        scores.append(float(metrics["reward_score"]))
    early = np.mean(scores[:3])
    late = np.mean(scores[-3:])
    assert late > early, (early, late, scores)


def test_adaptive_kl_controller(tok):
    cfg = get_config("debug")
    tr, state = _make_trainer(
        cfg, PPOConfig(gen_len=4, ppo_epochs=1, kl_coef=0.5,
                       ppo_target=1e-6, kl_horizon=1.0))
    before = tr.kl_coef
    state, m = tr.step(state, _prompt_batch(tok))
    # measured |KL| ≥ 0 is far above the microscopic target → coef must rise
    # (clipped to +20% per step) whenever any KL was measured
    if float(m["kl"]) > 1e-6:
        assert tr.kl_coef > before
    assert m["kl_coef"] == before  # metric reports the coef the step USED


def test_ppo_cli_e2e(tok, tmp_path):
    """Full driver: --stage rm produces the reward model, --stage ppo consumes
    it via --reward_model. Exercises manifest round-trip + restore template."""
    from datatunerx_tpu.tuning.parser import parse_train_args
    from datatunerx_tpu.tuning.train import run

    prefs = tmp_path / "prefs.jsonl"
    with open(prefs, "w") as f:
        for i in range(40):
            f.write(json.dumps({
                "instruction": f"q {i}", "chosen": f"fine answer {i}",
                "rejected": f"bad {i}",
            }) + "\n")
    storage = str(tmp_path / "storage")
    rm_args = parse_train_args([
        "--model_name_or_path", "preset:debug", "--stage", "rm",
        "--train_path", str(prefs), "--output_dir", str(tmp_path / "rm_out"),
        "--storage_path", storage, "--uid", "rm-run",
        "--template", "vanilla", "--block_size", "64",
        "--per_device_train_batch_size", "1", "--max_steps", "2",
        "--bf16", "false", "--lora_dropout", "0.0", "--logging_steps", "1",
    ])
    rm_res = run(rm_args)
    assert rm_res["manifest"]

    prompts = tmp_path / "prompts.jsonl"
    with open(prompts, "w") as f:
        for i in range(40):
            f.write(json.dumps({"instruction": f"question {i}"}) + "\n")
    ppo_args = parse_train_args([
        "--model_name_or_path", "preset:debug", "--stage", "ppo",
        "--reward_model", f"{storage}/rm-run",
        "--train_path", str(prompts), "--output_dir", str(tmp_path / "ppo_out"),
        "--storage_path", storage, "--uid", "ppo-run",
        "--template", "vanilla", "--block_size", "32",
        "--per_device_train_batch_size", "1", "--max_steps", "2",
        "--ppo_gen_len", "4", "--ppo_epochs", "1",
        "--bf16", "false", "--lora_dropout", "0.0", "--logging_steps", "1",
    ])
    res = run(ppo_args)
    assert res["steps"] == 2
    assert res["manifest"]
    manifest = json.loads(open(res["manifest"]).read())
    assert manifest["stage"] == "ppo"
    assert manifest["reward_model"].endswith("rm-run")
    # the saved policy checkpoint restores (v_head rides in the lora tree)
    from datatunerx_tpu.training.checkpoint import CheckpointManager

    mngr = CheckpointManager(res["checkpoint_dir"])
    assert mngr.latest_step() == 2
    mngr.close()
    # adaptive-KL controller state rides beside the checkpoints so --resume
    # doesn't reset kl_coef to --init_kl_coef
    import os as _os

    from datatunerx_tpu.training.ppo import load_controller_state

    cs = load_controller_state(res["checkpoint_dir"])
    assert cs is not None and cs["step"] == 2 and cs["kl_coef"] > 0
    assert _os.path.exists(_os.path.join(res["checkpoint_dir"],
                                         "ppo_controller.json"))
