"""Regenerate template goldens by RUNNING the reference template module
(read-only import from /root/reference) against the deterministic fake
tokenizer. Output: tests/goldens/templates.json.

Usage: python tests/goldens/gen_goldens.py
"""

import importlib.util
import json
import os
import sys

HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.dirname(HERE))  # for fake_tokenizer

from fake_tokenizer import FakeTokenizer  # noqa: E402

REF = "/root/reference/cmd/tuning/template.py"

CASES = [
    {
        "id": "single",
        "query": "What is a TPU?",
        "response": "A tensor processing unit.",
        "history": None,
        "system": None,
    },
    {
        "id": "multiturn_system",
        "query": "And v5e?",
        "response": "A cost-efficient TPU generation.",
        "history": [["Hi", "Hello!"], ["Name a chip", "TPU v4"]],
        "system": "Be terse.",
    },
]


def main():
    spec = importlib.util.spec_from_file_location("ref_template", REF)
    ref = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(ref)

    out = {}
    for name, template in sorted(ref.templates.items()):
        out[name] = {}
        for case in CASES:
            tok = FakeTokenizer()
            ref.get_template_and_fix_tokenizer(name, tok)
            pairs = template.encode_multiturn(
                tok,
                case["query"],
                case["response"],
                [tuple(h) for h in case["history"]] if case["history"] else None,
                case["system"],
            )
            prompt_ids, answer_ids = template.encode_oneturn(
                tok,
                case["query"],
                case["response"],
                [tuple(h) for h in case["history"]] if case["history"] else None,
                case["system"],
            )
            out[name][case["id"]] = {
                "pairs": [[list(a), list(b)] for a, b in pairs],
                "oneturn": [list(prompt_ids), list(answer_ids)],
                "specials": tok.special_tokens_map,
            }

    path = os.path.join(HERE, "templates.json")
    with open(path, "w") as f:
        json.dump({"cases": CASES, "templates": out}, f, indent=1, sort_keys=True)
    print(f"wrote {path}: {len(out)} templates x {len(CASES)} cases")


if __name__ == "__main__":
    main()
