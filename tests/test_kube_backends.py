"""JobSet/Deployment status feedback: a manifest-mode Finetune transitions
Pending→Running→Succeeded from cluster-reported conditions (VERDICT round-1
item 3 'done' criterion; replaces the hardcoded "Pending" of round 1)."""

import json
import os

import pytest

from datatunerx_tpu.operator.api import Finetune, ObjectMeta
from datatunerx_tpu.operator.backends import (
    ManifestBackend,
    deployment_state,
    jobset_state,
)
from datatunerx_tpu.operator.kubebackends import (
    JOBSET_GROUP,
    JOBSET_PLURAL,
    JOBSET_VERSION,
    KubeServingBackend,
    KubeTrainingBackend,
)
from datatunerx_tpu.operator.kubeclient import KubeClient
from datatunerx_tpu.operator.kubestore import KubeObjectStore
from datatunerx_tpu.operator.manager import build_manager
from datatunerx_tpu.training.checkpoint import write_manifest
from tests.fake_apiserver import FakeKubeApiServer
from tests.test_operator import _seed_deps


@pytest.fixture()
def cluster(tmp_path):
    srv = FakeKubeApiServer().start()
    client = KubeClient(base_url=srv.url)
    yield srv, client, str(tmp_path)
    srv.stop()


def _set_jobset_status(client, name, status, ns="default"):
    js = client.get(JOBSET_GROUP, JOBSET_VERSION, JOBSET_PLURAL, ns, name)
    js["status"] = status
    client.replace(JOBSET_GROUP, JOBSET_VERSION, JOBSET_PLURAL, ns, name, js,
                   subresource="status")


# ------------------------------------------------------------ state maps

def test_jobset_state_mapping():
    assert jobset_state({}) == "Pending"
    assert jobset_state({"replicatedJobsStatus": [{"active": 2}]}) == "Running"
    assert jobset_state({"replicatedJobsStatus": [{"ready": 1}]}) == "Running"
    assert jobset_state(
        {"conditions": [{"type": "Completed", "status": "True"}]}) == "Succeeded"
    assert jobset_state(
        {"conditions": [{"type": "Failed", "status": "True"}]}) == "Failed"
    assert jobset_state(
        {"conditions": [{"type": "Completed", "status": "False"}],
         "replicatedJobsStatus": [{"active": 1}]}) == "Running"


def test_deployment_state_mapping():
    assert deployment_state({}) == "PENDING"
    assert deployment_state({"availableReplicas": 1}) == "HEALTHY"
    assert deployment_state(
        {"conditions": [{"type": "ReplicaFailure", "status": "True"}]}) == "FAILED"


# ----------------------------------------------------- kube training loop

def test_kube_training_backend_submit_and_status(cluster):
    srv, client, workdir = cluster
    backend = KubeTrainingBackend(client, out_dir=os.path.join(workdir, "m"))
    assert backend.status("t1") == "NotFound"
    backend.submit("t1", {"args": ["--model_name_or_path", "m"], "num_hosts": 2})
    backend.submit("t1", {"args": ["--model_name_or_path", "m"]})  # idempotent
    assert backend.status("t1") == "Pending"

    js = client.get(JOBSET_GROUP, JOBSET_VERSION, JOBSET_PLURAL, "default", "t1")
    # the rendered JobSet carried the TPU topology + distributed env contract
    pod = js["spec"]["replicatedJobs"][0]["template"]["spec"]["template"]["spec"]
    assert pod["nodeSelector"]["cloud.google.com/gke-tpu-accelerator"]
    env_names = [e["name"] for e in pod["containers"][0]["env"]]
    assert "DTX_COORDINATOR_ADDRESS" in env_names

    _set_jobset_status(client, "t1", {"replicatedJobsStatus": [{"active": 2}]})
    assert backend.status("t1") == "Running"
    _set_jobset_status(client, "t1",
                       {"conditions": [{"type": "Completed", "status": "True"}]})
    assert backend.status("t1") == "Succeeded"
    backend.delete("t1")
    assert backend.status("t1") == "NotFound"
    backend.delete("t1")  # idempotent


def test_kube_serving_backend(cluster):
    srv, client, workdir = cluster
    backend = KubeServingBackend(client, out_dir=os.path.join(workdir, "s"))
    assert backend.status("s1") == "NotFound"
    backend.deploy("s1", {"llmPath": "/models/m", "checkpointPath": "/ckpt"})
    assert backend.status("s1") == "PENDING"
    assert backend.endpoint("s1") is None

    dep = client.get("apps", "v1", "deployments", "default", "s1")
    dep["status"] = {"availableReplicas": 1}
    client.replace("apps", "v1", "deployments", "default", "s1", dep,
                   subresource="status")
    assert backend.status("s1") == "HEALTHY"
    assert backend.endpoint("s1") == "http://s1.default.svc:8000"
    svc = client.get("", "v1", "services", "default", "s1")
    assert svc["spec"]["ports"][0]["port"] == 8000
    backend.delete("s1")
    assert backend.status("s1") == "NotFound"


def test_kube_serving_backend_renders_slots(cluster):
    """ADVICE r3 low: serveConfig.slots must reach the kube serving
    Deployment args, not just the local backend."""
    srv, client, workdir = cluster
    backend = KubeServingBackend(client, out_dir=os.path.join(workdir, "s2"))
    backend.deploy("s2", {"llmPath": "/models/m", "checkpointPath": "/ckpt",
                          "slots": 4})
    dep = client.get("apps", "v1", "deployments", "default", "s2")
    args = dep["spec"]["template"]["spec"]["containers"][0]["args"]
    i = args.index("--slots")
    assert args[i + 1] == "4"
    # absent slots -> flag omitted (server default applies)
    backend.deploy("s3", {"llmPath": "/models/m"})
    dep = client.get("apps", "v1", "deployments", "default", "s3")
    assert "--slots" not in dep["spec"]["template"]["spec"]["containers"][0]["args"]


# ------------------------------------- full manifest-mode Finetune lifecycle

def test_finetune_transitions_from_jobset_conditions(cluster):
    """The round-1 gap verbatim: in manifest mode a Finetune could never leave
    Pending. Now: JobSet active → Running; Completed → Succeeded (with
    provenance checkpoint CR), all through the apiserver."""
    srv, client, workdir = cluster
    storage = os.path.join(workdir, "storage")
    store = KubeObjectStore(client)
    training = KubeTrainingBackend(client, out_dir=os.path.join(workdir, "m"))
    from datatunerx_tpu.operator.backends import FakeServingBackend

    mgr = build_manager(store, training, FakeServingBackend(),
                        storage_path=storage, with_scoring=False)
    _seed_deps(store)

    ft = Finetune(metadata=ObjectMeta(name="mft"), spec={
        "llm": "llama2-7b", "dataset": "ds-a",
        "hyperparameter": {"hyperparameterRef": "hp-a"},
        "image": {"name": "img", "path": "/models/llama2-7b"},
        "node": 2,
    })
    store.create(ft)

    def wait_state(state, timeout=20.0):
        # watch-driven enqueues are async with the kube store: poll the
        # reconcile loop until the state lands instead of asserting after one
        # run_until_idle
        import time as _t

        deadline = _t.time() + timeout
        while _t.time() < deadline:
            mgr.run_until_idle()
            mgr.drain_scheduled()
            if store.get(Finetune, "mft").status.get("state") == state:
                return
            _t.sleep(0.05)
        raise AssertionError(
            f"never reached {state}; at "
            f"{store.get(Finetune, 'mft').status.get('state')!r}")

    wait_state(Finetune.STATE_PENDING)

    _set_jobset_status(client, "mft", {"replicatedJobsStatus": [{"active": 2}]})
    mgr.enqueue("Finetune", "default", "mft")
    wait_state(Finetune.STATE_RUNNING)

    uid = store.get(Finetune, "mft").metadata.uid
    write_manifest(storage, uid, "/storage/ckpt/9", metrics={"loss": 0.9})
    _set_jobset_status(client, "mft",
                       {"conditions": [{"type": "Completed", "status": "True"}]})
    mgr.enqueue("Finetune", "default", "mft")
    wait_state(Finetune.STATE_SUCCESSFUL)
    obj = store.get(Finetune, "mft")
    assert obj.status["llmCheckpoint"]["checkpointPath"] == "/storage/ckpt/9"
    store.stop()


# ------------------------------------------------ render-only status files

def test_manifest_backend_status_file_feedback(tmp_path):
    out = str(tmp_path / "manifests")
    backend = ManifestBackend(out)
    backend.submit("r1", {"args": ["--x", "1"]})
    assert backend.status("r1") == "Pending"

    # external applier drops a raw JobSet status
    with open(os.path.join(out, "r1-status.json"), "w") as f:
        json.dump({"replicatedJobsStatus": [{"active": 1}]}, f)
    assert backend.status("r1") == "Running"
    with open(os.path.join(out, "r1-status.json"), "w") as f:
        json.dump({"state": "Succeeded"}, f)
    assert backend.status("r1") == "Succeeded"
    backend.delete("r1")
    assert backend.status("r1") == "NotFound"
    assert not os.path.exists(os.path.join(out, "r1-status.json"))
