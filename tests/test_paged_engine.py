"""Paged KV cache + chunked prefill (vLLM PagedAttention / Sarathi-style
scheduling, PAPERS.md): the correctness bar is that paging is INVISIBLE in
the tokens — paged and dense engines must produce token-exact outputs for
greedy and fixed-seed sampled decode, across base and LoRA-adapter requests
and through every prefix-cache path — while the allocator's free list and
the scheduler's prefill-token budget deliver the HBM and latency wins."""

import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from datatunerx_tpu.models.llama import forward, init_cache
from datatunerx_tpu.ops.paged_attention import (
    BlockAllocator,
    BlockAllocatorError,
    init_paged_cache,
)
from datatunerx_tpu.serving.batched_engine import BatchedEngine

MODEL = "preset:debug"


@pytest.fixture(scope="module")
def dense():
    eng = BatchedEngine(MODEL, template="vanilla", max_seq_len=256,
                        slots=2, decode_chunk=4)
    yield eng
    eng.close()


@pytest.fixture(scope="module")
def paged():
    eng = BatchedEngine(MODEL, template="vanilla", max_seq_len=256,
                        slots=2, decode_chunk=4, kv_block_size=16)
    yield eng
    eng.close()


@pytest.fixture(scope="module")
def kernel_eng():
    """Pallas in-place decode kernel forced on (interpret mode under
    JAX_PLATFORMS=cpu) — every other knob identical to ``paged``, which is
    its gather-path oracle."""
    eng = BatchedEngine(MODEL, template="vanilla", max_seq_len=256,
                        slots=2, decode_chunk=4, kv_block_size=16,
                        paged_kernel="on")
    yield eng
    eng.close()


@pytest.fixture(scope="module")
def budgeted():
    """Paged + chunked prefill with an interleave budget — shared by the
    parity and scheduler-bound tests (engine compiles are the expensive
    part of this suite; a single request's output is budget-invariant)."""
    eng = BatchedEngine(MODEL, template="vanilla", max_seq_len=256,
                        slots=2, decode_chunk=4, kv_block_size=16,
                        prefill_chunk=64, prefill_token_budget=64)
    yield eng
    eng.close()


# ------------------------------------------------------------- allocator

def test_block_allocator_exhaustion_free_reuse():
    a = BlockAllocator(4)
    b1 = a.alloc(3)
    assert b1 == [0, 1, 2] and a.free_count == 1
    # refusal is atomic: a failed alloc takes nothing
    assert a.alloc(2) is None and a.free_count == 1
    b2 = a.alloc(1)
    assert b2 == [3] and a.free_count == 0
    assert a.alloc(1) is None  # exhausted
    a.free(b1)
    assert a.free_count == 3
    assert a.alloc(2) == [0, 1]  # freed blocks are reused lowest-first
    assert a.alloc(0) == []
    with pytest.raises(ValueError):
        BlockAllocator(0)


def test_block_allocator_free_rejects_corruption():
    """free() hardening: out-of-range ids, double-frees, and in-call
    duplicates raise the typed error BEFORE mutating — the silent
    alternative re-issues a live block to a second slot."""
    a = BlockAllocator(4)
    held = a.alloc(2)  # [0, 1]
    with pytest.raises(BlockAllocatorError):
        a.free([4])  # out of range (pool has ids 0..3)
    with pytest.raises(BlockAllocatorError):
        a.free([-1])
    with pytest.raises(BlockAllocatorError):
        a.free([2])  # never allocated — already on the free list
    with pytest.raises(BlockAllocatorError):
        a.free([0, 0])  # duplicate ids in one call
    a.free(held)  # the legitimate free still works...
    assert a.free_count == 4
    with pytest.raises(BlockAllocatorError):
        a.free(held)  # ...and replaying it is a double-free
    assert a.free_count == 4  # rejected frees changed nothing
    assert isinstance(BlockAllocatorError("x"), ValueError)


# ------------------------------------------------------- model primitive

def _debug_setup():
    from datatunerx_tpu.models import get_config, init_params

    cfg = get_config("debug")
    params = init_params(cfg, jax.random.PRNGKey(0))
    toks = jax.random.randint(jax.random.PRNGKey(1), (2, 8), 0,
                              cfg.vocab_size, jnp.int32)
    return cfg, params, toks


def test_paged_forward_matches_dense_exactly():
    """The gathered block view is element-identical to the dense row, so
    prefill AND a decode step must match bit-for-bit — including when a slot
    holds fewer blocks than full capacity (ragged table)."""
    cfg, params, toks = _debug_setup()
    B, P = toks.shape

    dense_c = init_cache(cfg, B, 16, dtype=jnp.float32, per_slot=True)
    ld, dense_c = forward(params, toks, cfg, cache=dense_c)

    paged_c = init_paged_cache(cfg, B, 8, 4, 4, dtype=jnp.float32)
    paged_c["block_tables"] = jnp.asarray([[0, 1, 2, 3], [4, 5, 6, 7]],
                                          jnp.int32)
    lp, paged_c = forward(params, toks, cfg, cache=paged_c)
    np.testing.assert_array_equal(np.asarray(ld), np.asarray(lp))

    nxt = jnp.argmax(ld[:, -1], axis=-1).astype(jnp.int32)[:, None]
    pos = jnp.full((B, 1), P, jnp.int32)
    l2d, _ = forward(params, nxt, cfg, positions=pos, cache=dense_c)
    l2p, _ = forward(params, nxt, cfg, positions=pos, cache=paged_c)
    np.testing.assert_array_equal(np.asarray(l2d), np.asarray(l2p))

    # ragged: slot 1 holds only the 2 blocks its short request needs
    ragged = init_paged_cache(cfg, B, 8, 4, 4, dtype=jnp.float32)
    ragged["block_tables"] = jnp.asarray([[0, 1, 2, 3], [4, 5, -1, -1]],
                                         jnp.int32)
    lr, _ = forward(params, toks, cfg, cache=ragged)
    np.testing.assert_array_equal(np.asarray(ld), np.asarray(lr))


def test_paged_int8_cache_matches_dense_int8():
    cfg, params, toks = _debug_setup()
    qd = init_cache(cfg, 2, 16, dtype=jnp.float32, per_slot=True,
                    quantize="int8")
    ld, _ = forward(params, toks, cfg, cache=qd)
    qp = init_paged_cache(cfg, 2, 8, 4, 4, quantize="int8")
    qp["block_tables"] = jnp.asarray([[0, 1, 2, 3], [4, 5, 6, 7]], jnp.int32)
    lp, qp = forward(params, toks, cfg, cache=qp)
    assert qp["k"].dtype == jnp.int8
    assert qp["k_scale"].shape == qp["k"].shape[:-1]
    np.testing.assert_array_equal(np.asarray(ld), np.asarray(lp))


# ------------------------------------------------------- engine parity

def test_paged_greedy_matches_dense(dense, paged):
    prompt = dense.tokenizer.encode("the quick brown fox jumps over")
    want = dense.generate(prompt, max_new_tokens=12)
    got = paged.generate(prompt, max_new_tokens=12)
    assert got == want, (got, want)
    # elastic accounting: every block returned after completion
    assert paged.free_kv_blocks == paged.total_kv_blocks


def test_paged_sampled_matches_dense(dense, paged):
    """Fixed-PRNG sampling: same seed → same rng stream per slot → identical
    tokens, because the paged logits are bit-identical to dense."""
    prompt = dense.tokenizer.encode("sampling determinism probe")
    for seed in (0, 7):
        want = dense.generate(prompt, max_new_tokens=10, temperature=0.8,
                              top_p=0.9, seed=seed)
        got = paged.generate(prompt, max_new_tokens=10, temperature=0.8,
                             top_p=0.9, seed=seed)
        assert got == want, (seed, got, want)


def test_paged_long_prompt_chunked_prefill_matches_dense(dense, budgeted):
    """A prompt long enough to take several prefill chunks must still decode
    token-exactly — chunked prefill is algebraically the same computation."""
    prompt = dense.tokenizer.encode("long context " * 70)
    want = dense.generate(prompt, max_new_tokens=8)
    got = budgeted.generate(prompt, max_new_tokens=8)
    assert got == want, (got, want)
    chunks = [e for e in budgeted.sched_trace if e[0] == "prefill"]
    assert len(chunks) >= 2, "prompt did not prefill in chunks"


# ------------------------------------------- pallas kernel decode parity
#
# The gather engine (``paged``) is the ORACLE: same pool, same tables, same
# scheduler — only the attention read differs. The bar is token-exactness,
# greedy AND fixed-seed sampled, across bf16/int8 pools, pooled adapters,
# ragged in-flight lens, and the chunked-prefill → kernel-decode handoff.

def test_kernel_decode_matches_gather_and_dense(dense, paged, kernel_eng):
    assert kernel_eng.decode_path == "pallas"
    assert paged.decode_path == "gather" and dense.decode_path == "dense"
    prompt = dense.tokenizer.encode("the quick brown fox jumps over")
    want = dense.generate(prompt, max_new_tokens=12)
    assert paged.generate(prompt, max_new_tokens=12) == want
    assert kernel_eng.generate(prompt, max_new_tokens=12) == want
    # elastic accounting unchanged by the kernel: every block returned
    assert kernel_eng.free_kv_blocks == kernel_eng.total_kv_blocks


def test_kernel_sampled_matches_gather(paged, kernel_eng):
    prompt = paged.tokenizer.encode("sampling determinism probe")
    for seed in (0, 7):
        want = paged.generate(prompt, max_new_tokens=10, temperature=0.8,
                              top_p=0.9, seed=seed)
        got = kernel_eng.generate(prompt, max_new_tokens=10, temperature=0.8,
                                  top_p=0.9, seed=seed)
        assert got == want, (seed, got, want)


def test_kernel_ragged_inflight_matches_gather(paged, kernel_eng):
    """Slots at DIFFERENT depths decoding concurrently (slots=2 forces
    overlap): the kernel walks each slot's own table/len, so ragged batches
    must match the gather engine token for token."""
    tok = paged.tokenizer
    prompts = [tok.encode("short one"),
               tok.encode("a much longer prompt with plenty of context " * 3)]
    want = [paged.generate(p, max_new_tokens=8 + 4 * i)
            for i, p in enumerate(prompts)]
    reqs = [kernel_eng.submit(p, max_new_tokens=8 + 4 * i)
            for i, p in enumerate(prompts)]
    for r, w in zip(reqs, want):
        assert r.done.wait(300) and r.error is None, r.error
        assert r.tokens == w, (r.tokens, w)


def test_kernel_chunked_prefill_handoff(dense, kernel_eng):
    """Chunked prefill stays on the gather path (T > 1) and hands its slot
    to KERNEL decode — the seam between the two paths must be invisible."""
    eng = BatchedEngine(MODEL, template="vanilla", max_seq_len=256,
                        slots=2, decode_chunk=4, kv_block_size=16,
                        prefill_chunk=64, prefill_token_budget=64,
                        paged_kernel="on")
    try:
        prompt = dense.tokenizer.encode("long context " * 70)
        want = dense.generate(prompt, max_new_tokens=8)
        got = eng.generate(prompt, max_new_tokens=8)
        assert got == want, (got, want)
        chunks = [e for e in eng.sched_trace if e[0] == "prefill"]
        assert len(chunks) >= 2, "prompt did not prefill in chunks"
    finally:
        eng.close()


def test_kernel_int8_kv_parity():
    """int8 kv_quant pools: the kernel dequantizes by the paged scale pools
    in place and must match the gather path's dequantized read exactly."""
    gather = BatchedEngine(MODEL, template="vanilla", max_seq_len=256,
                           slots=2, decode_chunk=4, kv_block_size=16,
                           kv_quant="int8", paged_kernel="off")
    kern = BatchedEngine(MODEL, template="vanilla", max_seq_len=256,
                         slots=2, decode_chunk=4, kv_block_size=16,
                         kv_quant="int8", paged_kernel="on")
    try:
        prompt = gather.tokenizer.encode("quantized cache kernel probe")
        for kw in ({}, {"temperature": 0.7, "top_p": 0.9, "seed": 11}):
            want = gather.generate(prompt, max_new_tokens=8, **kw)
            got = kern.generate(prompt, max_new_tokens=8, **kw)
            assert got == want, (kw, got, want)
    finally:
        gather.close()
        kern.close()


def test_kernel_pooled_adapter_parity(tmp_path):
    """Mixed-rank pooled adapters through kernel decode: LoRA deltas ride
    the projections (not attention), but the adapter-indexed q/k/v feeding
    the kernel must still produce gather-identical tokens — greedy and
    fixed-seed sampled, base + both tenants."""
    cks = _mixed_rank_checkpoints(tmp_path)
    gather = BatchedEngine(MODEL, adapters=cks, adapter_pool=2,
                           adapter_rank_max=8, template="vanilla",
                           max_seq_len=256, slots=2, decode_chunk=4,
                           kv_block_size=16, paged_kernel="off")
    kern = BatchedEngine(MODEL, adapters=cks, adapter_pool=2,
                         adapter_rank_max=8, template="vanilla",
                         max_seq_len=256, slots=2, decode_chunk=4,
                         kv_block_size=16, paged_kernel="on")
    try:
        prompt = gather.tokenizer.encode("tenant isolation kernel probe")
        want = {}
        for adapter in ("", "a", "b"):
            want[adapter] = gather.generate(prompt, max_new_tokens=8,
                                            adapter=adapter)
            got = kern.generate(prompt, max_new_tokens=8, adapter=adapter)
            assert got == want[adapter], (adapter, got, want[adapter])
        assert want["a"] != want[""] and want["b"] != want[""]  # non-vacuous
        for adapter in ("a", "b"):
            w = gather.generate(prompt, max_new_tokens=8, adapter=adapter,
                                temperature=0.8, top_p=0.9, seed=7)
            g = kern.generate(prompt, max_new_tokens=8, adapter=adapter,
                              temperature=0.8, top_p=0.9, seed=7)
            assert g == w, (adapter, g, w)
    finally:
        gather.close()
        kern.close()


def test_kernel_flag_validation():
    with pytest.raises(ValueError, match="kv_block_size"):
        BatchedEngine(MODEL, template="vanilla", max_seq_len=256, slots=2,
                      paged_kernel="on")  # dense cache: nothing to kernel
    with pytest.raises(ValueError, match="auto|on|off"):
        BatchedEngine(MODEL, template="vanilla", max_seq_len=256, slots=2,
                      kv_block_size=16, paged_kernel="sometimes")
    # auto on a CPU backend resolves to the gather oracle
    eng = BatchedEngine(MODEL, template="vanilla", max_seq_len=256, slots=2,
                        decode_chunk=4, kv_block_size=16,
                        paged_kernel="auto")
    try:
        assert eng.decode_path == "gather" and not eng.paged_kernel
    finally:
        eng.close()


def test_paged_lora_adapter_parity(tmp_path):
    """Adapter-indexed decode through the paged cache matches dense — the
    multi-tenant path must be as invisible as the base path."""
    from datatunerx_tpu.serving.adapters import make_adapter_checkpoint

    ck = make_adapter_checkpoint(str(tmp_path / "ck"), MODEL, seed=3)
    d = BatchedEngine(MODEL, adapters={"a": ck}, template="vanilla",
                      max_seq_len=256, slots=2, decode_chunk=4)
    p = BatchedEngine(MODEL, adapters={"a": ck}, template="vanilla",
                      max_seq_len=256, slots=2, decode_chunk=4,
                      kv_block_size=16)
    try:
        prompt = d.tokenizer.encode("adapter routing check")
        for adapter in ("", "a"):
            want = d.generate(prompt, max_new_tokens=8, adapter=adapter)
            got = p.generate(prompt, max_new_tokens=8, adapter=adapter)
            assert got == want, (adapter, got, want)
        # adapters must actually differ from base, or parity proves nothing
        assert (d.generate(prompt, max_new_tokens=8, adapter="a")
                != d.generate(prompt, max_new_tokens=8))
    finally:
        d.close()
        p.close()


# ------------------------------------------------ dynamic pooled adapters

def _mixed_rank_checkpoints(tmp_path, names=("a", "b")):
    """Adapters at DIFFERENT ranks (2 and 4) so pooled parity also proves
    rank-padding to r_max is numerically invisible."""
    from datatunerx_tpu.serving.adapters import make_adapter_checkpoint

    return {n: make_adapter_checkpoint(str(tmp_path / n), MODEL,
                                       seed=3 + i, rank=2 * (i + 1))
            for i, n in enumerate(names)}


def test_pooled_adapter_decode_matches_stacked(tmp_path):
    """The tentpole's correctness bar: the dynamic pool (rank-padded slots,
    load-on-miss at admission) is TOKEN-EXACT vs the static stacked-adapter
    engine — greedy AND fixed-seed sampled — and one heterogeneous-adapter
    batch decodes concurrently through one compiled program."""
    cks = _mixed_rank_checkpoints(tmp_path)
    static = BatchedEngine(MODEL, adapters=cks, template="vanilla",
                           max_seq_len=256, slots=2, decode_chunk=4)
    pooled = BatchedEngine(MODEL, adapters=cks, adapter_pool=2,
                           adapter_rank_max=8, template="vanilla",
                           max_seq_len=256, slots=2, decode_chunk=4,
                           kv_block_size=16)
    try:
        prompt = static.tokenizer.encode("tenant isolation probe")
        want = {}
        for adapter in ("", "a", "b"):
            want[adapter] = static.generate(prompt, max_new_tokens=8,
                                            adapter=adapter)
            got = pooled.generate(prompt, max_new_tokens=8, adapter=adapter)
            assert got == want[adapter], (adapter, got, want[adapter])
        # adapters must differ from base (and each other), or parity is vacuous
        assert want["a"] != want[""] and want["b"] != want[""]
        assert want["a"] != want["b"]
        # fixed-seed sampled decode: same rng stream, bit-identical logits
        for adapter in ("a", "b"):
            w = static.generate(prompt, max_new_tokens=8, adapter=adapter,
                                temperature=0.8, top_p=0.9, seed=7)
            g = pooled.generate(prompt, max_new_tokens=8, adapter=adapter,
                                temperature=0.8, top_p=0.9, seed=7)
            assert g == w, (adapter, g, w)
        # heterogeneous batch: base + both tenants IN FLIGHT TOGETHER
        # (slots=2 forces overlap) through the one decode program
        reqs = {a: pooled.submit(prompt, max_new_tokens=8, adapter=a)
                for a in ("a", "b", "")}
        for a, r in reqs.items():
            assert r.done.wait(300) and r.error is None, (a, r.error)
            assert r.tokens == want[a], (a, r.tokens, want[a])
        occ = pooled.adapter_occupancy()
        assert occ["resident"] == 2 and occ["pinned"] == 0
    finally:
        static.close()
        pooled.close()


def test_pooled_adapter_int8_kv_parity(tmp_path):
    """Pooled adapters over the int8-quantized paged KV cache match the
    static stack over the same quantized cache."""
    cks = _mixed_rank_checkpoints(tmp_path, names=("q",))
    static = BatchedEngine(MODEL, adapters=cks, template="vanilla",
                           max_seq_len=256, slots=2, decode_chunk=4,
                           kv_quant="int8", kv_block_size=16)
    pooled = BatchedEngine(MODEL, adapters=cks, adapter_pool=1,
                           adapter_rank_max=8, template="vanilla",
                           max_seq_len=256, slots=2, decode_chunk=4,
                           kv_quant="int8", kv_block_size=16)
    try:
        prompt = static.tokenizer.encode("quantized tenant probe")
        for adapter in ("", "q"):
            for kw in ({}, {"temperature": 0.7, "top_p": 0.9, "seed": 11}):
                want = static.generate(prompt, max_new_tokens=8,
                                       adapter=adapter, **kw)
                got = pooled.generate(prompt, max_new_tokens=8,
                                      adapter=adapter, **kw)
                assert got == want, (adapter, kw, got, want)
    finally:
        static.close()
        pooled.close()


def test_adapter_load_unload_zero_recompiles(tmp_path):
    """The acceptance criterion: loading/unloading adapters at runtime
    triggers ZERO recompiles — the pool is a program ARGUMENT with fixed
    geometry, so jax's executable cache never sees a new shape. Asserted
    via the jit caches of the engine's memoized programs."""
    from datatunerx_tpu.serving.adapters import make_adapter_checkpoint

    cks = _mixed_rank_checkpoints(tmp_path)
    eng = BatchedEngine(MODEL, adapters=cks, adapter_pool=2,
                        adapter_rank_max=8, template="vanilla",
                        max_seq_len=256, slots=2, decode_chunk=4,
                        kv_block_size=16)
    try:
        prompt = eng.tokenizer.encode("compile once, serve any tenant")
        base_out = {a: eng.generate(prompt, max_new_tokens=6, adapter=a)
                    for a in ("a", "b")}
        sizes = lambda: (eng._decode._cache_size(),  # noqa: E731
                         eng._prefill._cache_size(),
                         eng._prefill_chunk_fn._cache_size())
        before = sizes()
        # runtime load of a NEW adapter (evicts an unpinned resident:
        # pool=2 is full) and traffic on it — no new programs. The
        # compile_budget(0) window turns "no recompiles" from a jit-cache
        # size comparison into a hard sanitizer error naming any compile
        # site (checkpoint construction compiles, so it stays outside).
        from datatunerx_tpu.analysis.sanitizers import compile_budget

        ck_c = make_adapter_checkpoint(str(tmp_path / "c"), MODEL, seed=9,
                                       rank=8)
        with compile_budget(0, label="adapter load/unload"):
            eng.load_adapter("c", ck_c)
            assert eng.generate(prompt, max_new_tokens=6, adapter="c")
            eng.unload_adapter("c")
            # the evicted adapter reloads on miss — still no new programs,
            # and its output is unchanged (slot recycling is invisible)
            for a in ("a", "b"):
                assert eng.generate(prompt, max_new_tokens=6,
                                    adapter=a) == base_out[a]
        assert sizes() == before, (before, sizes())
        assert eng.adapter_occupancy()["evictions"] >= 1
    finally:
        eng.close()


# ------------------------------------------------------- prefix cache

def test_paged_prefix_cache_reuse_and_extend_parity(dense):
    eng = BatchedEngine(MODEL, template="vanilla", max_seq_len=256,
                        slots=2, decode_chunk=4, kv_block_size=16,
                        prefix_cache=4)
    try:
        tok = eng.tokenizer
        p1 = tok.encode("shared system prompt for every request here")
        want1 = dense.generate(p1, max_new_tokens=10)
        assert eng.generate(p1, max_new_tokens=10) == want1  # miss → store
        assert eng.generate(p1, max_new_tokens=10) == want1  # exact reuse
        p2 = tok.encode("shared system prompt for every request here plus")
        want2 = dense.generate(p2, max_new_tokens=10)
        assert eng.generate(p2, max_new_tokens=10) == want2  # prefix extend
        assert eng.prefill_stats["reuse"] >= 1
        assert eng.prefill_stats["extend"] >= 1
        # reuse/extend insert rows into blocks; all come back on finish
        assert eng.free_kv_blocks == eng.total_kv_blocks
    finally:
        eng.close()


# ------------------------------------------- elastic admission / exhaustion

def test_block_exhaustion_queues_drains_and_short_requests_reserve_few():
    """A pool of exactly one full-length slot's blocks serves 2 slots: the
    allocator (not the slot count) gates admission, requests queue while
    blocks are out, every completion returns its blocks — and the HBM win
    itself: a short chat reserves ceil((plen+max_new)/bs) blocks, not a
    dense row's max_seq_len/bs."""
    eng = BatchedEngine(MODEL, template="vanilla", max_seq_len=256,
                        slots=2, decode_chunk=4, kv_block_size=16,
                        kv_blocks=16)
    try:
        reqs = [eng.submit(eng.tokenizer.encode(f"request number {i}"),
                           max_new_tokens=6) for i in range(4)]
        for r in reqs:
            assert r.done.wait(300), "request stalled under block exhaustion"
            assert r.error is None, r.error
        assert eng.free_kv_blocks == eng.total_kv_blocks == 16

        req = eng.submit(eng.tokenizer.encode("hi"), max_new_tokens=16)
        peak_reserved = 0
        deadline = time.time() + 300
        while not req.done.is_set() and time.time() < deadline:
            peak_reserved = max(
                peak_reserved, eng.total_kv_blocks - eng.free_kv_blocks)
            time.sleep(0.002)
        assert req.done.wait(300) and req.error is None
        # plen=64 + buf=64 → ≤ 8 blocks of 16; a dense row would strand 16
        assert 0 < peak_reserved <= 8, peak_reserved
    finally:
        eng.close()


# ------------------------------------------------------- scheduler bound

def test_prefill_budget_bounds_decode_delay():
    """With prefill_token_budget set, a long-prompt admission may hold up
    in-flight decode by at most one budget's worth of prefill between decode
    chunks (the accepted stall = one prefill burst + one decode chunk)."""
    # chunk > budget on purpose: the budget is a HARD bound, so the tick
    # must clamp the chunk to the remaining budget rather than let one
    # chunk-sized burst overshoot it
    budget, chunk = 64, 128
    eng = BatchedEngine(MODEL, template="vanilla", max_seq_len=256,
                        slots=2, decode_chunk=4, kv_block_size=16,
                        prefill_chunk=chunk, prefill_token_budget=budget)
    try:
        tok = eng.tokenizer
        short = eng.submit(tok.encode("short request"), max_new_tokens=48)
        # wait until the short request is actively decoding
        deadline = time.time() + 300
        while not short.tokens and time.time() < deadline:
            time.sleep(0.002)
        assert short.tokens, "short request never started decoding"
        long_req = eng.submit(tok.encode("ctx " * 180), max_new_tokens=8)
        assert short.done.wait(300) and long_req.done.wait(300)
        assert short.error is None and long_req.error is None

        trace = list(eng.sched_trace)
        admit_i = next(i for i, e in enumerate(trace)
                       if e[0] == "admit" and e[3] == "chunked"
                       and e[2] > budget)
        activate_i = next(i for i, e in enumerate(trace)
                          if i > admit_i and e[0] == "activate")
        window = trace[admit_i:activate_i]
        # the long prompt really was interleaved: its prefill spans several
        # bursts with decode chunks in between
        assert sum(e[2] for e in window if e[0] == "prefill") > budget
        assert any(e[0] == "decode" for e in window)
        # bound: between consecutive decode chunks (and before the first
        # one), never more than `budget` prefill tokens
        burst = 0
        for e in window:
            if e[0] == "prefill":
                burst += e[2]
                assert burst <= budget, trace
            elif e[0] == "decode":
                burst = 0
    finally:
        eng.close()


# ------------------------------------------------------- gateway signal

def test_replica_stats_surface_free_blocks(paged, dense):
    from datatunerx_tpu.gateway.replica_pool import InProcessReplica

    rp = InProcessReplica("p0", paged)
    st = rp.stats()
    assert st["kv_blocks_total"] == paged.total_kv_blocks > 0
    assert st["kv_blocks_free"] == paged.free_kv_blocks
    assert 0.0 <= rp.busy_fraction() <= 1.0

    rd = InProcessReplica("d0", dense)
    st = rd.stats()
    assert st["kv_blocks_total"] == 0  # dense replicas keep the slot signal
    assert rd.busy_fraction() == 0.0


def test_serving_metrics_expose_block_gauges(paged):
    """The /metrics text the HTTPReplica scrape parses carries the free-block
    gauge for paged engines."""
    from datatunerx_tpu.serving import server as serving_server

    class _Sink:
        def __init__(self):
            self.code, self.body, self.headers = None, b"", {}

        def send_response(self, code):
            self.code = code

        def send_header(self, k, v):
            self.headers[k] = v

        def end_headers(self):
            pass

    sink = _Sink()
    handler = serving_server.Handler.__new__(serving_server.Handler)
    handler.send_response = sink.send_response
    handler.send_header = sink.send_header
    handler.end_headers = sink.end_headers
    handler.wfile = type("W", (), {"write": lambda self, b: sink.__setattr__(
        "body", sink.body + b)})()
    old = serving_server.STATE.engine
    serving_server.STATE.engine = paged
    try:
        handler._metrics()
    finally:
        serving_server.STATE.engine = old
    text = sink.body.decode()
    assert f"dtx_serving_kv_blocks_capacity {paged.total_kv_blocks}" in text
    assert "dtx_serving_kv_blocks_free " in text
