"""Concurrency stress: optimistic-concurrency + watch/reconcile under threads
(SURVEY.md §5.2 — the reference has no race testing at all; its controllers
are MaxConcurrentReconciles=1, which our Manager also honors per-kind via the
single reconcile loop)."""

import threading


from datatunerx_tpu.operator.api import Hyperparameter, LLM, ObjectMeta
from datatunerx_tpu.operator.store import Conflict, ObjectStore


def test_concurrent_updates_all_land_or_conflict():
    """N threads bump a counter with read-modify-write + conflict retry; the
    final count proves no lost updates."""
    store = ObjectStore()
    store.create(LLM(metadata=ObjectMeta(name="m"), spec={"count": 0}))
    N_THREADS, N_INCR = 8, 25
    errors = []

    def worker():
        for _ in range(N_INCR):
            while True:
                obj = store.get(LLM, "m")
                obj.spec["count"] += 1
                try:
                    store.update(obj)
                    break
                except Conflict:
                    continue
                except Exception as e:  # noqa: BLE001
                    errors.append(e)
                    return

    threads = [threading.Thread(target=worker) for _ in range(N_THREADS)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors
    assert store.get(LLM, "m").spec["count"] == N_THREADS * N_INCR


def test_concurrent_create_delete_storm():
    """Creates/deletes/lists racing must never corrupt the store or deliver
    stale watch events that crash subscribers."""
    store = ObjectStore()
    events = []
    store.watch(lambda e: events.append(e[0]))
    errors = []

    def creator(idx):
        try:
            for i in range(20):
                name = f"hp-{idx}-{i}"
                store.create(Hyperparameter(metadata=ObjectMeta(name=name)))
                if i % 3 == 0:
                    store.delete(Hyperparameter, name)
        except Exception as e:  # noqa: BLE001
            errors.append(e)

    def lister():
        try:
            for _ in range(60):
                store.list(Hyperparameter)
        except Exception as e:  # noqa: BLE001
            errors.append(e)

    threads = [threading.Thread(target=creator, args=(k,)) for k in range(4)]
    threads += [threading.Thread(target=lister) for _ in range(2)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors
    remaining = store.list(Hyperparameter)
    # 4 creators x 20 creates, every i%3==0 deleted (7 per creator)
    assert len(remaining) == 4 * (20 - 7)
    assert events.count("ADDED") == 80


def test_manager_background_loop_with_concurrent_mutations(tmp_path):
    """The threaded Manager loop reconciles while clients mutate concurrently;
    Conflict-retry must absorb the races (no surfaced errors)."""
    from datatunerx_tpu.operator.backends import (
        FakeServingBackend,
        FakeTrainingBackend,
    )
    from datatunerx_tpu.operator.manager import build_manager
    from datatunerx_tpu.operator.api import Dataset, Finetune

    store = ObjectStore()
    training = FakeTrainingBackend()
    mgr = build_manager(store, training, FakeServingBackend(),
                        storage_path=str(tmp_path), with_scoring=False)
    mgr.start()
    try:
        store.create(LLM(metadata=ObjectMeta(name="llm"), spec={}))
        store.create(Hyperparameter(metadata=ObjectMeta(name="hp"),
                                    spec={"parameters": {}}))
        store.create(Dataset(metadata=ObjectMeta(name="ds"), spec={
            "datasetMetadata": {"datasetInfo": {"subsets": [
                {"splits": {"train": {"file": "/t.csv"}}}]}}}))

        def spam(k):
            for i in range(10):
                store.create(Finetune(
                    metadata=ObjectMeta(name=f"ft-{k}-{i}"),
                    spec={"llm": "llm", "dataset": "ds",
                          "hyperparameter": {"hyperparameterRef": "hp"},
                          "image": {"path": "/m"}},
                ))

        threads = [threading.Thread(target=spam, args=(k,)) for k in range(3)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()

        import time

        deadline = time.time() + 20
        while time.time() < deadline:
            objs = store.list(Finetune)
            if len(objs) == 30 and all(
                o.status.get("state") in ("Pending",) for o in objs
            ):
                break
            time.sleep(0.2)
        objs = store.list(Finetune)
        assert len(objs) == 30
        assert all(o.status.get("state") == "Pending" for o in objs), [
            (o.metadata.name, o.status.get("state")) for o in objs[:5]
        ]
        assert len(training.jobs) == 30
        assert not mgr.errors, mgr.errors[:3]
    finally:
        mgr.stop()
