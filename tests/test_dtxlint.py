"""dtxlint (datatunerx_tpu/analysis): one true-positive and one clean
fixture per rule, plus framework behavior — inline suppressions, baseline
load/partition, JSON output, config parsing, and the CI contract that the
repo itself lints clean.

The DTX006/DTX007 positive fixtures reproduce the PRE-FIX gateway
drain-leak shape from ROADMAP ("/admin/drain never reaps"): a replica set
that spawns subprocesses, drains on request, and never terminates what it
drained — exactly what PR 4 fixed in gateway/server.py.
"""

import json
import textwrap

from datatunerx_tpu.analysis.baseline import (
    load_baseline,
    partition,
    save_baseline,
)
from datatunerx_tpu.analysis.cli import main as dtxlint_main
from datatunerx_tpu.analysis.config import LintConfig, load_config
from datatunerx_tpu.analysis.core import lint_paths, lint_source

CFG = LintConfig(mesh_axes=("dp", "fsdp", "tp", "sp"))


def run(src, config=CFG):
    res = lint_source(textwrap.dedent(src), path="fixture.py", config=config)
    return res


def rule_ids(src, config=CFG):
    return [f.rule for f in run(src, config).findings]


# ------------------------------------------------------------------ DTX001
def test_dtx001_flags_host_sync_reachable_from_hot_function():
    src = """
    import jax
    import numpy as np

    def log_metrics(m):
        return float(m["loss"])

    def train_step(state, batch):
        out = state.apply(batch)
        log_metrics(out)
        return np.asarray(out)
    """
    ids = rule_ids(src)
    assert ids.count("DTX001") == 2  # float() via call graph + np.asarray


def test_dtx001_clean_outside_hot_path_and_on_constants():
    src = """
    import numpy as np

    def train_step(state, batch):
        return state.apply(batch)

    def summarize(metrics):
        # same calls, but not reachable from a hot function
        return float(metrics["loss"]), np.asarray(metrics["hist"])

    def parse(v):
        return float("1.5")
    """
    assert rule_ids(src) == []


# ------------------------------------------------------------------ DTX002
def test_dtx002_flags_jit_in_loop_and_unstable_static_args():
    src = """
    import jax

    def compile_all(fns):
        out = []
        for f in fns:
            out.append(jax.jit(f))
        return out

    bad = jax.jit(lambda x: x, static_argnums={0, 1})
    """
    ids = rule_ids(src)
    assert ids.count("DTX002") == 2


def test_dtx002_clean_for_hoisted_jit_called_in_loop():
    src = """
    import jax

    step = jax.jit(lambda x: x + 1)

    def run(n):
        for i in range(n):
            step(i)
        return jax.jit(lambda y: y, static_argnums=(0,))
    """
    assert rule_ids(src) == []


# ------------------------------------------------------------------ DTX003
def test_dtx003_flags_python_branch_on_traced_value():
    src = """
    import jax
    import jax.numpy as jnp

    @jax.jit
    def f(x):
        if jnp.any(x > 0):
            return x
        return -x
    """
    assert rule_ids(src) == ["DTX003"]


def test_dtx003_allows_static_shape_branches_and_wrapped_names():
    src = """
    import jax
    import jax.numpy as jnp

    def impl(x):
        if x.ndim == 2:  # static under tracing
            return jnp.sum(x, axis=-1)
        return jnp.where(x > 0, x, -x)

    f = jax.jit(impl)

    def eager(x):
        # not jitted: Python control flow on values is fine
        if jnp.any(x > 0):
            return x
        return -x
    """
    assert rule_ids(src) == []


# ------------------------------------------------------------------ DTX004
def test_dtx004_flags_double_consumption_and_loop_reuse():
    src = """
    import jax

    def double(key):
        a = jax.random.normal(key, (2,))
        b = jax.random.uniform(key, (2,))
        return a + b

    def loop(key):
        return [jax.random.normal(key, (2,)) for _ in range(3)] if False \\
            else _loop(key)

    def _loop(key):
        out = []
        for i in range(3):
            out.append(jax.random.normal(key, (2,)))
        return out
    """
    ids = rule_ids(src)
    assert ids.count("DTX004") == 2


def test_dtx004_clean_split_branches_loop_carry_and_fold_in():
    src = """
    import jax

    def good(key, flag):
        k1, k2 = jax.random.split(key)
        a = jax.random.normal(k1, (2,))
        if flag:
            b = jax.random.uniform(k2, (2,))
        else:
            b = jax.random.normal(k2, (2,))
        return a + b

    def carry(key):
        out = []
        for i in range(3):
            key, sub = jax.random.split(key)
            out.append(jax.random.normal(sub, (2,)))
        return out

    def streams(key):
        # fold_in with distinct data is the documented idiom, not reuse
        return [jax.random.normal(jax.random.fold_in(key, i), (2,))
                for i in range(3)]
    """
    assert rule_ids(src) == []


# ------------------------------------------------------------------ DTX005
def test_dtx005_flags_undeclared_axis_name():
    src = """
    from jax.sharding import PartitionSpec as P

    def spec():
        return P("data", None)
    """
    assert rule_ids(src) == ["DTX005"]


def test_dtx005_clean_declared_axes_and_quiet_without_axes():
    src = """
    from jax.sharding import PartitionSpec as P

    def spec():
        return P(("dp", "fsdp"), None, "tp")
    """
    assert rule_ids(src) == []
    # no declared axes configured → nothing to check against
    assert rule_ids('from jax.sharding import PartitionSpec as P\n'
                    'x = P("whatever")\n', config=LintConfig()) == []


def test_dtx005_flags_collective_axis_name_drift():
    # positional axis_name
    src = """
    import jax

    def all_reduce(x):
        return jax.lax.psum(x, "data")
    """
    assert rule_ids(src) == ["DTX005"]
    # keyword + tuple form, and axis_index's position-0 argument
    src2 = """
    import jax

    def gather(x):
        i = jax.lax.axis_index("mdl")
        return jax.lax.all_gather(x, axis_name=("dp", "model")), i
    """
    assert rule_ids(src2) == ["DTX005", "DTX005"]


def test_dtx005_clean_collectives_declared_or_variable_axis():
    src = """
    import jax

    def reduce_ok(x, axis_name):
        y = jax.lax.pmean(x, "dp")
        z = jax.lax.psum(x, ("dp", "fsdp"))
        i = jax.lax.axis_index("tp")
        # a VARIABLE axis name (ring attention's parameter) is out of
        # static reach — must not be flagged
        return jax.lax.ppermute(y + z + i, axis_name, [(0, 1)])
    """
    assert rule_ids(src) == []


# ------------------------------------------------------------------ DTX006
# the pre-fix /admin/drain shape: a public method flips state the
# supervisor thread reconciles on, with no lock
DRAIN_LEAK_CLASS = """
import subprocess
import threading


class ReplicaSet:
    def __init__(self):
        self._lock = threading.Lock()
        self.target = 0
        self._procs = {}
        self._t = threading.Thread(target=self._supervise, daemon=True)
        self._t.start()

    def _supervise(self):
        while True:
            if len(self._procs) < self.target:
                self.spawn(str(len(self._procs)))

    def spawn(self, name):
        self._procs[name] = subprocess.Popen(["serve"])

    def scale(self, n):
        self.target = n

    def drain(self, name):
        self._procs[name].draining = True
"""


def test_dtx006_flags_pre_fix_drain_leak_shape_unlocked_public_write():
    ids = rule_ids(DRAIN_LEAK_CLASS)
    assert "DTX006" in ids  # scale() writes self.target, thread reads it


def test_dtx006_clean_when_writes_hold_the_lock():
    src = """
    import threading

    class ReplicaSet:
        def __init__(self):
            self._lock = threading.Lock()
            self.target = 0
            self._t = threading.Thread(target=self._loop, daemon=True)

        def _loop(self):
            while True:
                with self._lock:
                    n = self.target

        def scale(self, n):
            with self._lock:
                self.target = n
    """
    assert rule_ids(src) == []


# ------------------------------------------------------------------ DTX007
def test_dtx007_flags_pre_fix_drain_leak_shape_unreaped_subprocess():
    ids = rule_ids(DRAIN_LEAK_CLASS)
    # spawn() stores a Popen in self._procs and NO method of the class
    # ever terminates/joins values from it — the zombie-per-drain leak
    assert "DTX007" in ids


def test_dtx007_clean_when_a_method_reaps_and_for_escaping_handles():
    src = """
    import subprocess
    import threading

    class ReplicaSet:
        def __init__(self):
            self._procs = {}

        def spawn(self, name):
            self._procs[name] = subprocess.Popen(["serve"])

        def close(self):
            procs = list(self._procs.values())
            for proc in procs:
                proc.terminate()

    def run_once():
        proc = subprocess.Popen(["true"])
        proc.wait()

    def fire_and_forget(fn):
        threading.Thread(target=fn, daemon=True).start()

    def handoff():
        return subprocess.Popen(["true"])
    """
    assert rule_ids(src) == []


# ------------------------------------------------------------------ DTX008
def test_dtx008_flags_module_level_and_default_arg_device_work():
    src = """
    import jax
    import jax.numpy as jnp

    TABLE = jnp.ones((8,))

    def f(x, fill=jnp.zeros((4,))):
        return x + fill

    N_DEV = jax.device_count()
    """
    assert rule_ids(src) == ["DTX008"] * 3


def test_dtx008_clean_for_lazy_work_jit_wrappers_and_dtypes():
    src = """
    import jax
    import jax.numpy as jnp

    DTYPE = jnp.float32

    def make_table():
        return jnp.ones((8,))

    f = jax.jit(make_table)
    g = lambda: jnp.zeros((4,))
    """
    assert rule_ids(src) == []


# ------------------------------------------------------- framework behavior
def test_inline_suppression_comment_silences_one_rule():
    src = """
    import jax.numpy as jnp

    A = jnp.ones((2,))  # dtxlint: disable=DTX008 -- frozen table, deliberate
    B = jnp.ones((2,))  # dtxlint: disable=DTX001
    C = jnp.ones((2,))  # dtxlint: disable=all
    """
    res = run(src)
    assert [f.rule for f in res.findings] == ["DTX008"]  # only B still fires
    assert res.suppressed == 2


def test_baseline_roundtrip_and_partition(tmp_path):
    res = run("import jax.numpy as jnp\nA = jnp.ones((2,))\n")
    assert len(res.findings) == 1
    path = tmp_path / "baseline.json"
    save_baseline(str(path), res.findings)
    carried = load_baseline(str(path))
    new, baselined = partition(res.findings, carried)
    assert new == [] and len(baselined) == 1
    # a second, identical finding needs a second baseline entry
    two = res.findings * 2
    new, baselined = partition(two, carried)
    assert len(new) == 1 and len(baselined) == 1
    assert load_baseline(str(tmp_path / "missing.json")) == {}


def test_cli_json_output_and_exit_codes(tmp_path, capsys):
    bad = tmp_path / "bad.py"
    bad.write_text("import jax.numpy as jnp\nA = jnp.ones((2,))\n")
    rc = dtxlint_main([str(bad), "--format", "json", "--no-config",
                       "--no-baseline"])
    doc = json.loads(capsys.readouterr().out)
    assert rc == 1 and doc["failed"]
    assert doc["findings"][0]["rule"] == "DTX008"
    assert doc["findings"][0]["line"] == 2

    good = tmp_path / "good.py"
    good.write_text("def f():\n    return 1\n")
    assert dtxlint_main([str(good), "--no-config", "--no-baseline"]) == 0


def test_cli_write_baseline_then_clean(tmp_path, capsys):
    bad = tmp_path / "bad.py"
    bad.write_text("import jax.numpy as jnp\nA = jnp.ones((2,))\n")
    base = tmp_path / "base.json"
    assert dtxlint_main([str(bad), "--no-config", "--baseline",
                         str(base), "--write-baseline"]) == 0
    assert dtxlint_main([str(bad), "--no-config", "--baseline",
                         str(base)]) == 0
    capsys.readouterr()


def test_select_runs_only_named_rules(tmp_path):
    src = ("import jax\nimport jax.numpy as jnp\n"
           "A = jnp.ones((2,))\n"
           "def f(key):\n"
           "    a = jax.random.normal(key, (2,))\n"
           "    return a + jax.random.uniform(key, (2,))\n")
    p = tmp_path / "m.py"
    p.write_text(src)
    res = lint_paths([str(p)], config=LintConfig())
    assert {f.rule for f in res.findings} == {"DTX004", "DTX008"}
    from datatunerx_tpu.analysis.rules import rules_by_id

    res = lint_paths([str(p)], config=LintConfig(),
                     rules=rules_by_id(["DTX004"]))
    assert {f.rule for f in res.findings} == {"DTX004"}


def test_config_disable_and_toml_subset(tmp_path):
    (tmp_path / "pyproject.toml").write_text(textwrap.dedent("""
        [project]
        name = "x"

        [tool.dtxlint]
        baseline = "b.json"
        disable = ["DTX008"]
        hot-functions = [
            "train_step",
            "hot_*",
        ]
        mesh-axes = ["dp", "tp"]
    """))
    cfg = load_config(str(tmp_path))
    assert cfg.baseline == "b.json"
    assert cfg.disable == ("DTX008",)
    assert cfg.hot_functions == ("train_step", "hot_*")
    assert cfg.mesh_axes == ("dp", "tp")
    res = lint_source("import jax.numpy as jnp\nA = jnp.ones((2,))\n",
                      config=cfg)
    assert res.findings == []  # DTX008 disabled by config


def test_syntax_error_reports_dtx000_not_crash():
    res = lint_source("def broken(:\n", path="x.py")
    assert [f.rule for f in res.findings] == ["DTX000"]


# --------------------------------------------------------------- CI contract
def test_repo_lints_clean_at_head():
    """The acceptance gate: the shipped tree has zero non-suppressed
    findings against the shipped (empty-findings) baseline."""
    cfg = load_config(".")
    res = lint_paths(["datatunerx_tpu"], config=cfg)
    baseline = load_baseline(cfg.resolve(cfg.baseline))
    new, _ = partition(res.findings, baseline)
    assert new == [], "\n".join(f.render() for f in new)
    assert baseline == {}, "policy: the baseline stays empty"


def test_mesh_axes_extracted_from_mesh_module():
    from datatunerx_tpu.analysis.config import mesh_axes_for

    cfg = load_config(".")
    assert set(mesh_axes_for(cfg)) == {"dp", "fsdp", "tp", "sp"}
