"""dtxlint (datatunerx_tpu/analysis): one true-positive and one clean
fixture per rule, plus framework behavior — inline suppressions, baseline
load/partition, JSON output, config parsing, and the CI contract that the
repo itself lints clean.

The DTX006/DTX007 positive fixtures reproduce the PRE-FIX gateway
drain-leak shape from ROADMAP ("/admin/drain never reaps"): a replica set
that spawns subprocesses, drains on request, and never terminates what it
drained — exactly what PR 4 fixed in gateway/server.py.
"""

import ast
import dataclasses
import json
import subprocess
import textwrap
import time

import pytest

from datatunerx_tpu.analysis.baseline import (
    load_baseline,
    partition,
    save_baseline,
)
from datatunerx_tpu.analysis.cli import main as dtxlint_main
from datatunerx_tpu.analysis.config import LintConfig, load_config
from datatunerx_tpu.analysis.core import lint_paths, lint_source
from datatunerx_tpu.analysis.fix import (
    OverlapError,
    SpanEdit,
    apply_edits,
    fix_source,
)
from datatunerx_tpu.analysis.program import lint_program

CFG = LintConfig(mesh_axes=("dp", "fsdp", "tp", "sp"))


def run(src, config=CFG):
    res = lint_source(textwrap.dedent(src), path="fixture.py", config=config)
    return res


def rule_ids(src, config=CFG):
    return [f.rule for f in run(src, config).findings]


# ------------------------------------------------------------------ DTX001
def test_dtx001_flags_host_sync_reachable_from_hot_function():
    src = """
    import jax
    import numpy as np

    def log_metrics(m):
        return float(m["loss"])

    def train_step(state, batch):
        out = state.apply(batch)
        log_metrics(out)
        return np.asarray(out)
    """
    ids = rule_ids(src)
    assert ids.count("DTX001") == 2  # float() via call graph + np.asarray


def test_dtx001_clean_outside_hot_path_and_on_constants():
    src = """
    import numpy as np

    def train_step(state, batch):
        return state.apply(batch)

    def summarize(metrics):
        # same calls, but not reachable from a hot function
        return float(metrics["loss"]), np.asarray(metrics["hist"])

    def parse(v):
        return float("1.5")
    """
    assert rule_ids(src) == []


# ------------------------------------------------------------------ DTX002
def test_dtx002_flags_jit_in_loop_and_unstable_static_args():
    src = """
    import jax

    def compile_all(fns):
        out = []
        for f in fns:
            out.append(jax.jit(f))
        return out

    bad = jax.jit(lambda x: x, static_argnums={0, 1})
    """
    ids = rule_ids(src)
    assert ids.count("DTX002") == 2


def test_dtx002_clean_for_hoisted_jit_called_in_loop():
    src = """
    import jax

    step = jax.jit(lambda x: x + 1)

    def run(n):
        for i in range(n):
            step(i)
        return jax.jit(lambda y: y, static_argnums=(0,))
    """
    assert rule_ids(src) == []


# ------------------------------------------------------------------ DTX003
def test_dtx003_flags_python_branch_on_traced_value():
    src = """
    import jax
    import jax.numpy as jnp

    @jax.jit
    def f(x):
        if jnp.any(x > 0):
            return x
        return -x
    """
    assert rule_ids(src) == ["DTX003"]


def test_dtx003_allows_static_shape_branches_and_wrapped_names():
    src = """
    import jax
    import jax.numpy as jnp

    def impl(x):
        if x.ndim == 2:  # static under tracing
            return jnp.sum(x, axis=-1)
        return jnp.where(x > 0, x, -x)

    f = jax.jit(impl)

    def eager(x):
        # not jitted: Python control flow on values is fine
        if jnp.any(x > 0):
            return x
        return -x
    """
    assert rule_ids(src) == []


# ------------------------------------------------------------------ DTX004
def test_dtx004_flags_double_consumption_and_loop_reuse():
    src = """
    import jax

    def double(key):
        a = jax.random.normal(key, (2,))
        b = jax.random.uniform(key, (2,))
        return a + b

    def loop(key):
        return [jax.random.normal(key, (2,)) for _ in range(3)] if False \\
            else _loop(key)

    def _loop(key):
        out = []
        for i in range(3):
            out.append(jax.random.normal(key, (2,)))
        return out
    """
    ids = rule_ids(src)
    assert ids.count("DTX004") == 2


def test_dtx004_clean_split_branches_loop_carry_and_fold_in():
    src = """
    import jax

    def good(key, flag):
        k1, k2 = jax.random.split(key)
        a = jax.random.normal(k1, (2,))
        if flag:
            b = jax.random.uniform(k2, (2,))
        else:
            b = jax.random.normal(k2, (2,))
        return a + b

    def carry(key):
        out = []
        for i in range(3):
            key, sub = jax.random.split(key)
            out.append(jax.random.normal(sub, (2,)))
        return out

    def streams(key):
        # fold_in with distinct data is the documented idiom, not reuse
        return [jax.random.normal(jax.random.fold_in(key, i), (2,))
                for i in range(3)]
    """
    assert rule_ids(src) == []


# ------------------------------------------------------------------ DTX005
def test_dtx005_flags_undeclared_axis_name():
    src = """
    from jax.sharding import PartitionSpec as P

    def spec():
        return P("data", None)
    """
    assert rule_ids(src) == ["DTX005"]


def test_dtx005_clean_declared_axes_and_quiet_without_axes():
    src = """
    from jax.sharding import PartitionSpec as P

    def spec():
        return P(("dp", "fsdp"), None, "tp")
    """
    assert rule_ids(src) == []
    # no declared axes configured → nothing to check against
    assert rule_ids('from jax.sharding import PartitionSpec as P\n'
                    'x = P("whatever")\n', config=LintConfig()) == []


def test_dtx005_flags_collective_axis_name_drift():
    # positional axis_name
    src = """
    import jax

    def all_reduce(x):
        return jax.lax.psum(x, "data")
    """
    assert rule_ids(src) == ["DTX005"]
    # keyword + tuple form, and axis_index's position-0 argument
    src2 = """
    import jax

    def gather(x):
        i = jax.lax.axis_index("mdl")
        return jax.lax.all_gather(x, axis_name=("dp", "model")), i
    """
    assert rule_ids(src2) == ["DTX005", "DTX005"]


def test_dtx005_clean_collectives_declared_or_variable_axis():
    src = """
    import jax

    def reduce_ok(x, axis_name):
        y = jax.lax.pmean(x, "dp")
        z = jax.lax.psum(x, ("dp", "fsdp"))
        i = jax.lax.axis_index("tp")
        # a VARIABLE axis name (ring attention's parameter) is out of
        # static reach — must not be flagged
        return jax.lax.ppermute(y + z + i, axis_name, [(0, 1)])
    """
    assert rule_ids(src) == []


# ------------------------------------------------------------------ DTX006
# the pre-fix /admin/drain shape: a public method flips state the
# supervisor thread reconciles on, with no lock
DRAIN_LEAK_CLASS = """
import subprocess
import threading


class ReplicaSet:
    def __init__(self):
        self._lock = threading.Lock()
        self.target = 0
        self._procs = {}
        self._t = threading.Thread(target=self._supervise, daemon=True)
        self._t.start()

    def _supervise(self):
        while True:
            if len(self._procs) < self.target:
                self.spawn(str(len(self._procs)))

    def spawn(self, name):
        self._procs[name] = subprocess.Popen(["serve"])

    def scale(self, n):
        self.target = n

    def drain(self, name):
        self._procs[name].draining = True
"""


def test_dtx006_flags_pre_fix_drain_leak_shape_unlocked_public_write():
    ids = rule_ids(DRAIN_LEAK_CLASS)
    assert "DTX006" in ids  # scale() writes self.target, thread reads it


def test_dtx006_clean_when_writes_hold_the_lock():
    src = """
    import threading

    class ReplicaSet:
        def __init__(self):
            self._lock = threading.Lock()
            self.target = 0
            self._t = threading.Thread(target=self._loop, daemon=True)

        def _loop(self):
            while True:
                with self._lock:
                    n = self.target

        def scale(self, n):
            with self._lock:
                self.target = n
    """
    assert rule_ids(src) == []


# ------------------------------------------------------------------ DTX007
def test_dtx007_flags_pre_fix_drain_leak_shape_unreaped_subprocess():
    ids = rule_ids(DRAIN_LEAK_CLASS)
    # spawn() stores a Popen in self._procs and NO method of the class
    # ever terminates/joins values from it — the zombie-per-drain leak
    assert "DTX007" in ids


def test_dtx007_clean_when_a_method_reaps_and_for_escaping_handles():
    src = """
    import subprocess
    import threading

    class ReplicaSet:
        def __init__(self):
            self._procs = {}

        def spawn(self, name):
            self._procs[name] = subprocess.Popen(["serve"])

        def close(self):
            procs = list(self._procs.values())
            for proc in procs:
                proc.terminate()

    def run_once():
        proc = subprocess.Popen(["true"])
        proc.wait()

    def fire_and_forget(fn):
        threading.Thread(target=fn, daemon=True).start()

    def handoff():
        return subprocess.Popen(["true"])
    """
    assert rule_ids(src) == []


# ------------------------------------------------------------------ DTX008
def test_dtx008_flags_module_level_and_default_arg_device_work():
    src = """
    import jax
    import jax.numpy as jnp

    TABLE = jnp.ones((8,))

    def f(x, fill=jnp.zeros((4,))):
        return x + fill

    N_DEV = jax.device_count()
    """
    assert rule_ids(src) == ["DTX008"] * 3


def test_dtx008_clean_for_lazy_work_jit_wrappers_and_dtypes():
    src = """
    import jax
    import jax.numpy as jnp

    DTYPE = jnp.float32

    def make_table():
        return jnp.ones((8,))

    f = jax.jit(make_table)
    g = lambda: jnp.zeros((4,))
    """
    assert rule_ids(src) == []


# ------------------------------------------------------------------ DTX009
def test_dtx009_flags_blocking_calls_under_lock():
    src = """
    import queue
    import subprocess
    import threading

    class Pool:
        def __init__(self):
            self._lock = threading.Lock()
            self._q = queue.Queue()

        def tick(self):
            with self._lock:
                item = self._q.get()
                subprocess.run(["sync-replica"])
            return item
    """
    ids = rule_ids(src)
    assert ids.count("DTX009") == 2  # unbounded .get() + subprocess.run


def test_dtx009_clean_bounded_waits_and_non_lock_contexts():
    src = """
    import threading

    class Pool:
        def __init__(self):
            self._lock = threading.Lock()
            self._session = Session()

        def tick(self, proc, item_q):
            with self._lock:
                item = item_q.get(timeout=1.0)
                proc.wait(timeout=10)
            with self._session:  # not a lock: naming-based on purpose
                proc.communicate()
            proc.wait()  # blocking, but no lock held
            return item
    """
    assert rule_ids(src) == []


# ------------------------------------------------------------------ DTX010
def test_dtx010_flags_read_after_donation():
    src = """
    import jax

    step = jax.jit(lambda s, b: s, donate_argnums=(0,))

    def run(state, batch):
        out = step(state, batch)
        return out, state
    """
    assert rule_ids(src) == ["DTX010"]


def test_dtx010_clean_loop_carry_and_rebind_before_read():
    src = """
    import jax

    step = jax.jit(lambda s, b: s, donate_argnums=(0,))

    def train(state, batches):
        for b in batches:
            state = step(state, b)
        return state

    def reset(state, batch):
        _ = step(state, batch)
        state = make_state()
        return state
    """
    assert rule_ids(src) == []


def test_dtx010_conditional_rebind_does_not_clear_fallthrough_read():
    # `if err: state = reset()` only rebinds on one path — the other still
    # reads the donated buffer and must flag; a read INSIDE the rebinding
    # branch (after its store) is clean
    src = """
    import jax

    step = jax.jit(lambda s, b: s, donate_argnums=(0,))

    def run(state, batch, err):
        out = step(state, batch)
        if err:
            state = make_state()
        return out, state

    def fine(state, batch, err):
        out = step(state, batch)
        if err:
            state = make_state()
            log(state)
        return out
    """
    assert rule_ids(src) == ["DTX010"]


def test_dtx010_flags_loop_backedge_without_rebind():
    # the decode-loop shape the rule exists for: state is donated every
    # iteration but never rebound, so iteration N+1 reads N's dead buffer;
    # a loop whose target (or body) rebinds the victim is clean
    src = """
    import jax

    step = jax.jit(lambda s, b: s, donate_argnums=(0,))

    def decode(state, batches):
        outs = []
        for b in batches:
            outs.append(step(state, b))
        return outs

    def fresh_each(states, batch):
        for state in states:
            _ = step(state, batch)
    """
    assert rule_ids(src) == ["DTX010"]


# ------------------------------------------------------------------ DTX011
def test_dtx011_flags_lexical_lock_order_inversion():
    src = """
    import threading

    class Pool:
        def __init__(self):
            self._alloc_lock = threading.Lock()
            self._stats_lock = threading.Lock()

        def allocate(self):
            with self._alloc_lock:
                with self._stats_lock:
                    return 1

        def report(self):
            with self._stats_lock:
                with self._alloc_lock:
                    return 2
    """
    ids = rule_ids(src)
    assert ids.count("DTX011") == 1
    f = [x for x in run(src).findings if x.rule == "DTX011"][0]
    assert "lock-order inversion" in f.message
    assert "opposite order" in f.message


def test_dtx011_clean_on_consistent_global_order():
    src = """
    import threading

    class Pool:
        def __init__(self):
            self._alloc_lock = threading.Lock()
            self._stats_lock = threading.Lock()

        def allocate(self):
            with self._alloc_lock:
                with self._stats_lock:
                    return 1

        def audit(self):
            with self._alloc_lock:
                with self._stats_lock:
                    return 2

        def stats_only(self):
            with self._stats_lock:
                return 3
    """
    assert rule_ids(src) == []


def test_dtx011_multi_item_with_uses_acquisition_order():
    # `with a, b` then `with b, a` is the same ABBA spelled compactly
    src = """
    import threading

    _a_lock = threading.Lock()
    _b_lock = threading.Lock()

    def fwd():
        with _a_lock, _b_lock:
            pass

    def rev():
        with _b_lock, _a_lock:
            pass
    """
    assert rule_ids(src).count("DTX011") == 1


# ------------------------------------------------------------------ DTX012
def test_dtx012_flags_daemon_thread_without_shutdown_evidence():
    src = """
    import threading

    class Ticker:
        def start(self):
            self._t = threading.Thread(target=self._run, daemon=True)
            self._t.start()

        def _run(self):
            while True:
                pass
    """
    ids = rule_ids(src)
    assert ids == ["DTX012"]
    f = run(src).findings[0]
    assert "no shutdown evidence" in f.message
    assert "self._t" in f.message


def test_dtx012_clean_with_stop_event_or_join():
    src = """
    import threading

    class EventLoop:
        def __init__(self):
            self._stop = threading.Event()

        def start(self):
            self._t = threading.Thread(target=self._run, daemon=True)
            self._t.start()

        def _run(self):
            while not self._stop.is_set():
                pass

        def close(self):
            self._stop.set()

    class Joined:
        def start(self):
            self._t = threading.Thread(target=print, daemon=True)
            self._t.start()

        def close(self):
            self._t.join(timeout=5)

    class Scoped:
        def run_once(self):
            t = threading.Thread(target=print, daemon=True)
            t.start()
            t.join()
    """
    assert rule_ids(src) == []


def test_dtx012_local_handle_escaping_to_attr_uses_class_evidence():
    # the AdapterRegistry/Gateway shape: a local handle appended to (or
    # aliased into) a self attribute that close() drains and joins
    src = """
    import threading

    class Registry:
        def __init__(self):
            self._loaders = []

        def kick(self):
            t = threading.Thread(target=print, daemon=True)
            self._loaders.append(t)
            t.start()

        def close(self):
            workers = [w for w in self._loaders if w.is_alive()]
            for w in workers:
                w.join(timeout=5)

    class Promoter:
        def start(self):
            t = threading.Thread(target=print, daemon=True)
            self._promo = t
            t.start()

        def close(self):
            t = self._promo
            t.join(timeout=5)
    """
    assert rule_ids(src) == []


def test_dtx012_timer_cancel_counts_and_unstarted_ignored():
    src = """
    import threading

    class Debounce:
        def arm(self):
            self._timer = threading.Timer(1.0, print)
            self._timer.daemon = True
            self._timer.start()

        def close(self):
            self._timer.cancel()

    class NeverStarted:
        def build(self):
            self._t = threading.Thread(target=print, daemon=True)
    """
    assert rule_ids(src) == []


def test_dtx012_non_daemon_is_dtx007_territory():
    # no daemon flag: DTX012 stays quiet (DTX007 owns non-daemon handles)
    src = """
    import threading

    class Plain:
        def start(self):
            self._t = threading.Thread(target=print)
            self._t.start()
    """
    assert "DTX012" not in rule_ids(src)


# ------------------------------------------------------- hot-region markers
def test_hot_region_markers_flag_sync_inside_region_only():
    src = """
    import numpy as np

    def load_config(path):
        return np.asarray([1.0])  # called outside the region: cold

    def fetch_metrics(m):
        return np.asarray(m)  # called FROM the region: hot by propagation

    def main(batches):
        cfg = load_config("x")
        # dtxlint: hot-begin
        out = [fetch_metrics(b) for b in batches]
        # dtxlint: hot-end
        return cfg, out
    """
    res = run(src)
    assert [f.rule for f in res.findings] == ["DTX001"]
    assert res.findings[0].line == 8  # the asarray inside fetch_metrics


def test_hot_region_sync_flagged_lexically_and_clean_without_markers():
    marked = """
    def main(batches):
        # dtxlint: hot-begin
        for b in batches:
            loss = float(step(b))
        # dtxlint: hot-end
        return loss
    """
    assert rule_ids(marked) == ["DTX001"]
    unmarked = "\n".join(ln for ln in textwrap.dedent(marked).splitlines()
                         if "dtxlint" not in ln)
    assert rule_ids(unmarked) == []


# ------------------------------------------------- program graph (tentpole)
def _write_pkg(tmp_path, files):
    """A real on-disk package so module_name_for_path resolves pkg.*
    imports; lint_program stitches the per-module graphs together."""
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "__init__.py").write_text("")
    for name, src in files.items():
        (pkg / name).write_text(textwrap.dedent(src))
    return pkg


def _prog(pkg):
    res, stats = lint_program([str(pkg)], config=LintConfig(cache=""))
    return res


def test_program_graph_flags_cross_module_sync_from_hot_root(tmp_path):
    pkg = _write_pkg(tmp_path, {
        "helpers.py": """
            import numpy as np

            def to_host(x):
                return np.asarray(x)
        """,
        "train.py": """
            from pkg.helpers import to_host

            def train_step(state, batch):
                return to_host(state)
        """,
    })
    findings = _prog(pkg).findings
    assert [f.rule for f in findings] == ["DTX001"]
    assert "helpers.py" in findings[0].path  # flagged where the sync lives
    assert "train_step" in findings[0].message  # ... naming the hot root


def test_program_graph_clean_when_helper_not_reachable_from_hot(tmp_path):
    pkg = _write_pkg(tmp_path, {
        "helpers.py": """
            import numpy as np

            def to_host(x):
                return np.asarray(x)
        """,
        "train.py": """
            from pkg.helpers import to_host

            def train_step(state, batch):
                return state

            def summarize(metrics):
                return to_host(metrics)  # cold caller: no finding
        """,
    })
    assert _prog(pkg).findings == []


def test_program_graph_flags_blocking_leaf_across_modules(tmp_path):
    pkg = _write_pkg(tmp_path, {
        "net.py": """
            import requests

            def fetch(url):
                return requests.get(url)
        """,
        "pool.py": """
            import threading

            from pkg.net import fetch

            class Pool:
                def __init__(self):
                    self._lock = threading.Lock()

                def refresh(self):
                    with self._lock:
                        return fetch("http://replica/health")
        """,
    })
    findings = _prog(pkg).findings
    assert [f.rule for f in findings] == ["DTX009"]
    assert "pool.py" in findings[0].path  # flagged at the locked call site
    assert "requests.get" in findings[0].message  # ... naming the leaf


def test_program_graph_ignores_thread_target_reference_edges(tmp_path):
    # the ManagedReplicaSet shape: reconcile (under lock) starts a reaper
    # THREAD whose target sleeps/waits — that work runs on another frame,
    # so the held-lock reachability must not follow the target= reference
    pkg = _write_pkg(tmp_path, {
        "pool.py": """
            import threading
            import time

            class Pool:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._stop = threading.Event()

                def _reap(self, name):
                    time.sleep(0.1)

                def _start_reap(self, name):
                    # daemon=True keeps this DTX007-clean and the _stop
                    # event keeps it DTX012-clean; the rule under test
                    # here is DTX009's reachability, not handle leaks
                    threading.Thread(
                        target=self._reap, args=(name,), daemon=True
                    ).start()

                def reconcile(self):
                    with self._lock:
                        self._start_reap("r0")

                def close(self):
                    self._stop.set()
        """,
    })
    assert _prog(pkg).findings == []


def test_program_graph_flags_cross_module_lock_inversion(tmp_path):
    # neither module inverts on its own — the cycle only exists across the
    # call edges: alloc.reserve holds ALLOC and calls stats.record (takes
    # STATS), while stats.flush holds STATS and calls alloc.touch (takes
    # ALLOC). Per-module DTX011 is lexical-only; the program pass stitches
    # the held-lock reachability.
    pkg = _write_pkg(tmp_path, {
        "alloc.py": """
            import threading

            from pkg.stats import record

            ALLOC_LOCK = threading.Lock()

            def reserve():
                with ALLOC_LOCK:
                    record()

            def touch():
                with ALLOC_LOCK:
                    return 1
        """,
        "stats.py": """
            import threading

            STATS_LOCK = threading.Lock()

            def record():
                with STATS_LOCK:
                    return 2

            def flush():
                from pkg.alloc import touch

                with STATS_LOCK:
                    touch()
        """,
    })
    findings = [f for f in _prog(pkg).findings if f.rule == "DTX011"]
    assert len(findings) == 1
    assert "pkg.alloc.ALLOC_LOCK" in findings[0].message
    assert "pkg.stats.STATS_LOCK" in findings[0].message


def test_program_graph_cross_module_consistent_order_clean(tmp_path):
    pkg = _write_pkg(tmp_path, {
        "alloc.py": """
            import threading

            from pkg.stats import record

            ALLOC_LOCK = threading.Lock()

            def reserve():
                with ALLOC_LOCK:
                    record()

            def touch():
                with ALLOC_LOCK:
                    return 1
        """,
        "stats.py": """
            import threading

            STATS_LOCK = threading.Lock()

            def record():
                with STATS_LOCK:
                    return 2

            def flush():
                with STATS_LOCK:
                    return 3
        """,
    })
    assert [f for f in _prog(pkg).findings if f.rule == "DTX011"] == []


def test_program_graph_adjudicates_handle_dropped_by_callee(tmp_path):
    pkg = _write_pkg(tmp_path, {
        "util.py": """
            def log_proc(proc):
                print(proc.pid)

            def reap(proc):
                proc.wait()
        """,
        "runner.py": """
            import subprocess

            from pkg.util import log_proc, reap

            def leaky():
                proc = subprocess.Popen(["serve"])
                log_proc(proc)  # callee only drops it: still ours to reap

            def fine():
                proc = subprocess.Popen(["serve"])
                log_proc(proc)
                reap(proc)  # a callee disposes: ownership handed over
        """,
    })
    findings = _prog(pkg).findings
    assert [f.rule for f in findings] == ["DTX007"]
    assert "runner.py" in findings[0].path
    assert "`proc`" in findings[0].message


# ----------------------------------------------------------- autofix (--fix)
def test_fix_hoists_jit_and_defers_default_arg():
    src = textwrap.dedent("""
        import jax
        import jax.numpy as jnp


        def compile_steps(n):
            out = []
            for i in range(n):
                step = jax.jit(lambda x: x + 1)
                out.append(step(i))
            return out


        def pad(x, fill=jnp.zeros((4,))):
            return x + fill
    """)
    fixed, res = fix_source(src, "m.py")
    assert res.changed and res.applied == 2 and res.unfixable == 0
    assert lint_source(fixed, path="m.py", config=CFG).findings == []
    # the hoist keeps the binding ABOVE the loop, inside the function
    assert fixed.index("step = jax.jit") < fixed.index("for i in range(n):")
    assert "fill=None" in fixed and "fill = jnp.zeros((4,))" in fixed
    # idempotent: a second pass has nothing left to do
    again, res2 = fix_source(fixed, "m.py")
    assert again == fixed and not res2.changed and res2.applied == 0


def test_fix_refuses_loop_dependent_jit_and_module_constants():
    src = textwrap.dedent("""
        import jax
        import jax.numpy as jnp

        TABLE = jnp.ones((8,))

        def compile_all(fns):
            out = []
            for f in fns:
                g = jax.jit(f)
                out.append(g)
            return out
    """)
    fixed, res = fix_source(src, "m.py")
    # hoisting g=jax.jit(f) would change behavior (f varies per iteration)
    # and a module-level constant has no call-site-compatible rewrite:
    # both are REPORTED unfixable, and the source is left byte-identical
    assert fixed == src and not res.changed
    assert res.applied == 0 and res.unfixable == 2


def test_fix_dtx004_inserts_key_split_for_double_consumption():
    src = textwrap.dedent("""
        import jax


        def sample(key):
            a = jax.random.normal(key, (4,))
            b = jax.random.uniform(key, (4,))
            return a + b
    """)
    fixed, res = fix_source(src, "m.py")
    assert res.changed and res.applied == 1 and res.unfixable == 0
    assert lint_source(fixed, path="m.py", config=CFG).findings == []
    # the split lands BEFORE the first consumption (splitting after it
    # would itself reuse the consumed key) and rebinds the carry
    assert "key, key_split1 = jax.random.split(key)" in fixed
    assert fixed.index("= jax.random.split") < fixed.index("jax.random.normal")
    assert "jax.random.normal(key_split1, (4,))" in fixed
    assert "jax.random.uniform(key, (4,))" in fixed  # consumes the new carry
    # idempotent: nothing left to fix
    again, res2 = fix_source(fixed, "m.py")
    assert again == fixed and not res2.changed and res2.applied == 0


def test_fix_dtx004_loop_reuse_splits_per_iteration():
    src = textwrap.dedent("""
        import jax


        def rollout(key, n):
            out = []
            for i in range(n):
                out.append(jax.random.normal(key, (2,)))
            return out
    """)
    fixed, res = fix_source(src, "m.py")
    assert res.changed and res.applied == 1
    assert lint_source(fixed, path="m.py", config=CFG).findings == []
    # the split sits INSIDE the loop so every iteration advances the carry
    assert fixed.index("for i in range(n):") \
        < fixed.index("key, key_split1 = jax.random.split(key)")
    assert "jax.random.normal(key_split1, (2,))" in fixed
    again, res2 = fix_source(fixed, "m.py")
    assert again == fixed and not res2.changed


def test_fix_dtx004_respects_aliases_and_refuses_bare_imports():
    # module alias: the inserted split reuses the call's own module path
    src = textwrap.dedent("""
        from jax import random as jr


        def sample(key):
            a = jr.normal(key, (4,))
            b = jr.uniform(key, (4,))
            return a + b
    """)
    fixed, res = fix_source(src, "m.py")
    assert res.applied == 1
    assert "key, key_split1 = jr.split(key)" in fixed
    assert lint_source(fixed, path="m.py", config=CFG).findings == []
    # bare from-import: no module path to borrow `split` from — the
    # finding is reported unfixable and the source left untouched
    src2 = textwrap.dedent("""
        from jax.random import normal, uniform


        def sample(key):
            a = normal(key, (4,))
            b = uniform(key, (4,))
            return a + b
    """)
    fixed2, res2 = fix_source(src2, "m.py")
    assert fixed2 == src2 and not res2.changed and res2.unfixable == 1


def test_fix_dtx004_clean_split_idiom_untouched():
    src = textwrap.dedent("""
        import jax


        def sample(key):
            key, sub = jax.random.split(key)
            a = jax.random.normal(sub, (4,))
            b = jax.random.uniform(key, (4,))
            return a + b
    """)
    fixed, res = fix_source(src, "m.py")
    assert fixed == src and not res.changed and res.applied == 0


def test_apply_edits_adjacent_ok_overlap_refused():
    assert apply_edits("abcdef", [SpanEdit(0, 2, "X"),
                                  SpanEdit(2, 4, "Y")]) == "XYef"
    with pytest.raises(OverlapError):
        apply_edits("abcdef", [SpanEdit(0, 3, "X"), SpanEdit(2, 4, "Y")])
    with pytest.raises(OverlapError):
        apply_edits("ab", [SpanEdit(1, 5, "X")])  # out of range


def test_cli_fix_check_then_fix_then_check_clean(tmp_path, capsys):
    p = tmp_path / "m.py"
    src = ("import jax.numpy as jnp\n"
           "def f(x, fill=jnp.zeros((4,))):\n"
           "    return x + fill\n")
    p.write_text(src)
    common = ["--no-config", "--no-baseline", "--no-cache"]
    # --check: reports, exits 1, WRITES NOTHING
    assert dtxlint_main([str(p), "--fix", "--check"] + common) == 1
    assert p.read_text() == src
    # --fix: applies, re-lints clean
    assert dtxlint_main([str(p), "--fix"] + common) == 0
    assert "fill=None" in p.read_text()
    # CI idempotency gate is now green
    assert dtxlint_main([str(p), "--fix", "--check"] + common) == 0
    capsys.readouterr()


# ------------------------------------------------------------- CLI additions
def test_cli_changed_lints_only_files_differing_from_head(tmp_path, capsys,
                                                          monkeypatch):
    monkeypatch.chdir(tmp_path)
    git = ["git", "-c", "user.email=t@t", "-c", "user.name=t"]
    subprocess.run(["git", "init", "-q"], check=True)
    clean = tmp_path / "clean.py"
    clean.write_text("def f():\n    return 1\n")
    # stale.py carries a finding but will be UNCHANGED vs HEAD
    (tmp_path / "stale.py").write_text(
        "import jax.numpy as jnp\nA = jnp.ones((2,))\n")
    subprocess.run(["git", "add", "-A"], check=True)
    subprocess.run(git + ["commit", "-qm", "base"], check=True)

    common = ["--changed", "--no-config", "--no-baseline", "--no-cache"]
    assert dtxlint_main([str(tmp_path)] + common) == 0
    assert "no changed python files" in capsys.readouterr().out

    clean.write_text("import jax.numpy as jnp\nB = jnp.ones((3,))\n")
    assert dtxlint_main([str(tmp_path)] + common) == 1
    out = capsys.readouterr().out
    assert "clean.py" in out and "stale.py" not in out

    # git prints toplevel-relative paths: invoking from a SUBDIRECTORY must
    # still resolve them (the pre-commit shape — a silently-empty run here
    # green-lights dirty code)
    sub = tmp_path / "sub"
    sub.mkdir()
    monkeypatch.chdir(sub)
    assert dtxlint_main([str(tmp_path)] + common) == 1
    assert "clean.py" in capsys.readouterr().out

    # brand-NEW (untracked) files are the most common pre-commit case and
    # never show in `git diff HEAD` — they must still be linted
    monkeypatch.chdir(tmp_path)
    subprocess.run(["git", "add", "-A"], check=True)
    subprocess.run(git + ["commit", "-qm", "wip"], check=True)
    (tmp_path / "fresh.py").write_text(
        "import jax.numpy as jnp\nC = jnp.ones((4,))\n")
    assert dtxlint_main([str(tmp_path)] + common) == 1
    assert "fresh.py" in capsys.readouterr().out


def test_cli_format_json_holds_on_early_exit_paths(tmp_path, capsys,
                                                   monkeypatch):
    # the documented stdout contract (--format json → one schema-versioned
    # object) must hold on the --changed-empty and --fix --check paths too
    monkeypatch.chdir(tmp_path)
    git = ["git", "-c", "user.email=t@t", "-c", "user.name=t"]
    subprocess.run(["git", "init", "-q"], check=True)
    p = tmp_path / "m.py"
    p.write_text("def f():\n    return 1\n")
    subprocess.run(["git", "add", "-A"], check=True)
    subprocess.run(git + ["commit", "-qm", "base"], check=True)

    common = ["--no-config", "--no-baseline", "--no-cache", "--format",
              "json"]
    assert dtxlint_main([str(tmp_path), "--changed"] + common) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["version"] == 2 and doc["findings"] == [] and not doc["failed"]

    p.write_text("import jax\n\nfor i in range(2):\n    g = jax.jit(f)\n"
                 "    g(i)\n")
    assert dtxlint_main([str(p), "--fix", "--check"] + common) == 1
    doc = json.loads(capsys.readouterr().out)
    assert doc["failed"] and doc["fix"]["fixed"] == 1 \
        and doc["would_change"] == ["m.py"]  # display-path convention


def test_fix_dtx008_docstring_only_body_keeps_docstring():
    src = textwrap.dedent("""
    import jax.numpy as jnp


    def pad(x, fill=jnp.zeros((4,))):
        \"\"\"Docstring must stay first.\"\"\"
    """).lstrip()
    fixed, res = fix_source(src, "m.py", config=LintConfig())
    assert res.applied == 1
    mod = ast.parse(fixed)
    fn = mod.body[-1]
    assert ast.get_docstring(fn) == "Docstring must stay first."
    assert "if fill is None:" in fixed


def test_per_file_disable_is_config_level_not_suppression():
    cfg = LintConfig(per_file_disable=("*/generated/*.py:DTX008",
                                       "legacy_*.py:all"))
    src = "import jax.numpy as jnp\nA = jnp.ones((2,))\n"
    res = lint_source(src, path="pkg/generated/tables.py", config=cfg)
    assert res.findings == [] and res.suppressed == 0
    assert lint_source(src, path="legacy_x.py", config=cfg).findings == []
    kept = lint_source(src, path="pkg/other.py", config=cfg)
    assert [f.rule for f in kept.findings] == ["DTX008"]


# --------------------------------------------------------- cache and budget
def test_program_cache_reuse_and_repo_lint_budget(tmp_path):
    cfg = dataclasses.replace(load_config("."),
                              cache=str(tmp_path / "cache.json"))
    t0 = time.perf_counter()
    cold_res, cold_stats = lint_program(["datatunerx_tpu"], config=cfg)
    cold = time.perf_counter() - t0
    assert cold_stats.analyzed == cold_stats.files > 0

    t0 = time.perf_counter()
    warm_res, warm_stats = lint_program(["datatunerx_tpu"], config=cfg)
    warm = time.perf_counter() - t0
    assert warm_stats.reused == warm_stats.files == cold_stats.files
    assert ([f.render() for f in warm_res.findings]
            == [f.render() for f in cold_res.findings])
    # the acceptance bound: full-repo program lint well under ~10s, cached
    # run materially faster (locally ~6s cold vs ~0.1s warm) — coarse on
    # purpose, this is a budget alarm, not a benchmark
    assert cold < 10.0, f"cold program lint took {cold:.1f}s"
    assert warm < cold / 2, f"cache not materially faster ({warm:.2f}s)"


# ------------------------------------------------------- framework behavior
def test_inline_suppression_comment_silences_one_rule():
    src = """
    import jax.numpy as jnp

    A = jnp.ones((2,))  # dtxlint: disable=DTX008 -- frozen table, deliberate
    B = jnp.ones((2,))  # dtxlint: disable=DTX001
    C = jnp.ones((2,))  # dtxlint: disable=all
    """
    res = run(src)
    assert [f.rule for f in res.findings] == ["DTX008"]  # only B still fires
    assert res.suppressed == 2


def test_baseline_roundtrip_and_partition(tmp_path):
    res = run("import jax.numpy as jnp\nA = jnp.ones((2,))\n")
    assert len(res.findings) == 1
    path = tmp_path / "baseline.json"
    save_baseline(str(path), res.findings)
    carried = load_baseline(str(path))
    new, baselined = partition(res.findings, carried)
    assert new == [] and len(baselined) == 1
    # a second, identical finding needs a second baseline entry
    two = res.findings * 2
    new, baselined = partition(two, carried)
    assert len(new) == 1 and len(baselined) == 1
    assert load_baseline(str(tmp_path / "missing.json")) == {}


def test_cli_json_output_and_exit_codes(tmp_path, capsys):
    bad = tmp_path / "bad.py"
    bad.write_text("import jax.numpy as jnp\nA = jnp.ones((2,))\n")
    rc = dtxlint_main([str(bad), "--format", "json", "--no-config",
                       "--no-baseline", "--no-cache"])
    doc = json.loads(capsys.readouterr().out)
    assert rc == 1 and doc["failed"]
    assert doc["version"] == 2  # schema version for CI annotation tooling
    assert doc["cache"] == {"analyzed": 1, "reused": 0}
    assert doc["findings"][0]["rule"] == "DTX008"
    assert doc["findings"][0]["line"] == 2

    good = tmp_path / "good.py"
    good.write_text("def f():\n    return 1\n")
    assert dtxlint_main([str(good), "--no-config", "--no-baseline",
                         "--no-cache"]) == 0


def test_cli_write_baseline_then_clean(tmp_path, capsys):
    bad = tmp_path / "bad.py"
    bad.write_text("import jax.numpy as jnp\nA = jnp.ones((2,))\n")
    base = tmp_path / "base.json"
    assert dtxlint_main([str(bad), "--no-config", "--no-cache", "--baseline",
                         str(base), "--write-baseline"]) == 0
    assert dtxlint_main([str(bad), "--no-config", "--no-cache", "--baseline",
                         str(base)]) == 0
    capsys.readouterr()


def test_select_runs_only_named_rules(tmp_path):
    src = ("import jax\nimport jax.numpy as jnp\n"
           "A = jnp.ones((2,))\n"
           "def f(key):\n"
           "    a = jax.random.normal(key, (2,))\n"
           "    return a + jax.random.uniform(key, (2,))\n")
    p = tmp_path / "m.py"
    p.write_text(src)
    res = lint_paths([str(p)], config=LintConfig())
    assert {f.rule for f in res.findings} == {"DTX004", "DTX008"}
    from datatunerx_tpu.analysis.rules import rules_by_id

    res = lint_paths([str(p)], config=LintConfig(),
                     rules=rules_by_id(["DTX004"]))
    assert {f.rule for f in res.findings} == {"DTX004"}


def test_config_disable_and_toml_subset(tmp_path):
    (tmp_path / "pyproject.toml").write_text(textwrap.dedent("""
        [project]
        name = "x"

        [tool.dtxlint]
        baseline = "b.json"
        disable = ["DTX008"]
        hot-functions = [
            "train_step",
            "hot_*",
        ]
        mesh-axes = ["dp", "tp"]
    """))
    cfg = load_config(str(tmp_path))
    assert cfg.baseline == "b.json"
    assert cfg.disable == ("DTX008",)
    assert cfg.hot_functions == ("train_step", "hot_*")
    assert cfg.mesh_axes == ("dp", "tp")
    res = lint_source("import jax.numpy as jnp\nA = jnp.ones((2,))\n",
                      config=cfg)
    assert res.findings == []  # DTX008 disabled by config


def test_syntax_error_reports_dtx000_not_crash():
    res = lint_source("def broken(:\n", path="x.py")
    assert [f.rule for f in res.findings] == ["DTX000"]


# --------------------------------------------------------------- CI contract
def test_repo_lints_clean_at_head():
    """The acceptance gate: the shipped tree has zero non-suppressed
    findings against the shipped (empty-findings) baseline — with the
    cross-module program pass ON, over the same surface CI lints."""
    cfg = dataclasses.replace(load_config("."), cache="")
    res, _ = lint_program(
        ["datatunerx_tpu", "scripts", "bench.py", "__graft_entry__.py"],
        config=cfg)
    baseline = load_baseline(cfg.resolve(cfg.baseline))
    new, _ = partition(res.findings, baseline)
    assert new == [], "\n".join(f.render() for f in new)
    assert baseline == {}, "policy: the baseline stays empty"


def test_mesh_axes_extracted_from_mesh_module():
    from datatunerx_tpu.analysis.config import mesh_axes_for

    cfg = load_config(".")
    assert set(mesh_axes_for(cfg)) == {"dp", "fsdp", "tp", "sp"}
