"""CSV ingest, column mapping, padding/packing, batch iteration."""

import numpy as np

import jax
import jax.numpy as jnp

from datatunerx_tpu.data import BatchIterator, CsvDataset, get_template
from datatunerx_tpu.data.preprocess import pack_to_block, preprocess_records
from datatunerx_tpu.training.loss import IGNORE_INDEX
from fake_tokenizer import FakeTokenizer


def _write_csv(tmp_path, rows, header=("instruction", "response")):
    p = tmp_path / "data.csv"
    import csv

    with open(p, "w", newline="") as f:
        w = csv.writer(f)
        w.writerow(header)
        w.writerows(rows)
    return str(p)


def test_csv_load_and_column_mapping(tmp_path):
    # Dataset CR maps arbitrary column names -> instruction/response
    # (SURVEY.md §2.3 Dataset features MapTo contract)
    path = _write_csv(
        tmp_path,
        [["hi", "hello"], ["", "skipped"], ["ok", ""]],
        header=("q_col", "a_col"),
    )
    ds = CsvDataset(path, columns={"q_col": "instruction", "a_col": "response"})
    assert len(ds) == 3
    tok = FakeTokenizer()
    exs = ds.encode("default", tok, cutoff_len=64)
    # empty instruction or response rows are skipped (reference train.py:80-82)
    assert len(exs) == 1
    assert all(k in exs[0] for k in ("input_ids", "labels", "attention_mask"))


def test_jsonl_load(tmp_path):
    p = tmp_path / "d.jsonl"
    p.write_text('{"instruction": "a", "response": "b"}\n{"instruction": "c", "response": "d"}\n')
    ds = CsvDataset(str(p))
    assert len(ds) == 2


def test_batch_iterator_shapes_and_determinism(tmp_path):
    tok = FakeTokenizer()
    template = get_template("alpaca", tok)
    records = [{"instruction": f"i{k}", "response": f"r{k} " * (k % 7 + 1)} for k in range(37)]
    exs = preprocess_records(records, template, tok, cutoff_len=64)
    it = BatchIterator(exs, global_batch=8, block_size=64, pad_id=0, seed=5)
    assert it.steps_per_epoch() == 4
    b1 = list(it.epoch(0))
    b2 = list(it.epoch(0))
    assert len(b1) == 4
    for a, b in zip(b1, b2):
        np.testing.assert_array_equal(a["input_ids"], b["input_ids"])  # same seed
    assert b1[0]["input_ids"].shape == (8, 64)
    assert b1[0]["labels"].dtype == np.int32
    # epoch 1 differs (reshuffled)
    b3 = next(iter(it.epoch(1)))
    assert not np.array_equal(b1[0]["input_ids"], b3["input_ids"])


def test_grad_accum_reshape():
    exs = [{"input_ids": [1, 2, 3], "labels": [IGNORE_INDEX, 2, 3]} for _ in range(16)]
    it = BatchIterator(exs, global_batch=8, block_size=8, grad_accum=2, shuffle=False)
    batch = next(iter(it))
    assert batch["input_ids"].shape == (2, 4, 8)


def test_host_slicing():
    exs = [{"input_ids": [k], "labels": [k]} for k in range(32)]
    full = BatchIterator(exs, global_batch=8, block_size=4, shuffle=False)
    h0 = BatchIterator(exs, global_batch=8, block_size=4, shuffle=False, host_id=0, num_hosts=2)
    h1 = BatchIterator(exs, global_batch=8, block_size=4, shuffle=False, host_id=1, num_hosts=2)
    f, a, b = next(iter(full)), next(iter(h0)), next(iter(h1))
    np.testing.assert_array_equal(f["input_ids"], np.concatenate([a["input_ids"], b["input_ids"]]))


def test_packing_density_and_correctness():
    tok = FakeTokenizer()
    template = get_template("vanilla", tok)
    records = [{"instruction": "ab", "response": "cdef" * (k % 5 + 1)} for k in range(40)]
    exs = preprocess_records(records, template, tok, cutoff_len=64)
    packed = pack_to_block(exs, 64, pad_id=0)
    n_rows = packed["input_ids"].shape[0]
    assert n_rows < len(exs)  # actually packs
    # segment boundaries: first label of each segment is IGNORE
    for i in range(n_rows):
        segs = packed["segment_ids"][i]
        for j in np.unique(segs[segs > 0]):
            first = int(np.argmax(segs == j))
            assert packed["labels"][i, first] == IGNORE_INDEX
            # positions restart per segment
            assert packed["positions"][i, first] == 0


def test_packed_batch_trains(tmp_path):
    """End-to-end: packed batch with segment_ids flows through the train step."""
    from datatunerx_tpu.models.config import ModelConfig
    from datatunerx_tpu.models.llama import init_params
    from datatunerx_tpu.training import TrainConfig, Trainer

    cfg = ModelConfig(vocab_size=2048, hidden_size=32, intermediate_size=64,
                      num_layers=2, num_heads=2, num_kv_heads=2, max_seq_len=64,
                      remat="none")
    tok = FakeTokenizer()
    template = get_template("vanilla", tok)
    records = [{"instruction": f"in{k}", "response": "out" * (k % 4 + 1)} for k in range(24)]
    exs = preprocess_records(records, template, tok, cutoff_len=32)
    it = BatchIterator(exs, global_batch=4, block_size=32, pack=True, seed=1)
    tr = Trainer(cfg, TrainConfig(finetuning_type="lora", lora_rank=4,
                                  lora_dropout=0.0, compute_dtype=None,
                                  total_steps=10))
    state = tr.init_state(init_params(cfg, jax.random.PRNGKey(0)), jax.random.PRNGKey(1))
    batch = next(iter(it))
    batch = {k: jnp.asarray(v) for k, v in batch.items()}
    state, m = tr.train_step(state, batch)
    assert np.isfinite(float(m["loss"]))
    assert int(m["tokens"]) > 0


def test_pretrain_records_plain_lm():
    """--stage pt (reference lists pt with no runtime): text column → every
    token labeled, bos/eos framing, no template."""
    from datatunerx_tpu.data.preprocess import preprocess_pretrain_records

    tok = FakeTokenizer()
    out = preprocess_pretrain_records(
        [{"text": "plain corpus line"},
         {"instruction": "a", "response": "b"},  # SFT-shaped fallback
         {"text": ""}],  # empty → skipped (no instruction fallback either)
        tok, cutoff_len=32,
    )
    assert len(out) == 2
    ex = out[0]
    assert ex["labels"] == ex["input_ids"]  # no prompt masking
    assert ex["input_ids"][0] == tok.bos_token_id
    assert ex["input_ids"][-1] == tok.eos_token_id
    # column map applies: corpus column renamed to text
    mapped = preprocess_pretrain_records(
        [{"content": "xyz"}], tok, cutoff_len=32,
        columns={"content": "text"},
    )
    assert len(mapped) == 1


def test_pt_cli_e2e(tmp_path):
    import json as _json

    from datatunerx_tpu.tuning.parser import parse_train_args
    from datatunerx_tpu.tuning.train import run

    data = tmp_path / "corpus.jsonl"
    with open(data, "w") as f:
        for i in range(40):
            f.write(_json.dumps({"text": f"document number {i} body"}) + "\n")
    args = parse_train_args([
        "--model_name_or_path", "preset:debug", "--stage", "pt",
        "--train_path", str(data), "--output_dir", str(tmp_path / "out"),
        "--storage_path", str(tmp_path / "storage"), "--uid", "pt-run",
        "--block_size", "32", "--per_device_train_batch_size", "1",
        "--max_steps", "2", "--bf16", "false", "--logging_steps", "1",
        "--pack_sequences", "true",
    ])
    res = run(args)
    assert res["steps"] == 2
    assert res["manifest"]
