"""Prometheus remote-write wire format + jsonl logging.

The reference pushes snappy-compressed protobuf WriteRequests
(cmd/tuning/prometheus/metrics.py:21-39). These tests decode our hand-rolled
encoding with an independent decoder and verify the reference's
values-in-labels bug is NOT replicated (values are real samples)."""

import json
import struct
import threading
from http.server import BaseHTTPRequestHandler, HTTPServer

from datatunerx_tpu.training.metrics_log import (
    MetricsLogger,
    encode_write_request,
    push_remote_write,
    snappy_compress_literal,
)


# ---------------------------------------------------------- tiny decoders
def snappy_decompress(data: bytes) -> bytes:
    # varint uncompressed length
    n, shift, i = 0, 0, 0
    while True:
        b = data[i]
        n |= (b & 0x7F) << shift
        i += 1
        if not b & 0x80:
            break
        shift += 7
    out = bytearray()
    while i < len(data):
        tag = data[i]
        assert tag & 3 == 0, "test decoder handles literal elements only"
        length = (tag >> 2) + 1
        assert length <= 60
        out += data[i + 1 : i + 1 + length]
        i += 1 + length
    assert len(out) == n
    return bytes(out)


def _read_varint(buf, i):
    n, shift = 0, 0
    while True:
        b = buf[i]
        n |= (b & 0x7F) << shift
        i += 1
        if not b & 0x80:
            return n, i
        shift += 7


def parse_write_request(buf: bytes):
    series = []
    i = 0
    while i < len(buf):
        key, i = _read_varint(buf, i)
        assert key == (1 << 3) | 2  # timeseries
        ln, i = _read_varint(buf, i)
        ts_buf, i = buf[i : i + ln], i + ln
        labels, samples = {}, []
        j = 0
        while j < len(ts_buf):
            k2, j = _read_varint(ts_buf, j)
            ln2, j = _read_varint(ts_buf, j)
            payload, j = ts_buf[j : j + ln2], j + ln2
            if k2 == (1 << 3) | 2:  # Label
                m = 0
                kv = {}
                while m < len(payload):
                    k3, m = _read_varint(payload, m)
                    ln3, m = _read_varint(payload, m)
                    kv[k3 >> 3] = payload[m : m + ln3].decode()
                    m += ln3
                labels[kv[1]] = kv[2]
            elif k2 == (2 << 3) | 2:  # Sample
                m = 0
                val, ts = None, None
                while m < len(payload):
                    k3, m = _read_varint(payload, m)
                    if k3 == (1 << 3) | 1:
                        val = struct.unpack("<d", payload[m : m + 8])[0]
                        m += 8
                    else:
                        ts, m = _read_varint(payload, m)
                samples.append((val, ts))
        series.append((labels, samples))
    return series


def test_write_request_roundtrip():
    body = encode_write_request(
        {"dtx_train_loss": 1.25, "dtx_train_lr": 2e-4},
        {"uid": "abc", "phase": "train"},
        ts_ms=1234567,
    )
    series = parse_write_request(body)
    assert len(series) == 2
    by_name = {labels["__name__"]: (labels, samples) for labels, samples in series}
    labels, samples = by_name["dtx_train_loss"]
    assert labels["uid"] == "abc"
    # the fix for the reference bug: value is the SAMPLE, not a label
    assert samples == [(1.25, 1234567)]
    assert "loss" not in labels.values()


def test_snappy_literal_roundtrip():
    for payload in (b"", b"x", b"hello world" * 50):
        assert snappy_decompress(snappy_compress_literal(payload)) == payload


def test_push_remote_write_live():
    received = {}

    class Handler(BaseHTTPRequestHandler):
        def do_POST(self):
            received["path"] = self.path
            received["headers"] = dict(self.headers)
            received["body"] = self.rfile.read(int(self.headers["Content-Length"]))
            self.send_response(200)
            self.end_headers()

        def log_message(self, *a):
            pass

    srv = HTTPServer(("127.0.0.1", 0), Handler)
    t = threading.Thread(target=srv.handle_request, daemon=True)
    t.start()
    addr = f"http://127.0.0.1:{srv.server_port}"
    ok = push_remote_write(addr, {"dtx_eval_perplexity": 9.5}, {"uid": "u1"})
    t.join(timeout=5)
    srv.server_close()
    assert ok
    assert received["path"] == "/api/v1/write"
    assert received["headers"]["Content-Encoding"] == "snappy"
    assert received["headers"]["X-Prometheus-Remote-Write-Version"] == "0.1.0"
    series = parse_write_request(snappy_decompress(received["body"]))
    assert series[0][0]["__name__"] == "dtx_eval_perplexity"
    assert series[0][1][0][0] == 9.5


def test_push_remote_write_unreachable_never_raises():
    assert push_remote_write("http://127.0.0.1:1", {"m": 1.0}, {}, timeout=0.2) is False


def test_prefetch_advisory_fires_once_on_sustained_stalls(tmp_path, capsys,
                                                          monkeypatch):
    """ROADMAP "input-path stragglers" first slice: sustained
    pipe_step_wait_ms p95 over the threshold logs ONE suggested
    --prefetch_depth (double the current) and states it in the registry."""
    monkeypatch.setenv("DTX_PREFETCH_ADVISE_RECORDS", "5")
    monkeypatch.setenv("DTX_PREFETCH_ADVISE_MS", "5.0")
    lg = MetricsLogger(str(tmp_path), total_steps=100, prefetch_depth=2)
    for step in range(1, 5):
        lg.log_train(step, {"loss": 1.0, "pipe_step_wait_ms": 50.0})
    assert lg.prefetch_advisory is None  # not enough evidence yet
    lg.log_train(5, {"loss": 1.0, "pipe_step_wait_ms": 50.0})
    adv = lg.prefetch_advisory
    assert adv is not None
    assert adv["suggested_prefetch_depth"] == 4 and adv["prefetch_depth"] == 2
    assert adv["pipe_step_wait_ms_p95"] == 50.0
    out = capsys.readouterr().out
    assert out.count("[advice]") == 1
    assert "--prefetch_depth 4" in out
    # once per run: more stalled records never re-advise
    for step in range(6, 12):
        lg.log_train(step, {"loss": 1.0, "pipe_step_wait_ms": 80.0})
    assert capsys.readouterr().out.count("[advice]") == 0
    assert lg.registry.gauge("dtx_train_prefetch_depth_suggested").get() == 4


def test_prefetch_advisory_quiet_on_healthy_pipeline(tmp_path, capsys,
                                                     monkeypatch):
    monkeypatch.setenv("DTX_PREFETCH_ADVISE_RECORDS", "5")
    lg = MetricsLogger(str(tmp_path), total_steps=100, prefetch_depth=2)
    for step in range(1, 20):
        lg.log_train(step, {"loss": 1.0, "pipe_step_wait_ms": 0.2})
    # synchronous runs (no pipeline) never see the signal at all
    lg2 = MetricsLogger(str(tmp_path), total_steps=100)
    lg2.log_train(1, {"loss": 1.0})
    assert lg.prefetch_advisory is None and lg2.prefetch_advisory is None
    assert "[advice]" not in capsys.readouterr().out


def test_metrics_logger_jsonl(tmp_path):
    lg = MetricsLogger(str(tmp_path), total_steps=10)
    lg.log_train(5, {"loss": 2.0, "lr": 1e-4})
    lg.log_eval(5, {"eval_loss": 1.5, "perplexity": 4.48})
    tl = json.loads(open(tmp_path / "watch" / "trainer_log.jsonl").read())
    el = json.loads(open(tmp_path / "watch" / "eval_log.jsonl").read())
    assert tl["percentage"] == 50.0 and tl["loss"] == 2.0
    assert el["perplexity"] == 4.48
