"""Prometheus text-exposition validation for the serving and gateway
/metrics endpoints — parser-based, not substring matching.

A scrape that LOOKS right to a substring assert can still be rejected by a
real Prometheus server: samples before their # TYPE line, duplicate series,
unescaped label values. This parser enforces the exposition-format rules
the scraper cares about and both endpoints must satisfy.
"""

import json
import re
import threading
import urllib.request
from http.server import ThreadingHTTPServer

import pytest

from datatunerx_tpu.gateway.metrics import (
    Histogram,
    Registry,
    escape_label_value,
)
from datatunerx_tpu.obs.metrics import annotation_start
from datatunerx_tpu.gateway.replica_pool import InProcessReplica, ReplicaPool
from datatunerx_tpu.gateway.server import Gateway, serve
from datatunerx_tpu.serving import server as serving_server

SAMPLE_RE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?:\{(?P<labels>.*)\})?"
    r" (?P<value>[^ ]+)(?: (?P<ts>[0-9]+))?$"
)
LABEL_RE = re.compile(
    r'(?P<key>[a-zA-Z_][a-zA-Z0-9_]*)="(?P<val>(?:[^"\\]|\\.)*)"'
)
# OpenMetrics-style exemplar annotation: ` # {labels} value [timestamp]`.
# Emitted on histogram bucket lines (obs.metrics.Histogram exemplars);
# validated here, then stripped before the classic sample parse.
EXEMPLAR_RE = re.compile(
    r' # \{(?P<labels>(?:[a-zA-Z_][a-zA-Z0-9_]*='
    r'"(?:[^"\\]|\\.)*")(?:,[a-zA-Z_][a-zA-Z0-9_]*='
    r'"(?:[^"\\]|\\.)*")*)\} (?P<value>[^ ]+)(?: (?P<ts>[0-9.]+))?$'
)


def parse_exposition(text: str):
    """→ (samples {series_key: float}, types {metric: type}). Asserts the
    format invariants along the way. Exemplar annotations are validated
    (well-formed, bucket lines only) and stripped."""
    assert text.endswith("\n"), "exposition must end with a newline"
    types = {}
    samples = {}
    seen_type_after_sample = set()
    for lineno, line in enumerate(text.splitlines(), 1):
        if not line.strip():
            continue
        pos = -1 if line.startswith("#") else annotation_start(line)
        if pos >= 0:
            m = EXEMPLAR_RE.match(line[pos:])
            assert m, f"line {lineno}: malformed exemplar annotation: {line!r}"
            assert line[:pos].split("{")[0].endswith("_bucket"), \
                f"line {lineno}: exemplar on a non-bucket sample: {line!r}"
            float(m.group("value"))  # exemplar value must parse
            line = line[:pos]
        if line.startswith("# TYPE "):
            parts = line.split()
            assert len(parts) == 4, f"line {lineno}: malformed TYPE: {line!r}"
            _, _, name, mtype = parts
            assert mtype in ("counter", "gauge", "histogram", "summary",
                             "untyped"), f"line {lineno}: bad type {mtype}"
            assert name not in types, \
                f"line {lineno}: duplicate TYPE for {name}"
            assert name not in seen_type_after_sample, \
                f"line {lineno}: TYPE for {name} after its samples"
            types[name] = mtype
            continue
        if line.startswith("#"):
            continue  # HELP / comments
        m = SAMPLE_RE.match(line)
        assert m, f"line {lineno}: unparseable sample: {line!r}"
        name = m.group("name")
        base = re.sub(r"_(bucket|sum|count)$", "", name)
        tracked = base if base in types else name
        seen_type_after_sample.add(tracked)
        assert tracked in types, \
            f"line {lineno}: sample {name} precedes its TYPE line"
        labels = {}
        raw = m.group("labels")
        if raw is not None:
            consumed = LABEL_RE.findall(raw)
            rebuilt = ",".join(f'{k}="{v}"' for k, v in consumed)
            assert rebuilt == raw, \
                f"line {lineno}: malformed/unescaped labels: {raw!r}"
            labels = dict(consumed)
        key = (name, tuple(sorted(labels.items())))
        assert key not in samples, f"line {lineno}: duplicate series {key}"
        value = m.group("value")
        samples[key] = float("inf") if value == "+Inf" else float(value)
    return samples, types


# ------------------------------------------------------------ unit pieces
def test_escape_label_value():
    assert escape_label_value('a"b\\c\nd') == 'a\\"b\\\\c\\nd'


def test_registry_exposes_valid_format_with_nasty_labels():
    reg = Registry()
    reg.counter("t_requests_total", "help text").inc(
        {"path": 'with"quote', "other": "back\\slash\nnewline"})
    reg.gauge("t_depth").set(3)
    h = reg.histogram("t_latency_seconds", buckets=(0.1, 1.0, float("inf")))
    h.observe(0.05)
    h.observe(0.5)
    h.observe(99)
    samples, types = parse_exposition(reg.expose())
    assert types["t_latency_seconds"] == "histogram"
    assert samples[("t_latency_seconds_bucket", (("le", "0.1"),))] == 1
    assert samples[("t_latency_seconds_bucket", (("le", "1.0"),))] == 2
    assert samples[("t_latency_seconds_bucket", (("le", "+Inf"),))] == 3
    assert samples[("t_latency_seconds_count", ())] == 3


def test_histogram_percentile():
    h = Histogram("x", buckets=(0.1, 0.5, 1.0, float("inf")))
    for v in (0.05,) * 90 + (0.4,) * 9 + (2.0,):
        h.observe(v)
    assert h.percentile(0.5) == 0.1
    assert h.percentile(0.95) == 0.5
    assert h.percentile(1.0) == 1.0  # +Inf clamps to largest finite edge


# --------------------------------------------------------- live endpoints
class _StatsEngine:
    """Duck-typed engine exposing the attributes serving._metrics reads."""

    def __init__(self, partial_stats=False):
        self.slots = 4
        self._slot_req = [object(), None, None, None]
        # partial dict: the regression the .get() hardening covers
        self.prefill_stats = ({"full": 2} if partial_stats
                              else {"full": 2, "reuse": 1, "extend": 0})

    def chat(self, messages, **kw):
        return "ok"


@pytest.fixture()
def serving_url():
    old_engine = serving_server.STATE.engine
    old_model = serving_server.STATE.model_path
    serving_server.STATE.engine = _StatsEngine()
    serving_server.STATE.model_path = "preset:test"
    srv = ThreadingHTTPServer(("127.0.0.1", 0), serving_server.Handler)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    yield f"http://127.0.0.1:{srv.server_port}"
    srv.shutdown()
    serving_server.STATE.engine = old_engine
    serving_server.STATE.model_path = old_model


def _scrape(url):
    with urllib.request.urlopen(url + "/metrics", timeout=10) as r:
        assert r.headers["Content-Type"].startswith("text/plain")
        return r.read().decode()


def test_serving_metrics_exposition_valid(serving_url):
    samples, types = parse_exposition(_scrape(serving_url))
    assert types["dtx_serving_up"] == "gauge"
    assert samples[("dtx_serving_up", ())] == 1
    assert samples[("dtx_serving_slots_busy", ())] == 1
    assert samples[("dtx_serving_slots_capacity", ())] == 4
    assert samples[("dtx_serving_prefill_total", (("kind", "full"),))] == 2


def test_serving_metrics_survive_partial_stats_dict(serving_url):
    """A stats dict missing reuse/extend keys must scrape as zeros, not 500
    (the pre-hardening code indexed stats['reuse'] directly)."""
    serving_server.STATE.engine = _StatsEngine(partial_stats=True)
    samples, _ = parse_exposition(_scrape(serving_url))
    assert samples[("dtx_serving_prefix_cache_hits_total", ())] == 0
    assert samples[("dtx_serving_prefix_cache_partial_hits_total", ())] == 0
    assert samples[("dtx_serving_prefix_cache_misses_total", ())] == 2


def test_gateway_metrics_exposition_valid():
    pool = ReplicaPool([InProcessReplica("r0", _StatsEngine()),
                        InProcessReplica("r1", _StatsEngine())])
    gw = Gateway(pool, model_name="preset:test")
    srv = serve(gw, port=0, host="127.0.0.1")
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    url = f"http://127.0.0.1:{srv.server_port}"
    try:
        body = json.dumps({
            "messages": [{"role": "user", "content": "hi"}]}).encode()
        req = urllib.request.Request(
            url + "/chat/completions", data=body,
            headers={"Content-Type": "application/json"}, method="POST")
        urllib.request.urlopen(req, timeout=10).read()
        samples, types = parse_exposition(_scrape(url))
    finally:
        srv.shutdown()
        gw.close()
    assert types["dtx_gateway_request_latency_seconds"] == "histogram"
    assert types["dtx_gateway_replica_circuit_state"] == "gauge"
    assert samples[("dtx_gateway_requests_total", (("code", "200"),))] == 1
    assert samples[("dtx_gateway_queue_depth", ())] == 0
    for r in ("r0", "r1"):
        assert samples[(
            "dtx_gateway_replica_circuit_state",
            (("replica", r), ("state", "closed")))] == 1


def test_parse_exposition_label_value_containing_hash_is_not_exemplar():
    """A label VALUE with ' # ' is data, not an annotation — the parser
    must not flag it as a malformed exemplar (mirrors the gateway scrape
    parser's quote-aware tolerance)."""
    reg = Registry()
    reg.gauge("t_resident", "help").set(1, {"adapter": "a # b"})
    samples, _ = parse_exposition(reg.expose())
    assert samples[("t_resident", (("adapter", "a # b"),))] == 1
