"""The minimum end-to-end slice (SURVEY.md §7.3), fully live on one host:

Dataset/LLM/Hyperparameter CRs → FinetuneJob → controller launches a REAL
training subprocess (LoRA SFT, CPU) → Orbax checkpoint + completion manifest →
LLMCheckpoint CR → REAL serving subprocess answers /chat/completions → built-in
Scoring drives the endpoint → score recorded → job Successful, serving torn
down. Exercises every CRD and both process boundaries.
"""

import csv
import json
import os
import time

import pytest

from datatunerx_tpu.operator.api import (
    Dataset,
    Finetune,
    FinetuneJob,
    Hyperparameter,
    LLM,
    LLMCheckpoint,
    ObjectMeta,
    Scoring,
)
from datatunerx_tpu.operator.backends import LocalProcessBackend
from datatunerx_tpu.operator.manager import build_manager
from datatunerx_tpu.operator.store import ObjectStore
from datatunerx_tpu.serving.local_backend import LocalServingBackend

CPU_ENV = {"JAX_PLATFORMS": "cpu", "PALLAS_AXON_POOL_IPS": ""}


@pytest.mark.slow
def test_minimum_end_to_end_slice(tmp_path):
    storage = str(tmp_path / "storage")
    train_csv = str(tmp_path / "train.csv")
    rows = [("what is 2+2?", "4"), ("capital of France?", "Paris"),
            ("sky color?", "blue"), ("largest planet?", "Jupiter")] * 8
    with open(train_csv, "w", newline="") as f:
        w = csv.writer(f)
        w.writerow(["q", "a"])
        w.writerows(rows)

    os.environ["STORAGE_PATH"] = storage
    store = ObjectStore()
    training = LocalProcessBackend(str(tmp_path / "jobs"), extra_env=CPU_ENV)
    serving = LocalServingBackend(str(tmp_path / "jobs"), extra_env=CPU_ENV)
    mgr = build_manager(store, training, serving, storage_path=storage,
                        with_scoring=True)

    store.create(LLM(metadata=ObjectMeta(name="m"), spec={"path": "preset:debug"}))
    store.create(Hyperparameter(
        metadata=ObjectMeta(name="hp"),
        spec={"parameters": {
            "scheduler": "constant", "optimizer": "adamw", "loRA_R": "4",
            "loRA_Alpha": "16", "loRA_Dropout": "0.0", "learningRate": "1e-2",
            "epochs": "1", "blockSize": "64", "batchSize": "4",
            "gradAccSteps": "1", "PEFT": "true",
        }},
    ))
    store.create(Dataset(
        metadata=ObjectMeta(name="ds"),
        spec={"datasetMetadata": {"datasetInfo": {
            "subsets": [{"splits": {"train": {"file": train_csv}}}],
            "features": [
                {"name": "instruction", "mapTo": "q"},
                {"name": "response", "mapTo": "a"},
            ],
        }}},
    ))
    job = FinetuneJob(metadata=ObjectMeta(name="e2e"), spec={
        "finetune": {
            "name": "e2e-finetune",
            "finetuneSpec": {
                "llm": "m", "dataset": "ds",
                "hyperparameter": {"hyperparameterRef": "hp"},
                "image": {"name": "local", "path": "preset:debug"},
                "node": 1,
            },
        },
    })
    store.create(job)

    deadline = time.time() + 600
    state = ""
    while time.time() < deadline:
        mgr.drain_scheduled(horizon_s=120, max_wall_s=60)
        state = store.get(FinetuneJob, "e2e").status.get("state")
        if state in (FinetuneJob.STATE_SUCCESSFUL, FinetuneJob.STATE_FAILED):
            break
        time.sleep(0.2)

    ft = store.try_get(Finetune, "e2e-finetune")
    job = store.get(FinetuneJob, "e2e")
    diag = ""
    if state != FinetuneJob.STATE_SUCCESSFUL:
        diag = (
            f"job={json.dumps(job.status, default=str)[:800]}\n"
            f"ft={json.dumps(ft.status if ft else {}, default=str)[:400]}\n"
            f"trainer log:\n{training.log_tail('e2e-finetune')}\n"
        )
    assert state == FinetuneJob.STATE_SUCCESSFUL, diag

    # score recorded as a string; serving torn down after eval
    score = job.status["result"]["score"]
    assert isinstance(score, str) and float(score) >= 0.0
    assert serving.status("e2e") == "NotFound"
    # provenance chain complete
    ref = ft.status["llmCheckpoint"]["llmCheckpointRef"]
    ckpt = store.get(LLMCheckpoint, ref)
    assert os.path.isdir(ckpt.spec["checkpoint"]) or os.path.exists(ckpt.spec["checkpoint"])
    scoring = store.get(Scoring, "e2e")
    assert scoring.status["score"] == score
    assert len(scoring.status["details"]) == 5


@pytest.mark.slow
def test_concurrent_experiment_two_live_jobs(tmp_path):
    """FinetuneExperiment fan-out with TWO live training subprocesses running
    concurrently (north-star metric #2 shape: concurrent FinetuneJobs on
    shared hardware), aggregated to bestVersion."""
    from datatunerx_tpu.operator.api import FinetuneExperiment

    storage = str(tmp_path / "storage")
    train_csv = str(tmp_path / "train.csv")
    rows = [("q %d" % k, "a %d" % k) for k in range(32)]
    with open(train_csv, "w", newline="") as f:
        w = csv.writer(f)
        w.writerow(["q", "a"])
        w.writerows(rows)

    os.environ["STORAGE_PATH"] = storage
    store = ObjectStore()
    training = LocalProcessBackend(str(tmp_path / "jobs"), extra_env=CPU_ENV)
    serving = LocalServingBackend(str(tmp_path / "jobs"), extra_env=CPU_ENV)
    mgr = build_manager(store, training, serving, storage_path=storage,
                        with_scoring=True)

    store.create(LLM(metadata=ObjectMeta(name="m"), spec={"path": "preset:debug"}))
    store.create(Hyperparameter(
        metadata=ObjectMeta(name="hp"),
        spec={"parameters": {
            "scheduler": "constant", "optimizer": "adamw", "loRA_R": "4",
            "loRA_Dropout": "0.0", "learningRate": "1e-2", "epochs": "1",
            "blockSize": "64", "batchSize": "4", "PEFT": "true",
        }},
    ))
    store.create(Dataset(
        metadata=ObjectMeta(name="ds"),
        spec={"datasetMetadata": {"datasetInfo": {
            "subsets": [{"splits": {"train": {"file": train_csv}}}],
            "features": [{"name": "instruction", "mapTo": "q"},
                         {"name": "response", "mapTo": "a"}],
        }}},
    ))

    def job_entry(name, lr):
        return {"name": name, "spec": {"finetune": {
            "name": f"{name}-finetune",
            "finetuneSpec": {
                "llm": "m", "dataset": "ds",
                "hyperparameter": {"hyperparameterRef": "hp",
                                   "overrides": {"learningRate": lr}},
                "image": {"name": "local", "path": "preset:debug"},
                "node": 1,
            },
        }}}

    exp = FinetuneExperiment(
        metadata=ObjectMeta(name="exp-live"),
        spec={"finetuneJobs": [job_entry("cj1", "1e-2"), job_entry("cj2", "5e-3")]},
    )
    store.create(exp)

    deadline = time.time() + 900
    state = ""
    overlapped = False
    while time.time() < deadline:
        mgr.drain_scheduled(horizon_s=120, max_wall_s=60)
        running = [n for n in ("cj1-finetune", "cj2-finetune")
                   if training.status(n) == "Running"]
        overlapped = overlapped or len(running) == 2
        state = store.get(FinetuneExperiment, "exp-live").status.get("state", "")
        if state in ("Success", "Failed"):
            break
        time.sleep(0.2)

    exp = store.get(FinetuneExperiment, "exp-live")
    diag = json.dumps(exp.status, default=str)[:1200]
    assert state == "Success", diag + "\n" + training.log_tail("cj1-finetune")
    assert overlapped, "jobs never ran concurrently"
    best = exp.status["bestVersion"]
    assert best["hyperparameter"] == "hp"
    scores = {s["name"]: s["status"]["result"]["score"]
              for s in exp.status["jobsStatus"]}
    assert best["score"] == max(scores.values(), key=float)


@pytest.mark.slow
def test_four_concurrent_jobs_through_slice_placement(tmp_path):
    """North-star metric #2 at target width (VERDICT r2 next-round #5): a
    FinetuneExperiment of FOUR jobs over a 4-slice SlicePool, live CPU
    training backends — all four run concurrently on DISJOINT slices, each
    placement is recorded in Finetune.status and released on completion, and
    bestVersion aggregates across the sweep (reference fan-out
    finetuneexperiment_controller.go:123-152)."""
    from datatunerx_tpu.operator.api import FinetuneExperiment
    from datatunerx_tpu.operator.placement import Slice, SlicePool

    storage = str(tmp_path / "storage")
    train_csv = str(tmp_path / "train.csv")
    rows = [("q %d" % k, "a %d" % k) for k in range(32)]
    with open(train_csv, "w", newline="") as f:
        w = csv.writer(f)
        w.writerow(["q", "a"])
        w.writerows(rows)

    os.environ["STORAGE_PATH"] = storage
    store = ObjectStore()
    training = LocalProcessBackend(str(tmp_path / "jobs"), extra_env=CPU_ENV)
    serving = LocalServingBackend(str(tmp_path / "jobs"), extra_env=CPU_ENV)
    pool = SlicePool([
        Slice(f"sub{i}", topology="2x4", chips=8,
              node_selector={"cloud.google.com/gke-nodepool": f"tpu-sub{i}"})
        for i in range(4)
    ])  # a v5e-32 carved into 4 × 2x4 sub-slices (BASELINE.md row 3)
    mgr = build_manager(store, training, serving, storage_path=storage,
                        with_scoring=True, slice_pool=pool)

    store.create(LLM(metadata=ObjectMeta(name="m"),
                     spec={"path": "preset:debug"}))
    store.create(Hyperparameter(
        metadata=ObjectMeta(name="hp"),
        spec={"parameters": {
            "scheduler": "constant", "optimizer": "adamw", "loRA_R": "4",
            "loRA_Dropout": "0.0", "learningRate": "1e-2", "epochs": "1",
            "blockSize": "64", "batchSize": "4", "PEFT": "true",
        }},
    ))
    store.create(Dataset(
        metadata=ObjectMeta(name="ds"),
        spec={"datasetMetadata": {"datasetInfo": {
            "subsets": [{"splits": {"train": {"file": train_csv}}}],
            "features": [{"name": "instruction", "mapTo": "q"},
                         {"name": "response", "mapTo": "a"}],
        }}},
    ))

    lrs = ["1e-2", "5e-3", "2e-3", "1e-3"]
    names = [f"q{i}" for i in range(4)]

    def job_entry(name, lr):
        return {"name": name, "spec": {
            "finetune": {
                "name": f"{name}-finetune",
                "finetuneSpec": {
                    "llm": "m", "dataset": "ds",
                    "hyperparameter": {"hyperparameterRef": "hp",
                                       "overrides": {"learningRate": lr}},
                    "image": {"name": "local", "path": "preset:debug"},
                    "node": 1,
                },
            },
            # single-slot serving: 4 concurrent batched engines compiling
            # at once starves a CPU box; slot scaling is covered by
            # scripts/bench_serving.py + test_batched_engine
            "serveConfig": {"slots": 1},
        }}

    store.create(FinetuneExperiment(
        metadata=ObjectMeta(name="exp4"),
        spec={"finetuneJobs": [job_entry(n, lr)
                               for n, lr in zip(names, lrs)]},
    ))

    deadline = time.time() + 2400
    state = ""
    max_overlap = 0
    seen_placements: dict = {}
    while time.time() < deadline:
        mgr.drain_scheduled(horizon_s=120, max_wall_s=60)
        running = [n for n in names
                   if training.status(f"{n}-finetune") == "Running"]
        max_overlap = max(max_overlap, len(running))
        for n in names:
            ft = store.try_get(Finetune, f"{n}-finetune")
            placement = (ft.status.get("placement") or {}) if ft else {}
            if placement.get("name"):
                seen_placements[n] = placement["name"]
        state = store.get(FinetuneExperiment, "exp4").status.get("state", "")
        if state in ("Success", "Failed"):
            break
        time.sleep(0.2)

    exp = store.get(FinetuneExperiment, "exp4")
    diag = json.dumps(exp.status, default=str)[:1500]
    assert state == "Success", diag + "\n" + training.log_tail("q0-finetune")
    assert max_overlap == 4, (
        f"all four jobs must run concurrently (max overlap {max_overlap})")
    # disjoint placement: four jobs, four distinct sub-slices
    assert len(seen_placements) == 4 and \
        len(set(seen_placements.values())) == 4, seen_placements
    # placements released once the sweep is done
    assert pool.free_count() == 4
    best = exp.status["bestVersion"]
    scores = {s["name"]: s["status"]["result"]["score"]
              for s in exp.status["jobsStatus"]}
    assert len(scores) == 4
    assert best["score"] == max(scores.values(), key=float)
