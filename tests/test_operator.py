"""Controller state-machine tests (envtest-equivalent, SURVEY.md §4.1):
reconcilers run against the in-memory store + fake backends; assertions cover
the transitions in SURVEY.md §2.3/§3 — including the key one: Scoring.Score
set ⇒ job Successful + serving torn down (reference
finetunejob_controller.go:485-508)."""


import pytest

from datatunerx_tpu.operator.api import (
    Dataset,
    Finetune,
    FinetuneExperiment,
    FinetuneJob,
    Hyperparameter,
    LLM,
    LLMCheckpoint,
    ObjectMeta,
    Scoring,
)
from datatunerx_tpu.operator.backends import FakeServingBackend, FakeTrainingBackend
from datatunerx_tpu.operator.manager import build_manager
from datatunerx_tpu.operator.store import AlreadyExists, Conflict, NotFound, ObjectStore
from datatunerx_tpu.operator.webhooks import AdmissionError, AdmittingStore, admit
from datatunerx_tpu.training.checkpoint import write_manifest


# ------------------------------------------------------------ fixtures

def _seed_deps(store, ns="default"):
    store.create(LLM(metadata=ObjectMeta(name="llama2-7b", namespace=ns),
                     spec={"path": "/models/llama2-7b"}))
    store.create(Hyperparameter(
        metadata=ObjectMeta(name="hp-a", namespace=ns),
        spec={"parameters": {
            "scheduler": "cosine", "optimizer": "adamw", "loRA_R": "8",
            "loRA_Alpha": "32", "loRA_Dropout": "0.1", "learningRate": "2e-4",
            "epochs": "1", "blockSize": "512", "batchSize": "2",
            "gradAccSteps": "1", "PEFT": "true", "FP16": "false",
        }},
    ))
    store.create(Dataset(
        metadata=ObjectMeta(name="ds-a", namespace=ns),
        spec={"datasetMetadata": {"datasetInfo": {
            "subsets": [{"splits": {
                "train": {"file": "/data/train.csv"},
                "validate": {"file": "/data/val.csv"},
            }}],
            "features": [
                {"name": "instruction", "mapTo": "q"},
                {"name": "response", "mapTo": "a"},
            ],
        }}},
    ))


def _job_spec(suffix=""):
    return {
        "finetune": {
            "name": f"job{suffix}-finetune",
            "finetuneSpec": {
                "llm": "llama2-7b",
                "dataset": "ds-a",
                "hyperparameter": {"hyperparameterRef": "hp-a"},
                "image": {"name": "img", "path": "/models/llama2-7b"},
                "node": 1,
            },
        },
    }


@pytest.fixture()
def world(tmp_path):
    store = ObjectStore()
    training = FakeTrainingBackend()
    serving = FakeServingBackend()
    mgr = build_manager(store, training, serving,
                        storage_path=str(tmp_path / "storage"),
                        with_scoring=False)
    _seed_deps(store)
    return store, training, serving, mgr, str(tmp_path / "storage")


# ---------------------------------------------------------------- store

def test_store_crud_conflict_and_cascade():
    store = ObjectStore()
    llm = LLM(metadata=ObjectMeta(name="m"))
    created = store.create(llm)
    with pytest.raises(AlreadyExists):
        store.create(llm)

    stale = store.get(LLM, "m")
    fresh = store.get(LLM, "m")
    fresh.spec["x"] = 1
    store.update(fresh)
    stale.spec["x"] = 2
    with pytest.raises(Conflict):
        store.update(stale)

    # owner cascade
    child = Scoring(metadata=ObjectMeta(name="c"))
    child.metadata.owner_references.append(
        {"kind": "LLM", "name": "m", "uid": created.metadata.uid})
    store.create(child)
    store.delete(LLM, "m")
    with pytest.raises(NotFound):
        store.get(Scoring, "c")


def test_store_finalizer_gated_deletion():
    store = ObjectStore()
    ft = Finetune(metadata=ObjectMeta(name="f", finalizers=["x/y"]))
    store.create(ft)
    store.delete(Finetune, "f")
    obj = store.get(Finetune, "f")  # still present
    assert obj.metadata.deletion_timestamp is not None
    obj.metadata.finalizers.remove("x/y")
    store.update(obj)
    with pytest.raises(NotFound):
        store.get(Finetune, "f")


def test_store_persistence_roundtrip(tmp_path):
    d = str(tmp_path / "objs")
    store = ObjectStore(persist_dir=d)
    _seed_deps(store)
    store2 = ObjectStore(persist_dir=d)
    assert store2.get(Hyperparameter, "hp-a").spec["parameters"]["loRA_R"] == "8"
    assert len(store2.list(Dataset)) == 1


# -------------------------------------------------------------- webhooks

def test_webhook_validation():
    bad = Hyperparameter(metadata=ObjectMeta(name="h"),
                         spec={"parameters": {"scheduler": "warp-speed"}})
    with pytest.raises(AdmissionError, match="scheduler"):
        admit(bad)
    bad2 = Hyperparameter(metadata=ObjectMeta(name="h"),
                          spec={"parameters": {"int4": "true", "int8": "true"}})
    with pytest.raises(AdmissionError, match="mutually exclusive"):
        admit(bad2)
    bad3 = Dataset(metadata=ObjectMeta(name="d"), spec={})
    with pytest.raises(AdmissionError, match="subsets"):
        admit(bad3)
    bad4 = FinetuneJob(metadata=ObjectMeta(name="j"),
                       spec={"finetune": {"finetuneSpec": {"llm": "x"}}})
    with pytest.raises(AdmissionError, match="dataset"):
        admit(bad4)


def test_webhook_defaulting():
    hp = Hyperparameter(metadata=ObjectMeta(name="h"), spec={})
    admit(hp)
    assert hp.spec["parameters"]["loRA_R"] == "8"
    assert hp.spec["parameters"]["scheduler"] == "cosine"


def test_admitting_store_rejects():
    store = AdmittingStore(ObjectStore())
    with pytest.raises(AdmissionError):
        store.create(Dataset(metadata=ObjectMeta(name="d"), spec={}))


# -------------------------------------------------- finetune controller

def test_finetune_lifecycle_success(world):
    store, training, serving, mgr, storage = world
    ft = Finetune(metadata=ObjectMeta(name="run1"), spec={
        "llm": "llama2-7b", "dataset": "ds-a",
        "hyperparameter": {"hyperparameterRef": "hp-a",
                           "overrides": {"learningRate": "5e-4"}},
        "image": {"name": "img", "path": "/models/llama2-7b"},
        "node": 2,
    })
    store.create(ft)
    mgr.run_until_idle()
    obj = store.get(Finetune, "run1")
    assert obj.status["state"] == Finetune.STATE_PENDING
    # backend got the job with merged hyperparameters + our CLI contract
    spec = training.jobs["run1"]
    assert spec["num_hosts"] == 2
    args = " ".join(spec["args"])
    assert "--learning_rate 5e-4" in args  # override won
    assert "--lr_scheduler_type cosine" in args
    assert "--num_workers 2" in args
    assert "--columns" in args

    training.set_state("run1", "Running")
    mgr.enqueue("Finetune", "default", "run1")
    mgr.run_until_idle()
    assert store.get(Finetune, "run1").status["state"] == Finetune.STATE_RUNNING

    # completion: manifest appears on shared storage, job succeeds
    write_manifest(storage, obj.metadata.uid, "/storage/ckpt/42",
                   metrics={"loss": 1.5})
    training.set_state("run1", "Succeeded")
    mgr.enqueue("Finetune", "default", "run1")
    mgr.run_until_idle()

    obj = store.get(Finetune, "run1")
    assert obj.status["state"] == Finetune.STATE_SUCCESSFUL
    ref = obj.status["llmCheckpoint"]["llmCheckpointRef"]
    ckpt = store.get(LLMCheckpoint, ref)
    # provenance deep-copies (reference finetune_controller.go:621-653)
    assert ckpt.spec["hyperparameter"]["spec"]["parameters"]["loRA_R"] == "8"
    assert ckpt.spec["dataset"]["spec"]["datasetMetadata"]
    assert ckpt.spec["checkpoint"] == "/storage/ckpt/42"


def test_finetune_missing_deps_pending(world):
    store, training, serving, mgr, storage = world
    ft = Finetune(metadata=ObjectMeta(name="run2"), spec={
        "llm": "nope", "dataset": "ds-a",
        "hyperparameter": {"hyperparameterRef": "hp-a"},
        "image": {"path": "/m"},
    })
    store.create(ft)
    mgr.run_until_idle()
    assert store.get(Finetune, "run2").status["state"] == Finetune.STATE_PENDING
    assert "run2" not in training.jobs


def test_finetune_failure(world):
    store, training, serving, mgr, storage = world
    ft = Finetune(metadata=ObjectMeta(name="run3"), spec={
        "llm": "llama2-7b", "dataset": "ds-a",
        "hyperparameter": {"hyperparameterRef": "hp-a"},
        "image": {"path": "/m"},
    })
    store.create(ft)
    mgr.run_until_idle()
    training.set_state("run3", "Failed")
    mgr.enqueue("Finetune", "default", "run3")
    mgr.run_until_idle()
    assert store.get(Finetune, "run3").status["state"] == Finetune.STATE_FAILED


def test_finetune_deletion_tears_down_job(world):
    store, training, serving, mgr, storage = world
    ft = Finetune(metadata=ObjectMeta(name="run4"), spec={
        "llm": "llama2-7b", "dataset": "ds-a",
        "hyperparameter": {"hyperparameterRef": "hp-a"},
        "image": {"path": "/m"},
    })
    store.create(ft)
    mgr.run_until_idle()
    store.delete(Finetune, "run4")
    mgr.run_until_idle()
    assert "run4" in training.deleted
    with pytest.raises(NotFound):
        store.get(Finetune, "run4")


# ----------------------------------------------- finetunejob controller

def _drive_job_to_serve(store, training, serving, mgr, storage, name="jobA"):
    job = FinetuneJob(metadata=ObjectMeta(name=name), spec=_job_spec())
    job.spec["finetune"]["name"] = f"{name}-finetune"
    store.create(job)
    mgr.run_until_idle()
    mgr.drain_scheduled()

    ft_name = f"{name}-finetune"
    ft = store.get(Finetune, ft_name)
    assert store.get(FinetuneJob, name).status["state"] == FinetuneJob.STATE_FINETUNE

    # train completes
    training.set_state(ft_name, "Succeeded")
    write_manifest(storage, ft.metadata.uid, "/storage/ckpt/7", metrics={"loss": 1.0})
    mgr.enqueue("Finetune", "default", ft_name)
    mgr.run_until_idle()
    mgr.drain_scheduled()

    job = store.get(FinetuneJob, name)
    assert job.status["state"] == FinetuneJob.STATE_SERVE
    assert name in serving.apps
    return job


def test_finetunejob_full_pipeline(world):
    store, training, serving, mgr, storage = world
    name = "jobA"
    _drive_job_to_serve(store, training, serving, mgr, storage, name)

    # serving healthy -> Scoring CR created with inference URL
    serving.set_state(name, "HEALTHY")
    mgr.enqueue("FinetuneJob", "default", name)
    mgr.run_until_idle()
    mgr.drain_scheduled()
    scoring = store.get(Scoring, name)
    assert scoring.spec["inferenceService"].endswith("/chat/completions")
    assert scoring.spec["plugin"]["loadPlugin"] is False

    # score lands -> job Successful + serving torn down (the key transition)
    scoring.status["score"] = "87.5"
    store.update(scoring)
    mgr.run_until_idle()
    mgr.drain_scheduled()
    job = store.get(FinetuneJob, name)
    assert job.status["state"] == FinetuneJob.STATE_SUCCESSFUL
    assert job.status["result"]["score"] == "87.5"
    assert job.status["result"]["modelExportResult"] is True
    assert name in serving.deleted

    # back-references recorded (reference :213-257)
    assert name in store.get(LLM, "llama2-7b").status["referenceFinetuneName"]
    assert name in store.get(Dataset, "ds-a").status["referenceFinetuneName"]


def test_finetunejob_plugin_scoring(world):
    store, training, serving, mgr, storage = world
    name = "jobP"
    job_spec = _job_spec("P")
    job_spec["scoringPluginConfig"] = {"name": "my-plugin", "parameters": '{"k": 1}'}
    job = FinetuneJob(metadata=ObjectMeta(name=name), spec=job_spec)
    store.create(job)
    mgr.run_until_idle()
    mgr.drain_scheduled()
    ft_name = f"job{'P'}-finetune"
    ft = store.get(Finetune, ft_name)
    training.set_state(ft_name, "Succeeded")
    write_manifest(storage, ft.metadata.uid, "/ckpt", metrics={})
    mgr.enqueue("Finetune", "default", ft_name)
    mgr.run_until_idle()
    mgr.drain_scheduled()
    serving.set_state(name, "HEALTHY")
    mgr.enqueue("FinetuneJob", "default", name)
    mgr.run_until_idle()
    scoring = store.get(Scoring, name)
    assert scoring.spec["plugin"] == {
        "loadPlugin": True, "name": "my-plugin", "parameters": '{"k": 1}'}


def test_finetunejob_failure_propagates(world):
    store, training, serving, mgr, storage = world
    job = FinetuneJob(metadata=ObjectMeta(name="jobF"), spec=_job_spec("F"))
    job.spec["finetune"]["name"] = "jobF-finetune"
    # no retries: this test asserts the failure PROPAGATION path (the retry
    # path has its own tests); the spec default is now k8s-style backoff
    job.spec["finetune"]["finetuneSpec"]["backoffLimit"] = 0
    store.create(job)
    mgr.run_until_idle()
    mgr.drain_scheduled()
    training.set_state("jobF-finetune", "Failed")
    mgr.enqueue("Finetune", "default", "jobF-finetune")
    mgr.run_until_idle()
    mgr.drain_scheduled()
    assert store.get(FinetuneJob, "jobF").status["state"] == FinetuneJob.STATE_FAILED


# --------------------------------------- finetuneexperiment controller

def _experiment(names):
    return FinetuneExperiment(
        metadata=ObjectMeta(name="exp1"),
        spec={"finetuneJobs": [{"name": n, "spec": _job_spec(n)} for n in names]},
    )


def _finish_job(store, training, serving, mgr, storage, name, score):
    ft_name = f"job{name}-finetune"
    ft = store.get(Finetune, ft_name)
    training.set_state(ft_name, "Succeeded")
    write_manifest(storage, ft.metadata.uid, f"/ckpt/{name}", metrics={})
    mgr.enqueue("Finetune", "default", ft_name)
    mgr.run_until_idle()
    mgr.drain_scheduled()
    serving.set_state(name, "HEALTHY")
    mgr.enqueue("FinetuneJob", "default", name)
    mgr.run_until_idle()
    sc = store.get(Scoring, name)
    sc.status["score"] = score
    store.update(sc)
    mgr.run_until_idle()
    mgr.drain_scheduled()


def test_experiment_fanout_and_best_version(world):
    store, training, serving, mgr, storage = world
    exp = _experiment(["expj1", "expj2"])
    # fix child names to match helper expectations
    for e in exp.spec["finetuneJobs"]:
        e["spec"]["finetune"]["name"] = f"job{e['name']}-finetune"
    store.create(exp)
    mgr.run_until_idle()
    mgr.drain_scheduled()
    assert store.get(FinetuneExperiment, "exp1").status["state"] == \
        FinetuneExperiment.STATE_PROCESSING
    assert store.get(FinetuneJob, "expj1") and store.get(FinetuneJob, "expj2")

    _finish_job(store, training, serving, mgr, storage, "expj1", "55.0")
    _finish_job(store, training, serving, mgr, storage, "expj2", "91.0")
    mgr.drain_scheduled()

    exp = store.get(FinetuneExperiment, "exp1")
    assert exp.status["state"] == FinetuneExperiment.STATE_SUCCESS
    assert exp.status["bestVersion"]["score"] == "91.0"
    assert exp.status["bestVersion"]["dataset"] == "ds-a"
    by_name = {s["name"]: s["status"]["state"] for s in exp.status["jobsStatus"]}
    assert by_name == {"expj1": "Successful", "expj2": "Successful"}


def test_experiment_pause_resume(world):
    store, training, serving, mgr, storage = world
    exp = _experiment(["pj1"])
    store.create(exp)
    mgr.run_until_idle()
    mgr.drain_scheduled()
    assert store.try_get(FinetuneJob, "pj1") is not None

    exp = store.get(FinetuneExperiment, "exp1")
    exp.spec["pending"] = True
    store.update(exp)
    mgr.run_until_idle()
    mgr.drain_scheduled()
    exp = store.get(FinetuneExperiment, "exp1")
    assert exp.status["state"] == FinetuneExperiment.STATE_PENDING
    assert store.try_get(FinetuneJob, "pj1") is None  # children deleted

    exp.spec["pending"] = False
    store.update(exp)
    mgr.run_until_idle()
    mgr.drain_scheduled()
    assert store.try_get(FinetuneJob, "pj1") is not None  # resumed


def test_experiment_all_failed(world):
    store, training, serving, mgr, storage = world
    exp = _experiment(["fj1"])
    store.create(exp)
    mgr.run_until_idle()
    mgr.drain_scheduled()
    training.set_state("jobfj1-finetune", "Failed")
    mgr.enqueue("Finetune", "default", "jobfj1-finetune")
    mgr.run_until_idle()
    mgr.drain_scheduled()
    exp = store.get(FinetuneExperiment, "exp1")
    assert exp.status["state"] == FinetuneExperiment.STATE_FAILED


def test_finetune_bounded_retry_with_resume(world):
    """SURVEY §5.3: backoffLimit retries re-submit the job; the trainer resumes
    from its checkpoint (same uid -> same storage key)."""
    store, training, serving, mgr, storage = world
    ft = Finetune(metadata=ObjectMeta(name="run-retry"), spec={
        "llm": "llama2-7b", "dataset": "ds-a",
        "hyperparameter": {"hyperparameterRef": "hp-a"},
        "image": {"path": "/m"}, "backoffLimit": 2,
    })
    store.create(ft)
    mgr.run_until_idle()

    training.set_state("run-retry", "Failed")
    mgr.enqueue("Finetune", "default", "run-retry")
    mgr.run_until_idle()
    mgr.drain_scheduled()
    obj = store.get(Finetune, "run-retry")
    assert obj.status["retries"] == 1
    assert obj.status["state"] != Finetune.STATE_FAILED
    assert "run-retry" in training.jobs  # resubmitted

    # second failure, then success
    training.set_state("run-retry", "Failed")
    mgr.enqueue("Finetune", "default", "run-retry")
    mgr.run_until_idle()
    mgr.drain_scheduled()
    assert store.get(Finetune, "run-retry").status["retries"] == 2

    write_manifest(storage, obj.metadata.uid, "/ckpt/r", metrics={})
    training.set_state("run-retry", "Succeeded")
    mgr.enqueue("Finetune", "default", "run-retry")
    mgr.run_until_idle()
    assert store.get(Finetune, "run-retry").status["state"] == Finetune.STATE_SUCCESSFUL

    # exhausting the limit fails terminally
    ft2 = Finetune(metadata=ObjectMeta(name="run-exhaust"), spec={
        "llm": "llama2-7b", "dataset": "ds-a",
        "hyperparameter": {"hyperparameterRef": "hp-a"},
        "image": {"path": "/m"}, "backoffLimit": 0,
    })
    store.create(ft2)
    mgr.run_until_idle()
    training.set_state("run-exhaust", "Failed")
    mgr.enqueue("Finetune", "default", "run-exhaust")
    mgr.run_until_idle()
    assert store.get(Finetune, "run-exhaust").status["state"] == Finetune.STATE_FAILED


def test_trainer_args_render_tpu_quant_params():
    """Hyperparameter TPU additions flow to the trainer CLI: quantImpl
    selects the fused Pallas kernels (--quant_impl, round 3) next to int4
    and attention (the bitsandbytes kernel choice the reference hardwires,
    reference train.py:224-234)."""
    from datatunerx_tpu.operator.generate import build_trainer_args

    ft = Finetune(metadata=ObjectMeta(name="qi"), spec={
        "llm": "m", "dataset": "d",
        "hyperparameter": {"hyperparameterRef": "hp"},
        "image": {"path": "/m"},
    })
    ds_spec = {"datasetMetadata": {"datasetInfo": {
        "subsets": [{"splits": {"train": {"file": "/t.csv"}}}]}}}
    args = build_trainer_args(ft, ds_spec, {
        "int4": "true", "quantImpl": "pallas", "attention": "flash"})
    s = " ".join(str(a) for a in args)
    assert "--quantization int4" in s
    assert "--quant_impl pallas" in s
    assert "--attention flash" in s
