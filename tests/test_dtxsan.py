"""dtxsan (analysis/sanitizers): one deliberate-bug and one clean fixture
per runtime sanitizer, plus the shared plumbing — inline suppression, the
dtxlint-baseline contract, the JSON report shape, and idempotent re-scans.

Every test restores the process-global singletons to their prior state so
the suite behaves identically with and without DTX_SAN=1 (where the pytest
plugin has already installed them for the whole session), and deliberate
findings go into FRESH collectors so they never leak into the session
report of a sanitizer-enabled CI run.
"""

import contextlib
import json
import subprocess
import sys
import textwrap
import threading

import pytest

from datatunerx_tpu.analysis.baseline import save_baseline
from datatunerx_tpu.analysis.sanitizers import report as san_report
from datatunerx_tpu.analysis.sanitizers import runtime as san_runtime
from datatunerx_tpu.analysis.sanitizers.compile import (
    COMPILE_SANITIZER,
    CompileBudgetExceeded,
    compile_budget,
)
from datatunerx_tpu.analysis.sanitizers.lockorder import (
    LOCK_SANITIZER,
    LockOrderViolation,
)
from datatunerx_tpu.analysis.sanitizers.runtime import Collector
from datatunerx_tpu.analysis.sanitizers.threads import (
    THREAD_SANITIZER,
    allow_thread,
)

REPO = san_runtime.REPO_ROOT


@contextlib.contextmanager
def _lock_san():
    """Install the lock sanitizer with an empty graph; afterwards restore
    the pre-test enabled state and drop the deliberate edges."""
    was = LOCK_SANITIZER.enabled
    LOCK_SANITIZER.install()
    LOCK_SANITIZER.reset()
    try:
        yield LOCK_SANITIZER
    finally:
        LOCK_SANITIZER.reset()
        if not was:
            LOCK_SANITIZER.uninstall()


@contextlib.contextmanager
def _thread_san():
    was = THREAD_SANITIZER.installed
    THREAD_SANITIZER.install()
    try:
        yield THREAD_SANITIZER
    finally:
        if not was:
            THREAD_SANITIZER.uninstall()


# ------------------------------------------------------ SAN001 lock order
def test_lockorder_abba_cycle_reports_both_stacks():
    with _lock_san() as san:
        lock_a = threading.Lock()
        lock_b = threading.Lock()

        def order_ab():
            with lock_a:
                with lock_b:
                    pass

        def order_ba():
            with lock_b:
                with lock_a:
                    pass

        t1 = threading.Thread(target=order_ab)
        t1.start()
        t1.join()
        t2 = threading.Thread(target=order_ba)
        t2.start()
        t2.join()
        assert san.edge_count() == 2

        col = Collector()
        found = san.scan_into(col)
        assert len(found) == 1
        f = found[0]
        assert f.rule == "SAN001"
        assert "lock-order cycle" in f.message
        assert "opposite order was observed" in f.message
        # the finding anchors at an acquisition site in THIS file
        assert f.path.endswith("test_dtxsan.py")
        # evidence: BOTH edges, each with its acquisition stack
        detail = col.findings[0].detail
        assert detail.count("edge ") == 2
        assert detail.count("acquisition stack:") == 2
        assert "order_ab" in detail and "order_ba" in detail

        # idempotent re-scan: the collector dedupes, nothing doubles
        san.scan_into(col)
        assert len(col.findings) == 1


def test_lockorder_consistent_order_is_clean():
    with _lock_san() as san:
        lock_a = threading.Lock()
        lock_b = threading.Lock()

        def worker():
            with lock_a:
                with lock_b:
                    pass

        for _ in range(2):
            t = threading.Thread(target=worker)
            t.start()
            t.join()
        col = Collector()
        assert san.scan_into(col) == []


def test_lockorder_self_deadlock_raises_instead_of_hanging(monkeypatch):
    col = Collector()
    monkeypatch.setattr(san_runtime, "COLLECTOR", col)
    with _lock_san():
        lock = threading.Lock()
        lock.acquire()
        try:
            with pytest.raises(LockOrderViolation, match="self-deadlock"):
                lock.acquire()
        finally:
            lock.release()
    assert len(col.findings) == 1
    assert "non-reentrant Lock" in col.findings[0].finding.message


def test_lockorder_rlock_reentry_is_clean():
    with _lock_san() as san:
        rl = threading.RLock()
        with rl:
            with rl:
                pass
        col = Collector()
        assert san.scan_into(col) == []


def test_lockorder_declared_order_justifies_and_flags():
    with _lock_san() as san:
        low = threading.Lock()   # dtxsan: order(pool:1)
        high = threading.Lock()  # dtxsan: order(pool:2)
        with low:
            with high:  # 1 -> 2: the sanctioned direction
                pass
        col = Collector()
        assert san.scan_into(col) == []

        with high:
            with low:  # 2 -> 1: violates the declared ranks
                pass
        found = san.scan_into(col)
        assert len(found) == 1
        assert "declared lock order violated" in found[0].message
        assert "group pool" in found[0].message


# ------------------------------------------------------ SAN002 thread leak
def test_thread_leak_detected_with_spawn_site():
    with _thread_san() as san:
        before = set(threading.enumerate())
        stop = threading.Event()
        t = threading.Thread(target=stop.wait, name="leaky-probe-1",
                             daemon=True)
        t.start()
        try:
            col = Collector()
            found = san.audit(before, col, testid="test_thread_leak",
                              grace=0.05)
            assert len(found) == 1
            f = found[0]
            assert f.rule == "SAN002"
            assert "'leaky-probe'" in f.message  # counter suffix stripped
            assert f.path.endswith("test_dtxsan.py")
            detail = col.findings[0].detail
            assert "first leaked past: test_thread_leak" in detail
            assert "spawn stack:" in detail
            assert "test_dtxsan.py" in detail
        finally:
            stop.set()
            t.join(timeout=5)


def test_thread_joined_before_teardown_is_clean():
    with _thread_san() as san:
        before = set(threading.enumerate())
        t = threading.Thread(target=lambda: None)
        t.start()
        t.join()
        col = Collector()
        assert san.audit(before, col, grace=0.5) == []


def test_allow_thread_escape_hatch():
    with _thread_san() as san:
        before = set(threading.enumerate())
        stop = threading.Event()
        t = allow_thread(threading.Thread(target=stop.wait, daemon=True))
        t.start()
        try:
            col = Collector()
            assert san.audit(before, col, grace=0.05) == []
        finally:
            stop.set()
            t.join(timeout=5)


def test_plugin_fails_leaking_test(tmp_path):
    """End-to-end: a test that leaks a thread FAILS under DTX_SAN=thread
    via the plugin's teardown audit, naming the spawn site."""
    (tmp_path / "test_leak.py").write_text(textwrap.dedent("""
        import threading

        def test_leaves_a_worker():
            stop = threading.Event()
            threading.Thread(target=stop.wait, name="orphan",
                             daemon=True).start()
            assert True
    """))
    proc = subprocess.run(
        [sys.executable, "-m", "pytest", "test_leak.py", "-q",
         "-p", "datatunerx_tpu.analysis.sanitizers.plugin",
         "-p", "no:cacheprovider"],
        cwd=tmp_path, capture_output=True, text=True, timeout=120,
        env={**__import__("os").environ, "DTX_SAN": "thread",
             "DTX_SAN_FOREIGN": "1", "DTX_SAN_THREAD_GRACE": "0.1",
             "PYTHONPATH": REPO},
    )
    assert proc.returncode != 0
    assert "dtxsan thread-leak" in proc.stdout
    assert "'orphan'" in proc.stdout


# --------------------------------------------------- SAN003 compile budget
def _fresh_compile_san():
    was = COMPILE_SANITIZER.enabled
    COMPILE_SANITIZER.install()
    return was


def test_compile_budget_clean_then_breach():
    import jax
    import jax.numpy as jnp

    was = _fresh_compile_san()
    try:
        x = jnp.arange(8.0)  # inputs built OUTSIDE any budget window

        @jax.jit
        def f(v):
            return v * 2.0 + 1.0

        f(x).block_until_ready()  # warm
        with compile_budget(0, label="warmed"):
            f(x).block_until_ready()  # cache hit: zero fresh lowerings

        @jax.jit
        def g(v):
            return v * 3.0 - 1.0

        col = Collector()
        with pytest.raises(CompileBudgetExceeded, match="compile budget"):
            with compile_budget(0, label="fresh-program", collector=col):
                g(x).block_until_ready()
        assert len(col.findings) == 1
        f0 = col.findings[0].finding
        assert f0.rule == "SAN003"
        assert "fresh-program" in f0.message
        assert "test_dtxsan.py" in f0.message  # compile site attribution
    finally:
        COMPILE_SANITIZER.enabled = was


def test_compile_budget_no_raise_mode_records_only():
    import jax
    import jax.numpy as jnp

    was = _fresh_compile_san()
    try:
        x = jnp.arange(4.0)

        @jax.jit
        def h(v):
            return v - 0.5

        col = Collector()
        with compile_budget(0, raise_on_exceed=False, collector=col) as w:
            h(x).block_until_ready()
        assert w.seen >= 1
        assert len(col.findings) == 1
    finally:
        COMPILE_SANITIZER.enabled = was


def test_module_budget_breach_names_top_sites():
    import jax
    import jax.numpy as jnp

    was = _fresh_compile_san()
    try:
        COMPILE_SANITIZER.register_module_budget("tests/test_dtxsan.py", 0)
        x = jnp.arange(3.0)

        @jax.jit
        def m(v):
            return v + 7.0

        m(x).block_until_ready()
        col = Collector()
        found = COMPILE_SANITIZER.scan_into(col)
        mine = [f for f in found
                if "tests/test_dtxsan.py" in f.message]
        assert mine and "module compile budget exceeded" in mine[0].message
        assert "top sites:" in mine[0].message
    finally:
        with COMPILE_SANITIZER._mu:
            COMPILE_SANITIZER._module_budgets.pop("tests/test_dtxsan.py",
                                                  None)
        COMPILE_SANITIZER.enabled = was


def test_memo_key_fragmentation_is_caught(tmp_path, monkeypatch):
    """The acceptance criterion: revert the PR 14 memo-key invariant —
    make the program memo key vary per engine (as it would if adapter
    NAMES were part of it) — and the compile-budget sanitizer catches the
    resulting recompile that the shared-programs design eliminates."""
    import datatunerx_tpu.serving.batched_engine as be
    from datatunerx_tpu.serving.batched_engine import BatchedEngine

    was = _fresh_compile_san()
    kw = dict(template="vanilla", max_seq_len=128, slots=1, decode_chunk=4,
              kv_block_size=16)
    eng1 = BatchedEngine("preset:debug", **kw)
    try:
        prompt = eng1.tokenizer.encode("memo key probe")
        eng1.generate(prompt, max_new_tokens=4)  # warm the shared programs

        # control: an identical engine HITS the memo — zero fresh compiles
        eng2 = BatchedEngine("preset:debug", **kw)
        try:
            with compile_budget(0, label="memo-hit"):
                eng2.generate(prompt, max_new_tokens=4)
        finally:
            eng2.close()

        # seeded regression: per-engine key fragment (adapter names in the
        # key) forces a memo miss; fresh _Programs -> fresh jit wrappers
        # -> the SAME traffic now lowers programs again
        real_key = be._program_memo_key
        nonce = iter(range(10 ** 6))

        def fragmented(cfg, max_seq_len, kv_quant, epilogue="off"):
            k = real_key(cfg, max_seq_len, kv_quant, epilogue)
            return None if k is None else k + (f"adapters:{next(nonce)}",)

        monkeypatch.setattr(be, "_program_memo_key", fragmented)
        eng3 = BatchedEngine("preset:debug", **kw)
        try:
            col = Collector()
            with pytest.raises(CompileBudgetExceeded):
                with compile_budget(0, label="memo-fragmented",
                                    collector=col):
                    eng3.generate(prompt, max_new_tokens=4)
            assert col.findings
            assert "memo-fragmented" in col.findings[0].finding.message
        finally:
            eng3.close()
    finally:
        eng1.close()
        COMPILE_SANITIZER.enabled = was


# ------------------------------------------------- suppression / baseline
def test_inline_suppression_on_anchor_line(tmp_path):
    src = tmp_path / "mod.py"
    src.write_text(
        "x = 1\n"
        "y = 2  # dtxsan: disable=SAN002 — session-scoped server thread\n"
        "z = 3  # dtxsan: disable=all — kitchen sink\n")
    col = Collector()
    assert col.add("SAN002", (str(src), 2), "leak") is None
    assert col.add("SAN001", (str(src), 3), "anything") is None
    assert col.add("SAN001", (str(src), 2), "wrong rule") is not None
    assert col.suppressed == 2
    assert len(col.findings) == 1


def test_collector_dedupes_identical_findings(tmp_path):
    col = Collector()
    site = (str(tmp_path / "m.py"), 7)
    assert col.add("SAN002", site, "same fact") is not None
    assert col.add("SAN002", site, "same fact") is None
    assert len(col.findings) == 1


def test_report_baseline_and_json_contract(tmp_path):
    col = Collector()
    col.add("SAN001", (str(tmp_path / "a.py"), 3), "cycle x", detail="s1")
    col.add("SAN002", (str(tmp_path / "b.py"), 9), "leak y", detail="s2")
    findings, suppressed = col.snapshot()

    # raw round-trip keeps findings + evidence
    raw = tmp_path / "raw.json"
    san_report.write_raw(str(raw), findings, suppressed,
                         counters={"lowerings": 5, "backend_compiles": 2},
                         classes=("lock", "thread"))
    loaded, sup, counters, classes = san_report.load_raw(str(raw))
    assert [sf.finding.key() for sf in loaded] == \
        [sf.finding.key() for sf in findings]
    assert loaded[0].detail == "s1"
    assert counters == {"lowerings": 5, "backend_compiles": 2}
    assert classes == ["lock", "thread"]

    # with no baseline everything is NEW -> failed
    ev = san_report.evaluate(loaded, sup, no_baseline=True)
    assert ev["failed"] and len(ev["new"]) == 2

    # baselined findings carry, don't fail (mechanism only: policy keeps
    # the checked-in baseline EMPTY)
    bl = tmp_path / "baseline.json"
    save_baseline(str(bl), [sf.finding for sf in loaded])
    ev2 = san_report.evaluate(loaded, sup, baseline_path=str(bl))
    assert not ev2["failed"]
    assert ev2["baselined"] == 2 and ev2["new"] == []

    # the dtx lint-shaped JSON doc
    doc = san_report.build_doc(ev, counters=counters, classes=classes,
                               pytest_exit=0)
    assert set(doc) == {"version", "findings", "baselined", "suppressed",
                        "failed", "classes", "counters", "pytest_exit"}
    assert doc["version"] == san_report.JSON_SCHEMA_VERSION
    assert doc["findings"][0]["rule"] == "SAN001"
    assert doc["findings"][0]["detail"] == "s1"
    # a green sanitizer pass still fails the doc when pytest itself failed
    doc_red = san_report.build_doc(
        san_report.evaluate([], 0, no_baseline=True),
        pytest_exit=1)
    assert doc_red["failed"]


def test_cli_from_report(tmp_path, capsys):
    from datatunerx_tpu.analysis.sanitizers.cli import main as san_main

    raw = tmp_path / "r.json"
    col = Collector()
    col.add("SAN003", (str(tmp_path / "c.py"), 4), "budget blown")
    findings, suppressed = col.snapshot()
    san_report.write_raw(str(raw), findings, suppressed)
    rc = san_main(["--from-report", str(raw), "--no-baseline",
                   "--format", "json"])
    doc = json.loads(capsys.readouterr().out)
    assert rc == 1 and doc["failed"]
    assert doc["findings"][0]["rule"] == "SAN003"

    san_report.write_raw(str(raw), [], 0)
    rc = san_main(["--from-report", str(raw), "--no-baseline"])
    assert rc == 0
    assert "0 findings" in capsys.readouterr().out

    assert san_main(["--module-budget", "nonsense"]) == 2


def test_render_text_includes_detail():
    col = Collector()
    col.add("SAN001", (REPO + "/x.py", 1), "msg", detail="line1\nline2")
    findings, suppressed = col.snapshot()
    ev = san_report.evaluate(findings, suppressed, no_baseline=True)
    text = san_report.render_text(ev, counters={"lowerings": 1,
                                                "backend_compiles": 0})
    assert "msg" in text and "line1" in text
    assert "dtxsan: 1 finding" in text
    assert "1 lowered" in text


def test_parse_classes():
    pc = san_runtime.parse_classes
    assert pc("1") == ("lock", "thread", "compile")
    assert pc("all") == ("lock", "thread", "compile")
    assert pc("lock,compile") == ("lock", "compile")
    assert pc("thread, bogus") == ("thread",)
    assert pc("") == () and pc("0") == () and pc("off") == ()
