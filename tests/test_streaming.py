"""Streaming dataset ingest (ROADMAP §4): lazy record iteration, shuffle-
buffer batching, determinism, host slicing, and the --streaming CLI path."""

import json

import numpy as np
import pytest

from datatunerx_tpu.data.loader import (
    StreamingBatchIterator,
    StreamingCsvDataset,
)
from datatunerx_tpu.data.templates import get_template
from tests.fake_tokenizer import FakeTokenizer


@pytest.fixture(scope="module")
def tok():
    return FakeTokenizer()


def _write_jsonl(path, n):
    with open(path, "w") as f:
        for i in range(n):
            f.write(json.dumps({"instruction": f"q {i}",
                                "response": f"answer {i}"}) + "\n")
    return str(path)


def test_stream_reads_lazily(tmp_path):
    p = _write_jsonl(tmp_path / "d.jsonl", 10)
    ds = StreamingCsvDataset(p)
    it = iter(ds)
    first = next(it)
    assert first["instruction"] == "q 0"
    assert sum(1 for _ in it) == 9


def test_stream_csv(tmp_path):
    p = tmp_path / "d.csv"
    with open(p, "w") as f:
        f.write("instruction,response\n")
        for i in range(6):
            f.write(f"q {i},a {i}\n")
    recs = list(StreamingCsvDataset(str(p)))
    assert len(recs) == 6 and recs[3]["response"] == "a 3"


def test_stream_missing_file():
    with pytest.raises(FileNotFoundError):
        StreamingCsvDataset("/nonexistent/x.jsonl")


def test_streaming_batches_cover_dataset(tmp_path, tok):
    """Every example lands in exactly one batch per pass (full batches only),
    shapes are static, and the same seed reproduces the same order."""
    p = _write_jsonl(tmp_path / "d.jsonl", 37)
    tpl = get_template("vanilla", tok)

    def run():
        it = StreamingBatchIterator(
            StreamingCsvDataset(p), tpl, tok,
            global_batch=8, block_size=64, pad_id=0, buffer_size=16, seed=5,
        )
        return list(it.epoch(0))

    b1, b2 = run(), run()
    assert len(b1) == 37 // 8
    for a, b in zip(b1, b2):
        np.testing.assert_array_equal(a["input_ids"], b["input_ids"])
    for b in b1:
        assert b["input_ids"].shape == (8, 64)
        assert b["labels"].shape == (8, 64)
    # different epoch → different shuffle
    it3 = StreamingBatchIterator(
        StreamingCsvDataset(p), tpl, tok,
        global_batch=8, block_size=64, pad_id=0, buffer_size=16, seed=5,
    )
    b3 = list(it3.epoch(1))
    assert any(
        not np.array_equal(a["input_ids"], b["input_ids"])
        for a, b in zip(b1, b3)
    )


def test_streaming_host_slicing(tmp_path, tok):
    p = _write_jsonl(tmp_path / "d.jsonl", 32)
    tpl = get_template("vanilla", tok)
    full = list(StreamingBatchIterator(
        StreamingCsvDataset(p), tpl, tok,
        global_batch=8, block_size=64, pad_id=0, buffer_size=8, seed=1,
    ).epoch(0))
    parts = [
        list(StreamingBatchIterator(
            StreamingCsvDataset(p), tpl, tok,
            global_batch=8, block_size=64, pad_id=0, buffer_size=8, seed=1,
            host_id=h, num_hosts=2,
        ).epoch(0))
        for h in range(2)
    ]
    for s, fb in enumerate(full):
        got = np.concatenate([parts[0][s]["input_ids"],
                              parts[1][s]["input_ids"]])
        np.testing.assert_array_equal(got, fb["input_ids"])


def test_streaming_grad_accum_shape(tmp_path, tok):
    p = _write_jsonl(tmp_path / "d.jsonl", 16)
    tpl = get_template("vanilla", tok)
    b = next(iter(StreamingBatchIterator(
        StreamingCsvDataset(p), tpl, tok,
        global_batch=8, block_size=32, pad_id=0, buffer_size=8, grad_accum=2,
    )))
    assert b["input_ids"].shape == (2, 4, 32)


def test_streaming_cli_validation():
    from datatunerx_tpu.tuning.parser import parse_train_args

    with pytest.raises(ValueError, match="max_steps"):
        parse_train_args([
            "--model_name_or_path", "preset:debug", "--streaming",
            "--train_path", "x.jsonl",
        ])
    with pytest.raises(ValueError, match="sft/pt"):
        parse_train_args([
            "--model_name_or_path", "preset:debug", "--streaming",
            "--stage", "dpo", "--train_path", "x.jsonl", "--max_steps", "2",
        ])


def test_streaming_cli_e2e(tmp_path):
    from datatunerx_tpu.tuning.parser import parse_train_args
    from datatunerx_tpu.tuning.train import run

    p = _write_jsonl(tmp_path / "train.jsonl", 60)
    args = parse_train_args([
        "--model_name_or_path", "preset:debug", "--streaming",
        "--shuffle_buffer", "16",
        "--train_path", p, "--output_dir", str(tmp_path / "out"),
        "--storage_path", str(tmp_path / "storage"), "--uid", "stream-run",
        "--template", "vanilla", "--block_size", "64",
        "--per_device_train_batch_size", "1", "--max_steps", "3",
        "--bf16", "false", "--logging_steps", "1",
    ])
    res = run(args)
    assert res["steps"] == 3
    assert res["manifest"]


def test_thread_safe_encoding_clones_per_thread(tmp_path, tok):
    """ensure_thread_safe_encoding opts the iterator into per-thread
    tokenizer CLONES: a worker thread (HostPrefetcher) must never share the
    original tokenizer object with the main thread's generative-eval encode
    ("Already borrowed" with HF fast tokenizers)."""
    import threading

    p = _write_jsonl(tmp_path / "d.jsonl", 8)
    it = StreamingBatchIterator(
        StreamingCsvDataset(p), get_template("vanilla", tok), tok,
        global_batch=2, block_size=32, buffer_size=2,
    )
    assert it.ensure_thread_safe_encoding() is True
    assert it.ensure_thread_safe_encoding() is True  # idempotent
    # main thread gets its own clone too — never the shared original
    assert it._thread_tokenizer() is not tok
    assert it._thread_tokenizer() is it._thread_tokenizer()  # cached per thread

    seen = {}

    def worker(name):
        seen[name] = it._thread_tokenizer()

    threads = [threading.Thread(target=worker, args=(i,)) for i in range(2)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert seen[0] is not seen[1]
    assert tok not in seen.values()
    # clones encode identically: batches still come out the same
    batches = list(it.epoch(0))
    assert batches and all(b["input_ids"].shape[0] == 2 for b in batches)


def test_thread_safe_encoding_falls_back_when_not_clonable(tmp_path, tok):
    """A tokenizer that refuses deepcopy keeps the old behavior: the caller
    (tuning/train.py) sees False and leaves the pipeline synchronous."""
    class Unclonable(type(tok)):
        def __deepcopy__(self, memo):
            raise RuntimeError("rust tokenizer state is not forkable")

    bad = Unclonable()
    p = _write_jsonl(tmp_path / "d2.jsonl", 4)
    it = StreamingBatchIterator(
        StreamingCsvDataset(p), get_template("vanilla", bad), bad,
        global_batch=2, block_size=32, buffer_size=2,
    )
    assert it.ensure_thread_safe_encoding() is False
    assert it._thread_tokenizer() is bad  # unchanged: shared original


# ---------------------------------------------------------------- read-ahead

class _JitteryDataset(StreamingCsvDataset):
    """A record stream whose per-record latency jumps around — the shape of
    a gs:// line iterator under network jitter."""

    def __init__(self, path, sleep_scale=0.002, seed=7):
        super().__init__(path)
        self._sleep_scale = sleep_scale
        self._seed = seed

    def __iter__(self):
        import random
        import time

        rnd = random.Random(self._seed)
        for rec in super().__iter__():
            time.sleep(rnd.random() * self._sleep_scale)
            yield rec


def test_read_ahead_iterator_preserves_order_and_errors():
    from datatunerx_tpu.data.prefetch import ReadAheadIterator

    got = list(ReadAheadIterator(iter(range(100)), depth=4))
    assert got == list(range(100))

    def boom():
        yield 1
        yield 2
        raise RuntimeError("remote read died")

    it = ReadAheadIterator(boom(), depth=2)
    assert next(it) == 1 and next(it) == 2
    with pytest.raises(RuntimeError, match="remote read died"):
        next(it)


def test_read_ahead_matches_sync_under_jitter(tmp_path, tok):
    """The read-ahead path must be a pure latency optimization: batches are
    byte-identical to the synchronous path even when the raw reader's
    latency jitters (record order is preserved by the FIFO handoff)."""
    p = _write_jsonl(tmp_path / "d.jsonl", 41)
    tpl = get_template("vanilla", tok)

    def run(read_ahead):
        it = StreamingBatchIterator(
            _JitteryDataset(p), tpl, tok,
            global_batch=8, block_size=64, pad_id=0, buffer_size=16, seed=5,
            read_ahead=read_ahead,
        )
        return list(it.epoch(0))

    sync_batches = run(0)
    ra_batches = run(8)
    assert len(sync_batches) == len(ra_batches) == 41 // 8
    for a, b in zip(sync_batches, ra_batches):
        np.testing.assert_array_equal(a["input_ids"], b["input_ids"])
        np.testing.assert_array_equal(a["labels"], b["labels"])


def test_read_ahead_early_exit_stops_reader(tmp_path, tok):
    """Abandoning an epoch mid-stream (max_steps) must stop the reader
    thread promptly — a blocked put on the bounded queue would otherwise
    leak one thread per abandoned epoch."""
    import threading

    p = _write_jsonl(tmp_path / "d.jsonl", 64)
    tpl = get_template("vanilla", tok)
    before = threading.active_count()
    it = StreamingBatchIterator(
        StreamingCsvDataset(p), tpl, tok,
        global_batch=4, block_size=64, pad_id=0, buffer_size=4, seed=0,
        read_ahead=2,
    )
    gen = it.epoch(0)
    next(gen)  # consume one batch, then abandon the epoch
    gen.close()
    # the generator's finally closed the ReadAheadIterator; its thread
    # (daemon "dtx-readahead") must wind down
    deadline = 50
    while deadline and any(
            t.name == "dtx-readahead" and t.is_alive()
            for t in threading.enumerate()):
        import time

        time.sleep(0.1)
        deadline -= 1
    assert not any(t.name == "dtx-readahead" and t.is_alive()
                   for t in threading.enumerate())
    assert threading.active_count() <= before + 1
