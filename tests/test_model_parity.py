"""Numerical parity of the JAX decoder vs HF transformers (torch CPU).

SURVEY.md §4.2: the rebuild needs golden tests the reference never had. These
pin our forward pass to HF llama/mistral/qwen2 semantics (rotate-half RoPE, GQA,
RMSNorm eps placement, SwiGLU) at fp32 tolerance.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from datatunerx_tpu.models.config import ModelConfig
from datatunerx_tpu.models.llama import forward, init_cache
from datatunerx_tpu.utils.hf_convert import (
    config_from_hf,
    convert_hf_state_dict,
    export_hf_state_dict,
)

torch = pytest.importorskip("torch")


def _hf_logits(model, tokens_np, attn_np=None):
    with torch.no_grad():
        out = model(
            input_ids=torch.tensor(tokens_np),
            attention_mask=None if attn_np is None else torch.tensor(attn_np),
        )
    return out.logits.float().numpy()


def _make_hf(model_type: str):
    torch.manual_seed(0)
    common = dict(
        vocab_size=256,
        hidden_size=64,
        intermediate_size=128,
        num_hidden_layers=3,
        num_attention_heads=4,
        num_key_value_heads=2,
        max_position_embeddings=128,
        rms_norm_eps=1e-5,
        tie_word_embeddings=False,
    )
    if model_type == "llama":
        from transformers import LlamaConfig, LlamaForCausalLM

        cfg = LlamaConfig(**common)
        model = LlamaForCausalLM(cfg)
    elif model_type == "mistral":
        from transformers import MistralConfig, MistralForCausalLM

        cfg = MistralConfig(**common, sliding_window=16)
        model = MistralForCausalLM(cfg)
    elif model_type == "qwen2":
        from transformers import Qwen2Config, Qwen2ForCausalLM

        cfg = Qwen2Config(**common)
        model = Qwen2ForCausalLM(cfg)
    else:
        raise ValueError(model_type)
    model.eval()
    return cfg, model


@pytest.mark.parametrize("model_type", ["llama", "mistral", "qwen2"])
def test_forward_matches_hf(model_type):
    hf_cfg, model = _make_hf(model_type)
    cfg = config_from_hf(hf_cfg)
    assert cfg.num_kv_heads == 2
    if model_type == "qwen2":
        assert cfg.attention_bias
    if model_type == "mistral":
        assert cfg.sliding_window == 16

    params = convert_hf_state_dict(model.state_dict(), cfg)

    rng = np.random.default_rng(0)
    tokens = rng.integers(0, 256, size=(2, 24), dtype=np.int32)
    ref = _hf_logits(model, tokens)

    ours, _ = forward(params, jnp.asarray(tokens), cfg)
    np.testing.assert_allclose(np.asarray(ours), ref, atol=2e-4, rtol=2e-3)


def test_forward_with_padding_matches_hf():
    hf_cfg, model = _make_hf("llama")
    cfg = config_from_hf(hf_cfg)
    params = convert_hf_state_dict(model.state_dict(), cfg)

    rng = np.random.default_rng(1)
    tokens = rng.integers(0, 256, size=(2, 16), dtype=np.int32)
    attn = np.ones((2, 16), np.int32)
    attn[0, 12:] = 0  # right padding
    ref = _hf_logits(model, tokens, attn)

    ours, _ = forward(params, jnp.asarray(tokens), cfg, attention_mask=jnp.asarray(attn))
    # compare only non-pad positions
    np.testing.assert_allclose(
        np.asarray(ours)[:, :12], ref[:, :12], atol=2e-4, rtol=2e-3
    )


def test_kv_cache_decode_matches_full_forward():
    hf_cfg, model = _make_hf("llama")
    cfg = config_from_hf(hf_cfg)
    params = convert_hf_state_dict(model.state_dict(), cfg)

    rng = np.random.default_rng(2)
    tokens = jnp.asarray(rng.integers(0, 256, size=(1, 12), dtype=np.int32))

    full, _ = forward(params, tokens, cfg)

    cache = init_cache(cfg, batch=1, max_len=16, dtype=jnp.float32)
    prefill, cache = forward(
        params, tokens[:, :8], cfg,
        positions=jnp.arange(8, dtype=jnp.int32)[None], cache=cache,
    )
    np.testing.assert_allclose(
        np.asarray(prefill), np.asarray(full[:, :8]), atol=1e-4, rtol=1e-3
    )
    for t in range(8, 12):
        step, cache = forward(
            params, tokens[:, t : t + 1], cfg,
            positions=jnp.asarray([[t]], jnp.int32), cache=cache,
        )
        np.testing.assert_allclose(
            np.asarray(step[:, 0]), np.asarray(full[:, t]), atol=1e-4, rtol=1e-3
        )


def test_export_roundtrip():
    hf_cfg, model = _make_hf("llama")
    cfg = config_from_hf(hf_cfg)
    params = convert_hf_state_dict(model.state_dict(), cfg)
    sd = export_hf_state_dict(params, cfg)
    params2 = convert_hf_state_dict(sd, cfg)
    import jax

    for a, b in zip(jax.tree_util.tree_leaves(params), jax.tree_util.tree_leaves(params2)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_rope_scaling_runs():
    cfg = ModelConfig(
        vocab_size=64, hidden_size=32, intermediate_size=64, num_layers=2,
        num_heads=2, num_kv_heads=2, max_seq_len=16,
        rope_scaling_type="linear", rope_scaling_factor=2.0,
    )
    import jax

    params = __import__(
        "datatunerx_tpu.models.llama", fromlist=["init_params"]
    ).init_params(cfg, jax.random.PRNGKey(0))
    tokens = jnp.zeros((1, 32), jnp.int32)  # 2x the nominal max_seq_len
    logits, _ = forward(params, tokens, cfg)
    assert logits.shape == (1, 32, 64)
    assert np.isfinite(np.asarray(logits)).all()
