"""Dynamic multi-adapter plane (datatunerx_tpu/adapters/ + serving
/admin/adapters + gateway residency routing): the pool is a cache — load
on miss, pin while decoding, LRU-evict when full — and the whole fleet
becomes an adapter cache the gateway routes by residency. Engine-level
token parity lives in test_paged_engine.py; this file covers the store/
registry mechanics, the admission FIFO-wait, the admin HTTP contract, and
the gateway's load-on-miss → prefer-resident end-to-end path."""

import json
import threading
import urllib.error
import urllib.request
from http.server import ThreadingHTTPServer

import numpy as np
import pytest

from datatunerx_tpu.adapters import (
    AdapterPinnedError,
    AdapterRankError,
    AdapterRegistry,
    AdapterStore,
    AdapterTargetError,
    hbm_bytes,
)
from datatunerx_tpu.models import get_config
from datatunerx_tpu.models.lora import target_dims

MODEL = "preset:debug"


# ---------------------------------------------------------------- store unit

def _cfg():
    return get_config("debug")


def _layers(cfg, rank, targets=("q_proj", "v_proj"), fill=0.5):
    out = {}
    for t in targets:
        d_in, d_out = target_dims(cfg, t)
        out[t] = {"a": np.full((cfg.num_layers, d_in, rank), fill,
                               np.float32),
                  "b": np.full((cfg.num_layers, rank, d_out), fill,
                               np.float32)}
    return out


def test_store_insert_pads_rank_and_clear_zeroes():
    cfg = _cfg()
    store = AdapterStore(cfg, pool_slots=2, rank_max=8)
    rank = store.insert(1, _layers(cfg, rank=4), scaling=2.0, name="t")
    assert rank == 4
    tree, scales = store.tree
    a = np.asarray(tree["layers"]["q_proj"]["a"])
    assert a.shape[1] == 3  # base slot 0 + 2 pool slots
    assert (a[:, 1, :, :4] == 0.5).all()
    assert (a[:, 1, :, 4:] == 0.0).all()  # rank padding
    assert (a[:, 0] == 0.0).all() and (a[:, 2] == 0.0).all()
    assert float(scales[1]) == 2.0 and float(scales[0]) == 0.0
    store.clear(1)
    tree, scales = store.tree
    assert (np.asarray(tree["layers"]["q_proj"]["a"]) == 0.0).all()
    assert float(scales[1]) == 0.0


def test_store_rejects_bad_geometry():
    cfg = _cfg()
    store = AdapterStore(cfg, pool_slots=1, rank_max=4)
    with pytest.raises(AdapterRankError, match="rank 8 exceeds"):
        store.insert(1, _layers(cfg, rank=8), scaling=1.0, name="big")
    with pytest.raises(AdapterTargetError, match="o_proj"):
        store.insert(1, _layers(cfg, rank=2, targets=("o_proj",)),
                     scaling=1.0, name="wide")
    with pytest.raises(ValueError, match="slot 0"):
        store.insert(0, _layers(cfg, rank=2), scaling=1.0)
    assert hbm_bytes(cfg, 8, 8) == AdapterStore(
        cfg, pool_slots=8, rank_max=8).nbytes()


# ------------------------------------------------------------- registry unit

def _registry(pool_slots=2, rank_max=8, ranks=None):
    """Registry over a fake loader (no orbax): checkpoint path 'ck:<name>'
    loads constant-filled layers at the configured rank."""
    cfg = _cfg()
    store = AdapterStore(cfg, pool_slots=pool_slots, rank_max=rank_max)
    ranks = ranks or {}
    loads = []

    def loader(path):
        name = path.split(":", 1)[1]
        loads.append(name)
        return {"lora": {"layers": _layers(cfg, ranks.get(name, 2))},
                "_scaling": 4.0}

    reg = AdapterRegistry(store, loader=loader)
    return reg, loads


def test_registry_load_on_miss_hit_and_lru_eviction():
    reg, loads = _registry(pool_slots=2)
    for n in ("a", "b", "c"):
        reg.register(n, f"ck:{n}")
    # wait=True: block on the async load and return the pinned slot
    assert reg.acquire("a", wait=True) == 1
    assert reg.acquire("b", wait=True) == 2
    reg.release("a")
    reg.release("b")
    assert reg.acquire("a", wait=True) == 1  # hit: no reload
    reg.release("a")
    assert loads == ["a", "b"]
    assert reg.stats == {"loads": 2, "evictions": 0, "hits": 1, "misses": 2}
    # pool full → the COLDEST unpinned resident (b) is evicted for c
    assert reg.acquire("c", wait=True) == 2
    reg.release("c")
    assert reg.resident() == {"a": 1, "c": 2}
    assert reg.stats["evictions"] == 1 and loads == ["a", "b", "c"]
    # b reloads on demand into the next evictable slot
    assert reg.acquire("b", wait=True) is not None
    reg.release("b")


def test_registry_acquire_is_nonblocking_and_resolves():
    """The scheduler's contract: a miss returns None immediately (the
    load runs on a loader thread) and a later retry succeeds — decode is
    never held hostage by a checkpoint read. Retries while loading or
    exhausted must not inflate the miss counter."""
    import threading as _threading
    import time as _time

    cfg = _cfg()
    store = AdapterStore(cfg, pool_slots=1, rank_max=8)
    release = _threading.Event()

    def slow_loader(path):
        release.wait(10)
        return {"lora": {"layers": _layers(cfg, 2)}, "_scaling": 4.0}

    reg = AdapterRegistry(store, loader=slow_loader)
    reg.register("a", "ck:a")
    assert reg.acquire("a") is None  # load kicked, NOT blocked on it
    assert reg.acquire("a") is None  # still loading: no second load
    assert reg.stats["misses"] == 1  # retries are not phantom misses
    with pytest.raises(AdapterPinnedError):  # mid-load: not removable
        reg.unregister("a")
    release.set()
    deadline = _time.time() + 10
    idx = None
    while idx is None and _time.time() < deadline:
        idx = reg.acquire("a")
        if idx is None:
            _time.sleep(0.005)
    assert idx == 1 and reg.stats["loads"] == 1
    assert reg.stats["misses"] == 1 and reg.stats["hits"] == 0
    reg.release("a")


def test_registry_pinning_blocks_eviction_and_unload():
    reg, _ = _registry(pool_slots=1)
    reg.register("a", "ck:a")
    reg.register("b", "ck:b")
    assert reg.acquire("a", wait=True) == 1
    # a is pinned: nothing evictable → exhausted, caller FIFO-waits
    assert reg.acquire("b") is None
    with pytest.raises(AdapterPinnedError):
        reg.unregister("a")
    reg.release("a")
    assert reg.acquire("b", wait=True) == 1  # a (unpinned LRU) evicted
    reg.release("b")
    assert reg.unregister("b") and reg.names() == ["a"]


def test_registry_reregister_contract():
    reg, _ = _registry()
    reg.register("a", "ck:a")
    reg.register("a", "ck:a")  # idempotent
    reg.acquire("a", wait=True)
    with pytest.raises(AdapterPinnedError):  # live name, other weights
        reg.register("a", "ck:other")
    reg.release("a")
    with pytest.raises(AdapterPinnedError):  # still resident
        reg.register("a", "ck:other")
    reg.unregister("a")
    reg.register("a", "ck:other")  # gone → new binding allowed


def test_registry_rank_over_max_rejected_and_not_inserted():
    reg, _ = _registry(rank_max=4, ranks={"big": 16})
    reg.register("big", "ck:big")
    with pytest.raises(AdapterRankError, match="rank 16 exceeds"):
        reg.acquire("big", wait=True)
    occ = reg.occupancy()
    assert occ["resident"] == 0 and occ["free"] == 2
    with pytest.raises(KeyError):
        reg.acquire("never-registered")


# --------------------------------------------------- engine admission wait

def test_engine_fifo_waits_on_adapter_pool_exhaustion(tmp_path):
    """A 1-slot pool under 2-adapter traffic: the second request waits for
    the first to release its pin (like KV-block exhaustion), then loads —
    nobody errors, nobody deadlocks."""
    from datatunerx_tpu.serving.adapters import make_adapter_sweep
    from datatunerx_tpu.serving.batched_engine import BatchedEngine

    cks = make_adapter_sweep(str(tmp_path), MODEL, 2, ranks=(2,))
    eng = BatchedEngine(MODEL, adapters=cks, adapter_pool=1,
                        adapter_rank_max=8, template="vanilla",
                        max_seq_len=256, slots=2, decode_chunk=4,
                        kv_block_size=16)
    try:
        names = sorted(cks)
        prompt = eng.tokenizer.encode("contention probe")
        reqs = [eng.submit(prompt, max_new_tokens=8, adapter=n)
                for n in names]
        for n, r in zip(names, reqs):
            assert r.done.wait(300), f"{n} stalled under pool exhaustion"
            assert r.error is None, (n, r.error)
        assert ("adapter_wait", names[1]) in list(eng.sched_trace)
        occ = eng.adapter_occupancy()
        assert occ["pinned"] == 0 and occ["evictions"] >= 1
    finally:
        eng.close()


def test_rebind_invalidates_prefix_cache(tmp_path):
    """Re-registering a NAME with different weights must drop the prefix
    cache's rows for it — a cached KV row from the old binding would
    silently poison the new adapter's output."""
    from datatunerx_tpu.serving.adapters import make_adapter_checkpoint
    from datatunerx_tpu.serving.batched_engine import BatchedEngine

    ck1 = make_adapter_checkpoint(str(tmp_path / "v1"), MODEL, seed=3, rank=4)
    ck2 = make_adapter_checkpoint(str(tmp_path / "v2"), MODEL, seed=8, rank=4)
    eng = BatchedEngine(MODEL, adapter_pool=2, adapter_rank_max=8,
                        template="vanilla", max_seq_len=256, slots=2,
                        decode_chunk=4, kv_block_size=16, prefix_cache=8)
    try:
        prompt = eng.tokenizer.encode("system preamble for the tenant")
        eng.load_adapter("t", ck1)
        eng.load_adapter("ref", ck2)  # ck2's truth, under an unused name
        want_v2 = eng.generate(prompt, max_new_tokens=8, adapter="ref")
        out_v1 = eng.generate(prompt, max_new_tokens=8, adapter="t")
        assert eng.generate(prompt, max_new_tokens=8,
                            adapter="t") == out_v1  # prefix-cache hit path
        eng.unload_adapter("t")
        eng.load_adapter("t", ck2)  # same name, NEW weights
        got = eng.generate(prompt, max_new_tokens=8, adapter="t")
        assert got == want_v2, (got, want_v2)
        assert got != out_v1
    finally:
        eng.close()


def test_warm_failure_keeps_existing_registration(tmp_path):
    """A preload that fails on TRANSIENT pool exhaustion must not
    unregister a tenant that was already registered — warming a busy pool
    must never turn a working adapter off. A bad checkpoint registered by
    the same call still rolls back."""
    import time

    from datatunerx_tpu.serving.adapters import make_adapter_sweep
    from datatunerx_tpu.serving.batched_engine import BatchedEngine

    cks = make_adapter_sweep(str(tmp_path), MODEL, 2, ranks=(2,))
    a, b = sorted(cks)
    eng = BatchedEngine(MODEL, adapters=cks, adapter_pool=1,
                        adapter_rank_max=8, template="vanilla",
                        max_seq_len=256, slots=2, decode_chunk=4,
                        kv_block_size=16)
    try:
        prompt = eng.tokenizer.encode("hold the pool slot")
        req = eng.submit(prompt, max_new_tokens=160, adapter=a)
        deadline = time.time() + 300
        while not req.tokens and time.time() < deadline:
            time.sleep(0.002)
        assert req.tokens, "pin-holder never started decoding"
        # every slot pinned → warming b fails transiently…
        with pytest.raises(RuntimeError, match="exhausted"):
            eng.load_adapter(b, cks[b])
        # …but b (registered at construction) must survive
        assert b in eng.adapter_ids
        assert req.done.wait(300) and req.error is None
        assert eng.generate(prompt, max_new_tokens=4, adapter=b)
    finally:
        eng.close()


def test_decode_continues_during_adapter_load(tmp_path):
    """The async-load QoS contract: a cold adapter's checkpoint read must
    not stall decode — a base request submitted AFTER the cold-adapter
    request runs to completion while the load is still gated, and the
    cold request completes once the load lands."""
    from datatunerx_tpu.serving.adapters import make_adapter_checkpoint
    from datatunerx_tpu.serving.batched_engine import BatchedEngine

    ck = make_adapter_checkpoint(str(tmp_path / "cold"), MODEL, seed=4,
                                 rank=4)
    eng = BatchedEngine(MODEL, adapters={"cold": ck}, adapter_pool=1,
                        adapter_rank_max=8, template="vanilla",
                        max_seq_len=256, slots=2, decode_chunk=4,
                        kv_block_size=16)
    gate = threading.Event()
    orig_loader = eng.adapter_registry._loader

    def gated_loader(path):
        assert gate.wait(60), "test gate never opened"
        return orig_loader(path)

    eng.adapter_registry._loader = gated_loader
    try:
        prompt = eng.tokenizer.encode("latency isolation probe")
        cold = eng.submit(prompt, max_new_tokens=6, adapter="cold")
        base = eng.submit(prompt, max_new_tokens=6)
        # the base request finishes while the cold load is still gated
        assert base.done.wait(300) and base.error is None
        assert not cold.done.is_set()
        gate.set()
        assert cold.done.wait(300) and cold.error is None, cold.error
        assert "cold" in eng.resident_adapters
    finally:
        gate.set()
        eng.close()


# ---------------------------------------------------- admin HTTP contract

@pytest.fixture()
def pooled_server(tmp_path):
    """A real serving HTTP server over a real dynamic-pool engine."""
    from datatunerx_tpu.serving import server as serving
    from datatunerx_tpu.serving.batched_engine import BatchedEngine

    eng = BatchedEngine(MODEL, adapter_pool=2, adapter_rank_max=8,
                        template="vanilla", max_seq_len=256, slots=2,
                        decode_chunk=4, kv_block_size=16)
    old_engine, old_model = serving.STATE.engine, serving.STATE.model_path
    serving.STATE.engine, serving.STATE.model_path = eng, MODEL
    srv = ThreadingHTTPServer(("127.0.0.1", 0), serving.Handler)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    try:
        yield f"http://127.0.0.1:{srv.server_address[1]}", eng
    finally:
        srv.shutdown()
        serving.STATE.engine, serving.STATE.model_path = old_engine, old_model
        eng.close()


def _req(url, method="GET", body=None):
    data = json.dumps(body).encode() if body is not None else None
    req = urllib.request.Request(url, data=data, method=method,
                                 headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=60) as r:
            return r.status, json.load(r)
    except urllib.error.HTTPError as e:
        return e.code, json.load(e)


def test_admin_adapters_http_contract(pooled_server, tmp_path):
    from datatunerx_tpu.serving.adapters import make_adapter_checkpoint

    url, eng = pooled_server
    code, doc = _req(url + "/admin/adapters")
    assert code == 200 and doc["dynamic"] and doc["registered"] == []

    # register + warm a tenant at runtime
    ck = make_adapter_checkpoint(str(tmp_path / "t1"), MODEL, seed=5, rank=4)
    code, doc = _req(url + "/admin/adapters", "POST",
                     {"name": "t1", "checkpoint": ck})
    assert code == 200 and doc["resident"] and doc["rank"] == 4
    code, doc = _req(url + "/admin/adapters")
    assert doc["registered"] == ["t1"] and doc["resident"] == ["t1"]
    assert doc["pool"]["slots"] == 2 and doc["pool"]["free"] == 1

    # the freshly-registered name serves chat immediately
    code, doc = _req(url + "/chat/completions", "POST",
                     {"messages": [{"role": "user", "content": "hi"}],
                      "model": "t1", "max_tokens": 4})
    assert code == 200, doc

    # /metrics carries residency + pool occupancy for the gateway scrape
    with urllib.request.urlopen(url + "/metrics", timeout=10) as r:
        text = r.read().decode()
    assert 'dtx_serving_adapter_resident{adapter="t1"} 1' in text
    assert "dtx_serving_adapter_pool_slots_capacity 2" in text
    assert 'dtx_serving_adapter_requests_total{adapter="t1"}' in text

    # geometry violations answer 400 with the actionable message
    big = make_adapter_checkpoint(str(tmp_path / "big"), MODEL, seed=6,
                                  rank=16)
    code, doc = _req(url + "/admin/adapters", "POST",
                     {"name": "big", "checkpoint": big})
    assert code == 400 and "rank 16 exceeds" in doc["error"]
    code, _ = _req(url + "/admin/adapters", "POST", {"name": "x"})
    assert code == 400

    # DELETE evicts + unregisters; unknown names 404
    code, doc = _req(url + "/admin/adapters/t1", "DELETE")
    assert code == 200 and doc == {"unloaded": "t1"}
    code, _ = _req(url + "/admin/adapters/t1", "DELETE")
    assert code == 404
    code, doc = _req(url + "/admin/adapters")
    assert doc["registered"] == [] and doc["pool"]["free"] == 2


def test_admin_adapters_static_engine_501():
    from datatunerx_tpu.serving import server as serving

    class _Static:
        adapter_ids = {"": 0, "s": 1}

    old = serving.STATE.engine
    serving.STATE.engine = _Static()
    try:
        srv = ThreadingHTTPServer(("127.0.0.1", 0), serving.Handler)
        threading.Thread(target=srv.serve_forever, daemon=True).start()
        url = f"http://127.0.0.1:{srv.server_address[1]}"
        code, doc = _req(url + "/admin/adapters")
        assert code == 200 and doc == {"dynamic": False,
                                       "registered": ["s"],
                                       "resident": ["s"]}
        code, _ = _req(url + "/admin/adapters", "POST",
                       {"name": "n", "checkpoint": "p"})
        assert code == 501
        code, _ = _req(url + "/admin/adapters/s", "DELETE")
        assert code == 501
        srv.shutdown()
    finally:
        serving.STATE.engine = old


def test_adapter_label_parse_handles_escapes():
    """The gateway's /metrics scrape parser must round-trip exposition
    label escaping (obs.metrics.escape_label_value) — a tenant name with
    a quote/backslash must not register residency under a wrong name."""
    from datatunerx_tpu.gateway.replica_pool import _adapter_label
    from datatunerx_tpu.obs.metrics import format_sample

    p = "dtx_serving_adapter_resident{"
    for name in ("plain", 'a"b', "a\\b", "a\nb", 'tricky\\"x'):
        line = format_sample("dtx_serving_adapter_resident",
                             {"adapter": name}, 1)
        assert _adapter_label(line, p) == name, (name, line)
    assert _adapter_label(
        'dtx_serving_adapter_resident{adapter="gone"} 0', p) is None
    assert _adapter_label('dtx_other{adapter="x"} 1', p) is None
    assert _adapter_label(
        'dtx_serving_adapter_resident{adapter="unterminated', p) is None


# --------------------------------------------------- gateway e2e routing

def test_gateway_load_on_miss_then_prefers_resident(tmp_path):
    """The acceptance-criterion e2e: a request for a NON-resident adapter
    succeeds (routed to the replica that can load it, which loads on
    admission), and subsequent requests prefer the now-resident replica —
    with the outcome counters, metrics series, and the adapter_route trace
    event to prove each step."""
    from datatunerx_tpu.gateway.replica_pool import (
        InProcessReplica,
        ReplicaPool,
    )
    from datatunerx_tpu.gateway.server import Gateway
    from datatunerx_tpu.serving.adapters import make_adapter_checkpoint
    from datatunerx_tpu.serving.batched_engine import BatchedEngine

    ck = make_adapter_checkpoint(str(tmp_path / "t"), MODEL, seed=7, rank=4)
    e0 = BatchedEngine(MODEL, adapter_pool=1, template="vanilla",
                       max_seq_len=256, slots=2, decode_chunk=4,
                       kv_block_size=16)
    e1 = BatchedEngine(MODEL, adapters={"tenant": ck}, adapter_pool=1,
                       template="vanilla", max_seq_len=256, slots=2,
                       decode_chunk=4, kv_block_size=16)
    pool = ReplicaPool([InProcessReplica("r0", e0),
                        InProcessReplica("r1", e1)])
    gw = Gateway(pool, model_name=MODEL)
    try:
        req = {"messages": [{"role": "user", "content": "hello tenant"}],
               "model": "tenant", "max_tokens": 4}
        # 1st request: tenant resident nowhere → routed to r1 (the only
        # replica that KNOWS it) → load-on-miss at admission succeeds
        assert gw.chat(dict(req), trace_id="dtx-adp-1") is not None
        assert gw.router.adapter_routes["load_miss"] == 1
        assert "tenant" in e1.resident_adapters
        # 2nd request: r1 is now RESIDENT → preferred even though r0 is
        # equally idle (cache locality beats least-busy)
        assert gw.chat(dict(req), trace_id="dtx-adp-2") is not None
        assert gw.router.adapter_routes["resident"] == 1
        assert gw.router.adapter_requests["tenant"] == 2
        assert e0.adapter_requests == {}  # r0 never saw tenant traffic

        # the routing decision is IN the request trace
        doc = gw.trace("dtx-adp-1")
        events = [e for sp in doc["spans"]
                  for e in (sp.get("events") or [])
                  if e.get("name") == "adapter_route"]
        assert events and events[0]["outcome"] == "load_miss"
        doc2 = gw.trace("dtx-adp-2")
        events2 = [e for sp in doc2["spans"]
                   for e in (sp.get("events") or [])
                   if e.get("name") == "adapter_route"]
        assert events2 and events2[0]["outcome"] == "resident"
        assert events2[0]["resident"] == ["r1"]

        # gateway /metrics: outcomes + per-adapter demand + residency map
        text = gw.metrics_text()
        assert ('dtx_gateway_adapter_routes_total{outcome="load_miss"} 1'
                in text)
        assert ('dtx_gateway_adapter_routes_total{outcome="resident"} 1'
                in text)
        assert 'dtx_gateway_adapter_requests_total{adapter="tenant"} 2' in text
        assert ('dtx_gateway_adapter_resident_replicas{adapter="tenant"} 1'
                in text)
        # base traffic is untouched by the preference
        assert gw.chat({"messages": [{"role": "user", "content": "hi"}],
                        "max_tokens": 4}) is not None
    finally:
        gw.close()


# ------------------------------------------------------- operator wiring

def test_serveconfig_adapter_fields_flow_to_flags(tmp_path):
    """serveConfig.adapterPool/adapterRankMax → generate_serving_spec →
    LocalServingBackend argv (the operator path an admin actually uses)."""
    from datatunerx_tpu.operator.api import FinetuneJob
    from datatunerx_tpu.operator.generate import generate_serving_spec
    from datatunerx_tpu.operator.webhooks import (
        AdmissionError,
        _validate_serve_config,
    )

    job = FinetuneJob(
        spec={"finetune": {"finetuneSpec": {"llm": "m", "dataset": "d"}},
              "serveConfig": {"adapterPool": 16, "adapterRankMax": 32,
                              "slots": 4}})
    job.metadata.name = "j"
    spec = generate_serving_spec(job, {"llmPath": str(tmp_path)})
    assert spec["adapter_pool"] == 16 and spec["adapter_rank_max"] == 32

    _validate_serve_config({"adapterPool": 8})
    _validate_serve_config({"adapterPool": 8, "adapterRankMax": 16})
    with pytest.raises(AdmissionError):
        _validate_serve_config({"adapterPool": 0})
    with pytest.raises(AdmissionError, match="requires adapterPool"):
        _validate_serve_config({"adapterRankMax": 8})

    import subprocess
    from unittest import mock

    from datatunerx_tpu.serving.local_backend import LocalServingBackend

    backend = LocalServingBackend(str(tmp_path / "wd"))
    with mock.patch.object(subprocess, "Popen") as popen:
        popen.return_value = mock.Mock(poll=lambda: None)
        backend.deploy("svc", {"model_path": "preset:debug",
                               "adapter_pool": 16, "adapter_rank_max": 32})
    argv = popen.call_args[0][0]
    assert "--adapter_pool" in argv and "16" in argv
    assert "--adapter_rank_max" in argv and "32" in argv
