"""Two-process jax.distributed smoke test of the DTX_* pod-env contract
(parallel/distributed.py): the same envs the operator's JobSet manifests set
(operator/backends.py ManifestBackend) must bootstrap a working multi-process
JAX runtime with a cross-process collective."""

import os
import socket
import subprocess
import sys

import pytest

_WORKER = r"""
import os
import sys
import jax
jax.config.update("jax_platforms", "cpu")
from datatunerx_tpu.parallel.distributed import maybe_initialize_distributed

info = maybe_initialize_distributed(num_workers=2)
assert info["initialized"], info
assert info["num_processes"] == 2, info
assert jax.process_count() == 2
assert jax.device_count() == 2  # one CPU device per process

# cross-process collective: global array summed over both processes
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

mesh = Mesh(np.array(jax.devices()).reshape(2), ("dp",))
pid = jax.process_index()
local = jnp.full((1, 4), pid + 1, jnp.float32)
arr = jax.make_array_from_process_local_data(
    NamedSharding(mesh, P("dp", None)), np.asarray(local))
total = jax.jit(lambda x: x.sum(), out_shardings=NamedSharding(mesh, P()))(arr)
assert float(total) == 12.0, float(total)  # (1+2) * 4
print(f"proc {pid} OK", flush=True)
"""


def _free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.mark.slow
def test_two_process_bootstrap_and_collective(tmp_path):
    port = _free_port()
    procs = []
    pkg_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    for pid in range(2):
        env = dict(os.environ)
        env.update({
            "DTX_COORDINATOR_ADDRESS": f"127.0.0.1:{port}",
            "DTX_NUM_PROCESSES": "2",
            "DTX_PROCESS_ID": str(pid),
            "JAX_PLATFORMS": "cpu",
            "PYTHONPATH": pkg_root + os.pathsep + env.get("PYTHONPATH", ""),
        })
        procs.append(subprocess.Popen(
            [sys.executable, "-c", _WORKER], env=env,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        ))
    outs = []
    for p in procs:
        out, _ = p.communicate(timeout=240)
        outs.append(out)
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"proc {i} failed:\n{out[-2000:]}"
        assert f"proc {i} OK" in out
