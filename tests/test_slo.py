"""SLO plane (obs/slo.py) + histogram exemplars: spec validation, windowed
burn-rate math, /debug/slo on both servers, the promotion guard's SLO mode,
exemplar exposition end-to-end (p99 bucket → trace id → /debug/trace), and
the mixed-version scrape-parser tolerance."""

import json
import threading
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from datatunerx_tpu.obs.metrics import MS_BUCKETS, Registry
from datatunerx_tpu.obs.slo import (
    SLO,
    SLOEvaluator,
    default_slos,
    parse_slos,
    violations,
)
from tests.test_prometheus_exposition import parse_exposition


def _latency_slo(name="ttft", objective=0.9, threshold=250.0,
                 windows=(60.0, 600.0), metric="dtx_serving_ttft_ms"):
    return SLO.from_dict({
        "name": name, "objective": objective, "windows_s": list(windows),
        "sli": {"kind": "latency", "metric": metric,
                "threshold_ms": threshold}})


def _error_slo(name="avail", objective=0.9,
               metric="dtx_serving_requests_total"):
    return SLO.from_dict({
        "name": name, "objective": objective,
        "sli": {"kind": "error_ratio", "metric": metric,
                "bad": {"code": "^5"}}})


# ----------------------------------------------------------------- specs

def test_spec_validation_rejects_bad_configs():
    with pytest.raises(ValueError, match="objective"):
        SLO.from_dict({"name": "x", "objective": 1.0,
                       "sli": {"kind": "latency", "metric": "m",
                               "threshold": 1}})
    with pytest.raises(ValueError, match="kind"):
        SLO.from_dict({"name": "x", "objective": 0.9,
                       "sli": {"kind": "nope", "metric": "m"}})
    with pytest.raises(ValueError, match="threshold"):
        SLO.from_dict({"name": "x", "objective": 0.9,
                       "sli": {"kind": "latency", "metric": "m"}})
    with pytest.raises(ValueError, match="bad"):
        SLO.from_dict({"name": "x", "objective": 0.9,
                       "sli": {"kind": "error_ratio", "metric": "m"}})
    with pytest.raises(ValueError, match="duplicate"):
        parse_slos([{"name": "a", "objective": 0.9,
                     "sli": {"kind": "latency", "metric": "m",
                             "threshold": 1}}] * 2)
    # every plane's defaults must validate
    for plane in ("gateway", "serving", "loadgen"):
        assert default_slos(plane)


# ------------------------------------------------------------- evaluation

def test_latency_windowed_compliance_and_burn_rate():
    reg = Registry()
    h = reg.histogram("dtx_serving_ttft_ms", buckets=MS_BUCKETS)
    import time

    slo = _latency_slo(objective=0.9, threshold=250.0)
    ev = SLOEvaluator(reg, [slo])
    t0 = time.monotonic()  # fake stamps anchored AFTER the ctor baseline
    ev.sample(now=t0)
    # 8 fast + 2 slow = 80% compliance against a 90% objective
    for _ in range(8):
        h.observe(10.0)
    for _ in range(2):
        h.observe(5000.0)
    out = ev.evaluate(now=t0 + 30.0)
    assert len(out) == 1
    w = out[0]["windows"][0]
    assert (w["good"], w["total"]) == (8, 10)
    assert w["compliance"] == pytest.approx(0.8)
    assert w["burn_rate"] == pytest.approx(2.0)  # 20% bad / 10% budget
    assert out[0]["compliant"] is False  # both windows burning > 1.0
    assert out[0]["budget_remaining"] == 0.0
    assert out[0]["threshold_effective"] == 250.0


def test_multi_window_rule_needs_every_window_burning():
    reg = Registry()
    h = reg.histogram("dtx_serving_ttft_ms", buckets=MS_BUCKETS)
    import time

    slo = _latency_slo(objective=0.9, windows=(60.0, 600.0))
    ev = SLOEvaluator(reg, [slo])
    t0 = time.monotonic()
    ev.sample(now=t0)
    for _ in range(98):
        h.observe(10.0)
    ev.sample(now=t0 + 560.0)  # long-window baseline: 98 good, 0 bad
    for _ in range(2):
        h.observe(9000.0)  # a fast-window spike
    out = ev.evaluate(now=t0 + 600.0)[0]
    fast, slow = out["windows"]
    assert fast["burn_rate"] > 1.0          # fast window on fire
    assert slow["burn_rate"] <= 1.0         # 2% bad over the long window
    assert out["compliant"] is True         # not material yet — no page


def test_error_ratio_label_matching():
    reg = Registry()
    c = reg.counter("dtx_serving_requests_total")
    ev = SLOEvaluator(reg, [_error_slo(objective=0.9)])
    ev.sample()
    for code, n in (("200", 7), ("429", 1), ("500", 1), ("503", 1)):
        for _ in range(n):
            c.inc({"code": code})
    v = ev.verdicts()[0]
    # 429 counts as served (good); 5xx are the bad events
    assert (v["good"], v["total"]) == (8, 10)
    assert v["compliant"] is False
    assert "avail" in violations([v])[0]
    assert "0.9" in violations([v])[0]  # the objective is NAMED


def test_counter_reset_clamps_to_zero_delta():
    reg = Registry()
    c = reg.counter("dtx_serving_requests_total")
    for _ in range(5):
        c.inc({"code": "500"})
    ev = SLOEvaluator(reg, [_error_slo()])
    ev.sample()
    c.clear()  # a swapped engine restarting its counters
    v = ev.verdicts()[0]
    assert v["no_data"] is True and v["compliant"] is True


def test_restated_gauges_expose_cleanly():
    reg = Registry()
    h = reg.histogram("dtx_serving_ttft_ms", buckets=MS_BUCKETS)
    ev = SLOEvaluator(reg, default_slos("serving"))
    h.observe(10.0)
    ev.restate_gauges(ev.evaluate())
    samples, types = parse_exposition(reg.expose())
    assert types["dtx_slo_objective"] == "gauge"
    key = ("dtx_slo_compliant", (("slo", "serving-ttft-p95"),))
    assert samples[key] == 1
    assert ("dtx_slo_burn_rate",
            (("slo", "serving-ttft-p95"), ("window", "300s"))) in samples


# -------------------------------------------------------------- exemplars

def test_exemplar_kept_per_bucket_and_exposed():
    reg = Registry()
    h = reg.histogram("dtx_serving_ttft_ms", buckets=MS_BUCKETS)
    h.observe(3.0)                      # no trace id → no exemplar
    assert h.exemplars() == {}
    h.observe(3.0, trace_id="dtx-aa")
    h.observe(4.0, trace_id="dtx-bb")   # same bucket: LAST exemplar wins
    h.observe(9000.0, trace_id="dtx-slow")
    ex = h.exemplars()
    assert ex[5.0][0] == "dtx-bb"
    assert ex[10000.0][0] == "dtx-slow"
    text = reg.expose()
    assert '# {trace_id="dtx-bb"} 4.0' in text
    parse_exposition(text)  # valid format, bucket lines only


def test_exemplar_end_to_end_gateway(tmp_path):
    """Acceptance: a latency bucket's exemplar names a trace id that
    GET /debug/trace/<id> resolves."""
    from datatunerx_tpu.gateway.replica_pool import (
        InProcessReplica,
        ReplicaPool,
    )
    from datatunerx_tpu.gateway.server import Gateway, serve

    class _Eng:
        def chat(self, messages, **kw):
            return "ok"

    gw = Gateway(ReplicaPool([InProcessReplica("r0", _Eng())]),
                 model_name="m")
    srv = serve(gw, port=0, host="127.0.0.1")
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    url = f"http://127.0.0.1:{srv.server_port}"
    try:
        req = urllib.request.Request(
            url + "/chat/completions",
            data=json.dumps({"messages": [
                {"role": "user", "content": "hi"}]}).encode(),
            headers={"Content-Type": "application/json",
                     "X-DTX-Trace-Id": "dtx-exemplar-e2e"}, method="POST")
        with urllib.request.urlopen(req, timeout=10) as r:
            assert r.status == 200
        # the default wire is classic-parser safe: NO exemplar tails
        with urllib.request.urlopen(url + "/metrics", timeout=10) as r:
            plain = r.read().decode()
        assert " # {" not in plain
        parse_exposition(plain)
        # the explicit debug view carries them
        with urllib.request.urlopen(url + "/metrics?exemplars=1",
                                    timeout=10) as r:
            text = r.read().decode()
        parse_exposition(text)
        # find the exemplar on the gateway latency histogram and follow it
        tid = None
        for line in text.splitlines():
            if (line.startswith("dtx_gateway_request_latency_seconds_bucket")
                    and "# {trace_id=" in line):
                tid = line.split('trace_id="')[1].split('"')[0]
                break
        assert tid == "dtx-exemplar-e2e"
        with urllib.request.urlopen(
                url + "/debug/trace/" + tid, timeout=10) as r:
            doc = json.load(r)
        assert doc["trace_id"] == tid and doc["spans"]
    finally:
        srv.shutdown()
        gw.close()


def test_engine_tracing_off_observes_no_exemplars():
    """The tracing-off observe path must not attach exemplars (the
    zero-cost contract the token-parity test rides on)."""
    from datatunerx_tpu.obs.metrics import serving_latency_histograms

    reg = Registry()
    ttft, tpot, _ = serving_latency_histograms(reg)
    ttft.observe(5.0)   # what _complete does with tracing=False
    tpot.observe(1.0)
    assert ttft.exemplars() == {} and tpot.exemplars() == {}


# ------------------------------------------------------------- /debug/slo

def test_gateway_debug_slo_http():
    from datatunerx_tpu.gateway.replica_pool import (
        InProcessReplica,
        ReplicaPool,
    )
    from datatunerx_tpu.gateway.server import Gateway, serve

    class _Eng:
        def chat(self, messages, **kw):
            return "ok"

    gw = Gateway(ReplicaPool([InProcessReplica("r0", _Eng())]),
                 model_name="m")
    srv = serve(gw, port=0, host="127.0.0.1")
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    url = f"http://127.0.0.1:{srv.server_port}"
    try:
        gw.chat({"messages": [{"role": "user", "content": "hi"}]},
                trace_id="t1")
        gw.record_request(200)
        with urllib.request.urlopen(url + "/debug/slo", timeout=10) as r:
            doc = json.load(r)
        assert doc["plane"] == "gateway"
        names = {s["name"] for s in doc["slos"]}
        assert {"gateway-availability", "gateway-fast-requests"} <= names
        assert doc["compliant"] is True
    finally:
        srv.shutdown()
        gw.close()


def test_serving_debug_slo_http():
    from datatunerx_tpu.serving import server as serving

    old_engine, old_slo = serving.STATE.engine, serving.STATE.slo
    serving.STATE.engine = None
    serving.STATE.slo = None
    srv = ThreadingHTTPServer(("127.0.0.1", 0), serving.Handler)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{srv.server_port}/debug/slo",
                timeout=10) as r:
            doc = json.load(r)
        assert doc["plane"] == "serving"
        assert {s["name"] for s in doc["slos"]} == {
            "serving-availability", "serving-ttft-p95"}
    finally:
        srv.shutdown()
        serving.STATE.engine = old_engine
        serving.STATE.slo = old_slo


# ------------------------------------------------- scrape-parser tolerance

def test_http_replica_scrape_tolerates_exemplars():
    """Mixed-version fleet regression: a replica whose /metrics carries
    exemplar annotations (and unknown trailing annotations) must still
    scrape-parse into stats."""
    from datatunerx_tpu.gateway.replica_pool import HTTPReplica

    exposition = "\n".join([
        "# TYPE dtx_serving_slots_busy gauge",
        "dtx_serving_slots_busy 2",
        "# TYPE dtx_serving_slots_capacity gauge",
        "dtx_serving_slots_capacity 4 # future-annotation",
        "# TYPE dtx_serving_kv_blocks_free gauge",
        "dtx_serving_kv_blocks_free 77",
        "# TYPE dtx_serving_kv_blocks_capacity gauge",
        "dtx_serving_kv_blocks_capacity 128",
        "# TYPE dtx_serving_adapter_resident gauge",
        'dtx_serving_adapter_resident{adapter="t-a"} 1',
        '# TYPE dtx_serving_adapter_registered gauge',
        'dtx_serving_adapter_registered{adapter="t-a"} 1',
        'dtx_serving_adapter_registered{adapter="t # b"} 1',
        "# TYPE dtx_serving_ttft_ms histogram",
        'dtx_serving_ttft_ms_bucket{le="5.0"} 3 '
        '# {trace_id="dtx-abc"} 4.2 1700000000.1',
        'dtx_serving_ttft_ms_bucket{le="+Inf"} 3',
        "dtx_serving_ttft_ms_sum 12.0",
        "dtx_serving_ttft_ms_count 3",
    ]) + "\n"

    class _H(BaseHTTPRequestHandler):
        def do_GET(self):
            body = exposition.encode()
            self.send_response(200)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *a):
            pass

    srv = ThreadingHTTPServer(("127.0.0.1", 0), _H)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    try:
        rep = HTTPReplica("r0", f"http://127.0.0.1:{srv.server_port}")
        st = rep.stats()
        assert st["slots_busy"] == 2 and st["slots_total"] == 4
        assert st["kv_blocks_free"] == 77 and st["kv_blocks_total"] == 128
        assert st["resident_adapters"] == {"t-a"}
        # a label VALUE containing " # " is data, not an annotation
        assert st["adapters"] == {"t-a", "t # b"}
    finally:
        srv.shutdown()


# ------------------------------------------------------ promotion SLO mode

def test_promotion_slo_verdict_mode_rolls_back_naming_objective():
    from datatunerx_tpu.experiment.promotion import (
        PromotionConfig,
        PromotionController,
        ROLLED_BACK,
        SHIFTING,
    )
    from datatunerx_tpu.gateway.replica_pool import (
        InProcessReplica,
        ReplicaPool,
    )
    from datatunerx_tpu.gateway.server import Gateway

    class _Eng:
        def chat(self, messages, **kw):
            return "ok"

    pool = ReplicaPool([InProcessReplica("fleet-0", _Eng()),
                        InProcessReplica("canary", _Eng())])
    gw = Gateway(pool, model_name="m")
    try:
        cfg = PromotionConfig.from_dict({
            "schedule": [0.5, 1.0], "step_s": 0.0, "min_requests": 1,
            "slo_min_events": 2,
            "slos": [{
                "name": "promo-availability", "objective": 0.99,
                "sli": {"kind": "error_ratio",
                        "metric": "dtx_gateway_requests_total",
                        "bad": {"code": "^5"}}}],
        })
        promo = PromotionController(gw, "canary", config=cfg)
        assert promo.tick() == SHIFTING  # stage 0 begins, SLO sampled
        # stage traffic: mostly healthy, but 5xx blows the 99% objective
        canary = pool.get("canary")
        for _ in range(3):
            canary.record_outcome(True, 1.0)
        for code in (200, 200, 500):
            gw.record_request(code)
        assert promo.tick() == ROLLED_BACK
        assert "promo-availability" in promo.reason
        assert "0.99" in promo.reason
        assert promo.status()["slos"][0]["compliant"] is False
    finally:
        gw.close()


def test_promotion_slo_mode_clean_run_completes():
    from datatunerx_tpu.experiment.promotion import (
        COMPLETED,
        PromotionConfig,
        PromotionController,
        TERMINAL,
    )
    from datatunerx_tpu.gateway.replica_pool import (
        InProcessReplica,
        ReplicaPool,
    )
    from datatunerx_tpu.gateway.server import Gateway

    class _Eng:
        def chat(self, messages, **kw):
            return "ok"

    pool = ReplicaPool([InProcessReplica("fleet-0", _Eng()),
                        InProcessReplica("canary", _Eng())])
    gw = Gateway(pool, model_name="m")
    try:
        cfg = PromotionConfig.from_dict({
            "schedule": [0.5, 1.0], "step_s": 0.0, "min_requests": 1,
            "slo_min_events": 2,
            "slos": [{
                "name": "promo-availability", "objective": 0.99,
                "sli": {"kind": "error_ratio",
                        "metric": "dtx_gateway_requests_total",
                        "bad": {"code": "^5"}}}],
        })
        promo = PromotionController(gw, "canary", config=cfg)
        canary = pool.get("canary")
        for _ in range(24):
            if promo.state in TERMINAL:
                break
            canary.record_outcome(True, 1.0)
            gw.record_request(200)
            promo.tick()
        assert promo.state == COMPLETED
    finally:
        gw.close()


def test_promotion_slo_guard_runs_with_zero_canary_traffic():
    """A fleet-wide SLO breach rolls the promotion back even when the
    stage routed no requests to the canary (the SLO judges the gateway's
    registry, not the canary's outcome window)."""
    from datatunerx_tpu.experiment.promotion import (
        PromotionConfig,
        PromotionController,
        ROLLED_BACK,
        SHIFTING,
    )
    from datatunerx_tpu.gateway.replica_pool import (
        InProcessReplica,
        ReplicaPool,
    )
    from datatunerx_tpu.gateway.server import Gateway

    class _Eng:
        def chat(self, messages, **kw):
            return "ok"

    pool = ReplicaPool([InProcessReplica("fleet-0", _Eng()),
                        InProcessReplica("canary", _Eng())])
    gw = Gateway(pool, model_name="m")
    try:
        cfg = PromotionConfig.from_dict({
            "schedule": [0.5, 1.0], "step_s": 0.0, "min_requests": 1,
            "slo_min_events": 2,
            "slos": [{
                "name": "fleet-availability", "objective": 0.99,
                "sli": {"kind": "error_ratio",
                        "metric": "dtx_gateway_requests_total",
                        "bad": {"code": "^5"}}}],
        })
        promo = PromotionController(gw, "canary", config=cfg)
        assert promo.tick() == SHIFTING
        # fleet-wide 5xx during the stage; the canary served NOTHING
        for code in (200, 500, 500):
            gw.record_request(code)
        assert promo.tick() == ROLLED_BACK
        assert "fleet-availability" in promo.reason
    finally:
        gw.close()
