"""KV overcommit plane (ISSUE 15): refcounted copy-on-write block sharing,
on-demand table growth with youngest-first preemption, and fleet-true
gateway admission. The correctness bar everywhere is the paged engine's
original one — overcommit must be INVISIBLE in the tokens (growth, COW
mapping and preempt/resume all token-exact vs the eager engine) — while
the capacity win (more concurrent sessions on the same pool) and the
gateway's live free-block shed threshold are asserted directly."""

import json
import threading
import time
import urllib.request
from http.server import ThreadingHTTPServer

import pytest

from datatunerx_tpu.ops.paged_attention import (
    BlockAllocator,
    BlockAllocatorError,
)
from datatunerx_tpu.serving.batched_engine import BatchedEngine

MODEL = "preset:debug"


# --------------------------------------------------- allocator refcounts

def test_allocator_refcount_share_copy_free_ordering():
    """The COW substrate: alloc at ref 1, incref adds owners, every owner
    calls plain free, the block returns to the free list only at ref 0 —
    in ANY release order."""
    a = BlockAllocator(6)
    held = a.alloc(3)  # [0, 1, 2]
    assert [a.refcount(b) for b in held] == [1, 1, 1]
    a.incref(held[:2])  # a prefix-cache entry maps blocks 0, 1
    assert a.refcount(0) == 2 and a.refcount(2) == 1
    # first owner releases: shared blocks stay live, exclusive one frees
    a.free(held)
    assert a.refcount(0) == 1 and a.refcount(2) == 0
    assert a.free_count == 4  # 2 shared blocks still out
    # the freed exclusive block is reissuable while shares persist
    assert a.alloc(4) == [2, 3, 4, 5]
    # second owner releases in the other order
    a.incref([0])
    a.free([0, 1])
    assert a.refcount(0) == 1 and a.refcount(1) == 0
    a.free([0])
    assert a.refcount(0) == 0
    a.free([2, 3, 4, 5])
    assert a.free_count == 6


def test_allocator_refcount_typed_errors_preserved():
    """PR 13's corruption contract survives refcounting: double-frees,
    out-of-range ids, in-call duplicates, and increfs of free blocks all
    raise the typed error BEFORE any mutation."""
    a = BlockAllocator(4)
    held = a.alloc(2)
    a.incref(held)
    a.free(held)
    a.free(held)  # second owner — legitimate
    with pytest.raises(BlockAllocatorError):
        a.free(held)  # third free of a ref-0 block = double-free
    with pytest.raises(BlockAllocatorError):
        a.incref([0])  # incref of a FREE block = same corruption class
    with pytest.raises(BlockAllocatorError):
        a.incref([9])
    b = a.alloc(1)
    with pytest.raises(BlockAllocatorError):
        a.free([b[0], b[0]])  # duplicates in one call
    assert a.refcount(b[0]) == 1  # rejected calls changed nothing
    assert isinstance(BlockAllocatorError("x"), ValueError)


# ------------------------------------------------------- engine fixtures

@pytest.fixture(scope="module")
def dense():
    eng = BatchedEngine(MODEL, template="vanilla", max_seq_len=256,
                        slots=2, decode_chunk=4)
    yield eng
    eng.close()


@pytest.fixture(scope="module")
def over_cow():
    """Overcommit + COW prefix blocks; roomy pool so admission itself
    never gates the parity runs."""
    eng = BatchedEngine(MODEL, template="vanilla", max_seq_len=256,
                        slots=2, decode_chunk=4, kv_block_size=16,
                        kv_overcommit="on", prefix_cache=4)
    yield eng
    eng.close()


# ----------------------------------------------- COW token-exactness

def test_cow_reuse_and_extend_match_dense_copy_path(dense, over_cow):
    """The tentpole's exactness bar: COW block mapping (exact hit) and
    shared-prefix + chunked-suffix admission (strict-prefix hit) produce
    the same tokens as the dense engine — greedy AND fixed-seed sampled —
    and the trace shows the COW paths actually ran."""
    tok = dense.tokenizer
    p1 = tok.encode("shared system prompt for every request here")
    want1 = dense.generate(p1, max_new_tokens=10)
    assert over_cow.generate(p1, max_new_tokens=10) == want1  # cold
    assert over_cow.generate(p1, max_new_tokens=10) == want1  # COW reuse
    p2 = tok.encode("shared system prompt for every request here plus")
    want2 = dense.generate(p2, max_new_tokens=10)
    assert over_cow.generate(p2, max_new_tokens=10) == want2  # COW extend
    assert over_cow.prefill_stats["reuse"] >= 1
    assert over_cow.prefill_stats["extend"] >= 1
    modes = {e[3] for e in over_cow.sched_trace if e[0] == "admit"}
    assert "cow" in modes and "cow_extend" in modes, modes
    # fixed-seed sampled through a COW reuse: bit-identical logits + the
    # slot's own rng stream → identical tokens
    for seed in (0, 7):
        w = dense.generate(p1, max_new_tokens=10, temperature=0.8,
                           top_p=0.9, seed=seed)
        g = over_cow.generate(p1, max_new_tokens=10, temperature=0.8,
                              top_p=0.9, seed=seed)
        assert g == w, (seed, g, w)


def test_cow_block_accounting_shares_then_releases(over_cow):
    """Slots decref on release while cache entries keep their shares: the
    only blocks still out after the traffic above are the prefix-cache
    entries', each at refcount exactly 1, and dropping the cache returns
    the pool to full."""
    ents = [e for e in over_cow._prefix._d.values() if e.get("blocks")]
    assert ents, "COW cache holds no block entries"
    alloc = over_cow._allocator
    # entries SHARE physical blocks with each other (an extended prefix's
    # entry increfs its parent's full blocks): the reserved count is the
    # UNIQUE block set, and each block's refcount equals its owner count
    owners: dict = {}
    for e in ents:
        for b in e["blocks"]:
            owners[b] = owners.get(b, 0) + 1
    assert (over_cow.total_kv_blocks - over_cow.free_kv_blocks
            == len(owners))
    for b, n in owners.items():
        assert alloc.refcount(b) == n, (b, n, alloc.refcount(b))
    while over_cow._prefix.pop_lru_block_entry() is not None:
        pass  # pop hands ownership to us...
    for e in ents:
        alloc.free(e["blocks"])  # ...and we release it
    assert over_cow.free_kv_blocks == over_cow.total_kv_blocks


def test_cow_int8_kv_parity():
    eager = BatchedEngine(MODEL, template="vanilla", max_seq_len=256,
                          slots=2, decode_chunk=4, kv_block_size=16,
                          kv_quant="int8")
    cow = BatchedEngine(MODEL, template="vanilla", max_seq_len=256,
                        slots=2, decode_chunk=4, kv_block_size=16,
                        kv_quant="int8", kv_overcommit="on",
                        prefix_cache=4)
    try:
        prompt = eager.tokenizer.encode("quantized overcommit probe")
        for kw in ({}, {"temperature": 0.7, "top_p": 0.9, "seed": 11}):
            want = eager.generate(prompt, max_new_tokens=8, **kw)
            assert cow.generate(prompt, max_new_tokens=8, **kw) == want
            # second pass rides the COW reuse path (int8 scale pools copy
            # with the tail block)
            assert cow.generate(prompt, max_new_tokens=8, **kw) == want
        assert cow.prefill_stats["reuse"] >= 1
    finally:
        eager.close()
        cow.close()


def test_cow_pooled_adapter_parity(tmp_path):
    """Mixed-rank pooled LoRA adapters through COW admission: prefix
    entries key by adapter name, so each tenant reuses only its own
    prefix — token-exact vs the eager pooled engine."""
    from datatunerx_tpu.serving.adapters import make_adapter_checkpoint

    cks = {n: make_adapter_checkpoint(str(tmp_path / n), MODEL,
                                      seed=3 + i, rank=2 * (i + 1))
           for i, n in enumerate(("a", "b"))}
    eager = BatchedEngine(MODEL, adapters=cks, adapter_pool=2,
                          adapter_rank_max=8, template="vanilla",
                          max_seq_len=256, slots=2, decode_chunk=4,
                          kv_block_size=16)
    cow = BatchedEngine(MODEL, adapters=cks, adapter_pool=2,
                        adapter_rank_max=8, template="vanilla",
                        max_seq_len=256, slots=2, decode_chunk=4,
                        kv_block_size=16, kv_overcommit="on",
                        prefix_cache=4)
    try:
        prompt = eager.tokenizer.encode("tenant isolation overcommit probe")
        want = {}
        for adapter in ("", "a", "b"):
            want[adapter] = eager.generate(prompt, max_new_tokens=8,
                                           adapter=adapter)
            assert cow.generate(prompt, max_new_tokens=8,
                                adapter=adapter) == want[adapter]
            assert cow.generate(prompt, max_new_tokens=8,
                                adapter=adapter) == want[adapter]  # reuse
        assert want["a"] != want[""] and want["b"] != want[""]
        assert cow.prefill_stats["reuse"] >= 2
    finally:
        eager.close()
        cow.close()


# -------------------------------- growth, preemption, liveness, resume

def test_growth_under_exhaustion_liveness_and_exact_resume():
    """The preemption policy's whole contract on one tiny pool: every
    request completes (the oldest is never preempted, so forward progress
    is guaranteed — no deadlock), preempted sessions resume TOKEN-EXACTLY
    (live rng over the wire payload, greedy and sampled), and the pool is
    whole afterwards."""
    ref = BatchedEngine(MODEL, template="vanilla", max_seq_len=256,
                        slots=4, decode_chunk=4, kv_block_size=16)
    eng = BatchedEngine(MODEL, template="vanilla", max_seq_len=256,
                        slots=4, decode_chunk=4, kv_block_size=16,
                        kv_blocks=20, kv_overcommit="on")
    try:
        prompts = [eng.tokenizer.encode(f"request number {i} probing growth")
                   for i in range(4)]
        kws = [{}, {"temperature": 0.8, "top_p": 0.9, "seed": 3},
               {}, {"temperature": 0.7, "top_p": 0.95, "seed": 9}]
        want = [ref.generate(p, max_new_tokens=80, **kw)
                for p, kw in zip(prompts, kws)]
        reqs = [eng.submit(p, max_new_tokens=80, **kw)
                for p, kw in zip(prompts, kws)]
        for i, r in enumerate(reqs):
            assert r.done.wait(300), f"request {i} stalled (deadlock?)"
            assert r.error is None, (i, r.error)
            assert r.tokens == want[i], f"request {i} diverged after resume"
        # 4 sessions on a 20-block pool each growing toward ~9 blocks MUST
        # have preempted — and every export round-tripped back
        assert eng.preempt_stats.get("exported", 0) >= 1, eng.preempt_stats
        assert (eng.preempt_stats.get("resumed", 0)
                == eng.preempt_stats.get("exported", 0))
        assert eng.kv_stats["peak_sessions"] == 4
        assert eng.free_kv_blocks == eng.total_kv_blocks == 20
        # lazy reserve is visible in the ledger: eager would have wanted
        # far more than the pool holds at peak
        assert max(eng.kv_stats["session_blocks"]) <= 20
    finally:
        ref.close()
        eng.close()


def test_oldest_request_never_preempted():
    """The forward-progress invariant, asserted on the trace: no preempt
    event ever names the oldest live request's seq."""
    eng = BatchedEngine(MODEL, template="vanilla", max_seq_len=256,
                        slots=4, decode_chunk=4, kv_block_size=16,
                        kv_blocks=20, kv_overcommit="on")
    try:
        prompts = [eng.tokenizer.encode(f"victim ordering probe {i}")
                   for i in range(4)]
        reqs = [eng.submit(p, max_new_tokens=64) for p in prompts]
        for r in reqs:
            assert r.done.wait(300) and r.error is None
        preempted_seqs = {e[2] for e in eng.sched_trace
                          if e[0] in ("preempt", "preempt_prefill")}
        assert preempted_seqs, "pool never contended — test is vacuous"
        oldest = min(r.seq for r in reqs)
        assert oldest not in preempted_seqs
    finally:
        eng.close()


def test_overcommit_metrics_and_flag_validation():
    with pytest.raises(ValueError, match="kv_block_size"):
        BatchedEngine(MODEL, template="vanilla", max_seq_len=256, slots=2,
                      kv_overcommit="on")  # dense cache: nothing to grow
    with pytest.raises(ValueError, match="on|off"):
        BatchedEngine(MODEL, template="vanilla", max_seq_len=256, slots=2,
                      kv_block_size=16, kv_overcommit="sometimes")
    from datatunerx_tpu.serving import server as serving

    eng = BatchedEngine(MODEL, template="vanilla", max_seq_len=256,
                        slots=2, decode_chunk=4, kv_block_size=16,
                        kv_blocks=18, kv_overcommit="on")
    try:
        # off is the DEFAULT: a plain paged engine reserves eagerly
        assert not BatchedEngine.__init__.__defaults__ or True
        req = eng.submit(eng.tokenizer.encode("metrics probe"),
                         max_new_tokens=48)
        peak_ratio = 0.0
        deadline = time.time() + 300
        while not req.done.is_set() and time.time() < deadline:
            r = eng.kv_overcommit_ratio
            if r is not None:
                peak_ratio = max(peak_ratio, r)
            time.sleep(0.002)
        assert req.done.wait(300) and req.error is None
        # one live session demanding ceil((64+48)/16)=7 eager blocks on an
        # 18-block pool → ratio observed near 7/18
        assert peak_ratio > 0.0
        old = serving.STATE.engine
        serving.STATE.engine = eng
        try:
            text = serving.metrics_text()
        finally:
            serving.STATE.engine = old
        assert "dtx_serving_kv_blocks_reserved " in text
        assert "dtx_serving_kv_overcommit_ratio " in text
        assert "dtx_serving_kv_block_size 16" in text
        assert "dtx_serving_preemptions_total{" in text or \
            "# TYPE dtx_serving_preemptions_total counter" in text
    finally:
        eng.close()


def test_overcommit_off_reserves_eagerly_byte_identical():
    """--kv_overcommit off IS today's engine: the admission reserve is the
    full ceil((plen+max_new)/bs) up front, nothing ever preempts, the COW
    machinery never engages, and (given identical logits) the tokens
    match the overcommit engine's — the two modes differ only in WHEN
    blocks are held."""
    eng = BatchedEngine(MODEL, template="vanilla", max_seq_len=256,
                        slots=2, decode_chunk=4, kv_block_size=16,
                        kv_overcommit="off", prefix_cache=4)
    try:
        assert not eng.overcommit and not eng.cow
        assert eng._reserve_depth(64, 100) == 164  # eager math
        req = eng.submit(eng.tokenizer.encode("hi"), max_new_tokens=48)
        peak = 0
        deadline = time.time() + 300
        while not req.done.is_set() and time.time() < deadline:
            peak = max(peak, eng.total_kv_blocks - eng.free_kv_blocks)
            time.sleep(0.002)
        assert req.done.wait(300) and req.error is None
        # plen=64 + max_new=48 → exactly 7 blocks of 16, reserved up front
        assert peak == 7, peak
        assert eng.preempt_stats == {}
        # stored prefix entries are dense rows (trimmed), never blocks
        assert all(not e.get("blocks") for e in eng._prefix._d.values())
    finally:
        eng.close()


# ------------------------------------------- fleet-true gateway admission

class _BlockReplica:
    """A stats-only replica reporting a settable paged-KV inventory."""

    def __new__(cls, *a, **kw):
        from datatunerx_tpu.gateway.replica_pool import Replica

        class _Impl(Replica):
            def __init__(self, name, free, total=100, bs=16):
                super().__init__(name)
                self._st = {"slots_busy": 0, "slots_total": 4,
                            "kv_blocks_free": free, "kv_blocks_total": total,
                            "kv_block_size": bs, "adapters": None,
                            "resident_adapters": None,
                            "spec_enabled": False, "spec_accept_rate": None}

            def set_free(self, n):
                self._st["kv_blocks_free"] = n

            def stats(self):
                return dict(self._st)

            def probe_health(self):
                return True

            def chat(self, messages, **kw):
                return "ok"

            def chat_stream(self, messages, **kw):
                yield "ok"

        return _Impl(*a, **kw)


def test_gateway_sheds_on_live_fleet_free_block_sum():
    """The acceptance criterion's unit test: shrink the replicas' reported
    free blocks and watch the 429 threshold MOVE — admission is priced
    against the live fleet sum (prompt estimate + decode headroom, in
    blocks), not a static token budget."""
    from datatunerx_tpu.gateway.admission import (
        AdmissionController,
        Overloaded,
    )
    from datatunerx_tpu.gateway.replica_pool import ReplicaPool
    from datatunerx_tpu.gateway.server import Gateway

    r0 = _BlockReplica("r0", free=40, total=60)
    r1 = _BlockReplica("r1", free=40, total=60)
    pool = ReplicaPool([r0, r1])
    gw = Gateway(pool, admission=AdmissionController(
        pending_window_s=0.0))  # no pending carry: thresholds exact
    try:
        assert gw.fleet_kv_blocks() == {"free": 80, "total": 120,
                                        "block_size": 16}
        messages = [{"role": "user", "content": "x" * 160}]
        # estimate = 160/4 + 4 = 44 tokens; need = ceil((44+64)/16) = 7
        need = gw.admission.blocks_for_admit(
            gw.admission.estimate(messages), 16)
        assert need == 7
        assert gw.chat({"messages": messages}) == "ok"
        # fleet shrinks BELOW the admit price → shed, Retry-After attached
        for r in (r0, r1):
            r.set_free(3)
        with pytest.raises(Overloaded) as exc:
            gw.chat({"messages": messages})
        assert "fleet KV blocks" in str(exc.value.reason)
        assert exc.value.retry_after_s >= 1
        shed_at_6 = gw.admission.shed_count
        # threshold MOVES with the reports: exactly `need` free admits again
        r0.set_free(need)
        assert gw.chat({"messages": messages}) == "ok"
        assert gw.admission.shed_count == shed_at_6
        # dense fleet (no block signal) → static budget only, no shed
        r0._st["kv_blocks_total"] = 0
        r1._st["kv_blocks_total"] = 0
        r0.set_free(0)
        r1.set_free(0)
        assert gw.fleet_kv_blocks() is None
        assert gw.chat({"messages": messages}) == "ok"
    finally:
        gw.close()


def test_autoscale_hint_derives_from_fleet_blocks():
    from datatunerx_tpu.gateway.autoscale import autoscale_hint, parse_hint

    base = dict(replicas=2, available_replicas=2, queue_depth=0,
                queued_tokens=0, shed_count=0, p95_latency_s=0.5,
                shed_recent=0)
    low = autoscale_hint(**base, fleet_blocks={"free": 5, "total": 100})
    assert low["desiredReplicas"] == 3
    assert "KV blocks low" in low["reason"]
    assert low["fleetKvBlocksFree"] == 5
    assert low["fleetKvBlocksTotal"] == 100
    ok = autoscale_hint(**base, fleet_blocks={"free": 60, "total": 100})
    assert ok["desiredReplicas"] <= 2
    # the hint document still round-trips the operator-side validator
    assert parse_hint(json.loads(json.dumps(low))) is not None

    # wired end to end: the gateway's /autoscale body names blocks when
    # the live fleet sum is the binding signal
    from datatunerx_tpu.gateway.replica_pool import ReplicaPool
    from datatunerx_tpu.gateway.server import Gateway

    pool = ReplicaPool([_BlockReplica("r0", free=4, total=100)])
    gw = Gateway(pool)
    try:
        hint = gw.autoscale()
        assert hint["fleetKvBlocksFree"] == 4
        assert "KV blocks low" in hint["reason"]
        assert hint["desiredReplicas"] == 2
    finally:
        gw.close()


# ------------------------------------- truthful token counts on the wire

class _CharTokenizer:
    eos_token_id = 0

    def encode(self, text, add_special_tokens=True):
        return [ord(c) % 96 + 1 for c in str(text)]

    def decode(self, ids, skip_special_tokens=True):
        return "x" * len(ids)


class _UsageEngine:
    """Duck-typed engine with a REAL (char-level) tokenizer count behind
    _encode_chat — what the serving wire's usage must carry."""

    def __init__(self):
        self.tokenizer = _CharTokenizer()

    def _encode_chat(self, messages):
        text = "\n".join(str(m.get("content", "")) for m in messages)
        return self.tokenizer.encode(text), [0]

    def chat(self, messages, **kw):
        return "fine"

    def chat_stream(self, messages, **kw):
        yield "fi"
        yield "ne"


@pytest.fixture()
def usage_server():
    from datatunerx_tpu.serving import server as serving

    old_engine = serving.STATE.engine
    old_model = serving.STATE.model_path
    serving.STATE.engine = _UsageEngine()
    serving.STATE.model_path = "usage-test"
    srv = ThreadingHTTPServer(("127.0.0.1", 0), serving.Handler)
    th = threading.Thread(target=srv.serve_forever, daemon=True)
    th.start()
    try:
        yield f"http://127.0.0.1:{srv.server_address[1]}"
    finally:
        srv.shutdown()
        srv.server_close()
        serving.STATE.engine = old_engine
        serving.STATE.model_path = old_model


def test_serving_response_carries_tokenized_prompt_length(usage_server):
    messages = [{"role": "user", "content": "how long is this, really?"}]
    want = len(_UsageEngine()._encode_chat(messages)[0])
    body = json.dumps({"messages": messages}).encode()
    req = urllib.request.Request(
        usage_server + "/v1/chat/completions", data=body,
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=10) as r:
        doc = json.load(r)
    assert doc["usage"]["prompt_tokens"] == want
    assert doc["usage"]["total_tokens"] >= want
    # streaming: the terminal chunk carries the same count
    req = urllib.request.Request(
        usage_server + "/v1/chat/completions",
        data=json.dumps({"messages": messages, "stream": True}).encode(),
        headers={"Content-Type": "application/json"})
    seen = None
    with urllib.request.urlopen(req, timeout=10) as r:
        for raw in r:
            line = raw.decode().strip()
            if not line.startswith("data: ") or line == "data: [DONE]":
                continue
            evt = json.loads(line[len("data: "):])
            if "usage" in evt:
                seen = evt["usage"]
    assert seen == {"prompt_tokens": want}


def test_http_admission_equals_inprocess_admission(usage_server):
    """The regression test the satellite names: after one request through
    each replica flavor, both gateways' admission estimators have
    calibrated against the SAME replica-side tokenized count — an HTTP
    fleet admits exactly like an in-process one for the same prompt,
    instead of diverging on the chars-per-token heuristic."""
    from datatunerx_tpu.gateway.admission import AdmissionController
    from datatunerx_tpu.gateway.replica_pool import (
        HTTPReplica,
        InProcessReplica,
        ReplicaPool,
    )
    from datatunerx_tpu.gateway.server import Gateway

    messages = [{"role": "user", "content":
                 "calibration probe with a decently long prompt body"}]
    gw_http = Gateway(ReplicaPool([HTTPReplica("r0", usage_server)]),
                      admission=AdmissionController())
    gw_in = Gateway(ReplicaPool([InProcessReplica("r0", _UsageEngine())]),
                    admission=AdmissionController())
    try:
        before = gw_http.admission.estimate(messages)
        assert gw_http.chat({"messages": messages}) == "fine"
        assert gw_in.chat({"messages": messages}) == "fine"
        est_http = gw_http.admission.estimate(messages)
        est_in = gw_in.admission.estimate(messages)
        assert est_http == est_in
        assert abs(gw_http.admission.chars_per_token
                   - gw_in.admission.chars_per_token) < 1e-9
        # ...and calibration actually acted (char-level tokenizer → the
        # real ratio is ~1, far from the 4.0 heuristic)
        assert est_http > before
    finally:
        gw_http.close()
        gw_in.close()


# --------------------------------------------- chaos replay at overcommit

def test_replay_with_drain_at_overcommit_zero_5xx_zero_reprefill():
    """`dtx replay`-shaped chaos run on REAL overcommitted engines behind
    a real Gateway: a drain fires while the tight pools are preempting —
    sessions hand off (parked ones included), nothing 5xxes, and nothing
    re-prefills (preemption resume is a KV re-install, not a prefill)."""
    from datatunerx_tpu.gateway.admission import AdmissionController
    from datatunerx_tpu.gateway.replica_pool import (
        InProcessReplica,
        ReplicaPool,
    )
    from datatunerx_tpu.gateway.server import Gateway
    from datatunerx_tpu.loadgen.chaos import ChaosInjector
    from datatunerx_tpu.loadgen.replay import (
        LocalClient,
        ReplayRunner,
        drain_when_busy,
    )
    from datatunerx_tpu.loadgen.workload import WorkloadModel

    engines = [
        BatchedEngine(MODEL, template="vanilla", max_seq_len=128,
                      slots=2, decode_chunk=4, kv_block_size=16,
                      kv_blocks=10, kv_overcommit="on")
        for _ in range(2)
    ]
    pool = ReplicaPool([InProcessReplica(f"replica-{i}", e)
                        for i, e in enumerate(engines)])
    # static budget only: this test isolates ENGINE overcommit under
    # chaos; the fleet-block shed threshold has its own unit test above
    gw = Gateway(pool, model_name=MODEL,
                 admission=AdmissionController(
                     token_budget=10**6, fleet_blocks_fn=lambda: None))
    try:
        engines[0].generate(engines[0].tokenizer.encode("warm up"),
                            max_new_tokens=2)
        admits0 = sum(sum(e.prefill_stats.values()) for e in engines)
        wl = WorkloadModel(requests=10, sessions=3, rps=50, seed=7,
                           prompt_chars=40, prompt_cap_chars=120,
                           output_tokens=32, output_cap_tokens=48)
        events = wl.generate()
        mid = max(events[-1]["t"] * 0.5, 0.05)
        chaos = ChaosInjector(
            [{"t": round(mid, 3), "op": "drain", "replica": "replica-1"}],
            {"drain": lambda op: drain_when_busy(gw, op["replica"])})
        runner = ReplayRunner(LocalClient(gw), max_inflight=8)
        report = runner.run(events, chaos=chaos)
        assert report["errors"] == 0, report["codes"]
        handoff = gw.handoff_stats()
        assert handoff.get("cold", 0) == 0, handoff
        admissions = (sum(sum(e.prefill_stats.values()) for e in engines)
                      - admits0)
        requeued = sum(e.preempt_stats.get("requeued_prefill", 0)
                       for e in engines)
        re_prefills = admissions - report["requests"] - requeued
        assert re_prefills == 0, (
            f"{re_prefills} session(s) re-prefilled "
            f"(admissions={admissions}, requests={report['requests']})")
    finally:
        gw.close()
