"""Device health probe (VERDICT round-1 item 9): while the accelerator is
wedged, new Finetunes hold in Pending rather than being submitted; recovery
resumes submission."""

from datatunerx_tpu.operator.api import Finetune, ObjectMeta
from datatunerx_tpu.operator.backends import FakeServingBackend, FakeTrainingBackend
from datatunerx_tpu.operator.health import DeviceHealthProbe, probe_device_once
from datatunerx_tpu.operator.manager import build_manager
from datatunerx_tpu.operator.store import ObjectStore
from tests.test_operator import _seed_deps


class FakeProbe:
    def __init__(self, healthy=True):
        self.healthy = healthy
        self.last_error = None if healthy else "device probe hung (> 90s)"


def _world(probe):
    store = ObjectStore()
    training = FakeTrainingBackend()
    mgr = build_manager(store, training, FakeServingBackend(),
                        storage_path="/tmp/x", with_scoring=False,
                        health_probe=probe)
    _seed_deps(store)
    return store, training, mgr


def _finetune(name="hrun"):
    return Finetune(metadata=ObjectMeta(name=name), spec={
        "llm": "llama2-7b", "dataset": "ds-a",
        "hyperparameter": {"hyperparameterRef": "hp-a"},
        "image": {"path": "/m"},
    })


def test_unhealthy_device_holds_submission():
    probe = FakeProbe(healthy=False)
    store, training, mgr = _world(probe)
    store.create(_finetune())
    mgr.run_until_idle()
    obj = store.get(Finetune, "hrun")
    assert obj.status["state"] == Finetune.STATE_PENDING
    assert "hung" in obj.status["backendUnavailable"]
    assert "hrun" not in training.jobs  # never handed to the backend

    # recovery: probe flips healthy → submission proceeds, note cleared
    probe.healthy = True
    probe.last_error = None
    mgr.enqueue("Finetune", "default", "hrun")
    mgr.drain_scheduled()
    obj = store.get(Finetune, "hrun")
    assert "hrun" in training.jobs
    assert "backendUnavailable" not in obj.status


def test_healthy_probe_does_not_interfere():
    store, training, mgr = _world(FakeProbe(healthy=True))
    store.create(_finetune("hrun2"))
    mgr.run_until_idle()
    assert "hrun2" in training.jobs


def test_probe_device_once_real_subprocess(monkeypatch):
    """Exercise the real subprocess matmul path. The probe code is pinned to
    the CPU backend here because in THIS build environment the default device
    is the tunneled TPU, whose health is exactly what the probe exists to
    question (an un-pinned probe correctly hangs when the relay is wedged)."""
    import datatunerx_tpu.operator.health as health

    monkeypatch.setattr(
        health, "PROBE_CODE",
        "import jax; jax.config.update('jax_platforms', 'cpu');"
        "import jax.numpy as jnp;"
        "x = jnp.ones((256, 256), jnp.float32);"
        "print(float((x @ x)[0, 0]))",
    )
    assert probe_device_once(timeout_s=120.0) is None


def test_probe_detects_failure(monkeypatch):
    import datatunerx_tpu.operator.health as health

    monkeypatch.setattr(health, "PROBE_CODE", "import sys; sys.exit(3)")
    err = probe_device_once(timeout_s=30.0)
    assert err and "exited 3" in err

    p = DeviceHealthProbe(interval_s=999)
    assert p.healthy  # optimistic start
    p.check_now()
    assert not p.healthy and "exited 3" in p.last_error


def test_probe_skips_while_jobs_active(monkeypatch):
    """The probe must not contend with a running trainer for the
    single-client device: busy backend ⇒ no probe run that cycle."""
    import time

    import datatunerx_tpu.operator.health as health

    calls = {"n": 0}

    def fake_probe(timeout_s):
        calls["n"] += 1
        return None

    monkeypatch.setattr(health, "probe_device_once", fake_probe)
    busy = {"v": True}
    p = DeviceHealthProbe(interval_s=0.02, idle_check=lambda: not busy["v"])
    p.start()
    time.sleep(0.15)
    assert calls["n"] == 0  # never probed while busy
    busy["v"] = False
    deadline = time.time() + 2
    while calls["n"] == 0 and time.time() < deadline:
        time.sleep(0.02)
    p.stop()
    assert calls["n"] >= 1  # resumed once idle


def test_local_backend_has_active_jobs(tmp_path):
    import time

    from datatunerx_tpu.operator.backends import LocalProcessBackend

    # CPU env for the child: without it the subprocess initializes the real
    # (possibly wedged) accelerator at import time and never exits
    b = LocalProcessBackend(str(tmp_path), extra_env={
        "JAX_PLATFORMS": "cpu", "PALLAS_AXON_POOL_IPS": ""})
    assert not b.has_active_jobs()
    b.submit("j1", {"args": ["--help"]})  # exits after argparse prints help
    assert b.has_active_jobs()  # live while the subprocess runs
    deadline = time.time() + 180  # jax import in the child is slow under load
    while b.status("j1") == "Running" and time.time() < deadline:
        time.sleep(0.1)
    assert not b.has_active_jobs()
