"""Multi-tenant slice scheduling (SURVEY §7.4 hard part #3): concurrent
Finetunes map to DISJOINT sub-slices; exhausted pool holds jobs in Pending;
terminal states release slices; restarts rebuild assignments."""

import json

import pytest

from datatunerx_tpu.operator.api import Finetune, ObjectMeta
from datatunerx_tpu.operator.backends import (
    FakeServingBackend,
    FakeTrainingBackend,
    ManifestBackend,
)
from datatunerx_tpu.operator.manager import build_manager
from datatunerx_tpu.operator.placement import Slice, SlicePool, pool_from_env
from datatunerx_tpu.operator.store import ObjectStore
from tests.test_operator import _seed_deps


def _pool(n=2, chips=8):
    return SlicePool([
        Slice(f"slice-{i}", topology="2x4", chips=chips,
              node_selector={"cloud.google.com/gke-nodepool": f"pool-{i}"})
        for i in range(n)
    ])


def _finetune(name, node=1):
    return Finetune(metadata=ObjectMeta(name=name), spec={
        "llm": "llama2-7b", "dataset": "ds-a",
        "hyperparameter": {"hyperparameterRef": "hp-a"},
        "image": {"path": "/m"}, "node": node,
    })


# ------------------------------------------------------------------- pool

def test_pool_acquire_release_semantics():
    pool = _pool(2)
    a = pool.acquire("job-a")
    b = pool.acquire("job-b")
    assert a.name != b.name
    assert pool.acquire("job-c") is None  # exhausted
    assert pool.acquire("job-a").name == a.name  # idempotent
    pool.release("job-a")
    assert pool.acquire("job-c") is not None
    assert pool.free_count() == 0


def test_pool_smallest_fit_and_min_chips():
    pool = SlicePool([Slice("big", chips=32), Slice("small", chips=8)])
    assert pool.acquire("j1", min_chips=4).name == "small"  # smallest fit
    assert pool.acquire("j2", min_chips=16).name == "big"
    pool.release("j1")
    assert pool.acquire("j3", min_chips=64) is None  # nothing big enough


def test_pool_from_env(monkeypatch):
    monkeypatch.delenv("TPU_SLICE_POOL", raising=False)
    assert pool_from_env() is None
    monkeypatch.setenv("TPU_SLICE_POOL", json.dumps([
        {"name": "a", "topology": "4x4", "chips": 16,
         "nodeSelector": {"pool": "x"}},
        {"name": "b"},
    ]))
    pool = pool_from_env()
    assert [s.name for s in pool.slices()] == ["a", "b"]
    assert pool.slices()[0].chips == 16
    with pytest.raises(ValueError):
        SlicePool([Slice("dup"), Slice("dup")])


# ------------------------------------------------------------- controller

def test_controller_places_jobs_on_disjoint_slices():
    store = ObjectStore()
    training = FakeTrainingBackend()
    pool = _pool(2)
    mgr = build_manager(store, training, FakeServingBackend(),
                        storage_path="/tmp/x", with_scoring=False,
                        slice_pool=pool)
    _seed_deps(store)
    for n in ("p1", "p2", "p3"):
        store.create(_finetune(n))
    mgr.run_until_idle()

    s1 = store.get(Finetune, "p1").status.get("placement")
    s2 = store.get(Finetune, "p2").status.get("placement")
    assert s1 and s2 and s1["name"] != s2["name"]
    assert training.jobs["p1"]["node_selector"] == s1["nodeSelector"]
    assert training.jobs["p1"]["topology"] == "2x4"
    # hosts + --num_workers must match the ASSIGNED slice (8 chips = 2 hosts),
    # not spec.node — a multi-host podslice needs exactly its host count
    assert training.jobs["p1"]["num_hosts"] == 2
    args = training.jobs["p1"]["args"]
    assert args[args.index("--num_workers") + 1] == "2"

    # third job: pool exhausted → Pending with a reason, NOT submitted
    p3 = store.get(Finetune, "p3")
    assert p3.status["state"] == Finetune.STATE_PENDING
    assert p3.status["placementPending"] == "no free TPU slice"
    assert "p3" not in training.jobs

    # p1 finishes → slice freed → p3 gets placed on requeue
    training.set_state("p1", "Failed")
    mgr.enqueue("Finetune", "default", "p1")
    mgr.drain_scheduled()
    assert store.get(Finetune, "p1").status["state"] == Finetune.STATE_FAILED
    mgr.enqueue("Finetune", "default", "p3")
    mgr.drain_scheduled()
    p3 = store.get(Finetune, "p3")
    assert "p3" in training.jobs
    assert p3.status["placement"]["name"] == s1["name"]  # reused freed slice
    assert "placementPending" not in p3.status


def test_placement_restored_after_operator_restart(tmp_path):
    store = ObjectStore()
    training = FakeTrainingBackend()
    pool = _pool(2)
    mgr = build_manager(store, training, FakeServingBackend(),
                        storage_path="/tmp/x", with_scoring=False,
                        slice_pool=pool)
    _seed_deps(store)
    store.create(_finetune("r1"))
    mgr.run_until_idle()
    taken = store.get(Finetune, "r1").status["placement"]["name"]

    # "restart": fresh pool + manager over the same store
    pool2 = _pool(2)
    build_manager(store, FakeTrainingBackend(), FakeServingBackend(),
                  storage_path="/tmp/x", with_scoring=False, slice_pool=pool2)
    assert pool2.assignment("r1").name == taken
    assert pool2.free_count() == 1


def test_manifest_render_uses_placement_selector(tmp_path):
    backend = ManifestBackend(str(tmp_path))
    manifest = backend.render_training("j", {
        "args": ["--x"], "num_hosts": 1, "topology": "4x4",
        "node_selector": {"cloud.google.com/gke-nodepool": "pool-9"},
    })
    pod = (manifest["spec"]["replicatedJobs"][0]["template"]["spec"]
           ["template"]["spec"])
    assert pod["nodeSelector"]["cloud.google.com/gke-tpu-topology"] == "4x4"
    assert pod["nodeSelector"]["cloud.google.com/gke-nodepool"] == "pool-9"


def test_takeover_restore_rebuilds_not_merges():
    """A standby's stale boot snapshot must be DROPPED at takeover: jobs
    finished/re-placed by the old leader otherwise double-book slices."""
    from datatunerx_tpu.operator.manager import _restore_placements

    store = ObjectStore()
    _seed_deps(store)
    # standby's boot snapshot: job A held slice-0
    pool = _pool(2)
    pool.acquire("A")  # slice-0 (smallest-fit order is by chips, equal here)
    held_by_a = pool.assignment("A").name
    # meanwhile the old leader: A finished, B got that slice
    b = _finetune("B")
    b.status = {"state": Finetune.STATE_RUNNING,
                "placement": {"name": held_by_a}}
    store.create(b)
    a = _finetune("A")
    a.status = {"state": Finetune.STATE_SUCCESSFUL,
                "placement": {"name": held_by_a}}
    store.create(a)

    _restore_placements(store, pool)  # takeover rebuild
    assert pool.assignment("B").name == held_by_a
    assert pool.assignment("A") is None
    # terminal A's release must NOT free B's slice
    pool.release("A")
    assert pool.assignment("B").name == held_by_a
