"""KubeObjectStore against the fake apiserver (envtest-equivalent):
the same store semantics and controller pipeline covered by test_operator.py,
but through the k8s REST adapter — proving the controllers run unchanged
against an apiserver (VERDICT round-1 item 3; reference runs its reconcilers
against a real kube-apiserver via controller-runtime)."""

import time

import pytest

from datatunerx_tpu.operator.api import (
    Dataset,
    Finetune,
    FinetuneJob,
    LLM,
    LLMCheckpoint,
    ObjectMeta,
    Scoring,
)
from datatunerx_tpu.operator.backends import FakeServingBackend, FakeTrainingBackend
from datatunerx_tpu.operator.kubeclient import KubeClient
from datatunerx_tpu.operator.kubestore import KubeObjectStore, from_k8s, to_k8s
from datatunerx_tpu.operator.manager import build_manager
from datatunerx_tpu.operator.store import AlreadyExists, Conflict, NotFound
from datatunerx_tpu.training.checkpoint import write_manifest
from tests.fake_apiserver import FakeKubeApiServer
from tests.test_operator import _job_spec, _seed_deps


@pytest.fixture()
def kube():
    srv = FakeKubeApiServer().start()
    store = KubeObjectStore(KubeClient(base_url=srv.url))
    yield store
    store.stop()
    srv.stop()


def _eventually(mgr, predicate, timeout_s: float = 20.0, gap_s: float = 0.05):
    """envtest-style Eventually(): drive the manager (processing queued work,
    fast-forwarding poll requeues, letting async watch events land) until
    ``predicate()`` holds. Condition-based waiting, NOT an idle heuristic —
    with the conftest fast-poll intervals (0.1–0.2 s) and HTTP-latency
    reconciles, the manager legitimately never LOOKS idle in real time
    (each poll is due again by the time the rest of the queue was serviced),
    so any "queue is quiet" settle check deadlocks by design (VERDICT r4
    weak #3). The assertions below only need their target state to be
    REACHED; this helper waits for exactly that."""
    deadline = time.monotonic() + timeout_s
    while True:
        mgr.run_until_idle(max_wall_s=1.0)
        mgr.drain_scheduled(max_wall_s=1.0)
        try:
            if predicate():
                return
        except Exception:
            pass
        if time.monotonic() > deadline:
            try:
                if predicate():  # reached at the deadline — not a failure
                    return
            except Exception:
                pass  # a raising predicate is still "not reached"
            raise AssertionError(f"condition not reached in {timeout_s}s")
        time.sleep(gap_s)


# ----------------------------------------------------------- store parity

def test_kube_store_crud_conflict_and_cascade(kube):
    llm = LLM(metadata=ObjectMeta(name="m"))
    created = kube.create(llm)
    assert created.metadata.resource_version > 0
    with pytest.raises(AlreadyExists):
        kube.create(llm)

    stale = kube.get(LLM, "m")
    fresh = kube.get(LLM, "m")
    fresh.spec["x"] = 1
    kube.update(fresh)
    stale.spec["x"] = 2
    with pytest.raises(Conflict):
        kube.update(stale)

    # owner cascade (GC)
    child = Scoring(metadata=ObjectMeta(name="c"))
    child.metadata.owner_references.append(
        {"kind": "LLM", "name": "m", "uid": created.metadata.uid})
    kube.create(child)
    kube.delete(LLM, "m")
    with pytest.raises(NotFound):
        kube.get(Scoring, "c")


def test_kube_store_finalizer_gated_deletion(kube):
    ft = Finetune(metadata=ObjectMeta(name="f", finalizers=["x/y"]))
    kube.create(ft)
    kube.delete(Finetune, "f")
    obj = kube.get(Finetune, "f")  # still present
    assert obj.metadata.deletion_timestamp is not None
    obj.metadata.finalizers.remove("x/y")
    kube.update(obj)
    with pytest.raises(NotFound):
        kube.get(Finetune, "f")


def test_kube_store_status_subresource_isolation(kube):
    """A main-resource write cannot smuggle status, and vice versa."""
    llm = LLM(metadata=ObjectMeta(name="s"))
    kube.create(llm)
    obj = kube.get(LLM, "s")
    obj.spec["a"] = 1
    obj.status["b"] = 2
    kube.update(obj)  # store writes both surfaces in one call
    back = kube.get(LLM, "s")
    assert back.spec["a"] == 1 and back.status["b"] == 2

    # raw main PUT with different status must NOT change status
    client = kube.client
    raw = client.get("core.datatunerx.io", "v1beta1", "llms", "default", "s")
    raw["status"] = {"b": 999}
    raw["spec"] = {"a": 5}
    client.replace("core.datatunerx.io", "v1beta1", "llms", "default", "s", raw)
    back = kube.get(LLM, "s")
    assert back.spec["a"] == 5 and back.status["b"] == 2


def test_kube_store_list_label_selector(kube):
    for i, lbl in enumerate(("a", "a", "b")):
        kube.create(LLM(metadata=ObjectMeta(name=f"l{i}", labels={"grp": lbl})))
    assert len(kube.list(LLM)) == 3
    assert [o.metadata.name for o in kube.list(LLM, labels={"grp": "a"})] == ["l0", "l1"]


def test_kube_store_watch_delivers_events(kube):
    seen = []
    kube.watch(lambda ev: seen.append((ev[0], ev[1].metadata.name)))
    time.sleep(0.2)  # watch threads connect
    kube.create(LLM(metadata=ObjectMeta(name="w1")))
    obj = kube.get(LLM, "w1")
    obj.spec["x"] = 1
    kube.update(obj)
    kube.delete(LLM, "w1")
    deadline = time.time() + 5
    while time.time() < deadline:
        types = [t for t, n in seen if n == "w1"]
        if "ADDED" in types and "MODIFIED" in types and "DELETED" in types:
            return
        time.sleep(0.05)
    raise AssertionError(f"missing events, saw {seen}")


def test_roundtrip_conversion():
    ft = Finetune(metadata=ObjectMeta(
        name="r", namespace="ns1", labels={"a": "b"}, finalizers=["f/g"],
    ))
    ft.metadata.owner_references.append(
        {"kind": "FinetuneJob", "name": "j", "uid": "u-1"})
    ft.spec = {"llm": "m"}
    ft.status = {"state": "Running"}
    d = to_k8s(ft)
    assert d["metadata"]["ownerReferences"][0]["apiVersion"] == (
        "finetune.datatunerx.io/v1beta1")
    back = from_k8s(d)
    assert back.metadata.name == "r" and back.metadata.namespace == "ns1"
    assert back.metadata.owner_references == ft.metadata.owner_references
    assert back.spec == ft.spec and back.status == ft.status


# ------------------------------------------------- controllers, unchanged

def test_full_pipeline_against_kube_store(kube, tmp_path):
    """The key VERDICT round-1 'done' criterion: the FinetuneJob pipeline
    state machine runs UNCHANGED against an apiserver-backed store."""
    storage = str(tmp_path / "storage")
    training = FakeTrainingBackend()
    serving = FakeServingBackend()
    mgr = build_manager(kube, training, serving, storage_path=storage,
                        with_scoring=False)
    _seed_deps(kube)

    name = "jobk"
    job = FinetuneJob(metadata=ObjectMeta(name=name), spec=_job_spec("k"))
    job.spec["finetune"]["name"] = f"{name}-finetune"
    kube.create(job)
    _eventually(mgr, lambda: kube.get(FinetuneJob, name).status.get("state")
                == FinetuneJob.STATE_FINETUNE)

    ft_name = f"{name}-finetune"
    ft = kube.get(Finetune, ft_name)

    training.set_state(ft_name, "Succeeded")
    write_manifest(storage, ft.metadata.uid, "/storage/ckpt/7", metrics={"loss": 1.0})
    mgr.enqueue("Finetune", "default", ft_name)
    _eventually(mgr, lambda: kube.get(FinetuneJob, name).status.get("state")
                == FinetuneJob.STATE_SERVE)
    assert name in serving.apps

    serving.set_state(name, "HEALTHY")
    mgr.enqueue("FinetuneJob", "default", name)
    _eventually(mgr, lambda: kube.get(Scoring, name) is not None)
    scoring = kube.get(Scoring, name)
    assert scoring.spec["inferenceService"].endswith("/chat/completions")

    for _ in range(5):  # controller may touch Scoring concurrently
        scoring = kube.get(Scoring, name)
        scoring.status["score"] = "87.5"
        try:
            kube.update(scoring)
            break
        except Conflict:
            continue
    else:
        raise AssertionError("Scoring update lost 5 Conflict races in a row")
    _eventually(mgr, lambda: kube.get(FinetuneJob, name).status.get("state")
                == FinetuneJob.STATE_SUCCESSFUL)

    job = kube.get(FinetuneJob, name)
    assert job.status["result"]["score"] == "87.5"
    assert name in serving.deleted
    assert name in kube.get(LLM, "llama2-7b").status["referenceFinetuneName"]

    # provenance snapshot landed
    ckpt_ref = (job.status["finetuneStatus"]["llmCheckpoint"] or {}).get(
        "llmCheckpointRef")
    ckpt = kube.get(LLMCheckpoint, ckpt_ref)
    assert ckpt.spec["checkpoint"] == "/storage/ckpt/7"

    # deletion cascade: deleting the job tears down children via finalizers
    kube.delete(FinetuneJob, name)
    _eventually(mgr, lambda: kube.try_get("FinetuneJob", name) is None)
    with pytest.raises(NotFound):
        kube.get(FinetuneJob, name)
    assert name not in (
        kube.get(Dataset, "ds-a").status.get("referenceFinetuneName") or [])


def test_watch_recovers_from_410_gone(kube):
    """A compacted-history 410 must reset the bookmark, not wedge the watch
    in a permanent reconnect loop."""
    import urllib.error

    from datatunerx_tpu.operator.kubeclient import KubeClient

    calls = {"n": 0}
    real_urlopen = urllib.request.urlopen

    class FakeResp:
        def __init__(self, lines):
            self._lines = lines

        def __enter__(self):
            return iter(self._lines)

        def __exit__(self, *a):
            return False

    import urllib.request

    def fake_urlopen(req, timeout=None, context=None):
        calls["n"] += 1
        url = req.get_full_url() if hasattr(req, "get_full_url") else str(req)
        if calls["n"] == 1:
            assert "resourceVersion=999" in url
            raise urllib.error.HTTPError(url, 410, "Gone", {}, None)
        # second attempt must come WITHOUT the stale rv
        assert "resourceVersion" not in url
        return FakeResp([b'{"type":"ADDED","object":{"kind":"LLM","metadata":{"name":"w","resourceVersion":"5"}}}\n'])

    import threading

    stop = threading.Event()
    client = KubeClient(base_url="http://127.0.0.1:1")
    seen = []

    def on_event(t, o):
        seen.append(t)
        stop.set()  # end the watch loop once recovery delivered an event

    urllib.request.urlopen = fake_urlopen
    try:
        client.watch("core.datatunerx.io", "v1beta1", "llms", None,
                     on_event, stop,
                     resource_version="999", reconnect_delay=0.01)
    finally:
        urllib.request.urlopen = real_urlopen
    assert seen == ["ADDED"]
