"""DPO stage (reference reserves --stage dpo with no runtime,
cmd/tuning/parser.py:117-120): preference encoding, pair batching, loss
properties (log(2) at init, margin monotonicity), and an e2e CLI run that
drives preference gap apart."""

import json
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from datatunerx_tpu.data.loader import PreferenceBatchIterator
from datatunerx_tpu.data.preprocess import preprocess_preference_records
from datatunerx_tpu.data.templates import get_template
from datatunerx_tpu.models import get_config, init_params
from datatunerx_tpu.training import TrainConfig, Trainer
from tests.fake_tokenizer import FakeTokenizer


@pytest.fixture(scope="module")
def tok():
    return FakeTokenizer()


def _pairs(tok, n=8):
    tpl = get_template("vanilla", tok)
    records = [
        {"instruction": f"question {i}",
         "chosen": f"good answer number {i}",
         "rejected": f"bad {i}"}
        for i in range(n)
    ]
    return preprocess_preference_records(records, tpl, tok, cutoff_len=64)


def test_preference_encoding(tok):
    pairs = _pairs(tok, 3)
    assert len(pairs) == 3
    for p in pairs:
        assert set(p) == {"chosen_ids", "chosen_labels",
                          "rejected_ids", "rejected_labels"}
        # prompt positions masked on both sides; response tokens labeled
        from datatunerx_tpu.training.loss import IGNORE_INDEX

        assert p["chosen_labels"][0] == IGNORE_INDEX
        assert any(l != IGNORE_INDEX for l in p["chosen_labels"])
    # malformed records skipped
    tpl = get_template("vanilla", tok)
    assert preprocess_preference_records(
        [{"instruction": "x", "chosen": "", "rejected": "y"}], tpl, tok) == []


def test_preference_batches_stay_aligned(tok):
    pairs = _pairs(tok, 8)
    it = PreferenceBatchIterator(pairs, global_batch=4, block_size=64,
                                 pad_id=tok.pad_token_id or 0, seed=3)
    batches = list(it.epoch(0))
    assert len(batches) == 2
    b = batches[0]
    assert b["chosen_ids"].shape == (4, 64)
    assert b["rejected_ids"].shape == (4, 64)
    # alignment: each chosen row's prompt equals its rejected row's prompt
    # (the prompt is the IGNORE-masked prefix)
    from datatunerx_tpu.training.loss import IGNORE_INDEX

    for r in range(4):
        c_prompt_len = int(np.argmax(b["chosen_labels"][r] != IGNORE_INDEX))
        r_prompt_len = int(np.argmax(b["rejected_labels"][r] != IGNORE_INDEX))
        np.testing.assert_array_equal(
            b["chosen_ids"][r][: min(c_prompt_len, r_prompt_len)],
            b["rejected_ids"][r][: min(c_prompt_len, r_prompt_len)],
        )


def test_dpo_requires_lora():
    with pytest.raises(ValueError, match="lora"):
        TrainConfig(stage="dpo", finetuning_type="full")


def test_dpo_loss_is_log2_at_init(tok):
    """LoRA B=0 at init ⇒ policy ≡ reference ⇒ margin 0 ⇒ loss = ln 2."""
    cfg = get_config("debug")
    tr = Trainer(cfg, TrainConfig(
        stage="dpo", finetuning_type="lora", lora_rank=4, lora_dropout=0.0,
        total_steps=10, compute_dtype=None, dpo_beta=0.1,
    ))
    state = tr.init_state(init_params(cfg, jax.random.PRNGKey(0)),
                          jax.random.PRNGKey(1))
    pairs = _pairs(tok, 4)
    batch = next(iter(PreferenceBatchIterator(
        pairs, global_batch=4, block_size=64, pad_id=tok.pad_token_id or 0)))
    batch = {k: jnp.asarray(v) for k, v in batch.items()}
    m = tr.eval_step(state, batch)
    loss = float(m["sum_nll"]) / float(m["tokens"])
    assert abs(loss - np.log(2.0)) < 1e-4, loss


def test_dpo_training_improves_preference_margin(tok):
    """A few steps of DPO must push chosen log-probs above rejected ones."""
    cfg = get_config("debug")
    tr = Trainer(cfg, TrainConfig(
        stage="dpo", finetuning_type="lora", lora_rank=8, lora_dropout=0.0,
        learning_rate=5e-3, total_steps=30, compute_dtype=None, dpo_beta=0.5,
    ))
    state = tr.init_state(init_params(cfg, jax.random.PRNGKey(0)),
                          jax.random.PRNGKey(1))
    pairs = _pairs(tok, 4)
    batch = next(iter(PreferenceBatchIterator(
        pairs, global_batch=4, block_size=64, pad_id=tok.pad_token_id or 0)))
    batch = {k: jnp.asarray(v) for k, v in batch.items()}
    first = None
    for _ in range(30):
        state, m = tr.train_step(state, batch)
        first = float(m["loss"]) if first is None else first
    final = float(m["loss"])
    assert final < first < np.log(2.0) + 1e-3, (first, final)
    assert final < 0.5  # well below the indifference point


def test_dpo_cli_e2e(tmp_path):
    """Full driver path: --stage dpo over a jsonl preference dataset."""
    from datatunerx_tpu.tuning.parser import parse_train_args
    from datatunerx_tpu.tuning.train import run

    data = tmp_path / "prefs.jsonl"
    with open(data, "w") as f:
        for i in range(40):  # ≥ one global batch on the 8-device CPU mesh
            f.write(json.dumps({
                "instruction": f"q {i}", "chosen": f"great answer {i}",
                "rejected": f"terrible {i}",
            }) + "\n")
    out = str(tmp_path / "out")
    storage = str(tmp_path / "storage")
    args = parse_train_args([
        "--model_name_or_path", "preset:debug", "--stage", "dpo",
        "--train_path", str(data), "--output_dir", out,
        "--storage_path", storage, "--uid", "dpo-run",
        "--template", "vanilla", "--max_steps", "3", "--bf16", "false",
        "--remat", "none", "--per_device_train_batch_size", "4",
        "--block_size", "64", "--logging_steps", "1", "--dpo_beta", "0.2",
    ])
    r = run(args)
    assert r["steps"] == 3
    log = [json.loads(l) for l in
           open(os.path.join(out, "watch", "trainer_log.jsonl"))]
    assert len(log) == 3 and all(np.isfinite(e["loss"]) for e in log)
    assert log[0]["loss"] <= np.log(2.0) + 1e-3  # starts at indifference
    mf = json.load(open(os.path.join(storage, "dpo-run", "manifest.json")))
    assert mf["finetuning_type"] == "lora"


def test_dpo_eval_with_held_out_pairs(tmp_path, tok):
    """--evaluation_path in dpo stage produces eval_loss (mean pair loss,
    no bogus perplexity), with tail padding excluded from the mean."""
    from datatunerx_tpu.tuning.parser import parse_train_args
    from datatunerx_tpu.tuning.train import run

    def write(path, n):
        with open(path, "w") as f:
            for i in range(n):
                f.write(json.dumps({
                    "instruction": f"q {i}", "chosen": f"nice {i}",
                    "rejected": f"nope {i}"}) + "\n")
    train, ev = tmp_path / "t.jsonl", tmp_path / "e.jsonl"
    write(train, 40)
    write(ev, 5)  # NOT a multiple of the eval batch → exercises tail padding
    out, storage = str(tmp_path / "out"), str(tmp_path / "s")
    args = parse_train_args([
        "--model_name_or_path", "preset:debug", "--stage", "dpo",
        "--train_path", str(train), "--evaluation_path", str(ev),
        "--output_dir", out, "--storage_path", storage, "--uid", "dpo-ev",
        "--template", "vanilla", "--max_steps", "2", "--bf16", "false",
        "--remat", "none", "--per_device_train_batch_size", "4",
        "--per_device_eval_batch_size", "2", "--block_size", "64",
        "--logging_steps", "1",
    ])
    r = run(args)
    assert "eval_loss" in r["metrics"]
    assert "perplexity" not in r["metrics"]
    # barely-trained model ≈ indifference: mean pair loss near ln2, which
    # tail-padding pollution (3 fake pairs of 8) would visibly distort
    assert abs(r["metrics"]["eval_loss"] - np.log(2.0)) < 0.2


def test_hyperparameter_admission_rejects_dpo_without_peft():
    from datatunerx_tpu.operator.api import Hyperparameter, ObjectMeta
    from datatunerx_tpu.operator.webhooks import AdmissionError, admit

    bad = Hyperparameter(metadata=ObjectMeta(name="h"), spec={
        "parameters": {"trainerType": "dpo", "PEFT": "false"}})
    with pytest.raises(AdmissionError, match="dpo requires PEFT"):
        admit(bad)
    ok = Hyperparameter(metadata=ObjectMeta(name="h2"), spec={
        "parameters": {"trainerType": "dpo"}})
    admit(ok)
