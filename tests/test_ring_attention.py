"""Ring attention vs full-sequence XLA attention on the 8-device CPU mesh."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from datatunerx_tpu.ops.attention import make_causal_bias, xla_attention
from datatunerx_tpu.ops.ring_attention import ring_attention_sharded
from datatunerx_tpu.parallel.mesh import make_mesh
from datatunerx_tpu.parallel.sharding import compat_shard_map


@pytest.mark.parametrize("shape", [(1, 1, 1, 8), (2, 1, 1, 4)])
def test_ring_matches_full_attention(shape, devices8):
    mesh = make_mesh(shape)
    sp = shape[3]
    B, T, H, KV, d = 2, 64 * sp, 4, 2, 16
    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.normal(size=(B, T, H, d)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(B, T, KV, d)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(B, T, KV, d)), jnp.float32)

    pos = jnp.broadcast_to(jnp.arange(T)[None], (B, T))
    ref = xla_attention(q, k, v, make_causal_bias(pos, pos))

    out = ring_attention_sharded(q, k, v, mesh)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_ring_gradients_flow(devices8):
    mesh = make_mesh((1, 1, 1, 4))
    B, T, H, d = 1, 128, 2, 16
    rng = np.random.default_rng(1)
    q = jnp.asarray(rng.normal(size=(B, T, H, d)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(B, T, H, d)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(B, T, H, d)), jnp.float32)

    def loss_ring(q, k, v):
        return jnp.sum(ring_attention_sharded(q, k, v, mesh) ** 2)

    def loss_ref(q, k, v):
        pos = jnp.arange(T)[None]
        return jnp.sum(xla_attention(q, k, v, make_causal_bias(pos, pos)) ** 2)

    g_ring = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_ring, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=5e-4, rtol=5e-4)


def test_ring_training_through_trainer(devices8):
    """--attention ring end-to-end: the model dispatches to ring attention
    under an sp>1 mesh and the train step runs + decreases loss."""
    import jax.numpy as jnp

    from datatunerx_tpu.models.config import ModelConfig
    from datatunerx_tpu.models.llama import init_params
    from datatunerx_tpu.training import TrainConfig, Trainer
    from datatunerx_tpu.training.loss import IGNORE_INDEX

    cfg = ModelConfig(
        vocab_size=128, hidden_size=32, intermediate_size=64, num_layers=2,
        num_heads=4, num_kv_heads=2, max_seq_len=256, remat="none",
        attention_impl="ring",
    )
    mesh = make_mesh((2, 1, 1, 4))
    tr = Trainer(cfg, TrainConfig(finetuning_type="lora", lora_rank=4,
                                  lora_dropout=0.0, learning_rate=2e-2,
                                  scheduler="constant", total_steps=10,
                                  compute_dtype=None), mesh=mesh)
    state = tr.init_state(init_params(cfg, jax.random.PRNGKey(0)),
                          jax.random.PRNGKey(1))
    rng = np.random.default_rng(0)
    toks = rng.integers(4, 128, (4, 64)).astype(np.int32)
    labels = toks.copy()
    labels[:, :8] = IGNORE_INDEX
    batch = {"input_ids": jnp.asarray(toks), "labels": jnp.asarray(labels)}
    losses = []
    for _ in range(6):
        state, m = tr.train_step(state, batch)
        losses.append(float(m["loss"]))
    assert losses[-1] < losses[0], losses

    # parity: same model with plain xla attention on a single device
    import dataclasses

    from datatunerx_tpu.ops.ring_attention import set_ring_context

    set_ring_context(None)
    xcfg = dataclasses.replace(cfg, attention_impl="xla")
    tr2 = Trainer(xcfg, TrainConfig(finetuning_type="lora", lora_rank=4,
                                    lora_dropout=0.0, learning_rate=2e-2,
                                    scheduler="constant", total_steps=10,
                                    compute_dtype=None))
    s2 = tr2.init_state(init_params(cfg, jax.random.PRNGKey(0)),
                        jax.random.PRNGKey(1))
    s2, m2 = tr2.train_step(s2, batch)
    np.testing.assert_allclose(losses[0], float(m2["loss"]), rtol=1e-5)


def test_ring_flash_matches_xla_ring_fwd_and_grads():
    """The ring-of-flash path (DTX_RING_IMPL=flash default) must match the
    chunked-einsum XLA ring — fwd and all three gradients — on the virtual
    sp mesh. The xla ring materializes O(T_local^2) scores (34 GB at T=32k,
    caught by AOT certification r5); flash-per-chunk is the long-context
    fix and this is its numerics anchor."""
    import numpy as np

    from datatunerx_tpu.ops.ring_attention import (
        ring_attention,
        ring_flash_attention,
    )
    from datatunerx_tpu.parallel.mesh import make_mesh

    mesh = make_mesh(devices=jax.devices()[:4], sp=4, dp=1)
    B, T, H, KV, d = 2, 512, 4, 2, 64  # GQA 2:1, T_local = 128
    ks = jax.random.split(jax.random.PRNGKey(11), 3)
    q = jax.random.normal(ks[0], (B, T, H, d), jnp.float32)
    k = jax.random.normal(ks[1], (B, T, KV, d), jnp.float32)
    v = jax.random.normal(ks[2], (B, T, KV, d), jnp.float32)

    from jax.sharding import PartitionSpec as P

    spec = P(None, "sp", None, None)

    def run(base):
        import functools

        fn = functools.partial(base, axis_name="sp")

        def loss(q, k, v):
            return (compat_shard_map(fn, mesh=mesh,
                                     in_specs=(spec, spec, spec),
                                     out_specs=spec, check=False)
                    (q, k, v).astype(jnp.float32) ** 2).sum()

        out = compat_shard_map(fn, mesh=mesh, in_specs=(spec, spec, spec),
                               out_specs=spec, check=False)(q, k, v)
        grads = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
        return out, grads

    out_x, g_x = run(ring_attention)
    out_f, g_f = run(ring_flash_attention)
    np.testing.assert_allclose(np.asarray(out_f), np.asarray(out_x),
                               rtol=2e-3, atol=2e-3)
    for a, b, name in zip(g_f, g_x, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-3, atol=2e-3,
                                   err_msg=f"d{name} mismatch")
