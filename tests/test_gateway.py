"""Inference gateway (gateway/): routing, admission control, circuit
breakers, mid-stream failover, autoscale hints, and operator wiring.

CPU-only and model-free: replicas wrap duck-typed fake engines (the
InProcessReplica contract), so every scenario — including killing a replica
mid-stream — runs in milliseconds. The HTTP surface is exercised through a
real ThreadingHTTPServer on a loopback port.
"""

import json
import subprocess
import threading
import time
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from datatunerx_tpu.gateway.admission import (
    AdmissionController,
    Overloaded,
    estimate_prompt_tokens,
)
from datatunerx_tpu.gateway.autoscale import autoscale_hint, parse_hint
from datatunerx_tpu.gateway.replica_pool import (
    CircuitBreaker,
    HTTPReplica,
    InProcessReplica,
    NoReplicaAvailable,
    ReplicaError,
    ReplicaPool,
)
from datatunerx_tpu.gateway.router import session_key
from datatunerx_tpu.gateway.server import Gateway, ManagedReplicaSet, serve


class FakeEngine:
    """Duck-typed engine: chat/chat_stream/slots/_slot_req/adapter_ids."""

    def __init__(self, name, reply="hello world", slots=4, adapters=(),
                 delay=0.0, die_after_deltas=None):
        self.name = name
        self.reply = reply
        self.slots = slots
        self._slot_req = [None] * slots
        self.adapter_ids = {"": 0, **{a: i + 1 for i, a in enumerate(adapters)}}
        self.delay = delay
        self.die_after_deltas = die_after_deltas
        self.dead = False
        self.calls = 0

    def chat(self, messages, **kw):
        self.calls += 1
        if self.dead:
            raise RuntimeError(f"{self.name} is dead")
        if self.delay:
            time.sleep(self.delay)
        return self.reply

    def chat_stream(self, messages, **kw):
        self.calls += 1
        # two-char deltas, dying after die_after_deltas when configured
        for i in range(0, len(self.reply), 2):
            if self.dead:
                raise RuntimeError(f"{self.name} died mid-stream")
            if (self.die_after_deltas is not None
                    and i // 2 >= self.die_after_deltas):
                self.dead = True
                raise RuntimeError(f"{self.name} died mid-stream")
            if self.delay:
                time.sleep(self.delay)
            yield self.reply[i:i + 2]


def make_gateway(engines, policy="least_busy", admission=None, **gw_kw):
    pool = ReplicaPool([InProcessReplica(e.name, e) for e in engines])
    return Gateway(pool, policy=policy, admission=admission, **gw_kw)


MSGS = [{"role": "user", "content": "hi there"}]


# ---------------------------------------------------------------- breakers
def test_circuit_breaker_lifecycle():
    b = CircuitBreaker(failure_threshold=2, cooldown_s=0.05)
    assert b.state == "closed" and b.allow()
    b.record_failure()
    assert b.state == "closed"
    b.record_failure()
    assert b.state == "open" and not b.allow()
    time.sleep(0.06)
    assert b.state == "half_open" and b.allow()  # one probe allowed
    b.record_failure()  # probe failed → re-open
    assert b.state == "open"
    time.sleep(0.06)
    b.record_success()
    assert b.state == "closed"


# ----------------------------------------------------------------- routing
def test_least_busy_routing_prefers_idle_replica():
    busy, idle = FakeEngine("r0"), FakeEngine("r1")
    busy._slot_req[0] = busy._slot_req[1] = object()  # 2/4 slots busy
    gw = make_gateway([busy, idle])
    # distinct conversations so session affinity doesn't pin
    for i in range(4):
        gw.chat({"messages": [{"role": "user", "content": f"q{i}"}]})
    assert idle.calls == 4 and busy.calls == 0


def test_round_robin_rotates_over_replicas():
    engines = [FakeEngine(f"r{i}") for i in range(3)]
    gw = make_gateway(engines, policy="round_robin")
    for i in range(6):
        gw.chat({"messages": [{"role": "user", "content": f"q{i}"}]})
    assert [e.calls for e in engines] == [2, 2, 2]


def test_session_affinity_pins_conversation():
    engines = [FakeEngine("r0"), FakeEngine("r1")]
    gw = make_gateway(engines, policy="round_robin")
    convo = [{"role": "system", "content": "you are helpful"},
             {"role": "user", "content": "turn 1"}]
    gw.chat({"messages": convo})
    first = [e.calls for e in engines].index(1)
    # later turns share messages[0] → same replica despite round-robin
    for turn in range(2, 6):
        gw.chat({"messages": convo + [
            {"role": "user", "content": f"turn {turn}"}]})
    assert engines[first].calls == 5
    assert engines[1 - first].calls == 0
    assert session_key(convo) == session_key(
        convo + [{"role": "user", "content": "later"}])


def test_adapter_awareness_routes_to_loaded_replica():
    plain = FakeEngine("r0")
    tuned = FakeEngine("r1", adapters=("billing-bot",))
    gw = make_gateway([plain, tuned])
    for i in range(3):
        gw.chat({"messages": [{"role": "user", "content": f"q{i}"}],
                 "model": "billing-bot"})
    assert tuned.calls == 3 and plain.calls == 0


def test_draining_replica_gets_no_new_requests():
    engines = [FakeEngine("r0"), FakeEngine("r1")]
    gw = make_gateway(engines, policy="round_robin")
    assert gw.pool.drain("r0")
    for i in range(4):
        gw.chat({"messages": [{"role": "user", "content": f"q{i}"}]})
    assert engines[0].calls == 0 and engines[1].calls == 4


# ---------------------------------------------------------------- failover
def test_nonstream_failover_on_dead_replica():
    dead, alive = FakeEngine("r0"), FakeEngine("r1", reply="from r1")
    dead.dead = True
    dead._slot_req = [None] * 4  # looks idle → least-busy picks it first
    alive._slot_req[0] = object()
    gw = make_gateway([dead, alive])
    assert gw.chat({"messages": MSGS}) == "from r1"
    assert gw.pool.get("r0").breaker._failures >= 1


def test_midstream_failover_resumes_without_duplicating_prefix():
    dying = FakeEngine("r0", reply="hello world", die_after_deltas=2)
    backup = FakeEngine("r1", reply="hello world")
    backup._slot_req[0] = object()  # bias first pick to r0
    gw = make_gateway([dying, backup])
    deltas = list(gw.chat_stream({"messages": MSGS}))
    # r0 emitted "he","ll" then died; r1 re-served and the gateway skipped
    # the 4 already-emitted chars — the client sees the text exactly once
    assert "".join(deltas) == "hello world"
    assert dying.calls == 1 and backup.calls == 1
    assert gw.registry.counter("dtx_gateway_failovers_total").get() == 1


def test_all_replicas_dead_raises():
    e0, e1 = FakeEngine("r0"), FakeEngine("r1")
    e0.dead = e1.dead = True
    gw = make_gateway([e0, e1])
    with pytest.raises(NoReplicaAvailable):
        gw.chat({"messages": MSGS})


def test_breaker_opens_after_repeated_failures_and_recovers():
    flaky, steady = FakeEngine("r0"), FakeEngine("r1")
    flaky.dead = True
    gw = make_gateway([flaky, steady])
    gw.pool.get("r0").breaker.cooldown_s = 60  # no half-open during test
    for i in range(5):
        gw.chat({"messages": [{"role": "user", "content": f"q{i}"}]})
    assert gw.pool.get("r0").breaker.state == "open"
    # circuit open → r0 is no longer even attempted
    flaky.calls = 0
    gw.chat({"messages": [{"role": "user", "content": "after open"}]})
    assert flaky.calls == 0


# --------------------------------------------------------------- admission
def test_admission_sheds_past_token_budget():
    adm = AdmissionController(max_queue=100, token_budget=40)
    msgs = [{"role": "user", "content": "x" * 60}]  # ~19 tokens
    t1 = adm.try_admit(msgs)
    t2 = adm.try_admit(msgs)
    with pytest.raises(Overloaded) as ei:
        adm.try_admit(msgs)
    assert ei.value.retry_after_s >= 1
    assert adm.shed_count == 1
    t1.release()
    t2.release()
    adm.try_admit(msgs).release()  # budget freed → admits again


def test_admission_bounds_queue_depth():
    adm = AdmissionController(max_queue=2, token_budget=10_000)
    tickets = [adm.try_admit(MSGS) for _ in range(2)]
    with pytest.raises(Overloaded):
        adm.try_admit(MSGS)
    for t in tickets:
        t.release()


def test_estimate_tokens_scales_with_content():
    small = estimate_prompt_tokens([{"role": "user", "content": "hi"}])
    big = estimate_prompt_tokens([{"role": "user", "content": "x" * 4000}])
    assert big > small * 10


def test_estimate_tokens_chars_per_token_configurable():
    msgs = [{"role": "user", "content": "x" * 400}]
    default = estimate_prompt_tokens(msgs)  # 400/4 + 4
    dense = estimate_prompt_tokens(msgs, chars_per_token=2.0)  # 400/2 + 4
    assert default == 104
    assert dense == 204


def test_estimate_tokens_prefers_real_tokenizer():
    msgs = [{"role": "user", "content": "hello world"}]
    exact = estimate_prompt_tokens(msgs, count_tokens=lambda t: 7)
    assert exact == 7 + 4
    # a tokenizer that blows up must not shed the request: heuristic fallback
    def broken(text):
        raise RuntimeError("tokenizer died")

    fallback = estimate_prompt_tokens(msgs, count_tokens=broken)
    assert fallback == estimate_prompt_tokens(msgs)


def test_admission_controller_uses_configured_estimator():
    counted = []

    def count(text):
        counted.append(text)
        return 30

    adm = AdmissionController(max_queue=10, token_budget=40,
                              count_tokens=count)
    t1 = adm.try_admit([{"role": "user", "content": "abc"}])
    assert t1.tokens == 34  # 30 counted + template overhead
    with pytest.raises(Overloaded):  # 34 + 34 > 40
        adm.try_admit([{"role": "user", "content": "def"}])
    assert counted == ["abc", "def"]
    t1.release()


# --------------------------------------------------------------- autoscale
def test_autoscale_hint_scales_up_on_backlog_and_down_when_idle():
    up = autoscale_hint(replicas=2, available_replicas=2, queue_depth=20,
                        queued_tokens=5000, shed_count=0, p95_latency_s=1.0)
    assert up["desiredReplicas"] == 3 and "queue depth" in up["reason"]
    shed = autoscale_hint(replicas=1, available_replicas=1, queue_depth=3,
                          queued_tokens=900, shed_count=7, p95_latency_s=0.5)
    assert shed["desiredReplicas"] == 2
    down = autoscale_hint(replicas=3, available_replicas=3, queue_depth=0,
                          queued_tokens=0, shed_count=0, p95_latency_s=0.1)
    assert down["desiredReplicas"] == 2 and down["reason"] == "idle"
    assert parse_hint(down) == down | {"reason": "idle"}
    assert parse_hint({"replicas": "x"}) is None
    # a long-past overload blip (cumulative sheds, none recent) must NOT
    # ratchet the fleet up forever
    stale = autoscale_hint(replicas=2, available_replicas=2, queue_depth=2,
                           queued_tokens=100, shed_count=50, shed_recent=0,
                           p95_latency_s=0.5)
    assert stale["desiredReplicas"] == 2


def test_gateway_autoscale_uses_shed_delta_not_lifetime_total():
    slow = FakeEngine("r0", delay=0.2)
    gw = make_gateway(
        [slow], admission=AdmissionController(max_queue=1, token_budget=10**6))
    t = threading.Thread(
        target=lambda: gw.chat({"messages": MSGS}))
    t.start()
    while gw.admission.depth == 0:
        time.sleep(0.005)
    with pytest.raises(Overloaded):
        gw.admission.try_admit(MSGS)
    hint1 = gw.autoscale()  # shed happened since last poll → scale up
    assert hint1["shedCount"] == 1 and hint1["desiredReplicas"] == 2
    t.join()
    # no new sheds since hint1: the lifetime total alone must not demand more
    t2 = threading.Thread(target=lambda: gw.chat({"messages": MSGS}))
    t2.start()
    while gw.admission.depth == 0:
        time.sleep(0.005)
    hint2 = gw.autoscale()
    t2.join()
    assert hint2["shedCount"] == 1  # cumulative still reported
    assert "shedding" not in hint2["reason"]


def test_capacity_clamps_hint_to_bounds_and_free_slices():
    from datatunerx_tpu.operator.capacity import serving_replicas_for

    hint = {"replicas": 2, "desiredReplicas": 3}
    assert serving_replicas_for(hint, max_replicas=8) == 3
    assert serving_replicas_for(hint, max_replicas=2) == 2
    assert serving_replicas_for(hint, max_replicas=8, free_slices=0) == 2
    assert serving_replicas_for({"replicas": 4, "desiredReplicas": 3},
                                min_replicas=4) == 4


# ---------------------------------------------------------- operator wiring
def test_serving_spec_carries_gateway_fields():
    from datatunerx_tpu.operator.api import FinetuneJob, ObjectMeta
    from datatunerx_tpu.operator.generate import generate_serving_spec
    from datatunerx_tpu.operator.webhooks import admit

    job = FinetuneJob(
        metadata=ObjectMeta(name="j1", namespace="default"),
        spec={"finetune": {"finetuneSpec": {
            "llm": "m", "dataset": "d",
            "hyperparameter": {"hyperparameterRef": "h"}}},
            "serveConfig": {"replicas": 3}},
    )
    admit(job)  # defaulting: replicas>1 implies gateway + policy + bounds
    cfg = job.spec["serveConfig"]
    assert cfg["gateway"] is True and cfg["maxReplicas"] == 3
    spec = generate_serving_spec(job, {})
    assert spec["replicas"] == 3 and spec["gateway"] is True
    assert spec["policy"] == "least_busy" and spec["max_replicas"] == 3


def test_webhook_rejects_bad_serve_config():
    from datatunerx_tpu.operator.api import FinetuneJob, ObjectMeta
    from datatunerx_tpu.operator.webhooks import AdmissionError, admit

    def job(serve):
        return FinetuneJob(
            metadata=ObjectMeta(name="j", namespace="default"),
            spec={"finetune": {"finetuneSpec": {
                "llm": "m", "dataset": "d",
                "hyperparameter": {"hyperparameterRef": "h"}}},
                "serveConfig": serve},
        )

    with pytest.raises(AdmissionError):
        admit(job({"replicas": 0}))
    with pytest.raises(AdmissionError):
        admit(job({"minReplicas": 3, "maxReplicas": 1}))
    with pytest.raises(AdmissionError):
        admit(job({"policy": "fastest"}))


def test_crd_schema_includes_gateway_fields():
    from datatunerx_tpu.operator.api import FinetuneJob
    from datatunerx_tpu.operator.crdgen import crd_for

    crd = crd_for(FinetuneJob)
    serve = (crd["spec"]["versions"][0]["schema"]["openAPIV3Schema"]
             ["properties"]["spec"]["properties"]["serveConfig"]["properties"])
    for field in ("replicas", "gateway", "policy", "minReplicas",
                  "maxReplicas"):
        assert field in serve, field


def test_controller_applies_clamped_scale():
    from datatunerx_tpu.operator.api import FinetuneJob, ObjectMeta
    from datatunerx_tpu.operator.finetunejob_controller import (
        FinetuneJobController,
    )

    class FakeBackend:
        def __init__(self):
            self.scaled = []
            self.hint = autoscale_hint(
                replicas=2, available_replicas=2, queue_depth=30,
                queued_tokens=9000, shed_count=4, p95_latency_s=2.0)

        def scale_hint(self, name):
            return self.hint

        def scale(self, name, n):
            self.scaled.append((name, n))

    backend = FakeBackend()
    ctrl = FinetuneJobController(backend)
    job = FinetuneJob(
        metadata=ObjectMeta(name="j1", namespace="default"),
        spec={"serveConfig": {"replicas": 2, "gateway": True,
                              "minReplicas": 1, "maxReplicas": 5}},
    )
    changed = ctrl._reconcile_autoscale(job)
    assert changed
    assert backend.scaled == [("j1", 3)]
    assert job.status["result"]["serving"]["desiredReplicas"] == 3

    # maxReplicas caps the hint → no scale call when already at the cap
    backend.scaled.clear()
    job.spec["serveConfig"]["maxReplicas"] = 2
    ctrl._reconcile_autoscale(job)
    assert backend.scaled == []


# ------------------------------------------------------------ http surface
@pytest.fixture()
def http_gateway():
    made = []

    def start(engines, **kw):
        gw = make_gateway(engines, **kw)
        srv = serve(gw, port=0, host="127.0.0.1")
        threading.Thread(target=srv.serve_forever, daemon=True).start()
        made.append((gw, srv))
        return gw, f"http://127.0.0.1:{srv.server_port}"

    yield start
    for gw, srv in made:
        srv.shutdown()
        gw.close()


def _post(url, path, payload, headers=None):
    req = urllib.request.Request(
        url + path, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json", **(headers or {})},
        method="POST")
    return urllib.request.urlopen(req, timeout=30)


def test_http_chat_round_trip_with_trace_id(http_gateway):
    gw, url = http_gateway([FakeEngine("r0", reply="pong")])
    with _post(url, "/v1/chat/completions",
               {"messages": MSGS},
               {"X-DTX-Trace-Id": "trace-abc123"}) as r:
        body = json.load(r)
        assert r.headers["X-DTX-Trace-Id"] == "trace-abc123"
    assert body["choices"][0]["message"]["content"] == "pong"
    # absent header → gateway generates one
    with _post(url, "/chat/completions", {"messages": MSGS}) as r:
        assert r.headers["X-DTX-Trace-Id"].startswith("dtx-")


def test_http_midstream_failover_completes_stream(http_gateway):
    dying = FakeEngine("r0", reply="hello world", die_after_deltas=2)
    backup = FakeEngine("r1", reply="hello world")
    backup._slot_req[0] = object()
    gw, url = http_gateway([dying, backup])
    with _post(url, "/chat/completions",
               {"messages": MSGS, "stream": True}) as r:
        events = [line.decode().strip()[len("data: "):]
                  for line in r if line.strip().startswith(b"data: ")]
    assert events[-1] == "[DONE]"
    text = "".join(
        json.loads(e)["choices"][0]["delta"].get("content", "")
        for e in events[:-1] if not e.startswith("[")
    )
    assert text == "hello world"
    assert dying.dead and backup.calls == 1


def test_http_overload_returns_429_while_inflight_completes(http_gateway):
    slow = FakeEngine("r0", reply="slow answer", delay=0.5)
    gw, url = http_gateway(
        [slow], admission=AdmissionController(max_queue=1, token_budget=10**6))

    results = {}

    def inflight():
        with _post(url, "/chat/completions", {"messages": MSGS}) as r:
            results["inflight"] = json.load(r)

    t = threading.Thread(target=inflight)
    t.start()
    # wait until the in-flight request holds the queue slot before poking
    deadline = time.monotonic() + 5
    while gw.admission.depth == 0 and time.monotonic() < deadline:
        time.sleep(0.005)
    assert gw.admission.depth == 1
    shed_status = None
    while time.monotonic() < deadline:
        # sustained overload: keep poking until admission sheds
        try:
            with _post(url, "/chat/completions",
                       {"messages": [{"role": "user", "content": "x"}]}):
                pass
        except urllib.error.HTTPError as e:
            shed_status = (e.code, e.headers.get("Retry-After"))
            break
        time.sleep(0.01)
    t.join(timeout=10)
    assert shed_status is not None, "overload never shed"
    code, retry_after = shed_status
    assert code == 429
    assert retry_after is not None and int(retry_after) >= 1
    # the in-flight request completed despite the shed
    assert results["inflight"]["choices"][0]["message"]["content"] == \
        "slow answer"
    assert gw.admission.shed_count >= 1


def test_http_metrics_report_queue_shed_and_circuit(http_gateway):
    flaky = FakeEngine("r0")
    flaky.dead = True
    steady = FakeEngine("r1")
    gw, url = http_gateway([flaky, steady])
    gw.pool.get("r0").breaker.cooldown_s = 60
    for i in range(4):
        _post(url, "/chat/completions",
              {"messages": [{"role": "user", "content": f"q{i}"}]}).read()
    gw.admission._shed = 2  # exercise the shed counter surface
    with urllib.request.urlopen(url + "/metrics", timeout=10) as r:
        text = r.read().decode()
    assert "# TYPE dtx_gateway_queue_depth gauge" in text
    assert "dtx_gateway_queue_depth 0" in text
    assert "dtx_gateway_shed_total 2" in text
    assert ('dtx_gateway_replica_circuit_state{replica="r0",state="open"} 1'
            in text)
    assert ('dtx_gateway_replica_circuit_state{replica="r1",state="closed"} 1'
            in text)
    assert "dtx_gateway_request_latency_seconds_bucket" in text


def test_http_healthz_autoscale_drain_and_404(http_gateway):
    gw, url = http_gateway([FakeEngine("r0"), FakeEngine("r1")])
    with urllib.request.urlopen(url + "/healthz", timeout=10) as r:
        h = json.load(r)
    assert h["status"] == "HEALTHY" and h["available"] == 2
    with urllib.request.urlopen(url + "/autoscale", timeout=10) as r:
        hint = parse_hint(json.load(r))
    assert hint is not None and hint["replicas"] == 2
    with _post(url, "/admin/drain", {"replica": "r0"}) as r:
        assert json.load(r)["draining"] == "r0"
    with urllib.request.urlopen(url + "/autoscale", timeout=10) as r:
        assert json.load(r)["availableReplicas"] == 1
    with pytest.raises(urllib.error.HTTPError) as ei:
        _post(url, "/admin/drain", {"replica": "ghost"})
    assert ei.value.code == 404
    # scale without a managed replica set → 501
    with pytest.raises(urllib.error.HTTPError) as ei:
        _post(url, "/admin/scale", {"replicas": 3})
    assert ei.value.code == 501


def test_http_bad_requests(http_gateway):
    gw, url = http_gateway([FakeEngine("r0")])
    with pytest.raises(urllib.error.HTTPError) as ei:
        _post(url, "/chat/completions", {"messages": []})
    assert ei.value.code == 400
    with pytest.raises(urllib.error.HTTPError) as ei:
        _post(url, "/nope", {})
    assert ei.value.code == 404


def test_perplexity_client_error_does_not_trip_breaker():
    """A 400 from the replica is the CLIENT's fault: the gateway must map it
    to 400 (ValueError), not 502, and must not open the replica's circuit."""
    from http.server import BaseHTTPRequestHandler, HTTPServer

    from datatunerx_tpu.gateway.replica_pool import HTTPReplica

    class Handler(BaseHTTPRequestHandler):
        def do_POST(self):
            body = json.dumps({"error": "completion is required"}).encode()
            self.send_response(400)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *a):
            pass

    srv = HTTPServer(("127.0.0.1", 0), Handler)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    try:
        replica = HTTPReplica("r0", f"http://127.0.0.1:{srv.server_port}")
        gw = Gateway(ReplicaPool([replica]))
        for _ in range(5):
            with pytest.raises(ValueError, match="completion is required"):
                gw.perplexity({"prompt": "p"})
        assert replica.breaker.state == "closed"
    finally:
        srv.shutdown()


# ------------------------------------------------------- subprocess replicas
@pytest.mark.slow
def test_local_backend_deploys_gateway_with_real_replicas(tmp_path):
    """LocalServingBackend spec.replicas=2 → gateway process fronting two
    serving.server subprocesses with real debug models: HEALTHY gate, chat
    round trip, autoscale hint, and graceful downscale via /admin/scale."""
    from datatunerx_tpu.serving.local_backend import LocalServingBackend

    backend = LocalServingBackend(
        str(tmp_path / "jobs"),
        extra_env={"JAX_PLATFORMS": "cpu", "PALLAS_AXON_POOL_IPS": ""})
    backend.deploy("gwjob", {
        "model_path": "preset:debug", "template": "vanilla",
        "replicas": 2, "slots": 2,
    })
    try:
        deadline = time.monotonic() + 240
        while time.monotonic() < deadline:
            if backend.status("gwjob") == "HEALTHY":
                break
            time.sleep(1)
        assert backend.status("gwjob") == "HEALTHY"
        url = backend.endpoint("gwjob")
        with _post(url, "/chat/completions", {
                "messages": [{"role": "user", "content": "ping"},],
                "max_tokens": 4}) as r:
            body = json.load(r)
        assert body["choices"][0]["message"]["content"] is not None
        hint = backend.scale_hint("gwjob")
        assert hint is not None and hint["replicas"] == 2
        assert backend.scale("gwjob", 1)
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            hint = backend.scale_hint("gwjob")
            if hint and hint["replicas"] == 1:
                break
            time.sleep(0.5)
        assert hint and hint["replicas"] == 1
    finally:
        backend.delete("gwjob")


# --------------------------------------------------- drain reaping (PR 4)
class FakeProc:
    """subprocess.Popen stand-in: alive until terminate()/kill()."""

    def __init__(self):
        self.returncode = None
        self.terminated = False

    def poll(self):
        return self.returncode

    def terminate(self):
        self.terminated = True
        self.returncode = -15

    def kill(self):
        self.returncode = -9

    def wait(self, timeout=None):
        if self.returncode is None:
            raise subprocess.TimeoutExpired("fake", timeout or 0)
        return self.returncode


class WarmableFakeEngine(FakeEngine):
    """FakeEngine + the dynamic-adapter surface inheritance reads/writes:
    a resident warm set with checkpoints, and a load_adapter recorder."""

    def __init__(self, name, warm_set=None, **kw):
        super().__init__(name, adapters=tuple(warm_set or ()), **kw)
        self._warm = dict(warm_set or {})
        self.resident_adapters = dict.fromkeys(self._warm, 1)
        self.loaded: list = []

    def adapter_catalog(self):
        return dict(self._warm)

    def load_adapter(self, name, checkpoint, preload=True):
        self.loaded.append((name, checkpoint))
        self._warm[name] = checkpoint
        self.resident_adapters[name] = 1
        return {"name": name, "checkpoint": checkpoint}

    def healthy(self):
        return True


class FakeManagedReplicaSet(ManagedReplicaSet):
    """ManagedReplicaSet whose spawn() creates an in-process replica and a
    FakeProc instead of a real serving.server subprocess — the reap logic
    under test (drain → terminate → pool removal → replacement → weight +
    warm-set inheritance) is identical."""

    engine_factory = staticmethod(lambda name: FakeEngine(name))

    def spawn(self):
        with self._lock:
            idx = self._next_idx
            self._next_idx += 1
        name = f"replica-{idx}"
        with self._lock:
            self._procs[name] = FakeProc()
        replica = InProcessReplica(name, self.engine_factory(name))
        self._apply_inheritance(replica)
        self.pool.add(replica)
        return replica


def _wait_until(cond, timeout=5.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(0.01)
    return cond()


def test_admin_drain_reaps_process_and_restores_target(tmp_path):
    """ROADMAP bug: /admin/drain used to only set the draining flag — the
    subprocess and pool entry leaked, and reconcile grew the fleet past
    target by one zombie per drain. Now: drained replica's process is
    terminated, its pool entry removed, and the fleet returns to target."""
    pool = ReplicaPool()
    mrs = FakeManagedReplicaSet(pool, [], workdir=str(tmp_path / "w"),
                                drain_timeout_s=2.0, supervise_interval_s=0)
    gw = Gateway(pool)
    gw.replica_set = mrs
    try:
        mrs.scale(2)
        assert sorted(r.name for r in pool.replicas()) == [
            "replica-0", "replica-1"]
        proc0 = mrs._procs["replica-0"]

        assert gw.drain("replica-0")
        assert _wait_until(lambda: pool.get("replica-0") is None)
        assert proc0.terminated, "drained replica's subprocess must be reaped"
        assert "replica-0" not in mrs._procs, "no zombie pool entry"

        # replacement spawned: fleet back at target, not target+zombie
        mrs._reconcile()
        assert _wait_until(lambda: len(pool.replicas()) == 2)
        assert len(mrs._procs) == 2
        assert all(p.poll() is None for p in mrs._procs.values())

        # unknown replica still 404s through the gateway entry point
        assert not gw.drain("replica-404")
    finally:
        mrs.close()
        pool.close()


def test_drain_waits_for_inflight_before_reaping(tmp_path):
    pool = ReplicaPool()
    mrs = FakeManagedReplicaSet(pool, [], workdir=str(tmp_path / "w"),
                                drain_timeout_s=5.0, supervise_interval_s=0)
    try:
        mrs.scale(1)
        replica = pool.get("replica-0")
        replica.acquire()  # simulate an in-flight request
        assert mrs.drain("replica-0")
        time.sleep(0.3)
        assert pool.get("replica-0") is not None, \
            "reaper must wait for in-flight work"
        replica.release()
        assert _wait_until(lambda: pool.get("replica-0") is None)
        assert "replica-0" not in mrs._procs
    finally:
        mrs.close()
        pool.close()


def test_pool_level_drain_is_reaped_by_supervisor(tmp_path):
    """Safety net: a managed replica drained directly on the pool (old
    /admin/drain path) is picked up by the next reconcile tick."""
    pool = ReplicaPool()
    mrs = FakeManagedReplicaSet(pool, [], workdir=str(tmp_path / "w"),
                                drain_timeout_s=2.0, supervise_interval_s=0)
    try:
        mrs.scale(2)
        pool.drain("replica-1")  # bypasses ManagedReplicaSet.drain
        mrs._reconcile()  # what the supervisor thread runs periodically
        assert _wait_until(lambda: pool.get("replica-1") is None)
        assert "replica-1" not in mrs._procs
        assert _wait_until(lambda: len(pool.replicas()) == 2)
    finally:
        mrs.close()
        pool.close()


def test_drain_replacement_inherits_weight_and_warm_set(tmp_path):
    """Regression: the replacement spawned for a drained replica used to
    join at defaults (weight 1.0, cold adapter pool) — mid-promotion that
    skews the smooth-WRR shares, and every tenant pays load-on-miss again.
    Now it inherits the drained replica's traffic weight at spawn and
    rebuilds its resident warm set once healthy."""
    pool = ReplicaPool()
    mrs = FakeManagedReplicaSet(pool, [], workdir=str(tmp_path / "w"),
                                drain_timeout_s=2.0, supervise_interval_s=0)
    mrs.engine_factory = staticmethod(
        lambda name: WarmableFakeEngine(name))
    gw = Gateway(pool)
    gw.replica_set = mrs
    try:
        mrs.scale(2)
        drained = pool.get("replica-0")
        drained.weight = 0.25  # mid-promotion canary share
        drained.engine._warm = {"tenant-a": "/ckpts/a",
                                "tenant-b": "/ckpts/b"}
        drained.engine.resident_adapters = {"tenant-a": 1, "tenant-b": 1}

        assert gw.drain("replica-0")
        assert _wait_until(lambda: pool.get("replica-2") is not None)
        replacement = pool.get("replica-2")
        assert replacement.weight == 0.25, \
            "replacement must inherit the drained replica's traffic weight"
        assert _wait_until(
            lambda: sorted(replacement.engine.loaded) == [
                ("tenant-a", "/ckpts/a"), ("tenant-b", "/ckpts/b")]), \
            replacement.engine.loaded

        # a DOWNSCALED replica's state is NOT inherited: the next scale-up
        # spawn joins at defaults (no stale entry misapplied)
        pool.get("replica-1").weight = 0.5
        mrs.scale(1)
        assert _wait_until(lambda: len(pool.replicas()) == 1)
        mrs.scale(2)
        assert _wait_until(lambda: len(pool.replicas()) == 2)
        newest = max(pool.replicas(), key=lambda r: r.name)
        assert newest.weight == 1.0
    finally:
        mrs.close()
        pool.close()


# ------------------------------------- client errors vs replica faults (PR 4)
class ClientErrorEngine(FakeEngine):
    """Engine that rejects the REQUEST (unknown adapter / over-length
    prompt) — the engine contract raises ValueError/KeyError for these,
    never a replica-level fault."""

    def __init__(self, name, exc):
        super().__init__(name)
        self.exc = exc

    def chat(self, messages, **kw):
        self.calls += 1
        raise self.exc

    def chat_stream(self, messages, **kw):
        self.calls += 1
        raise self.exc
        yield  # pragma: no cover — make it a generator


@pytest.mark.parametrize("exc", [ValueError("prompt too long"),
                                 KeyError("unknown adapter 'x'")])
def test_inprocess_client_error_does_not_trip_breaker_or_fail_over(exc):
    bad = ClientErrorEngine("r0", exc)
    healthy = FakeEngine("r1", reply="ok")
    pool = ReplicaPool([InProcessReplica("r0", bad)])
    gw = Gateway(pool)
    with pytest.raises(ValueError):
        gw.chat({"messages": MSGS})
    assert pool.get("r0").breaker.state == "closed", \
        "a client error must not count against the replica"

    # with a healthy sibling available the request must STILL fail (the
    # request itself is bad) instead of failing over and masking the 400
    gw2 = make_gateway([ClientErrorEngine("r0", exc), healthy],
                       policy="round_robin")
    for _ in range(2):  # whichever replica round-robin picks first
        with pytest.raises(ValueError):
            gw2.chat({"messages": MSGS})
    assert healthy.calls <= 2  # served directly, never via failover retries

    with pytest.raises(ValueError):
        list(Gateway(ReplicaPool([InProcessReplica(
            "r2", ClientErrorEngine("r2", exc))])).chat_stream(
                {"messages": MSGS}))


def test_replica_types_agree_on_client_error_mapping():
    """InProcessReplica and HTTPReplica side by side: the same client
    mistake surfaces as ValueError (→ gateway 400) from both, not as
    ReplicaError (→ breaker trip + 503)."""

    class Reject400(BaseHTTPRequestHandler):
        def do_POST(self):
            body = json.dumps({"error": "unknown model/adapter 'x'"}).encode()
            self.send_response(400)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *a):
            pass

    srv = ThreadingHTTPServer(("127.0.0.1", 0), Reject400)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    try:
        replicas = [
            HTTPReplica("http0", f"http://127.0.0.1:{srv.server_port}"),
            InProcessReplica("proc0", ClientErrorEngine(
                "proc0", KeyError("unknown model/adapter 'x'"))),
        ]
        for replica in replicas:
            with pytest.raises(ValueError):
                replica.chat(MSGS, max_new_tokens=4)
            with pytest.raises(ValueError):
                list(replica.chat_stream(MSGS, max_new_tokens=4))

        # a genuine replica fault still raises ReplicaError from both
        dead_http = HTTPReplica("dead", "http://127.0.0.1:9")  # closed port
        with pytest.raises(ReplicaError):
            dead_http.chat(MSGS)
        dead_proc = InProcessReplica("deadp", FakeEngine("deadp"))
        dead_proc.engine.dead = True
        with pytest.raises(ReplicaError):
            dead_proc.chat(MSGS)
    finally:
        srv.shutdown()
