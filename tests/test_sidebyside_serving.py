"""BASELINE row 6: Scoring-driven side-by-side comparison of N tuned
checkpoints through ONE multi-adapter serving engine.

The reference serves each tuned checkpoint as its own Ray Serve deployment
and a sibling operator scores them over /chat/completions
(/root/reference/pkg/util/generate/generate.go:160-329). TPU-native shape:
one BatchedEngine stacks all adapters ([L, E, ...] leaves, per-slot adapter
indexing) so N checkpoints share one set of base weights in HBM, and one
Scoring CR per adapter — spec.model routes each CR's probes to its adapter
via the OpenAI "model" field — produces N comparable status.score values.
"""

import json
import threading
import time
import urllib.request
from http.server import ThreadingHTTPServer

import pytest

from datatunerx_tpu.operator.api import ObjectMeta, Scoring
from datatunerx_tpu.operator.reconciler import Manager
from datatunerx_tpu.operator.store import ObjectStore
from datatunerx_tpu.scoring.controller import ScoringController
from datatunerx_tpu.serving import server as serving_server
from datatunerx_tpu.serving.adapters import make_adapter_checkpoint
from datatunerx_tpu.serving.batched_engine import BatchedEngine


@pytest.fixture(scope="module")
def stack(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("sidebyside")
    paths = {f"a{i}": make_adapter_checkpoint(str(tmp / f"ckpt{i}"),
                                              "preset:debug", seed=i)
             for i in range(3)}
    eng = BatchedEngine("preset:debug", adapters=paths, template="vanilla",
                        max_seq_len=256, slots=4, decode_chunk=4)
    serving_server.STATE.engine = eng
    serving_server.STATE.model_path = "preset:debug"
    srv = ThreadingHTTPServer(("127.0.0.1", 0), serving_server.Handler)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    url = f"http://127.0.0.1:{srv.server_port}/chat/completions"
    yield eng, url
    srv.shutdown()
    eng.close()
    serving_server.STATE.engine = None


def test_adapters_stacked_in_one_engine(stack):
    eng, _ = stack
    assert set(eng.adapter_ids) == {"", "a0", "a1", "a2"}
    # one stacked tree, not three engines: adapter axis E = 1 base + 3 named
    tree, scales = eng.lora_stack
    leaf = tree["layers"]["q_proj"]["a"]
    assert leaf.shape[1] == 4


def test_model_field_routes_to_adapter_over_http(stack):
    _, url = stack
    answers = {}
    for name in ("a0", "a1", "a2"):
        req = urllib.request.Request(
            url,
            data=json.dumps({
                "messages": [{"role": "user", "content": "route check"}],
                "max_tokens": 8, "temperature": 0.0, "model": name,
            }).encode(),
            headers={"Content-Type": "application/json"}, method="POST")
        with urllib.request.urlopen(req, timeout=120) as r:
            answers[name] = json.load(r)["choices"][0]["message"]["content"]
    assert len(answers) == 3  # all three adapters answered through one engine


def test_scoring_crs_compare_three_adapters(stack):
    """Three Scoring CRs against ONE inferenceService, one per adapter:
    the operator drives all three to status.score — the side-by-side
    comparison BASELINE row 6 claims."""
    eng, url = stack
    store = ObjectStore()
    mgr = Manager(store)
    mgr.register(ScoringController(timeout=300.0))

    probes = [{"prompt": "compare adapters", "reference": "yes"}]
    for name in ("a0", "a1", "a2"):
        store.create(Scoring(
            metadata=ObjectMeta(name=f"cmp-{name}"),
            spec={"inferenceService": url, "model": name, "probes": probes}))

    deadline = time.monotonic() + 300
    while time.monotonic() < deadline:
        mgr.run_until_idle(max_wall_s=30.0)
        mgr.drain_scheduled()
        scores = {n: store.get(Scoring, f"cmp-{n}").status.get("score")
                  for n in ("a0", "a1", "a2")}
        if all(s is not None for s in scores.values()):
            break
        time.sleep(0.1)
    assert all(s is not None for s in scores.values()), scores
    for s in scores.values():
        assert 0.0 <= float(s) <= 100.0
    # the engine served every adapter's probes (full prefills, no cross-talk)
    assert eng.prefill_stats["full"] >= 3


def test_scoring_rejects_unknown_adapter(stack):
    _, url = stack
    store = ObjectStore()
    mgr = Manager(store)
    mgr.register(ScoringController(timeout=60.0))
    store.create(Scoring(
        metadata=ObjectMeta(name="cmp-bad"),
        spec={"inferenceService": url, "model": "nope",
              "probes": [{"prompt": "x", "reference": "y"}]}))
    mgr.run_until_idle(max_wall_s=20.0)
    sc = store.get(Scoring, "cmp-bad")
    # 400 from the server is transport-level: recorded, retried, never scored
    assert sc.status.get("score") is None
    assert "400" in (sc.status.get("lastError") or "")
