"""Closed-loop e2e: experiment → continuous scoring → canary promotion.

The acceptance scenario for the experiment plane, CPU-only and model-free:
3 fake jobs share a 2-slice fake pool, one job is preempted by a pool
shrink and resumed FROM A REAL ORBAX CHECKPOINT (the probe reads the step
through the trainer's CheckpointManager), the continuous-scoring watcher
keeps a live leaderboard and early-stops the clear loser, and the winner is
promoted through the in-process gateway: canary replica → weighted traffic
shift whose shares are observable at the fake engines → 100% rollout.
A companion case exercises auto-rollback when the canary regresses, and the
HTTP surface (POST/GET /admin/promote, GET /debug/trace/<id>) is driven
over a real loopback server.
"""

import importlib.util
import json
import os
import threading
import time
import urllib.request
import uuid

import pytest

from datatunerx_tpu.experiment.metrics import ExperimentMetrics
from datatunerx_tpu.experiment.pool import PoolSlice, SharedSlicePool
from datatunerx_tpu.experiment.runner import (
    PHASE_DONE,
    PHASE_PROMOTE,
    ExperimentRunner,
)
from datatunerx_tpu.experiment.scheduler import (
    PREEMPTED,
    RUNNING,
    STOPPED,
    SliceScheduler,
)
from datatunerx_tpu.experiment.watcher import (
    ContinuousScoringWatcher,
    Leaderboard,
)
from datatunerx_tpu.gateway.replica_pool import InProcessReplica, ReplicaPool
from datatunerx_tpu.gateway.server import Gateway, serve
from datatunerx_tpu.operator.backends import (
    FakeServingBackend,
    FakeTrainingBackend,
)

EIGHT = {"meshShape": "dp=8"}


class FakeEngine:
    def __init__(self, name, reply="hello world", dead=False):
        self.name = name
        self.reply = reply
        self.slots = 4
        self._slot_req = [None] * 4
        self.dead = dead
        self.calls = 0

    def chat(self, messages, **kw):
        self.calls += 1
        if self.dead:
            raise RuntimeError(f"{self.name} is dead")
        return self.reply


def _metrics_lint():
    path = os.path.join(os.path.dirname(__file__), os.pardir, "scripts",
                        "metrics_lint.py")
    spec = importlib.util.spec_from_file_location("metrics_lint", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _msg():
    return [{"role": "user", "content": f"q-{uuid.uuid4().hex}"}]


def _pump(gw, n):
    for _ in range(n):
        gw.chat({"messages": _msg()})


# ---------------------------------------------------------------- the loop
def test_closed_loop_e2e(tmp_path):
    import numpy as np

    from datatunerx_tpu.training.checkpoint import CheckpointManager

    # job-a trains with REAL periodic orbax checkpoints: its preemption
    # must record the step the orbax restore path will hand back
    ckpt_dir = str(tmp_path / "job-a-ckpts")
    mngr = CheckpointManager(ckpt_dir, save_interval_steps=1)
    mngr.maybe_save({"w": np.ones(2, np.float32)}, step=3, force=True)
    mngr.close()

    em = ExperimentMetrics(experiment="e2e")
    backend = FakeTrainingBackend()
    pool = SharedSlicePool([PoolSlice("s0"), PoolSlice("s1")])
    sched = SliceScheduler(pool, backend, metrics=em)

    feeds = {"job-a": {1: 80.0, 2: 85.0}, "job-b": {1: 20.0, 2: 22.0},
             "job-c": {1: 50.0, 2: 55.0}}
    revealed = {n: 0 for n in feeds}
    watcher = ContinuousScoringWatcher(
        sched,
        lambda j: [s for s in sorted(feeds[j.name])
                   if s <= revealed[j.name]],
        lambda j, s: feeds[j.name][s],
        board=Leaderboard(), metrics=em,
        early_stop_margin=30.0, min_evals=2)

    fleet = [FakeEngine("fleet-0"), FakeEngine("fleet-1")]
    gw_pool = ReplicaPool([InProcessReplica(e.name, e) for e in fleet])
    gw = Gateway(gw_pool, model_name="e2e")
    serving = FakeServingBackend()
    canary_engine = FakeEngine("canary", reply="promoted!")
    runner = ExperimentRunner(
        "e2e", sched, watcher, gateway=gw, serving_backend=serving,
        canary_replica_factory=lambda job: InProcessReplica(
            "unused", canary_engine),
        promotion_config={"schedule": [0.25, 1.0], "min_requests": 4,
                          "step_s": 60.0},
        metrics=em)

    sched.add_job("job-a", {"parameters": EIGHT,
                            "checkpoint_dir": ckpt_dir})
    sched.add_job("job-b", {"parameters": EIGHT})
    sched.add_job("job-c", {"parameters": EIGHT})

    # ---- tick 1: two slices, first two jobs run, job-c queues
    runner.tick()
    assert {j.name for j in sched.jobs() if j.state == RUNNING} \
        == {"job-a", "job-b"}

    # ---- first eval lands for a and b: live leaderboard
    revealed["job-a"] = revealed["job-b"] = 1
    runner.tick()
    assert watcher.board.leader().job == "job-a"

    # ---- pool shrinks under job-a: PREEMPTION with the orbax step
    displaced = sched.shrink(pool.assignment("job-a").name)
    assert displaced == "job-a"
    job_a = sched.job("job-a")
    assert job_a.state == PREEMPTED and job_a.resume_step == 3

    # ---- next tick: the displaced LEADER evicts the trailing job-b and
    # RESUMES from its checkpoint
    runner.tick()
    assert job_a.state == RUNNING and job_a.resumes == 1
    assert backend.jobs["job-a"]["env"]["DTX_RESUME_FROM_STEP"] == "3"
    assert sched.job("job-b").state == PREEMPTED

    # ---- pool grows back: job-b resumes beside the leader
    sched.grow(PoolSlice("s2"))
    runner.tick()
    assert sched.job("job-b").state == RUNNING

    # ---- second evals land: job-b is a clear loser → early-stopped,
    # freeing its slice for job-c
    revealed["job-a"] = revealed["job-b"] = 2
    runner.tick()
    assert sched.job("job-b").state == STOPPED
    runner.tick()
    assert sched.job("job-c").state == RUNNING
    revealed["job-c"] = 2
    runner.tick()

    # ---- training completes; the winner is the leaderboard leader
    backend.set_state("job-a", "Succeeded")
    backend.set_state("job-c", "Succeeded")
    runner.tick()
    assert runner.phase == PHASE_PROMOTE
    assert runner.winner.job == "job-a" and runner.winner.score == 85.0

    # ---- promotion: canary deploys via the serving backend, waits for
    # HEALTHY, then the weighted shift begins
    runner.tick()  # deploys; backend still PENDING
    assert "e2e-canary" in serving.apps
    assert runner.promotion is None
    serving.set_state("e2e-canary", "HEALTHY")
    runner.tick()  # replica in pool + promotion starts
    assert runner.promotion is not None
    runner.tick()  # stage 0 weights applied (canary 25%)
    canary = gw_pool.get("e2e-canary")
    assert canary is not None and canary.weight == pytest.approx(0.25)
    assert all(gw_pool.get(e.name).weight == pytest.approx(0.375)
               for e in fleet)

    # ---- observable shift: smooth WRR gives the canary EXACTLY its share
    _pump(gw, 16)
    assert canary_engine.calls == 4  # 25% of 16
    runner.tick()  # judge stage 0 (clean) → advance to weight 1.0
    assert canary.weight == pytest.approx(1.0)
    assert all(gw_pool.get(e.name).weight == 0.0 for e in fleet)
    before = canary_engine.calls
    _pump(gw, 6)
    assert canary_engine.calls == before + 6  # full rollout: all traffic
    runner.tick()  # judge final stage → COMPLETED
    assert runner.phase == PHASE_DONE
    assert runner.promotion.state == "completed"
    assert runner.events[-1]["promoted"] is True

    # ---- promotion phases visible as spans via GET /debug/trace/<id>
    srv = serve(gw, port=0, host="127.0.0.1")
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    try:
        url = (f"http://127.0.0.1:{srv.server_port}"
               f"/debug/trace/{runner.trace_id}")
        with urllib.request.urlopen(url, timeout=5) as r:
            doc = json.load(r)
        names = [s["name"] for s in doc["spans"]]
        assert "experiment.train" in names
        assert "experiment.promote" in names
        assert "promotion" in names
        assert names.count("promotion.stage") == 2
        stage_weights = sorted(s["attrs"]["weight"] for s in doc["spans"]
                               if s["name"] == "promotion.stage")
        assert stage_weights == [0.25, 1.0]
    finally:
        srv.shutdown()

    # ---- dtx_experiment_* exposition passes the metrics lint, and the
    # gateway exposes the per-replica weight series
    lint = _metrics_lint()
    assert lint.lint_exposition(em.expose(), "experiment") == []
    text = em.expose()
    assert "dtx_experiment_preemptions_total 2" in text  # a, then b evicted
    assert "dtx_experiment_resumes_total 2" in text
    assert "dtx_experiment_early_stops_total 1" in text
    assert 'dtx_experiment_promotion_phase{phase="completed"} 1' in text
    gw_text = gw.metrics_text()
    assert lint.lint_exposition(gw_text, "gateway") == []
    assert ('dtx_gateway_replica_weight{replica="e2e-canary"} 1'
            in gw_text)


# ---------------------------------------------------------------- rollback
def test_promotion_rolls_back_on_canary_regression():
    fleet = [FakeEngine("fleet-0"), FakeEngine("fleet-1")]
    pool = ReplicaPool([InProcessReplica(e.name, e) for e in fleet])
    gw = Gateway(pool, model_name="rb")
    em = ExperimentMetrics(experiment="rb")
    bad = FakeEngine("canary", dead=True)  # every canary attempt errors
    pool.add(InProcessReplica("canary", bad))

    promo = gw.start_promotion(
        "canary", config={"schedule": [0.5, 1.0], "min_requests": 3,
                          "step_s": 60.0},
        metrics=em, background=False)
    promo.tick()  # stage 0: canary at 50%
    assert pool.get("canary").weight == pytest.approx(0.5)
    # requests still succeed END-TO-END (failover), but the canary's
    # outcome window fills with errors and its breaker opens
    _pump(gw, 12)
    assert bad.calls >= 3
    state = promo.tick()
    assert state == "rolled_back"
    assert promo.reason
    assert pool.get("canary").weight == 0.0
    assert all(pool.get(e.name).weight == pytest.approx(1.0)
               for e in fleet)
    text = em.expose()
    assert "dtx_experiment_rollbacks_total 1" in text
    assert 'dtx_experiment_promotions_total{outcome="rolled_back"} 1' in text
    assert 'dtx_experiment_promotion_phase{phase="rolled_back"} 1' in text
    # a terminal promotion releases the single-flight slot
    promo2 = gw.start_promotion("canary", config={"schedule": [1.0]},
                                background=False)
    assert promo2 is not promo
    gw.close()


# ------------------------------------------------------------- http surface
@pytest.fixture()
def http_gateway():
    made = []

    def start(engines, **kw):
        pool = ReplicaPool([InProcessReplica(e.name, e) for e in engines])
        gw = Gateway(pool, **kw)
        srv = serve(gw, port=0, host="127.0.0.1")
        threading.Thread(target=srv.serve_forever, daemon=True).start()
        made.append((gw, srv))
        return gw, f"http://127.0.0.1:{srv.server_port}"

    yield start
    for gw, srv in made:
        srv.shutdown()
        gw.close()


def _post(url, path, payload):
    req = urllib.request.Request(
        url + path, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"}, method="POST")
    try:
        with urllib.request.urlopen(req, timeout=10) as r:
            return r.status, json.load(r)
    except urllib.error.HTTPError as e:
        return e.code, json.load(e)


def _get(url, path):
    try:
        with urllib.request.urlopen(url + path, timeout=10) as r:
            return r.status, json.load(r)
    except urllib.error.HTTPError as e:
        return e.code, json.load(e)


def test_admin_promote_http_contract(http_gateway):
    fleet = [FakeEngine("fleet-0"), FakeEngine("fleet-1")]
    gw, url = http_gateway(fleet, model_name="m")
    canary_engine = FakeEngine("canary", reply="new model")
    gw.pool.add(InProcessReplica("canary", canary_engine))

    code, body = _get(url, "/admin/promote")
    assert code == 404  # nothing started yet
    code, body = _post(url, "/admin/promote", {"replica": "ghost"})
    assert code == 400
    code, body = _post(url, "/admin/promote",
                       {"replica": "canary", "schedule": [0.5, 1.0],
                        "min_requests": 2, "step_s": 30.0})
    assert code == 202
    trace_id = body["trace_id"]
    assert body["canary"] == "canary" and body["schedule"] == [0.5, 1.0]
    code, _ = _post(url, "/admin/promote", {"replica": "canary"})
    assert code == 409  # single flight while active

    # traffic over the HTTP surface drives the stages forward
    deadline = time.monotonic() + 30
    state = ""
    while time.monotonic() < deadline:
        _post(url, "/chat/completions", {"messages": _msg()})
        code, body = _get(url, "/admin/promote")
        state = body["state"]
        if state in ("completed", "rolled_back"):
            break
        time.sleep(0.05)
    assert state == "completed"
    assert canary_engine.calls > 0

    # the whole shift is one trace: root + one span per stage
    code, doc = _get(url, f"/debug/trace/{trace_id}")
    assert code == 200
    names = [s["name"] for s in doc["spans"]]
    assert "promotion" in names and names.count("promotion.stage") == 2

    # weights survived to full rollout and are scrapeable
    with urllib.request.urlopen(url + "/metrics", timeout=5) as r:
        text = r.read().decode()
    assert 'dtx_gateway_replica_weight{replica="canary"} 1' in text
    assert 'dtx_gateway_replica_weight{replica="fleet-0"} 0' in text
    assert "dtx_gateway_replica_attempts_total" in text


def test_promote_schedule_validation(http_gateway):
    gw, url = http_gateway([FakeEngine("fleet-0"), FakeEngine("x")])
    code, body = _post(url, "/admin/promote",
                       {"replica": "x", "schedule": [0.5, 0.25]})
    assert code == 400 and "schedule" in body["error"]
    code, body = _post(url, "/admin/promote",
                       {"replica": "x", "schedule": [0.5]})
    assert code == 400  # must end at 1.0


def test_single_transient_error_does_not_roll_back():
    """The error-rate guard waits for min_requests of evidence — one
    transient canary failure (breaker still closed) must not kill the
    promotion."""
    fleet = [FakeEngine("fleet-0"), FakeEngine("fleet-1")]
    pool = ReplicaPool([InProcessReplica(e.name, e) for e in fleet])
    gw = Gateway(pool, model_name="tr")
    flaky = FakeEngine("canary")
    pool.add(InProcessReplica("canary", flaky))
    promo = gw.start_promotion(
        "canary", config={"schedule": [0.5, 1.0], "min_requests": 6,
                          "step_s": 60.0}, background=False)
    promo.tick()  # stage 0 at 50%
    flaky.dead = True
    _pump(gw, 2)  # exactly one canary attempt — it fails, failover serves
    flaky.dead = False
    assert promo.tick() == "shifting"  # 1 error, < min_requests: no verdict
    assert promo.stage == 0
    # healthy traffic dilutes the transient: 1 error over 25 canary
    # attempts = 4% < max_error_rate 5% → the stage advances, no rollback
    _pump(gw, 48)
    assert promo.tick() == "shifting" and promo.stage == 1
    _pump(gw, 8)
    assert promo.tick() == "completed"
    gw.close()


def test_replica_added_mid_shift_joins_the_weight_scheme():
    """The fleet is resolved live: a replica added during the shift is
    folded in at the next weight application and reset on completion —
    it must not keep weight 1.0 while the canary is 'fully rolled out'."""
    fleet = [FakeEngine("fleet-0"), FakeEngine("fleet-1")]
    pool = ReplicaPool([InProcessReplica(e.name, e) for e in fleet])
    gw = Gateway(pool, model_name="grow")
    canary_engine = FakeEngine("canary")
    pool.add(InProcessReplica("canary", canary_engine))
    promo = gw.start_promotion(
        "canary", config={"schedule": [0.5, 1.0], "min_requests": 2,
                          "step_s": 60.0}, background=False)
    promo.tick()
    late = FakeEngine("late-joiner")
    pool.add(InProcessReplica("late-joiner", late))  # autoscale mid-shift
    _pump(gw, 8)
    promo.tick()  # advance to 1.0: the late joiner must be weighted out
    assert promo.state in ("shifting", "completed")
    _pump(gw, 4)
    while promo.tick() not in ("completed", "rolled_back"):
        _pump(gw, 2)
    assert promo.state == "completed"
    assert pool.get("late-joiner").weight == 0.0
    assert pool.get("canary").weight == pytest.approx(1.0)
    gw.close()


# ------------------------------------------------------------ dtx experiment
def test_cli_fake_backend_runs_whole_loop(tmp_path, capsys):
    """`dtx experiment -f examples/experiment.json --backend fake` drives
    the entire closed loop in-process: simulated training, leaderboard,
    early stop, canary shift to 100%."""
    from datatunerx_tpu.cli import main as dtx_main

    spec = os.path.join(os.path.dirname(__file__), os.pardir, "examples",
                        "experiment.json")
    status_path = str(tmp_path / "status.json")
    rc = dtx_main(["experiment", "-f", spec, "--backend", "fake",
                   "--tick_s", "0", "--status_json", status_path])
    assert rc == 0
    out = capsys.readouterr().out
    assert '"event": "early_stop"' in out
    assert '"event": "promotion_started"' in out
    status = json.load(open(status_path))
    assert status["phase"] == "done"
    assert status["winner"] == "job-a"
    assert status["promotion"]["state"] == "completed"
    assert status["promotion"]["weight"] == 1.0
    board = {e["job"]: e for e in status["leaderboard"]["standings"]}
    assert board["job-b"]["evals"] >= 2  # loser was continuously scored


# ----------------------------------------------------------- weighted WRR
def test_weighted_routing_shares_are_exact():
    engines = [FakeEngine("a"), FakeEngine("b"), FakeEngine("c")]
    pool = ReplicaPool([InProcessReplica(e.name, e) for e in engines])
    gw = Gateway(pool, model_name="w")
    gw.set_weight("a", 0.375)
    gw.set_weight("b", 0.375)
    gw.set_weight("c", 0.25)
    _pump(gw, 16)
    assert {e.name: e.calls for e in engines} == {"a": 6, "b": 6, "c": 4}
    # weight 0 receives nothing
    gw.set_weight("c", 0.0)
    for e in engines:
        e.calls = 0
    _pump(gw, 8)
    assert engines[2].calls == 0 and sum(e.calls for e in engines) == 8
    # uniform weights restore the pre-weight least-busy behavior (no WRR)
    gw.set_weight("a", 1.0)
    gw.set_weight("b", 1.0)
    gw.set_weight("c", 1.0)
    _pump(gw, 4)
    assert sum(e.calls for e in engines) == 12
