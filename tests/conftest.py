"""Test bootstrap: force an 8-device virtual CPU mesh before tests touch JAX.

SURVEY.md §4.3: multi-host sharding is tested without hardware via a virtual
multi-device CPU platform — the same pjit/GSPMD programs that run on a TPU slice
run unchanged over 8 local CPU devices.

Note: the harness's sitecustomize registers the tunneled TPU ("axon") backend at
interpreter start, so env vars are too late here; ``jax.config.update`` still
switches the platform before any computation runs.
"""

import os

os.environ.setdefault("TF_ENABLE_ONEDNN_OPTS", "0")

# Persistent XLA compilation cache (VERDICT r4 #5): the suite's dominant cost
# is recompiling the same debug-model programs — in-process jits AND every
# spawned tuning.train / serving.server subprocess (env vars inherit). Keyed
# by HLO+config, so correctness-neutral; measured 43s -> 16s on one CLI e2e.
# Repo-local dir so repeat suite runs start warm (gitignored).
#
# The dir is fingerprinted by the HOST CPU: this VM can land on machines with
# different CPU features between sessions, and XLA:CPU AOT blobs compiled for
# one feature set SIGILL/abort on another (cpu_aot_loader warns exactly this;
# one full-suite run died with Fatal Python error: Aborted mid-execution).
# A migration just means a cold cache, never a crash.


def _host_fingerprint() -> str:
    import hashlib

    try:
        with open("/proc/cpuinfo") as f:
            flags = sorted({line for line in f
                            if line.startswith(("flags", "model name"))})
        return hashlib.sha256("".join(flags).encode()).hexdigest()[:12]
    except OSError:
        import platform

        return hashlib.sha256(platform.processor().encode()).hexdigest()[:12]


def _jax_version() -> tuple:
    from importlib.metadata import version

    try:
        return tuple(int(x) for x in version("jax").split(".")[:2])
    except Exception:  # noqa: BLE001 — unknown version: assume modern
        return (99, 0)


# jax 0.4.x XLA:CPU cache use INSIDE the suite's own process corrupts the
# heap (observed deterministically on 0.4.37: in-process cache hits on the
# e2e train-step program die with "corrupted double-linked list"/SIGSEGV —
# reproduced with a two-run() script, warm or warming cache, orbax in the
# mix). SPAWNED subprocesses are unaffected — every prior round ran the
# subprocess-heavy tests with the inherited cache env and a warming dir.
# So: the env vars are always exported (trainer/serving subprocesses inherit
# them and share compiles across spawns), but the PYTEST process itself only
# enables the cache on jax >= 0.5; on 0.4.x it is explicitly forced off
# in-process below. (In-process compile reuse comes from the Trainer
# step-program memo instead — training/train_lib.py.)
_cache_dir = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), ".jax_compilation_cache",
    _host_fingerprint())
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", _cache_dir)
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0")
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES", "0")

# Fast-poll the controller state machines (VERDICT r3 #7): the suite spent
# most of its 17 min in 3-30s requeue sleeps. The reference-parity defaults
# are unchanged in production; these envs only shrink the WAITS — every
# transition and assertion is identical. Must be set before any
# datatunerx_tpu.operator import reads them at module load.
for _k, _v in (
    ("DTX_POLL_INTERVAL_S", "0.1"),
    ("DTX_RUNNING_POLL_S", "0.2"),
    ("DTX_EXPERIMENT_POLL_S", "0.1"),
    ("DTX_SERVE_POLL_S", "0.1"),
    ("DTX_SCORING_RETRY_S", "0.2"),
    ("DTX_RECALIBRATE_REQUEUE_S", "0.2"),
    ("DTX_ERROR_REQUEUE_S", "0.3"),
    ("DTX_IDLE_HORIZON_S", "0.05"),
):
    os.environ.setdefault(_k, _v)

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 8)
except AttributeError:
    # jax < 0.5 has no jax_num_cpu_devices option. The XLA_FLAGS route still
    # works post-import because the CPU backend initializes lazily on first
    # device use — and the env var inherits into spawned trainer/serving
    # subprocesses, matching the config-option path.
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8"
    ).strip()
# the env vars above bind spawned subprocesses (fresh interpreters read them
# at import); for THIS process the config is set explicitly — enabled from
# the env values on jax >= 0.5, forced OFF on 0.4.x (see the heap-corruption
# note above; the env may have been read at import, so the off state must be
# asserted, not assumed).
if _jax_version() >= (0, 5):
    jax.config.update("jax_compilation_cache_dir",
                      os.environ["JAX_COMPILATION_CACHE_DIR"])
    jax.config.update("jax_persistent_cache_min_compile_time_secs",
                      float(os.environ["JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS"]))
    jax.config.update("jax_persistent_cache_min_entry_size_bytes",
                      int(os.environ["JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES"]))
else:
    jax.config.update("jax_compilation_cache_dir", None)

import pytest  # noqa: E402

# dtxsan (runtime sanitizer plane): opt-in via DTX_SAN=1 (or a class list,
# e.g. DTX_SAN=lock,compile). The plugin installs the lock-order / thread-leak
# / compile-budget instrumentation at configure time and reports via the
# dtxlint-style baseline contract at session finish. Must be declared here
# (top-level conftest) so pytest_configure runs before any test imports spawn
# threads or take locks.
if os.environ.get("DTX_SAN", "").strip().lower() not in ("", "0", "off"):
    pytest_plugins = ("datatunerx_tpu.analysis.sanitizers.plugin",)


@pytest.fixture(scope="session")
def devices8():
    devs = jax.devices()
    assert len(devs) >= 8, f"expected 8 virtual CPU devices, got {len(devs)}"
    return devs
