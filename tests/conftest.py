"""Test bootstrap: force an 8-device virtual CPU mesh before tests touch JAX.

SURVEY.md §4.3: multi-host sharding is tested without hardware via a virtual
multi-device CPU platform — the same pjit/GSPMD programs that run on a TPU slice
run unchanged over 8 local CPU devices.

Note: the harness's sitecustomize registers the tunneled TPU ("axon") backend at
interpreter start, so env vars are too late here; ``jax.config.update`` still
switches the platform before any computation runs.
"""

import os

os.environ.setdefault("TF_ENABLE_ONEDNN_OPTS", "0")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_num_cpu_devices", 8)

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def devices8():
    devs = jax.devices()
    assert len(devs) >= 8, f"expected 8 virtual CPU devices, got {len(devs)}"
    return devs
