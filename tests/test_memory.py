"""HBM capacity accounting (VERDICT r3 #4): exact param/opt/grad byte math
via eval_shape + shard divisors, analytic activation peaks, and the
admission gate that fails provably-oversized Finetunes before submission.

These tests ARE the BASELINE.md rows-4/5 capacity claims: if a stated
configuration stops fitting its stated hardware, they fail loudly.
"""

import pytest

from datatunerx_tpu.models import get_config
from datatunerx_tpu.operator.capacity import check_admission, resolve_model_config
from datatunerx_tpu.parallel.memory import (
    Footprint,
    check_fits,
    estimate_footprint,
    hbm_budget,
)
from datatunerx_tpu.training import TrainConfig


def _lora_cfg(**kw):
    return TrainConfig(finetuning_type="lora", lora_rank=8,
                       lora_targets=("q_proj", "v_proj"), **kw)


# ------------------------------------------------------------- components

def test_footprint_component_sanity_7b_qlora():
    """llama2-7b nf4: params ≈ 3.5 GB packed + ~0.5 GB bf16 embed/lm_head;
    adapters/opt/grads tiny; BASELINE row 2 geometry fits one v5e chip."""
    cfg = get_config("llama2-7b", quantization="int4",
                     attention_impl="flash", remat="full")
    fp = estimate_footprint(cfg, _lora_cfg(), batch=4, seq=1024)
    assert 3.3e9 < fp.params < 4.5e9, fp.gb()
    assert fp.lora < 0.1e9
    assert fp.opt_state < 0.2e9
    assert fp.grads < 0.1e9
    assert fp.total < hbm_budget("v5e"), fp.gb()


def test_quantization_shrinks_params():
    cfg16 = get_config("llama2-7b")
    cfg4 = get_config("llama2-7b", quantization="int4")
    tc = _lora_cfg()
    p16 = estimate_footprint(cfg16, tc, batch=1, seq=128).params
    p4 = estimate_footprint(cfg4, tc, batch=1, seq=128).params
    # 13.5 GB bf16 → ~3.9 GB (nf4 payload + bf16 embed/lm_head/norms)
    assert p4 < p16 * 0.35, (p4 / 1e9, p16 / 1e9)


def test_fsdp_shards_params_and_opt_state():
    cfg = get_config("mistral-7b")
    tc = TrainConfig(finetuning_type="full")
    solo = estimate_footprint(cfg, tc, batch=16, seq=1024)
    sharded = estimate_footprint(cfg, tc, batch=16, seq=1024,
                                 mesh_shape={"fsdp": 16})
    # kernels shard 16-way; norms replicate, so a bit above /16
    assert sharded.params < solo.params / 12
    assert sharded.opt_state < solo.opt_state / 12
    assert sharded.grads < solo.grads / 12
    # batch shards over fsdp too
    assert sharded.activations < solo.activations / 12


def test_remat_policy_orders_activation_memory():
    cfg_full = get_config("tinyllama-1.1b", remat="full",
                          attention_impl="flash")
    cfg_dots = get_config("tinyllama-1.1b", remat="dots",
                          attention_impl="flash")
    cfg_none = get_config("tinyllama-1.1b", remat="none",
                          attention_impl="flash")
    tc = _lora_cfg()
    a_full = estimate_footprint(cfg_full, tc, batch=8, seq=1024).activations
    a_dots = estimate_footprint(cfg_dots, tc, batch=8, seq=1024).activations
    a_none = estimate_footprint(cfg_none, tc, batch=8, seq=1024).activations
    assert a_full < a_dots < a_none


def test_grad_accum_reduces_activations_not_grads():
    cfg = get_config("tinyllama-1.1b", attention_impl="flash")
    one = estimate_footprint(cfg, _lora_cfg(grad_accum=1), batch=8, seq=1024)
    four = estimate_footprint(cfg, _lora_cfg(grad_accum=4), batch=8, seq=1024)
    assert four.activations < one.activations / 3
    assert four.grads == one.grads


# --------------------------------------------------- BASELINE.md rows 4-5

def test_baseline_mistral_7b_full_param_fits_v5e16():
    """BASELINE row 4: Mistral-7B full-parameter FSDP on v5e-16."""
    cfg = get_config("mistral-7b", attention_impl="flash", remat="full")
    tc = TrainConfig(finetuning_type="full")
    fits, fp, budget = check_fits(cfg, tc, batch=16, seq=1024,
                                  mesh_shape={"fsdp": 16})
    assert fits, (fp.gb(), budget / 1e9)


def test_baseline_qwen14b_qlora_fits_v5e1():
    """BASELINE row 5: Qwen1.5-14B nf4 QLoRA on a single v5e chip.
    batch=1: the 152k-vocab fp32 logits cast dominates; batch 2 at T1024
    exceeds 15 GB, so 1 is the stated operating point."""
    cfg = get_config("qwen1.5-14b", quantization="int4",
                     attention_impl="flash", remat="full")
    fits, fp, budget = check_fits(cfg, _lora_cfg(), batch=1, seq=1024)
    assert fits, (fp.gb(), budget / 1e9)


def test_oversized_rejected_7b_full_param_single_chip():
    """Full-parameter 7B on one v5e chip: 14.5 GB params + 29 GB adam
    moments can never fit 16 GB — the checker must say so."""
    cfg = get_config("llama2-7b", attention_impl="flash", remat="full")
    tc = TrainConfig(finetuning_type="full", optimizer="adamw")
    fits, fp, _ = check_fits(cfg, tc, batch=1, seq=512)
    assert not fits
    assert fp.params + fp.opt_state > 16e9


def test_unknown_generation_raises():
    with pytest.raises(KeyError):
        hbm_budget("v99")


# ------------------------------------------------------------- admission

_HP = {
    "loRA_R": "8", "loRA_Alpha": "32", "batchSize": "4",
    "blockSize": "1024", "PEFT": "true", "int4": "true",
    "attention": "flash",
}


def test_admission_admits_resolvable_fitting_job():
    assert check_admission("preset:llama2-7b", dict(_HP), n_chips=1) is None


def test_admission_rejects_oversized_with_breakdown():
    hp = dict(_HP, PEFT="false", int4="false")  # full-param 7B, 1 chip
    denied = check_admission("preset:llama2-7b", hp, n_chips=1)
    assert denied is not None
    reason, breakdown = denied
    assert "exceeds" in reason and "budget" in reason
    assert breakdown["total"] > 16


def test_admission_rejects_mesh_larger_than_slice():
    hp = dict(_HP, meshShape="fsdp=16")
    denied = check_admission("preset:llama2-7b", hp, n_chips=4)
    assert denied is not None
    assert "chips" in denied[0]


def test_admission_admits_unresolvable_model_path():
    assert check_admission("/models/does-not-exist", dict(_HP),
                           n_chips=1) is None


def test_admission_admits_on_garbled_numerics():
    hp = dict(_HP, batchSize="not-a-number")
    assert check_admission("preset:llama2-7b", hp, n_chips=1) is None


def test_admission_respects_meshshape_sharding():
    """Full-param 7B that cannot fit 1 chip is admitted on 16 with fsdp.
    batchSize is PER-DEVICE (--per_device_train_batch_size): 1/chip here."""
    hp = dict(_HP, PEFT="false", int4="false", meshShape="fsdp=16",
              batchSize="1")
    assert check_admission("preset:llama2-7b", hp, n_chips=16) is None


def test_admission_batch_is_per_device():
    """The same per-device batchSize must yield the same per-chip estimate
    regardless of slice width — a 4-chip dp mesh must NOT dilute it 4x."""
    hp = dict(_HP)  # qwen would be tighter, but llama2-7b is the fixture
    hp["batchSize"] = "4"
    solo = check_admission("preset:llama2-7b", hp, n_chips=1)
    wide = check_admission("preset:llama2-7b", hp, n_chips=4)
    assert solo is None and wide is None
    # and an oversized per-device batch is rejected on EVERY width
    hp["batchSize"] = "64"
    assert check_admission("preset:llama2-7b", hp, n_chips=1) is not None
    assert check_admission("preset:llama2-7b", hp, n_chips=4) is not None


def test_admission_partial_mesh_mirrors_trainer_semantics():
    """_mesh_shape_from must equal tuning/train.py:147-158 exactly:
    fsdp-only -> dp absorbs the remaining chips (admit full-param Mistral
    on 16); dp-only -> fsdp defaults to 1, which cannot tile 16 chips, so
    the job is rejected AT ADMISSION with the same error the trainer's
    mesh_shape_for would raise on-slice."""
    hp = {"PEFT": "false", "batchSize": "1", "blockSize": "1024",
          "attention": "flash", "meshShape": "fsdp=16"}
    assert check_admission("preset:mistral-7b", hp, n_chips=16) is None

    hp["meshShape"] = "dp=1"
    denied = check_admission("preset:mistral-7b", hp, n_chips=16)
    assert denied is not None and "tile" in denied[0]


def test_resolve_model_config_from_dir(tmp_path):
    import dataclasses as dc
    import json

    cfg = get_config("debug")
    (tmp_path / "config.json").write_text(json.dumps(dc.asdict(cfg)))
    got = resolve_model_config(str(tmp_path))
    assert got is not None and got.hidden_size == cfg.hidden_size


def test_footprint_total_is_sum():
    fp = Footprint(params=1, lora=2, opt_state=3, grads=4, activations=5,
                   logits=6)
    assert fp.total == 21
    assert fp.gb()["total"] == round(21 / 1e9, 3)


# -------------------------------------------- controller admission wiring

def test_finetune_controller_fails_oversized_job_at_admission(tmp_path):
    """An oversized job (full-param 7B on one host) goes STATE_FAILED with
    an admissionDenied reason + byte breakdown instead of being submitted."""
    from datatunerx_tpu.operator.api import (
        Dataset, Finetune, Hyperparameter, LLM, ObjectMeta)
    from datatunerx_tpu.operator.backends import (
        FakeServingBackend, FakeTrainingBackend)
    from datatunerx_tpu.operator.manager import build_manager
    from datatunerx_tpu.operator.store import ObjectStore

    store = ObjectStore()
    training = FakeTrainingBackend()
    mgr = build_manager(store, training, FakeServingBackend(),
                        storage_path=str(tmp_path / "storage"),
                        with_scoring=False)
    ns = "default"
    store.create(LLM(metadata=ObjectMeta(name="big", namespace=ns),
                     spec={"path": "preset:llama2-7b"}))
    store.create(Hyperparameter(
        metadata=ObjectMeta(name="hp-big", namespace=ns),
        spec={"parameters": {"PEFT": "false", "batchSize": "1",
                             "blockSize": "512", "attention": "flash"}}))
    store.create(Dataset(
        metadata=ObjectMeta(name="ds-big", namespace=ns),
        spec={"datasetMetadata": {"datasetInfo": {"subsets": [{"splits": {
            "train": {"file": "/data/train.csv"}}}]}}}))
    store.create(Finetune(metadata=ObjectMeta(name="too-big", namespace=ns),
                          spec={"llm": "big", "dataset": "ds-big",
                                "hyperparameter": {
                                    "hyperparameterRef": "hp-big"},
                                "image": {"name": "img",
                                          "path": "preset:llama2-7b"},
                                "node": 1}))
    mgr.sync_all()
    mgr.run_until_idle()
    ft = store.get(Finetune, "too-big", ns)
    assert ft.status.get("state") == Finetune.STATE_FAILED
    assert "exceeds" in ft.status.get("admissionDenied", "")
    assert ft.status.get("hbmEstimateGB", {}).get("total", 0) > 16
    assert "too-big" not in training.jobs


def test_finetune_controller_admits_fitting_job(tmp_path):
    """Same wiring, QLoRA variant that fits: submission must proceed."""
    from datatunerx_tpu.operator.api import (
        Dataset, Finetune, Hyperparameter, LLM, ObjectMeta)
    from datatunerx_tpu.operator.backends import (
        FakeServingBackend, FakeTrainingBackend)
    from datatunerx_tpu.operator.manager import build_manager
    from datatunerx_tpu.operator.store import ObjectStore

    store = ObjectStore()
    training = FakeTrainingBackend()
    mgr = build_manager(store, training, FakeServingBackend(),
                        storage_path=str(tmp_path / "storage"),
                        with_scoring=False)
    ns = "default"
    store.create(LLM(metadata=ObjectMeta(name="big", namespace=ns),
                     spec={"path": "preset:llama2-7b"}))
    store.create(Hyperparameter(
        metadata=ObjectMeta(name="hp-fit", namespace=ns),
        spec={"parameters": {"PEFT": "true", "int4": "true", "loRA_R": "8",
                             "batchSize": "4", "blockSize": "1024",
                             "attention": "flash"}}))
    store.create(Dataset(
        metadata=ObjectMeta(name="ds-big", namespace=ns),
        spec={"datasetMetadata": {"datasetInfo": {"subsets": [{"splits": {
            "train": {"file": "/data/train.csv"}}}]}}}))
    store.create(Finetune(metadata=ObjectMeta(name="fits", namespace=ns),
                          spec={"llm": "big", "dataset": "ds-big",
                                "hyperparameter": {
                                    "hyperparameterRef": "hp-fit"},
                                "image": {"name": "img",
                                          "path": "preset:llama2-7b"},
                                "node": 1}))
    mgr.sync_all()
    mgr.run_until_idle()
    ft = store.get(Finetune, "fits", ns)
    assert "admissionDenied" not in ft.status
    assert ft.status.get("state") in (Finetune.STATE_PENDING,
                                      Finetune.STATE_RUNNING)
