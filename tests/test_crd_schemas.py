"""CRD structural-schema enforcement (VERDICT r3 #5): the PUBLISHED
deploy/crds/ manifests drive validation and field pruning on the fake
apiserver, making crdgen.py's schemas load-bearing instead of decorative.

Differential contract:
- every example/deploy CR manifest round-trips UNCHANGED through a
  schema-enforcing apiserver (install CRDs first, then create);
- a corpus of deliberately-wrong manifests is rejected with
  apiserver-shaped 422 Invalid errors naming the bad field;
- unknown fields are pruned exactly where the schema closes a node
  (meshShape, scoring probes) and preserved everywhere
  x-kubernetes-preserve-unknown-fields is written;
- the status subresource split is strict: status is stripped on create,
  immutable through main-resource writes, and only writable via /status.
"""

import copy
import glob
import json
import os

import pytest
import yaml

from datatunerx_tpu.operator.api import KIND_BY_NAME
from datatunerx_tpu.operator.kubeclient import ApiError, KubeClient
from tests.fake_apiserver import FakeKubeApiServer

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_published_crds():
    """The deploy/crds/ YAML files as shipped — NOT all_crds() directly, so
    a stale checked-in manifest fails these tests."""
    docs = []
    for path in sorted(glob.glob(os.path.join(REPO, "deploy", "crds", "*.yaml"))):
        with open(path) as f:
            docs.extend(d for d in yaml.safe_load_all(f) if d)
    assert len(docs) == 8, [d["metadata"]["name"] for d in docs]
    return docs


@pytest.fixture()
def cluster():
    srv = FakeKubeApiServer().start()
    client = KubeClient(base_url=srv.url)
    for crd in _load_published_crds():
        client.request(
            "POST",
            "/apis/apiextensions.k8s.io/v1/customresourcedefinitions",
            body=crd)
    yield srv, client
    srv.stop()


def _path_for(doc, name=None):
    cls = KIND_BY_NAME[doc["kind"]]
    group, _, version = cls.api_version.partition("/")
    plural = cls.kind.lower() + "s"
    ns = doc.get("metadata", {}).get("namespace", "default")
    base = f"/apis/{group}/{version}/namespaces/{ns}/{plural}"
    return f"{base}/{name}" if name else base


def _create(client, doc):
    return client.request("POST", _path_for(doc), body=doc)


def test_published_crds_match_crdgen():
    """deploy/crds/ is generated — drift between the checked-in YAML and
    crdgen.py means the published schemas are stale."""
    from datatunerx_tpu.operator.crdgen import all_crds

    published = {d["metadata"]["name"]: d for d in _load_published_crds()}
    for crd in all_crds():
        assert published[crd["metadata"]["name"]] == crd, \
            f"stale deploy/crds/{crd['metadata']['name']}.yaml — " \
            "run scripts/gen_crds.py"


def test_all_example_manifests_roundtrip_unchanged(cluster):
    """Every CR in examples/ creates cleanly and the stored spec is
    byte-identical to what was sent (no field was pruned or rejected)."""
    srv, client = cluster
    n = 0
    for path in sorted(glob.glob(os.path.join(REPO, "examples", "*.json"))):
        with open(path) as f:
            docs = json.load(f)
        if not isinstance(docs, list):
            continue  # non-CR example (e.g. an experiment spec)
        for doc in docs:
            if doc.get("kind") not in KIND_BY_NAME:
                continue
            sent_spec = copy.deepcopy(doc.get("spec", {}))
            created = _create(client, doc)
            assert created["spec"] == sent_spec, (path, doc["metadata"])
            n += 1
    assert n >= 6  # quickstart + rlhf corpora


REJECT_CORPUS = [
    # (kind, spec, expected fragment of the apiserver error)
    ("Finetune", {"llm": "m"}, "spec.dataset: Required value"),
    ("Finetune", {"llm": "m", "dataset": "d", "node": "two"},
     "spec.node: Invalid value"),
    ("Finetune", {"llm": "m", "dataset": "d", "backoffLimit": True},
     "spec.backoffLimit: Invalid value"),
    ("Hyperparameter", {"parameters": {"scheduler": "warp"}},
     "spec.parameters.scheduler: Unsupported value"),
    ("Hyperparameter", {"parameters": {"optimizer": "sgd9000"}},
     "Unsupported value"),
    ("Hyperparameter", {"parameters": {"quantImpl": "cuda"}},
     "spec.parameters.quantImpl: Unsupported value"),
    ("Hyperparameter", {"parameters": {"batchSize": 4}},
     "spec.parameters.batchSize: Invalid value"),  # reference quirk: strings
    ("Hyperparameter", {"parameters": {"meshShape": {"dp": "four"}}},
     "spec.parameters.meshShape.dp: Invalid value"),
    ("Hyperparameter", {"parameters": "r=8"},
     "spec.parameters: Invalid value"),
    ("FinetuneJob", {}, "spec.finetune: Required value"),
    ("FinetuneJob", {"finetune": {"name": "x"}},
     "spec.finetune.finetuneSpec: Required value"),
    ("FinetuneExperiment", {"pending": True},
     "spec.finetuneJobs: Required value"),
    ("FinetuneExperiment", {"finetuneJobs": {"name": "a"}},
     "spec.finetuneJobs: Invalid value"),
    ("Dataset", {}, "spec.datasetMetadata: Required value"),
    ("Dataset", {"datasetMetadata": {"datasetInfo": {"subsets": "train"}}},
     "subsets: Invalid value"),
    ("Scoring", {"metric": "vibes"}, "spec.metric: Unsupported value"),
    ("Scoring", {"probes": [{"prompt": 42}]},
     "spec.probes[0].prompt: Invalid value"),
]


@pytest.mark.parametrize(
    "kind,spec,fragment",
    REJECT_CORPUS,
    ids=[f"{k}-{frag.split(':')[0].replace('.', '_')}"
         for k, _, frag in REJECT_CORPUS])
def test_wrong_manifests_rejected_with_apiserver_errors(cluster, kind, spec,
                                                        fragment):
    srv, client = cluster
    cls = KIND_BY_NAME[kind]
    doc = {"apiVersion": cls.api_version, "kind": kind,
           "metadata": {"name": "bad", "namespace": "default"},
           "spec": spec}
    with pytest.raises(ApiError) as ei:
        _create(client, doc)
    assert ei.value.status == 422, ei.value.body
    assert "is invalid" in ei.value.body
    assert fragment in ei.value.body, (fragment, ei.value.body)


def test_unknown_fields_pruned_in_closed_meshshape(cluster):
    """meshShape is a CLOSED node: a typo'd axis is pruned (so it can never
    silently change the mesh) while unknown fields under the open
    parameters node survive (x-kubernetes-preserve-unknown-fields)."""
    srv, client = cluster
    doc = {"apiVersion": "core.datatunerx.io/v1beta1",
           "kind": "Hyperparameter",
           "metadata": {"name": "prune", "namespace": "default"},
           "spec": {"parameters": {
               "meshShape": {"dp": 2, "fspd": 4},     # typo'd axis
               "customAnnotation": "kept",            # open node: preserved
           }}}
    created = _create(client, doc)
    assert created["spec"]["parameters"]["meshShape"] == {"dp": 2}
    assert created["spec"]["parameters"]["customAnnotation"] == "kept"


def test_unknown_fields_pruned_in_closed_probes(cluster):
    srv, client = cluster
    doc = {"apiVersion": "extension.datatunerx.io/v1beta1", "kind": "Scoring",
           "metadata": {"name": "prune-probe", "namespace": "default"},
           "spec": {"probes": [{"prompt": "p", "reference": "r",
                                "weight": 2}]}}
    created = _create(client, doc)
    assert created["spec"]["probes"] == [{"prompt": "p", "reference": "r"}]


def test_open_nodes_preserve_unknown_fields(cluster):
    """LLM.spec is open: arbitrary extra fields (quickstart's `family`)
    must survive exactly as written."""
    srv, client = cluster
    doc = {"apiVersion": "core.datatunerx.io/v1beta1", "kind": "LLM",
           "metadata": {"name": "open", "namespace": "default"},
           "spec": {"path": "preset:debug", "family": "llama",
                    "extra": {"nested": [1, 2]}}}
    created = _create(client, doc)
    assert created["spec"] == doc["spec"]


def test_update_also_schema_gated(cluster):
    srv, client = cluster
    doc = {"apiVersion": "core.datatunerx.io/v1beta1",
           "kind": "Hyperparameter",
           "metadata": {"name": "upd", "namespace": "default"},
           "spec": {"parameters": {"scheduler": "cosine"}}}
    created = _create(client, doc)
    bad = copy.deepcopy(created)
    bad["spec"]["parameters"]["scheduler"] = "warp"
    with pytest.raises(ApiError) as ei:
        client.request("PUT", _path_for(doc, "upd"), body=bad)
    assert ei.value.status == 422
    assert "Unsupported value" in ei.value.body


def test_status_subresource_split_strict(cluster):
    """Create strips status; main-resource PUT cannot touch status; /status
    PUT writes only status."""
    srv, client = cluster
    doc = {"apiVersion": "finetune.datatunerx.io/v1beta1", "kind": "Finetune",
           "metadata": {"name": "st", "namespace": "default"},
           "spec": {"llm": "m", "dataset": "d"},
           "status": {"state": "SUCCESSFUL"}}
    created = _create(client, doc)
    assert created["status"] == {}  # stripped on create

    smuggle = copy.deepcopy(created)
    smuggle["status"] = {"state": "SUCCESSFUL"}
    updated = client.request("PUT", _path_for(doc, "st"), body=smuggle)
    assert updated["status"] == {}  # main write cannot set status

    st = copy.deepcopy(updated)
    st["status"] = {"state": "RUNNING"}
    via_sub = client.request("PUT", _path_for(doc, "st") + "/status", body=st)
    assert via_sub["status"] == {"state": "RUNNING"}
    # and a status write cannot smuggle spec changes
    st2 = copy.deepcopy(via_sub)
    st2["spec"] = {"llm": "other", "dataset": "d"}
    st2["status"] = {"state": "RUNNING", "x": 1}
    via_sub2 = client.request("PUT", _path_for(doc, "st") + "/status",
                              body=st2)
    assert via_sub2["spec"] == {"llm": "m", "dataset": "d"}


def test_builtin_kinds_stay_ungated(cluster):
    """No CRD stored for jobsets: arbitrary shapes pass through (the fake
    mirrors a real apiserver's builtin handling, which we don't model)."""
    srv, client = cluster
    created = client.request(
        "POST", "/apis/jobset.x-k8s.io/v1alpha2/namespaces/default/jobsets",
        body={"apiVersion": "jobset.x-k8s.io/v1alpha2", "kind": "JobSet",
              "metadata": {"name": "js", "namespace": "default"},
              "spec": {"replicatedJobs": "whatever"}})
    assert created["spec"] == {"replicatedJobs": "whatever"}
