"""Scoring: metrics math, built-in scorer with custom probes, plugin path,
controller retry semantics."""

import json
import threading
from http.server import BaseHTTPRequestHandler, HTTPServer

import pytest

from datatunerx_tpu.operator.api import ObjectMeta, Scoring
from datatunerx_tpu.operator.manager import build_manager
from datatunerx_tpu.operator.backends import FakeServingBackend, FakeTrainingBackend
from datatunerx_tpu.operator.store import ObjectStore
from datatunerx_tpu.scoring.metrics import bleu4, generation_scores, rouge_l, rouge_n
from datatunerx_tpu.scoring.plugin import register_plugin


def test_metrics_math():
    assert rouge_n("the cat sat", "the cat sat", 1) == 1.0
    assert rouge_n("dog", "the cat sat", 1) == 0.0
    assert rouge_l("a b c d", "a x c d") == pytest.approx(0.75)
    assert bleu4("same tokens here ok", "same tokens here ok") == pytest.approx(1.0)
    s = generation_scores("paris", "Paris is the capital")
    assert 0 <= s["rouge-1"] <= 1


class _ChatStub(BaseHTTPRequestHandler):
    def do_POST(self):
        body = json.loads(self.rfile.read(int(self.headers["Content-Length"])))
        prompt = body["messages"][0]["content"]
        answer = {"say blue": "blue", "say cat": "cat"}.get(prompt, "dunno")
        payload = json.dumps({"choices": [{"message": {"content": answer}}]}).encode()
        self.send_response(200)
        self.send_header("Content-Length", str(len(payload)))
        self.end_headers()
        self.wfile.write(payload)

    def log_message(self, *a):
        pass


@pytest.fixture()
def chat_stub():
    srv = HTTPServer(("127.0.0.1", 0), _ChatStub)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    yield f"http://127.0.0.1:{srv.server_port}/chat/completions"
    srv.shutdown()


def test_builtin_scoring_with_custom_probes(chat_stub, tmp_path):
    store = ObjectStore()
    mgr = build_manager(store, FakeTrainingBackend(), FakeServingBackend(),
                        storage_path=str(tmp_path), with_scoring=True)
    store.create(Scoring(
        metadata=ObjectMeta(name="sc1"),
        spec={
            "inferenceService": chat_stub,
            "plugin": {"loadPlugin": False},
            "probes": [
                {"prompt": "say blue", "reference": "blue"},
                {"prompt": "say cat", "reference": "cat"},
            ],
        },
    ))
    mgr.run_until_idle()
    sc = store.get(Scoring, "sc1")
    assert sc.status["score"] == "100.0"
    assert len(sc.status["details"]) == 2


def test_plugin_scoring(chat_stub, tmp_path):
    register_plugin("always-42", lambda url, params: 42.0)
    store = ObjectStore()
    mgr = build_manager(store, FakeTrainingBackend(), FakeServingBackend(),
                        storage_path=str(tmp_path), with_scoring=True)
    store.create(Scoring(
        metadata=ObjectMeta(name="sc2"),
        spec={"inferenceService": chat_stub,
              "plugin": {"loadPlugin": True, "name": "always-42"}},
    ))
    mgr.run_until_idle()
    assert store.get(Scoring, "sc2").status["score"] == "42.0"


def test_scoring_retries_on_unreachable_endpoint(tmp_path):
    store = ObjectStore()
    mgr = build_manager(store, FakeTrainingBackend(), FakeServingBackend(),
                        storage_path=str(tmp_path), with_scoring=True)
    store.create(Scoring(
        metadata=ObjectMeta(name="sc3"),
        spec={"inferenceService": "http://127.0.0.1:1/chat/completions",
              "plugin": {"loadPlugin": False}},
    ))
    mgr.run_until_idle()
    sc = store.get(Scoring, "sc3")
    assert sc.status.get("score") is None
    assert "lastError" in sc.status  # transient failure recorded, retry queued
