"""``dtx install`` (VERDICT r2 next-round #6): one command rendering CRDs +
RBAC + operator Deployment + env config, parity with the reference's
dtx-ctl/Helm install flow (reference INSTALL.md:26-48,115-144). The rendered
bundle must apply cleanly against the fake apiserver, idempotently."""


import pytest

from datatunerx_tpu.cli import main as cli_main
from datatunerx_tpu.operator.install import (
    apply_manifest,
    install,
    render_install_manifests,
)
from datatunerx_tpu.operator.kubeclient import KubeClient
from tests.fake_apiserver import FakeKubeApiServer


@pytest.fixture()
def apiserver():
    srv = FakeKubeApiServer().start()
    yield srv
    srv.stop()


def test_render_bundle_shape():
    docs = render_install_manifests(
        namespace="dtx-ns",
        env={"S3_ACCESS_KEY": "ak", "S3_SECRET_KEY": "sk",
             "S3_ENDPOINT": "http://minio:9000", "STORAGE_PATH": "/st"},
    )
    kinds = [d["kind"] for d in docs]
    assert kinds.count("CustomResourceDefinition") == 8
    for required in ("Namespace", "ServiceAccount", "ClusterRole",
                     "ClusterRoleBinding", "ConfigMap", "Secret", "Service",
                     "MutatingWebhookConfiguration",
                     "ValidatingWebhookConfiguration", "Deployment"):
        assert required in kinds, f"missing {required}"
    # credentials in the Secret, plain config in the ConfigMap
    secret = next(d for d in docs if d["kind"] == "Secret")
    cm = next(d for d in docs if d["kind"] == "ConfigMap")
    assert set(secret["stringData"]) == {"S3_ACCESS_KEY", "S3_SECRET_KEY"}
    assert cm["data"]["S3_ENDPOINT"] == "http://minio:9000"
    assert "S3_SECRET_KEY" not in cm["data"]
    # deployment wires both via envFrom and runs the kube backend
    dep = next(d for d in docs if d["kind"] == "Deployment")
    c = dep["spec"]["template"]["spec"]["containers"][0]
    assert any("configMapRef" in e for e in c["envFrom"])
    assert "--backend=kube" in c["args"]
    assert dep["metadata"]["namespace"] == "dtx-ns"


def test_install_applies_cleanly_and_idempotently(apiserver):
    client = KubeClient(base_url=apiserver.url)
    lines = install(client, namespace="dtx-ns",
                    env={"S3_ACCESS_KEY": "ak"})
    assert all(line.endswith("created") for line in lines), lines

    # CRDs present, cluster-scoped
    crds = client.request(
        "GET", "/apis/apiextensions.k8s.io/v1/customresourcedefinitions")
    names = {i["metadata"]["name"] for i in crds["items"]}
    assert "finetunejobs.finetune.datatunerx.io" in names
    assert "datasets.extension.datatunerx.io" in names

    # second run: everything updates in place (create-or-update)
    lines2 = install(client, namespace="dtx-ns",
                     env={"S3_ACCESS_KEY": "ak2"})
    assert all(line.endswith("configured") for line in lines2), lines2
    sec = client.request(
        "GET", "/api/v1/namespaces/dtx-ns/secrets/dtx-credentials")
    assert sec["stringData"]["S3_ACCESS_KEY"] == "ak2"


def test_dry_run_output_applies_against_fake(apiserver, capsys):
    """The --dry-run manifests are the install: applying its output must
    produce the same objects (VERDICT done-criterion)."""
    rc = cli_main(["install", "--dry-run", "-n", "dtx-ns",
                   "--set", "S3_ACCESS_KEY=k", "--set", "STORAGE_PATH=/st"])
    assert rc == 0
    out = capsys.readouterr().out
    import yaml

    docs = [d for d in yaml.safe_load_all(out) if d]
    client = KubeClient(base_url=apiserver.url)
    for doc in docs:
        assert apply_manifest(client, doc, namespace="dtx-ns") == "created"
    dep = client.request(
        "GET",
        "/apis/apps/v1/namespaces/dtx-ns/deployments/"
        "datatunerx-tpu-controller-manager")
    assert dep["spec"]["template"]["spec"]["containers"][0]["command"][0] == \
        "python"


def test_cli_install_against_fake_server(apiserver, capsys):
    rc = cli_main(["install", "-n", "dtx-ns", "--kube-url", apiserver.url,
                   "--set", "S3_ACCESS_KEY=k"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "deployment/datatunerx-tpu-controller-manager created" in out
    assert "namespace/dtx-ns created" in out


def test_webhook_service_routes_to_operator():
    docs = render_install_manifests(namespace="nsx")
    svc = next(d for d in docs if d["kind"] == "Service")
    vwc = next(d for d in docs
               if d["kind"] == "ValidatingWebhookConfiguration")
    cc = vwc["webhooks"][0]["clientConfig"]["service"]
    assert cc["name"] == svc["metadata"]["name"]
    assert cc["namespace"] == "nsx"
    assert svc["spec"]["ports"][0]["port"] == 9443
