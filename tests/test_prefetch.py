"""Pipelined input path tests (data/prefetch.py): ordering, backpressure,
exception propagation, shutdown, device placement marking, non-blocking
metrics resolution — and the load-bearing guarantee, asserted end-to-end
through the real trainer entrypoint: the pipelined loop is LOSS-IDENTICAL to
the synchronous loop on a fixed seed."""

import csv
import json
import os
import threading
import time

import numpy as np
import pytest

from datatunerx_tpu.data.prefetch import (
    DevicePrefetcher,
    HostPrefetcher,
    MetricsBuffer,
    PipelineStats,
    PlacedBatch,
    prefetch_batches,
)


class CountingSource:
    """Iterator that records how far the worker has pulled, with an optional
    failure point and a gate to block production."""

    def __init__(self, n, fail_at=None, gate=None):
        self.n = n
        self.fail_at = fail_at
        self.gate = gate
        self.pulled = 0

    def __iter__(self):
        for i in range(self.n):
            if self.gate is not None:
                self.gate.wait()
            if self.fail_at is not None and i == self.fail_at:
                raise ValueError(f"boom at {i}")
            self.pulled = i + 1
            yield {"i": i}


# ------------------------------------------------------------ HostPrefetcher

def test_host_prefetcher_preserves_order():
    src = CountingSource(25)
    with HostPrefetcher(src, depth=3) as pf:
        got = [b["i"] for b in pf]
    assert got == list(range(25))


def test_host_prefetcher_accepts_callable_source():
    with HostPrefetcher(lambda: iter(CountingSource(5)), depth=2) as pf:
        assert [b["i"] for b in pf] == [0, 1, 2, 3, 4]


def test_host_prefetcher_bounded_queue_backpressure():
    src = CountingSource(100)
    pf = HostPrefetcher(src, depth=2)
    try:
        deadline = time.monotonic() + 2.0
        while src.pulled < 3 and time.monotonic() < deadline:
            time.sleep(0.01)
        time.sleep(0.2)  # give an unbounded worker time to run away
        # queue holds `depth`, worker holds at most one more blocked on put
        assert src.pulled <= 3, f"worker ran ahead: pulled {src.pulled}"
        next(pf)  # consuming one frees one slot…
        deadline = time.monotonic() + 2.0
        while src.pulled < 4 and time.monotonic() < deadline:
            time.sleep(0.01)
        time.sleep(0.1)
        assert src.pulled <= 4  # …and the worker advances exactly one
    finally:
        pf.close()


def test_host_prefetcher_propagates_worker_exception():
    pf = HostPrefetcher(CountingSource(10, fail_at=2), depth=2)
    assert next(pf)["i"] == 0
    assert next(pf)["i"] == 1
    with pytest.raises(ValueError, match="boom at 2"):
        next(pf)
    # after the error the iterator is finished, not wedged
    with pytest.raises(StopIteration):
        next(pf)


def test_host_prefetcher_shutdown_mid_epoch():
    """close() must stop a worker blocked on a FULL queue and join it."""
    src = CountingSource(10_000)
    pf = HostPrefetcher(src, depth=2)
    deadline = time.monotonic() + 2.0
    while src.pulled < 3 and time.monotonic() < deadline:
        time.sleep(0.01)  # worker now blocked on put()
    pf.close()
    assert not pf._thread.is_alive()
    with pytest.raises(StopIteration):
        next(pf)
    pf.close()  # idempotent


def test_host_prefetcher_shutdown_while_source_blocked():
    """close() while the worker is inside next(source) — the thread is daemon
    so it cannot block interpreter exit; close() must still return promptly."""
    gate = threading.Event()
    src = CountingSource(10, gate=gate)
    pf = HostPrefetcher(src, depth=2)
    t0 = time.monotonic()
    pf.close()
    assert time.monotonic() - t0 < 3.0
    gate.set()  # unblock the worker so it can exit


def test_host_prefetcher_rejects_bad_depth():
    with pytest.raises(ValueError):
        HostPrefetcher(CountingSource(1), depth=0)


# ---------------------------------------------------------- DevicePrefetcher

def test_device_prefetcher_marks_and_orders():
    placed_log = []

    def place(b):
        placed_log.append(b["i"])
        return {"i": b["i"], "placed": True}

    out = list(DevicePrefetcher(iter(CountingSource(8)), place, depth=2))
    assert [b["i"] for b in out] == list(range(8))
    assert placed_log == list(range(8))
    assert all(isinstance(b, PlacedBatch) for b in out)


def test_device_prefetcher_keeps_depth_in_flight():
    placed = []

    def place(b):
        placed.append(b["i"])
        return b

    dp = DevicePrefetcher(iter(CountingSource(10)), place, depth=3)
    first = next(dp)
    assert first["i"] == 0
    # pulling one batch fills the buffer: the returned one + depth ahead
    assert len(placed) <= 4


def test_trainer_put_batch_passes_placed_through():
    from datatunerx_tpu.models.config import ModelConfig
    from datatunerx_tpu.training.train_lib import TrainConfig, Trainer

    cfg = ModelConfig(vocab_size=64, hidden_size=16, intermediate_size=32,
                      num_layers=1, num_heads=2, num_kv_heads=2,
                      max_seq_len=32, remat="none")
    tr = Trainer(cfg, TrainConfig(finetuning_type="lora", lora_rank=2,
                                  lora_dropout=0.0, total_steps=10,
                                  compute_dtype=None))
    marker = object()
    out = tr._put_batch(PlacedBatch({"input_ids": marker}))
    assert out["input_ids"] is marker  # no re-placement


# ------------------------------------------------------------- MetricsBuffer

class FakeArr:
    def __init__(self, value, ready=False):
        self.value = value
        self.ready = ready

    def is_ready(self):
        return self.ready

    def __float__(self):
        return float(self.value)


def test_metrics_buffer_holds_back_newest_until_ready():
    buf = MetricsBuffer(lag=1)
    buf.push(1, {"loss": FakeArr(1.0)}, {"epoch": 0.1})
    assert buf.pop_ready() == []  # newest entry, not ready: held
    buf.push(2, {"loss": FakeArr(2.0)})
    out = buf.pop_ready()  # step 1 now older than the lag window: resolved
    assert out == [(1, {"loss": 1.0, "epoch": 0.1})]
    assert len(buf) == 1


def test_metrics_buffer_resolves_ready_entries_early():
    buf = MetricsBuffer(lag=1)
    buf.push(1, {"loss": FakeArr(3.0, ready=True)})
    assert buf.pop_ready() == [(1, {"loss": 3.0})]


def test_metrics_buffer_drain_resolves_everything():
    buf = MetricsBuffer(lag=2)
    buf.push(1, {"loss": FakeArr(1.0)})
    buf.push(2, {"loss": FakeArr(2.0)})
    out = buf.drain()
    assert [s for s, _ in out] == [1, 2]
    assert len(buf) == 0


def test_metrics_buffer_handles_plain_floats():
    buf = MetricsBuffer(lag=1)
    buf.push(5, {"loss": 0.5, "lr": 1e-4})
    assert buf.pop_ready() == [(5, {"loss": 0.5, "lr": 1e-4})]


# ------------------------------------------------------------ pipeline stats

def test_pipeline_stats_snapshot_means_and_resets():
    st = PipelineStats()
    st.record("host_build_ms", 2.0)
    st.record("host_build_ms", 4.0)
    snap = st.snapshot()
    assert snap == {"pipe_host_build_ms": 3.0}
    assert st.snapshot() == {}  # reset


def test_prefetch_batches_composes_and_reports_stats():
    stats = PipelineStats()
    it, host = prefetch_batches(
        CountingSource(6),
        place_fn=lambda b: {"i": b["i"]},
        depth=2, stats=stats,
    )
    try:
        assert [b["i"] for b in it] == list(range(6))
    finally:
        host.close()
    snap = stats.snapshot()
    assert "pipe_host_build_ms" in snap
    assert "pipe_device_put_ms" in snap
    assert "pipe_step_wait_ms" in snap
    assert "pipe_queue_depth" in snap


# ------------------------------------------- loss parity with the sync loop

def _parity_flags(tmp_path, tag, prefetch_depth):
    train = str(tmp_path / "train.csv")
    evalp = str(tmp_path / "eval.csv")
    out = str(tmp_path / f"out-{tag}")
    storage = str(tmp_path / f"storage-{tag}")
    if not os.path.exists(train):
        rows = [("add %d+%d" % (k, k), "answer %d" % (2 * k))
                for k in range(64)]
        with open(train, "w", newline="") as f:
            w = csv.writer(f)
            w.writerow(["instruction", "response"])
            w.writerows(rows)
        with open(evalp, "w", newline="") as f:
            w = csv.writer(f)
            w.writerow(["instruction", "response"])
            w.writerows(rows[:8])
    return [
        "--model_name_or_path", "preset:debug",
        "--train_path", train,
        "--evaluation_path", evalp,  # eval rides the pipeline too
        "--eval_steps", "2",
        "--output_dir", out,
        "--storage_path", storage,
        "--template", "vanilla",
        "--block_size", "64",
        "--per_device_train_batch_size", "2",
        "--max_steps", "4",
        "--logging_steps", "1",
        "--learning_rate", "0.01",
        "--bf16", "false",
        "--remat", "none",
        "--seed", "7",
        "--uid", f"parity-{tag}",
        "--prefetch_depth", str(prefetch_depth),
    ], out


def _loss_seq(out_dir):
    path = os.path.join(out_dir, "watch", "trainer_log.jsonl")
    recs = [json.loads(line) for line in open(path)]
    return [(r["current_steps"], r["loss"]) for r in recs]


def test_pipelined_loop_loss_identical_to_synchronous(tmp_path):
    """The tentpole invariant: pipelining changes WHEN work happens, never
    the numbers — the same seed must produce the exact same loss sequence
    through the real entrypoint with the pipeline on and off."""
    from datatunerx_tpu.tuning.parser import parse_train_args
    from datatunerx_tpu.tuning.train import run

    argv_sync, out_sync = _parity_flags(tmp_path, "sync", 0)
    argv_pipe, out_pipe = _parity_flags(tmp_path, "pipe", 3)
    r_sync = run(parse_train_args(argv_sync))
    r_pipe = run(parse_train_args(argv_pipe))
    assert r_sync["steps"] == r_pipe["steps"] == 4
    sync_losses = _loss_seq(out_sync)
    pipe_losses = _loss_seq(out_pipe)
    assert [s for s, _ in sync_losses] == [s for s, _ in pipe_losses] == [1, 2, 3, 4]
    assert sync_losses == pipe_losses  # bit-identical, not approximately
    # the EVAL path rides the same pipeline (ROADMAP follow-on): prefetched
    # eval must be loss-identical to the synchronous eval too
    def eval_seq(out_dir):
        path = os.path.join(out_dir, "watch", "eval_log.jsonl")
        return [(r["current_steps"], r["eval_loss"])
                for r in map(json.loads, open(path))]

    sync_eval, pipe_eval = eval_seq(out_sync), eval_seq(out_pipe)
    assert sync_eval and sync_eval == pipe_eval
    # pipeline health metrics ride the pipelined run's log records only
    pipe_recs = [json.loads(line) for line in
                 open(os.path.join(out_pipe, "watch", "trainer_log.jsonl"))]
    assert any("pipe_host_build_ms" in r for r in pipe_recs)
    assert any("pipe_device_put_ms" in r for r in pipe_recs)
    sync_recs = [json.loads(line) for line in
                 open(os.path.join(out_sync, "watch", "trainer_log.jsonl"))]
    assert not any("pipe_host_build_ms" in r for r in sync_recs)


def test_pipelined_trainer_losses_match_inline(devices8):
    """In-process parity on the Trainer API: identical batches through
    Trainer.train_step directly vs via DevicePrefetcher-placed batches."""
    import jax

    from datatunerx_tpu.models.config import ModelConfig
    from datatunerx_tpu.models.llama import init_params
    from datatunerx_tpu.parallel.mesh import make_mesh
    from datatunerx_tpu.parallel.sharding import place_batch
    from datatunerx_tpu.training.loss import IGNORE_INDEX
    from datatunerx_tpu.training.train_lib import TrainConfig, Trainer

    cfg = ModelConfig(vocab_size=128, hidden_size=32, intermediate_size=64,
                      num_layers=2, num_heads=4, num_kv_heads=2,
                      max_seq_len=64, remat="none")
    mesh = make_mesh((4, 2, 1, 1))
    rng = np.random.default_rng(0)
    batches = []
    for _ in range(4):
        toks = rng.integers(4, 128, size=(8, 16)).astype(np.int32)
        labels = toks.copy()
        labels[:, :4] = IGNORE_INDEX
        batches.append({"input_ids": toks, "labels": labels})

    def losses(pipelined):
        tr = Trainer(cfg, TrainConfig(
            finetuning_type="lora", lora_rank=4, lora_dropout=0.0,
            learning_rate=1e-2, scheduler="constant", optimizer="adamw",
            total_steps=10, compute_dtype=None), mesh=mesh)
        params = init_params(cfg, jax.random.PRNGKey(0))
        state = tr.init_state(params, jax.random.PRNGKey(1))
        out = []
        if pipelined:
            it, host = prefetch_batches(
                iter(batches), place_fn=lambda b: place_batch(b, mesh),
                depth=2)
            try:
                for b in it:
                    state, m = tr.train_step(state, b)
                    out.append(float(m["loss"]))
            finally:
                host.close()
        else:
            for b in batches:
                state, m = tr.train_step(state, b)
                out.append(float(m["loss"]))
        return out

    assert losses(False) == losses(True)


# --------------------------------------------------------- in-run retuning

def test_host_prefetcher_resize_deepens_live_queue():
    """resize() grows the bounded queue while the worker runs: the worker
    immediately fills the new headroom, order is preserved, nothing drops."""
    src = CountingSource(100)
    pf = HostPrefetcher(src, depth=2)
    try:
        deadline = time.monotonic() + 2.0
        while src.pulled < 3 and time.monotonic() < deadline:
            time.sleep(0.01)
        assert src.pulled <= 3  # old bound holds first
        assert pf.resize(6) == 6
        deadline = time.monotonic() + 2.0
        while src.pulled < 7 and time.monotonic() < deadline:
            time.sleep(0.01)
        # queue now holds 6, worker blocked holding one more
        assert 6 <= src.pulled <= 7, f"resize not honored: {src.pulled}"
        got = [b["i"] for b in pf]
        assert got == list(range(100))  # order + completeness survive
    finally:
        pf.close()


def test_host_prefetcher_resize_rejects_bad_depth():
    pf = HostPrefetcher(CountingSource(3), depth=2)
    try:
        with pytest.raises(ValueError):
            pf.resize(0)
    finally:
        pf.close()


class _FakePrefetcher:
    def __init__(self):
        self.resized_to = None

    def resize(self, depth):
        self.resized_to = depth
        return depth


def test_metrics_logger_retunes_live_prefetcher(tmp_path, monkeypatch):
    """The once-per-run advisory ACTS when a live prefetcher is attached:
    the queue is resized to the suggested depth, the advisory records it,
    and effective_prefetch_depth carries the new depth into later epochs."""
    from datatunerx_tpu.training.metrics_log import MetricsLogger

    monkeypatch.setenv("DTX_PREFETCH_ADVISE_RECORDS", "5")
    logger = MetricsLogger(str(tmp_path), total_steps=100, prefetch_depth=2)
    pf = _FakePrefetcher()
    logger.attach_prefetcher(pf)
    for step in range(5):
        logger.log_train(step, {"loss": 1.0, "pipe_step_wait_ms": 50.0})
    adv = logger.prefetch_advisory
    assert adv is not None and adv["retuned"] is True
    assert adv["suggested_prefetch_depth"] == 4
    assert pf.resized_to == 4
    assert logger.effective_prefetch_depth() == 4


def test_metrics_logger_retune_opt_out(tmp_path, monkeypatch):
    from datatunerx_tpu.training.metrics_log import MetricsLogger

    monkeypatch.setenv("DTX_PREFETCH_ADVISE_RECORDS", "5")
    monkeypatch.setenv("DTX_PREFETCH_RETUNE", "0")
    logger = MetricsLogger(str(tmp_path), total_steps=100, prefetch_depth=2)
    pf = _FakePrefetcher()
    logger.attach_prefetcher(pf)
    for step in range(5):
        logger.log_train(step, {"loss": 1.0, "pipe_step_wait_ms": 50.0})
    adv = logger.prefetch_advisory
    assert adv is not None and adv["retuned"] is False
    assert pf.resized_to is None  # advise-only: the flag stays a suggestion
    assert logger.effective_prefetch_depth() == 2


def test_metrics_logger_no_retune_without_stall(tmp_path, monkeypatch):
    from datatunerx_tpu.training.metrics_log import MetricsLogger

    monkeypatch.setenv("DTX_PREFETCH_ADVISE_RECORDS", "5")
    logger = MetricsLogger(str(tmp_path), total_steps=100, prefetch_depth=2)
    pf = _FakePrefetcher()
    logger.attach_prefetcher(pf)
    for step in range(8):
        logger.log_train(step, {"loss": 1.0, "pipe_step_wait_ms": 0.1})
    assert logger.prefetch_advisory is None
    assert pf.resized_to is None
