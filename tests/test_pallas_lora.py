"""Fused LoRA Pallas kernel vs the XLA composite path."""

import numpy as np

import jax.numpy as jnp

from datatunerx_tpu.ops.pallas_lora import pallas_lora_matmul


def test_fused_lora_matches_composite():
    rng = np.random.default_rng(0)
    K, N, r = 128, 256, 8
    x = jnp.asarray(rng.normal(size=(4, 40, K)), jnp.float32)  # M=160: padding
    w = jnp.asarray(rng.normal(size=(K, N), scale=0.05), jnp.float32)
    a = jnp.asarray(rng.normal(size=(K, r), scale=0.05), jnp.float32)
    b = jnp.asarray(rng.normal(size=(r, N), scale=0.05), jnp.float32)
    scale = 2.0

    ref = x @ w + ((x @ a) @ b) * scale
    out = pallas_lora_matmul(x, w, a, b, scale, block_m=64, block_n=128)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-4, rtol=2e-4)


def test_fused_lora_zero_adapter_is_base_matmul():
    rng = np.random.default_rng(1)
    K, N, r = 64, 128, 4
    x = jnp.asarray(rng.normal(size=(8, K)), jnp.float32)
    w = jnp.asarray(rng.normal(size=(K, N)), jnp.float32)
    a = jnp.asarray(rng.normal(size=(K, r)), jnp.float32)
    b = jnp.zeros((r, N), jnp.float32)
    out = pallas_lora_matmul(x, w, a, b, 4.0, block_m=8, block_n=128)
    np.testing.assert_allclose(np.asarray(out), np.asarray(x @ w), atol=1e-4)
