"""Speculative decoding (serving/speculative.py + BatchedEngine spec tick).

The correctness bar has two layers:

- the ACCEPTANCE MATH: greedy acceptance reproduces sequential argmax decode
  token-for-token by construction, and the sampled rejection/residual scheme
  emits tokens whose marginal distribution is EXACTLY the target's (the
  Leviathan/Chen guarantee) — verified analytically against empirical
  frequencies over many PRNG keys;
- the ENGINE: spec-on greedy output is token-identical to spec-off across
  dense + paged caches, concurrent ragged batches, stop tokens, pooled
  mixed-rank adapters, and the adaptive-k controller's shrink/disable paths —
  while ``--spec_mode off`` leaves the engine byte-identical to before.
"""

import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from datatunerx_tpu.ops.paged_attention import blocks_for_depth
from datatunerx_tpu.serving.batched_engine import BatchedEngine
from datatunerx_tpu.serving.speculative import (
    AdaptiveK,
    accept_tokens,
    build_draft,
    sampling_probs,
)

MODEL = "preset:debug"


# ------------------------------------------------------------ fixtures

@pytest.fixture(scope="module")
def dense_pair():
    """Spec-off / spec-on twins over a dense per-slot cache. The draft is
    take:2 — ALL of the 2-layer debug model, i.e. a perfect draft — so the
    all-accept path is exercised."""
    off = BatchedEngine(MODEL, template="vanilla", max_seq_len=256,
                        slots=3, decode_chunk=4)
    on = BatchedEngine(MODEL, template="vanilla", max_seq_len=256,
                       slots=3, decode_chunk=4,
                       spec_draft="take:2", spec_k=3, spec_mode="on")
    yield off, on
    off.close()
    on.close()


@pytest.fixture(scope="module")
def paged_pair():
    """Paged twins with a WEAK draft (take:1 of a random 2-layer model —
    near-zero acceptance), so rejection, residual correction and ragged
    per-row advance over block tables all run for real."""
    off = BatchedEngine(MODEL, template="vanilla", max_seq_len=256,
                        slots=3, decode_chunk=4, kv_block_size=16)
    on = BatchedEngine(MODEL, template="vanilla", max_seq_len=256,
                       slots=3, decode_chunk=4, kv_block_size=16,
                       spec_draft="take:1", spec_k=3, spec_mode="on")
    yield off, on
    off.close()
    on.close()


# ------------------------------------------------- acceptance-rule units

def test_sampling_probs_matches_sample_jit_semantics():
    logits = jnp.asarray([2.0, 1.0, 0.5, -1.0])
    # greedy: one-hot argmax
    p = sampling_probs(logits, 0.0, 1.0)
    np.testing.assert_array_equal(np.asarray(p), [1.0, 0.0, 0.0, 0.0])
    # top_p = 1: plain softmax of logits/t, fast path == exact path
    t = 0.7
    exact = np.asarray(sampling_probs(logits, t, 1.0))
    fast = np.asarray(sampling_probs(logits, t, 1.0, exact_topp=False))
    want = np.asarray(jax.nn.softmax(logits / t))
    np.testing.assert_allclose(exact, want, rtol=1e-5)
    np.testing.assert_allclose(fast, want, rtol=1e-5)
    # top_p < 1: the tail is cut and the kept mass renormalized. softmax
    # here is [.609, .224, .136, .030]: the nucleus rule keeps a token
    # while the mass BEFORE it is <= top_p, so 0.7 keeps exactly two.
    p = np.asarray(sampling_probs(logits, 1.0, 0.7))
    soft = np.asarray(jax.nn.softmax(logits))
    assert p[3] == 0.0 and p[2] == 0.0  # tail outside the 0.7 nucleus
    np.testing.assert_allclose(p[:2], soft[:2] / soft[:2].sum(), rtol=1e-5)
    assert abs(p.sum() - 1.0) < 1e-5


def test_accept_greedy_is_argmax_comparison():
    V, k = 6, 3
    p = np.zeros((k + 1, V), np.float32)
    p[0, 2] = p[1, 4] = p[2, 1] = p[3, 5] = 1.0  # target argmax: 2,4,1,5
    q = np.zeros((k, V), np.float32)
    q[:, 0] = 1.0
    rng = jax.random.PRNGKey(0)
    # drafts agree at 0 and 1, diverge at 2 → accept 2, correct to argmax
    a, extra, _ = accept_tokens(jnp.asarray(p), jnp.asarray(q),
                                jnp.asarray([2, 4, 0]), 0.0, rng, True)
    assert int(a) == 2 and int(extra) == 1
    # full agreement → accept all, bonus = argmax of the k-th dist
    a, extra, _ = accept_tokens(jnp.asarray(p), jnp.asarray(q),
                                jnp.asarray([2, 4, 1]), 0.0, rng, True)
    assert int(a) == 3 and int(extra) == 5
    # immediate divergence → accept none, correct to argmax of p_0
    a, extra, _ = accept_tokens(jnp.asarray(p), jnp.asarray(q),
                                jnp.asarray([0, 0, 0]), 0.0, rng, True)
    assert int(a) == 0 and int(extra) == 2
    # spec_on=False: forced plain step regardless of agreement
    a, extra, _ = accept_tokens(jnp.asarray(p), jnp.asarray(q),
                                jnp.asarray([2, 4, 1]), 0.0, rng, False)
    assert int(a) == 0 and int(extra) == 2


def test_accept_all_accept_and_all_reject_edges():
    V, k = 4, 2
    rng = jax.random.PRNGKey(1)
    # q == p → ratio 1 → every proposal accepted (sampled mode)
    p = np.asarray([[0.4, 0.3, 0.2, 0.1]] * (k + 1), np.float32)
    q = p[:k]
    for seed in range(8):
        a, _, _ = accept_tokens(
            jnp.asarray(p), jnp.asarray(q), jnp.asarray([0, 1]),
            1.0, jax.random.PRNGKey(seed), True)
        assert int(a) == k
    # draft proposes a token with ZERO target mass → always rejected,
    # and the residual (= p with q's mass removed) never re-emits it
    p0 = np.asarray([[0.0, 0.5, 0.5, 0.0]] * (k + 1), np.float32)
    q0 = np.zeros((k, V), np.float32)
    q0[:, 0] = 1.0
    for seed in range(16):
        a, extra, _ = accept_tokens(
            jnp.asarray(p0), jnp.asarray(q0), jnp.asarray([0, 0]),
            1.0, jax.random.PRNGKey(seed), True)
        assert int(a) == 0 and int(extra) in (1, 2)
    del rng


def test_residual_scheme_is_distribution_exact():
    """The Leviathan guarantee, checked empirically: with draft dist q and
    target dist p over a tiny vocab, the emitted FIRST token's frequency
    over many keys matches p — even though q is badly mismatched."""
    V, k = 4, 1
    p = np.asarray([0.5, 0.25, 0.15, 0.1], np.float32)
    q = np.asarray([0.05, 0.05, 0.45, 0.45], np.float32)
    p_full = jnp.asarray(np.stack([p] * (k + 1)))
    q_full = jnp.asarray(q[None, :])
    n = 4000
    keys = jax.random.split(jax.random.PRNGKey(42), n)
    # the draft samples d_0 ~ q with its own keys; acceptance consumes the
    # slot key — exactly the program's split discipline
    dkeys = jax.random.split(jax.random.PRNGKey(7), n)
    d0 = jax.vmap(
        lambda kk: jax.random.categorical(kk, jnp.log(q_full[0])))(dkeys)

    def one(key, d):
        a, extra, _ = accept_tokens(p_full, q_full, d[None], 1.0, key, True)
        return jnp.where(a > 0, d, extra)

    toks = np.asarray(jax.jit(jax.vmap(one))(keys, d0.astype(jnp.int32)))
    freq = np.bincount(toks, minlength=V) / n
    # 4000 samples: generous 4-sigma-ish tolerance, deterministic seeds
    np.testing.assert_allclose(freq, p, atol=0.04)


def test_blocks_for_depth_reserve_math():
    assert blocks_for_depth(32, 16) == 2
    assert blocks_for_depth(33, 16) == 3
    # spec overshoot rides on top…
    assert blocks_for_depth(32, 16, overshoot=5) == 3
    # …but never past the table width (cap = max_seq_len)
    assert blocks_for_depth(250, 16, overshoot=16, cap_depth=256) == 16
    assert blocks_for_depth(256, 16, overshoot=5, cap_depth=256) == 16


# ------------------------------------------------------ controller units

def test_adaptive_k_shrinks_and_disables():
    ctrl = AdaptiveK(k_max=4, mode="auto", floor=0.35, min_obs=2,
                     probe_every=3)
    assert ctrl.current_k() == 4 and ctrl.use_spec()
    # collapse acceptance on slot 0 → slot disabled, k shrinks, auto mode
    # stands down globally
    for _ in range(6):
        ctrl.observe([(0, 0, 4)])
    assert not ctrl.slot_enabled(0)
    assert ctrl.current_k() == 1
    assert not ctrl.use_spec()
    assert ctrl.disabled_events >= 1
    # plain fallback probes periodically so spec can win back
    for _ in range(3):
        ctrl.note_plain_step()
    assert ctrl.use_spec()  # the probe step
    # healthy acceptance restores full k; a released slot starts clean
    ctrl.reset_slot(0)
    assert ctrl.slot_enabled(0)
    for _ in range(30):
        ctrl.observe([(1, 4, 4)])
    assert ctrl.current_k() == 4 and ctrl.use_spec()
    # mode=on never stands down globally (per-slot gating still applies)
    pinned = AdaptiveK(k_max=2, mode="on", floor=0.5, min_obs=1)
    pinned.observe([(0, 0, 2)] * 8)
    assert pinned.use_spec()


def test_build_draft_take_and_validation():
    cfg, params, _ = __import__(
        "datatunerx_tpu.utils.model_loader",
        fromlist=["load_model_and_tokenizer"],
    ).load_model_and_tokenizer(MODEL)
    dcfg, dparams = build_draft("take:1", cfg, params)
    assert dcfg.num_layers == 1
    # early layers + embedding/unembedding are the target's own arrays
    assert dparams["embed_tokens"]["embedding"] is \
        params["embed_tokens"]["embedding"]
    np.testing.assert_array_equal(
        np.asarray(dparams["layers"]["q_proj"]["kernel"][0]),
        np.asarray(params["layers"]["q_proj"]["kernel"][0]))
    with pytest.raises(ValueError, match="out of range"):
        build_draft("take:9", cfg, params)
    # vocab mismatch is refused (acceptance compares one vocabulary)
    with pytest.raises(ValueError, match="vocab"):
        build_draft("preset:tinyllama-1.1b", cfg, params)


# -------------------------------------------------- engine-level parity

def test_spec_greedy_token_exact_dense_all_accept(dense_pair):
    off, on = dense_pair
    tok = off.tokenizer
    for text in ("the quick brown fox", "a completely different prompt"):
        ids = tok.encode(text)
        want = off.generate(ids, max_new_tokens=16)
        got = on.generate(ids, max_new_tokens=16)
        assert got == want, (text, got, want)
    info = on.spec_info()
    assert info["enabled"] and info["proposed"] > 0
    # a perfect (full self) draft must accept everything
    assert info["accept_rate"] == 1.0


def test_spec_greedy_token_exact_paged_rejections(paged_pair):
    off, on = paged_pair
    tok = off.tokenizer
    for text in ("hello world this is serving", "short"):
        ids = tok.encode(text)
        want = off.generate(ids, max_new_tokens=16)
        got = on.generate(ids, max_new_tokens=16)
        assert got == want, (text, got, want)
    info = on.spec_info()
    # the weak draft must have been REJECTED sometimes — the correction
    # path ran, and output still matched exactly
    assert info["accepted"] < info["proposed"]


def test_spec_concurrent_ragged_advance_paged(paged_pair):
    """Concurrent requests of different lengths advance raggedly inside one
    verify program (per-row accepted lengths differ); every stream must
    match its spec-off twin and every block must return to the free list."""
    off, on = paged_pair
    tok = off.tokenizer
    free0 = on.free_kv_blocks
    prompts = [tok.encode("first request about weather"),
               tok.encode("second one"),
               tok.encode("third request that is somewhat longer than both")]
    want = [off.submit(p, max_new_tokens=8 + 4 * i)
            for i, p in enumerate(prompts)]
    got = [on.submit(p, max_new_tokens=8 + 4 * i)
           for i, p in enumerate(prompts)]
    for w, g in zip(want, got):
        assert w.done.wait(120) and g.done.wait(120)
        assert g.tokens == w.tokens, (g.tokens, w.tokens)
    deadline = time.monotonic() + 10
    while on.free_kv_blocks != free0 and time.monotonic() < deadline:
        time.sleep(0.01)
    assert on.free_kv_blocks == free0  # ragged release leaked nothing


def test_spec_stop_token_truncates_identically(paged_pair):
    off, on = paged_pair
    tok = off.tokenizer
    ids = tok.encode("the quick brown fox")
    base = off.generate(ids, max_new_tokens=12)
    stop = {base[4]}  # a token greedy decode WILL emit mid-stream
    want = off.generate(ids, max_new_tokens=12, stop_ids=stop)
    got = on.generate(ids, max_new_tokens=12, stop_ids=stop)
    assert want == base[:4]  # sanity: the stop actually truncated
    assert got == want


def test_spec_sampled_runs_and_respects_budget(paged_pair):
    """Sampled spec decode is distribution-exact (proved at the math layer);
    at the engine layer it must run the topp/simple program variants,
    respect max_new_tokens, and differ per seed like any sampler."""
    _, on = paged_pair
    tok = on.tokenizer
    ids = tok.encode("sampling prompt")
    outs = {tuple(on.generate(ids, max_new_tokens=10, temperature=0.9,
                              top_p=0.8, seed=s)) for s in range(3)}
    assert all(len(o) <= 10 for o in outs)
    assert len(outs) > 1  # different seeds explore
    simple = on.generate(ids, max_new_tokens=10, temperature=0.9, seed=0)
    assert len(simple) <= 10


def test_spec_mixed_rank_pooled_adapters_in_verify_batch(tmp_path):
    """Pooled LoRA adapters stay program ARGUMENTS through the verify
    forward: mixed-rank adapters decoding concurrently under spec match
    their spec-off twin token-for-token."""
    from datatunerx_tpu.serving.adapters import make_adapter_sweep

    ckpts = make_adapter_sweep(str(tmp_path), MODEL, 2)  # ranks differ
    kw = dict(template="vanilla", max_seq_len=256, slots=3, decode_chunk=4,
              kv_block_size=16, adapter_pool=2, adapter_rank_max=16)
    off = BatchedEngine(MODEL, adapters=ckpts, **kw)
    on = BatchedEngine(MODEL, adapters=ckpts, spec_draft="take:2",
                       spec_k=3, spec_mode="on", **kw)
    try:
        tok = off.tokenizer
        names = ["", *sorted(ckpts)]
        prompts = [tok.encode(f"adapter request {i}") for i in range(3)]
        want = [off.submit(p, max_new_tokens=10, adapter=a)
                for p, a in zip(prompts, names)]
        got = [on.submit(p, max_new_tokens=10, adapter=a)
               for p, a in zip(prompts, names)]
        for w, g in zip(want, got):
            assert w.done.wait(180) and g.done.wait(180)
            assert g.tokens == w.tokens, (g.tokens, w.tokens)
        info = on.spec_info()
        assert set(info["adapter_accept_rate"]) >= set(names)
    finally:
        off.close()
        on.close()


def test_spec_mode_off_is_byte_identical(paged_pair):
    """--spec_mode off must leave the engine exactly as before: no spec
    structures, no draft load, the pre-spec decode program path."""
    off, _ = paged_pair
    eng = BatchedEngine(MODEL, template="vanilla", max_seq_len=256,
                        slots=3, decode_chunk=4, kv_block_size=16,
                        spec_draft="take:1", spec_mode="off")
    try:
        assert eng.spec is None and eng._spec_overshoot == 0
        ids = eng.tokenizer.encode("off mode prompt")
        assert eng.generate(ids, max_new_tokens=8) == \
            off.generate(ids, max_new_tokens=8)
    finally:
        eng.close()
    with pytest.raises(ValueError, match="spec_draft_config"):
        BatchedEngine(MODEL, template="vanilla", max_seq_len=256, slots=2,
                      spec_mode="on")


def test_spec_adaptive_auto_falls_back_and_stays_exact():
    """spec_mode=auto with a hopeless draft: the controller must stand down
    to the plain pending-form program (never-slower contract) and output
    must STILL be token-exact — the fallback is the same decode math."""
    off = BatchedEngine(MODEL, template="vanilla", max_seq_len=256,
                        slots=2, decode_chunk=4, kv_block_size=16)
    on = BatchedEngine(MODEL, template="vanilla", max_seq_len=256,
                       slots=2, decode_chunk=4, kv_block_size=16,
                       spec_draft="take:1", spec_k=4, spec_mode="auto")
    try:
        ids = off.tokenizer.encode("adversarial workload prompt")
        want = off.generate(ids, max_new_tokens=48)
        got = on.generate(ids, max_new_tokens=48)
        assert got == want
        info = on.spec_info()
        assert info["plain_steps"] > 0, info  # the fallback actually ran
        assert info["k"] <= 2  # collapsed acceptance shrank k
    finally:
        off.close()
        on.close()


def test_spec_metrics_and_replica_stats(paged_pair):
    _, on = paged_pair
    from datatunerx_tpu.gateway.replica_pool import InProcessReplica

    st = InProcessReplica("r0", on).stats()
    assert st["spec_enabled"] is True
    assert st["spec_accept_rate"] is not None
    info = on.spec_info()
    for key in ("proposed", "accepted", "spec_steps", "plain_steps", "k",
                "mode", "draft"):
        assert key in info


def test_router_prefers_spec_replicas():
    """Greedy (spec-friendly) traffic narrows to spec-enabled replicas with
    healthy acceptance; sampled traffic and spec-less fleets are untouched."""
    from datatunerx_tpu.gateway.replica_pool import Replica, ReplicaPool
    from datatunerx_tpu.gateway.router import Router

    class FakeReplica(Replica):
        def __init__(self, name, spec_enabled, rate):
            super().__init__(name)
            self._st = {"slots_busy": 0, "slots_total": 4,
                        "kv_blocks_free": 64, "kv_blocks_total": 64,
                        "adapters": None, "resident_adapters": None,
                        "spec_enabled": spec_enabled,
                        "spec_accept_rate": rate}

        def probe_health(self):
            return True

        def stats(self):
            return self._st

    specful = FakeReplica("spec", True, 0.9)
    specless = FakeReplica("plain", False, None)
    collapsed = FakeReplica("collapsed", True, 0.05)
    pool = ReplicaPool([specful, specless, collapsed])
    for r in (specful, specless, collapsed):
        r.healthy = True
    router = Router(pool, policy="round_robin")
    picks = {router.route(prefer_spec=True).name for _ in range(6)}
    assert picks == {"spec"}  # healthy-acceptance spec replica wins
    picks = {router.route(prefer_spec=False).name for _ in range(6)}
    assert picks == {"spec", "plain", "collapsed"}  # non-spec-friendly: all
    assert router.spec_routes["preferred"] > 0


# ------------------------------------------------------- tree-draft units

import jax.numpy as jnp  # noqa: E402

from datatunerx_tpu.serving.speculative import (  # noqa: E402
    TreeSpec,
    accept_tree_tokens,
    parse_spec_tree,
    tree_draft_mask,
    tree_verify_mask,
)


def test_parse_spec_tree_and_validation():
    t = parse_spec_tree("4x3")
    assert (t.width, t.depth) == (4, 3)
    assert t.step_tokens == 13  # pending + 4*3 nodes
    assert str(t) == "4x3"
    assert parse_spec_tree("1X1") == TreeSpec(1, 1)
    for bad in ("", "4", "4x", "x3", "4x3x2", "axb"):
        with pytest.raises(ValueError, match="WxD"):
            parse_spec_tree(bad)
    for oob in ("0x3", "65x2", "4x0", "4x17"):
        with pytest.raises(ValueError, match="out of range"):
            parse_spec_tree(oob)


def test_tree_verify_mask_ancestry():
    # W=2, D=2 — columns: 0 pending, 1=(d1,b0), 2=(d1,b1), 3=(d2,b0),
    # 4=(d2,b1). Each node sees the root + ITS OWN chain, never a sibling.
    want = np.array([[1, 0, 0, 0, 0],
                     [1, 1, 0, 0, 0],
                     [1, 0, 1, 0, 0],
                     [1, 1, 0, 1, 0],
                     [1, 0, 1, 0, 1]], bool)
    np.testing.assert_array_equal(tree_verify_mask(2, 2), want)
    # degenerate 1-wide tree IS the chain: lower-triangular
    np.testing.assert_array_equal(tree_verify_mask(1, 3),
                                  np.tril(np.ones((4, 4), bool)))


def test_tree_draft_mask_own_path_only():
    np.testing.assert_array_equal(
        tree_draft_mask(2, 1), np.array([[1, 1, 0], [1, 0, 1]], bool))
    np.testing.assert_array_equal(
        tree_draft_mask(2, 2),
        np.array([[1, 1, 0, 1, 0], [1, 0, 1, 0, 1]], bool))


def test_accept_tree_greedy_longest_surviving_path():
    """Greedy tree acceptance = sequential argmax decode by construction:
    a node survives iff its token matches the target argmax at its parent
    column; the deepest surviving branch wins; the extra token is the
    argmax at the divergence point."""
    V, W, D = 8, 2, 2
    # target argmaxes: col0→2, col1→4, col2→5, col3→1, col4→7
    p = np.zeros((1 + W * D, V), np.float32)
    for c, tok in enumerate((2, 4, 5, 1, 7)):
        p[c, tok] = 1.0
    q = jnp.zeros((D, W, V), jnp.float32)
    rng = jax.random.PRNGKey(0)

    def run(d_toks, spec_on=True):
        a, b, extra, _ = accept_tree_tokens(
            jnp.asarray(p), q, jnp.asarray(d_toks, jnp.int32), 0.0, rng,
            spec_on, width=W, depth=D)
        return int(a), int(b), int(extra)

    # branch 0 survives both depths → full path + bonus at its leaf
    assert run([[2, 3], [4, 0]]) == (2, 0, 1)
    # branch 1 is the survivor (branch 0 dies at depth 1)
    assert run([[3, 2], [0, 5]]) == (2, 1, 7)
    # branch 0 survives depth 1 only; extra = argmax at its depth-1 col
    assert run([[2, 3], [0, 0]]) == (1, 0, 4)
    # both branches die at depth 1 → plain step: argmax of the root dist
    a, _, extra = run([[0, 1], [0, 0]])
    assert (a, extra) == (0, 2)
    # spec_on=False forces the plain step regardless of agreement
    a, _, extra = run([[2, 3], [4, 0]], spec_on=False)
    assert (a, extra) == (0, 2)


def test_accept_tree_width1_matches_chain_rule():
    """A 1-wide tree is a chain: greedy acceptance must agree with
    accept_tokens on the same distributions (both count the agreeing
    prefix and correct at the divergence)."""
    V, D = 6, 3
    rng = jax.random.PRNGKey(2)
    p = np.zeros((D + 1, V), np.float32)
    for i, tok in enumerate((2, 4, 1, 5)):
        p[i, tok] = 1.0
    q = np.zeros((D, V), np.float32)
    q[:, 0] = 1.0
    for d in ([2, 4, 0], [2, 4, 1], [0, 0, 0]):
        a_c, extra_c, _ = accept_tokens(
            jnp.asarray(p), jnp.asarray(q), jnp.asarray(d), 0.0, rng, True)
        a_t, _, extra_t, _ = accept_tree_tokens(
            jnp.asarray(p), jnp.asarray(q)[:, None],
            jnp.asarray(d, jnp.int32)[:, None], 0.0, rng, True,
            width=1, depth=D)
        assert int(a_t) == int(a_c), d
        assert int(extra_t) == int(extra_c), d


def test_tree_sibling_rejection_is_distribution_exact():
    """The SpecInfer guarantee, checked empirically: W iid siblings from a
    badly-mismatched draft q, recursive-rejection acceptance against the
    running residual — the emitted FIRST token's marginal over many keys
    is EXACTLY the target p."""
    V, W = 4, 2
    p = np.asarray([0.5, 0.25, 0.15, 0.1], np.float32)
    q0 = np.asarray([0.05, 0.05, 0.45, 0.45], np.float32)
    # D=1: bonus rows never touch the FIRST emitted token
    p_cols = jnp.asarray(np.stack([p] * (1 + W)))
    q_tree = jnp.asarray(np.broadcast_to(q0, (1, W, V)).copy())
    n = 4000
    keys = jax.random.split(jax.random.PRNGKey(42), n)
    dkeys = jax.random.split(jax.random.PRNGKey(7), n)

    def draw(kk):
        k1, k2 = jax.random.split(kk)
        return jnp.stack([jax.random.categorical(k1, jnp.log(q0)),
                          jax.random.categorical(k2, jnp.log(q0))])

    d0 = jax.vmap(draw)(dkeys).astype(jnp.int32)[:, None, :]  # [n, 1, W]

    def one(key, d):
        a, b, extra, _ = accept_tree_tokens(
            p_cols, q_tree, d, 1.0, key, True, width=W, depth=1)
        return jnp.where(a > 0, d[0, b], extra)

    toks = np.asarray(jax.jit(jax.vmap(one))(keys, d0))
    freq = np.bincount(toks, minlength=V) / n
    np.testing.assert_allclose(freq, p, atol=0.04)


def test_accept_tree_all_accept_edge():
    """q == p → the FIRST sibling's ratio test always passes (u * q <= r
    with r = p = q), so some branch is always accepted."""
    V, W, D = 4, 3, 2
    p = np.asarray([[0.4, 0.3, 0.2, 0.1]] * (1 + W * D), np.float32)
    q = np.broadcast_to(np.asarray([0.4, 0.3, 0.2, 0.1], np.float32),
                        (D, W, V)).copy()
    for seed in range(8):
        a, _, _, _ = accept_tree_tokens(
            jnp.asarray(p), jnp.asarray(q),
            jnp.zeros((D, W), jnp.int32), 1.0,
            jax.random.PRNGKey(seed), True, width=W, depth=D)
        assert int(a) >= 1


# ------------------------------------------------ tree engine-level parity

@pytest.fixture(scope="module")
def tree_pair(paged_pair):
    """The paged_pair's off twin plus a WEAK-draft 2x2 tree engine
    (mode=on so the controller cannot stand down): rejections, branch
    selection, window compaction and ragged per-row advance all run for
    real against the identically-configured non-spec oracle."""
    off, _ = paged_pair
    on = BatchedEngine(MODEL, template="vanilla", max_seq_len=256,
                       slots=3, decode_chunk=4, kv_block_size=16,
                       spec_draft="take:1", spec_k=3, spec_mode="on",
                       spec_tree="2x2")
    yield off, on
    on.close()


def test_tree_greedy_token_exact_concurrent_and_no_leak(tree_pair):
    """Greedy tree decode is token-exact vs the non-spec oracle — single
    and concurrent ragged streams — and every block the tree's
    (1 + W*D)-token window reservation took comes back: the
    blocks_for_depth overshoot used the per-step token count, not the
    chain's k+1."""
    off, on = tree_pair
    tok = off.tokenizer
    free0 = on.free_kv_blocks
    ids = tok.encode("hello world this is serving")
    want = off.generate(ids, max_new_tokens=16)
    got = on.generate(ids, max_new_tokens=16)
    assert got == want, (got, want)
    info = on.spec_info()
    assert info["tree_steps"] > 0
    assert info["tree"]["spec"] == "2x2"
    # the weak draft was REJECTED sometimes — branch selection, rollback
    # and window compaction all ran, and output still matched exactly
    assert info["accepted"] < info["proposed"]

    prompts = [tok.encode("first request about weather"),
               tok.encode("second one"),
               tok.encode("third request that is somewhat longer than both")]
    want = [off.submit(p, max_new_tokens=8 + 4 * i)
            for i, p in enumerate(prompts)]
    got = [on.submit(p, max_new_tokens=8 + 4 * i)
           for i, p in enumerate(prompts)]
    for w, g in zip(want, got):
        assert w.done.wait(180) and g.done.wait(180)
        assert g.tokens == w.tokens, (g.tokens, w.tokens)
    deadline = time.monotonic() + 10
    while on.free_kv_blocks != free0 and time.monotonic() < deadline:
        time.sleep(0.01)
    assert on.free_kv_blocks == free0


@pytest.mark.slow
def test_tree_sampled_runs_and_respects_budget(tree_pair):
    _, on = tree_pair
    tok = on.tokenizer
    ids = tok.encode("sampling prompt")
    outs = {tuple(on.generate(ids, max_new_tokens=10, temperature=0.9,
                              top_p=0.8, seed=s)) for s in range(2)}
    assert all(len(o) <= 10 for o in outs)
    assert len(outs) > 1


def test_tree_overshoot_is_step_tokens(tree_pair):
    """The satellite fix: reservation math takes the PER-STEP token count.
    A 2x2 tree writes 1 + 2*2 = 5 tokens per verify step — more than the
    chain's spec_k + 1 = 4 — so sizing overshoot by the chain formula
    would overflow the reserved tail and corrupt a neighbor's block."""
    _, on = tree_pair
    assert on.spec_tree.step_tokens == 5
    assert on._spec_overshoot == 5
    assert on._tick_advance == 5  # max(decode_chunk=4, step_tokens)


def test_tree_engine_validation_and_off_modes():
    # tree without a draft is refused
    with pytest.raises(ValueError, match="spec_draft_config"):
        BatchedEngine(MODEL, template="vanilla", max_seq_len=256, slots=2,
                      spec_tree="2x2")
    # malformed WxD is refused with the format named
    with pytest.raises(ValueError, match="WxD"):
        BatchedEngine(MODEL, template="vanilla", max_seq_len=256, slots=2,
                      spec_draft="take:1", spec_tree="nope")
    # a tree that cannot fit the sequence budget is refused
    with pytest.raises(ValueError, match="max_seq_len"):
        BatchedEngine(MODEL, template="vanilla", max_seq_len=16, slots=2,
                      kv_block_size=16, spec_draft="take:1",
                      spec_tree="64x16")
    # spec_mode=off ignores the tree entirely — byte-identical off path
    eng = BatchedEngine(MODEL, template="vanilla", max_seq_len=256,
                        slots=2, decode_chunk=4, kv_block_size=16,
                        spec_draft="take:1", spec_mode="off",
                        spec_tree="2x2")
    try:
        assert eng.spec is None and eng._spec_overshoot == 0
    finally:
        eng.close()


def test_chain_engine_has_no_tree_surface(paged_pair):
    """--spec_tree unset: spec_info carries no tree document and the
    overshoot stays the chain's spec_k + 1 — the PR 14 engine unchanged."""
    _, on = paged_pair
    info = on.spec_info()
    assert "tree" not in info
    assert on.spec_tree is None
    assert on._spec_overshoot == 4  # spec_k=3 → k+1


# ----------------------------------------- learned ragged tree shapes (units)

def test_ragged_widths_validation_and_masks():
    from datatunerx_tpu.serving.speculative import _widths_tuple

    assert _widths_tuple(2, 2) == (2, 2)
    assert _widths_tuple((3, 2, 1)) == (3, 2, 1)
    with pytest.raises(ValueError, match="non-increasing"):
        _widths_tuple((1, 2))
    with pytest.raises(ValueError, match=">= 1"):
        _widths_tuple((2, 0))
    # ragged ancestry, widths (2, 1): cols 0 root, 1=(d1,b0), 2=(d1,b1),
    # 3=(d2,b0) — branch 1 simply has no depth-2 column
    want = np.array([[1, 0, 0, 0],
                     [1, 1, 0, 0],
                     [1, 0, 1, 0],
                     [1, 1, 0, 1]], bool)
    np.testing.assert_array_equal(tree_verify_mask((2, 1)), want)
    # a widths tuple that IS the rectangle matches the (W, D) form
    np.testing.assert_array_equal(tree_verify_mask((2, 2)),
                                  tree_verify_mask(2, 2))
    # ragged draft mask at depth 2 of (2, 1): one live branch over the
    # 1 + 2 + 1 window — root, own depth-1 ancestor, own write lane
    np.testing.assert_array_equal(
        tree_draft_mask((2, 1), 2), np.array([[1, 1, 0, 1]], bool))


def test_accept_tree_ragged_widths_greedy():
    """Learned (2, 1) shape: branch 1 exists at depth 1 only. Its chain
    stops at its live depth, and dead lanes (d_toks -1, q 0) never win a
    test — acceptance over the ragged flattened window stays exactly the
    sequential-greedy rule."""
    V = 8
    # cols: 0 root→2, 1=(d1,b0)→4, 2=(d1,b1)→5, 3=(d2,b0)→1
    p = np.zeros((4, V), np.float32)
    for c, tok in enumerate((2, 4, 5, 1)):
        p[c, tok] = 1.0
    q = jnp.zeros((2, 2, V), jnp.float32)
    rng = jax.random.PRNGKey(0)

    def run(d_toks):
        a, b, extra, _ = accept_tree_tokens(
            jnp.asarray(p), q, jnp.asarray(d_toks, jnp.int32), 0.0, rng,
            True, widths=(2, 1))
        return int(a), int(b), int(extra)

    # branch 0 survives both depths → full path + bonus at its leaf
    assert run([[2, 3], [4, -1]]) == (2, 0, 1)
    # branch 1 survives depth 1; its chain ENDS there (no depth-2 lane)
    assert run([[3, 2], [0, -1]]) == (1, 1, 5)
    # everything dies at depth 1 → plain step from the root distribution
    a, _, extra = run([[0, 1], [0, -1]])
    assert (a, extra) == (0, 2)


def test_adaptive_tree_buckets_and_monotone_cap():
    from datatunerx_tpu.serving.speculative import AdaptiveTree

    ctrl = AdaptiveTree(3, mode="on", tree=parse_spec_tree("4x3"))
    # no evidence yet: the full rectangle
    assert ctrl.current_plan() == ("tree", (4, 4, 4))
    # first observation seeds the EMAs directly; survival 1.0 / 0.4 / 0.1
    # buckets to W / ceil(W/2) / 1 at the 0.6 / 0.3 thresholds
    ctrl.observe_tree([1.0, 0.4, 0.1], 0.0)
    assert ctrl.current_plan() == ("tree", (4, 2, 1))
    # monotone cap: a depth whose own bucket exceeds the depth above it is
    # clamped (prefix-live branch chains), whatever its own EMA says
    ctrl2 = AdaptiveTree(3, mode="on", tree=parse_spec_tree("4x3"))
    ctrl2.observe_tree([0.4, 0.1, 1.0], 0.0)
    assert ctrl2.current_plan() == ("tree", (2, 1, 1))


def test_adaptive_tree_decisive_margin_caps_root():
    from datatunerx_tpu.serving.speculative import AdaptiveTree

    ctrl = AdaptiveTree(3, mode="on", tree=parse_spec_tree("4x2"))
    # the draft root's top-2 margin is (nearly) always decisive: sibling
    # roots are wasted draft FLOPs, so depth-1 width caps at 1 — and the
    # monotone chain drags every deeper width down with it
    ctrl.observe_tree([1.0, 1.0], 1.0)
    assert ctrl.current_plan() == ("tree", (1, 1))
    # sub-threshold decisiveness leaves the learned widths alone
    ctrl2 = AdaptiveTree(3, mode="on", tree=parse_spec_tree("4x2"))
    ctrl2.observe_tree([1.0, 1.0], 0.5)
    assert ctrl2.current_plan() == ("tree", (4, 4))


def test_adaptive_tree_global_floor_and_migration_state():
    from datatunerx_tpu.serving.speculative import AdaptiveTree

    def mk():
        return AdaptiveTree(3, mode="on", tree=parse_spec_tree("4x2"))

    ctrl = mk()
    ctrl.observe_tree([1.0, 0.4], 0.0)
    ctrl.observe([(0, 2, 4)])  # slot 0 acceptance history (rate 0.5)
    assert ctrl.current_plan() == ("tree", (4, 2))
    # collapsed GLOBAL acceptance overrides the per-depth evidence: the
    # width-1 chain-of-depth-D last resort, same as the fixed controller
    ctrl.global_ema = 0.1
    assert ctrl.current_plan() == ("tree", (1, 1))
    ctrl.global_ema = 0.5

    # the dtx-kv-session "spec" sub-document warms a cold importer: the
    # learned widths survive migration instead of restarting at (W,)*D
    state = ctrl.export_slot_state(0)
    cold = mk()
    cold.import_slot_state(5, state)
    assert cold.current_plan() == ("tree", (4, 2))
    assert cold._slot_ema[5][0] == pytest.approx(0.5)
    assert cold.global_ema == pytest.approx(0.5)
    # a live controller's own evidence is NOT overwritten by an import
    warm = mk()
    warm.observe_tree([0.1, 0.1], 0.0)
    warm.import_slot_state(5, state)
    assert warm.current_plan() == ("tree", (1, 1))


# ------------------------------------------- fused sampling epilogue (engine)

@pytest.fixture(scope="module")
def epilogue_pair():
    """Identical spec engines differing ONLY in --sampling_epilogue: off is
    the legacy per-row vmap sampler, on routes the draw through the fused
    epilogue (resolved to the blocked-XLA oracle impl on CPU — the same
    tile walk the Pallas kernel reproduces bitwise, pinned by
    test_pallas_sampling)."""
    # take:2 (perfect draft) keeps the acceptance EMA — and so the
    # adaptive k — stable across generates: fixed-seed streams only
    # repeat when the k path repeats. Non-spec programs are already
    # memoized by paged_pair (same engine config, off == CPU auto).
    kw = dict(template="vanilla", max_seq_len=256, slots=3, decode_chunk=4,
              kv_block_size=16, spec_draft="take:2", spec_k=3,
              spec_mode="on")
    off = BatchedEngine(MODEL, sampling_epilogue="off", **kw)
    on = BatchedEngine(MODEL, sampling_epilogue="on", **kw)
    yield off, on
    off.close()
    on.close()


@pytest.mark.slow
def test_epilogue_greedy_token_exact_and_counted(epilogue_pair):
    # slow: first user of the epilogue_pair fixture — prices the fused
    # spec program family. CI's spec smoke step runs this file unfiltered.
    off, on = epilogue_pair
    assert on.sampling_epilogue == "on"
    assert on._epilogue_impl in ("xla", "kernel")
    assert off._epilogue_impl == "off"
    tok = off.tokenizer
    ids = tok.encode("fused epilogue request")
    want = off.generate(ids, max_new_tokens=16)
    got = on.generate(ids, max_new_tokens=16)
    assert got == want, (got, want)
    assert on.sampling_stats["fused_steps"] > 0
    assert off.sampling_stats["fused_steps"] == 0
    assert off.sampling_stats["legacy_steps"] > 0
    info = on.spec_info()
    assert info["sampling_epilogue"] == "on"
    assert info["fused_steps"] > 0


@pytest.mark.slow
def test_epilogue_sampled_fixed_seed_deterministic(epilogue_pair):
    """The fused draw is distribution-exact (test_pallas_sampling pins the
    primitive against sampling_probs); at the engine layer a fixed seed
    must reproduce the stream exactly and distinct seeds must explore.
    slow: compiles the whole sampled-mode spec program family — the CI
    spec smoke step runs this file unfiltered, like the tree sampled
    budget test above."""
    _, on = epilogue_pair
    tok = on.tokenizer
    ids = tok.encode("sampled epilogue prompt")
    a = on.generate(ids, max_new_tokens=10, temperature=0.9, seed=3)
    assert a == on.generate(ids, max_new_tokens=10, temperature=0.9, seed=3)
    assert len(a) <= 10
    b = on.generate(ids, max_new_tokens=10, temperature=0.9, seed=4)
    assert a != b  # distinct seeds explore
    # (topp-mode determinism rides the plain-engine test below — one
    # compiled program instead of the whole spec family)


@pytest.mark.slow
def test_epilogue_int8_kv_quant_token_exact():
    # slow: compiles the epilogue-on int8 program family — the CI spec
    # smoke step runs this file unfiltered.
    # dense int8 cache: the off twin's programs are already compiled by
    # test_batched_engine's int8 engine (same memo key), so this pair
    # prices only the epilogue-on int8 program family
    kw = dict(template="vanilla", max_seq_len=256, slots=2, decode_chunk=4,
              kv_quant="int8", spec_draft="take:2",
              spec_k=3, spec_mode="on")
    off = BatchedEngine(MODEL, sampling_epilogue="off", **kw)
    on = BatchedEngine(MODEL, sampling_epilogue="on", **kw)
    try:
        ids = off.tokenizer.encode("quantized cache with fused sampling")
        want = off.generate(ids, max_new_tokens=12)
        got = on.generate(ids, max_new_tokens=12)
        assert got == want, (got, want)
        assert on.sampling_stats["fused_steps"] > 0
    finally:
        off.close()
        on.close()


@pytest.mark.slow
def test_epilogue_mixed_rank_pooled_adapters_token_exact(tmp_path):
    # slow: two pooled-adapter engines — CI spec smoke runs this file
    # unfiltered
    from datatunerx_tpu.serving.adapters import make_adapter_sweep

    ckpts = make_adapter_sweep(str(tmp_path), MODEL, 2)  # ranks differ
    kw = dict(template="vanilla", max_seq_len=256, slots=3, decode_chunk=4,
              kv_block_size=16, adapter_pool=2, adapter_rank_max=16,
              spec_draft="take:2", spec_k=3, spec_mode="on")
    off = BatchedEngine(MODEL, adapters=ckpts, sampling_epilogue="off", **kw)
    on = BatchedEngine(MODEL, adapters=ckpts, sampling_epilogue="on", **kw)
    try:
        tok = off.tokenizer
        names = ["", *sorted(ckpts)]
        prompts = [tok.encode(f"adapter epilogue request {i}")
                   for i in range(3)]
        want = [off.submit(p, max_new_tokens=10, adapter=a)
                for p, a in zip(prompts, names)]
        got = [on.submit(p, max_new_tokens=10, adapter=a)
               for p, a in zip(prompts, names)]
        for w, g in zip(want, got):
            assert w.done.wait(180) and g.done.wait(180)
            assert g.tokens == w.tokens, (g.tokens, w.tokens)
    finally:
        off.close()
        on.close()


def test_epilogue_off_and_cpu_auto_share_programs():
    """--sampling_epilogue off is byte-identical to the pre-epilogue
    engine: on CPU `auto` resolves off, so the explicit-off engine and a
    default engine hit the SAME _PROGRAM_MEMO entry — one compiled program
    set, identical traces, identical output."""
    if jax.default_backend() == "tpu":
        pytest.skip("auto resolves to on under a TPU backend")
    kw = dict(template="vanilla", max_seq_len=256, slots=2, decode_chunk=4,
              kv_block_size=16)
    auto = BatchedEngine(MODEL, **kw)
    off = BatchedEngine(MODEL, sampling_epilogue="off", **kw)
    try:
        assert auto.sampling_epilogue == "off"
        assert auto._epilogue_impl == off._epilogue_impl == "off"
        assert off._decode is auto._decode  # same memoized _Programs
        assert off._prefill is auto._prefill
        ids = auto.tokenizer.encode("identical path")
        assert off.generate(ids, max_new_tokens=8) == \
            auto.generate(ids, max_new_tokens=8)
    finally:
        auto.close()
        off.close()


@pytest.mark.slow
def test_epilogue_plain_engine_fused_decode():
    """The fused draw also serves the plain (non-spec) decode program —
    the epilogue is not a spec-only surface.
    slow: prices the plain fused greedy + exact-topp programs — the CI
    spec smoke step runs this file unfiltered."""
    kw = dict(template="vanilla", max_seq_len=256, slots=2, decode_chunk=4,
              kv_block_size=16)
    off = BatchedEngine(MODEL, sampling_epilogue="off", **kw)
    on = BatchedEngine(MODEL, sampling_epilogue="on", **kw)
    try:
        ids = off.tokenizer.encode("plain decode fused epilogue")
        assert on.generate(ids, max_new_tokens=10) == \
            off.generate(ids, max_new_tokens=10)
        assert on.sampling_stats["fused_steps"] > 0
        assert on.spec_info() is None  # no spec surface grew
        # topp-mode epilogue: exact-nucleus path, fixed-seed deterministic
        t = on.generate(ids, max_new_tokens=8, temperature=0.9, top_p=0.7,
                        seed=0)
        assert t == on.generate(ids, max_new_tokens=8, temperature=0.9,
                                top_p=0.7, seed=0)
    finally:
        off.close()
        on.close()


@pytest.mark.slow
def test_tree_adaptation_and_epilogue_zero_recompiles():
    """SAN003: the learned controller's width replans and the epilogue's
    per-batch mode switches must land on ALREADY-COMPILED programs — the
    bucketed width set and the static mode set bound the program memo, so
    steady-state serving never lowers a fresh program mid-traffic.
    slow: pre-warms every width bucket's program set (the point of the
    test) — the CI spec smoke step runs this file unfiltered."""
    from datatunerx_tpu.analysis.sanitizers import compile_budget

    eng = BatchedEngine(MODEL, template="vanilla", max_seq_len=256,
                        slots=2, decode_chunk=4, kv_block_size=16,
                        spec_draft="take:1", spec_k=3, spec_mode="on",
                        spec_tree="2x2", sampling_epilogue="on")
    try:
        tok = eng.tokenizer
        ids = tok.encode("prewarm prompt")
        ctrl = eng.spec_ctrl
        # every plan the W=2 bucket set {2, 1} + monotone cap can produce
        plans = {(1.0, 1.0): (2, 2), (1.0, 0.4): (2, 1), (0.1, 0.1): (1, 1)}

        def pin(fr):
            # reset ALL learned signals (the weak take:1 draft's real
            # acceptance would otherwise drag the global EMA under the
            # 0.3 floor and pin every plan at the width-1 chain)
            with ctrl._lock:
                ctrl._depth_ema = [None] * len(ctrl._depth_ema)
                ctrl._decisive_ema = None
                ctrl.global_ema = None
            ctrl.observe_tree(list(fr), 0.0)

        # pre-warm every width bucket (greedy) plus ONE plan's sampled
        # variant outside the window: this is where the bounded program
        # set compiles
        for fr, widths in plans.items():
            pin(fr)
            assert ctrl.current_plan() == ("tree", widths)
            eng.generate(ids, max_new_tokens=6)
        pin((1.0, 1.0))
        eng.generate(ids, max_new_tokens=6, temperature=0.9, seed=1)
        # a 1-token sampled request never drafts (no headroom), so it runs
        # the PLAIN decode program in "simple" mode — compile that variant
        # here, outside the window, since the window replays the same shape
        pin((1.0, 1.0))
        eng.generate(ids, max_new_tokens=1, temperature=0.9, seed=1)
        with compile_budget(0, label="tree replan + epilogue mode switch"):
            for fr in reversed(list(plans)):
                pin(fr)
                eng.generate(ids, max_new_tokens=6)
            # epilogue mode switch (greedy ↔ simple) on a warmed plan.
            # One token = ONE spec tick, which reads the plan exactly
            # once at the pinned state — the weak draft's real acceptance
            # evidence cannot replan onto a sampled variant the pre-warm
            # did not compile.
            pin((1.0, 1.0))
            eng.generate(ids, max_new_tokens=1, temperature=0.9, seed=2)
        assert eng.sampling_stats["fused_steps"] > 0
    finally:
        eng.close()
