"""CLI contract + end-to-end tiny training runs through the real entrypoint.

SURVEY.md §4.4: the Go↔Python seam is the flag list the controller emits
(reference internal/controller/finetune/finetune_controller.go:457-514); encode
it once and test both sides. CONTROLLER_FLAGS below is that single encoding —
operator/generate tests import it too.
"""

import csv
import json
import os

import pytest

from datatunerx_tpu.tuning.parser import parse_train_args

# The exact flag sequence the reference controller emits (values are
# representative). finetune_controller.go:457-514 — including the --lora_r
# (not --lora_rank) spelling and Go strconv.Quote()d --columns.
CONTROLLER_FLAGS = [
    "--model_name_or_path", "{model}",
    "--train_path", "{train}",
    "--evaluation_path", "{eval}",
    "--columns", '"{\\"q\\": \\"instruction\\", \\"a\\": \\"response\\"}"',
    "--output_dir", "{out}",
    "--deepspeed", "/tuning/ds_config.json",
    "--lora_target", "q_proj,v_proj",
    "--lr_scheduler_type", "cosine",
    "--optim", "adamw",
    "--quantization", "int8",
    "--lora_r", "4",
    "--lora_alpha", "16",
    "--lora_dropout", "0.05",
    "--learning_rate", "0.01",
    "--num_train_epochs", "2",
    "--block_size", "64",
    "--per_device_train_batch_size", "2",
    "--warmup_ratio", "0.1",
    "--weight_decay", "0.01",
    "--gradient_accumulation_steps", "2",
    "--fp16", "false",
    "--num_workers", "1",
    "--storage_path", "{storage}",
    "--metrics_export_address", "",
    "--uid", "test-uid-123",
]


def _flags(tmp_path, **extra):
    model = "preset:debug"
    train = str(tmp_path / "train.csv")
    evalp = str(tmp_path / "eval.csv")
    out = str(tmp_path / "out")
    storage = str(tmp_path / "storage")
    rows = [("add %d+%d" % (k, k), "answer %d" % (2 * k)) for k in range(96)]
    for p, rws in ((train, rows), (evalp, rows[:8])):
        with open(p, "w", newline="") as f:
            w = csv.writer(f)
            w.writerow(["q", "a"])
            w.writerows(rws)
    subs = {"{model}": model, "{train}": train, "{eval}": evalp, "{out}": out,
            "{storage}": storage}
    argv = [subs.get(a, a) for a in CONTROLLER_FLAGS]
    for k, v in extra.items():
        argv += [f"--{k}", str(v)]
    return argv, out, storage


def test_controller_flag_surface_parses(tmp_path):
    argv, out, storage = _flags(tmp_path)
    args = parse_train_args(argv)
    assert args.lora_rank == 4  # via --lora_r alias
    assert args.columns_map == {"q": "instruction", "a": "response"}
    assert args.quantization == "int8"
    assert args.fp16 is False
    assert args.deepspeed == "/tuning/ds_config.json"  # accepted, ignored
    assert args.num_train_epochs == 2.0
    assert args.uid == "test-uid-123"


def test_missing_required_flags():
    with pytest.raises(ValueError, match="train_path"):
        parse_train_args(["--model_name_or_path", "m", "--storage_path", "s"])
    with pytest.raises(ValueError, match="storage_path"):
        parse_train_args(["--model_name_or_path", "m", "--train_path", "t"])


def test_e2e_train_eval_manifest(tmp_path):
    """Full pipeline on CPU: CSV -> LoRA SFT -> checkpoint + manifest + logs."""
    from datatunerx_tpu.tuning.train import main

    argv, out, storage = _flags(
        tmp_path, template="alpaca", max_steps="4", logging_steps="1",
        bf16="false", remat="none", attention="xla",
    )
    assert main(argv) == 0

    # jsonl logs (reference callback.py:144-155 contract)
    trainer_log = [
        json.loads(l)
        for l in open(os.path.join(out, "watch", "trainer_log.jsonl"))
    ]
    assert len(trainer_log) == 4
    assert {"loss", "lr", "epoch", "current_steps", "total_steps", "percentage"} <= set(trainer_log[0])
    eval_log = [json.loads(l) for l in open(os.path.join(out, "watch", "eval_log.jsonl"))]
    assert {"eval_loss", "perplexity"} <= set(eval_log[-1])

    # completion manifest at the deterministic key (replaces pod-exec scrape)
    mf = json.load(open(os.path.join(storage, "test-uid-123", "manifest.json")))
    assert mf["steps"] == 4
    assert os.path.isdir(mf["checkpoint"])
    assert "loss" in mf["metrics"]
    # legacy checkpoint_path file kept for reference-contract compatibility
    legacy = open(os.path.join(storage, "test-uid-123", "checkpoint_path")).read()
    assert legacy == mf["checkpoint"]


def test_e2e_resume(tmp_path):
    """Kill-and-resume: second run restores from the checkpoint and continues."""
    from datatunerx_tpu.tuning.train import run

    argv, out, storage = _flags(
        tmp_path, template="alpaca", max_steps="2", save_steps="2",
        bf16="false", remat="none",
    )
    args = parse_train_args(argv)
    r1 = run(args)
    assert r1["steps"] == 2

    argv2, _, _ = _flags(
        tmp_path, template="alpaca", max_steps="4", save_steps="2",
        bf16="false", remat="none",
    )
    args2 = parse_train_args(argv2)
    r2 = run(args2)
    assert r2["steps"] == 4
    mf = json.load(open(os.path.join(storage, "test-uid-123", "manifest.json")))
    assert mf["steps"] == 4


def test_e2e_full_finetune_and_export(tmp_path):
    from datatunerx_tpu.tuning.train import run
    from datatunerx_tpu.utils.model_loader import load_model_and_tokenizer

    export = str(tmp_path / "export")
    argv, out, storage = _flags(
        tmp_path, template="alpaca", max_steps="2", finetuning_type="full",
        bf16="false", remat="none", export_dir=export, quantization="",
    )
    args = parse_train_args(argv)
    r = run(args)
    assert r["steps"] == 2
    assert os.path.exists(os.path.join(export, "model.npz"))
    # exported model round-trips through the loader
    cfg, params, tok = load_model_and_tokenizer(export)
    assert cfg.num_layers == 2


def test_export_only_invocation(tmp_path):
    """--export_dir without --train_path exports and exits cleanly."""
    from datatunerx_tpu.tuning.parser import parse_train_args
    from datatunerx_tpu.tuning.train import run

    export = str(tmp_path / "exp")
    args = parse_train_args([
        "--model_name_or_path", "preset:debug", "--export_dir", export,
        "--storage_path", str(tmp_path / "s"), "--bf16", "false",
    ])
    r = run(args)
    assert r["steps"] == 0
    assert os.path.exists(os.path.join(export, "model.npz"))


def test_eval_once_per_epoch(tmp_path):
    """eval_steps=0 (default) evaluates at each epoch boundary + final."""
    from datatunerx_tpu.tuning.train import main

    argv, out, storage = _flags(
        tmp_path, template="alpaca", num_train_epochs="2", logging_steps="1",
        bf16="false", remat="none",
    )
    # drop the max_steps-free run to 2 epochs of 3 steps: 96 rows / gb 32 = 3
    assert main(argv) == 0
    eval_log = [json.loads(l) for l in open(os.path.join(out, "watch", "eval_log.jsonl"))]
    # one mid-epoch eval (after epoch 1) + final eval
    assert len(eval_log) == 2, eval_log


def test_profile_trace_capture(tmp_path):
    """--profile_steps captures a profiler trace + records it in the manifest."""
    from datatunerx_tpu.tuning.parser import parse_train_args
    from datatunerx_tpu.tuning.train import run

    argv, out, storage = _flags(
        tmp_path, template="vanilla", max_steps="3", bf16="false",
        remat="none", profile_steps="1", quantization="",
    )
    args = parse_train_args(argv)
    r = run(args)
    assert r["steps"] == 3
    trace_dir = os.path.join(out, "trace")
    assert os.path.isdir(trace_dir) and os.listdir(trace_dir)
    mf = json.load(open(os.path.join(storage, "test-uid-123", "manifest.json")))
    assert mf["trace"] == trace_dir


@pytest.mark.slow  # generative eval decodes token-by-token unjitted: the two
# generate e2e tests are the suite's slowest (75s+50s on 2 CPUs) and tier-1
# has a hard 870s budget; `pytest -m slow` / the full suite still runs them
def test_predict_with_generate(tmp_path):
    """Generative eval: generated_predictions.jsonl + rouge/bleu in eval log
    (reference GenEvalSeq2SeqTrainer contract)."""
    from datatunerx_tpu.tuning.parser import parse_train_args
    from datatunerx_tpu.tuning.train import run

    argv, out, storage = _flags(
        tmp_path, template="vanilla", max_steps="2", bf16="false",
        remat="none", quantization="", predict_with_generate="true",
        max_new_tokens="8", generate_examples="4",
    )
    args = parse_train_args(argv)
    r = run(args)
    preds = [json.loads(l) for l in
             open(os.path.join(out, "generated_predictions.jsonl"))]
    assert len(preds) == 4
    assert {"prompt", "label", "predict"} <= set(preds[0])
    assert {"rouge-1", "rouge-2", "rouge-l", "bleu-4"} <= set(r["metrics"])


@pytest.mark.parametrize("preset", ["mistral-7b", "qwen1.5-7b"])
def test_model_family_smoke(tmp_path, preset):
    """Sliding-window (mistral) and qkv-bias (qwen) variants train through the
    CLI on scaled-down dims."""
    import dataclasses as _dc

    from datatunerx_tpu.models.config import PRESETS
    from datatunerx_tpu.tuning.parser import parse_train_args
    from datatunerx_tpu.tuning.train import run

    big = PRESETS[preset]
    tiny = _dc.replace(
        big, name=f"tiny-{preset}", vocab_size=3104, hidden_size=32,
        intermediate_size=64, num_layers=2, num_heads=4, num_kv_heads=big.num_kv_heads
        if big.num_kv_heads <= 4 else 4, max_seq_len=128,
        sliding_window=16 if big.sliding_window else None,
    )
    PRESETS[f"tiny-{preset}"] = tiny
    try:
        argv, out, storage = _flags(
            tmp_path, template="vanilla", max_steps="2", bf16="false",
            remat="none", quantization="",
        )
        argv[argv.index("preset:debug")] = f"preset:tiny-{preset}"
        args = parse_train_args(argv)
        r = run(args)
        assert r["steps"] == 2
        assert "loss" in r["metrics"]
    finally:
        del PRESETS[f"tiny-{preset}"]


@pytest.mark.slow  # see test_predict_with_generate
def test_generate_eval_at_step_intervals(tmp_path):
    """--generate_eval_steps N: rouge/bleu points land in the eval log DURING
    training, not just at the end (VERDICT round-1 item 9)."""
    from datatunerx_tpu.tuning.parser import parse_train_args
    from datatunerx_tpu.tuning.train import run

    argv, out, storage = _flags(
        tmp_path, template="vanilla", max_steps="3", bf16="false",
        remat="none", quantization="", predict_with_generate="true",
        max_new_tokens="8", generate_examples="4", generate_eval_steps="1",
    )
    args = parse_train_args(argv)
    r = run(args)
    assert r["steps"] == 3
    eval_log = [json.loads(l) for l in
                open(os.path.join(out, "watch", "eval_log.jsonl"))]
    gen_rows = [(e["current_steps"], e) for e in eval_log if "rouge-l" in e]
    # interval points at steps 1 and 2 plus the full end-of-run pass at 3
    steps = sorted(s for s, _ in gen_rows)
    assert steps == [1, 2, 3], eval_log
