"""Reward-model stage (reference cmd/tuning/parser.py:117-120 lists rm;
reward_model arg :74-76): pairwise ranking loss over preference pairs with a
trainable value head — loss = ln2 at a symmetric start is NOT guaranteed (the
head scores differ across sequences), so the bar is trainability: accuracy on
the training pairs climbs and loss drops; plus e2e CLI + export carrying the
head."""

import json
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from datatunerx_tpu.data.loader import PreferenceBatchIterator
from datatunerx_tpu.data.preprocess import preprocess_preference_records
from datatunerx_tpu.data.templates import get_template
from datatunerx_tpu.models import get_config, init_params
from datatunerx_tpu.training import TrainConfig, Trainer
from tests.fake_tokenizer import FakeTokenizer


@pytest.fixture(scope="module")
def tok():
    return FakeTokenizer()


def _pairs(tok, n=8):
    tpl = get_template("vanilla", tok)
    records = [
        {"instruction": f"question {i}",
         "chosen": f"good answer number {i}",
         "rejected": f"bad {i}"}
        for i in range(n)
    ]
    return preprocess_preference_records(records, tpl, tok, cutoff_len=64)


def test_rm_requires_lora():
    with pytest.raises(ValueError, match="lora"):
        TrainConfig(stage="rm", finetuning_type="full")


def test_rm_state_has_value_head():
    cfg = get_config("debug")
    tr = Trainer(cfg, TrainConfig(stage="rm", finetuning_type="lora",
                                  lora_rank=4, total_steps=5,
                                  compute_dtype=None))
    state = tr.init_state(init_params(cfg, jax.random.PRNGKey(0)),
                          jax.random.PRNGKey(1))
    assert "v_head" in state.lora
    assert state.lora["v_head"].shape == (cfg.hidden_size,)
    # sft states must NOT grow a head
    tr2 = Trainer(cfg, TrainConfig(finetuning_type="lora", lora_rank=4,
                                   total_steps=5, compute_dtype=None))
    state2 = tr2.init_state(init_params(cfg, jax.random.PRNGKey(0)),
                            jax.random.PRNGKey(1))
    assert "v_head" not in state2.lora


def test_rm_training_learns_to_rank(tok):
    cfg = get_config("debug")
    tr = Trainer(cfg, TrainConfig(
        stage="rm", finetuning_type="lora", lora_rank=8, lora_dropout=0.0,
        learning_rate=5e-3, total_steps=40, compute_dtype=None,
    ))
    state = tr.init_state(init_params(cfg, jax.random.PRNGKey(0)),
                          jax.random.PRNGKey(1))
    pairs = _pairs(tok, 4)
    batch = next(iter(PreferenceBatchIterator(
        pairs, global_batch=4, block_size=64, pad_id=tok.pad_token_id or 0)))
    batch = {k: jnp.asarray(v) for k, v in batch.items()}
    first = None
    for _ in range(40):
        state, m = tr.train_step(state, batch)
        first = float(m["loss"]) if first is None else first
    final = float(m["loss"])
    assert np.isfinite(first) and np.isfinite(final)
    assert final < first, (first, final)
    assert final < 0.3  # chosen reliably outscores rejected


def test_rm_gradients_reach_value_head(tok):
    """The head must actually train (a dead head would silently reduce rm to
    random ranking)."""
    cfg = get_config("debug")
    tr = Trainer(cfg, TrainConfig(
        stage="rm", finetuning_type="lora", lora_rank=4, lora_dropout=0.0,
        learning_rate=1e-2, total_steps=5, compute_dtype=None,
    ))
    state = tr.init_state(init_params(cfg, jax.random.PRNGKey(0)),
                          jax.random.PRNGKey(1))
    head0 = np.asarray(state.lora["v_head"])
    pairs = _pairs(tok, 4)
    batch = next(iter(PreferenceBatchIterator(
        pairs, global_batch=4, block_size=64, pad_id=tok.pad_token_id or 0)))
    state, _ = tr.train_step(state, {k: jnp.asarray(v)
                                     for k, v in batch.items()})
    assert np.abs(np.asarray(state.lora["v_head"]) - head0).max() > 0


def test_rm_cli_e2e_with_export(tmp_path):
    from datatunerx_tpu.tuning.parser import parse_train_args
    from datatunerx_tpu.tuning.train import run

    data = tmp_path / "prefs.jsonl"
    with open(data, "w") as f:
        for i in range(40):
            f.write(json.dumps({
                "instruction": f"q {i}", "chosen": f"great answer {i}",
                "rejected": f"terrible {i}",
            }) + "\n")
    out = str(tmp_path / "out")
    storage = str(tmp_path / "storage")
    export = str(tmp_path / "export")
    args = parse_train_args([
        "--model_name_or_path", "preset:debug", "--stage", "rm",
        "--train_path", str(data), "--output_dir", out,
        "--storage_path", storage, "--uid", "rm-run",
        "--export_dir", export,
        "--template", "vanilla", "--max_steps", "3", "--bf16", "false",
        "--remat", "none", "--per_device_train_batch_size", "4",
        "--block_size", "64", "--logging_steps", "1",
    ])
    r = run(args)
    assert r["steps"] == 3
    log = [json.loads(l) for l in
           open(os.path.join(out, "watch", "trainer_log.jsonl"))]
    assert len(log) == 3 and all(np.isfinite(e["loss"]) for e in log)
    # exported reward model carries the value head
    sd = np.load(os.path.join(export, "model.npz"))
    assert "v_head.weight" in sd


def test_rm_reachable_through_operator():
    """trainerType rm must pass admission (with PEFT) and render --stage rm
    in the trainer args — otherwise the stage exists only on the CLI."""
    from datatunerx_tpu.operator.api import Hyperparameter, ObjectMeta
    from datatunerx_tpu.operator.generate import build_trainer_args
    from datatunerx_tpu.operator.webhooks import AdmissionError, admit

    ok = Hyperparameter(metadata=ObjectMeta(name="h-rm"), spec={
        "parameters": {"trainerType": "rm"}})
    admit(ok)
    with pytest.raises(AdmissionError, match="PEFT"):
        admit(Hyperparameter(metadata=ObjectMeta(name="h-rm2"), spec={
            "parameters": {"trainerType": "rm", "PEFT": "false"}}))
    # ppo is a real stage now (training/ppo.py) but needs its reward model
    with pytest.raises(AdmissionError, match="rewardModel"):
        admit(Hyperparameter(metadata=ObjectMeta(name="h-ppo"), spec={
            "parameters": {"trainerType": "ppo"}}))
    admit(Hyperparameter(metadata=ObjectMeta(name="h-ppo2"), spec={
        "parameters": {"trainerType": "ppo",
                       "rewardModel": "/storage/rm-run"}}))

    from datatunerx_tpu.operator.api import Finetune

    ft = Finetune(metadata=ObjectMeta(name="ft", namespace="d"), spec={
        "image": {"path": "preset:debug"}})
    ds_spec = {"datasetMetadata": {"datasetInfo": {"subsets": [
        {"splits": {"train": {"file": "/data/prefs.jsonl"}}}]}}}
    args = build_trainer_args(ft, ds_spec, {"trainerType": "rm"})
    joined = " ".join(args)
    assert "--stage rm" in joined
    assert "--finetuning_type lora" in joined


def test_rm_stage_rejected_without_lora_cli():
    from datatunerx_tpu.tuning.parser import parse_train_args

    with pytest.raises(ValueError, match="lora"):
        parse_train_args([
            "--model_name_or_path", "preset:debug", "--stage", "rm",
            "--finetuning_type", "full", "--train_path", "x.jsonl",
            "--output_dir", "/tmp/o",
        ])
