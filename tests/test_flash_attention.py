"""Flash attention kernel vs the XLA reference path (interpret mode on CPU)."""

import dataclasses

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from datatunerx_tpu.models.config import ModelConfig
from datatunerx_tpu.models.llama import forward, init_params
from datatunerx_tpu.ops.attention import make_causal_bias, xla_attention
from datatunerx_tpu.ops.flash_attention import flash_attention


def _qkv(rng, B=2, T=128, H=4, KV=2, d=32):
    q = jnp.asarray(rng.normal(size=(B, T, H, d)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(B, T, KV, d)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(B, T, KV, d)), jnp.float32)
    return q, k, v


@pytest.mark.parametrize("T,block", [(128, 64), (256, 128), (96, 32)])
def test_flash_matches_xla_causal(T, block):
    rng = np.random.default_rng(0)
    q, k, v = _qkv(rng, T=T)
    B = q.shape[0]
    pos = jnp.broadcast_to(jnp.arange(T)[None], (B, T))
    bias = make_causal_bias(pos, pos)
    ref = xla_attention(q, k, v, bias)
    out = flash_attention(q, k, v, block_q=block, block_k=block)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


def test_flash_gqa_grouping():
    """Each query head must read its own KV group, not a mixed one."""
    rng = np.random.default_rng(1)
    q, k, v = _qkv(rng, B=1, T=64, H=4, KV=2)
    pos = jnp.arange(64)[None]
    bias = make_causal_bias(pos, pos)
    ref = xla_attention(q, k, v, bias)
    out = flash_attention(q, k, v, block_q=32, block_k=32)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_model_forward_flash_matches_xla():
    cfg = ModelConfig(
        vocab_size=128, hidden_size=64, intermediate_size=96, num_layers=2,
        num_heads=4, num_kv_heads=2, max_seq_len=256, remat="none",
    )
    params = init_params(cfg, jax.random.PRNGKey(0))
    toks = jnp.asarray(np.random.default_rng(2).integers(0, 128, (2, 128), np.int32))
    ref, _ = forward(params, toks, cfg)
    fcfg = dataclasses.replace(cfg, attention_impl="flash")
    out, _ = forward(params, toks, fcfg)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-4, rtol=1e-4)


def test_flash_falls_back_for_packed_and_cache():
    """Packed segments / cache decode silently use the exact biased path."""
    cfg = ModelConfig(
        vocab_size=128, hidden_size=32, intermediate_size=64, num_layers=1,
        num_heads=2, num_kv_heads=2, max_seq_len=64, remat="none",
        attention_impl="flash",
    )
    params = init_params(cfg, jax.random.PRNGKey(0))
    toks = jnp.asarray(np.random.default_rng(3).integers(0, 128, (1, 32), np.int32))
    segs = jnp.asarray(np.repeat([[1, 2]], 16, axis=1).reshape(1, 32))
    logits, _ = forward(params, toks, cfg, segment_ids=segs)
    assert np.isfinite(np.asarray(logits)).all()

    from datatunerx_tpu.models.llama import init_cache

    cache = init_cache(cfg, 1, 32, dtype=jnp.float32)
    logits2, cache = forward(params, toks[:, :8], cfg,
                             positions=jnp.arange(8)[None], cache=cache)
    assert np.isfinite(np.asarray(logits2)).all()


def test_flash_training_grad_matches_xla():
    """Backward pass through the kernel (interpret-mode autodiff) vs XLA."""
    rng = np.random.default_rng(4)
    q, k, v = _qkv(rng, B=1, T=64, H=2, KV=2, d=16)
    pos = jnp.arange(64)[None]
    bias = make_causal_bias(pos, pos)

    def loss_ref(q, k, v):
        return jnp.sum(xla_attention(q, k, v, bias) ** 2)

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, block_q=32, block_k=32) ** 2)

    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    g_fl = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_ref, g_fl):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a), atol=5e-4, rtol=5e-4)


def test_flash_segment_masking_matches_xla():
    """Packed-segment flash vs the biased XLA path, forward + gradients."""
    from datatunerx_tpu.ops.flash_attention import flash_attention as fa

    rng = np.random.default_rng(7)
    B, T, H, KV, d = 2, 128, 4, 2, 16
    q = jnp.asarray(rng.normal(size=(B, T, H, d)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(B, T, KV, d)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(B, T, KV, d)), jnp.float32)
    # three segments + trailing padding (id 0)
    segs = np.zeros((B, T), np.int32)
    segs[:, :40] = 1
    segs[:, 40:90] = 2
    segs[:, 90:120] = 3
    segs = jnp.asarray(segs)
    pos = jnp.broadcast_to(jnp.arange(T)[None], (B, T))  # row-global positions

    bias = make_causal_bias(pos, pos, q_segment_ids=segs, kv_segment_ids=segs)
    ref = xla_attention(q, k, v, bias)
    out = fa(q, k, v, segment_ids=segs, block_q=32, block_k=32)
    valid = np.asarray(segs > 0)
    np.testing.assert_allclose(np.asarray(out)[valid], np.asarray(ref)[valid],
                               atol=2e-5, rtol=2e-5)

    def loss_ref(q, k, v):
        m = jnp.asarray(valid)[:, :, None, None]
        return jnp.sum(jnp.where(m, xla_attention(q, k, v, bias), 0.0) ** 2)

    def loss_fa(q, k, v):
        m = jnp.asarray(valid)[:, :, None, None]
        return jnp.sum(jnp.where(
            m, fa(q, k, v, segment_ids=segs, block_q=32, block_k=32), 0.0) ** 2)

    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    g_fa = jax.grad(loss_fa, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_ref, g_fa):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                   atol=5e-4, rtol=5e-4)


def test_packed_training_flash_matches_xla():
    """End-to-end: packed batch trained with attention_impl=flash equals xla."""
    from datatunerx_tpu.models.config import ModelConfig
    from datatunerx_tpu.models.llama import init_params
    from datatunerx_tpu.training import TrainConfig, Trainer
    from datatunerx_tpu.training.loss import IGNORE_INDEX

    base = dict(vocab_size=256, hidden_size=32, intermediate_size=64,
                num_layers=2, num_heads=4, num_kv_heads=2, max_seq_len=128,
                remat="none")
    rng = np.random.default_rng(9)
    toks = rng.integers(4, 256, (2, 128)).astype(np.int32)
    segs = np.zeros((2, 128), np.int32)
    segs[:, :50] = 1
    segs[:, 50:110] = 2
    positions = np.concatenate([np.arange(50), np.arange(60), np.zeros(18)]
                               ).astype(np.int32)[None].repeat(2, 0)
    labels = np.where(segs > 0, toks, IGNORE_INDEX)
    batch = {"input_ids": jnp.asarray(toks), "labels": jnp.asarray(labels),
             "segment_ids": jnp.asarray(segs),
             "positions": jnp.asarray(positions),
             "attention_mask": jnp.asarray((segs > 0).astype(np.int32))}

    losses = {}
    for impl in ("xla", "flash"):
        cfg = ModelConfig(**base, attention_impl=impl)
        tr = Trainer(cfg, TrainConfig(finetuning_type="lora", lora_rank=4,
                                      lora_dropout=0.0, learning_rate=1e-2,
                                      scheduler="constant", total_steps=5,
                                      compute_dtype=None))
        state = tr.init_state(init_params(cfg, jax.random.PRNGKey(0)),
                              jax.random.PRNGKey(1))
        state, m = tr.train_step(state, batch)
        losses[impl] = float(m["loss"])
    np.testing.assert_allclose(losses["flash"], losses["xla"], rtol=1e-5)
