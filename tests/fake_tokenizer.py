"""Deterministic HF-tokenizer stand-in for template golden tests (no network)."""


class FakeTokenizer:
    def __init__(self, add_bos_token=True):
        self.bos_token_id = 1
        self.eos_token_id = 2
        self.eos_token = "</s>"
        self.pad_token = None
        self.pad_token_id = None
        self.add_bos_token = add_bos_token
        self._special = {"</s>": 2}

    def encode(self, text, add_special_tokens=False):
        assert not add_special_tokens
        # stable per-character ids, offset away from special ids
        return [10 + (ord(c) % 1987) for c in text]

    def convert_tokens_to_ids(self, token):
        if token not in self._special:
            self._special[token] = 3000 + len(self._special)
        return self._special[token]

    def add_special_tokens(self, mapping, replace_additional_special_tokens=False):
        for tok in mapping.get("additional_special_tokens", []):
            self.convert_tokens_to_ids(tok)

    @property
    def special_tokens_map(self):
        return dict(self._special)
