"""Load-replay + chaos harness (datatunerx_tpu/loadgen/): workload shape,
trace round-trip, chaos scheduling, the replay runner, and the closed-loop
acceptance — a replay with a mid-stream replica kill + adapter eviction
holds its availability SLO through gateway failover, while a tightened
objective makes the same harness exit nonzero naming the objective."""

import io
import json
import time

import pytest

from datatunerx_tpu.loadgen.chaos import ChaosInjector, load_chaos
from datatunerx_tpu.loadgen.replay import (
    LocalClient,
    ReplayRunner,
    apply_tighten,
    build_selftest_fleet,
    main as replay_main,
    slo_epilogue,
)
from datatunerx_tpu.loadgen.workload import (
    WorkloadModel,
    read_trace,
    summarize,
    write_trace,
)
from datatunerx_tpu.obs.slo import SLOEvaluator, default_slos


# ------------------------------------------------------------------ workload

def test_workload_deterministic_and_heavy_tailed():
    a = WorkloadModel(requests=60, sessions=5, seed=3,
                      adapters=["t-a", "t-b", "t-c"]).generate()
    b = WorkloadModel(requests=60, sessions=5, seed=3,
                      adapters=["t-a", "t-b", "t-c"]).generate()
    assert a == b  # same seed, same trace — replayable by construction
    c = WorkloadModel(requests=60, sessions=5, seed=4,
                      adapters=["t-a", "t-b", "t-c"]).generate()
    assert a != c
    sizes = sorted(sum(len(m["content"]) for m in e["messages"]) for e in a)
    assert sizes[-1] > 3 * sizes[len(sizes) // 2]  # a real tail
    assert all(e["t"] <= n["t"] for e, n in zip(a, a[1:]))
    models = [e["model"] for e in a]
    assert "" in models  # base traffic interleaved
    assert {"t-a", "t-b", "t-c"} <= set(m for m in models if m)


def test_workload_sessions_reuse_prefixes():
    events = WorkloadModel(requests=40, sessions=3, seed=0).generate()
    by_session: dict = {}
    for e in events:
        by_session.setdefault(e["session"], []).append(e)
    multi = [evs for evs in by_session.values() if len(evs) > 1]
    assert multi
    for evs in multi:
        system = evs[0]["messages"][0]
        for e in evs[1:]:
            # every turn reopens with the SAME system prompt — the reused
            # prefix a prefix cache / affinity router keys on
            assert e["messages"][0] == system
            assert e["turn"] > evs[0]["turn"] or e is evs[0]


def test_trace_roundtrip_and_validation(tmp_path):
    model = WorkloadModel(requests=12, sessions=2, seed=1,
                          adapters=["t-a"])
    events = model.generate()
    path = tmp_path / "trace.jsonl"
    write_trace(str(path), events, model.meta())
    meta, back = read_trace(str(path))
    assert back == events
    assert meta["requests"] == 12
    assert summarize(back)["requests"] == 12
    with pytest.raises(ValueError, match="kind"):
        read_trace(io.StringIO('{"kind": "nope", "version": 1}\n'))
    with pytest.raises(ValueError, match="version"):
        read_trace(io.StringIO('{"kind": "dtx-load-trace", "version": 9}\n'))
    with pytest.raises(ValueError, match="bad event"):
        read_trace(io.StringIO(
            '{"kind": "dtx-load-trace", "version": 1}\n{"t": "x"}\n'))


# --------------------------------------------------------------------- chaos

def test_chaos_fires_in_order_and_skips_unknown_ops():
    fired = []
    inj = ChaosInjector(
        [{"t": 0.02, "op": "beta"}, {"t": 0.0, "op": "alpha"},
         {"t": 0.01, "op": "mystery"}],
        {"alpha": lambda op: fired.append("alpha") or {"ok": 1},
         "beta": lambda op: fired.append("beta") or {"ok": 1}})
    inj.run(speed=1.0)
    assert fired == ["alpha", "beta"]
    log = inj.report()
    assert [e["op"] for e in log] == ["alpha", "mystery", "beta"]
    skipped = next(e for e in log if e["op"] == "mystery")
    assert skipped["ok"] is None and "skipped" in skipped["detail"]


def test_chaos_action_failure_is_logged_not_raised():
    def boom(op):
        raise RuntimeError("refused")

    inj = ChaosInjector([{"t": 0.0, "op": "drain"}], {"drain": boom})
    inj.run()
    assert inj.report()[0]["ok"] is False
    assert "refused" in inj.report()[0]["detail"]


def test_load_chaos_inline_and_validation():
    ops = load_chaos('[{"t": 1.0, "op": "drain", "replica": "r1"}]')
    assert ops[0]["op"] == "drain"
    with pytest.raises(ValueError, match="needs t and op"):
        load_chaos('[{"op": "drain"}]')


# -------------------------------------------------------------------- runner

class _StubClient:
    def __init__(self):
        self.calls = []

    def send(self, event, trace_id):
        self.calls.append(event)
        fail = bool(event.get("fail"))
        return {"code": 502 if fail else 200, "error": None,
                "chars": 4, "ttft_ms": 20.0 if not fail else None,
                "latency_ms": 35.0}


def test_replay_runner_reports_and_records():
    client = _StubClient()
    runner = ReplayRunner(client, max_inflight=4)
    events = [{"t": 0.0, "messages": [{"role": "user", "content": "a"}]},
              {"t": 0.01, "messages": [{"role": "user", "content": "b"}],
               "fail": True},
              {"t": 0.02, "messages": [{"role": "user", "content": "c"}]}]
    report = runner.run(events, speed=100.0)
    assert report["requests"] == 3 and report["errors"] == 1
    assert report["codes"] == {"200": 2, "502": 1}
    assert report["ttft_ms_p50"] == 20.0
    assert report["ttft_ms_p99"] == 20.0
    text = runner.registry.expose()
    assert 'dtx_loadgen_requests_total{code="200"} 2' in text
    assert 'dtx_loadgen_requests_total{code="502"} 1' in text
    # exemplars link every histogram bucket back to a replay trace id
    assert '# {trace_id="dtx-load-' in text


def test_epilogue_passes_and_fails_by_objective():
    client = _StubClient()
    runner = ReplayRunner(client)
    evaluator = SLOEvaluator(runner.registry, default_slos("loadgen"))
    # the tightened twin judges the SAME run (all ttfts are 20ms, so a
    # 1ms threshold must violate); both baselines predate the traffic,
    # exactly like the CLI building its evaluator before runner.run
    tight = apply_tighten(default_slos("loadgen"),
                          ["loadgen-fast-ttft=0.99@1"])
    ev2 = SLOEvaluator(runner.registry, tight)
    t0 = time.monotonic()
    runner.run([{"t": 0.0,
                 "messages": [{"role": "user", "content": "x"}]}] * 5,
               speed=1e6)
    lines = []
    verdict = slo_epilogue(evaluator, since_t=t0 - 1,
                           out=lines.append)
    assert verdict["pass"] is True
    assert any("PASS" in ln for ln in lines)
    verdict2 = slo_epilogue(ev2, since_t=t0 - 1, out=lines.append)
    assert verdict2["pass"] is False
    assert "loadgen-fast-ttft" in verdict2["violations"][0]
    assert "0.99" in verdict2["violations"][0]


def test_apply_tighten_validates():
    with pytest.raises(ValueError, match="no such SLO"):
        apply_tighten(default_slos("loadgen"), ["nope=0.5"])
    with pytest.raises(ValueError, match="NAME=OBJECTIVE"):
        apply_tighten(default_slos("loadgen"), ["bare"])
    # objective 1.0 must be a clean validation error, not a
    # ZeroDivisionError later in the epilogue
    with pytest.raises(ValueError, match="error budget"):
        apply_tighten(default_slos("loadgen"), ["loadgen-availability=1.0"])


# ------------------------------------------------------- closed-loop proof

def test_replay_with_kill_and_adapter_evict_holds_availability_slo():
    """Acceptance: mid-stream replica kill + adapter eviction; the
    availability SLO stays green because gateway failover absorbs the
    faults, and the verdict comes from the same SLOEvaluator class the
    gateway's /debug/slo serves."""
    gw, engines = build_selftest_fleet(["tenant-a", "tenant-b"])
    try:
        model = WorkloadModel(requests=40, sessions=4, rps=120.0, seed=11,
                              adapters=["tenant-a", "tenant-b"])
        events = model.generate()
        mid = events[len(events) // 2]["t"]
        chaos = ChaosInjector(
            [{"t": mid, "op": "kill", "replica": "replica-1"},
             {"t": mid, "op": "adapter_unload", "adapter": "tenant-b"}],
            {"kill": lambda op: [setattr(e, "fail", True)
                                 for e in engines
                                 if e.name == op["replica"]] and {"ok": 1},
             "adapter_unload": lambda op: {
                 "unloaded": [e.unload_adapter(op["adapter"])
                              for e in engines]}})
        runner = ReplayRunner(LocalClient(gw), max_inflight=8)
        evaluator = SLOEvaluator(runner.registry, default_slos("loadgen"))
        t0 = time.monotonic()
        report = runner.run(events, chaos=chaos)
        assert report["requests"] == 40
        killed = [e for e in report["chaos"] if e["op"] == "kill"]
        assert killed and killed[0]["ok"] is True
        # the kill may surface as a handful of failovers, never as an
        # availability breach: the SLO tolerates 1% server-side errors
        verdict = slo_epilogue(evaluator, since_t=t0 - 1,
                               out=lambda s: None)
        avail = next(v for v in verdict["verdicts"]
                     if v["name"] == "loadgen-availability")
        assert avail["compliant"] is True, verdict
        # same code path the gateway serves at /debug/slo
        assert isinstance(evaluator, type(gw.slo))
        assert gw.slo_report()["plane"] == "gateway"
    finally:
        gw.close()


def test_replay_cli_selftest_pass_and_tightened_detection(tmp_path, capsys):
    """The CI smoke contract, driven through the real CLI entry: healthy
    selftest exits 0 (with the drain chaos op fired); a deliberately
    tightened objective exits 1 and NAMES the objective."""
    report_path = tmp_path / "report.json"
    rc = replay_main(["--selftest", "--requests", "16", "--rps", "80",
                      "--report_json", str(report_path)])
    out = capsys.readouterr().out
    assert rc == 0
    assert "SLO verdict: PASS" in out
    assert "drain" in out  # the injected chaos op is visible in the log
    report = json.loads(report_path.read_text())
    assert report["slo"]["pass"] is True
    assert report["requests"] == 16

    rc = replay_main(["--selftest", "--requests", "12", "--rps", "80",
                      "--tighten", "loadgen-fast-ttft=0.999@0.001"])
    out = capsys.readouterr().out
    assert rc == 1
    assert "SLO loadgen-fast-ttft violated" in out
    assert "0.999" in out


def test_replay_cli_record_then_replay_trace(tmp_path, capsys):
    trace = tmp_path / "t.jsonl"
    rc = replay_main(["--record", str(trace), "--requests", "8",
                      "--rps", "100", "--seed", "5"])
    assert rc == 0 and trace.exists()
    rc = replay_main(["--selftest", "--trace", str(trace)])
    assert rc == 0
    out = capsys.readouterr().out
    assert "trace " in out and "SLO verdict: PASS" in out


def test_chaos_ops_past_replay_end_logged_as_skipped():
    """An op scheduled after the traffic ends must appear in the report as
    skipped — a clean verdict next to a half-run schedule would lie."""
    fired = []
    inj = ChaosInjector(
        [{"t": 0.0, "op": "drain"}, {"t": 60.0, "op": "kill", "replica": "r0"}],
        {"drain": lambda op: fired.append("drain") or {"ok": 1},
         "kill": lambda op: fired.append("kill")})
    inj.start(speed=1.0)
    time.sleep(0.1)
    inj.stop()
    assert fired == ["drain"]
    log = inj.report()
    assert [e["op"] for e in log] == ["drain", "kill"]
    missed = log[1]
    assert missed["ok"] is None and "replay ended" in missed["detail"]
    assert missed["args"] == {"replica": "r0"}
