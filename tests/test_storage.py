"""Object-storage plane: URI-aware dataset ingest + manifests (VERDICT
round-1 item 4). ``memory://`` (in-process fsspec filesystem) stands in for
gs://; the code path is identical — only the scheme's backend differs."""

import json

import pytest

from datatunerx_tpu.data.loader import CsvDataset
from datatunerx_tpu.training.checkpoint import read_manifest, write_manifest
from datatunerx_tpu.utils import storage


@pytest.fixture(autouse=True)
def clean_memory_fs():
    import fsspec

    fs = fsspec.filesystem("memory")
    yield
    for p in list(fs.store):
        fs.store.pop(p, None)


def test_uri_helpers():
    assert storage.is_uri("gs://b/k") and storage.is_uri("memory://x")
    assert not storage.is_uri("/tmp/x")
    assert storage.join("gs://b", "a", "c.json") == "gs://b/a/c.json"
    assert storage.join("/tmp", "a") == "/tmp/a"


def test_read_write_roundtrip_memory():
    storage.write_text("memory://bucket/dir/file.txt", "hello")
    assert storage.exists("memory://bucket/dir/file.txt")
    assert storage.read_text("memory://bucket/dir/file.txt") == "hello"
    assert not storage.exists("memory://bucket/dir/nope.txt")


def test_csv_dataset_from_uri():
    storage.write_text(
        "memory://data/train.csv",
        "instruction,response\nhello,world\nfoo,bar\n",
    )
    ds = CsvDataset("memory://data/train.csv")
    assert len(ds) == 2
    assert ds.records[0]["instruction"] == "hello"


def test_jsonl_dataset_from_uri():
    rows = [{"instruction": "a", "response": "b"},
            {"instruction": "c", "response": "d"}]
    storage.write_text("memory://data/train.jsonl",
                       "\n".join(json.dumps(r) for r in rows))
    ds = CsvDataset("memory://data/train.jsonl")
    assert len(ds) == 2 and ds.records[1]["response"] == "d"


def test_dataset_uri_missing_raises():
    with pytest.raises(FileNotFoundError):
        CsvDataset("memory://data/absent.csv")


def test_manifest_roundtrip_over_uri():
    path = write_manifest("memory://runs", "uid-1", "gs://ckpts/uid-1/7",
                          metrics={"loss": 1.25}, extra={"lora_scaling": 2.0})
    assert path == "memory://runs/uid-1/manifest.json"
    m = read_manifest("memory://runs", "uid-1")
    assert m["checkpoint"] == "gs://ckpts/uid-1/7"
    assert m["metrics"]["loss"] == 1.25 and m["lora_scaling"] == 2.0
    # legacy path file (reference train.py:383-389 contract)
    assert storage.read_text("memory://runs/uid-1/checkpoint_path") == (
        "gs://ckpts/uid-1/7")
    assert read_manifest("memory://runs", "uid-2") is None


def test_s3_storage_options_from_env(monkeypatch):
    from datatunerx_tpu.operator.config import object_store_options

    monkeypatch.setenv("S3_ENDPOINT", "minio.ns.svc:9000")
    monkeypatch.setenv("S3_ACCESSKEYID", "ak")
    monkeypatch.setenv("S3_SECRETACCESSKEY", "sk")
    monkeypatch.setenv("S3_SECURE", "false")
    opts = object_store_options("s3://bucket/key.csv")
    assert opts["key"] == "ak" and opts["secret"] == "sk"
    assert opts["client_kwargs"]["endpoint_url"] == "http://minio.ns.svc:9000"
    assert object_store_options("gs://bucket/key.csv") == {}
