"""Serving engine: jitted while-loop decode vs stepwise reference, sampling,
chat templating."""

import pytest

import jax.numpy as jnp

from datatunerx_tpu.models.llama import forward, init_cache
from datatunerx_tpu.serving.engine import InferenceEngine


@pytest.fixture(scope="module")
def engine():
    return InferenceEngine("preset:debug", template="vanilla", max_seq_len=256)


def _reference_greedy(engine, prompt_ids, max_new):
    """Stepwise python-loop decode (the pre-jit implementation)."""
    cfg, params, tok = engine.cfg, engine.params, engine.tokenizer
    cache = init_cache(cfg, 1, len(prompt_ids) + max_new, dtype=jnp.bfloat16)
    logits, cache = forward(
        params, jnp.asarray([prompt_ids], jnp.int32), cfg,
        positions=jnp.arange(len(prompt_ids))[None], cache=cache,
        compute_dtype=jnp.bfloat16,
    )
    out = []
    pos = len(prompt_ids)
    nxt = int(jnp.argmax(logits[0, -1]))
    for _ in range(max_new):
        if nxt == tok.eos_token_id:
            break
        out.append(nxt)
        logits, cache = forward(
            params, jnp.asarray([[nxt]], jnp.int32), cfg,
            positions=jnp.asarray([[pos]]), cache=cache,
            compute_dtype=jnp.bfloat16,
        )
        nxt = int(jnp.argmax(logits[0, -1]))
        pos += 1
    return out


def test_jit_decode_matches_stepwise(engine):
    prompt = engine.tokenizer.encode("the quick brown fox")
    a = engine.generate(prompt, max_new_tokens=12)
    # left-pad bucketing must not change greedy output vs exact-length decode
    b = _reference_greedy(engine, prompt, 12)
    assert a == b, (a, b)


def test_same_bucket_prompts_share_shapes(engine):
    # both prompts land in the 64-token bucket; second call must reuse compiles
    out1 = engine.generate(engine.tokenizer.encode("abc"), max_new_tokens=4)
    out2 = engine.generate(engine.tokenizer.encode("a longer prompt here"),
                           max_new_tokens=4)
    assert isinstance(out1, list) and isinstance(out2, list)


def test_sampling_deterministic_per_seed(engine):
    prompt = engine.tokenizer.encode("hello")
    a = engine.generate(prompt, max_new_tokens=8, temperature=0.9, seed=7)
    b = engine.generate(prompt, max_new_tokens=8, temperature=0.9, seed=7)
    c = engine.generate(prompt, max_new_tokens=8, temperature=0.9, seed=8)
    assert a == b
    # different seeds normally diverge on a random model (not guaranteed, but
    # overwhelmingly likely over 8 tokens of a 3104-way softmax)
    assert a != c or len(a) == 0


def test_chat_assembles_history_and_system(engine):
    msgs = [
        {"role": "system", "content": "be brief"},
        {"role": "user", "content": "hi"},
        {"role": "assistant", "content": "hello"},
        {"role": "user", "content": "bye"},
    ]
    # vanilla template ignores history/system but the assembly path must not
    # crash and must produce a string
    out = engine.chat(msgs, max_new_tokens=4)
    assert isinstance(out, str)


def test_max_tokens_cap(engine):
    prompt = engine.tokenizer.encode("x" * 10)
    out = engine.generate(prompt, max_new_tokens=3,
                          stop_ids={-1})  # unreachable stop -> hits the cap
    assert len(out) == 3


def test_oversized_max_tokens_clamped(engine):
    """max_tokens >= max_seq_len must degrade gracefully, not crash."""
    prompt = engine.tokenizer.encode("hello world " * 50)
    out = engine.generate(prompt, max_new_tokens=512, stop_ids={-1})
    # engine max_seq_len=256 -> budget clamped; no trace error, bounded output
    assert 0 < len(out) <= 256


def test_long_prompt_truncated_not_overflowed(engine):
    prompt = engine.tokenizer.encode("x" * 1000)  # >> max_seq_len
    out = engine.generate(prompt, max_new_tokens=8, stop_ids={-1})
    assert len(out) == 8


def test_empty_prompt_no_nan(engine):
    out = engine.generate([], max_new_tokens=4, stop_ids={-1})
    assert len(out) == 4
    assert all(isinstance(t, int) and 0 <= t < engine.cfg.vocab_size for t in out)


def test_numpy_stop_ids_respected(engine):
    import numpy as _np

    # np integer stop ids must not be dropped by the filter
    prompt = engine.tokenizer.encode("abc")
    full = engine.generate(prompt, max_new_tokens=8, stop_ids={-1})
    if full:  # stop on the first token the model actually produces
        stopped = engine.generate(prompt, max_new_tokens=8,
                                  stop_ids={_np.int64(full[0])})
        assert stopped == []


def test_concurrent_chat_requests(engine):
    """ThreadingHTTPServer serves requests concurrently; the engine must be
    safe under parallel chat() calls (per-call caches, shared params)."""
    import threading

    results, errors = [], []

    def worker(i):
        try:
            out = engine.chat([{"role": "user", "content": f"msg {i}"}],
                              max_new_tokens=4)
            results.append(out)
        except Exception as e:  # noqa: BLE001
            errors.append(e)

    threads = [threading.Thread(target=worker, args=(i,)) for i in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    assert not errors, errors
    assert len(results) == 4


def test_quantized_engine_generates():
    """Serve-time int8 quantization: engine quantizes post-load and decodes."""
    e = InferenceEngine("preset:debug", template="vanilla", max_seq_len=128,
                        quantization="int8")
    assert "quant" in e.params["layers"]["q_proj"]
    out = e.generate(e.tokenizer.encode("hello"), max_new_tokens=4, stop_ids={-1})
    assert len(out) == 4


def test_engine_greedy_matches_hf_generate(tmp_path):
    """Engine greedy decode vs transformers greedy generate on the same tiny
    llama checkpoint — serving correctness pinned to the HF reference."""
    torch = pytest.importorskip("torch")
    from transformers import LlamaConfig, LlamaForCausalLM

    torch.manual_seed(0)
    hf_cfg = LlamaConfig(
        vocab_size=256, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=128, rms_norm_eps=1e-5,
        tie_word_embeddings=False, bos_token_id=1, eos_token_id=2,
    )
    model = LlamaForCausalLM(hf_cfg).eval()

    from datatunerx_tpu.utils.hf_convert import config_from_hf, convert_hf_state_dict
    from datatunerx_tpu.serving.engine import InferenceEngine

    # build normally, then swap in the HF-converted model (jit retraces on the
    # new shapes; avoids duplicating __init__ wiring)
    eng = InferenceEngine("preset:debug", template="vanilla", max_seq_len=128)
    eng.cfg = config_from_hf(hf_cfg)
    eng.params = convert_hf_state_dict(model.state_dict(), eng.cfg)

    prompt = [5, 17, 23, 99, 140, 7]
    with torch.no_grad():
        hf_out = model.generate(
            torch.tensor([prompt]), max_new_tokens=12, do_sample=False,
            eos_token_id=2, pad_token_id=2,
        )[0].tolist()[len(prompt):]
    # exact match is safe here: tests always run on the CPU backend
    # (conftest); bf16-vs-fp32 argmax near-ties could flip cross-backend
    ours = eng.generate(prompt, max_new_tokens=12)
    # HF stops AFTER emitting eos; ours stops before returning it
    hf_trimmed = hf_out[:-1] if hf_out and hf_out[-1] == 2 else hf_out
    assert ours == hf_trimmed, (ours, hf_trimmed)
