"""Golden-token parity of the template registry vs the reference implementation.

Goldens were produced by executing the reference's template module against the
same deterministic fake tokenizer (tests/goldens/gen_goldens.py); these tests
pin our re-implementation to identical token streams for all 18 templates.
"""

import json
import os

import pytest

from datatunerx_tpu.data.templates import get_template, list_templates
from datatunerx_tpu.data.preprocess import encode_supervised_example
from datatunerx_tpu.training.loss import IGNORE_INDEX
from fake_tokenizer import FakeTokenizer

GOLDENS = json.load(
    open(os.path.join(os.path.dirname(__file__), "goldens", "templates.json"))
)


def _case_args(case):
    history = [tuple(h) for h in case["history"]] if case["history"] else None
    return case["query"], case["response"], history, case["system"]


def test_all_reference_templates_present():
    assert sorted(GOLDENS["templates"]) == list_templates()


@pytest.mark.parametrize("name", sorted(GOLDENS["templates"]))
@pytest.mark.parametrize("case", GOLDENS["cases"], ids=lambda c: c["id"])
def test_template_matches_reference(name, case):
    golden = GOLDENS["templates"][name][case["id"]]
    tok = FakeTokenizer()
    template = get_template(name, tok)
    q, r, h, s = _case_args(case)

    pairs = template.encode_turns(tok, q, r, h, s)
    assert [[list(a), list(b)] for a, b in pairs] == golden["pairs"]

    prompt, answer = template.encode_oneturn(tok, q, r, h, s)
    assert [list(prompt), list(answer)] == golden["oneturn"]

    assert tok.special_tokens_map == golden["specials"]


def test_supervised_masking_semantics():
    """Reference cmd/tuning/train.py:73-117: prompt masked, response trained."""
    tok = FakeTokenizer()
    template = get_template("llama2", tok)
    ids, labels = encode_supervised_example(
        template, tok, "hello", "world", cutoff_len=1024
    )
    assert len(ids) == len(labels)
    pairs = template.encode_turns(tok, "hello", "world")
    (src, tgt), = pairs
    assert labels[: len(src)] == [IGNORE_INDEX] * len(src)
    assert labels[len(src):] == tgt
    assert ids == src + tgt


def test_supervised_proportional_truncation():
    tok = FakeTokenizer()
    template = get_template("vanilla", tok)
    long_q = "q" * 300
    long_r = "r" * 100
    ids, labels = encode_supervised_example(
        template, tok, long_q, long_r, cutoff_len=100
    )
    assert len(ids) <= 100
    n_src = sum(1 for l in labels if l == IGNORE_INDEX)
    n_tgt = len(labels) - n_src
    # proportional split: source gets ~3/4 of the budget
    assert 70 <= n_src <= 78 and 20 <= n_tgt <= 28, (n_src, n_tgt)


def test_supervised_efficient_eos_multiturn():
    """efficient_eos: later turns carry eos as first label of the source span;
    one final eos appended (reference train.py:97-106)."""
    tok = FakeTokenizer()
    template = get_template("chatml", tok)
    ids, labels = encode_supervised_example(
        template, tok, "b", "B", history=[("a", "A")], cutoff_len=1024
    )
    assert ids[-1] == tok.eos_token_id and labels[-1] == tok.eos_token_id
    pairs = template.encode_turns(tok, "b", "B", history=[("a", "A")])
    (s0, t0), (s1, t1) = pairs
    # second turn's source span starts with eos in the labels
    idx = len(s0) + len(t0)
    assert labels[idx] == tok.eos_token_id
    assert labels[idx + 1 : idx + len(s1)] == [IGNORE_INDEX] * (len(s1) - 1)


def test_unknown_template_raises():
    with pytest.raises(KeyError):
        get_template("nope")
