"""Quantization correctness: pack/dequant math, XLA matmuls, Pallas kernels
(interpret mode on CPU) vs the XLA reference, and the QLoRA training path."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from datatunerx_tpu.models.config import ModelConfig
from datatunerx_tpu.models.llama import forward, init_params
from datatunerx_tpu.ops.quant import (
    NF4_CODE,
    dequant_int8,
    dequant_nf4,
    matmul_int8,
    matmul_nf4,
    nf4_scales,
    quantize_int8,
    quantize_nf4,
    quantize_model_params,
)

CFG = ModelConfig(
    vocab_size=256, hidden_size=64, intermediate_size=128, num_layers=2,
    num_heads=4, num_kv_heads=2, max_seq_len=64, remat="none",
)


def _w(rng, shape, scale=0.05):
    return jnp.asarray(rng.normal(size=shape, scale=scale), jnp.float32)


def test_int8_roundtrip_error():
    rng = np.random.default_rng(0)
    w = _w(rng, (128, 64))
    qw = quantize_int8(w)
    assert qw["q"].dtype == jnp.int8
    deq = dequant_int8(qw["q"], qw["scale"])
    err = np.abs(np.asarray(deq - w))
    per_chan_max = np.max(np.abs(np.asarray(w)), axis=0)
    assert (err.max(axis=0) <= per_chan_max / 127 * 1.01).all()


def test_int8_matmul_matches_dequant():
    rng = np.random.default_rng(1)
    w = _w(rng, (64, 96))
    x = _w(rng, (8, 64), scale=1.0)
    qw = quantize_int8(w)
    ref = x @ dequant_int8(qw["q"], qw["scale"])
    out = matmul_int8(x, qw["q"], qw["scale"])
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-4)


def test_nf4_roundtrip_error():
    rng = np.random.default_rng(2)
    w = _w(rng, (128, 64))
    qw = quantize_nf4(w)
    assert qw["packed"].dtype == jnp.uint8
    assert qw["packed"].shape == (128 * 64 // 64, 32)
    deq = dequant_nf4(qw, (128, 64))
    # nf4 max error per block <= scale * max code gap (~0.14) + double-quant slack
    scales = np.asarray(nf4_scales(qw))
    blocks_err = np.abs(np.asarray(deq - w)).T.reshape(-1, 64)
    gap = np.max(np.diff(NF4_CODE)) / 2
    assert (blocks_err.max(axis=1) <= scales * gap * 1.2 + 1e-3).all()


def test_nf4_codebook_values_exact():
    # weights already equal to code values * scale must roundtrip exactly
    scale = 0.07
    w = jnp.asarray(np.tile(NF4_CODE * scale, 8).reshape(2, 64).T, jnp.float32)
    qw = quantize_nf4(w)
    deq = dequant_nf4(qw, (64, 2))
    np.testing.assert_allclose(np.asarray(deq), np.asarray(w), atol=scale / 120)


def test_nf4_matmul_matches_dequant():
    rng = np.random.default_rng(3)
    w = _w(rng, (64, 96))
    x = _w(rng, (8, 64), scale=1.0)
    qw = quantize_nf4(w)
    ref = x @ dequant_nf4(qw, (64, 96))
    out = matmul_nf4(x, qw, (64, 96))
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-4)


@pytest.mark.parametrize("mode", ["int8", "int4"])
def test_pallas_kernels_match_xla(mode):
    from datatunerx_tpu.ops.pallas_quant import pallas_matmul_int8, pallas_matmul_nf4

    rng = np.random.default_rng(4)
    K, N = 128, 256
    w = _w(rng, (K, N))
    x = _w(rng, (4, 40, K), scale=1.0)  # M=160: exercises row padding
    if mode == "int8":
        qw = quantize_int8(w)
        ref = matmul_int8(x, qw["q"], qw["scale"])
        out = pallas_matmul_int8(x, qw["q"], qw["scale"], block_m=64, block_n=128)
    else:
        qw = quantize_nf4(w)
        ref = matmul_nf4(x, qw, (K, N))
        out = pallas_matmul_nf4(x, qw, (K, N), block_m=64, block_n=128)
    assert out.shape == ref.shape
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-3, rtol=2e-3)


def test_pallas_nf4_odd_chunk_k():
    """Real-model K values (5632, 11008) are not 128·64-multiples: with
    K=1408 the kernel runs 2 chunks of 11 blocks — odd blocks-per-chunk and
    a multi-step K grid, the shape class the chunk-major layout exists for."""
    from datatunerx_tpu.ops.pallas_quant import _pick_chunk, pallas_matmul_nf4

    K, N = 1408, 128
    assert _pick_chunk(K // 64, 64) == 11 * 64
    rng = np.random.default_rng(11)
    w = _w(rng, (K, N))
    x = _w(rng, (24, K), scale=1.0)
    qw = quantize_nf4(w)
    ref = matmul_nf4(x, qw, (K, N))
    out = pallas_matmul_nf4(x, qw, (K, N), block_m=64, block_n=128)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-3, rtol=2e-3)


@pytest.mark.parametrize("mode", ["int8", "int4"])
def test_quantized_forward_close_to_full(mode):
    import dataclasses

    params = init_params(CFG, jax.random.PRNGKey(0))
    toks = jnp.asarray(np.random.default_rng(5).integers(0, 256, (2, 16), np.int32))
    full, _ = forward(params, toks, CFG)

    qcfg = dataclasses.replace(CFG, quantization=mode)
    qparams = quantize_model_params(params, mode)
    quant, _ = forward(qparams, toks, qcfg)
    # quantized logits track full-precision within loose tolerance
    corr = np.corrcoef(np.asarray(full).ravel(), np.asarray(quant).ravel())[0, 1]
    assert corr > 0.99, corr


def test_qlora_training_decreases_loss():
    """QLoRA: frozen quantized base + trainable adapters (reference
    bnb int4 + peft path, cmd/tuning/train.py:224-280)."""
    import dataclasses

    from datatunerx_tpu.training import TrainConfig, Trainer
    from datatunerx_tpu.training.loss import IGNORE_INDEX

    qcfg = dataclasses.replace(CFG, quantization="int4")
    params = quantize_model_params(init_params(CFG, jax.random.PRNGKey(0)), "int4")
    tr = Trainer(qcfg, TrainConfig(
        finetuning_type="lora", lora_rank=4, lora_dropout=0.0,
        learning_rate=3e-2, scheduler="constant", total_steps=30,
        compute_dtype=None,
    ))
    state = tr.init_state(params, jax.random.PRNGKey(1))
    rng = np.random.default_rng(6)
    toks = rng.integers(4, 256, (4, 16)).astype(np.int32)
    labels = toks.copy()
    labels[:, :4] = IGNORE_INDEX
    batch = {"input_ids": jnp.asarray(toks), "labels": jnp.asarray(labels)}
    losses = []
    for _ in range(20):
        state, m = tr.train_step(state, batch)
        losses.append(float(m["loss"]))
    assert losses[-1] < losses[0] - 0.2, losses
    # base stayed quantized (no kernel materialized in state)
    assert "quant" in state.params["layers"]["q_proj"]


def test_stacked_quantize_matches_per_layer():
    """quantize_model_params' one-dispatch stacked path must be bit-identical
    to the per-matrix reference functions (searchsorted-on-midpoints ==
    16-way argmin, including tie behavior)."""
    import numpy as np

    import jax
    import jax.numpy as jnp

    from datatunerx_tpu.ops.quant import (
        _quantize_int8_stacked,
        _quantize_nf4_stacked,
        quantize_int8,
        quantize_nf4,
    )

    kern = jax.random.normal(jax.random.PRNGKey(3), (3, 128, 64), jnp.float32)
    st = _quantize_nf4_stacked(kern)
    for i in range(3):
        ref = quantize_nf4(kern[i])
        # stacked layout stores flat bytes per layer (tile-padding-free);
        # same bytes, same order as the per-matrix [nb, b/2] format
        np.testing.assert_array_equal(np.asarray(st["packed"][i]),
                                      np.asarray(ref["packed"]).reshape(-1))
        np.testing.assert_array_equal(np.asarray(st["scale_q"][i]),
                                      np.asarray(ref["scale_q"]))
        np.testing.assert_allclose(np.asarray(st["meta"][i]),
                                   np.asarray(ref["meta"]), rtol=1e-7)
    st8 = _quantize_int8_stacked(kern)
    for i in range(3):
        ref8 = quantize_int8(kern[i])
        np.testing.assert_array_equal(np.asarray(st8["q"][i]),
                                      np.asarray(ref8["q"]))
        # jit fusion may reorder the absmax reduction: 1-ulp scale drift ok
        np.testing.assert_allclose(np.asarray(st8["scale"][i]),
                                   np.asarray(ref8["scale"]), rtol=1e-6)


def test_pallas_quant_kernels_differentiate():
    """QLoRA training through the fused kernels: grads w.r.t. x must match
    the XLA reference path (the custom_vjp backward is dx = g @ Wᵀ on
    dequantized weights; frozen base gets no grads)."""
    import numpy as np

    import jax
    import jax.numpy as jnp

    from datatunerx_tpu.ops.pallas_quant import (
        pallas_matmul_int8,
        pallas_matmul_nf4,
    )

    rng = np.random.default_rng(6)
    K, N = 128, 256
    w = _w(rng, (K, N))
    x = jnp.asarray(rng.standard_normal((8, K)), jnp.float32)

    q8 = quantize_int8(w)
    g_pallas = jax.grad(lambda x: jnp.sum(
        pallas_matmul_int8(x, q8["q"], q8["scale"], block_m=64, block_n=128) ** 2
    ))(x)
    g_ref = jax.grad(lambda x: jnp.sum(
        matmul_int8(x, q8["q"], q8["scale"]) ** 2))(x)
    np.testing.assert_allclose(np.asarray(g_pallas), np.asarray(g_ref),
                               atol=1e-2, rtol=1e-2)

    q4 = quantize_nf4(w)
    g_pallas = jax.grad(lambda x: jnp.sum(
        pallas_matmul_nf4(x, q4, (K, N), block_m=64, block_n=128) ** 2))(x)
    g_ref = jax.grad(lambda x: jnp.sum(matmul_nf4(x, q4, (K, N)) ** 2))(x)
    np.testing.assert_allclose(np.asarray(g_pallas), np.asarray(g_ref),
                               atol=1e-2, rtol=1e-2)


def test_pallas_nf4_transposed_kernel_matches_reference():
    """The fused dx kernel (g @ Wᵀ with per-tile dequant, round-3): exact
    against the XLA dequant product across N-tile accumulation (nn > 1),
    non-128·64-multiple K, and row padding."""
    import numpy as np

    import jax.numpy as jnp

    from datatunerx_tpu.ops.pallas_quant import _pallas_matmul_nf4_t_impl

    rng = np.random.default_rng(11)
    for K, N, M in ((320, 256, 8), (384, 512, 33), (128, 384, 64)):
        w = _w(rng, (K, N))
        q4 = quantize_nf4(w)
        wd = np.asarray(dequant_nf4(q4, (K, N)))
        g = jnp.asarray(rng.standard_normal((M, N)), jnp.float32)
        dx = _pallas_matmul_nf4_t_impl(g, q4, (K, N),
                                       block_m=32, block_n=128)
        ref = np.asarray(g) @ wd.T
        np.testing.assert_allclose(np.asarray(dx), ref, atol=1e-3, rtol=1e-3)
