"""Experiment plane units: shared slice pool (mesh gang-fit), elastic
scheduler (preempt/resume via the orbax restore path, score-aware
priorities), continuous-scoring watcher (leaderboard, early stop) and the
scoring-controller bridge.

CPU-only: FakeTrainingBackend drives job transitions; the one orbax test
saves/restores a tiny pytree to prove preemption records the real
checkpoint step a resumed job would restore from.
"""

import json
import threading

import pytest

from datatunerx_tpu.experiment.metrics import ExperimentMetrics
from datatunerx_tpu.experiment.pool import PoolSlice, SharedSlicePool, mesh_fits
from datatunerx_tpu.experiment.scheduler import (
    FAILED,
    PENDING,
    PREEMPTED,
    RUNNING,
    STOPPED,
    SUCCEEDED,
    SliceScheduler,
)
from datatunerx_tpu.experiment.watcher import (
    ContinuousScoringWatcher,
    Leaderboard,
    scoring_cr_score,
)
from datatunerx_tpu.operator.backends import FakeTrainingBackend

EIGHT = {"meshShape": "dp=8"}  # needs all 8 chips of a 2x4 slice
ANY = {}  # absorbs into whatever slice it gets


def make_sched(slices=("s0", "s1"), chips=8, metrics=None, probe=None):
    pool = SharedSlicePool([PoolSlice(n, chips=chips) for n in slices])
    backend = FakeTrainingBackend()
    kw = {}
    if probe is not None:
        kw["checkpoint_probe"] = probe
    sched = SliceScheduler(pool, backend, metrics=metrics, **kw)
    return sched, backend, pool


# ------------------------------------------------------------------- pool
def test_mesh_gang_fit_uses_trainer_mesh_parser():
    assert mesh_fits(EIGHT, 8)
    assert not mesh_fits(EIGHT, 4)  # dp=8 cannot tile 4 chips
    assert mesh_fits({"meshShape": "dp=2,tp=2"}, 4)
    assert not mesh_fits({"meshShape": "dp=3"}, 8)  # 3 doesn't tile 8
    assert mesh_fits(ANY, 8)  # absent meshShape absorbs


def test_pool_acquires_smallest_fitting_slice_and_releases():
    pool = SharedSlicePool([PoolSlice("big", chips=16),
                            PoolSlice("small", chips=8)])
    assert pool.acquire("flex", ANY).name == "small"  # smallest fit wins
    pool.release("flex")
    s = pool.acquire("job-a", EIGHT)
    assert s.name == "small"  # gang-fit is EXACT tiling: dp=8 ∉ 16 chips
    assert pool.acquire("job-a", EIGHT).name == "small"  # idempotent
    assert pool.acquire("job-b", EIGHT) is None  # big can't tile dp=8
    s2 = pool.acquire("job-b", {"meshShape": "dp=8,fsdp=2"})
    assert s2.name == "big"
    pool.release("job-a")
    assert pool.acquire("job-c", EIGHT).name == "small"


def test_pool_remove_slice_reports_displaced_holder():
    pool = SharedSlicePool([PoolSlice("s0"), PoolSlice("s1")])
    pool.acquire("job-a", ANY)
    assert pool.remove_slice("missing") is None
    held = pool.assignment("job-a").name
    other = "s1" if held == "s0" else "s0"
    assert pool.remove_slice(other) is None  # free slice: nobody displaced
    assert pool.remove_slice(held) == "job-a"
    assert pool.size() == 0


# -------------------------------------------------------------- scheduler
def test_scheduler_admits_up_to_pool_capacity():
    sched, backend, _ = make_sched()
    for n in ("job-a", "job-b", "job-c"):
        sched.add_job(n, {"parameters": EIGHT})
    events = sched.tick()
    assert [e["event"] for e in events] == ["started", "started"]
    states = {j.name: j.state for j in sched.jobs()}
    assert sorted(s for s in states.values()) == [PENDING, RUNNING, RUNNING]
    assert set(backend.jobs) == {e["job"] for e in events}
    # a job finishing frees its slice for the pending one
    running = [n for n, s in states.items() if s == RUNNING]
    backend.set_state(running[0], "Succeeded")
    events = sched.tick()
    kinds = {e["event"] for e in events}
    assert kinds == {"succeeded", "started"}
    assert sched.job(running[0]).state == SUCCEEDED
    assert all(j.state in (RUNNING, SUCCEEDED) for j in sched.jobs())


def test_scheduler_failure_is_terminal_and_frees_slice():
    sched, backend, pool = make_sched(slices=("s0",))
    sched.add_job("job-a", {"parameters": EIGHT})
    sched.add_job("job-b", {"parameters": EIGHT})
    sched.tick()
    backend.set_state("job-a", "Failed")
    sched.tick()
    assert sched.job("job-a").state == FAILED
    assert sched.job("job-b").state == RUNNING
    assert pool.holder_of("s0") == "job-b"


def test_preempt_and_resume_via_orbax_restore_path(tmp_path):
    """Preemption records the job's latest ORBAX step — probed through the
    trainer's CheckpointManager — and the resumed submission carries it;
    the saved state actually restores through the same manager (the path a
    resumed trainer takes)."""
    import numpy as np

    from datatunerx_tpu.training.checkpoint import CheckpointManager

    ckpt_dir = str(tmp_path / "ckpts")
    state = {"w": np.arange(4, dtype=np.float32)}
    mngr = CheckpointManager(ckpt_dir, save_interval_steps=1)
    assert mngr.maybe_save(state, step=2, force=True)
    mngr.close()

    sched, backend, pool = make_sched(slices=("s0",))
    sched.add_job("job-a", {"parameters": EIGHT, "checkpoint_dir": ckpt_dir})
    sched.tick()
    assert sched.job("job-a").state == RUNNING

    step = sched.preempt("job-a")
    assert step == 2
    job = sched.job("job-a")
    assert job.state == PREEMPTED and job.preemptions == 1
    assert "job-a" in backend.deleted
    assert pool.holder_of("s0") is None

    events = sched.tick()  # slice is free again: the job resumes
    assert events[0]["event"] == "resumed"
    assert events[0]["resume_step"] == 2
    assert job.state == RUNNING and job.resumes == 1
    assert backend.jobs["job-a"]["env"]["DTX_RESUME_FROM_STEP"] == "2"

    # the restore path the resumed trainer takes hands the state back
    mngr = CheckpointManager(ckpt_dir)
    restored, got_step = mngr.restore(state)
    mngr.close()
    assert got_step == 2
    np.testing.assert_array_equal(np.asarray(restored["w"]), state["w"])


def test_shrink_preempts_holder_and_leader_evicts_lower_priority():
    em = ExperimentMetrics()
    sched, backend, pool = make_sched(metrics=em, probe=lambda j: None)
    sched.add_job("leader", {"parameters": EIGHT})
    sched.add_job("loser", {"parameters": EIGHT})
    sched.tick()
    sched.set_score("leader", 90.0)
    sched.set_score("loser", 10.0)
    doomed = pool.assignment("leader").name
    displaced = sched.shrink(doomed)
    assert displaced == "leader"
    assert sched.job("leader").state == PREEMPTED
    assert pool.size() == 1
    # next tick: the displaced leader outranks the running loser and takes
    # its slice back (score-aware eviction)
    events = sched.tick()
    kinds = [e["event"] for e in events]
    assert "evicted" in kinds and "resumed" in kinds
    assert sched.job("loser").state == PREEMPTED
    assert sched.job("leader").state == RUNNING
    assert em.registry.counter("dtx_experiment_preemptions_total").get() == 2


def test_eviction_requires_victims_slice_to_fit_contender():
    """A displaced leader must not evict a job whose slice its mesh can't
    tile — that would burn the victim's checkpoint interval for nothing
    and thrash it every tick."""
    pool = SharedSlicePool([PoolSlice("small", chips=4)])
    backend = FakeTrainingBackend()
    sched = SliceScheduler(pool, backend, checkpoint_probe=lambda j: None)
    sched.add_job("loser", {"parameters": {"meshShape": "dp=4"}})
    sched.tick()
    sched.set_score("loser", 10.0)
    # leader needs 8 chips; the only running job holds a 4-chip slice
    sched.add_job("leader", {"parameters": EIGHT})
    sched.set_score("leader", 90.0)
    for _ in range(3):
        events = sched.tick()
        assert all(e["event"] != "evicted" for e in events)
    assert sched.job("loser").state == RUNNING
    assert sched.job("loser").preemptions == 0
    assert sched.job("leader").state == PENDING


def test_resume_marker_never_leaks_into_later_submissions():
    """The env copy handed to the backend must not alias job.spec: a
    resume step recorded once must not reappear on a later submission the
    scheduler didn't decide (probe came back None)."""
    steps = iter([7, None])
    sched, backend, _ = make_sched(slices=("s0",),
                                   probe=lambda j: next(steps))
    original_env = {"KEEP": "1"}
    sched.add_job("job-a", {"parameters": EIGHT, "env": original_env})
    sched.tick()
    sched.preempt("job-a")  # probe -> 7
    sched.tick()
    assert backend.jobs["job-a"]["env"]["DTX_RESUME_FROM_STEP"] == "7"
    assert "DTX_RESUME_FROM_STEP" not in original_env  # spec not mutated
    sched.preempt("job-a")  # probe -> None: no step this time
    sched.tick()
    assert "DTX_RESUME_FROM_STEP" not in backend.jobs["job-a"]["env"]
    assert backend.jobs["job-a"]["env"]["KEEP"] == "1"


def test_unscored_job_never_evicts_a_runner():
    sched, backend, pool = make_sched(slices=("s0",), probe=lambda j: None)
    sched.add_job("runner", {"parameters": EIGHT})
    sched.tick()
    sched.set_score("runner", 5.0)
    sched.add_job("newcomer", {"parameters": EIGHT})
    events = sched.tick()
    assert all(e["event"] != "evicted" for e in events)
    assert sched.job("newcomer").state == PENDING


# ---------------------------------------------------------------- watcher
def drive_watcher(feeds, margin=None, min_evals=2):
    """feeds: {job: {step: score}} revealed one step per tick."""
    em = ExperimentMetrics()
    sched, backend, _ = make_sched(slices=("s0", "s1", "s2"), metrics=em)
    for name in feeds:
        sched.add_job(name, {"parameters": ANY})
    sched.tick()
    revealed = {n: 0 for n in feeds}

    def checkpoints(job):
        return [s for s in sorted(feeds[job.name]) if s <= revealed[job.name]]

    def score(job, step):
        return feeds[job.name][step]

    w = ContinuousScoringWatcher(sched, checkpoints, score,
                                 board=Leaderboard(), metrics=em,
                                 early_stop_margin=margin,
                                 min_evals=min_evals)
    return sched, w, em, revealed


def test_watcher_scores_new_checkpoints_into_leaderboard():
    sched, w, em, revealed = drive_watcher(
        {"a": {1: 50.0, 2: 60.0}, "b": {1: 40.0, 2: 45.0}})
    assert w.tick() == []  # nothing revealed yet
    revealed["a"] = revealed["b"] = 1
    events = w.tick()
    assert {(e["job"], e["step"]) for e in events} == {("a", 1), ("b", 1)}
    assert w.tick() == []  # already scored: no re-scoring
    revealed["a"] = revealed["b"] = 2
    w.tick()
    board = w.board
    assert board.leader().job == "a" and board.leader().score == 60.0
    assert board.entry("b").history == [(1, 40.0), (2, 45.0)]
    assert sched.job("a").score == 60.0  # priorities fed
    assert em.registry.gauge("dtx_experiment_best_score").get() == 60.0
    assert em.registry.counter("dtx_experiment_evals_total").get() == 4


def test_watcher_early_stops_clear_loser_and_frees_slice():
    sched, w, em, revealed = drive_watcher(
        {"a": {1: 80.0, 2: 85.0}, "b": {1: 20.0, 2: 22.0}},
        margin=30.0, min_evals=2)
    revealed["a"] = revealed["b"] = 1
    assert all(e["event"] != "early_stop" for e in w.tick())  # 1 eval < min
    revealed["a"] = revealed["b"] = 2
    events = w.tick()
    stops = [e for e in events if e["event"] == "early_stop"]
    assert [e["job"] for e in stops] == ["b"]
    assert sched.job("b").state == STOPPED
    assert sched.job("b").stop_reason == "early_stop"
    assert sched.pool.assignment("b") is None
    assert em.registry.counter("dtx_experiment_early_stops_total").get() == 1
    # the leader is never early-stopped, scores notwithstanding
    assert sched.job("a").state == RUNNING


def test_watcher_retries_unready_endpoint_next_tick():
    calls = []

    sched, backend, _ = make_sched(slices=("s0",))
    sched.add_job("a", {"parameters": ANY})
    sched.tick()

    def score(job, step):
        calls.append(step)
        return None if len(calls) == 1 else 42.0

    w = ContinuousScoringWatcher(sched, lambda j: [1], score)
    assert w.tick() == []  # endpoint not ready: skipped, NOT marked scored
    events = w.tick()
    assert events[0]["score"] == 42.0
    assert calls == [1, 1]


# ------------------------------------------------- scoring-controller bridge
def test_scoring_cr_bridge_drives_existing_controller():
    """scoring_cr_score creates a Scoring CR and reconciles it through the
    EXISTING ScoringController against a live /chat/completions endpoint —
    the generative-eval path the continuous watcher uses in production."""
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    from datatunerx_tpu.operator.store import ObjectStore
    from datatunerx_tpu.scoring.controller import ScoringController

    class Chat(BaseHTTPRequestHandler):
        def do_POST(self):
            body = json.dumps({"choices": [{"message": {
                "role": "assistant", "content": "Paris"}}]}).encode()
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *a):
            pass

    srv = ThreadingHTTPServer(("127.0.0.1", 0), Chat)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    try:
        url = f"http://127.0.0.1:{srv.server_port}/chat/completions"
        store = ObjectStore()
        score = scoring_cr_score(
            store, ScoringController(timeout=5.0), "exp-a-step1", url,
            probes=[{"prompt": "Capital of France?", "reference": "Paris"}])
        assert score == 100.0
    finally:
        srv.shutdown()


def test_poll_interval_resolved_at_construction(monkeypatch):
    """DTX_EXPERIMENT_POLL_S is read when the controller is BUILT, not at
    import — operators/tests override it without a module reload."""
    from datatunerx_tpu.operator.finetuneexperiment_controller import (
        FinetuneExperimentController,
    )

    monkeypatch.setenv("DTX_EXPERIMENT_POLL_S", "0.321")
    assert FinetuneExperimentController().poll_s == pytest.approx(0.321)
    monkeypatch.delenv("DTX_EXPERIMENT_POLL_S")
    assert FinetuneExperimentController().poll_s == 5.0
    assert FinetuneExperimentController(poll_s=1.5).poll_s == 1.5
