"""Deviceless AOT compile path (scripts/aot_certify.py) regression guard.

Certifies, at debug scale, that the topology-based AOT pipeline this repo's
TPU compile evidence rests on keeps working: get_topology_desc for a v5e
target, Mosaic lowering of a Pallas kernel with the interpret gate forced
off, and a full train step lowered/compiled for the TPU target with cost +
memory analysis available. Runs in a subprocess because the AOT flow needs
DTX_PALLAS_INTERPRET=0 and a topology client registered before model code
traces — state that must not leak into the CPU-mesh suite process.
"""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_PROBE = r"""
import json
import os
os.environ["DTX_PALLAS_INTERPRET"] = "0"
os.environ.setdefault("JAX_PLATFORMS", "cpu")
import jax
jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
from jax.experimental import topologies
from jax.sharding import SingleDeviceSharding

topo = topologies.get_topology_desc(platform="tpu", topology_name="v5e:2x2")
dev = topo.devices[0]
sh = SingleDeviceSharding(dev)

# 1) a Pallas kernel must actually lower through Mosaic, not interpret mode
from datatunerx_tpu.ops.flash_attention import flash_attention
q = jax.ShapeDtypeStruct((1, 256, 4, 64), jnp.bfloat16, sharding=sh)
lo = jax.jit(lambda q, k, v: flash_attention(q, k, v)).lower(q, q, q)
assert "tpu_custom_call" in lo.as_text(), "flash kernel not Mosaic-lowered"
lo.compile()

# 2) a full debug train step compiles for the TPU target with analyses
import sys
sys.path.insert(0, os.environ["DTX_REPO"])
from scripts.aot_certify import _lora_cfg, _single_chip_step, _cost, _memory
from datatunerx_tpu.models import get_config

cfg = get_config("debug", attention_impl="flash", remat="full")
compiled = _single_chip_step(cfg, _lora_cfg(), 2, 128, dev)
cost, mem = _cost(compiled), _memory(compiled)
assert cost["flops"] and cost["bytes_accessed"], cost
assert mem["peak_bytes"] > 0, mem
print(json.dumps({"ok": True, "cost": cost, "peak": mem["peak_bytes"]}))
"""


@pytest.mark.slow
def test_aot_pipeline_compiles_for_v5e_target():
    env = dict(os.environ, DTX_REPO=REPO,
               PYTHONPATH=REPO + os.pathsep + os.environ.get("PYTHONPATH", ""))
    # a fresh interpreter: sitecustomize must not have bound the axon client
    # to a device before jax_platforms flips to cpu
    out = subprocess.run([sys.executable, "-c", _PROBE], env=env,
                         capture_output=True, text=True, timeout=900)
    assert out.returncode == 0, out.stderr[-2000:]
    doc = json.loads(out.stdout.strip().splitlines()[-1])
    assert doc["ok"] is True
