"""Trainer tests: LoRA math, grad-accum exactness, freeze masking, GSPMD parity.

SURVEY.md §4: the reference has zero tests; these cover the semantics its stack
delegated to peft/HF/DeepSpeed — adapter init, masked loss, accumulation — plus
the multi-device sharding the reference never tested at all.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from datatunerx_tpu.models.config import ModelConfig
from datatunerx_tpu.models.llama import forward, init_params
from datatunerx_tpu.models.lora import init_lora_params, lora_scaling, merge_lora
from datatunerx_tpu.parallel.mesh import make_mesh
from datatunerx_tpu.training.loss import IGNORE_INDEX, causal_lm_loss
from datatunerx_tpu.training.train_lib import TrainConfig, Trainer

CFG = ModelConfig(
    vocab_size=128, hidden_size=32, intermediate_size=64, num_layers=2,
    num_heads=4, num_kv_heads=2, max_seq_len=64, remat="none",
)


def _batch(rng, B=4, T=16, accum=None):
    toks = rng.integers(4, 128, size=(B, T)).astype(np.int32)
    labels = toks.copy()
    labels[:, : T // 4] = IGNORE_INDEX  # mask a "prompt" prefix
    b = {"input_ids": jnp.asarray(toks), "labels": jnp.asarray(labels)}
    if accum:
        b = {k: v.reshape(accum, B // accum, T) for k, v in b.items()}
    return b


def test_lora_init_is_identity():
    params = init_params(CFG, jax.random.PRNGKey(0))
    lora = init_lora_params(CFG, jax.random.PRNGKey(1), rank=4)
    toks = jnp.asarray(np.random.default_rng(0).integers(0, 128, (2, 8), np.int32))
    base, _ = forward(params, toks, CFG)
    with_lora, _ = forward(params, toks, CFG, lora=(lora, lora_scaling(32, 4)))
    np.testing.assert_allclose(np.asarray(base), np.asarray(with_lora), atol=1e-6)


def test_lora_merge_matches_adapter_forward():
    params = init_params(CFG, jax.random.PRNGKey(0))
    lora = init_lora_params(CFG, jax.random.PRNGKey(1), rank=4,
                            targets=("q_proj", "v_proj", "down_proj"))
    # make B nonzero so the delta is real
    lora = jax.tree_util.tree_map(
        lambda x: x + 0.01 * jax.random.normal(jax.random.PRNGKey(2), x.shape), lora
    )
    s = lora_scaling(32, 4)
    toks = jnp.asarray(np.random.default_rng(0).integers(0, 128, (2, 8), np.int32))
    adapter, _ = forward(params, toks, CFG, lora=(lora, s))
    merged, _ = forward(merge_lora(params, lora, s), toks, CFG)
    np.testing.assert_allclose(np.asarray(adapter), np.asarray(merged), atol=1e-4)


def test_loss_ignores_masked_tokens():
    logits = jnp.zeros((1, 5, 16), jnp.float32)
    labels = jnp.asarray([[IGNORE_INDEX, 1, IGNORE_INDEX, 2, 3]])
    s, n = causal_lm_loss(logits, labels)
    assert int(n) == 3  # labels[1:] -> [1, IGNORE, 2, 3]
    np.testing.assert_allclose(float(s) / int(n), np.log(16), rtol=1e-5)


def _make_trainer(**kw):
    defaults = dict(
        finetuning_type="lora", lora_rank=4, lora_dropout=0.0,
        learning_rate=1e-2, scheduler="constant", optimizer="adamw",
        total_steps=50, compute_dtype=None,
    )
    defaults.update(kw)
    return Trainer(CFG, TrainConfig(**defaults))


@pytest.mark.parametrize("ftype", ["lora", "full"])
def test_loss_decreases(ftype):
    lr = 3e-2 if ftype == "lora" else 5e-3  # rank-4 q/v adapters need a hot lr
    tr = _make_trainer(finetuning_type=ftype, learning_rate=lr,
                       lora_targets=("q_proj", "v_proj", "gate_proj", "down_proj"))
    params = init_params(CFG, jax.random.PRNGKey(0))
    state = tr.init_state(params, jax.random.PRNGKey(42))
    batch = _batch(np.random.default_rng(0))
    losses = []
    for _ in range(30):
        state, m = tr.train_step(state, batch)
        losses.append(float(m["loss"]))
    assert losses[-1] < losses[0] - 0.3, losses


def test_grad_accum_matches_full_batch():
    tr1 = _make_trainer(grad_accum=1)
    tr2 = _make_trainer(grad_accum=2)
    params = init_params(CFG, jax.random.PRNGKey(0))
    import jax.numpy as _jnp
    s1 = tr1.init_state(jax.tree_util.tree_map(_jnp.copy, params), jax.random.PRNGKey(7))
    s2 = tr2.init_state(jax.tree_util.tree_map(_jnp.copy, params), jax.random.PRNGKey(7))
    rng = np.random.default_rng(3)
    full = _batch(rng, B=4, T=16)
    micro = {k: v.reshape(2, 2, 16) for k, v in full.items()}
    s1, m1 = tr1.train_step(s1, full)
    s2, m2 = tr2.train_step(s2, micro)
    np.testing.assert_allclose(float(m1["loss"]), float(m2["loss"]), rtol=1e-5)
    for a, b in zip(jax.tree_util.tree_leaves(s1.lora), jax.tree_util.tree_leaves(s2.lora)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-5)


def test_freeze_only_updates_selected_layers():
    tr = _make_trainer(finetuning_type="freeze", num_layer_trainable=1,
                       name_module_trainable="mlp", learning_rate=1e-2)
    params = init_params(CFG, jax.random.PRNGKey(0))
    state = tr.init_state(params, jax.random.PRNGKey(9))
    before = jax.tree_util.tree_map(np.asarray, state.params)
    state, _ = tr.train_step(state, _batch(np.random.default_rng(1)))
    after = jax.tree_util.tree_map(np.asarray, state.params)

    # embed unchanged
    np.testing.assert_array_equal(
        before["embed_tokens"]["embedding"], after["embed_tokens"]["embedding"]
    )
    gate_b, gate_a = before["layers"]["gate_proj"]["kernel"], after["layers"]["gate_proj"]["kernel"]
    # layer 0 frozen, layer 1 (last) trained
    np.testing.assert_array_equal(gate_b[0], gate_a[0])
    assert np.abs(gate_b[1] - gate_a[1]).max() > 0
    # attention untouched in mlp mode
    np.testing.assert_array_equal(
        before["layers"]["q_proj"]["kernel"], after["layers"]["q_proj"]["kernel"]
    )


@pytest.mark.parametrize("shape", [(4, 1, 2, 1), (1, 4, 2, 1), (2, 2, 2, 1)])
def test_sharded_training_matches_single_device(shape, devices8):
    batch = _batch(np.random.default_rng(5), B=8, T=16)
    params = init_params(CFG, jax.random.PRNGKey(0))

    ref_tr = _make_trainer()
    ref_state = ref_tr.init_state(jax.tree_util.tree_map(jnp.copy, params), jax.random.PRNGKey(11))
    ref_state, ref_m = ref_tr.train_step(ref_state, batch)
    ref_state, ref_m2 = ref_tr.train_step(ref_state, batch)

    mesh = make_mesh(shape)
    tr = _make_trainer()
    tr.mesh = mesh
    state = tr.init_state(jax.tree_util.tree_map(jnp.copy, params), jax.random.PRNGKey(11))
    state, m = tr.train_step(state, batch)
    state, m2 = tr.train_step(state, batch)

    np.testing.assert_allclose(float(ref_m["loss"]), float(m["loss"]), rtol=1e-5)
    np.testing.assert_allclose(float(ref_m2["loss"]), float(m2["loss"]), rtol=1e-4)
    for a, b in zip(
        jax.tree_util.tree_leaves(ref_state.lora), jax.tree_util.tree_leaves(state.lora)
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-5)


def test_full_param_fsdp_sharding(devices8):
    """Full-param training with params+opt state sharded (ZeRO-3 equivalent)."""
    mesh = make_mesh((1, 8, 1, 1))
    tr = _make_trainer(finetuning_type="full", learning_rate=1e-3)
    tr.mesh = mesh
    params = init_params(CFG, jax.random.PRNGKey(0))
    state = tr.init_state(params, jax.random.PRNGKey(3))
    batch = _batch(np.random.default_rng(2), B=8, T=16)
    losses = []
    for _ in range(6):
        state, m = tr.train_step(state, batch)
        losses.append(float(m["loss"]))
    assert losses[-1] < losses[0], losses
    # params actually sharded over fsdp axis
    kern = state.params["layers"]["q_proj"]["kernel"]
    assert kern.sharding.spec[1] == "fsdp", kern.sharding.spec


def test_sharded_grad_accum(devices8):
    """Regression: accumulation axis must NOT be sharded over data axes."""
    mesh = make_mesh((2, 2, 2, 1))
    full = _batch(np.random.default_rng(8), B=8, T=16)
    micro = {k: v.reshape(2, 4, 16) for k, v in full.items()}

    ref = _make_trainer(grad_accum=2)
    s_ref = ref.init_state(init_params(CFG, jax.random.PRNGKey(0)), jax.random.PRNGKey(13))
    s_ref, m_ref = ref.train_step(s_ref, micro)

    tr = _make_trainer(grad_accum=2)
    tr.mesh = mesh
    s = tr.init_state(init_params(CFG, jax.random.PRNGKey(0)), jax.random.PRNGKey(13))
    s, m = tr.train_step(s, micro)

    np.testing.assert_allclose(float(m_ref["loss"]), float(m["loss"]), rtol=1e-5)
    for a, b in zip(jax.tree_util.tree_leaves(s_ref.lora), jax.tree_util.tree_leaves(s.lora)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-5)


def test_freeze_opt_state_skips_frozen_leaves():
    """Frozen leaves (embed, norms, attn in mlp mode) get no AdamW moments."""
    tr = _make_trainer(finetuning_type="freeze", name_module_trainable="mlp")
    params = init_params(CFG, jax.random.PRNGKey(0))
    state = tr.init_state(params, jax.random.PRNGKey(1))
    n_params = len(jax.tree_util.tree_leaves(params))
    n_opt = len(jax.tree_util.tree_leaves(state.opt_state))
    # moments only for gate/up/down kernels (3 leaves x mu+nu + counts) — far
    # fewer arrays than 2x all params
    assert n_opt < n_params, (n_opt, n_params)


def test_full_param_step_preserves_param_dtype():
    """One full-param train step must keep bf16 params bf16: a bare
    params+updates add promotes to fp32 (updates are fp32), silently
    doubling the state and breaking train-step buffer donation — caught by
    AOT buffer-assignment analysis (scripts/aot_certify.py, round 5)."""
    import jax
    import jax.numpy as jnp

    from datatunerx_tpu.models import get_config, init_params
    from datatunerx_tpu.training import TrainConfig, Trainer

    cfg = get_config("debug", attention_impl="xla", remat="none")
    tr = Trainer(cfg, TrainConfig(finetuning_type="full",
                                  compute_dtype=jnp.bfloat16))
    params = init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.bfloat16)
    state = tr.init_state(params, jax.random.PRNGKey(1))
    toks = jax.random.randint(jax.random.PRNGKey(2), (2, 16), 0,
                              cfg.vocab_size, jnp.int32)
    state2, _ = tr.train_step(state, {"input_ids": toks, "labels": toks})
    before = jax.tree_util.tree_map(lambda x: x.dtype, state.params)
    after = jax.tree_util.tree_map(lambda x: x.dtype, state2.params)
    assert before == after, "param dtypes drifted after one step"


def test_step_program_memo_shares_compiled_steps():
    """Equal (model_cfg, train_cfg, mesh) trainers share one jitted step —
    N trainers in a process compile each distinct program once (and on
    jax 0.4.x, where the persistent compilation cache is unusable, this is
    the only cross-trainer compile reuse there is)."""
    a = _make_trainer()
    b = _make_trainer()
    assert a._train_step is b._train_step
    assert a._eval_step is b._eval_step
    c = _make_trainer(lora_rank=8)  # different program: no sharing
    assert c._train_step is not a._train_step
    # and the shared program still trains: results equal across instances
    params = init_params(CFG, jax.random.PRNGKey(0))
    import jax.numpy as _jnp
    sa = a.init_state(jax.tree_util.tree_map(_jnp.copy, params),
                      jax.random.PRNGKey(3))
    sb = b.init_state(jax.tree_util.tree_map(_jnp.copy, params),
                      jax.random.PRNGKey(3))
    batch = _batch(np.random.default_rng(1))
    _, ma = a.train_step(sa, batch)
    _, mb = b.train_step(sb, batch)
    assert float(ma["loss"]) == float(mb["loss"])
