"""HA webhook certs (VERDICT r3 missing #1 / next-round #6): the CA +
serving cert live in one Secret shared by every controller-manager replica —
boot converges N replicas on ONE CA via optimistic concurrency, ongoing
rotation is gated on the election leader, standbys hot-reload the shared
chain, and a leader crash mid-rotation never leaves admission returning cert
errors (the promoted standby re-asserts the current CA).

Reference parity: the cert-rotator keeps its certs in a Secret that HA
manager replicas share (reference
cmd/controller-manager/app/controller_manager.go:72-111).
"""

import datetime
import ssl
import threading
import time

import pytest

from datatunerx_tpu.operator.kubeclient import ApiError, KubeClient
from datatunerx_tpu.operator.webhook_server import (
    AdmissionWebhookServer,
    SecretBackedCertManager,
    install_webhooks,
)
from tests.fake_apiserver import FakeKubeApiServer

GROUP_CORE = "core.datatunerx.io"
NS = "dtx-system"
SECRET = "dtx-webhook-server-cert"


@pytest.fixture()
def apiserver():
    srv = FakeKubeApiServer().start()
    yield srv
    srv.stop()


@pytest.fixture()
def client(apiserver):
    return KubeClient(base_url=apiserver.url)


def _cm(client, tmp_path, sub, **kw):
    # TLS cert generation needs the optional `cryptography` dep (dev extra);
    # skip — not error — where it's absent
    pytest.importorskip("cryptography")
    return SecretBackedCertManager(
        client, namespace=NS, secret_name=SECRET,
        cert_dir=str(tmp_path / sub),
        dns_names=["localhost", "127.0.0.1"], **kw)


def _read(path):
    with open(path, "rb") as f:
        return f.read()


def _hp(name, params):
    return {
        "apiVersion": f"{GROUP_CORE}/v1beta1",
        "kind": "Hyperparameter",
        "metadata": {"name": name, "namespace": "default"},
        "spec": {"parameters": params},
    }


def _assert_admission_enforced(client, suffix):
    """A valid CR lands (with defaults applied) and an invalid one is denied
    by the webhook — i.e. the TLS path to the webhook server is healthy in
    both directions. Any cert error would surface as a 500 'webhook call
    failed', not a 400 denial."""
    created = client.request(
        "POST",
        f"/apis/{GROUP_CORE}/v1beta1/namespaces/default/hyperparameters",
        body=_hp(f"ok-{suffix}", {"scheduler": "linear"}),
    )
    assert created["spec"]["parameters"]["optimizer"] == "adamw"
    with pytest.raises(ApiError) as ei:
        client.request(
            "POST",
            f"/apis/{GROUP_CORE}/v1beta1/namespaces/default/hyperparameters",
            body=_hp(f"bad-{suffix}", {"loRA_Dropout": "2.0"}),
        )
    assert ei.value.status == 400
    assert "loRA_Dropout" in ei.value.body


# -------------------------------------------------------------- convergence

def test_fresh_install_replicas_converge_on_one_ca(client, tmp_path):
    """N replicas booting against an empty cluster race to create the
    Secret; exactly one generation wins and every replica ends up serving
    the winner's chain."""
    managers = [_cm(client, tmp_path, f"r{i}") for i in range(3)]
    results = [None] * 3

    def boot(i):
        results[i] = managers[i].ensure(as_leader=True)

    threads = [threading.Thread(target=boot, args=(i,)) for i in range(3)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()

    assert all(r is True for r in results)  # every dir was (re)materialized
    cas = {_read(m.ca_path) for m in managers}
    certs = {_read(m.cert_path) for m in managers}
    assert len(cas) == 1 and len(certs) == 1
    sec = client.get("", "v1", "secrets", NS, SECRET)
    import base64

    assert base64.b64decode(sec["data"]["ca.crt"]) == cas.pop()


def test_standby_never_generates(client, tmp_path):
    standby = _cm(client, tmp_path, "standby")
    assert standby.ensure(as_leader=False) is False
    with pytest.raises(ApiError):
        client.get("", "v1", "secrets", NS, SECRET)  # still absent

    leader = _cm(client, tmp_path, "leader")
    assert leader.ensure(as_leader=True) is True
    # the standby now adopts the leader's chain without generating
    assert standby.ensure(as_leader=False) is True
    assert _read(standby.ca_path) == _read(leader.ca_path)
    assert standby.ensure(as_leader=False) is False  # converged: no churn


def test_secret_rotation_is_leader_gated(client, tmp_path):
    leader = _cm(client, tmp_path, "leader")
    standby = _cm(client, tmp_path, "standby")
    assert leader.ensure(as_leader=True) is True
    assert standby.ensure(as_leader=False) is True
    old_ca = _read(standby.ca_path)

    # push both into the refresh margin: the standby must NOT rotate
    for m in (leader, standby):
        m.refresh_margin = datetime.timedelta(days=9999)
    assert standby.needs_rotation()
    assert standby.ensure(as_leader=False) is False  # stale but not leader
    assert _read(standby.ca_path) == old_ca

    assert leader.ensure(as_leader=True) is True  # leader rotates the Secret
    leader.refresh_margin = datetime.timedelta(days=30)
    standby.refresh_margin = datetime.timedelta(days=30)
    assert standby.ensure(as_leader=False) is True  # standby hot-adopts
    new_ca = _read(standby.ca_path)
    assert new_ca != old_ca
    assert new_ca == _read(leader.ca_path)


# ------------------------------------------------- serving + failover e2e

def test_standby_rotation_loop_hot_reloads_tls(client, tmp_path):
    """A standby's rotation loop picks up the leader's new Secret and
    reloads its TLS context in place — new handshakes serve the new chain."""
    leader = _cm(client, tmp_path, "leader")
    leader.ensure(as_leader=True)
    standby_cm = _cm(client, tmp_path, "standby")
    standby = AdmissionWebhookServer(standby_cm, host="127.0.0.1", port=0)
    standby.start(rotation_check_s=0.05, is_leader=lambda: False)
    try:
        assert _read(standby_cm.ca_path) == _read(leader.ca_path)

        def _served_cert():
            ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
            ctx.check_hostname = False
            ctx.verify_mode = ssl.CERT_NONE
            import socket

            with socket.create_connection(("127.0.0.1", standby.port),
                                          timeout=5) as s:
                with ctx.wrap_socket(s) as tls:
                    return tls.getpeercert(binary_form=True)

        before = _served_cert()
        leader.refresh_margin = datetime.timedelta(days=9999)
        assert leader.ensure(as_leader=True) is True  # rotate the Secret
        deadline = time.time() + 10
        while time.time() < deadline:
            if _read(standby_cm.ca_path) == _read(leader.ca_path) \
                    and _served_cert() != before:
                break
            time.sleep(0.05)
        assert _read(standby_cm.ca_path) == _read(leader.ca_path)
        assert _served_cert() != before  # live TLS reload, no restart
    finally:
        standby.stop()


def test_leader_killed_mid_rotation_failover_keeps_admission_green(
        client, tmp_path):
    """The VERDICT r3 #6 failover scenario: the leader rotates the Secret
    and dies BEFORE re-patching the caBundle. The promoted standby converges
    on the new Secret, reloads TLS, re-asserts the current CA into the
    webhook configs (manager._reassert_ca on promotion), and admission never
    returns cert errors."""
    leader_cm = _cm(client, tmp_path, "leader")
    leader = AdmissionWebhookServer(leader_cm, host="127.0.0.1", port=0)
    leader.start()
    standby_cm = _cm(client, tmp_path, "standby")
    standby = AdmissionWebhookServer(standby_cm, host="127.0.0.1", port=0)
    standby.start(rotation_check_s=0.05, is_leader=lambda: False)
    try:
        install_webhooks(client, leader_cm.ca_bundle_b64(),
                         f"https://localhost:{leader.port}")
        _assert_admission_enforced(client, "pre")

        # leader rotates the Secret ... and crashes before install_webhooks
        leader_cm.refresh_margin = datetime.timedelta(days=9999)
        assert leader_cm.ensure(as_leader=True) is True
        leader.stop()  # killed mid-rotation: caBundle still carries old CA

        # promotion: what manager.py's leader callback does on takeover —
        # converge on the Secret, reload TLS, re-assert the CURRENT CA
        # (routing follows the Service to the surviving replica; url-style
        # here, so the re-install also points at the standby's port)
        standby_cm.refresh_margin = datetime.timedelta(days=30)
        standby_cm.ensure(as_leader=True)
        standby._ssl_ctx.load_cert_chain(standby_cm.cert_path,
                                         standby_cm.key_path)
        install_webhooks(client, standby_cm.ca_bundle_b64(),
                         f"https://localhost:{standby.port}")

        _assert_admission_enforced(client, "post")
    finally:
        standby.stop()
        leader.stop()


# --------------------------------------------------------------- install.py

def test_install_renders_ha_deployment(tmp_path):
    from datatunerx_tpu.operator.install import (
        CERT_SECRET,
        render_install_manifests,
    )

    docs = render_install_manifests(namespace="dtx-ha", replicas=2)
    dep = next(d for d in docs if d["kind"] == "Deployment")
    assert dep["spec"]["replicas"] == 2
    args = dep["spec"]["template"]["spec"]["containers"][0]["args"]
    # replicas>1 forces the election on — never two active cert rotators
    assert "--leader-elect=true" in args
    assert f"--webhook-cert-secret={CERT_SECRET}" in args
    assert "--webhook-service-namespace=dtx-ha" in args

    role = next(d for d in docs if d["kind"] == "ClusterRole")
    secret_rules = [r for r in role["rules"]
                    if "secrets" in r.get("resources", [])]
    assert secret_rules and \
        {"create", "get", "update"} <= set(secret_rules[0]["verbs"])


def test_install_ha_bundle_applies_and_managers_share_ca(client, tmp_path):
    """Apply the HA bundle to the fake apiserver, then boot two
    Secret-backed cert managers the way two replicas would: one CA."""
    pytest.importorskip("cryptography")
    from datatunerx_tpu.operator.install import install

    lines = install(client, namespace="dtx-ha", replicas=2)
    assert any(line.startswith("deployment/") for line in lines)

    a = SecretBackedCertManager(client, namespace="dtx-ha",
                                secret_name=SECRET,
                                cert_dir=str(tmp_path / "a"),
                                dns_names=["localhost"])
    b = SecretBackedCertManager(client, namespace="dtx-ha",
                                secret_name=SECRET,
                                cert_dir=str(tmp_path / "b"),
                                dns_names=["localhost"])
    assert a.ensure(as_leader=True) is True
    assert b.ensure(as_leader=True) is False or \
        _read(b.ca_path) == _read(a.ca_path)
    assert _read(a.ca_path) == _read(b.ca_path)
