"""Lease-based leader election (VERDICT round-1 missing item 3: controller
HA): single winner, renewal holds the lease, takeover after expiry,
lost-leadership callback."""

import threading
import time

from datatunerx_tpu.operator.kubeclient import KubeClient
from datatunerx_tpu.operator.leaderelection import (
    LEASE_GROUP,
    LEASE_PLURAL,
    LEASE_VERSION,
    LeaderElector,
)
from tests.fake_apiserver import FakeKubeApiServer


def _cluster():
    srv = FakeKubeApiServer().start()
    return srv, KubeClient(base_url=srv.url)


def test_single_winner_and_renewal():
    srv, client = _cluster()
    try:
        a = LeaderElector(client, identity="a", lease_duration_s=2,
                          renew_period_s=0.05)
        b = LeaderElector(client, identity="b", lease_duration_s=2,
                          renew_period_s=0.05)
        assert a.try_acquire_or_renew() is True
        assert b.try_acquire_or_renew() is False  # held and fresh
        assert a.try_acquire_or_renew() is True   # renewal succeeds
        lease = client.get(LEASE_GROUP, LEASE_VERSION, LEASE_PLURAL,
                           "default", a.lease_name)
        assert lease["spec"]["holderIdentity"] == "a"
        assert lease["spec"]["leaseTransitions"] == 0
    finally:
        srv.stop()


def test_takeover_after_expiry():
    srv, client = _cluster()
    try:
        a = LeaderElector(client, identity="a", lease_duration_s=0.2,
                          renew_period_s=0.05)
        b = LeaderElector(client, identity="b", lease_duration_s=0.2,
                          renew_period_s=0.05)
        assert a.try_acquire_or_renew()
        time.sleep(0.4)  # a stops renewing; lease expires
        assert b.try_acquire_or_renew() is True
        lease = client.get(LEASE_GROUP, LEASE_VERSION, LEASE_PLURAL,
                           "default", b.lease_name)
        assert lease["spec"]["holderIdentity"] == "b"
        assert lease["spec"]["leaseTransitions"] == 1
        # a's next renew discovers the loss
        assert a.try_acquire_or_renew() is False
    finally:
        srv.stop()


def test_run_loop_callbacks_on_loss():
    srv, client = _cluster()
    try:
        events = []
        a = LeaderElector(
            client, identity="a", lease_duration_s=0.3, renew_period_s=0.05,
            on_started_leading=lambda: events.append("started"),
            on_stopped_leading=lambda: events.append("stopped"),
        )
        a.start()
        deadline = time.time() + 5
        while "started" not in events and time.time() < deadline:
            time.sleep(0.02)
        assert a.is_leader and events == ["started"]

        # usurper grabs the lease by force (simulates this replica pausing
        # past the lease duration, e.g. a long GC or network partition)
        lease = client.get(LEASE_GROUP, LEASE_VERSION, LEASE_PLURAL,
                           "default", a.lease_name)
        lease["spec"]["holderIdentity"] = "b"
        lease["spec"]["renewTime"] = "2099-01-01T00:00:00.000000Z"
        client.replace(LEASE_GROUP, LEASE_VERSION, LEASE_PLURAL, "default",
                       a.lease_name, lease)
        deadline = time.time() + 5
        while "stopped" not in events and time.time() < deadline:
            time.sleep(0.02)
        assert events == ["started", "stopped"]
        assert not a.is_leader
    finally:
        a.stop()
        srv.stop()


def test_two_elector_failover_end_to_end():
    """Replica A leads; A dies; replica B takes over within a lease window."""
    srv, client = _cluster()
    try:
        stop_a = threading.Event()
        a = LeaderElector(client, identity="a", lease_duration_s=0.4,
                          renew_period_s=0.1)
        b = LeaderElector(client, identity="b", lease_duration_s=0.4,
                          renew_period_s=0.1)
        ta = threading.Thread(target=a.run, args=(stop_a,), daemon=True)
        ta.start()
        deadline = time.time() + 5
        while not a.is_leader and time.time() < deadline:
            time.sleep(0.02)
        assert a.is_leader
        b.start()  # joins the election second; must NOT grab the held lease
        time.sleep(0.3)
        assert not b.is_leader

        stop_a.set()  # replica A dies (stops renewing)
        deadline = time.time() + 5
        while not b.is_leader and time.time() < deadline:
            time.sleep(0.02)
        assert b.is_leader
    finally:
        b.stop()
        srv.stop()


def test_leader_abdicates_when_apiserver_unreachable():
    """Renew failures past the lease duration must fire on_stopped_leading —
    holding leadership through a partition is split-brain."""
    srv, client = _cluster()
    events = []
    a = LeaderElector(
        client, identity="a", lease_duration_s=0.3, renew_period_s=0.05,
        on_started_leading=lambda: events.append("started"),
        on_stopped_leading=lambda: events.append("stopped"),
    )
    a.start()
    deadline = time.time() + 5
    while "started" not in events and time.time() < deadline:
        time.sleep(0.02)
    assert a.is_leader
    srv.stop()  # apiserver partition: every renew now errors
    deadline = time.time() + 5
    while "stopped" not in events and time.time() < deadline:
        time.sleep(0.02)
    a.stop()
    assert events == ["started", "stopped"]
    assert not a.is_leader
