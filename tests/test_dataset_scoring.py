"""Dataset-driven scoring (VERDICT round-1 item 7): score a served model over
a real eval split (≥100 examples), generation and perplexity metrics, wired
through the Scoring controller."""

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from datatunerx_tpu.operator.api import Dataset, ObjectMeta, Scoring
from datatunerx_tpu.operator.store import ObjectStore
from datatunerx_tpu.scoring.controller import ScoringController
from datatunerx_tpu.scoring.dataset_scoring import (
    columns_from_dataset_spec,
    load_eval_records,
    score_dataset,
    split_file_from_dataset_spec,
)
from datatunerx_tpu.utils import storage


def _dataset_spec(test_file, features=None):
    return {"datasetMetadata": {"datasetInfo": {
        "subsets": [{"splits": {
            "train": {"file": "/nope/train.csv"},
            "test": {"file": test_file},
        }}],
        "features": features or [],
    }}}


@pytest.fixture()
def eval_split():
    import fsspec

    rows = ["q,a"] + [f"question {i},answer {i}" for i in range(120)]
    storage.write_text("memory://ds/test.csv", "\n".join(rows))
    yield "memory://ds/test.csv"
    fs = fsspec.filesystem("memory")
    for p in list(fs.store):
        fs.store.pop(p, None)


class _EchoServer:
    """Fake serving endpoint: /chat/completions echoes 'answer <i>' when the
    prompt contains i (perfect model); /perplexity returns fixed NLL."""

    def __init__(self):
        outer = self

        class H(BaseHTTPRequestHandler):
            def do_POST(self):
                n = int(self.headers.get("Content-Length", 0))
                req = json.loads(self.rfile.read(n))
                outer.calls.append(self.path)
                if self.path == "/perplexity":
                    ntok = len(req["completion"].split())
                    body = {"nll_sum": 0.5 * ntok, "num_tokens": ntok}
                else:
                    prompt = req["messages"][0]["content"]
                    idx = prompt.split()[-1]
                    body = {"choices": [{"message": {
                        "role": "assistant", "content": f"answer {idx}"}}]}
                data = json.dumps(body).encode()
                self.send_response(200)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def log_message(self, *a):
                pass

        self.calls = []
        self.srv = ThreadingHTTPServer(("127.0.0.1", 0), H)
        self.thread = threading.Thread(target=self.srv.serve_forever,
                                       daemon=True)
        self.thread.start()

    @property
    def url(self):
        return f"http://127.0.0.1:{self.srv.server_port}/chat/completions"

    def stop(self):
        self.srv.shutdown()
        self.srv.server_close()
        self.thread.join(timeout=5)


def test_split_and_column_extraction(eval_split):
    spec = _dataset_spec(eval_split, features=[
        {"name": "instruction", "mapTo": "q"},
        {"name": "response", "mapTo": "a"},
    ])
    assert split_file_from_dataset_spec(spec) == eval_split
    assert columns_from_dataset_spec(spec) == {"q": "instruction", "a": "response"}
    records = load_eval_records(spec, max_examples=100)
    assert len(records) == 100
    assert records[0] == {"prompt": "question 0", "reference": "answer 0"}


def test_validate_split_fallback():
    spec = {"datasetMetadata": {"datasetInfo": {"subsets": [{"splits": {
        "validate": {"file": "/v.csv"}}}]}}}
    assert split_file_from_dataset_spec(spec) == "/v.csv"
    assert split_file_from_dataset_spec({"datasetMetadata": {}}) is None


def test_generation_scoring_over_split(eval_split):
    spec = _dataset_spec(eval_split, features=[
        {"name": "instruction", "mapTo": "q"},
        {"name": "response", "mapTo": "a"},
    ])
    srv = _EchoServer()
    try:
        result = score_dataset(srv.url, spec, metric="generation",
                               max_examples=100)
    finally:
        srv.stop()
    # perfect echo model → perfect rouge-l → score 100
    assert result["score"] == "100.0"
    assert result["details"]["examples"] == 100
    assert result["details"]["rouge-l"] == 1.0


def test_perplexity_scoring_over_split(eval_split):
    import math

    spec = _dataset_spec(eval_split, features=[
        {"name": "instruction", "mapTo": "q"},
        {"name": "response", "mapTo": "a"},
    ])
    srv = _EchoServer()
    try:
        result = score_dataset(srv.url, spec, metric="perplexity",
                               max_examples=50)
    finally:
        srv.stop()
    assert any(c == "/perplexity" for c in srv.calls)
    # fixed mean NLL 0.5 → score = 100·e^-0.5, ppl = e^0.5
    assert abs(float(result["score"]) - 100 * math.exp(-0.5)) < 0.01
    assert abs(result["details"]["perplexity"] - math.exp(0.5)) < 1e-9


def test_controller_dataset_scoring_e2e(eval_split):
    store = ObjectStore()
    store.create(Dataset(
        metadata=ObjectMeta(name="ds-eval"),
        spec=_dataset_spec(eval_split, features=[
            {"name": "instruction", "mapTo": "q"},
            {"name": "response", "mapTo": "a"},
        ]),
    ))
    srv = _EchoServer()
    sc = Scoring(metadata=ObjectMeta(name="s-ds"),
                 spec={"inferenceService": srv.url, "datasetRef": "ds-eval"})
    store.create(sc)
    try:
        res = ScoringController(timeout=10).reconcile(store, store.get(Scoring, "s-ds"))
    finally:
        srv.stop()
    assert res is None
    got = store.get(Scoring, "s-ds")
    assert got.status["score"] == "100.0"
    assert got.status["details"]["examples"] == 100


def test_controller_dataset_missing_retries():
    store = ObjectStore()
    sc = Scoring(metadata=ObjectMeta(name="s-miss"),
                 spec={"inferenceService": "http://x/chat/completions",
                       "datasetRef": "absent"})
    store.create(sc)
    res = ScoringController(timeout=1).reconcile(store, store.get(Scoring, "s-miss"))
    from datatunerx_tpu.scoring.controller import RETRY_S

    assert res is not None and res.requeue_after == RETRY_S
    assert "not found" in store.get(Scoring, "s-miss").status["lastError"]


def test_controller_bad_metric_permanent():
    store = ObjectStore()
    sc = Scoring(metadata=ObjectMeta(name="s-bad"),
                 spec={"inferenceService": "http://x/chat/completions",
                       "datasetRef": "d", "metric": "vibes"})
    store.create(sc)
    res = ScoringController(timeout=1).reconcile(store, store.get(Scoring, "s-bad"))
    assert res is None
    assert "invalid scoring spec" in store.get(Scoring, "s-bad").status["error"]


def test_engine_perplexity_sanity():
    """Real-engine NLL: correct token count, finite ppl, and the engine's own
    greedy continuation scores no worse than a mismatched completion."""
    from datatunerx_tpu.serving.engine import InferenceEngine

    eng = InferenceEngine("preset:debug", template="vanilla", max_seq_len=256)
    tok = eng.tokenizer
    prompt = tok.encode("the quick brown")
    greedy = eng.generate(prompt, max_new_tokens=6)
    if not greedy:
        pytest.skip("debug model immediately emitted eos")
    r1 = eng.perplexity(prompt, greedy)
    assert r1["num_tokens"] == len(greedy)
    assert 0 < r1["perplexity"] < float("inf")
    # a shuffled/wrong completion of the same length can't beat greedy
    wrong = list(reversed(greedy)) if len(greedy) > 1 else [greedy[0] + 1]
    r2 = eng.perplexity(prompt, wrong)
    assert r1["mean_nll"] <= r2["mean_nll"] + 1e-6
