"""Unified observability plane (datatunerx_tpu/obs, PR 7).

Three contracts under test:

  spans    — lifecycle (open→close, nesting, orphan reap), the bounded
             trace ring (MRU eviction, per-trace span cap, JSONL log),
             and the engine bridge that folds scheduler timelines into
             per-request spans with true TTFT/TPOT.
  metrics  — MS_BUCKETS histogram bucket math and exposition round-trip
             through the PR 2 parser; the serving/gateway /metrics now
             built from ONE registry (build info, uptime, latency
             histograms all in a single valid exposition).
  end2end  — GET /debug/trace/<id> on the gateway returns the merged
             gateway→replica→engine timeline for both in-process and
             HTTP replicas, and tracing is decode-invisible: enabled vs
             disabled engines emit token-exact outputs.
"""

import json
import threading
import urllib.request
from http.server import ThreadingHTTPServer

import pytest

from datatunerx_tpu.obs.metrics import (
    MS_BUCKETS,
    Histogram,
    Registry,
    set_build_info,
    set_uptime,
)
from datatunerx_tpu.obs.trace import (
    Span,
    Tracer,
    TraceStore,
    build_request_span,
)
from tests.test_prometheus_exposition import parse_exposition


# ------------------------------------------------------------- histograms

def test_ms_buckets_histogram_bucket_math():
    h = Histogram("t_ms", buckets=MS_BUCKETS)
    for v in (0.4, 2.0, 9.9, 10.0, 600.0, 50_000.0):
        h.observe(v)
    samples, types = parse_exposition(
        "\n".join(h.expose()) + "\n")
    assert types["t_ms"] == "histogram"
    # cumulative counts at the edges the observes straddle
    assert samples[("t_ms_bucket", (("le", "1.0"),))] == 1
    assert samples[("t_ms_bucket", (("le", "2.5"),))] == 2
    # 10.0 lands IN the le=10 bucket (le is inclusive)
    assert samples[("t_ms_bucket", (("le", "10.0"),))] == 4
    assert samples[("t_ms_bucket", (("le", "1000.0"),))] == 5
    assert samples[("t_ms_bucket", (("le", "+Inf"),))] == 6
    assert samples[("t_ms_count", ())] == 6
    assert samples[("t_ms_sum", ())] == pytest.approx(50622.3)


def test_registry_shared_across_planes_single_exposition():
    reg = Registry()
    set_build_info(reg, "serving")
    set_uptime(reg, "serving")
    reg.histogram("dtx_serving_ttft_ms", buckets=MS_BUCKETS).observe(12.0)
    samples, types = parse_exposition(reg.expose())
    assert types["dtx_build_info"] == "gauge"
    assert types["dtx_serving_uptime_seconds"] == "gauge"
    assert types["dtx_serving_ttft_ms"] == "histogram"
    key = next(k for k in samples if k[0] == "dtx_build_info")
    assert ("plane", "serving") in key[1]


def test_registry_returns_same_metric_object():
    reg = Registry()
    assert reg.counter("a_total") is reg.counter("a_total")
    assert reg.histogram("b_ms") is reg.histogram("b_ms")


# ------------------------------------------------------------------ spans

def test_span_lifecycle_nesting_and_store():
    store = TraceStore()
    tracer = Tracer(store=store)
    with tracer.span("outer", trace_id="t1") as outer:
        outer.event("hello", k=1)
        with tracer.span("inner") as inner:  # inherits t1 via contextvar
            assert inner.trace_id == "t1"
            assert inner.parent == "outer"
    doc = store.get("t1")
    names = {s["name"]: s for s in doc["spans"]}
    assert set(names) == {"outer", "inner"}
    assert names["outer"]["status"] == "ok"
    assert names["outer"]["duration_ms"] >= 0
    assert names["outer"]["events"][0]["name"] == "hello"
    assert tracer.open_count() == 0


def test_span_error_status_on_exception():
    tracer = Tracer()
    with pytest.raises(RuntimeError):
        with tracer.span("boom", trace_id="t2"):
            raise RuntimeError("kaput")
    doc = tracer.store.get("t2")
    assert doc["spans"][0]["status"] == "error"
    assert "kaput" in doc["spans"][0]["attrs"]["error"]


def test_explicit_start_finish_no_contextvar_leak():
    tracer = Tracer()
    sp = tracer.start("gen", trace_id="t3", parent=None)
    # explicit spans never install themselves as the ambient parent
    with tracer.span("other", trace_id="t4") as other:
        assert other.parent is None
    tracer.finish(sp)
    assert tracer.store.get("t3") is not None


def test_orphan_reap():
    tracer = Tracer(orphan_age_s=0.0)
    sp = tracer.start("leaked", trace_id="t5")
    assert tracer.open_count() == 1
    assert tracer.reap_orphans(max_age_s=0.0) == 1
    assert tracer.open_count() == 0
    doc = tracer.store.get("t5")
    assert doc["spans"][0]["status"] == "orphaned"
    # a request that outlived the reaper and then completed must not land
    # in the trace a second time
    tracer.finish(sp)
    assert len(tracer.store.get("t5")["spans"]) == 1


def test_trace_ring_eviction_and_span_cap():
    store = TraceStore(capacity=2, max_spans_per_trace=3)
    for tid in ("a", "b", "c"):
        store.add(Span("s", trace_id=tid).to_dict())
    # capacity 2: oldest trace evicted whole
    assert store.get("a") is None
    assert store.get("b") is not None and store.get("c") is not None
    assert store.evictions == 1
    # adding to an existing trace bumps it to MRU: "b" survives the next add
    store.add(Span("s2", trace_id="b").to_dict())
    store.add(Span("s", trace_id="d").to_dict())
    assert store.get("b") is not None
    assert store.get("c") is None
    # span cap: extra spans dropped, trace retained
    for i in range(5):
        store.add(Span(f"s{i}", trace_id="d").to_dict())
    assert len(store.get("d")["spans"]) == 3


def test_trace_store_jsonl_log(tmp_path):
    path = tmp_path / "spans.jsonl"
    store = TraceStore(jsonl_path=str(path))
    store.add(Span("one", trace_id="x").to_dict())
    store.add(Span("two", trace_id="y").to_dict())
    lines = [json.loads(ln) for ln in path.read_text().splitlines()]
    assert [ln["name"] for ln in lines] == ["one", "two"]


# ---------------------------------------------------------- engine bridge

def test_build_request_span_ttft_tpot_math():
    """Scripted admit/prefill/activate/decode sequence: the derived
    TTFT/TPOT must be the wall deltas of the scripted stamps."""
    t0 = 100.0
    timeline = [(t0 + 0.001, "admit", {"slot": 0, "mode": "chunked"}),
                (t0 + 0.010, "prefill", {"tokens": 64}),
                (t0 + 0.020, "prefill", {"tokens": 64}),
                (t0 + 0.025, "activate", {"slot": 0}),
                (t0 + 0.200, "finish", {"slot": 0})]
    first, last, n = t0 + 0.050, t0 + 0.170, 7
    span = build_request_span("tid", t0, timeline, first, last, n,
                              wall_submit_ms=1.7e12)
    assert span["trace_id"] == "tid"
    assert span["attrs"]["ttft_ms"] == pytest.approx(50.0)
    assert span["attrs"]["tpot_ms"] == pytest.approx(20.0)  # 120ms / 6
    assert span["attrs"]["n_tokens"] == 7
    # events sorted by offset; duration covers through the last stamp
    names = [e["name"] for e in span["events"]]
    assert names == ["admit", "prefill", "prefill", "activate",
                     "first_token", "finish"]
    assert span["duration_ms"] == pytest.approx(200.0)
    assert span["status"] == "ok"


def test_build_request_span_error_and_no_tokens():
    span = build_request_span("tid", 10.0, [(10.001, "admit", {})],
                              None, None, 0, wall_submit_ms=0.0,
                              error="device fault")
    assert span["status"] == "error"
    assert span["attrs"]["error"] == "device fault"
    assert "ttft_ms" not in span["attrs"]


# --------------------------------------------------------- engine tracing

MODEL = "preset:debug"


@pytest.fixture(scope="module")
def traced_engine():
    from datatunerx_tpu.serving.batched_engine import BatchedEngine

    eng = BatchedEngine(MODEL, template="vanilla", max_seq_len=256,
                        slots=2, decode_chunk=4)
    yield eng
    eng.close()


def test_engine_request_span_timeline(traced_engine):
    eng = traced_engine
    ids = eng.tokenizer.encode("observability plane test prompt")
    req = eng.submit(ids, max_new_tokens=6, trace_id="trace-eng-1")
    assert req.done.wait(timeout=120)
    doc = eng.trace_store.get("trace-eng-1")
    assert doc is not None
    span = doc["spans"][0]
    assert span["name"] == "engine.request"
    names = [e["name"] for e in span["events"]]
    assert names[0] == "admit"
    assert "first_token" in names and "finish" in names
    assert span["attrs"]["n_tokens"] == len(req.tokens)
    assert span["attrs"]["ttft_ms"] > 0
    assert span["attrs"]["tpot_ms"] > 0
    # the shared-registry histograms saw the same request
    assert eng.registry.histogram("dtx_serving_ttft_ms").count >= 1
    assert eng.registry.histogram("dtx_serving_tpot_ms").count >= 1


def test_engine_mints_trace_id_when_absent(traced_engine):
    eng = traced_engine
    ids = eng.tokenizer.encode("no id supplied")
    req = eng.submit(ids, max_new_tokens=3)
    assert req.done.wait(timeout=120)
    assert req.trace_id.startswith("dtx-")
    assert eng.trace_store.get(req.trace_id) is not None


def test_tracing_disabled_is_token_exact(traced_engine):
    """Side-by-side: a tracing-disabled engine must decode the exact same
    tokens (greedy) — instrumentation cannot perturb the model."""
    from datatunerx_tpu.serving.batched_engine import BatchedEngine

    eng_off = BatchedEngine(MODEL, template="vanilla", max_seq_len=256,
                            slots=2, decode_chunk=4, tracing=False)
    try:
        ids = traced_engine.tokenizer.encode(
            "the quick brown fox inspects the telemetry")
        out_on = traced_engine.generate(list(ids), max_new_tokens=12)
        out_off = eng_off.generate(list(ids), max_new_tokens=12)
        assert out_on == out_off
        assert len(eng_off.trace_store) == 0  # nothing recorded when off
    finally:
        eng_off.close()


def test_engine_chunked_prefill_span_events():
    """A chunked admission's span carries the prefill chunk events the PR 5
    sched_trace only kept in a test deque."""
    from datatunerx_tpu.serving.batched_engine import BatchedEngine

    eng = BatchedEngine(MODEL, template="vanilla", max_seq_len=256,
                        slots=2, decode_chunk=4, kv_block_size=16,
                        prefill_chunk=64, prefill_token_budget=64)
    try:
        ids = (eng.tokenizer.encode("long context ") * 40)[:150]
        req = eng.submit(ids, max_new_tokens=4, trace_id="trace-chunked")
        assert req.done.wait(timeout=120)
        span = eng.trace_store.get("trace-chunked")["spans"][0]
        names = [e["name"] for e in span["events"]]
        assert names[0] == "admit"
        assert names.count("prefill") >= 2  # 150 tokens / 64-chunk
        assert "activate" in names
        assert eng.registry.histogram(
            "dtx_serving_prefill_chunk_ms").count >= 2
    finally:
        eng.close()


# ------------------------------------------------- gateway /debug endpoints

class _TracedFakeEngine:
    """Duck-typed engine with the real trace plumbing: records an
    engine-side span per chat under the caller's trace id."""

    def __init__(self):
        self.trace_store = TraceStore()
        self.slots = 2
        self._slot_req = [None, None]
        self.prefill_stats = {"full": 0, "reuse": 0, "extend": 0}

    def chat(self, messages, trace_id="", **kw):
        self.trace_store.add(
            build_request_span(trace_id, 1.0,
                               [(1.001, "admit", {"slot": 0})],
                               1.05, 1.17, 7, wall_submit_ms=0.0))
        return "fake reply"


def _gateway(replicas):
    from datatunerx_tpu.gateway.replica_pool import ReplicaPool
    from datatunerx_tpu.gateway.server import Gateway

    return Gateway(ReplicaPool(replicas), model_name="preset:test")


def test_gateway_debug_trace_inprocess_merge():
    from datatunerx_tpu.gateway.replica_pool import InProcessReplica

    gw = _gateway([InProcessReplica("r0", _TracedFakeEngine())])
    try:
        out = gw.chat({"messages": [{"role": "user", "content": "hi"}]},
                      trace_id="t-merge")
        assert out == "fake reply"
        doc = gw.trace("t-merge")
        names = [s["name"] for s in doc["spans"]]
        assert "gateway.request" in names and "engine.request" in names
        engine_span = next(s for s in doc["spans"]
                           if s["name"] == "engine.request")
        assert engine_span["replica"] == "r0"
        assert engine_span["attrs"]["ttft_ms"] == pytest.approx(50.0)
        assert engine_span["attrs"]["tpot_ms"] == pytest.approx(20.0)
        gw_span = next(s for s in doc["spans"]
                       if s["name"] == "gateway.request")
        assert [e["name"] for e in gw_span["events"]][:2] == [
            "admitted", "route"]
        # queue-wait histogram observed exactly one admission
        assert gw.registry.histogram("dtx_gateway_queue_wait_ms").count == 1
    finally:
        gw.close()


@pytest.fixture()
def serving_http_url():
    """A real serving HTTP server (ThreadingHTTPServer + the serving
    Handler) fronting the traced fake engine — the HTTP-replica half."""
    from datatunerx_tpu.serving import server as serving

    old_engine, old_model = serving.STATE.engine, serving.STATE.model_path
    serving.STATE.engine = _TracedFakeEngine()
    serving.STATE.model_path = "preset:test"
    srv = ThreadingHTTPServer(("127.0.0.1", 0), serving.Handler)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    yield f"http://127.0.0.1:{srv.server_port}"
    srv.shutdown()
    serving.STATE.engine = old_engine
    serving.STATE.model_path = old_model


def test_gateway_debug_trace_http_replica_merge(serving_http_url):
    """End-to-end over HTTP: gateway → X-DTX-Trace-Id header → serving
    handler → engine trace ring → GET /debug/trace merge at the gateway."""
    from datatunerx_tpu.gateway.replica_pool import HTTPReplica
    from datatunerx_tpu.gateway.server import serve

    gw = _gateway([HTTPReplica("r0", serving_http_url)])
    srv = serve(gw, port=0, host="127.0.0.1")
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    url = f"http://127.0.0.1:{srv.server_port}"
    try:
        body = json.dumps(
            {"messages": [{"role": "user", "content": "hi"}]}).encode()
        req = urllib.request.Request(
            url + "/chat/completions", data=body,
            headers={"Content-Type": "application/json",
                     "X-DTX-Trace-Id": "t-http"}, method="POST")
        with urllib.request.urlopen(req, timeout=10) as r:
            assert r.headers["X-DTX-Trace-Id"] == "t-http"
        # replica half served by serving's own /debug/trace endpoint
        with urllib.request.urlopen(
                serving_http_url + "/debug/trace/t-http", timeout=10) as r:
            rdoc = json.load(r)
        assert rdoc["spans"][0]["name"] == "engine.request"
        # merged view at the gateway
        with urllib.request.urlopen(
                url + "/debug/trace/t-http", timeout=10) as r:
            doc = json.load(r)
        names = [s["name"] for s in doc["spans"]]
        assert "gateway.request" in names and "engine.request" in names
        # unknown id → 404
        with pytest.raises(urllib.error.HTTPError) as e:
            urllib.request.urlopen(url + "/debug/trace/nope", timeout=10)
        assert e.value.code == 404
    finally:
        srv.shutdown()
        gw.close()


def test_gateway_stream_failover_trace():
    """A mid-stream replica death shows up in the trace as a retry event
    with the resumption offset."""
    from datatunerx_tpu.gateway.replica_pool import InProcessReplica

    class DyingEngine:
        def chat_stream(self, messages, **kw):
            yield "hel"
            raise RuntimeError("replica died mid-stream")

    class HealthyEngine:
        def chat_stream(self, messages, **kw):
            yield "hello"
            yield " world"

    gw = _gateway([InProcessReplica("dying", DyingEngine()),
                   InProcessReplica("ok", HealthyEngine())])
    # force deterministic routing order: dying first
    gw.router.policy = "round_robin"
    try:
        text = "".join(gw.chat_stream(
            {"messages": [{"role": "user", "content": "hi"}]},
            trace_id="t-failover"))
        assert text == "hello world"
        span = next(s for s in gw.trace("t-failover")["spans"]
                    if s["name"] == "gateway.stream")
        events = [e["name"] for e in span["events"]]
        assert "retry" in events
        retry = next(e for e in span["events"] if e["name"] == "retry")
        assert retry["resumed_at_char"] == 3
        assert span["attrs"]["attempts"] == 2
    finally:
        gw.close()


def test_gateway_metrics_has_build_info_uptime_and_queue_wait():
    from datatunerx_tpu.gateway.replica_pool import InProcessReplica

    gw = _gateway([InProcessReplica("r0", _TracedFakeEngine())])
    try:
        gw.chat({"messages": [{"role": "user", "content": "hi"}]},
                trace_id="t-m")
        samples, types = parse_exposition(gw.metrics_text())
        assert types["dtx_build_info"] == "gauge"
        assert types["dtx_gateway_uptime_seconds"] == "gauge"
        assert types["dtx_gateway_queue_wait_ms"] == "histogram"
        assert samples[("dtx_gateway_queue_wait_ms_count", ())] == 1
        assert samples[("dtx_gateway_trace_open_spans", ())] == 0
    finally:
        gw.close()


def test_serving_metrics_histograms_from_shared_registry(serving_http_url):
    with urllib.request.urlopen(serving_http_url + "/metrics",
                                timeout=10) as r:
        samples, types = parse_exposition(r.read().decode())
    assert types["dtx_serving_ttft_ms"] == "histogram"
    assert types["dtx_serving_tpot_ms"] == "histogram"
    assert types["dtx_serving_prefill_chunk_ms"] == "histogram"
    assert types["dtx_build_info"] == "gauge"
    assert types["dtx_serving_uptime_seconds"] == "gauge"
    assert types["dtx_serving_requests_total"] == "counter"
    assert samples[("dtx_serving_slots_capacity", ())] == 2


# ------------------------------------------------------------- profiling

def test_profiler_single_flight(tmp_path, monkeypatch):
    """One capture at a time per process; stubbed jax.profiler so the test
    exercises the gating, not XLA."""
    import jax

    from datatunerx_tpu.obs.profiling import Profiler

    calls = []
    monkeypatch.setattr(jax.profiler, "start_trace",
                        lambda d: calls.append(("start", d)))
    monkeypatch.setattr(jax.profiler, "stop_trace",
                        lambda: calls.append(("stop", None)))
    p = Profiler()
    assert p.start(str(tmp_path / "t1"), seconds=30) == 30.0
    assert p.status()["dir"].endswith("t1")
    assert p.start(str(tmp_path / "t2"), seconds=30) is None  # refused
    p.close()  # cancels the window, joins the worker
    assert p.status() is None
    assert [c[0] for c in calls] == ["start", "stop"]
    # the returned window is the CLAMPED one the worker will actually run
    assert p.start(str(tmp_path / "t3"), seconds=600) == 120.0
    p.close()


def test_resolve_profile_dir_confinement(tmp_path, monkeypatch):
    from datatunerx_tpu.obs.profiling import resolve_profile_dir

    monkeypatch.setenv("DTX_PROFILE_DIR", str(tmp_path))
    assert resolve_profile_dir("run1") == str(tmp_path / "run1")
    assert resolve_profile_dir(str(tmp_path / "abs")) == str(
        tmp_path / "abs")
    auto = resolve_profile_dir(None)
    assert auto.startswith(str(tmp_path))
    with pytest.raises(ValueError):
        resolve_profile_dir("../outside")
    with pytest.raises(ValueError):
        resolve_profile_dir("/etc/cron.d")


def test_serving_debug_profile_endpoint(serving_http_url, tmp_path,
                                        monkeypatch):
    import jax

    from datatunerx_tpu.obs import profiling

    monkeypatch.setattr(jax.profiler, "start_trace", lambda d: None)
    monkeypatch.setattr(jax.profiler, "stop_trace", lambda: None)
    monkeypatch.setattr(profiling, "_PROFILER", profiling.Profiler())
    monkeypatch.setenv("DTX_PROFILE_DIR", str(tmp_path))

    def post(payload):
        body = json.dumps(payload).encode()
        return urllib.request.urlopen(urllib.request.Request(
            serving_http_url + "/debug/profile", data=body,
            headers={"Content-Type": "application/json"}, method="POST"),
            timeout=10)

    try:
        # a dir escaping the allowed root is refused before any state change
        with pytest.raises(urllib.error.HTTPError) as e:
            post({"seconds": 1, "dir": "../escape"})
        assert e.value.code == 400
        with post({"seconds": 600, "dir": str(tmp_path / "p")}) as r:
            assert r.status == 202
            out = json.load(r)
            assert out["profiling"].endswith("p")
            assert out["seconds"] == 120.0  # echoed CLAMPED, not requested
        with pytest.raises(urllib.error.HTTPError) as e:
            post({"seconds": 30, "dir": str(tmp_path / "q")})
        assert e.value.code == 409  # second capture refused, not corrupted
    finally:
        profiling.process_profiler().close()


# --------------------------------------------------------- training logger

def test_metrics_logger_prom_exposition(tmp_path):
    from datatunerx_tpu.training.metrics_log import MetricsLogger

    lg = MetricsLogger(str(tmp_path), total_steps=100, uid="u1")
    lg.log_train(10, {"loss": 1.25, "lr": 1e-4,
                      "pipe_step_wait_ms": 0.7, "pipe_queue_depth": 1.5})
    lg.log_eval(10, {"eval_loss": 2.5, "rouge-1": 0.5})
    prom = (tmp_path / "watch" / "metrics.prom").read_text()
    samples, types = parse_exposition(prom)
    assert samples[("dtx_train_loss", (("uid", "u1"),))] == 1.25
    # the pipeline-health signals ROADMAP wants for prefetch autotuning
    assert samples[("dtx_train_pipe_step_wait_ms", (("uid", "u1"),))] == 0.7
    assert samples[("dtx_train_pipe_queue_depth", (("uid", "u1"),))] == 1.5
    assert samples[("dtx_eval_eval_loss", (("uid", "u1"),))] == 2.5
    # jsonl key "rouge-1" sanitized into a valid metric name
    assert ("dtx_eval_rouge_1", (("uid", "u1"),)) in samples
    assert types["dtx_build_info"] == "gauge"


def test_metrics_logger_jsonl_behavior_unchanged(tmp_path):
    """The registry mirror is additive: the jsonl record a `dtx train` user
    watches is byte-for-byte what the pre-PR logger wrote (loss parity)."""
    from datatunerx_tpu.training.metrics_log import MetricsLogger

    lg = MetricsLogger(str(tmp_path), total_steps=10)
    lg.log_train(1, {"loss": 0.5, "lr": 3e-4})
    rec = json.loads(
        (tmp_path / "watch" / "trainer_log.jsonl").read_text())
    assert rec["loss"] == 0.5
    assert rec["lr"] == 3e-4
    assert rec["current_steps"] == 1
    assert rec["total_steps"] == 10
    assert set(rec) == {"current_steps", "total_steps", "percentage",
                        "elapsed_time", "eta", "loss", "lr"}
