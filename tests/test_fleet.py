"""Disaggregated fleet plane (datatunerx_tpu/fleet/): replica roles,
fleet-shared prefix tier, peer-replica KV spill.

The correctness bars are the ISSUE's oracles:

- a session exported MID-chunked-prefill (role handoff / drain) resumes
  on the peer with the prompt work done so far KEPT — no re-prefill of
  completed chunks — and finishes TOKEN-EXACTLY vs an undisturbed run
  (greedy, fixed-seed sampled, int8 kv_quant, pooled adapters);
- a prefix published through the fleet tier activates on a second
  replica with ZERO prefill chunks (asserted on sched_trace) and
  token-exact output;
- a preemption-parked session spilled to a peer resumes token-exactly,
  with the source/coordinator counters reconciling
  (preempt_stats["spilled"] == dtx_fleet_spill_total{outcome="ok"}).

Coordinator policy (two-phase ordering, tombstones, lease release,
role-deficit spawning, role-preference routing) is pinned on fakes;
the operator plumbing (CRD schema, webhook validation, serving-spec
pass-through) rides along as satellites.
"""

import json
import threading
import time

import pytest

from datatunerx_tpu.fleet import (
    FleetPlane,
    HandoffCoordinator,
    PrefixTier,
    SpillCoordinator,
)
from datatunerx_tpu.fleet.prefix_tier import payload_bytes
from datatunerx_tpu.gateway.replica_pool import (
    InProcessReplica,
    ReplicaError,
    ReplicaPool,
)
from datatunerx_tpu.serving.batched_engine import BatchedEngine
from tests.test_session_handoff import _import_and_wait, _throttled

MODEL = "preset:debug"


def _throttled_prefill(eng, delay=0.05):
    """Slow each prefill CHUNK so a test can deterministically catch a
    request mid-chunked-prefill. Returns the original to restore."""
    orig = eng._prefill_chunk_fn

    def slow(*a, **k):
        time.sleep(delay)
        return orig(*a, **k)

    eng._prefill_chunk_fn = slow
    return orig


def _export_mid_prefill(src, prompt, **kw):
    """Submit a chunk-prefilling prompt on ``src``, catch it with the
    prompt PARTIALLY done, and export with include_prefill=True."""
    orig = _throttled_prefill(src)
    try:
        req = src.submit(prompt, **kw)
        deadline = time.monotonic() + 30
        caught = False
        while time.monotonic() < deadline:
            if any(0 < st["done"] < st["plen"]
                   for st in src._pending.values()):
                caught = True
                break
            time.sleep(0.002)
        assert caught, "request never caught mid-chunked-prefill"
        doc = src.export_sessions(include_prefill=True)
    finally:
        src._prefill_chunk_fn = orig
    assert len(doc["sessions"]) == 1, doc
    assert req.done.wait(10) and "session migrated" in (req.error or "")
    return doc["sessions"][0]


def _prefill_tokens(eng, mark=0):
    """Prompt tokens chunk-prefilled since trace index ``mark``."""
    return sum(ev[2] for ev in list(eng.sched_trace)[mark:]
               if ev[0] == "prefill")


@pytest.fixture(scope="module")
def chunked_pair():
    """Twin paged engines whose prefill is CHUNKED (budget 64/tick) —
    the shape mid-prefill handoff exists for."""
    mk = lambda: BatchedEngine(  # noqa: E731 — twin ctor, used twice
        MODEL, template="vanilla", max_seq_len=256, slots=2,
        decode_chunk=4, kv_block_size=16, prefill_chunk=64,
        prefill_token_budget=64)
    src, dst = mk(), mk()
    yield src, dst
    src.close()
    dst.close()


# ------------------------------------------- mid-prefill export / import

def test_mid_prefill_export_import_parity(chunked_pair):
    """A session exported mid-chunked-prefill resumes on the peer where
    the source stopped: the importer chunk-prefills ONLY the remaining
    prompt tail, and the continuation is token-exact vs an undisturbed
    run — greedy and fixed-seed sampled."""
    src, dst = chunked_pair
    prompt = src.tokenizer.encode("chunked prefill handoff target " * 30)
    for kw in ({}, {"temperature": 0.8, "top_p": 0.9, "seed": 11}):
        want = src.generate(prompt, max_new_tokens=12, **kw)
        payload = _export_mid_prefill(src, prompt, max_new_tokens=12, **kw)
        pending = payload.get("pending")
        assert pending, "payload lost the prompt tail"
        tail, done_src = len(pending["ids"]), int(pending["done"])
        assert tail > 0 and done_src > 0, pending
        mark = len(dst.sched_trace)
        handle, _ = _import_and_wait(dst, payload)
        assert handle.tokens == want, (kw, handle.tokens, want)
        # prompt work KEPT: the target chunk-prefills only the tail the
        # source had not reached, strictly less than the full prompt
        done = _prefill_tokens(dst, mark)
        assert 0 < done <= tail < tail + done_src, (done, tail, done_src)
    assert src.session_stats["export"].get("ok_prefill", 0) >= 2
    # elastic accounting both sides
    assert src.free_kv_blocks == src.total_kv_blocks
    assert dst.free_kv_blocks == dst.total_kv_blocks


def test_mid_prefill_export_skipped_without_flag(chunked_pair):
    """Steady-state exports (no include_prefill) SKIP mid-prefill slots
    — the session finishes its prompt in place, undisturbed."""
    src, _ = chunked_pair
    prompt = src.tokenizer.encode("skip me while prefill runs " * 30)
    want = src.generate(prompt, max_new_tokens=8)
    orig = _throttled_prefill(src)
    try:
        req = src.submit(prompt, max_new_tokens=8)
        deadline = time.monotonic() + 30
        while not any(0 < st["done"] < st["plen"]
                      for st in src._pending.values()) \
                and time.monotonic() < deadline:
            time.sleep(0.002)
        doc = src.export_sessions()  # include_prefill defaults False
    finally:
        src._prefill_chunk_fn = orig
    assert doc["sessions"] == []
    assert any(s["reason"] == "prefill_in_progress"
               for s in doc["skipped"]), doc
    assert src.session_stats["export"].get("skipped_prefill", 0) >= 1
    assert req.done.wait(120) and req.error is None
    assert req.tokens == want


def test_mid_prefill_int8_and_pooled_adapter_parity(tmp_path):
    """The mid-prefill wire is exact for int8 kv_quant caches (native
    encoding) and for pooled-adapter sessions (adapter resolved by NAME
    on the importer, load-on-miss included)."""
    from datatunerx_tpu.serving.adapters import make_adapter_checkpoint

    ck = {"t-a": make_adapter_checkpoint(str(tmp_path / "a"), MODEL,
                                         seed=3, rank=2)}
    mk = lambda: BatchedEngine(  # noqa: E731
        MODEL, adapters=ck, adapter_pool=1, adapter_rank_max=4,
        template="vanilla", max_seq_len=256, slots=2, decode_chunk=4,
        kv_block_size=16, prefill_chunk=64, prefill_token_budget=64,
        kv_quant="int8")
    src, dst = mk(), mk()
    try:
        prompt = src.tokenizer.encode("tenant prefill on the move " * 30)
        want = src.generate(prompt, max_new_tokens=10, adapter="t-a")
        payload = _export_mid_prefill(src, prompt, max_new_tokens=10,
                                      adapter="t-a")
        assert payload["adapter"] == "t-a"
        assert payload["kv"]["wire"] == "int8"
        handle, meta = _import_and_wait(dst, payload)
        assert handle.tokens == want, (handle.tokens, want)
        assert meta["adapter"] == "t-a"
        # adapter parity is vacuous if base produces the same tokens
        assert want != src.generate(prompt, max_new_tokens=10)
    finally:
        src.close()
        dst.close()


# --------------------------------------------------- prefix tier (unit)

def _prefix_payload(fp, nbytes=100, adapter="", cursor=64):
    return {"fingerprint": fp, "adapter": adapter, "cursor": cursor,
            "kv": {"k": "x" * nbytes}}


def test_prefix_tier_directory_lru_budget():
    tier = PrefixTier(byte_budget=250)
    assert tier.publish(_prefix_payload("f1"), source="r0")
    assert not tier.publish(_prefix_payload("f1"), source="r1")  # re-offer
    assert tier.holders("f1") == {"r0", "r1"}
    assert tier.publish(_prefix_payload("f2"), source="r0")
    # third entry blows the budget: the LRU entry (f1) is evicted; the
    # directory forgets it but holders keep serving their local copies
    assert tier.publish(_prefix_payload("f3"), source="r0")
    assert tier.entries == 2 and tier.counters["evicted"] == 1
    assert tier.holders("f1") == set()
    assert tier.bytes_used <= 250
    # unkeyed payloads are refused, not stored
    assert not tier.publish({"kv": {"k": "x"}})
    assert payload_bytes(_prefix_payload("f", nbytes=10)) >= 10
    st = tier.stats()
    assert st["entries"] == 2 and st["publishes"] == 3


class _FakePrefixReplica:
    """Replica fake for tier sync: exports canned entries, records
    import offers, and can refuse (409) or fault (transport)."""

    def __init__(self, name, entries=(), mode="ok"):
        self.name = name
        self.role = "mixed"
        self._entries = list(entries)
        self.mode = mode
        self.offered = []

    def export_prefix_entries(self, exclude=None, max_entries=4):
        ex = set(exclude or ())
        return {"entries": [e for e in self._entries
                            if e["fingerprint"] not in ex][:max_entries]}

    def import_prefix_entry(self, payload):
        self.offered.append(payload["fingerprint"])
        if self.mode == "refuse":
            raise ReplicaError(f"{self.name}: no blocks", status=409)
        if self.mode == "fault":
            raise ReplicaError(f"{self.name}: connection reset")
        return {"imported": True, "fingerprint": payload["fingerprint"]}


def test_prefix_tier_sync_pull_push_and_refusals():
    tier = PrefixTier(1 << 20)
    src = _FakePrefixReplica("r0", entries=[_prefix_payload("f1")])
    ok = _FakePrefixReplica("r1")
    out = tier.sync(src)
    assert out["pulled"] == 1 and tier.entries == 1
    out = tier.sync(ok)
    assert out["pushed"] == 1 and tier.counters["hits"] == 1
    # idempotent: r1 is a known holder now, nothing re-offered
    assert tier.sync(ok) == {"pulled": 0, "pushed": 0, "refused": 0}
    assert ok.offered == ["f1"]

    # a 409 refusal counts a miss but stays RETRYABLE
    busy = _FakePrefixReplica("r2", mode="refuse")
    tier.sync(busy)
    tier.sync(busy)
    assert busy.offered == ["f1", "f1"]
    assert tier.counters["misses"] == 2

    # a transport fault marks the replica failed for the entry — it is
    # not re-offered forever
    broken = _FakePrefixReplica("r3", mode="fault")
    tier.sync(broken)
    tier.sync(broken)
    assert broken.offered == ["f1"]


def test_prefix_import_refusal_paths(chunked_pair):
    """Engine-level refusals: no prefix cache / wrong model signature —
    and the replica shim maps refusals to 409 ReplicaErrors so the tier
    treats them as retryable misses, not replica faults."""
    src, _ = chunked_pair  # chunked_pair engines have NO prefix cache
    pcache = BatchedEngine(MODEL, template="vanilla", max_seq_len=256,
                           slots=2, decode_chunk=4, kv_block_size=16,
                           prefix_cache=4)
    try:
        prompt = pcache.tokenizer.encode("publish this prefix " * 10)
        req = pcache.submit(prompt, max_new_tokens=4)
        assert req.done.wait(120) and req.error is None
        doc = pcache.export_prefix_entries()
        assert len(doc["entries"]) == 1
        payload = doc["entries"][0]

        with pytest.raises(ValueError, match="prefix cache disabled"):
            src.import_prefix_entry(json.loads(json.dumps(payload)))

        bad = json.loads(json.dumps(payload))
        bad["model_sig"]["layers"] = 999
        with pytest.raises(ValueError, match="incompatible model"):
            pcache.import_prefix_entry(bad)

        rep = InProcessReplica("r-shim", src)
        with pytest.raises(ReplicaError) as ei:
            rep.import_prefix_entry(payload)
        assert ei.value.status == 409
    finally:
        pcache.close()


def test_prefix_tier_second_replica_zero_prefill():
    """The tier's whole point: replica A prefills a shared prompt once,
    the tier publishes it, and replica B's FIRST request against that
    prompt admits with ZERO prefill chunks (sched_trace asserted) and
    token-exact output."""
    mk = lambda: BatchedEngine(  # noqa: E731
        MODEL, template="vanilla", max_seq_len=256, slots=2,
        decode_chunk=4, kv_block_size=16, prefill_chunk=64,
        prefill_token_budget=64, prefix_cache=4)
    a, b = mk(), mk()
    try:
        prompt = a.tokenizer.encode("shared system preamble " * 20)
        req = a.submit(prompt, max_new_tokens=8)
        assert req.done.wait(120) and req.error is None
        want = req.tokens

        tier = PrefixTier(16 << 20)
        ra, rb = InProcessReplica("rA", a), InProcessReplica("rB", b)
        out = tier.sync(ra)
        assert out["pulled"] >= 1, out
        out = tier.sync(rb)
        assert out["pushed"] >= 1, out
        fp = next(iter(tier._d))
        assert tier.holders(fp) >= {"rA", "rB"}

        mark = len(b.sched_trace)
        req_b = b.submit(prompt, max_new_tokens=8)
        assert req_b.done.wait(120) and req_b.error is None
        assert req_b.tokens == want, (req_b.tokens, want)
        # zero prefill chunks on B: the imported entry served the whole
        # prompt via the exact-hit admission path
        assert _prefill_tokens(b, mark) == 0
        assert any(ev[0] == "admit" and ev[3] == "cache"
                   for ev in list(b.sched_trace)[mark:])
        assert b.prefill_stats["reuse"] == 1
        assert b.session_stats["import_prefix"].get("ok", 0) == 1
    finally:
        a.close()
        b.close()


# ------------------------------------------- coordinator policy (fakes)

class _FakePool:
    def __init__(self, replicas):
        self._replicas = list(replicas)

    def available(self):
        return list(self._replicas)


class _FakeSessionReplica:
    """Replica fake for handoff/spill policy: canned stats, canned
    export/hold docs, scripted import outcomes, recorded calls."""

    def __init__(self, name, role="mixed", free_blocks=8, busy=0,
                 parked=0, export_doc=None, hold_doc=None,
                 import_mode="ok"):
        self.name = name
        self.role = role
        self._stats = {"slots_busy": busy, "kv_blocks_free": free_blocks,
                       "sessions_parked": parked}
        self.export_doc = export_doc
        self.hold_doc = hold_doc
        self.import_mode = import_mode
        self.calls = []

    def stats_snapshot(self):
        return dict(self._stats)

    def export_sessions(self, slots=None, wire=None,
                        include_prefill=False):
        self.calls.append(("export", include_prefill))
        return self.export_doc

    def import_session(self, payload):
        self.calls.append(("import", payload.get("trace_id")))
        if self.import_mode == "refuse":
            raise ReplicaError(f"{self.name}: full", status=409)
        if self.import_mode == "fault":
            raise ReplicaError(f"{self.name}: died")
        return ({"session": payload.get("trace_id"),
                 "text_so_far": "tail "}, iter(["rest"]))

    def hold_parked(self, max_sessions=4, hold_s=10.0):
        self.calls.append(("hold", max_sessions, hold_s))
        return self.hold_doc

    def drop_parked(self, trace_ids):
        self.calls.append(("drop", list(trace_ids)))
        if getattr(self, "drop_fails", False):
            raise ReplicaError(f"{self.name}: drop lost")
        return {"dropped": list(trace_ids)}

    def release_parked(self, trace_ids):
        self.calls.append(("release", list(trace_ids)))
        return {"released": list(trace_ids)}


def test_handoff_coordinator_policy():
    parked = {}
    sess = {"trace_id": "t1", "tokens": [1, 2]}
    src = _FakeSessionReplica(
        "pf", role="prefill", busy=2,
        export_doc={"sessions": [sess],
                    "skipped": [{"slot": 1,
                                 "reason": "prefill_in_progress"}]})
    dec = _FakeSessionReplica("dc", role="decode", free_blocks=9)
    hc = HandoffCoordinator(_FakePool([src, dec]),
                            park=lambda t, e: parked.__setitem__(t, e))
    out = hc.tick()
    assert out == {"moved": 1, "cold": 0, "skipped": 1}
    assert hc.counters == {"ok": 1, "cold": 0, "skipped": 1, "none": 0}
    assert parked["t1"]["target"] == "dc"
    assert parked["t1"]["text_so_far"] == "tail "
    # steady-state export never ships mid-prefill tails
    assert ("export", False) in src.calls
    # decode-preferring targets only — a second PREFILL replica with more
    # free blocks still ranks behind the decode replica
    pf2 = _FakeSessionReplica("pf2", role="prefill", free_blocks=99)
    from datatunerx_tpu.fleet.handoff import decode_targets

    targets = decode_targets(_FakePool([src, dec, pf2]), "pf")
    assert [t.name for t in targets] == ["dc", "pf2"]

    # every peer refuses → tombstone parked so the client re-prefills
    parked.clear()
    dec.import_mode = "refuse"
    pf2.import_mode = "refuse"
    hc2 = HandoffCoordinator(_FakePool([src, dec, pf2]),
                             park=lambda t, e: parked.__setitem__(t, e))
    hc2.tick()
    assert parked["t1"] == {"failed": True}
    assert hc2.counters["cold"] == 1

    # a prefill source with work but NO peers at all
    hc3 = HandoffCoordinator(_FakePool([src]),
                             park=lambda t, e: None)
    hc3.tick()
    assert hc3.counters["none"] == 1


def test_spill_coordinator_two_phase_ordering():
    events = []
    sess = {"trace_id": "s1", "seq": 7, "payload": {"trace_id": "s1"}}
    src = _FakeSessionReplica("ovc", parked=1,
                              hold_doc={"sessions": [sess], "parked": 1})
    dst = _FakeSessionReplica("peer", role="decode", free_blocks=4)
    orig_drop = src.drop_parked

    def drop_traced(tids):
        events.append("drop")
        return orig_drop(tids)

    src.drop_parked = drop_traced
    sc = SpillCoordinator(
        _FakePool([src, dst]),
        park=lambda t, e: events.append(("park", t, e["target"])))
    out = sc.tick()
    assert out["moved"] == 1 and sc.counters["ok"] == 1
    # park-before-drop: the continuation must be waiting BEFORE the drop
    # terminates the source stream
    assert events == [("park", "s1", "peer"), "drop"]
    assert ("hold", sc.max_sessions, sc.hold_s) in src.calls

    # every peer 409s → released immediately (no lease wait), refused
    src2 = _FakeSessionReplica("ovc2", parked=1,
                               hold_doc={"sessions": [sess], "parked": 1})
    full = _FakeSessionReplica("full", role="decode", free_blocks=2,
                               import_mode="refuse")
    sc2 = SpillCoordinator(_FakePool([src2, full]), park=lambda t, e: None)
    assert sc2.tick()["refused"] == 1
    assert sc2.counters["refused"] == 1
    assert ("release", ["s1"]) in src2.calls

    # no peer with free blocks → skipped WITHOUT leasing anything
    src3 = _FakeSessionReplica("ovc3", parked=1,
                               hold_doc={"sessions": [sess], "parked": 1})
    empty = _FakeSessionReplica("dry", role="decode", free_blocks=0)
    sc3 = SpillCoordinator(_FakePool([src3, empty]), park=lambda t, e: None)
    assert sc3.tick()["skipped"] == 1
    assert not any(c[0] == "hold" for c in src3.calls)

    # drop failure is LOUD (single-ownership depends on the drop landing)
    src4 = _FakeSessionReplica("ovc4", parked=1,
                               hold_doc={"sessions": [sess], "parked": 1})
    src4.drop_fails = True
    dst4 = _FakeSessionReplica("peer4", role="decode", free_blocks=4)
    sc4 = SpillCoordinator(_FakePool([src4, dst4]), park=lambda t, e: None)
    sc4.tick()
    assert sc4.counters["error"] == 1


# --------------------------------------------- peer spill (real engines)

def test_peer_spill_token_exact_counters_reconcile():
    """A preemption-parked session re-homed onto a peer resumes
    TOKEN-EXACTLY, and the books balance: the source's
    preempt_stats["spilled"] equals the coordinator's ok count, and the
    continuation (text_so_far + stream) is byte-identical to an
    undisturbed run."""
    a = BatchedEngine(MODEL, template="vanilla", max_seq_len=256,
                      slots=4, decode_chunk=4, kv_block_size=16,
                      kv_blocks=20, kv_overcommit="on")
    b = BatchedEngine(MODEL, template="vanilla", max_seq_len=256,
                      slots=4, decode_chunk=4, kv_block_size=16)
    try:
        prompts = [a.tokenizer.encode(f"spill pressure probe {i}")
                   for i in range(4)]
        want_text = [b.tokenizer.decode(
            b.generate(p, max_new_tokens=80), skip_special_tokens=True)
            for p in prompts]

        parked_box = {}
        pool = ReplicaPool([InProcessReplica("A", a, role="mixed"),
                            InProcessReplica("B", b, role="decode")])
        sc = SpillCoordinator(
            pool, park=lambda t, e: parked_box.__setitem__(t, e))

        orig = _throttled(a, delay=0.05)
        try:
            reqs = [a.submit(p, max_new_tokens=80, trace_id=f"spill-{i}")
                    for i, p in enumerate(prompts)]
            deadline = time.monotonic() + 90
            while sc.counters["ok"] == 0 and time.monotonic() < deadline:
                if a.parked_sessions:
                    sc.tick()
                if all(r.done.is_set() for r in reqs):
                    break
                time.sleep(0.01)
        finally:
            a._decode = orig
        assert sc.counters["ok"] >= 1, (
            f"pool never spilled: {sc.counters}, "
            f"preempt={a.preempt_stats}")

        for i, r in enumerate(reqs):
            assert r.done.wait(300), f"request {i} stalled"
            if r.error is None:
                # resumed locally (or never preempted): exact in place
                text = a.tokenizer.decode(r.tokens,
                                          skip_special_tokens=True)
                assert text == want_text[i], i
                continue
            assert "session migrated" in r.error, (i, r.error)
            ent = parked_box[r.trace_id]
            assert ent.get("failed") is not True, ent
            assert ent["target"] == "B"
            text = ent["text_so_far"] + "".join(ent["stream"])
            assert text == want_text[i], (i, text, want_text[i])

        # the books: every coordinator ok is a source-side spilled drop
        assert a.preempt_stats.get("spilled", 0) == sc.counters["ok"]
        assert b.session_stats["import"].get("ok", 0) >= sc.counters["ok"]
        # pools whole again on both sides
        assert a.free_kv_blocks == a.total_kv_blocks
        assert b.free_kv_blocks == b.total_kv_blocks
    finally:
        a.close()
        b.close()


# --------------------------------------------------- plane gating + wiring

def test_fleet_plane_gating_and_gateway_metrics():
    """Defaults build NO plane (byte-identical gateway); any flag builds
    it, ticks cover only the enabled pieces, and the dtx_fleet_* series
    appear in /metrics exactly when the plane exists."""
    from datatunerx_tpu.gateway.server import Gateway
    from tests.test_gateway import FakeEngine

    plane = FleetPlane(_FakePool([]), park=lambda t, e: None)
    assert not plane.enabled and plane.tick() == {} and plane.stats() == {}

    pool = ReplicaPool([InProcessReplica("r0", FakeEngine("r0"))])
    gw = Gateway(pool)
    try:
        assert gw.fleet is None
        assert "dtx_fleet_" not in gw.metrics_text()
    finally:
        gw.slo.stop()

    pool2 = ReplicaPool([InProcessReplica("r0", FakeEngine("r0"))])
    gw2 = Gateway(pool2, prefill_threshold=8, fleet_prefix_bytes=1 << 20,
                  fleet_handoff=True, fleet_spill=True)
    try:
        assert gw2.fleet is not None and gw2.fleet.enabled
        out = gw2.fleet.tick()
        assert set(out) == {"handoff", "spill", "prefix"}
        text = gw2.metrics_text()
        for series in ("dtx_fleet_prefix_entries",
                       "dtx_fleet_prefix_bytes",
                       "dtx_fleet_handoff_total",
                       "dtx_fleet_spill_total"):
            assert series in text, series
    finally:
        gw2.fleet.stop()
        gw2.slo.stop()


def test_router_role_preference_never_filters():
    from datatunerx_tpu.gateway.router import Router
    from tests.test_gateway import FakeEngine

    pf = InProcessReplica("pf", FakeEngine("pf"), role="prefill")
    dc = InProcessReplica("dc", FakeEngine("dc"), role="decode")
    router = Router(ReplicaPool([pf, dc]), prefill_threshold=32)
    assert router.route(prompt_tokens=64).name == "pf"
    assert router.route(prompt_tokens=8).name == "dc"
    # threshold boundary: exactly AT the threshold counts as long
    assert router.route(prompt_tokens=32).name == "pf"
    assert router.role_routes == {"prefill": 2, "decode": 1, "blind": 0}
    # no token estimate → role-blind (and not counted as a role route)
    router.route()
    assert router.role_routes["blind"] == 0

    # preference, never a filter: an all-mixed fleet routes as before
    mixed = Router(ReplicaPool([
        InProcessReplica("m0", FakeEngine("m0")),
        InProcessReplica("m1", FakeEngine("m1"))]), prefill_threshold=32)
    mixed.route(prompt_tokens=64)
    assert mixed.role_routes == {"prefill": 0, "decode": 0, "blind": 1}
    # threshold 0 = the PR 15 router, role logic never consulted
    off = Router(ReplicaPool([pf, dc]))
    off.route(prompt_tokens=64)
    assert off.role_routes == {"prefill": 0, "decode": 0, "blind": 0}


def test_managed_replica_set_role_deficit(tmp_path):
    """Replacement spawns take the role furthest below its cycle share —
    a dead prefill replica is replaced by a prefill replica, whichever
    index died."""
    from datatunerx_tpu.gateway.server import ManagedReplicaSet
    from tests.test_gateway import FakeEngine

    pool = ReplicaPool([])
    mgr = ManagedReplicaSet(pool, [], workdir=str(tmp_path),
                            supervise_interval_s=0,
                            roles=["prefill", "decode", "decode"])
    try:
        assert mgr._next_role() == "prefill"  # fresh fleet: cycle order
        pool.add(InProcessReplica("r0", FakeEngine("r0"), role="prefill"))
        assert mgr._next_role() == "decode"
        pool.add(InProcessReplica("r1", FakeEngine("r1"), role="decode"))
        assert mgr._next_role() == "decode"  # decode wants 2 of 3
        pool.add(InProcessReplica("r2", FakeEngine("r2"), role="decode"))
        # balanced fleet: the first cycle entry wins the tie
        assert mgr._next_role() == "prefill"
        # a DRAINING prefill replica no longer counts toward its share
        pool.get("r0").drain()
        assert mgr._next_role() == "prefill"
        # role-less sets keep spawning role-less
        mgr2 = ManagedReplicaSet(pool, [], workdir=str(tmp_path),
                                 supervise_interval_s=0)
        assert mgr2._next_role() is None
    finally:
        mgr._shutdown.set()


# ----------------------------------------------------- operator plumbing

def _fleet_job(serve):
    from datatunerx_tpu.operator.api import FinetuneJob, ObjectMeta

    return FinetuneJob(
        metadata=ObjectMeta(name="j", namespace="default"),
        spec={"finetune": {"finetuneSpec": {
            "llm": "m", "dataset": "d",
            "hyperparameter": {"hyperparameterRef": "h"}}},
            "serveConfig": serve},
    )


def test_crd_schema_includes_fleet_fields():
    from datatunerx_tpu.operator.api import FinetuneJob
    from datatunerx_tpu.operator.crdgen import crd_for

    crd = crd_for(FinetuneJob)
    serve = (crd["spec"]["versions"][0]["schema"]["openAPIV3Schema"]
             ["properties"]["spec"]["properties"]["serveConfig"]
             ["properties"])
    for field in ("kvOvercommit", "specDraft", "specK", "specMode",
                  "role", "prefillThreshold", "fleetPrefixMb",
                  "fleetHandoff", "fleetSpill"):
        assert field in serve, field
    assert serve["role"]["type"] == "string"
    assert serve["fleetPrefixMb"]["type"] == "number"


def test_webhook_validates_fleet_serve_config():
    from datatunerx_tpu.operator.webhooks import AdmissionError, admit

    # single role needs no gateway; a cycle does
    admit(_fleet_job({"role": "prefill"}))
    admit(_fleet_job({"role": "prefill,decode", "replicas": 2}))
    admit(_fleet_job({"role": "prefill,decode", "gateway": True}))
    for bad in ({"role": "pilot"},
                {"role": "prefill,decode"},  # cycle, no gateway
                {"prefillThreshold": 0},
                {"fleetPrefixMb": 0},
                {"kvOvercommit": "maybe"},
                {"specMode": "sometimes"},
                {"specK": 0}):
        with pytest.raises(AdmissionError):
            admit(_fleet_job(bad))


def test_serving_spec_carries_fleet_fields():
    from datatunerx_tpu.operator.generate import generate_serving_spec
    from datatunerx_tpu.operator.webhooks import admit

    job = _fleet_job({"replicas": 2, "role": "prefill,decode",
                      "prefillThreshold": 48, "fleetPrefixMb": 8.5,
                      "fleetHandoff": True, "fleetSpill": True,
                      "kvOvercommit": "on", "specMode": "auto",
                      "specK": 3})
    admit(job)
    spec = generate_serving_spec(job, {})
    assert spec["role"] == "prefill,decode"
    assert spec["prefill_threshold"] == 48
    assert spec["fleet_prefix_mb"] == 8.5
    assert spec["fleet_handoff"] is True and spec["fleet_spill"] is True
    assert spec["kv_overcommit"] == "on"
    assert spec["spec_mode"] == "auto" and spec["spec_k"] == 3
    # absent knobs stay falsy — the backend adds no argv for them
    bare = generate_serving_spec(_fleet_job({}), {})
    assert not bare["role"] and not bare["fleet_handoff"]
    assert not bare["fleet_prefix_mb"] and not bare["prefill_threshold"]


# -------------------------------------------- gateway chaos (fake fleet)

def test_selftest_fleet_role_cycle_mid_prefill_rehoming():
    """The CI role-cycle smoke in miniature: draining the PREFILL
    replica while sessions are mid-prefill re-homes them with their
    prompt work kept (mid_prefill_imports counted on the survivor), and
    every client stream completes with exact text."""
    from datatunerx_tpu.loadgen.replay import build_selftest_fleet

    gw, engines = build_selftest_fleet(adapters=[], delay_s=0.01,
                                       roles=["prefill", "decode"],
                                       prefill_steps=5)
    try:
        req = {"messages": [{"role": "user", "content": "hi"}],
               "max_tokens": 6}
        texts = {}

        def consume(i):
            texts[i] = "".join(
                gw.chat_stream(dict(req), trace_id=f"dtx-pf-{i}"))

        ths = [threading.Thread(target=consume, args=(i,))
               for i in range(3)]
        for th in ths:
            th.start()
        # drain the prefill replica while its sessions are still paying
        # prefill steps (5 steps x 10ms leaves a wide window)
        deadline = time.monotonic() + 5
        pf = gw.pool.get("replica-0")
        while not pf.inflight and time.monotonic() < deadline:
            time.sleep(0.002)
        assert gw.drain("replica-0")
        for th in ths:
            th.join(timeout=15)
        assert all(texts[i] == "tok " * 6 for i in range(3)), texts
        assert sum(e.mid_prefill_imports for e in engines) >= 1
        assert not gw.handoff_stats().get("cold")
    finally:
        gw.slo.stop()
