"""Interpret-mode unit tests for the fused sampling epilogue
(ops/pallas_sampling.py): the Pallas kernel must reproduce the blocked-XLA
oracle token for token — greedy bitwise (shared max/compare tile walk),
sampled exactly under a fixed seed (both sides consume the same per-row
uniforms over the identical tile schedule) — and the oracle itself must
agree with the legacy sampler's semantics (``jnp.argmax`` ties, the
``sampling_probs`` distribution, the exact_topp nucleus). Engine-level
epilogue parity lives in test_speculative.py; these tests pin the
primitive."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from datatunerx_tpu.ops.pallas_sampling import (
    MODES,
    default_impl,
    fused_sample,
    sample_rows,
)
from datatunerx_tpu.serving.speculative import sampling_probs


def _logits(key, s, v, scale=4.0):
    return jax.random.normal(key, (s, v)) * scale


def _keys(seed, s):
    return jax.vmap(jax.random.PRNGKey)(jnp.arange(seed, seed + s))


# ------------------------------------------------------- kernel vs oracle

@pytest.mark.parametrize("vocab", [256, 2048])
def test_greedy_kernel_matches_oracle_and_argmax(vocab):
    logits = _logits(jax.random.PRNGKey(0), 5, vocab)
    temps = jnp.zeros((5,))
    tp = jnp.ones((5,))
    kern = fused_sample(logits, temps, tp, None, mode="greedy",
                        impl="kernel", interpret=True)
    xla = fused_sample(logits, temps, tp, None, mode="greedy", impl="xla")
    ref = jnp.argmax(logits, axis=-1)
    np.testing.assert_array_equal(np.asarray(kern), np.asarray(xla))
    np.testing.assert_array_equal(np.asarray(kern), np.asarray(ref))


def test_greedy_tie_rule_is_first_occurrence():
    # ties across tile boundaries: jnp.argmax takes the FIRST maximum;
    # both impls must agree (strict > across tiles, min-index within)
    v = 512
    logits = jnp.zeros((3, v))
    logits = logits.at[0, 7].set(5.0).at[0, 300].set(5.0)
    logits = logits.at[1, 130].set(2.0).at[1, 131].set(2.0)
    # row 2: all-equal row — argmax is index 0
    temps = jnp.zeros((3,))
    kern = fused_sample(logits, temps, jnp.ones((3,)), None, mode="greedy",
                        impl="kernel", interpret=True)
    xla = fused_sample(logits, temps, jnp.ones((3,)), None, mode="greedy",
                       impl="xla")
    ref = jnp.argmax(logits, axis=-1)
    np.testing.assert_array_equal(np.asarray(kern), np.asarray(ref))
    np.testing.assert_array_equal(np.asarray(xla), np.asarray(ref))


@pytest.mark.parametrize("vocab", [256, 1000])
def test_simple_kernel_matches_oracle_fixed_seed(vocab):
    s = 6
    logits = _logits(jax.random.PRNGKey(1), s, vocab)
    temps = jnp.asarray([0.7, 1.0, 1.3, 0.5, 2.0, 0.9])
    tp = jnp.ones((s,))
    for seed in range(2):
        keys = _keys(100 + seed * s, s)
        kern = fused_sample(logits, temps, tp, keys, mode="simple",
                            impl="kernel", interpret=True)
        xla = fused_sample(logits, temps, tp, keys, mode="simple",
                           impl="xla")
        np.testing.assert_array_equal(np.asarray(kern), np.asarray(xla))


@pytest.mark.slow
def test_simple_greedy_rows_inside_sampled_batch():
    # slow: CI's kernel parity smoke step runs this file unfiltered.
    # temp <= 0 rows inside a "simple" batch resolve to argmax on both
    # sides regardless of the drawn uniform
    s, v = 4, 384
    logits = _logits(jax.random.PRNGKey(2), s, v)
    temps = jnp.asarray([0.0, 1.0, -1.0, 0.8])
    keys = _keys(7, s)
    kern = fused_sample(logits, temps, jnp.ones((s,)), keys, mode="simple",
                        impl="kernel", interpret=True)
    xla = fused_sample(logits, temps, jnp.ones((s,)), keys, mode="simple",
                       impl="xla")
    ref = np.asarray(jnp.argmax(logits, axis=-1))
    np.testing.assert_array_equal(np.asarray(kern), np.asarray(xla))
    assert int(kern[0]) == ref[0] and int(kern[2]) == ref[2]


def test_non_multiple_of_128_vocab_pads_dead():
    # pad lanes must never win: put the true max at the LAST real lane
    v = 130  # pads to 256
    logits = jnp.full((2, v), -3.0)
    logits = logits.at[:, v - 1].set(9.0)
    temps = jnp.asarray([0.0, 1.0])
    keys = _keys(3, 2)
    for mode, kk in (("greedy", None), ("simple", keys)):
        kern = fused_sample(logits, temps, jnp.ones((2,)), kk, mode=mode,
                            impl="kernel", interpret=True)
        xla = fused_sample(logits, temps, jnp.ones((2,)), kk, mode=mode,
                           impl="xla")
        np.testing.assert_array_equal(np.asarray(kern), np.asarray(xla))
        assert int(kern[0]) == v - 1
        assert 0 <= int(kern[1]) < v


# -------------------------------------------- distribution-level exactness

@pytest.mark.slow
def test_simple_empirical_matches_sampling_probs():
    # the inverse-CDF draw must follow softmax(logits/t) — the same
    # distribution sampling_probs(top_p=1) describes. Tiny vocab, many
    # fixed-seed draws, loose 4-sigma gate.
    # slow: many-draw empirical sweep — CI's kernel parity smoke step
    # runs this file unfiltered.
    v, n = 8, 3000
    logits = jnp.asarray([[1.0, 2.0, 0.5, -1.0, 0.0, 1.5, -2.0, 0.2]])
    logits = jnp.pad(logits, ((0, 0), (0, 0)))  # [1, 8]
    temp = 0.9
    want = np.asarray(sampling_probs(logits[0], temp, 1.0))
    keys = jax.vmap(jax.random.PRNGKey)(jnp.arange(n))
    toks = fused_sample(jnp.tile(logits, (n, 1)), jnp.full((n,), temp),
                        jnp.ones((n,)), keys, mode="simple", impl="xla")
    counts = np.bincount(np.asarray(toks), minlength=v) / n
    for i in range(v):
        sigma = max((want[i] * (1 - want[i]) / n) ** 0.5, 1e-6)
        assert abs(counts[i] - want[i]) <= 4 * sigma + 0.01, (
            i, counts[i], want[i])


def test_topp_tokens_stay_in_nucleus_and_match_probs_support():
    v, n = 8, 800
    logits = jnp.asarray([1.0, 3.0, 0.5, -1.0, 2.0, -0.5, 0.0, -2.0])
    temp, top_p = 1.0, 0.6
    want = np.asarray(sampling_probs(logits, temp, top_p, exact_topp=True))
    support = set(np.nonzero(want > 0)[0].tolist())
    keys = jax.vmap(jax.random.PRNGKey)(jnp.arange(n))
    toks = fused_sample(jnp.tile(logits[None], (n, 1)),
                        jnp.full((n,), temp), jnp.full((n,), top_p), keys,
                        mode="topp", impl="xla")
    got = set(np.asarray(toks).tolist())
    assert got <= support, (got, support)
    # empirical frequencies track the truncated distribution
    counts = np.bincount(np.asarray(toks), minlength=v) / n
    for i in support:
        sigma = max((want[i] * (1 - want[i]) / n) ** 0.5, 1e-6)
        assert abs(counts[i] - want[i]) <= 4 * sigma + 0.02


def test_topp_greedy_rows_and_top_p_one():
    s, v = 3, 320
    logits = _logits(jax.random.PRNGKey(5), s, v)
    temps = jnp.asarray([0.0, 1.0, 1.0])
    tps = jnp.asarray([0.5, 1.0, 0.4])
    keys = _keys(11, s)
    toks = fused_sample(logits, temps, tps, keys, mode="topp", impl="xla")
    assert int(toks[0]) == int(jnp.argmax(logits[0]))
    # top_p == 1 row: nucleus never cuts — token drawn from the full
    # softmax support
    assert 0 <= int(toks[1]) < v


# ------------------------------------------------------------ API contract

def test_sample_rows_preserves_legacy_rng_stream():
    # the migration payload carries per-slot rng: sample_rows must split
    # exactly like the legacy vmap(split) pair (slot 0 kept)
    s, v = 4, 256
    rng = jnp.stack([jax.random.PRNGKey(i) for i in range(s)])
    logits = _logits(jax.random.PRNGKey(9), s, v)
    temps = jnp.full((s,), 0.8)
    toks, new_rng = sample_rows(logits, temps, jnp.ones((s,)), rng,
                                mode="simple", impl="xla")
    split = jax.vmap(jax.random.split)(rng)
    np.testing.assert_array_equal(np.asarray(new_rng),
                                  np.asarray(split[:, 0]))
    want = fused_sample(logits, temps, jnp.ones((s,)), split[:, 1],
                        mode="simple", impl="xla")
    np.testing.assert_array_equal(np.asarray(toks), np.asarray(want))


def test_mode_validation_and_default_impl(monkeypatch):
    with pytest.raises(ValueError):
        fused_sample(jnp.zeros((1, 128)), jnp.zeros((1,)), jnp.ones((1,)),
                     None, mode="nope")
    assert set(MODES) == {"greedy", "simple", "topp"}
    monkeypatch.setenv("DTX_SAMPLING_EPILOGUE_KERNEL", "0")
    assert default_impl() == "xla"
    monkeypatch.setenv("DTX_SAMPLING_EPILOGUE_KERNEL", "1")
    assert default_impl() == "kernel"
    monkeypatch.delenv("DTX_SAMPLING_EPILOGUE_KERNEL")
    assert default_impl() == ("kernel" if jax.default_backend() == "tpu"
                              else "xla")
