"""Native C++ packer vs the pure-Python reference implementations."""

import numpy as np

from datatunerx_tpu import native
from datatunerx_tpu.data.preprocess import pack_to_block, pad_to_block


def _examples(rng, n=50, max_len=40):
    out = []
    for _ in range(n):
        ln = int(rng.integers(1, max_len))
        ids = rng.integers(1, 1000, ln).astype(np.int32).tolist()
        labels = list(ids)
        for i in range(min(3, ln)):
            labels[i] = -100
        out.append({"input_ids": ids, "labels": labels})
    return out


def test_native_builds():
    assert native.available(), "g++ build of the native packer failed"


def test_fill_batch_matches_python():
    rng = np.random.default_rng(0)
    exs = _examples(rng)
    a = pad_to_block(exs, 48, pad_id=7, use_native=True)
    b = pad_to_block(exs, 48, pad_id=7, use_native=False)
    for k in b:
        np.testing.assert_array_equal(a[k], b[k], err_msg=k)


def test_pack_matches_python():
    rng = np.random.default_rng(1)
    exs = _examples(rng)
    a = pack_to_block(exs, 64, pad_id=0, use_native=True)
    b = pack_to_block(exs, 64, pad_id=0, use_native=False)
    # same packing algorithm (first-fit over descending lengths) -> identical
    for k in b:
        np.testing.assert_array_equal(a[k], b[k], err_msg=k)


def test_native_speedup_sanity():
    """Not a benchmark — just asserts the native path actually runs end to end
    on a larger batch without divergence."""
    rng = np.random.default_rng(2)
    exs = _examples(rng, n=2000, max_len=120)
    a = pad_to_block(exs, 128, use_native=True)
    b = pad_to_block(exs, 128, use_native=False)
    np.testing.assert_array_equal(a["labels"], b["labels"])
