"""Multi-tenant QoS plane (datatunerx_tpu/tenancy/ + gateway admission +
engine preemption + adapter pin/host tiers): tenants are a scheduling
dimension, not a label. This file covers the directory round-trip and the
webhook's rejects, pin-tier eviction immunity and the host-RAM adapter
tier (including the _entry_bytes dict-shape regression), the weighted-
fair admission math and the quota 429 naming its tenant, prefetch-on-
route firing before admission (trace-asserted), the per-tenant metric
families passing the metrics lint, and the gating contract: with no
tenant config every plane behaves byte-identically to a pre-tenancy
build — eviction order, preemption order, and exposition families."""

import importlib.util
import json
import os
import threading
import urllib.error
import urllib.request
from http.server import ThreadingHTTPServer

import numpy as np
import pytest

from datatunerx_tpu.tenancy import HostAdapterTier, load_tenants
from datatunerx_tpu.tenancy.directory import (
    TIER_RANK,
    TenantDirectory,
    TenantSpec,
    tenant_entry_from_crd,
    validate_tenant_entry,
)
from datatunerx_tpu.tenancy.host_tier import _entry_bytes

MODEL = "preset:debug"


def _metrics_lint():
    path = os.path.join(os.path.dirname(__file__), os.pardir, "scripts",
                        "metrics_lint.py")
    spec = importlib.util.spec_from_file_location("metrics_lint", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# ------------------------------------------------------------ directory

def test_directory_roundtrip_and_resolution(tmp_path):
    cfg = {"plat": {"tier": "pinned", "adapters": ["plat-a"], "share": 4,
                    "ttft_p95_ms": 500},
           "batch": {"tier": "bulk", "share": 1, "kv_block_quota": 24}}
    for source in (cfg, json.dumps(cfg), json.dumps({"tenants": cfg})):
        d = load_tenants(source)
        assert isinstance(d, TenantDirectory)
        assert sorted(d.names()) == ["batch", "plat"]
    # file path source (the --tenants_config flag's shape)
    p = tmp_path / "tenants.json"
    p.write_text(json.dumps(cfg))
    d = load_tenants(str(p))
    assert d.get("plat").tier == "pinned"
    assert d.get("plat").share == 4.0
    assert d.get("batch").kv_block_quota == 24
    # spec round-trip through to_dict/from_dict is lossless
    spec = d.get("plat")
    assert (TenantSpec.from_dict("plat", spec.to_dict()).to_dict()
            == spec.to_dict())
    assert load_tenants(d) is d  # already-built directory passes through

    # resolution precedence: explicit name > adapter mapping > anonymous
    assert d.resolve(tenant="batch", adapter="plat-a").name == "batch"
    assert d.resolve(adapter="plat-a").name == "plat"
    assert d.resolve(tenant="ghost", adapter="plat-a").name == "plat"
    assert d.resolve(tenant="ghost") is None
    assert d.resolve() is None

    assert d.pinned_adapters() == {"plat-a"}
    assert d.shares() == {"plat": 4.0, "batch": 1.0}

    # upsert/remove bump the generation (the pin-refresh trigger)
    g0 = d.generation
    d.upsert("batch", {"tier": "standard", "share": 2})
    assert d.generation > g0 and d.get("batch").tier == "standard"
    assert d.remove("batch") and not d.remove("batch")
    assert d.get("batch") is None

    # falsy config = plane off, not an empty directory
    assert load_tenants(None) is None
    assert load_tenants("") is None


def test_tenant_entry_validation_and_crd_keys():
    validate_tenant_entry("t", {"tier": "bulk", "adapters": ["a"],
                                "share": 2, "kv_block_quota": 8,
                                "ttft_p95_ms": 300})
    with pytest.raises(ValueError, match="tier"):
        validate_tenant_entry("t", {"tier": "gold"})
    with pytest.raises(ValueError, match="adapters"):
        validate_tenant_entry("t", {"adapters": "not-a-list"})
    with pytest.raises(ValueError, match="adapters"):
        validate_tenant_entry("t", {"adapters": [""]})
    with pytest.raises(ValueError, match="share"):
        validate_tenant_entry("t", {"share": 0})
    with pytest.raises(ValueError, match="kv_block_quota"):
        validate_tenant_entry("t", {"kv_block_quota": -1})
    with pytest.raises(ValueError, match="ttft_p95_ms"):
        validate_tenant_entry("t", {"ttft_p95_ms": -5})
    # CRD camelCase keys map onto the python entry shape
    entry = tenant_entry_from_crd({"tier": "pinned", "kvBlockQuota": 8,
                                   "ttftP95Ms": 250})
    assert entry["kv_block_quota"] == 8 and entry["ttft_p95_ms"] == 250
    validate_tenant_entry("t", entry)


def test_webhook_rejects_bad_tenant_config():
    from datatunerx_tpu.operator.webhooks import (
        AdmissionError,
        _validate_serve_config,
    )

    _validate_serve_config({"tenants": {"plat": {"tier": "pinned",
                                                 "kvBlockQuota": 8}}})
    _validate_serve_config({"hostAdapterCacheMb": 64})
    with pytest.raises(AdmissionError, match="serveConfig.tenants"):
        _validate_serve_config({"tenants": {"p": {"tier": "gold"}}})
    with pytest.raises(AdmissionError, match="non-empty"):
        _validate_serve_config({"tenants": {}})
    with pytest.raises(AdmissionError, match="mutually"):
        _validate_serve_config({"tenants": {"p": {"tier": "bulk"}},
                                "tenantsConfig": "/etc/tenants.json"})
    with pytest.raises(AdmissionError, match="hostAdapterCacheMb"):
        _validate_serve_config({"hostAdapterCacheMb": -1})


# ------------------------------------------------------------ host tier

def test_host_tier_entry_bytes_dict_shape_regression():
    """The registry loader hands the tier its {target: {"a": arr, "b":
    arr}} layer tree — a flat-iteration sizing saw nested dicts as
    0-byte objects and refused every put. The walk must recurse."""
    arr = np.zeros((4, 8), np.float32)
    assert _entry_bytes({"q_proj": {"a": arr, "b": arr},
                         "v_proj": {"a": arr, "b": arr}}) == 4 * arr.nbytes
    assert _entry_bytes([arr, (arr, arr)]) == 3 * arr.nbytes
    assert _entry_bytes({"q": [{"a": arr}]}) == arr.nbytes
    assert _entry_bytes({}) == 0
    # ...and therefore a real-shaped entry is accepted by put()
    tier = HostAdapterTier(max_bytes=8 * arr.nbytes)
    assert tier.put("t", "ck:t", {"q_proj": {"a": arr, "b": arr}}, 2.0)
    assert tier.stats()["bytes"] == 2 * arr.nbytes


def test_host_tier_lru_bounds_and_drop():
    arr = np.ones((16, 16), np.float32)  # 1 KiB
    one = arr.nbytes
    tier = HostAdapterTier(max_bytes=int(2.5 * one))
    assert tier.put("a", "ck:a", {"q": {"a": arr}}, 1.0)
    assert tier.put("b", "ck:b", {"q": {"a": arr}}, 1.0)
    assert tier.get("a", "ck:a") is not None  # refresh: b is now coldest
    assert tier.put("c", "ck:c", {"q": {"a": arr}}, 1.0)  # evicts b
    assert tier.get("b", "ck:b") is None
    assert tier.get("a", "ck:a") is not None
    s = tier.stats()
    assert s["evictions"] == 1 and s["entries"] == 2
    assert s["bytes"] <= s["max_bytes"]
    # an entry bigger than the whole budget is refused, not thrashed in
    big = np.ones((64, 16), np.float32)
    assert not tier.put("big", "ck:big", {"q": {"a": big}}, 1.0)
    # keyed by (name, checkpoint): a rebind can't serve stale weights
    assert tier.get("a", "ck:other") is None
    assert tier.drop("a") == 1 and tier.get("a", "ck:a") is None


def test_registry_pin_immunity_and_host_tier_reload():
    """Pinned-tier adapters never LRU-evict, and an evicted standard
    adapter reloads from the host tier with zero checkpoint reads."""
    from datatunerx_tpu.adapters import AdapterRegistry, AdapterStore
    from datatunerx_tpu.models import get_config
    from datatunerx_tpu.models.lora import target_dims

    cfg = get_config("debug")
    store = AdapterStore(cfg, pool_slots=2, rank_max=8)
    loads = []

    def loader(path):
        name = path.split(":", 1)[1]
        loads.append(name)
        out = {}
        for t in ("q_proj", "v_proj"):
            d_in, d_out = target_dims(cfg, t)
            out[t] = {"a": np.full((cfg.num_layers, d_in, 2), 0.5,
                                   np.float32),
                      "b": np.full((cfg.num_layers, 2, d_out), 0.5,
                                   np.float32)}
        return {"lora": {"layers": out}, "_scaling": 4.0}

    tier = HostAdapterTier(max_bytes=64 << 20)
    reg = AdapterRegistry(store, loader=loader, host_tier=tier)
    for n in ("p", "a", "b"):
        reg.register(n, f"ck:{n}")
    reg.set_pinned({"p"})
    assert reg.acquire("p", wait=True) is not None
    reg.release("p")
    assert reg.acquire("a", wait=True) is not None
    reg.release("a")
    # pool full; p is the LRU-coldest but PINNED → a is the victim
    assert reg.acquire("b", wait=True) is not None
    reg.release("b")
    res = reg.resident()
    assert "p" in res and "a" not in res, res
    # evict→reload of a: served from the host tier, no second orbax read
    assert reg.acquire("a", wait=True) is not None
    reg.release("a")
    assert loads == ["p", "a", "b"]  # a loaded from checkpoint ONCE
    assert reg.host_hits == 1 and reg.orbax_loads == 3
    hs = reg.host_tier_stats()
    assert hs["host_hits"] == 1 and hs["entries"] >= 1
    # every slot pinned → preload reports exhaustion instead of hanging
    reg.set_pinned({"p", "a"})
    assert "a" in reg.resident() and "p" in reg.resident()
    with pytest.raises(RuntimeError, match="exhausted"):
        reg.preload("b")
    # unregister purges host-tier copies: a deleted adapter can't resurrect
    reg.set_pinned({"p"})
    reg.unregister("a")
    assert tier.get("a", "ck:a") is None


# ------------------------------------------------------------- admission

def test_weighted_fair_admission_math():
    from datatunerx_tpu.gateway.admission import (
        AdmissionController,
        Overloaded,
    )

    ac = AdmissionController(max_queue=16, token_budget=100)
    small = {"name": "small", "share": 1.0, "share_total": 4.0,
             "kv_block_quota": 0}
    big = {"name": "big", "share": 3.0, "share_total": 4.0,
           "kv_block_quota": 0}
    msgs = [{"role": "user", "content": "x"}]
    # below the 80% contention watermark any tenant bursts past its share
    # (work-conserving): 50 > cap of 25 but the pool is idle
    t1 = ac.try_admit(msgs, tokens=50, tenant=small)
    # contended now (50+40 > 80): small's cap is 100*1/4 = 25 → shed,
    # and the message names the tenant, the math, and the shares
    with pytest.raises(Overloaded, match=r"tenant small over fair share "
                                         r"\(50\+40>25 tokens, "
                                         r"share 1/4\)"):
        ac.try_admit(msgs, tokens=40, tenant=small)
    # the HIGH-share tenant still fits under ITS cap (75) while contended
    t2 = ac.try_admit(msgs, tokens=40, tenant=big)
    usage = ac.tenant_usage()
    assert usage["tokens"] == {"small": 50, "big": 40}
    assert usage["blocks"]["small"] > 0  # admits are always block-priced
    t2.release()
    t1.release()
    # zeroed reservations are pruned — no dead series linger
    assert ac.tenant_usage()["tokens"] == {}
    # anonymous traffic is never share-gated (the pre-tenancy path)
    with ac.try_admit(msgs, tokens=90):
        assert ac.tenant_usage()["tokens"] == {}


def test_kv_block_quota_shed_names_tenant():
    from datatunerx_tpu.gateway.admission import (
        AdmissionController,
        Overloaded,
    )

    ac = AdmissionController(max_queue=16, token_budget=4096)
    msgs = [{"role": "user", "content": "q"}]
    # blocks_for_admit(16, 16) = ceil((16 + 64 headroom)/16) = 5
    bulk = {"name": "bulkco", "share": 1.0, "share_total": 1.0,
            "kv_block_quota": 9}
    t1 = ac.try_admit(msgs, tokens=16, tenant=bulk)
    assert ac.tenant_usage()["blocks"]["bulkco"] == 5
    with pytest.raises(Overloaded) as ei:
        ac.try_admit(msgs, tokens=16, tenant=bulk)
    assert "tenant bulkco KV block quota exhausted" in str(ei.value)
    assert "(5+5>9 blocks)" in str(ei.value)
    # releasing the first reservation re-opens the quota
    t1.release()
    with ac.try_admit(msgs, tokens=16, tenant=bulk):
        pass
    # quota 0 = unlimited
    free = {"name": "free", "share": 1.0, "share_total": 1.0,
            "kv_block_quota": 0}
    for _ in range(4):
        ac.try_admit(msgs, tokens=16, tenant=free)


# ------------------------------------------- engine preemption + parity

def test_tier_aware_preemption_token_exact(tmp_path):
    """The isolation contract end to end on a starved pool: a pinned
    tenant's session — deliberately the YOUNGEST, i.e. exactly the
    session the pre-tenancy youngest-first policy kills first — survives
    a bulk preemption storm un-preempted, bulk sessions preempted under
    pressure resume TOKEN-EXACTLY (the PR 15 park/resume fabric), and
    the tenancy-off control engine preempts that same youngest session,
    proving the tier filter (not luck) is what saved it."""
    from datatunerx_tpu.serving.batched_engine import BatchedEngine

    tenants = {"plat": {"tier": "pinned", "share": 4},
               "batch": {"tier": "bulk", "share": 1}}
    # admission reserves blocks for the BUCKET-padded prompt (64) plus
    # one tick's advance → 5 blocks of 16 per session: four sessions on
    # a 20-block pool admit concurrently with ZERO free blocks. The
    # 60-token bulk prompts outgrow their reservation within ~5 decode
    # ticks, so reclaim fires while the pinned session (~14 ticks of
    # life, never growing) is mid-decode — deterministically.
    ref = BatchedEngine(MODEL, template="vanilla", max_seq_len=256,
                        slots=4, decode_chunk=4, kv_block_size=16)
    engines = {
        "qos": BatchedEngine(MODEL, template="vanilla", max_seq_len=256,
                             slots=4, decode_chunk=4, kv_block_size=16,
                             kv_blocks=20, kv_overcommit="on",
                             tenants=tenants),
        "control": BatchedEngine(MODEL, template="vanilla",
                                 max_seq_len=256, slots=4, decode_chunk=4,
                                 kv_block_size=16, kv_blocks=20,
                                 kv_overcommit="on"),
    }
    try:
        # pairwise-distinct prompts: a shared prefix would admit later
        # sessions through the prefix-cache/COW path with a SMALLER
        # reservation, collapsing the geometry this test is built on
        bulk_prompts = [
            list(ref.tokenizer.encode(f"storm lane {i} bulk probe " * 15)[:60])
            for i in range(3)]
        assert all(len(p) == 60 for p in bulk_prompts)
        # all four sessions decode in lock-step from the padded cursor
        # (64), so block demand crosses the 5-block reservation for
        # EVERYONE at the same tick. At that tick the oldest bulk's
        # reclaim fires: tenancy-off picks the youngest victim (the
        # pin); tenancy-on filters pinned out and a bulk pays instead,
        # and the pin's own one-block growth succeeds from the freed
        # pool. max_new=28 keeps the pin alive at that tick (>16) but
        # finished before the SECOND contention tick (≤32), where a
        # youngest-with-no-younger-victims session must self-preempt
        pin_prompt = list(ref.tokenizer.encode("pinned latency probe " * 5)[:17])
        assert len(pin_prompt) == 17
        kws = [{}, {"temperature": 0.8, "top_p": 0.9, "seed": 3}, {}]
        want_bulk = [ref.generate(p, max_new_tokens=80, **kw)
                     for p, kw in zip(bulk_prompts, kws)]
        want_pin = ref.generate(pin_prompt, max_new_tokens=28)
        for mode, eng in engines.items():
            reqs = [eng.submit(p, max_new_tokens=80, tenant="batch", **kw)
                    for p, kw in zip(bulk_prompts, kws)]
            pin = eng.submit(pin_prompt, max_new_tokens=28, tenant="plat")
            for i, r in enumerate(reqs):
                assert r.done.wait(300), f"{mode}: bulk {i} stalled"
                assert r.error is None, (mode, i, r.error)
                assert r.tokens == want_bulk[i], \
                    f"{mode}: bulk {i} diverged after preempt/resume"
            assert pin.done.wait(300) and pin.error is None
            assert pin.tokens == want_pin, f"{mode}: pinned diverged"
            preempted = {e[2] for e in eng.sched_trace
                         if e[0] in ("preempt", "preempt_prefill")}
            assert preempted, f"{mode}: pool never contended — vacuous"
            if mode == "qos":
                # the storm never touched the pinned tenant...
                assert pin.seq not in preempted, \
                    "bulk requester preempted a pinned tenant"
                # ...and it DID park bulk sessions that resumed exactly
                assert preempted & {r.seq for r in reqs}
                usage = eng.tenant_usage()
                assert usage["plat"]["requests"] == 1
                assert usage["plat"]["tier"] == "pinned"
                assert usage["batch"]["requests"] == 3
            else:
                # tenancy off: the same youngest session is the victim —
                # the pre-tenancy order, byte-identical
                assert pin.seq in preempted, \
                    "control engine spared the youngest (test is vacuous)"
                assert eng.tenant_usage() is None
    finally:
        ref.close()
        for eng in engines.values():
            eng.close()


# ---------------------------------------------------------- gateway e2e

def test_gateway_prefetch_quota_and_tenant_metrics(tmp_path):
    """Prefetch-on-route fires BEFORE admission completes (trace-event
    order), the quota 429 names the tenant on the gateway path, and both
    planes' dtx_*_tenant_* families render and pass the metrics lint."""
    from datatunerx_tpu.gateway.admission import Overloaded
    from datatunerx_tpu.gateway.replica_pool import (
        InProcessReplica,
        ReplicaPool,
    )
    from datatunerx_tpu.gateway.server import Gateway
    from datatunerx_tpu.serving import server as serving
    from datatunerx_tpu.serving.adapters import make_adapter_checkpoint
    from datatunerx_tpu.serving.batched_engine import BatchedEngine

    ck = make_adapter_checkpoint(str(tmp_path / "t"), MODEL, seed=7, rank=4)
    tenants = {"acme": {"tier": "pinned", "adapters": ["t-a"], "share": 3},
               "bulkco": {"tier": "bulk", "share": 1, "kv_block_quota": 1}}
    eng = BatchedEngine(MODEL, adapters={"t-a": ck}, adapter_pool=2,
                        template="vanilla", max_seq_len=256, slots=2,
                        decode_chunk=4, kv_block_size=16, tenants=tenants,
                        host_adapter_cache_mb=64)
    pool = ReplicaPool([InProcessReplica("r0", eng)])
    gw = Gateway(pool, model_name=MODEL, tenants=tenants)
    try:
        # adapter registered but not resident → the route prefetches, and
        # the trace shows the prefetch event BEFORE the admission event
        req = {"messages": [{"role": "user", "content": "hello acme"}],
               "model": "t-a", "max_tokens": 4}
        # "" is a legal completion (the tiny debug model can sample EOS
        # first); only None would mean the request failed
        assert gw.chat(dict(req), trace_id="dtx-tn-1") is not None
        doc = gw.trace("dtx-tn-1")
        root = next(sp for sp in doc["spans"]
                    if sp["name"] == "gateway.request")
        names = [e.get("name") for e in (root.get("events") or [])]
        assert "adapter_prefetch" in names and "admitted" in names
        assert names.index("adapter_prefetch") < names.index("admitted"), \
            f"prefetch did not precede admission: {names}"
        assert root["attrs"]["tenant"] == "acme"

        # the weighted-fair pricing row divides by the directory Σshares
        row = gw._admission_tenant(gw.tenants.get("acme"))
        assert row == {"name": "acme", "share": 3.0, "share_total": 4.0,
                       "kv_block_quota": 0}

        # quota 429 on the gateway path names the tenant and the quota
        with pytest.raises(Overloaded, match="tenant bulkco KV block "
                                             "quota exhausted"):
            gw.chat({"messages": [{"role": "user", "content": "flood"}],
                     "max_tokens": 4}, tenant="bulkco")

        # gateway exposition: per-tenant families present + lint-clean
        lint = _metrics_lint()
        gw_text = gw.metrics_text()
        assert "dtx_gateway_tenant_requests_total" in gw_text
        assert 'tenant="acme"' in gw_text
        assert "dtx_gateway_tenant_share" in gw_text
        assert lint.lint_exposition(gw_text, "gateway") == []

        # serving exposition: usage + host-tier families + lint-clean.
        # A FRESH ServingState: the module-global registry accretes
        # families across tests, and this render must reflect only this
        # engine's planes
        old_state = serving.STATE
        serving.STATE = serving.ServingState()
        serving.STATE.engine, serving.STATE.model_path = eng, MODEL
        try:
            sv_text = serving.metrics_text()
        finally:
            serving.STATE = old_state
        assert "dtx_serving_tenant_requests_total" in sv_text
        assert "dtx_serving_tenant_tier" in sv_text
        assert 'tenant="acme"' in sv_text
        assert lint.lint_exposition(sv_text, "serving") == []
    finally:
        gw.close()


def test_admin_tenants_http_contract(tmp_path):
    """GET/POST /admin/tenants over a real loopback server: read the
    directory, upsert with validation, remove, and 404 when off."""
    from datatunerx_tpu.gateway.replica_pool import (
        InProcessReplica,
        ReplicaPool,
    )
    from datatunerx_tpu.gateway.server import Gateway, make_handler
    from datatunerx_tpu.serving.batched_engine import BatchedEngine

    def _req(url, method="GET", body=None):
        data = json.dumps(body).encode() if body is not None else None
        rq = urllib.request.Request(
            url, data=data, method=method,
            headers={"Content-Type": "application/json"})
        try:
            with urllib.request.urlopen(rq, timeout=60) as r:
                return r.status, json.load(r)
        except urllib.error.HTTPError as e:
            return e.code, json.load(e)

    eng = BatchedEngine(MODEL, template="vanilla", max_seq_len=256,
                        slots=2, decode_chunk=4, kv_block_size=16)
    gw = Gateway(ReplicaPool([InProcessReplica("r0", eng)]),
                 model_name=MODEL,
                 tenants={"plat": {"tier": "pinned", "share": 2}})
    srv = ThreadingHTTPServer(("127.0.0.1", 0), make_handler(gw))
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    url = f"http://127.0.0.1:{srv.server_address[1]}"
    try:
        code, doc = _req(url + "/admin/tenants")
        assert code == 200 and doc["tenants"]["plat"]["tier"] == "pinned"
        gen0 = doc["generation"]
        # upsert a tenant; the generation advances (pin-refresh signal)
        code, doc = _req(url + "/admin/tenants", "POST",
                         {"name": "batch", "tier": "bulk", "share": 1,
                          "kv_block_quota": 16})
        assert code == 200 and doc["generation"] > gen0
        assert doc["tenants"]["batch"]["kv_block_quota"] == 16
        # validation errors surface as 400 naming the field
        code, doc = _req(url + "/admin/tenants", "POST",
                         {"name": "bad", "tier": "gold"})
        assert code == 400 and "tier" in doc["error"]
        # remove round-trips; unknown removals 404
        code, doc = _req(url + "/admin/tenants", "POST",
                         {"name": "batch", "remove": True})
        assert code == 200 and "batch" not in doc["tenants"]
        code, _ = _req(url + "/admin/tenants", "POST",
                       {"name": "batch", "remove": True})
        assert code == 404
    finally:
        srv.shutdown()
        gw.close()

    # tenancy off → the surface says so rather than faking an empty plane
    eng2 = BatchedEngine(MODEL, template="vanilla", max_seq_len=256,
                         slots=2, decode_chunk=4, kv_block_size=16)
    gw2 = Gateway(ReplicaPool([InProcessReplica("r0", eng2)]),
                  model_name=MODEL)
    srv2 = ThreadingHTTPServer(("127.0.0.1", 0), make_handler(gw2))
    threading.Thread(target=srv2.serve_forever, daemon=True).start()
    url2 = f"http://127.0.0.1:{srv2.server_address[1]}"
    try:
        code, doc = _req(url2 + "/admin/tenants")
        assert code == 404 and "not enabled" in doc["error"]
        code, _ = _req(url2 + "/admin/tenants", "POST",
                       {"name": "x", "tier": "bulk"})
        assert code == 404
    finally:
        srv2.shutdown()
        gw2.close()


# ---------------------------------------------------- no-config identity

def test_no_tenant_config_byte_identity():
    """The gating contract: with NO tenant config, every tenancy hook is
    inert — a tenant header changes nothing (not the tokens, not the
    victim order, not a single exposition family)."""
    from datatunerx_tpu.gateway.replica_pool import (
        InProcessReplica,
        ReplicaPool,
    )
    from datatunerx_tpu.gateway.server import Gateway
    from datatunerx_tpu.serving import server as serving
    from datatunerx_tpu.serving.batched_engine import BatchedEngine

    eng = BatchedEngine(MODEL, template="vanilla", max_seq_len=256,
                        slots=2, decode_chunk=4, kv_block_size=16)
    gw = Gateway(ReplicaPool([InProcessReplica("r0", eng)]),
                 model_name=MODEL)
    try:
        assert eng.tenants is None and gw.tenants is None
        assert eng.tenant_usage() is None
        prompt = eng.tokenizer.encode("identity probe")
        plain = eng.generate(prompt, max_new_tokens=8)
        assert eng.generate(prompt, max_new_tokens=8,
                            tenant="ghost") == plain
        # victim selection is the pre-tenancy order, exactly: the filter
        # passes victims through untouched and the pick is youngest-first
        class _R:
            def __init__(self, seq, tier):
                self.seq, self.tenant_tier = seq, tier

        req_of = {0: _R(5, "bulk"), 1: _R(9, "pinned"), 2: _R(7, "bulk")}
        assert eng._tenant_filter_victims(_R(1, "bulk"), [0, 1, 2],
                                          req_of) == [0, 1, 2]
        assert eng._pick_victim([0, 1, 2], req_of) == 1  # youngest wins
        # a tenant header through the gateway is inert, never a 4xx
        # ("" is a legal completion for the tiny debug model)
        assert gw.chat({"messages": [{"role": "user", "content": "hi"}],
                        "max_tokens": 4}, tenant="ghost") is not None
        assert gw.admission.tenant_usage() == {"tokens": {}, "blocks": {}}
        # neither plane grows a tenant family without config (fresh
        # ServingState: the module registry is sticky across tests)
        assert "dtx_gateway_tenant_" not in gw.metrics_text()
        old_state = serving.STATE
        serving.STATE = serving.ServingState()
        serving.STATE.engine, serving.STATE.model_path = eng, MODEL
        try:
            sv_text = serving.metrics_text()
        finally:
            serving.STATE = old_state
        assert "dtx_serving_tenant_" not in sv_text
        assert "dtx_serving_adapter_host_" not in sv_text
    finally:
        gw.close()


def test_tier_rank_order_is_the_scheduling_contract():
    """TIER_RANK is load-bearing in _pick_victim: bulk must give way
    before standard before pinned, and every directory tier has a rank."""
    assert TIER_RANK["bulk"] < TIER_RANK["standard"] < TIER_RANK["pinned"]
    from datatunerx_tpu.tenancy.directory import TIERS

    assert set(TIERS) == set(TIER_RANK)
