"""Headline benchmark: LoRA SFT tokens/sec/chip (BASELINE.md north-star #1).

Prints exactly ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

Runs on whatever backend JAX selects (the driver provides one real TPU chip).
The model is tinyllama-1.1b (real llama-family config that fits one v5e chip in
bf16 with LoRA); batch geometry mirrors the reference's operating point
(block_size 1024, reference cmd/tuning/train.py:50-51).

``vs_baseline``: the reference publishes no numbers (BASELINE.md), so the
denominator is this project's own round-1 recorded measurement — values > 1.0
mean speedup over round 1.
"""

import json
import os
import sys
import threading
import time

BENCH_TIMEOUT_S = float(os.environ.get("DTX_BENCH_TIMEOUT_S", "480"))
# Pre-flight deadline: generous enough for first-compile of a tiny matmul
# (~20-40s cold) but far below the full watchdog, so a wedged relay costs
# ~90s + a CPU smoke run instead of the whole 480s budget.
PREFLIGHT_TIMEOUT_S = float(os.environ.get("DTX_BENCH_PREFLIGHT_S", "90"))

# Round-1 recorded tokens/sec/chip on TPU v5e-1 (see BASELINE.md); update only
# alongside BASELINE.md.
ROUND1_BASELINE_TOKS_PER_SEC = 12996.0  # TPU v5e-1, tinyllama-1.1b LoRA B8xT1024


def main():
    import jax

    if os.environ.get("DTX_BENCH_FORCE_CPU"):
        # env-var platform selection is intercepted by the tunnel's
        # sitecustomize; config.update is the only reliable CPU escape
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp

    from datatunerx_tpu.models import get_config, init_params
    from datatunerx_tpu.training import TrainConfig, Trainer
    from datatunerx_tpu.training.loss import IGNORE_INDEX

    on_tpu = jax.default_backend() == "tpu"
    if on_tpu:
        model, B, T, steps = "tinyllama-1.1b", 8, 1024, 20
        B = int(os.environ.get("DTX_BENCH_BATCH", B))
    else:  # CPU smoke fallback so bench never hard-fails
        model, B, T, steps = "debug", 8, 128, 5

    # perf knobs: the Pallas flash kernel is Mosaic-validated on the v5e
    # (scripts/tpu_validate.py 8/8, BASELINE.md round-2 pass) and is 1.34×
    # the xla-attention round-1 number — it is the TPU default. CPU smoke
    # keeps xla (flash off-TPU would dispatch interpret mode: slow, no signal).
    attention = os.environ.get("DTX_BENCH_ATTENTION",
                               "flash" if on_tpu else "xla")
    remat = os.environ.get("DTX_BENCH_REMAT", "dots")
    cfg = get_config(model, remat=remat, attention_impl=attention)
    tr = Trainer(
        cfg,
        TrainConfig(
            finetuning_type="lora", lora_rank=8, lora_alpha=32.0,
            lora_dropout=0.05, lora_targets=("q_proj", "v_proj"),
            learning_rate=2e-4, scheduler="cosine", optimizer="adamw",
            total_steps=1000, compute_dtype=jnp.bfloat16,
        ),
    )
    params = init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.bfloat16)
    state = tr.init_state(params, jax.random.PRNGKey(1))

    rng = jax.random.PRNGKey(2)
    toks = jax.random.randint(rng, (B, T), 0, cfg.vocab_size, jnp.int32)
    labels = jnp.where(
        jnp.arange(T)[None, :] < T // 8, IGNORE_INDEX, toks
    )  # prompt-masked SFT batch shape
    batch = {"input_ids": toks, "labels": labels}

    # warmup / compile. NOTE: sync via host value fetch, not block_until_ready —
    # the tunneled TPU backend's block_until_ready can return before remote
    # execution finishes, which inflates throughput by ~5000x.
    state, m = tr.train_step(state, batch)
    float(m["loss"])

    t0 = time.perf_counter()
    for _ in range(steps):
        state, m = tr.train_step(state, batch)
    float(m["loss"])  # device-to-host fetch = true pipeline drain
    dt = time.perf_counter() - t0

    toks_per_sec = B * T * steps / dt
    vs = (
        toks_per_sec / ROUND1_BASELINE_TOKS_PER_SEC
        if (ROUND1_BASELINE_TOKS_PER_SEC and on_tpu)
        else 1.0
    )
    tag = (f",{attention}" if attention != "xla" else "") + (
        f",remat={remat}" if remat != "dots" else "")
    tag += f",B{B}" if B != 8 else ""
    print(
        json.dumps(
            {
                "metric": f"lora_sft_tokens_per_sec_per_chip[{model},B{B}xT{T}{tag}]",
                "value": round(toks_per_sec, 1),
                "unit": "tokens/s/chip",
                "vs_baseline": round(vs, 3),
            }
        )
    )


def _preflight_device_ok():
    """Probe the default device with a tiny matmul in a SUBPROCESS.

    The tunneled TPU backend wedges by hanging (not erroring), and once a
    process has initialized the wedged platform it cannot recover — so the
    probe must be isolated. If the probe hangs or fails, the bench falls back
    to the CPU smoke immediately instead of burning the full watchdog budget.
    """
    import subprocess

    code = (
        "import jax, jax.numpy as jnp;"
        "x = jnp.ones((256, 256), jnp.float32);"
        "print(float((x @ x)[0, 0]))"
    )
    try:
        p = subprocess.run(
            [sys.executable, "-c", code],
            timeout=PREFLIGHT_TIMEOUT_S, capture_output=True, text=True,
        )
    except subprocess.TimeoutExpired:
        return False
    return p.returncode == 0 and "256.0" in p.stdout


def _run_with_watchdog():
    """The tunneled TPU backend can wedge indefinitely (device ops hang, not
    error). Run the bench on a daemon thread; if it exceeds the deadline, emit
    the error JSON line and hard-exit so the driver always gets exactly one
    line of stdout."""
    if not os.environ.get("DTX_BENCH_FORCE_CPU") and not _preflight_device_ok():
        # Device hung/failed the pre-flight: emit the CPU smoke line rather
        # than a bench_error so BENCH_rN always carries signal.
        os.environ["DTX_BENCH_FORCE_CPU"] = "1"

    result = {}

    def target():
        try:
            main()
            result["ok"] = True
        except Exception as e:  # noqa: BLE001
            result["err"] = str(e)[:200]

    t = threading.Thread(target=target, daemon=True)
    t.start()
    t.join(BENCH_TIMEOUT_S)
    if t.is_alive():
        print(json.dumps({"metric": "bench_error", "value": 0,
                          "unit": f"timeout after {BENCH_TIMEOUT_S}s (TPU backend hung)",
                          "vs_baseline": 0.0}), flush=True)
        os._exit(1)
    if "err" in result:
        print(json.dumps({"metric": "bench_error", "value": 0,
                          "unit": result["err"], "vs_baseline": 0.0}))
        sys.exit(1)


if __name__ == "__main__":
    _run_with_watchdog()
